"""TP for the LoRA/frozen-base path (VERDICT r1 items 6 + 8).

Pins: the factored LoRA forward (x@W + s·(x@A)@B, never forming W+ΔW)
equals the merged forward; SFT training with --tensor_parallel 2 matches
pure data parallelism; adapter replicas stay consistent across tensor
ranks (the copy_to_tp_region gradient boundary); 7B-width shapes train.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
from distributed_lion_tpu.models.lora import (
    LoraConfig,
    apply_adapters,
    lora_adapter_specs,
    lora_apply_fn,
    lora_init,
    merge_lora,
)
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS, make_mesh
from distributed_lion_tpu.parallel.tensor_parallel import llama_param_specs, validate_tp
from distributed_lion_tpu.train.loop import TrainConfig, Trainer

MODEL = LlamaConfig.tiny(compute_dtype=jnp.float32)
LORA = LoraConfig(r=4, alpha=8)


def test_factored_matches_merged():
    """The LoraTensor factored forward == merging W+ΔW densely."""
    base = llama_init(jax.random.key(0), MODEL)
    adapters = lora_init(jax.random.key(1), base, LORA)
    # break the B=0 identity so the delta actually contributes
    adapters = jax.tree.map(
        lambda x: x + 0.01 * jax.random.normal(jax.random.key(2), x.shape, x.dtype),
        adapters,
    )
    tokens = np.random.default_rng(0).integers(0, MODEL.vocab_size,
                                               size=(2, 16)).astype(np.int32)
    factored = lora_apply_fn(
        lambda p, t: llama_apply(p, t, MODEL), base, LORA)(adapters, tokens)
    merged = llama_apply(merge_lora(base, adapters, LORA), tokens, MODEL)
    np.testing.assert_allclose(np.asarray(factored), np.asarray(merged),
                               rtol=2e-4, atol=2e-4)


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=5, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=32, logging_steps=1,
        output_dir=None, seed=7,
    )
    base.update(kw)
    return TrainConfig(**base)


def _sft_trainer(mesh, cfg, tp: int):
    """Mirror cli/run_sft's wiring for tp>1 vs the closure path."""
    base = llama_init(jax.random.key(0), MODEL)
    adapters = lora_init(jax.random.key(1), base, LORA)
    if tp > 1:
        validate_tp(MODEL, tp, "llama")
        base_specs = llama_param_specs(MODEL)
        adapter_specs = lora_adapter_specs(adapters, base_specs, TENSOR_AXIS)

        def loss_fn(params, frozen, batch, dropout_key):
            eff = apply_adapters(frozen, params, LORA, tp_axis=TENSOR_AXIS,
                                 base_specs=base_specs)
            logits = llama_apply(eff, batch, MODEL, tp_axis=TENSOR_AXIS)
            return clm_loss_and_metrics(logits, batch)

        return Trainer(cfg, mesh, apply_fn=None, params=adapters,
                       param_specs=adapter_specs, loss_fn=loss_fn,
                       frozen_params=base, frozen_specs=base_specs)
    apply = lora_apply_fn(lambda p, t: llama_apply(p, t, MODEL), base, LORA)
    return Trainer(cfg, mesh, lambda p, t, key: apply(p, t), adapters)


def _train(trainer, n_steps=5):
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset

    blocks = synthetic_lm_dataset(
        max(64, trainer.global_train_batch() * 2), trainer.cfg.block_size,
        MODEL.vocab_size, seed=11)
    hist = trainer.train(
        batch_iterator(blocks, trainer.global_train_batch(), seed=0),
        max_steps=n_steps)
    adapters = jax.tree.map(np.asarray, jax.device_get(trainer.params))
    trainer.close()
    return [h["loss"] for h in hist if "loss" in h], adapters


def test_sft_tp_matches_dp():
    """dp=4 x tp=2 SFT ≡ dp=4 SFT: same losses, same adapters (f32)."""
    losses_dp, ad_dp = _train(
        _sft_trainer(make_mesh(data=4, devices=jax.devices()[:4]), _cfg(), 1))
    losses_tp, ad_tp = _train(
        _sft_trainer(make_mesh(data=4, tensor=2), _cfg(tensor_parallel=2), 2))
    np.testing.assert_allclose(losses_tp, losses_dp, rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(ad_dp), jax.tree.leaves(ad_tp)):
        # ballot-flip envelope on zero-gradient coords (see pipeline test)
        assert np.abs(a - b).max() <= 2 * 1e-3 * 5 + 1e-6


def test_sft_tp_matches_dp_nf4_base():
    """dp=4 x tp=2 SFT with an NF4-quantized frozen base ≡ dp=4 with the
    SAME quantized base (the reference's flagship at scale: multi-chip
    QLoRA, sft_llama2.py:141-153). The shaped QuantizedTensor layout lets
    the dense PartitionSpecs shard codes/absmax; each rank dequantizes only
    its shard."""
    from distributed_lion_tpu.ops.quant import quantize_tree, validate_quant_tp

    base = llama_init(jax.random.key(0), MODEL)
    # block=16 so tiny-model projections (last dim 64/128) shard 2-way
    qbase = quantize_tree(base, "nf4", min_size=1024, block=16)

    apply = lora_apply_fn(lambda p, t: llama_apply(p, t, MODEL), qbase, LORA)
    tr_dp = Trainer(_cfg(), make_mesh(data=4, devices=jax.devices()[:4]),
                    lambda p, t, key: apply(p, t),
                    lora_init(jax.random.key(1), base, LORA))
    losses_dp, ad_dp = _train(tr_dp)

    base_specs = llama_param_specs(MODEL)
    validate_quant_tp(qbase, base_specs, 2, TENSOR_AXIS)
    adapters = lora_init(jax.random.key(1), base, LORA)
    adapter_specs = lora_adapter_specs(adapters, base_specs, TENSOR_AXIS)

    def loss_fn(params, frozen, batch, dropout_key):
        eff = apply_adapters(frozen, params, LORA, tp_axis=TENSOR_AXIS,
                             base_specs=base_specs)
        logits = llama_apply(eff, batch, MODEL, tp_axis=TENSOR_AXIS)
        return clm_loss_and_metrics(logits, batch)

    tr_tp = Trainer(_cfg(tensor_parallel=2), make_mesh(data=4, tensor=2),
                    apply_fn=None, params=adapters,
                    param_specs=adapter_specs, loss_fn=loss_fn,
                    frozen_params=qbase, frozen_specs=base_specs)
    losses_tp, ad_tp = _train(tr_tp)
    np.testing.assert_allclose(losses_tp, losses_dp, rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(ad_dp), jax.tree.leaves(ad_tp)):
        assert np.abs(a - b).max() <= 2 * 1e-3 * 5 + 1e-6


def test_quant_tp_misaligned_block_rejected():
    """validate_quant_tp names the offending leaf when block alignment
    can't shard (e.g. default nf4 block 64 == the whole last dim here)."""
    import pytest

    from distributed_lion_tpu.ops.quant import quantize_tree, validate_quant_tp

    base = llama_init(jax.random.key(0), MODEL)
    qbase = quantize_tree(base, "nf4", min_size=1024)  # block 64 → 1 block/row
    with pytest.raises(ValueError, match="quant"):
        validate_quant_tp(qbase, llama_param_specs(MODEL), 2, TENSOR_AXIS)


def test_sft_tp_adapter_replicas_consistent():
    """The copy_to_tp_region boundary's job: after training, every
    REPLICATED adapter factor (A for the col-parallel wq/wv targets) must be
    bit-identical on all devices — without the boundary psum, per-rank A
    gradients/momenta diverge across the tensor axis and this fails."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset

    trainer = _sft_trainer(make_mesh(data=4, tensor=2),
                           _cfg(tensor_parallel=2, max_steps=3), 2)
    blocks = synthetic_lm_dataset(
        max(64, trainer.global_train_batch() * 2), trainer.cfg.block_size,
        MODEL.vocab_size, seed=11)
    hist = trainer.train(
        batch_iterator(blocks, trainer.global_train_batch(), seed=0),
        max_steps=3)
    assert all(np.isfinite(h["loss"]) for h in hist if "loss" in h)
    checked = 0
    for path, ab in trainer.params.items():
        a = ab["A"]
        if len(a.addressable_shards) > 1 and a.addressable_shards[0].data.shape == a.shape:
            shards = [np.asarray(s.data) for s in a.addressable_shards]
            for s in shards[1:]:
                np.testing.assert_array_equal(shards[0], s, err_msg=path)
            checked += 1
    assert checked > 0  # at least one replicated A factor was compared
    trainer.close()


def test_dpo_tp_trains():
    """DPO with tensor parallelism: policy + frozen ref both sharded."""
    from distributed_lion_tpu.models.lora import apply_adapters as apply_ad
    from distributed_lion_tpu.train.dpo import make_dpo_loss_fn_frozen

    mesh = make_mesh(data=4, tensor=2)
    base = llama_init(jax.random.key(0), MODEL)
    lora_cfg = LoraConfig(r=4, alpha=8, target_patterns=("wq", "wk", "wv", "wo"))
    adapters = lora_init(jax.random.key(1), base, lora_cfg)
    base_specs = llama_param_specs(MODEL)
    adapter_specs = lora_adapter_specs(adapters, base_specs, TENSOR_AXIS)

    def policy_apply(params, frozen, tokens):
        eff = apply_ad(frozen["base"], params, lora_cfg, tp_axis=TENSOR_AXIS,
                       base_specs=base_specs)
        return llama_apply(eff, tokens, MODEL, tp_axis=TENSOR_AXIS)

    loss_fn = make_dpo_loss_fn_frozen(
        policy_apply=policy_apply,
        ref_apply=lambda frozen, t: llama_apply(frozen["ref"], t, MODEL,
                                                tp_axis=TENSOR_AXIS),
    )
    cfg = _cfg(tensor_parallel=2, max_steps=3)
    trainer = Trainer(cfg, mesh, apply_fn=None, params=adapters,
                      loss_fn=loss_fn, param_specs=adapter_specs,
                      frozen_params={"base": base, "ref": base},
                      frozen_specs={"base": base_specs, "ref": base_specs})
    rng = np.random.default_rng(0)
    gb = trainer.global_train_batch()

    def batches():
        while True:
            tok = rng.integers(0, MODEL.vocab_size, size=(gb, 32)).astype(np.int32)
            mask = np.ones((gb, 32), np.float32)
            yield {"chosen": tok, "rejected": tok[::-1].copy(),
                   "chosen_mask": mask, "rejected_mask": mask}

    hist = trainer.train(batches(), max_steps=3)
    assert all(np.isfinite(h["loss"]) for h in hist if "loss" in h)
    trainer.close()


def test_lora_7b_widths_smoke():
    """Factored LoRA at Llama-2-7B widths (d=4096, d_ff=11008, vocab 32000;
    depth scaled to 2 layers): one SFT train step runs and is finite. Pins
    that the factored path never materializes W+dW at 7B-width shapes (the
    merged form would build a second full weight set inside the step)."""
    model = LlamaConfig.llama2_7b(n_layer=2, n_ctx=128,
                                  param_dtype=jnp.bfloat16)
    base = llama_init(jax.random.key(0), model)
    lora_cfg = LoraConfig(r=8, alpha=16)
    adapters = lora_init(jax.random.key(1), base, lora_cfg)
    apply = lora_apply_fn(lambda p, t: llama_apply(p, t, model), base, lora_cfg)
    mesh = make_mesh(data=1, devices=jax.devices()[:1])
    cfg = _cfg(per_device_train_batch_size=1, block_size=128, max_steps=1)
    trainer = Trainer(cfg, mesh, lambda p, t, key: apply(p, t), adapters)
    tokens = np.random.default_rng(0).integers(
        0, model.vocab_size, size=(1, 128)).astype(np.int32)

    def batches():
        while True:
            yield tokens

    hist = trainer.train(batches(), max_steps=1)
    assert np.isfinite(hist[-1]["loss"])
    trainer.close()


def test_gpt2_lora_decode():
    """GPT-2 generation consumes LoraTensor-adapted params (factored qkv and
    proj dispatch in the decode path)."""
    from distributed_lion_tpu.models.gpt2 import (
        GPT2Config, gpt2_apply, gpt2_decode, gpt2_init, gpt2_init_cache,
    )

    model = GPT2Config.tiny(compute_dtype=jnp.float32)
    base = gpt2_init(jax.random.key(0), model)
    cfg = LoraConfig(r=4, alpha=8, target_patterns=("qkv", "proj", "fc"))
    adapters = lora_init(jax.random.key(1), base, cfg)
    adapters = jax.tree.map(
        lambda x: x + 0.01 * jax.random.normal(jax.random.key(2), x.shape, x.dtype),
        adapters)
    eff = apply_adapters(base, adapters, cfg)
    tokens = np.random.default_rng(0).integers(0, model.vocab_size,
                                               size=(2, 8)).astype(np.int32)
    full = gpt2_apply(eff, tokens, model)
    dec, _ = gpt2_decode(eff, tokens, model, gpt2_init_cache(model, 2, 8), 0)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full),
                               rtol=1e-4, atol=1e-4)
