"""GPT-2 model tests: shapes, causality, dtype discipline, param count."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.models.gpt2 import GPT2Config, count_params, gpt2_apply, gpt2_init
from distributed_lion_tpu.models.loss import clm_loss_and_metrics


def test_forward_shapes_and_dtype():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = gpt2_apply(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32  # f32 logits out of bf16 compute


def test_causality():
    """Changing a future token must not affect past logits."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(1, 12)).astype(np.int32)
    toks2 = toks.copy()
    toks2[0, -1] = (toks2[0, -1] + 1) % cfg.vocab_size
    l1 = gpt2_apply(params, jnp.asarray(toks), cfg)
    l2 = gpt2_apply(params, jnp.asarray(toks2), cfg)
    np.testing.assert_array_equal(np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]))
    assert not np.array_equal(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


def test_param_count_124m():
    cfg = GPT2Config.gpt2_124m()
    shapes = jax.eval_shape(lambda k: gpt2_init(k, cfg), jax.random.key(0))
    n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert 124_000_000 < n < 125_000_000  # GPT-2 small, tied embeddings


def test_loss_and_accuracy():
    logits = jnp.zeros((1, 4, 10))
    # make position 0 predict the label at position 1 perfectly
    logits = logits.at[0, 0, 7].set(100.0)
    tokens = jnp.asarray([[1, 7, 2, 3]], jnp.int32)
    loss, m = clm_loss_and_metrics(logits, tokens)
    assert float(m["accuracy"]) >= 1 / 3  # 1 of 3 shifted positions correct
    assert float(m["n_tokens"]) == 3.0
    # uniform logits → loss ≈ ln(10) on the other positions
    assert 0.0 < float(loss) < np.log(10) + 0.1


def test_loss_mask():
    logits = jnp.zeros((1, 4, 10))
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    mask = jnp.asarray([[0, 0, 1, 1]], jnp.float32)  # only last two labels count
    _, m = clm_loss_and_metrics(logits, tokens, mask)
    assert float(m["n_tokens"]) == 2.0


def test_dropout_changes_output_only_with_key():
    cfg = GPT2Config.tiny(dropout=0.5)
    params = gpt2_init(jax.random.key(0), cfg)
    toks = jnp.ones((1, 8), jnp.int32)
    a = gpt2_apply(params, toks, cfg, dropout_key=jax.random.key(1))
    b = gpt2_apply(params, toks, cfg, dropout_key=jax.random.key(2))
    c = gpt2_apply(params, toks, cfg)  # deterministic (eval) path
    d = gpt2_apply(params, toks, cfg)
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(c), np.asarray(d))


def test_remat_policy_dots_matches_full():
    """remat_policy is a perf knob, not a numerics knob: same loss, same
    grads as the full-recompute policy."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init

    toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32)

    def loss_for(policy):
        cfg = GPT2Config.tiny(remat=True, remat_policy=policy)
        params = gpt2_init(jax.random.key(0), cfg)

        def loss(p):
            logits = gpt2_apply(p, toks, cfg)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        return jax.value_and_grad(loss)(params)

    l_full, g_full = loss_for("full")
    l_dots, g_dots = loss_for("dots")
    np.testing.assert_allclose(float(l_full), float(l_dots), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_dots)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
