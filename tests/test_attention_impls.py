"""Attention implementation parity: xla / xla_bf16 / flash / splash dispatch.

The XLA materialized-scores path is the semantic reference; the Pallas
kernels (flash, splash) must match it numerically — forward AND backward —
since those impls are pure perf knobs. The one exception is ``xla_bf16``,
which INTENTIONALLY trades ~bf16-rounding error on the stored scores for
HBM bandwidth (its test below bounds the divergence rather than demanding
parity). Kernels run in interpret mode here (no TPU in CI).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from distributed_lion_tpu.ops.attention import (
    attention,
    attention_splash,
    attention_xla,
)


def _qkv(B=2, H=4, T=128, hd=64, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    return (jax.random.normal(k1, (B, H, T, hd), jnp.float32),
            jax.random.normal(k2, (B, H, T, hd), jnp.float32),
            jax.random.normal(k3, (B, H, T, hd), jnp.float32))


def test_splash_forward_matches_xla():
    q, k, v = _qkv()
    ref = attention_xla(q, k, v)
    got = attention_splash(q, k, v, interpret=True)
    assert float(jnp.abs(ref - got).max()) < 2e-3


def test_splash_backward_matches_xla():
    q, k, v = _qkv(seed=1)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    g_ref = jax.grad(loss(attention_xla), argnums=(0, 1, 2))(q, k, v)
    g_spl = jax.grad(
        loss(lambda q, k, v: attention_splash(q, k, v, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_spl):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9))
        assert rel < 5e-3, rel


def test_splash_block_size_override():
    q, k, v = _qkv(T=256, seed=2)
    ref = attention_xla(q, k, v)
    got = attention_splash(q, k, v, interpret=True, block_q=128, block_kv=128)
    assert float(jnp.abs(ref - got).max()) < 2e-3


def test_dispatch_names():
    q, k, v = _qkv(T=64)
    # xla always available; unknown impl refused
    attention(q, k, v, impl="xla")
    with pytest.raises(ValueError, match="unknown attention impl"):
        attention(q, k, v, impl="warp")


def test_xla_bf16_close_to_xla():
    """xla_bf16 stores bf16 scores (throughput opt-in) — forward must stay
    within bf16 rounding of the f32-scores path, gradients finite and
    close in relative terms."""
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(seed=3))
    ref = attention(q, k, v, impl="xla").astype(jnp.float32)
    got = attention(q, k, v, impl="xla_bf16").astype(jnp.float32)
    assert float(jnp.abs(ref - got).max()) < 5e-2

    def loss(impl):
        return lambda q, k, v: (attention(q, k, v, impl=impl)
                                .astype(jnp.float32) ** 2).sum()

    g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss("xla_bf16"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_got):
        a, b = a.astype(jnp.float32), b.astype(jnp.float32)
        assert bool(jnp.all(jnp.isfinite(b)))
        rel = float(jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9))
        assert rel < 5e-2, rel


def test_parse_attn_spec_grammar():
    """impl[@BQxBKV[@BQBxBKVB]] — fwd-only, fwd+bwd, and bare forms."""
    from distributed_lion_tpu.ops.attention import parse_attn_spec

    assert parse_attn_spec("xla") == ("xla", 0, 0, 0, 0)
    assert parse_attn_spec("flash@512x1024") == ("flash", 512, 1024, 0, 0)
    assert parse_attn_spec("flash@512x1024@256x512") == \
        ("flash", 512, 1024, 256, 512)
    assert parse_attn_spec("splash@128x256") == ("splash", 128, 256, 0, 0)


def test_bwd_tiles_refused_off_flash():
    from distributed_lion_tpu.ops.attention import attention

    q = k = v = jnp.zeros((1, 2, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="flash-kernel knob"):
        attention(q, k, v, impl="splash", block_q_bwd=64)
    with pytest.raises(ValueError, match="flash-kernel knob"):
        attention(q, k, v, impl="xla", block_kv_bwd=128)
