"""Attention implementation parity: xla / xla_bf16 / flash / splash dispatch.

The XLA materialized-scores path is the semantic reference; the Pallas
kernels (flash, splash) must match it numerically — forward AND backward —
since those impls are pure perf knobs. The one exception is ``xla_bf16``,
which INTENTIONALLY trades ~bf16-rounding error on the stored scores for
HBM bandwidth (its test below bounds the divergence rather than demanding
parity). Kernels run in interpret mode here (no TPU in CI).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from distributed_lion_tpu.ops.attention import (
    attention,
    attention_splash,
    attention_xla,
)


def _qkv(B=2, H=4, T=128, hd=64, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    return (jax.random.normal(k1, (B, H, T, hd), jnp.float32),
            jax.random.normal(k2, (B, H, T, hd), jnp.float32),
            jax.random.normal(k3, (B, H, T, hd), jnp.float32))


def test_splash_forward_matches_xla():
    q, k, v = _qkv()
    ref = attention_xla(q, k, v)
    got = attention_splash(q, k, v, interpret=True)
    assert float(jnp.abs(ref - got).max()) < 2e-3


def test_splash_backward_matches_xla():
    q, k, v = _qkv(seed=1)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    g_ref = jax.grad(loss(attention_xla), argnums=(0, 1, 2))(q, k, v)
    g_spl = jax.grad(
        loss(lambda q, k, v: attention_splash(q, k, v, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_spl):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9))
        assert rel < 5e-3, rel


def test_splash_block_size_override():
    q, k, v = _qkv(T=256, seed=2)
    ref = attention_xla(q, k, v)
    got = attention_splash(q, k, v, interpret=True, block_q=128, block_kv=128)
    assert float(jnp.abs(ref - got).max()) < 2e-3


def test_dispatch_names():
    q, k, v = _qkv(T=64)
    # xla always available; unknown impl refused
    attention(q, k, v, impl="xla")
    with pytest.raises(ValueError, match="unknown attention impl"):
        attention(q, k, v, impl="warp")


def test_xla_bf16_close_to_xla():
    """xla_bf16 stores bf16 scores (throughput opt-in) — forward must stay
    within bf16 rounding of the f32-scores path, gradients finite and
    close in relative terms."""
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(seed=3))
    ref = attention(q, k, v, impl="xla").astype(jnp.float32)
    got = attention(q, k, v, impl="xla_bf16").astype(jnp.float32)
    assert float(jnp.abs(ref - got).max()) < 5e-2

    def loss(impl):
        return lambda q, k, v: (attention(q, k, v, impl=impl)
                                .astype(jnp.float32) ** 2).sum()

    g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss("xla_bf16"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_got):
        a, b = a.astype(jnp.float32), b.astype(jnp.float32)
        assert bool(jnp.all(jnp.isfinite(b)))
        rel = float(jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9))
        assert rel < 5e-2, rel


def test_parse_attn_spec_grammar():
    """impl[@BQxBKV[@BQBxBKVB]] — fwd-only, fwd+bwd, and bare forms."""
    from distributed_lion_tpu.ops.attention import parse_attn_spec

    assert parse_attn_spec("xla") == ("xla", 0, 0, 0, 0)
    assert parse_attn_spec("flash@512x1024") == ("flash", 512, 1024, 0, 0)
    assert parse_attn_spec("flash@512x1024@256x512") == \
        ("flash", 512, 1024, 256, 512)
    assert parse_attn_spec("splash@128x256") == ("splash", 128, 256, 0, 0)


def test_bwd_tiles_refused_off_flash():
    from distributed_lion_tpu.ops.attention import attention

    q = k = v = jnp.zeros((1, 2, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="flash-kernel knob"):
        attention(q, k, v, impl="splash", block_q_bwd=64)
    with pytest.raises(ValueError, match="flash-kernel knob"):
        attention(q, k, v, impl="xla", block_kv_bwd=128)

def test_auto_picks_tuned_flash_at_swept_flagship_shape(monkeypatch):
    """VERDICT r3 item 6: `auto` on TPU at the swept flagship shape
    (T=1024, no caller-pinned tiles) must dispatch to the MEASURED winner —
    tile-tuned flash@512x1024 (98,099 tok/s/chip vs xla's 85.7k,
    scripts/SWEEP_r3_raw/sweep2.jsonl) — while unswept shapes keep the xla
    fallback and caller-pinned tiles are honored. Backend + kernel are
    monkeypatched: this pins DISPATCH, the kernels' math is pinned by the
    equivalence tests above."""
    from distributed_lion_tpu.ops import attention as A

    calls = []

    def fake_flash(q, k, v, *, causal=True, block_q=0, block_kv=0,
                   block_q_bwd=0, block_kv_bwd=0):
        calls.append((block_q, block_kv, block_q_bwd, block_kv_bwd))
        return q

    def fake_xla(q, k, v, *, causal=True, score_dtype=None):
        calls.append("xla")
        return q

    monkeypatch.setattr(A, "attention_flash", fake_flash)
    monkeypatch.setattr(A, "attention_xla", fake_xla)
    monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")

    q, k, v = _qkv(T=1024)
    A.attention(q, k, v, impl="auto")
    assert calls[-1] == (512, 1024, 0, 0)  # tuned tiles at the swept shape

    q, k, v = _qkv(T=1024, hd=128)
    A.attention(q, k, v, impl="auto")
    # T=1024 but head_dim 128 (Llama shapes): NOT the swept shape — the
    # GPT-2-tuned tiles must not leak onto it (keeps the 7B bench leg's
    # round-3 xla methodology)
    assert calls[-1] == "xla"

    A.attention(q, k, v, impl="auto", block_q=256, block_kv=256)
    assert calls[-1] == (256, 256, 0, 0)  # pinned tiles honored via flash

    q, k, v = _qkv(T=512)
    A.attention(q, k, v, impl="auto")
    assert calls[-1] == "xla"  # unswept shape keeps the conservative path

    A.attention(q, k, v, impl="auto", block_q=128, block_kv=128)
    assert calls[-1] == (128, 128, 0, 0)  # pinned tiles win at any shape

    q, k, v = _qkv(T=2048)
    A.attention(q, k, v, impl="auto")
    assert calls[-1] == (0, 0, 0, 0)  # long-context regime: default flash

    monkeypatch.setattr(A.jax, "default_backend", lambda: "cpu")
    q, k, v = _qkv(T=1024)
    A.attention(q, k, v, impl="auto")
    assert calls[-1] == "xla"  # no TPU: never the pallas kernel


def test_auto_bwd_only_tiles_dispatch(monkeypatch):
    """ISSUE 3 satellite: `auto` with ONLY backward tiles pinned must
    dispatch to flash on TPU (honoring the tiles), and off TPU must degrade
    to xla with the flash-only knobs dropped — never fall into the
    explicit-impl flash-knob ValueError (that guard is for explicit
    xla/splash requests that would silently tune nothing)."""
    from distributed_lion_tpu.ops import attention as A

    calls = []

    def fake_flash(q, k, v, *, causal=True, block_q=0, block_kv=0,
                   block_q_bwd=0, block_kv_bwd=0):
        calls.append((block_q, block_kv, block_q_bwd, block_kv_bwd))
        return q

    def fake_xla(q, k, v, *, causal=True, score_dtype=None):
        calls.append("xla")
        return q

    monkeypatch.setattr(A, "attention_flash", fake_flash)
    monkeypatch.setattr(A, "attention_xla", fake_xla)

    q, k, v = _qkv(T=512)
    monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")
    A.attention(q, k, v, impl="auto", block_q_bwd=256, block_kv_bwd=512)
    assert calls[-1] == (0, 0, 256, 512)  # bwd-only pins reach flash intact

    monkeypatch.setattr(A.jax, "default_backend", lambda: "cpu")
    A.attention(q, k, v, impl="auto", block_q_bwd=256, block_kv_bwd=512)
    assert calls[-1] == "xla"  # degrades like bare auto, no ValueError

    # the explicit-impl guard stays loud
    with pytest.raises(ValueError, match="flash-kernel knob"):
        A.attention(q, k, v, impl="xla", block_q_bwd=256)
