"""Trainable MoE (VERDICT r1 item 5): GPT-2 with Switch-MoE FFN blocks,
vote-Lion training over dp and dp x ep meshes.

Pins: loss decreases on the 8-device mesh with --moe_experts; expert
parallelism (dispatch/return all_to_all + expert-sharded grads + the
expert-axis grad psum for dense leaves) trains and keeps replicas
consistent; ep=1 and ep=4 agree on the forward loss.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer

MODEL = GPT2Config.tiny(n_layer=4, moe_experts=4)


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=3e-3, warmup_steps=2,
        max_steps=30, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=32, logging_steps=5,
        output_dir=None, seed=7,
    )
    base.update(kw)
    return TrainConfig(**base)


def test_moe_init_structure():
    params = gpt2_init(jax.random.key(0), MODEL)
    moe_blocks = [i for i, b in enumerate(params["blocks"]) if "moe" in b]
    assert moe_blocks == [1, 3]  # every 2nd block (moe_every=2)
    assert params["blocks"][1]["moe"]["w_in"].shape == (4, 64, 256)


def test_moe_loss_decreases_dp():
    """run_clm semantics: --moe_experts 4 on a pure-dp 8-device mesh."""
    mesh = make_mesh(data=8)
    trainer = Trainer.for_gpt2(_cfg(), mesh, MODEL, seed=1)
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  MODEL.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, losses
    assert all(np.isfinite(h.get("aux_loss", 1.0)) for h in hist)
    trainer.close()


def test_moe_expert_parallel_trains():
    """dp=2 x ep=4: expert banks sharded, tokens over both axes."""
    mesh = make_mesh(data=2, expert=4)
    trainer = Trainer.for_gpt2(_cfg(max_steps=20), mesh, MODEL, seed=1)
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  MODEL.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.2, losses
    # dense params replicated across ALL devices must agree bit-for-bit
    wte = trainer.params["wte"]
    shards = [np.asarray(s.data) for s in wte.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    trainer.close()


def test_moe_ep_forward_matches_ep1():
    """Same params, same rows: the ep=4 sharded forward loss must equal the
    single-device forward (routing/drops are identical — capacity is computed
    per LOCAL token count, so use equal local counts)."""
    from jax import shard_map

    from distributed_lion_tpu.models.loss import clm_loss_sharded_rows

    mesh = make_mesh(data=2, expert=4)
    params = gpt2_init(jax.random.key(0), MODEL)
    specs = None
    from distributed_lion_tpu.models.gpt2 import gpt2_moe_param_specs

    specs = gpt2_moe_param_specs(MODEL)
    rows = 16  # 2 per (data, expert) shard
    tokens = np.random.default_rng(0).integers(
        0, MODEL.vocab_size, size=(rows, 32)).astype(np.int32)

    @jax.jit
    def sharded_loss(params, tokens):
        def body(p, t):
            loss_local, m = clm_loss_sharded_rows(
                gpt2_apply(p, t, MODEL, expert_axis="expert", return_aux=True)[0],
                t, "expert")
            return jax.lax.pmean(m["loss"], "data")

        return shard_map(
            body, mesh=mesh, in_specs=(specs, P(("data", "expert"))),
            out_specs=P(), check_vma=False,
        )(params, tokens)

    got = float(sharded_loss(params, tokens))

    # reference: per-2-row groups through the single-device moe (same local
    # capacity as each (data, expert) shard saw), loss = token-weighted mean
    from distributed_lion_tpu.models.loss import clm_loss_and_metrics

    losses = []
    for i in range(0, rows, 2):
        logits = gpt2_apply(params, tokens[i:i + 2], MODEL, return_aux=True)[0]
        losses.append(float(clm_loss_and_metrics(logits, tokens[i:i + 2])[0]))
    ref = float(np.mean(losses))
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_moe_guards():
    mesh = make_mesh(data=2, expert=4)
    with pytest.raises(ValueError, match="divisible"):
        Trainer.for_gpt2(_cfg(), mesh, GPT2Config.tiny(n_layer=4, moe_experts=6))
    with pytest.raises(ValueError, match="expert"):
        Trainer.for_gpt2(_cfg(), mesh, GPT2Config.tiny(n_layer=4))  # dense + ep>1


def test_moe_decode_matches_apply():
    """The export->generate cycle works for MoE checkpoints: cached decode
    logits match the full forward position-for-position. Decode never drops
    tokens (capacity_override = per-call token count), so compare against a
    capacity_factor high enough that the full forward doesn't drop either —
    where both paths keep every token, they must agree."""
    from distributed_lion_tpu.models.gpt2 import gpt2_decode, gpt2_init_cache

    model = GPT2Config.tiny(n_layer=4, moe_experts=4, moe_capacity_factor=4.0)
    params = gpt2_init(jax.random.key(2), model)
    tokens = np.random.default_rng(1).integers(
        0, model.vocab_size, size=(2, 12)).astype(np.int32)
    full = gpt2_apply(params, tokens, model, return_aux=True)[0]
    cache = gpt2_init_cache(model, 2, 16)
    dec, _ = gpt2_decode(params, tokens, model, cache, 0)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full),
                               rtol=2e-2, atol=2e-2)


def test_moe_ep_tp_trajectory_matches_ep():
    """dp=2 x ep=2 x tp=2 ≡ dp=2 x ep=2: adding the tensor axis —
    Megatron-split attention AND per-expert FFNs (w_in column / w_out row,
    b_out added after the row psum) — is a pure re-schedule on top of the
    ep mesh: identical routing groups, identical voters. (ep itself is NOT
    trajectory-equal to pure dp: row sharding changes the voter grouping —
    its semantics are pinned by the forward-equality and convergence tests
    above.) f32 compute so the vote's sign threshold sees no reordering
    noise."""
    import dataclasses

    model_f32 = dataclasses.replace(MODEL, compute_dtype=np.float32,
                                    moe_experts=2)

    def run(mesh, **cfg_kw):
        cfg = _cfg(learning_rate=1e-3, max_steps=5, logging_steps=1, **cfg_kw)
        trainer = Trainer.for_gpt2(cfg, mesh, model_f32, seed=123)
        blocks = synthetic_lm_dataset(
            max(64, trainer.global_train_batch() * 2), 32,
            model_f32.vocab_size, seed=11)
        hist = trainer.train(
            batch_iterator(blocks, trainer.global_train_batch(), seed=0),
            max_steps=5)
        params = jax.tree.map(np.asarray, jax.device_get(trainer.params))
        trainer.close()
        return [h["loss"] for h in hist if "loss" in h], params

    losses_ep, params_ep = run(
        make_mesh(data=2, expert=2, devices=jax.devices()[:4]),
        expert_parallel=2)
    losses_x, params_x = run(make_mesh(data=2, expert=2, tensor=2),
                             expert_parallel=2, tensor_parallel=2)
    np.testing.assert_allclose(losses_x, losses_ep, rtol=1e-4, atol=1e-4)
    envelope = 2 * 1e-3 * 5
    for a, b in zip(jax.tree.leaves(params_ep), jax.tree.leaves(params_x)):
        assert np.abs(a.astype(np.float64) - b.astype(np.float64)).max() \
            <= envelope


def test_moe_tp_only_trains():
    """ep=1 with tp=2: the tensor split applies without an expert axis."""
    mesh = make_mesh(data=4, tensor=2)
    trainer = Trainer.for_gpt2(_cfg(max_steps=20, tensor_parallel=2),
                               mesh, MODEL, seed=1)
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  MODEL.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(),
                                        seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, losses
    trainer.close()
