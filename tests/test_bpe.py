"""GPT-2 byte-level BPE (VERDICT r1 item 7).

The strongest offline compatibility check available: train a vocabulary
with our trainer, save it in the published vocab.json/merges.txt format,
load THE SAME FILES with ``transformers.GPT2Tokenizer`` (the reference's
tokenizer class, run_clm.py:398-423), and demand token-for-token identical
encodings. That pins the byte↔unicode table, the pre-tokenization regex,
and the merge procedure — so the real GPT-2 files are a drop-in for the
true 50257 vocabulary.
"""

import numpy as np
import pytest

from distributed_lion_tpu.data.bpe import (
    BPETokenizer,
    bytes_to_unicode,
    train_bpe,
    unicode_to_bytes,
)

CORPUS = [
    "The quick brown fox jumps over the lazy dog. " * 20,
    "Distributed Lion votes with one bit per parameter, per worker. " * 20,
    "Pack my box with five dozen liquor jugs — naturally! " * 20,
    "números, façade, naïve, 北京, emoji 🦁 and tabs\tand\nnewlines. " * 10,
]
HELD_OUT = (
    "A naïve fox votes 42 times\nwith one-bit ballots — quick! 北京 🦁 "
    "jugs over the lazy parameter."
)


@pytest.fixture(scope="module")
def tok():
    return train_bpe(CORPUS, vocab_size=600)


def test_byte_unicode_table_bijection():
    b2u = bytes_to_unicode()
    assert len(b2u) == 256
    assert len(set(b2u.values())) == 256
    u2b = unicode_to_bytes()
    assert all(u2b[v] == k for k, v in b2u.items())


def test_roundtrip(tok):
    for text in CORPUS + [HELD_OUT]:
        ids = tok.encode(text)
        assert tok.decode(ids) == text
    assert tok.decode(tok.encode(HELD_OUT, add_bos=True, add_eos=True)) == HELD_OUT


def test_compression(tok):
    ids = tok.encode(CORPUS[0])
    assert len(ids) < len(CORPUS[0].encode("utf-8")) * 0.6  # beats bytes


def test_save_load_identical(tok, tmp_path):
    tok.save(str(tmp_path))
    rt = BPETokenizer.load(str(tmp_path))
    assert rt.vocab == tok.vocab
    assert rt.encode(HELD_OUT) == tok.encode(HELD_OUT)


def test_matches_hf_gpt2_tokenizer(tok, tmp_path):
    """Our files + our encoder == transformers' GPT2Tokenizer on the same
    files: exact algorithm/format compatibility."""
    transformers = pytest.importorskip("transformers")
    tok.save(str(tmp_path))
    hf = transformers.GPT2Tokenizer(
        vocab_file=str(tmp_path / "vocab.json"),
        merges_file=str(tmp_path / "merges.txt"),
    )
    for text in [HELD_OUT] + CORPUS:
        ours = tok.encode(text)
        theirs = hf.encode(text)
        assert ours == theirs, (text[:40], ours[:10], theirs[:10])


def test_load_tokenizer_dispatch(tok, tmp_path):
    from distributed_lion_tpu.data.tokenizer import load_tokenizer

    tok.save(str(tmp_path))
    t1 = load_tokenizer(f"bpe:{tmp_path}")
    t2 = load_tokenizer(str(tmp_path))  # auto-detect vocab.json+merges.txt
    assert t1.encode(HELD_OUT) == t2.encode(HELD_OUT) == tok.encode(HELD_OUT)
    fallback = load_tokenizer(None)
    assert fallback.vocab_size == 259


def test_text_pipeline_with_bpe(tok, tmp_path):
    """run_clm's text: data path tokenizes with the trained BPE."""
    from distributed_lion_tpu.data.sources import tokens_from_text_files

    tok.save(str(tmp_path / "tok"))
    corpus_file = tmp_path / "corpus.txt"
    corpus_file.write_text(" ".join(CORPUS), encoding="utf-8")
    blocks = tokens_from_text_files([str(corpus_file)], block_size=32,
                                    tokenizer_name=f"bpe:{tmp_path / 'tok'}")
    assert len(blocks) > 0 and blocks.dtype == np.int32 or blocks.dtype == np.uint16
    assert int(np.asarray(blocks).max()) < tok.vocab_size
