"""Serve observability plane (ISSUE 17): the LogHistogram sketch pinned
against numpy (accuracy bound + merge algebra), the request clocks, the
SLO monitor's burn-rate/breach semantics under an injected clock, the
metrics-on == metrics-off bit-identity matrix (the plane must be
observationally free), the workload generator's determinism + schema,
the timing columns on every terminal status, and the banked slo section
of the serving evidence artifact."""

import importlib.util
import json
import os

import numpy as np
import pytest

from distributed_lion_tpu.serve.metrics import (
    LogHistogram,
    RequestTimes,
    ServeMetrics,
    SLOMonitor,
    TickLatencyWindow,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, *rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, *rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ the sketch
def test_sketch_percentiles_match_numpy_within_bin_bound():
    """Percentile queries answer within the geometric-bin error bound: a
    value lands in a bin of width ratio base = 10**(1/bins_per_decade)
    and is reported as the bin's geometric midpoint, so the relative
    error is at most sqrt(base) - 1 (~3.7% at 32 bins/decade) plus the
    rank discretization — pinned at 8% against numpy on a heavy-tail
    sample, the shape serve latencies actually have."""
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=2.0, sigma=1.2, size=5000)
    sk = LogHistogram()
    for v in samples:
        sk.add(float(v))
    for q in (50.0, 95.0, 99.0):
        ref = float(np.percentile(samples, q))
        got = sk.percentile(q)
        assert abs(got - ref) / ref < 0.08, (q, got, ref)
    s = sk.summary()
    assert s["count"] == 5000
    assert s["min"] == pytest.approx(float(samples.min()))
    assert s["max"] == pytest.approx(float(samples.max()))
    assert s["mean"] == pytest.approx(float(samples.mean()))


def test_sketch_merge_is_associative_and_matches_union():
    """merge is pure bin-count addition: (a+b)+c == a+(b+c) == the
    sketch built from the concatenated samples, bin-for-bin — the
    property that lets a fleet fold replicas in any order."""
    rng = np.random.default_rng(11)
    parts = [rng.lognormal(1.0, s, size=400) for s in (0.5, 1.0, 1.5)]
    sks = []
    for p in parts:
        sk = LogHistogram()
        for v in p:
            sk.add(float(v))
        sks.append(sk)
    union = LogHistogram()
    for v in np.concatenate(parts):
        union.add(float(v))
    left = sks[0].merge(sks[1]).merge(sks[2])
    right = sks[0].merge(sks[1].merge(sks[2]))
    for m in (left, right):
        np.testing.assert_array_equal(m.counts, union.counts)
        assert m.n == union.n
        assert m.vmin == union.vmin and m.vmax == union.vmax
        assert m.percentile(99.0) == union.percentile(99.0)
    # inputs are untouched (merge is pure, not in-place)
    assert sks[0].n == 400
    # layout mismatch refuses instead of silently mis-binning
    with pytest.raises(ValueError, match="layout"):
        sks[0].merge(LogHistogram(bins_per_decade=16))


def test_sketch_refuses_bad_samples_and_empty_is_honest():
    sk = LogHistogram()
    with pytest.raises(ValueError, match="non-finite"):
        sk.add(float("nan"))
    with pytest.raises(ValueError, match="non-finite"):
        sk.add(float("inf"))
    with pytest.raises(ValueError, match="count"):
        sk.add(1.0, count=0)
    assert sk.percentile(99.0) == 0.0
    assert sk.summary()["count"] == 0
    # out-of-range values land in the under/overflow buckets, clamped to
    # the observed extrema on query — never dropped, never exaggerated
    sk.add(1e-9)
    sk.add(1e9)
    assert sk.n == 2
    assert sk.percentile(0.0) == pytest.approx(1e-9)
    assert sk.percentile(100.0) == pytest.approx(1e9)


def test_tick_latency_window_recency_vs_history():
    """The bounded window answers RECENT percentiles exactly (numpy over
    the last `window` samples) while the sketch keeps full history —
    the slow-replica gate reads the window, so a one-off jit-compile
    spike ages out instead of dominating p99 forever."""
    win = TickLatencyWindow(window=8)
    win.add(1000.0)                      # the compile spike
    for _ in range(20):
        win.add(1.0)
    assert len(win) == 21                # full history count
    assert win.percentile(99) == pytest.approx(1.0)   # spike aged out
    assert win.sketch.n == 21            # ...but not forgotten
    assert win.sketch.vmax == 1000.0


# ----------------------------------------------------- the request clocks
def test_request_times_derivations_and_queue_side_death():
    rt = RequestTimes()
    rt.submitted("a", 3)
    rt.first_token("a", 5)
    assert rt.finished("a", 9) == {
        "queue_ticks": 2, "ttft_ticks": 2, "decode_ticks": 4}
    # queue-side death: the whole life was queue wait
    rt.submitted("b", 1)
    assert rt.finished("b", 7) == {"queue_ticks": 6, "decode_ticks": 0}
    # clocks retire on finish — steady-state memory is inflight-bounded
    assert rt._submit == {} and rt._first == {}


# -------------------------------------------------------- the SLO monitor
def test_slo_monitor_burn_rate_and_edge_triggered_breach():
    """Burn rate = window violation fraction / error budget; crossing
    1.0 with enough samples counts ONE breach until the window recovers
    (edge-triggered — a sustained breach is one event, not one per
    request). With p99=0.90 the budget is 0.10, so 2 violations in a
    10-wide window burn at exactly 2.0."""
    m = SLOMonitor(ttft_ms=100.0, tok_ms=10.0, p99=0.90, window=10,
                   min_count=4)
    for _ in range(8):
        assert m.observe(50.0, 5.0) is False
    assert m.burn_rate() == 0.0 and m.breaches == 0
    assert m.observe(500.0, 5.0) is True          # TTFT violation
    assert m.observe(50.0, 50.0) is True          # tok-latency violation
    assert m.burn_rate() == pytest.approx(2.0)
    assert m.breaches == 1
    assert m.violations_ttft == 1 and m.violations_tok == 1
    # sustained breach: no double count
    m.observe(500.0, 5.0)
    assert m.breaches == 1
    # a request that never produced a token violates a monitored TTFT
    assert m.observe(None, None) is True
    # recovery re-arms the edge
    for _ in range(10):
        m.observe(50.0, 5.0)
    assert m.burn_rate() == 0.0
    m.observe(500.0, 5.0)
    m.observe(500.0, 5.0)
    assert m.breaches == 2
    snap = m.snapshot()
    assert snap["requests"] == m.requests
    assert snap["error_budget"] == pytest.approx(0.1)


def test_slo_breach_under_injected_slow_tick_journals_event(tmp_path):
    """The end-to-end breach path under a DETERMINISTIC injected clock:
    a ServeMetrics plane whose time_fn serves scripted stamps sees slow
    TTFTs, the armed monitor crosses burn rate 1.0, and the breach rides
    the run journal as a strict-JSON `slo_breach` event."""
    from distributed_lion_tpu.train import journal as journal_mod

    clock = iter(x / 1000.0 for x in range(0, 100000, 500))  # 500ms steps
    sm = ServeMetrics(RequestTimes(), slo=SLOMonitor(
        ttft_ms=100.0, p99=0.90, window=8, min_count=4),
        time_fn=lambda: next(clock))
    jrnl = journal_mod.Journal(str(tmp_path))
    journal_mod.install(jrnl)
    try:
        for i in range(8):
            sm.on_submit(i)
            sm.on_first_token(i)     # every TTFT is 500ms > the 100ms SLO
            sm.on_finish(i, {"queue_ticks": 0, "ttft_ticks": 1,
                             "decode_ticks": 0}, "length", tick=i)
        sm.drain(64)
    finally:
        journal_mod.uninstall(jrnl)
        jrnl.close()
    assert sm.slo.breaches == 1
    events = []
    with open(tmp_path / "journal_rank0.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "event":
                events.append(rec)
    breach = [r for r in events if r["name"] == "slo_breach"]
    assert len(breach) == 1
    assert breach[0]["burn_rate"] > 1.0
    assert breach[0]["window_violations"] >= 4
    drained = [r for r in events if r["name"] == "serve_metrics"]
    assert len(drained) == 1
    assert drained[0]["ttft_ms_count"] == 8
    assert drained[0]["slo_violations"] == 8
    # the journal file stays strict-schema under the flattened fields
    vm = _load("vm_sm", "scripts", "validate_metrics.py")
    assert vm.validate_journal_file(
        str(tmp_path / "journal_rank0.jsonl")) == []


# ------------------------------------------- metrics-on == metrics-off
def _tiny_engine(metrics=False, slo=False, moe=False, **kw):
    import jax

    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
    from distributed_lion_tpu.serve.engine import (
        ServeConfig, ServeModel, ServingEngine)

    cfg = GPT2Config.tiny(moe_experts=4) if moe else GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    scfg = ServeConfig(max_seqs=4, block_size=4, max_blocks_per_seq=8,
                       metrics=metrics, **kw)
    model = ServeModel.for_gpt2(params, cfg)
    draft = model if kw.get("speculate", "").startswith("draft") else None
    eng = ServingEngine(model, scfg, draft_model=draft)
    if slo:
        eng.metrics = ServeMetrics(eng.times, slo=SLOMonitor(
            ttft_ms=10_000.0, tok_ms=10_000.0))
    return eng, cfg


def _workload(cfg, n=6, seed=3):
    from distributed_lion_tpu.serve.engine import Request

    rng = np.random.default_rng(seed)
    lens = (3, 9, 5, 14, 2, 7, 11)
    reqs = [Request(req_id=i,
                    tokens=[int(t) for t in
                            rng.integers(1, cfg.vocab_size,
                                         lens[i % len(lens)])],
                    max_new_tokens=8, seed=i) for i in range(n)]
    arrivals = {i: i // 2 for i in range(n)}
    return reqs, arrivals


@pytest.mark.parametrize("variant", [
    {},                                          # greedy
    {"temperature": 0.9, "top_k": 40},           # sampled
    {"prefix_cache": True},                      # CoW prefix cache
    {"speculate": "ngram:4"},                    # speculative decode
    {"tp": 2},                                   # tensor-parallel tick
    {"moe": True, "ep": 2},                      # expert-parallel MoE
])
def test_metrics_on_is_bit_identical_to_metrics_off(variant):
    """The whole plane must be observationally free: the SAME workload
    through a metrics+SLO-armed engine and a bare engine produces
    byte-identical token streams and reasons across the decode-path
    matrix — greedy / sampled / prefix-cache / speculative / tp."""
    eng_off, cfg = _tiny_engine(**variant)
    reqs, arrivals = _workload(cfg)
    base = eng_off.run(reqs, dict(arrivals))

    eng_on, _ = _tiny_engine(metrics=True, slo=True, **variant)
    reqs2, _ = _workload(cfg)
    done = eng_on.run(reqs2, dict(arrivals))

    assert set(done) == set(base)
    for i in base:
        assert done[i].tokens == base[i].tokens, i
        assert done[i].reason == base[i].reason, i
        # every completion carries the tick clocks; wall TTFT only when
        # the plane is armed
        assert isinstance(done[i].timing["queue_ticks"], int)
        assert isinstance(done[i].timing["decode_ticks"], int)
        assert "ttft_ms" in done[i].timing
        assert "ttft_ms" not in (base[i].timing or {})
    snap = eng_on.metrics.snapshot()
    assert snap["ttft_ms"]["count"] == len(reqs)
    assert snap["tok_ms"]["count"] > 0
    assert snap["slo"]["requests"] == len(reqs)


def test_metrics_on_fleet_migration_identity_and_aggregation():
    """The fleet leg of the matrix: a metrics-armed 2-replica fleet with
    an injected replica crash produces the same token streams as the
    bare single engine, every terminal status carries its timing, and
    metrics_snapshot() folds the surviving replicas' sketches."""
    from distributed_lion_tpu.serve.replica_plane import ServingFleet
    from distributed_lion_tpu.train import resilience

    eng, cfg = _tiny_engine()
    reqs, arrivals = _workload(cfg)
    base = eng.run(reqs, dict(arrivals))

    def factory():
        e, _ = _tiny_engine(metrics=True, slo=True)
        return e

    resilience.inject_fault(
        "serve", resilience.parse_serve_specs("replica_crash:0:2"))
    try:
        fleet = ServingFleet(factory, replicas=2)
        reqs2, _ = _workload(cfg)
        done = fleet.run(reqs2, dict(arrivals))
    finally:
        resilience.inject_fault("serve", [])
    assert fleet.stats["migrations"] > 0
    assert set(done) == set(base)
    for i in base:
        assert done[i].tokens == base[i].tokens, i
        assert isinstance(done[i].timing["queue_ticks"], int)
    snap = fleet.metrics_snapshot()
    assert snap is not None
    assert snap["ttft_ms"]["count"] >= len(reqs)
    assert snap["gauges"]["migrations"] == fleet.stats["migrations"]


def test_timing_columns_on_every_terminal_status():
    """A queue-side death is the status most tempted to skip the books:
    an engine with one slot and an immediate deadline must still emit
    queue_ticks/decode_ticks on the timeout completion (and the api
    response record echoes them)."""
    from distributed_lion_tpu.serve import api
    from distributed_lion_tpu.serve.engine import Request

    eng, cfg = _tiny_engine(metrics=True)
    reqs, _ = _workload(cfg, n=2)
    # req 1 expires while queued behind req 0 (deadline already passed)
    reqs[1] = Request(req_id=1, tokens=reqs[1].tokens, max_new_tokens=4,
                      seed=1, deadline_s=-1.0)
    done = eng.run(reqs, {0: 0, 1: 0})
    assert done[1].reason == "timeout"
    t = done[1].timing
    assert t["queue_ticks"] >= 0 and t["decode_ticks"] >= 0
    rec = api.completion_record(done[1])
    assert rec["reason"] == "timeout"
    assert isinstance(rec["queue_ticks"], int)
    assert isinstance(rec["decode_ticks"], int)


# ------------------------------------------------- workload_gen + schema
def test_workload_gen_deterministic_and_schema_valid(tmp_path):
    wg = _load("wg_sm", "scripts", "workload_gen.py")
    a = wg.generate(requests=40, seed=5, deadline_frac=0.3)
    b = wg.generate(requests=40, seed=5, deadline_frac=0.3)
    assert a == b                       # byte-identical workload per seed
    assert a != wg.generate(requests=40, seed=6, deadline_frac=0.3)
    # arrivals are non-decreasing (open-loop clock) and bursts exist
    ticks = [r["arrival_tick"] for r in a]
    assert ticks == sorted(ticks)
    assert any(ticks.count(t) > 1 for t in ticks)
    assert any("prefix_group" in r for r in a)
    assert any("deadline_s" in r for r in a)
    p = tmp_path / "requests.jsonl"
    wg.write_jsonl(a, str(p))
    vm = _load("vm_wg", "scripts", "validate_metrics.py")
    assert vm.validate_request_file(str(p)) == []
    # the CLI writes the same bytes the library call produced
    out2 = tmp_path / "cli.jsonl"
    wg.main(["--requests", "40", "--seed", "5", "--deadline_frac", "0.3",
             "--out", str(out2)])
    assert out2.read_bytes() == p.read_bytes()


def test_response_schema_requires_timing_columns(tmp_path):
    vm = _load("vm_resp", "scripts", "validate_metrics.py")
    good = {"id": "r1", "reason": "timeout", "tokens": [], "prompt_len": 3,
            "n_generated": 0, "queue_ticks": 4, "decode_ticks": 0}
    p = tmp_path / "responses.jsonl"
    p.write_text(json.dumps(good) + "\n")
    assert vm.validate_response_file(str(p)) == []
    for strip, bad in (("queue_ticks", None), ("decode_ticks", None),
                       ("queue_ticks", -1), ("queue_ticks", 1.5)):
        doc = dict(good)
        if bad is None:
            doc.pop(strip)
        else:
            doc[strip] = bad
        p.write_text(json.dumps(doc) + "\n")
        errs = vm.validate_response_file(str(p))
        assert errs and strip in errs[0], (strip, bad, errs)
    # negative wall TTFT is a lie, not a measurement
    doc = dict(good, ttft_ms=-3.0)
    p.write_text(json.dumps(doc) + "\n")
    assert vm.validate_response_file(str(p))


# ------------------------------------------------- the evidence artifact
def _load_ce():
    return _load("ce_sm", "scripts", "check_evidence.py")


def test_banked_artifact_passes_slo_stage():
    """The committed CPU artifact satisfies the ISSUE 17 stage: strict
    schema (ordered quantiles, status counts), all three markers, zero
    token loss, banked p99s inside the banked targets — the gate
    runbook stage 5n re-judges after the on-chip recapture."""
    ce = _load_ce()
    assert ce.slo_ok()
    with open(ce.SERVE_ARTIFACT) as f:
        doc = json.load(f)
    sec = doc["slo"]
    assert sec["markers"]["metrics_inert"] is True
    assert sec["tokens_lost"] == 0
    assert sec["ttft_ms"]["p50"] <= sec["ttft_ms"]["p99"]
    assert sec["status_counts"]["eos"] + sec["status_counts"]["length"] > 0


def test_slo_stage_rejects_bad_artifacts(tmp_path):
    ce = _load_ce()
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "serving.json"

    def reject(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        p.write_text(json.dumps(doc))
        assert not ce.slo_ok(str(p))

    # artifact predates ISSUE 17 entirely (also a schema violation now)
    reject(lambda d: d.pop("slo"))
    # each marker flips the stage
    for k in ("metrics_inert", "zero_token_loss", "responses_timed"):
        reject(lambda d, k=k: d["slo"]["markers"].update({k: False}))
    # a sketch that reports p50 > p99 is lying — schema rejects
    reject(lambda d: d["slo"]["ttft_ms"].update(
        p50=d["slo"]["ttft_ms"]["p99"] + 1.0))
    # a negative TTFT is not a latency
    reject(lambda d: d["slo"]["ttft_ms"].update(p50=-1.0))
    # missing status counts (the statuses that tempt silent dropping)
    reject(lambda d: d["slo"]["status_counts"].pop("timeout"))
    reject(lambda d: d["slo"].pop("status_counts"))
    # token loss is a regression even with markers forged true
    reject(lambda d: d["slo"].update(tokens_lost=2))
    # banked p99 outside the banked target = SLO regression
    reject(lambda d: d["slo"]["targets"].update(
        ttft_ms=d["slo"]["ttft_ms"]["p99"] / 2.0))
    # an empty soak proved nothing
    reject(lambda d: d["slo"].update(requests=0))
    # the untouched artifact still passes from the tmp copy
    p.write_text(json.dumps(good))
    assert ce.slo_ok(str(p))
