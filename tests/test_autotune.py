"""Autotune subsystem (ISSUE 6): the device-keyed tuning cache round-trips,
fails LOUDLY (never silently) into defaults, ignores entries keyed to other
devices, kills wedged candidates under the per-candidate timeout guard,
agrees with ``parse_attn_spec`` about what a resolved spec means, and —
the invariant everything leans on — elections are BIT-identical tuned vs
default on both the XLA and Pallas optimizer paths: every knob the tuner
owns changes where/when work happens, never what is elected."""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.ops import autotune
from distributed_lion_tpu.optim import distributed_lion, init_global_state
from distributed_lion_tpu.optim.sharded import make_sharded_step, shard_state
from distributed_lion_tpu.parallel import make_mesh


@pytest.fixture(autouse=True)
def _fresh_cache_memo():
    autotune.invalidate_cache()
    yield
    autotune.invalidate_cache()


def _entry(value, ms=1.0):
    return {"value": value, "ms": ms}


# ------------------------------------------------------------- cache basics

def test_cache_round_trip(tmp_path):
    p = str(tmp_path / "cache.json")
    key = autotune.cache_key("TPU v5 lite", "flash_tiles", "T1024xD64",
                             "bfloat16")
    autotune.save_cache({key: _entry({"block_q": 512, "block_kv": 1024})},
                        path=p)
    got = autotune.lookup("flash_tiles", "T1024xD64", "bfloat16",
                          device_kind="TPU v5 lite", path=p)
    assert got == {"block_q": 512, "block_kv": 1024}
    # a different shape/dtype/knob misses
    assert autotune.lookup("flash_tiles", "T2048xD64", "bfloat16",
                           device_kind="TPU v5 lite", path=p) is None
    assert autotune.lookup("flash_tiles", "T1024xD64", "float32",
                           device_kind="TPU v5 lite", path=p) is None
    assert autotune.lookup("splash_tiles", "T1024xD64", "bfloat16",
                           device_kind="TPU v5 lite", path=p) is None


def test_device_key_mismatch_ignored(tmp_path):
    """An entry measured on a TPU must be INVISIBLE on any other device —
    the device kind is part of the key, not a filter someone must remember
    to apply."""
    p = str(tmp_path / "cache.json")
    key = autotune.cache_key("TPU v5 lite", "lion_row_block", "N100",
                             "float32")
    autotune.save_cache({key: _entry({"row_block": 2048})}, path=p)
    assert autotune.lookup("lion_row_block", "N100", "float32",
                           device_kind="cpu", path=p) is None
    assert autotune.lookup("lion_row_block", "N100", "float32",
                           device_kind="TPU v5 lite", path=p) == \
        {"row_block": 2048}


def test_wildcard_shape_is_operator_fallback(tmp_path):
    p = str(tmp_path / "cache.json")
    key = autotune.cache_key("cpu", "lion_row_block", "*", "float32")
    autotune.save_cache({key: _entry({"row_block": 256})}, path=p)
    assert autotune.lookup("lion_row_block", "N12345", "float32",
                           device_kind="cpu", path=p) == {"row_block": 256}
    # exact beats wildcard
    exact = autotune.cache_key("cpu", "lion_row_block", "N12345", "float32")
    autotune.save_cache({key: _entry({"row_block": 256}),
                         exact: _entry({"row_block": 1024})}, path=p)
    assert autotune.lookup("lion_row_block", "N12345", "float32",
                           device_kind="cpu", path=p) == {"row_block": 1024}


def test_corrupt_cache_falls_back_loudly(tmp_path, capsys):
    p = str(tmp_path / "cache.json")
    with open(p, "w") as f:
        f.write("{definitely not json")
    assert autotune.load_cache(p) == {}
    assert autotune.lookup("flash_tiles", "T1024xD64", "bfloat16",
                           device_kind="cpu", path=p) is None
    err = capsys.readouterr().err
    assert "FALLING BACK" in err and p in err


def test_schema_violation_falls_back_loudly(tmp_path, capsys):
    p = str(tmp_path / "cache.json")
    bad = {"format": autotune.CACHE_FORMAT, "entries": {
        "cpu|flash_tiles|T1024xD64|bfloat16":
            {"value": {"block_q": "big"}, "ms": 1.0}}}
    with open(p, "w") as f:
        json.dump(bad, f)
    assert autotune.validate_cache_doc(bad)
    assert autotune.load_cache(p) == {}
    assert "FALLING BACK" in capsys.readouterr().err


def test_validate_cache_doc_schema():
    good_key = autotune.cache_key("cpu", "vocab_chunks", "N256xV509",
                                  "float32")
    good = {"format": autotune.CACHE_FORMAT,
            "entries": {good_key: _entry({"vocab_chunks": 8})}}
    assert autotune.validate_cache_doc(good) == []
    assert autotune.validate_cache_doc([]) != []          # not an object
    assert autotune.validate_cache_doc({}) != []          # wrong format
    assert autotune.validate_cache_doc(
        {"format": autotune.CACHE_FORMAT, "entries": 3}) != []
    for entry in (
        {"value": {}, "ms": 1.0},                  # empty value
        {"value": {"x": 1.5}, "ms": 1.0},          # non-int knob value
        {"value": {"x": True}, "ms": 1.0},         # bool is not an int knob
        {"value": {"x": 1}, "ms": -1.0},           # negative ms
        {"value": {"x": 1}},                       # ms missing
        {"value": {"x": 1}, "ms": float("nan")},   # NaN ms
    ):
        doc = {"format": autotune.CACHE_FORMAT, "entries": {good_key: entry}}
        assert autotune.validate_cache_doc(doc), entry
    # bad keys: wrong arity, unknown knob
    for key in ("cpu|flash_tiles|T1", "cpu|warp_tiles|T1|f32", "a|b"):
        doc = {"format": autotune.CACHE_FORMAT,
               "entries": {key: _entry({"x": 1})}}
        assert autotune.validate_cache_doc(doc), key


def test_save_cache_refuses_invalid(tmp_path):
    with pytest.raises(ValueError, match="invalid cache"):
        autotune.save_cache({"busted": {"value": {}, "ms": 0.0}},
                            path=str(tmp_path / "c.json"))


def test_validate_metrics_covers_tuning_cache(tmp_path):
    """scripts/validate_metrics.py validates tuning_cache.json through the
    ONE schema authority (autotune.validate_cache_doc)."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "validate_metrics", os.path.join(repo, "scripts",
                                         "validate_metrics.py"))
    vm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vm)
    good = tmp_path / "tuning_cache.json"
    autotune.save_cache(
        {autotune.cache_key("cpu", "vocab_chunks", "N1xV2", "float32"):
         _entry({"vocab_chunks": 2})}, path=str(good))
    assert vm.validate_json_doc(str(good)) == []
    bad = tmp_path / "b" / "tuning_cache.json"
    bad.parent.mkdir()
    bad.write_text(json.dumps({"format": "wrong", "entries": {}}))
    assert vm.validate_json_doc(str(bad))
    # dispatch rides the embedded format stamp too: a $DLT_TUNE_CACHE at
    # any filename still gets the STRICT schema, not the generic checks
    odd = tmp_path / "tc.json"
    odd.write_text(json.dumps({
        "format": autotune.CACHE_FORMAT,
        "entries": {"cpu|vocab_chunks|N1xV2|float32":
                    {"value": {"vocab_chunks": "nope"}, "ms": 1.0}}}))
    assert vm.validate_json_doc(str(odd))


# ------------------------------------------------- winner selection + guard

def test_select_winner_deterministic_tie_break():
    cands = [{"row_block": 128}, {"row_block": 256}, {"row_block": 512}]
    results = [{"candidate": c, "ms": ms}
               for c, ms in zip(cands, (2.0, 1.0, 1.0))]
    win = autotune.select_winner(results)
    # tie at 1.0ms → the EARLIER candidate (smaller tile) wins
    assert win["candidate"] == {"row_block": 256} and win["index"] == 1
    assert autotune.select_winner(
        [{"candidate": c, "ms": None, "error": "x"} for c in cands]) is None


def test_candidate_order_is_fixed_and_excludes_known_bad_tile():
    a = autotune.tile_candidates("flash_tiles", {"t": 1024})
    assert a == autotune.tile_candidates("flash_tiles", {"t": 1024})
    # ascending sizes (ties → smallest tile via select_winner's index rule)
    assert a[0] == {"block_q": 128, "block_kv": 128}
    # the tile that hung remote compile >14 min in round 3 stays out
    assert {"block_q": 1024, "block_kv": 1024} not in a
    assert autotune.tile_candidates("lion_row_block", {}) == [
        {"row_block": rb} for rb in (128, 256, 512, 1024, 2048)]


def test_timeout_guard_kills_slow_candidate():
    """The per-candidate compile/run guard: a trial that wedges (here: the
    _test_sleep_s hook standing in for a pathological tile's compile) is
    SIGKILLed at the budget and reported as a timeout row — it can never
    eat more than timeout_s of a window."""
    payload = {"knob": "vocab_chunks", "candidate": {"vocab_chunks": 2},
               "info": {"n": 8, "d": 4, "v": 16, "dtype": "float32"},
               "iters": 1, "_test_sleep_s": 120}
    t0 = time.monotonic()
    r = autotune.run_trial_child(payload, timeout_s=3.0)
    elapsed = time.monotonic() - t0
    assert "timeout" in r.get("error", ""), r
    assert elapsed < 60, elapsed  # killed at the budget, not after 120s


# ---------------------------------------------- resolver ↔ dispatch bridge

def test_resolve_attn_spec_agrees_with_parse_attn_spec(tmp_path):
    """The cache resolver's output is a spec parse_attn_spec reads back to
    EXACTLY the cached tiles — the one grammar shared by bench/sweep and
    the attention dispatch can't drift from the cache."""
    from distributed_lion_tpu.ops.attention import parse_attn_spec

    p = str(tmp_path / "cache.json")
    key = autotune.cache_key("cpu", "flash_tiles",
                             autotune.attn_shape_key(1024, 64), "bfloat16")
    autotune.save_cache(
        {key: _entry({"block_q": 512, "block_kv": 1024,
                      "block_q_bwd": 256, "block_kv_bwd": 512})}, path=p)
    spec = autotune.resolve_attn_spec("auto", t=1024, head_dim=64,
                                      dtype="bfloat16", device_kind="cpu",
                                      path=p)
    assert spec == "flash@512x1024@256x512"
    assert parse_attn_spec(spec) == ("flash", 512, 1024, 256, 512)
    # fwd-only entry → fwd-only spec
    autotune.save_cache(
        {key: _entry({"block_q": 256, "block_kv": 256})}, path=p)
    spec = autotune.resolve_attn_spec("auto", t=1024, head_dim=64,
                                      dtype="bfloat16", device_kind="cpu",
                                      path=p)
    assert spec == "flash@256x256"
    assert parse_attn_spec(spec) == ("flash", 256, 256, 0, 0)
    # operator-written bwd-only entry (schema-valid; the dispatch honors
    # bwd-only pins) must resolve without crashing and round-trip: 0 means
    # "kernel default" in the grammar exactly as in the attention kwargs
    autotune.save_cache(
        {key: _entry({"block_q_bwd": 256, "block_kv_bwd": 512})}, path=p)
    spec = autotune.resolve_attn_spec("auto", t=1024, head_dim=64,
                                      dtype="bfloat16", device_kind="cpu",
                                      path=p)
    assert spec == "flash@0x0@256x512"
    assert parse_attn_spec(spec) == ("flash", 0, 0, 256, 512)
    # miss → unchanged; explicit specs pass through untouched
    assert autotune.resolve_attn_spec("auto", t=64, head_dim=64,
                                      dtype="bfloat16", device_kind="cpu",
                                      path=p) == "auto"
    assert autotune.resolve_attn_spec("xla", t=1024, head_dim=64,
                                      dtype="bfloat16", device_kind="cpu",
                                      path=p) == "xla"


def test_attention_auto_dispatch_consults_cache(tmp_path, monkeypatch):
    """`auto` on TPU with a cache hit dispatches flash with the MEASURED
    tiles (outranking the built-in heuristics); backend + kernel are
    monkeypatched — this pins DISPATCH, kernel math is pinned elsewhere."""
    from distributed_lion_tpu.ops import attention as A

    p = str(tmp_path / "cache.json")
    autotune.save_cache(
        {autotune.cache_key("cpu", "flash_tiles",
                            autotune.attn_shape_key(256, 32), "float32"):
         _entry({"block_q": 128, "block_kv": 256, "block_q_bwd": 64,
                 "block_kv_bwd": 128})}, path=p)
    monkeypatch.setenv("DLT_TUNE_CACHE", p)
    autotune.invalidate_cache()
    calls = []
    monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        A, "attention_flash",
        lambda q, k, v, causal=True, **kw: calls.append(kw) or q)
    q = jnp.zeros((1, 2, 256, 32), jnp.float32)
    A.attention(q, q, q, impl="auto")
    assert calls == [{"block_q": 128, "block_kv": 256,
                      "block_q_bwd": 64, "block_kv_bwd": 128}]
    # an unswept shape misses the cache and keeps the heuristic path (xla
    # at T=256 off the flagship shape → attention_flash NOT called)
    calls.clear()
    q2 = jnp.zeros((1, 2, 256, 16), jnp.float32)
    A.attention(q2, q2, q2, impl="auto")
    assert calls == []
    # caller-pinned tiles OUTRANK the cache (an explicit auto@BQxBKV spec
    # must stay sweepable even at a cached shape)
    calls.clear()
    A.attention(q, q, q, impl="auto", block_q=64, block_kv=64)
    assert calls == [{"block_q": 64, "block_kv": 64,
                      "block_q_bwd": 0, "block_kv_bwd": 0}]


def test_resolve_auto_comm_consults_vote_buckets_cache(tmp_path,
                                                       monkeypatch):
    from distributed_lion_tpu.train.loop import TrainConfig, resolve_auto_comm

    p = str(tmp_path / "cache.json")
    n = 17_000_000
    autotune.save_cache(
        {autotune.cache_key("cpu", "vote_buckets", f"N{n}", "int8"):
         _entry({"vote_buckets": 8})}, path=p)
    monkeypatch.setenv("DLT_TUNE_CACHE", p)
    autotune.invalidate_cache()
    mesh = make_mesh(data=8, devices=jax.devices()[:8])
    r = resolve_auto_comm(TrainConfig(wire="packed_a2a", vote_every=1),
                          mesh, n, params_replicated=True)
    assert r.vote_buckets == 8          # measured value outranks heuristic
    r = resolve_auto_comm(TrainConfig(wire="packed_a2a", vote_every=1),
                          mesh, n - 1, params_replicated=True)
    assert r.vote_buckets == 4          # miss → heuristic (≥16M → 4)
    cfg = TrainConfig(wire="packed_a2a", vote_every=1, vote_buckets=1)
    assert resolve_auto_comm(cfg, mesh, n, True) is cfg  # explicit wins


# ------------------------------------------ bit-identity: tuned vs default

@pytest.mark.parametrize("vote_buckets", [1, 4])
def test_elections_bit_identical_tuned_vs_default(vote_buckets):
    """The acceptance invariant: tuned row_block values (and the XLA path)
    produce BYTE-identical params/momenta across vote_buckets {1, 4} —
    tiling is never allowed to move an election or a weight."""
    mesh = make_mesh(data=8)
    rng = np.random.default_rng(11)
    params = {
        "w": jnp.asarray(rng.normal(size=(777, 13)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(259,)).astype(np.float32)),
    }
    grads = {
        "w": jnp.asarray(rng.normal(size=(8, 777, 13)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(8, 259)).astype(np.float32)),
    }
    results = []
    configs = [("xla", 0), ("pallas", 0), ("pallas", 128), ("pallas", 2048)]
    for kern, rb in configs:
        opt = distributed_lion(learning_rate=0.02, weight_decay=0.05,
                               wire="sign_psum", kernel=kern, row_block=rb,
                               vote_buckets=vote_buckets)
        state = shard_state(init_global_state(opt, params, 8), mesh)
        step = make_sharded_step(opt, mesh)
        p = params
        for _ in range(3):
            p, state = step(p, grads, state)
        results.append((kern, rb, p, state))
    _, _, p0, s0 = results[0]
    for kern, rb, p, s in results[1:]:
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(p0[k]), np.asarray(p[k]),
                err_msg=f"params diverged at kernel={kern} row_block={rb}")
            np.testing.assert_array_equal(
                np.asarray(s0.exp_avg[k]), np.asarray(s.exp_avg[k]),
                err_msg=f"momentum diverged at kernel={kern} row_block={rb}")


def test_bad_row_block_rejected_at_build():
    with pytest.raises(ValueError, match="multiple of 32"):
        distributed_lion(row_block=100)
    with pytest.raises(ValueError, match="multiple of 32"):
        distributed_lion(row_block=16)


# ------------------------------------------------- tuner CLI end to end

def test_run_tune_cpu_end_to_end(tmp_path, monkeypatch, capsys):
    """The tuner runs end-to-end on CPU (interpret/xla fallback):
    unsupported TPU-only knobs are skipped WITH a reason, a supported knob
    is measured, and the committed artifact round-trips through the strict
    loader and the resolver."""
    from distributed_lion_tpu.cli import run_tune

    p = str(tmp_path / "tuning_cache.json")
    monkeypatch.setenv("DLT_TUNE_CACHE", p)
    autotune.invalidate_cache()
    rc = run_tune.main(["--preset", "smoke", "--in-process", "--iters", "1",
                        "--knobs", "flash_tiles,vocab_chunks"])
    assert rc == 0
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert "flash_tiles" in summary["skipped"]          # with a reason
    assert "unsupported" in summary["skipped"]["flash_tiles"]
    assert "vocab_chunks" in summary["tuned"]
    entries = autotune.load_cache(p)
    assert len(entries) == 1
    (key,) = entries
    assert key.startswith("cpu|vocab_chunks|")
    # and the resolver sees what the tuner wrote
    knob, shape, dtype = key.split("|")[1:]
    v = autotune.lookup(knob, shape, dtype, device_kind="cpu", path=p)
    assert v == entries[key]["value"]
    assert v["vocab_chunks"] in (1, 2, 4, 8, 16, 32)
