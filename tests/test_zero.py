"""ZeRO-1 sharded AdamW (optim/zero.py) on 8 virtual devices: trajectory
identical to replicated optax AdamW, state memory 1/W per device, Trainer
integration converges."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.optim.optax_adapter import adamw
from distributed_lion_tpu.optim.zero import (
    adamw_zero1,
    expand_zero_state,
    squeeze_zero_state,
    zero1_chunk,
)
from distributed_lion_tpu.parallel import make_mesh
from distributed_lion_tpu.parallel.mesh import DATA_AXIS
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _params():
    rng = np.random.default_rng(5)
    return {
        "w": jnp.asarray(rng.normal(size=(7, 9)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(11,)).astype(np.float32)),
    }


def test_zero1_matches_replicated_adamw():
    """Sharded-state AdamW must produce the SAME parameter trajectory as the
    replicated optax baseline (same grads on every worker)."""
    world = 8
    mesh = make_mesh(data=world)
    params = _params()
    opt_z = adamw_zero1(learning_rate=1e-2, weight_decay=0.1)
    opt_r = adamw(learning_rate=1e-2, weight_decay=0.1)
    state_z = jax.device_put(
        opt_z.init(params, world=world),
        type(opt_z.init(params, world=world))(
            count=NamedSharding(mesh, P()),
            m=NamedSharding(mesh, P(DATA_AXIS)),
            v=NamedSharding(mesh, P(DATA_AXIS)),
        ),
    )
    state_r = opt_r.init(params)

    rng = np.random.default_rng(6)
    grads_seq = [
        jax.tree.map(lambda p: jnp.asarray(
            rng.normal(size=p.shape).astype(np.float32)), params)
        for _ in range(5)
    ]

    from distributed_lion_tpu.optim.zero import Zero1State

    @jax.jit
    def zstep(params, state, grads):
        def body(p, s, g):
            p2, s2 = opt_z.step(p, g, squeeze_zero_state(s))
            return p2, expand_zero_state(s2)

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(), Zero1State(P(), P(DATA_AXIS), P(DATA_AXIS)), P()),
            out_specs=(P(), Zero1State(P(), P(DATA_AXIS), P(DATA_AXIS))),
            check_vma=False,
        )(params, state, grads)

    pz, pr = params, params
    for g in grads_seq:
        pz, state_z = zstep(pz, state_z, g)
        pr, state_r = opt_r.step(pr, g, state_r)
    for a, b in zip(jax.tree.leaves(pz), jax.tree.leaves(pr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)


def test_zero1_state_is_sharded():
    world = 8
    n = sum(p.size for p in jax.tree.leaves(_params()))
    opt = adamw_zero1()
    st = opt.init(_params(), world=world)
    assert st.m.shape == (world, zero1_chunk(n, world))
    # per-device bytes = total/W when sharded over data
    mesh = make_mesh(data=world)
    m = jax.device_put(st.m, NamedSharding(mesh, P(DATA_AXIS)))
    assert m.addressable_shards[0].data.size == zero1_chunk(n, world)


def test_zero1_trainer_converges():
    cfg = TrainConfig(
        lion=False, async_grad=False, zero1=True, learning_rate=1e-3,
        weight_decay=0.0, warmup_steps=5, max_steps=20,
        per_device_train_batch_size=2, gradient_accumulation_steps=2,
        block_size=32, logging_steps=10, eval_steps=10**6, save_steps=10**6,
        seed=0, output_dir=None,
    )
    mesh = make_mesh(data=8)
    model_cfg = GPT2Config.tiny()
    t = Trainer.for_gpt2(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)
    h = t.train(batch_iterator(blocks, t.global_train_batch(), seed=0), max_steps=20)
    losses = [x["loss"] for x in h if "loss" in x]
    assert losses[-1] < losses[0]
    # params stay replicated across all devices after the all_gather exchange
    wte = t.params["wte"]
    shards = [np.asarray(s.data) for s in wte.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    t.close()


def test_zero1_guards():
    """Misuse combinations fail fast instead of silently corrupting state
    (ADVICE r1): zero1+lion, zero1+async_grad, zero1+tensor/seq axis."""
    import pytest

    from distributed_lion_tpu.train.loop import make_optimizer

    with pytest.raises(ValueError, match="zero1"):
        make_optimizer(TrainConfig(lion=True, zero1=True))
    with pytest.raises(ValueError, match="async_grad"):
        make_optimizer(TrainConfig(lion=False, async_grad=True, zero1=True))
    cfg = TrainConfig(
        lion=False, async_grad=False, zero1=True, max_steps=1,
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        block_size=32, output_dir=None,
    )
    with pytest.raises(ValueError, match="tensor"):
        Trainer.for_gpt2(cfg, make_mesh(data=4, tensor=2), GPT2Config.tiny())
    with pytest.raises(ValueError, match="seq"):
        Trainer.for_gpt2(cfg, make_mesh(data=4, seq=2), GPT2Config.tiny())


def test_seq_parallel_nctx_guard():
    """sp*T_local beyond the positional table must raise at config time, not
    silently clamp the wpe slice (ADVICE r1)."""
    import pytest

    cfg = TrainConfig(
        lion=True, async_grad=True, max_steps=1,
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        block_size=256, seq_parallel=2, output_dir=None,
    )
    with pytest.raises(ValueError, match="n_ctx"):
        Trainer.for_gpt2(cfg, make_mesh(data=4, seq=2), GPT2Config.tiny())
