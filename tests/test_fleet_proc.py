"""Process-isolated replicas (ISSUE 20 layer b): real OS processes
behind the fleet's engine duck surface. The acceptance matrix — a REAL
SIGKILL mid-decode under live socket traffic, greedy + sampled ×
prefix_cache on/off, zero accepted-token loss and token-identical
migrated outputs — plus heartbeat-miss strikes declaring a stalled
child dead, the wire framing/codec edges, and the ``--replica_procs``
CLI path with a killed child."""

import json
import threading

import jax
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.serve import fleet_proc, net
from distributed_lion_tpu.serve.engine import (
    RecoveryRecord,
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)
from distributed_lion_tpu.serve.replica_plane import ServingFleet
from distributed_lion_tpu.train import resilience

_CFG = GPT2Config.tiny()
_PARAMS = gpt2_init(jax.random.key(0), _CFG)
_MODEL = ServeModel.for_gpt2(_PARAMS, _CFG)

_SERVE = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)


def _engine(**kw):
    return ServingEngine(_MODEL, ServeConfig(**{**_SERVE, **kw}))


def _builder(**kw):
    # init_seed 0 == the module-level _PARAMS: the child process builds
    # the SAME weights from the same seed, no checkpoint file involved
    return {"kind": "gpt2_tiny", "init_seed": 0,
            "serve": {**_SERVE, **kw}}


def _reqs(n=4, max_new=10, groups=False):
    rng = np.random.default_rng(23)
    shared = [int(t) for t in rng.integers(1, _CFG.vocab_size, 6)]
    out = []
    for i in range(n):
        toks = [int(t) for t in rng.integers(1, _CFG.vocab_size, 3 + i)]
        d = {"id": f"p{i}", "max_new_tokens": max_new, "seed": i}
        if groups and i % 2 == 0:
            d.update(tokens=shared + toks, prefix_group="sys")
        else:
            d["tokens"] = toks
        out.append(d)
    return out


def _as_request(d):
    return Request(req_id=d["id"], tokens=list(d["tokens"]),
                   max_new_tokens=d["max_new_tokens"],
                   seed=d.get("seed", 0),
                   prefix_group=d.get("prefix_group"))


@pytest.fixture(autouse=True)
def _clean_serve_faults():
    resilience.inject_fault("serve", [])
    yield
    resilience.inject_fault("serve", [])


# ------------------------------------------------------- framing + codecs
def test_frame_stream_edges():
    buf = bytearray()
    assert fleet_proc._take_frame(buf) is None          # empty
    frame = fleet_proc._HEADER.pack(7) + b'{"a": 1}'[:7]
    buf += frame[:5]
    assert fleet_proc._take_frame(buf) is None          # split mid-frame
    buf += frame[5:]
    with pytest.raises(fleet_proc.ReplicaGone, match="corrupt frame"):
        fleet_proc._take_frame(bytearray(
            fleet_proc._HEADER.pack(3) + b"}{!"))       # garbage payload
    with pytest.raises(fleet_proc.ReplicaGone, match="exceeds"):
        fleet_proc._take_frame(bytearray(
            fleet_proc._HEADER.pack(fleet_proc.MAX_FRAME_BYTES + 1)))


def test_record_codec_ships_deadlines_as_remaining_seconds():
    rec = RecoveryRecord(req_id="d", tokens=[1, 2], committed=[9],
                         seed=3, budget=8, prefix_group="g",
                         deadline_at=107.5)
    wire = fleet_proc.record_to_wire(rec, now=100.0)
    assert wire["deadline_remaining_s"] == 7.5          # never absolute
    back = fleet_proc.record_from_wire(wire, now=20.0)  # other epoch
    assert back.deadline_at == 27.5
    assert (back.tokens, back.committed, back.seed, back.budget,
            back.prefix_group) == ([1, 2], [9], 3, 8, "g")
    free = fleet_proc.record_to_wire(
        RecoveryRecord("f", [1], [], 0, None), now=0.0)
    assert "deadline_remaining_s" not in free and "budget" not in free


# --------------------------------------------------- single-replica round trip
def test_process_replica_round_trip_matches_in_process_engine():
    reqs = _reqs(n=3)
    offline = _engine().run([_as_request(d) for d in reqs])
    rep = fleet_proc.ProcessReplica(_builder())
    try:
        assert rep.pid != 0 and rep.proc.poll() is None  # a real process
        for d in reqs:
            rep.submit(_as_request(d))
        assert [r.req_id for r in rep.pending] == [d["id"] for d in reqs]
        done = {}
        ticks = 0
        while rep.has_work():
            for c in rep.step():
                done[c.req_id] = c
            ticks += 1
            assert ticks < 100
        for d in reqs:
            assert done[d["id"]].tokens == offline[d["id"]].tokens
            assert done[d["id"]].reason == offline[d["id"]].reason
        assert not rep.pending and rep.export_records() == []
        assert rep.stats["prefill_dispatches"] > 0  # stats mirror rode over
    finally:
        rep.close()
    assert rep.proc.poll() is not None              # reaped, not leaked
    with pytest.raises(fleet_proc.ReplicaGone, match="closed"):
        rep.step()


# --------------------------------------------------- THE acceptance matrix
@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
@pytest.mark.parametrize("prefix_cache", [False, True])
def test_sigkill_mid_decode_under_live_socket_traffic(sampling,
                                                      prefix_cache):
    """A replica child is SIGKILLed for real AFTER its engine stepped
    (tokens were truly sampled, the reply never sent) while a live
    socket client is mid-stream. The fleet sees EOF, declares the
    process dead, migrates from its shadow — and every response is
    token-identical to the never-killed offline run: zero accepted
    tokens lost, greedy and sampled, prefix cache on and off."""
    samp = (dict(temperature=0.0) if sampling == "greedy"
            else dict(temperature=0.8, top_k=20))
    eng_kw = dict(prefix_cache=prefix_cache, **samp)
    reqs = _reqs(groups=prefix_cache)
    offline = _engine(**eng_kw).run([_as_request(d) for d in reqs])
    resilience.inject_fault(
        "serve", resilience.parse_serve_specs("replica_kill:0:2"))
    fleet = ServingFleet(
        fleet_proc.process_replica_factory(_builder(**eng_kw)),
        replicas=2)
    srv = net.ServeServer(fleet, port=0)
    th = threading.Thread(target=srv.run, kwargs={"max_wall_s": 300.0},
                          daemon=True)
    th.start()
    try:
        out = net.drive_open_loop(*srv.addr, records=reqs, tick_s=0.0,
                                  max_wall_s=240.0)
    finally:
        srv.stop = True
        th.join(timeout=30)
        srv.close()
        fleet.close()
    lost = 0
    for d in reqs:
        got = out["responses"][d["id"]]["tokens"]
        assert got == offline[d["id"]].tokens, (sampling, prefix_cache,
                                                d["id"])
        lost += max(len(offline[d["id"]].tokens) - len(got), 0)
    assert lost == 0
    assert fleet.stats["replica_crashes"] == 1
    assert fleet.stats["replicas_declared_dead"] == 1
    assert fleet.stats["migrations"] >= 1
    assert fleet.stats["failed"] == 0 and fleet.stats["timeouts"] == 0
    assert fleet.lifecycle()[0] == "departed"


def test_heartbeat_stall_strikes_then_declares_dead(tmp_path):
    """A child that stalls (alive, not replying) accumulates
    ``replica_heartbeat_missed`` strikes and is declared dead at the
    miss budget — its requests migrate and finish token-identically on
    the healthy peer, with the journal carrying the strike trail."""
    from distributed_lion_tpu.train import journal as journal_mod

    reqs = _reqs(n=4, max_new=8)
    offline = _engine().run([_as_request(d) for d in reqs])
    jrnl = journal_mod.Journal(str(tmp_path))
    journal_mod.install(jrnl)
    try:
        fleet = ServingFleet(
            fleet_proc.process_replica_factory(_builder()),
            replicas=2, heartbeat_max_misses=2)
        # warm both children first (their first engine.step compiles) so
        # a tight heartbeat window only ever times a stalled reply
        fleet.run([Request("warm0", [1, 2], 2, 0),
                   Request("warm1", [3, 4], 2, 0)])
        done = {}
        stalled = False
        todo = [_as_request(d) for d in reqs]
        while todo or fleet.has_work():
            while todo:
                fleet.submit(todo.pop(0))
            if not stalled and all(
                    len(r.assigned) > 0 for r in fleet.replicas):
                for rep in fleet.replicas:
                    rep.engine.heartbeat_timeout_s = 0.3
                fleet.replicas[0].engine.stall_next_tick(3000)
                stalled = True
            for c in fleet.step():
                done[c.req_id] = c
        fleet.close()
    finally:
        journal_mod.uninstall(jrnl)
        jrnl.close()
    assert stalled
    assert fleet.stats["heartbeat_misses"] >= 2
    assert fleet.stats["replicas_declared_dead"] == 1
    for d in reqs:
        assert done[d["id"]].tokens == offline[d["id"]].tokens, d["id"]
    events = [r for r in jrnl.tail() if r.get("kind") == "event"]
    misses = [r for r in events if r["name"] == "replica_heartbeat_missed"]
    assert len(misses) >= 2
    assert all(r["replica"] == 0 and r["max_misses"] == 2 for r in misses)
    dead = next(r for r in events if r["name"] == "replica_declared_dead")
    assert dead["cause"] == "heartbeat_lost" and dead["misses"] == 2
    left = next(r for r in events if r["name"] == "replica_left")
    assert left["cause"] == "heartbeat_lost"


# ----------------------------------------------------------------- the CLI
def test_run_serve_cli_replica_procs_with_injected_kill(tmp_path):
    """``--replica_procs`` end to end: two worker processes serve a
    request file, one is SIGKILLed mid-decode by ``--inject_serve
    replica_kill``, and the responses match the in-process single-engine
    run — the CLI wiring of the whole layer."""
    from distributed_lion_tpu.cli.run_serve import main

    reqs = tmp_path / "requests.jsonl"
    reqs.write_text("".join(
        json.dumps({"id": f"c{i}", "tokens": [7 + i, 3, 5 + i],
                    "max_new_tokens": 6, "seed": i}) + "\n"
        for i in range(3)))
    out = tmp_path / "responses.jsonl"
    base = ["--model_family", "gpt2", "--model_name", "tiny",
            "--requests", str(reqs), "--out", str(out),
            "--temperature", "0", "--max_seqs", "2", "--block_size", "4"]
    records = main(base + ["--replicas", "2", "--replica_procs",
                           "--inject_serve", "replica_kill:0:2"])
    solo = main(base)
    assert [r["tokens"] for r in records] == [r["tokens"] for r in solo]
    assert all(r["n_generated"] == 6 for r in records)
