"""Streaming socket front end (ISSUE 20 layer a): wire schema parity
with the file mode, per-token streaming at the host tick boundary,
honest backpressure (pool-tight reject frame + client backoff), queued
deadline expiry over the wire, and the open-loop driver + byte-identical
request stream ``workload_gen --stream`` pins."""

import contextlib
import importlib.util
import json
import os
import socket
import threading

import jax
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.serve import net
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CFG = GPT2Config.tiny()
_PARAMS = gpt2_init(jax.random.key(0), _CFG)
_MODEL = ServeModel.for_gpt2(_PARAMS, _CFG)


def _engine(**kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    base.update(kw)
    return ServingEngine(_MODEL, ServeConfig(**base))


def _reqs(n=3, max_new=8):
    rng = np.random.default_rng(11)
    return [{"id": f"n{i}", "tokens": [int(t) for t in rng.integers(
                 1, _CFG.vocab_size, 3 + 2 * i)],
             "max_new_tokens": max_new, "seed": i} for i in range(n)]


def _as_request(d):
    return Request(req_id=d["id"], tokens=list(d["tokens"]),
                   max_new_tokens=d["max_new_tokens"],
                   seed=d.get("seed", 0),
                   prefix_group=d.get("prefix_group"))


@contextlib.contextmanager
def _serving(target, **kw):
    """A live server on an ephemeral port, ticking in a daemon thread —
    the single-threaded production loop; the test plays the client."""
    srv = net.ServeServer(target, port=0, **kw)
    th = threading.Thread(target=srv.run, kwargs={"max_wall_s": 120.0},
                          daemon=True)
    th.start()
    try:
        yield srv
    finally:
        srv.stop = True
        th.join(timeout=15)
        srv.close()
        assert not th.is_alive()


# ------------------------------------------------------------ determinism
def test_encode_request_is_canonical_and_rerun_stable():
    a = net.encode_request({"id": "x", "tokens": [3, 1], "seed": 0})
    b = net.encode_request({"seed": 0, "tokens": [3, 1], "id": "x"})
    assert a == b and a.endswith(b"\n")          # key order cannot leak
    with pytest.raises(ValueError):
        net.encode_request({"id": "x", "bad": float("nan")})


def test_workload_stream_digest_is_a_pure_function_of_the_seed():
    """The ``--stream`` determinism pin: same generator seed, same wire
    BYTES (not merely the same distribution) — and a different seed is a
    different stream."""
    spec = importlib.util.spec_from_file_location(
        "wg_net", os.path.join(REPO, "scripts", "workload_gen.py"))
    wg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wg)
    kw = dict(requests=16, vocab=64, out_max=8)
    a = wg.stream_sha256(wg.generate(seed=5, **kw))
    b = wg.stream_sha256(wg.generate(seed=5, **kw))
    c = wg.stream_sha256(wg.generate(seed=6, **kw))
    assert a == b and a != c and len(a) == 64


# -------------------------------------------------------------- streaming
def test_streamed_tokens_match_done_frame_and_offline_run():
    reqs = _reqs()
    offline = _engine().run([_as_request(d) for d in reqs])
    with _serving(_engine()) as srv:
        client = net.ServeClient(*srv.addr)
        for d in reqs:
            streamed = []
            done = client.request(dict(d), on_tokens=streamed.extend)
            assert done["event"] == "done" and done["id"] == d["id"]
            # the per-tick frames concatenate to EXACTLY the final
            # output — no token duplicated, none withheld until the end
            assert streamed == done["tokens"]
            assert done["tokens"] == offline[d["id"]].tokens
            assert done["reason"] == offline[d["id"]].reason
            assert done["n_generated"] == len(done["tokens"])
            assert done["queue_ticks"] >= 0   # lifecycle clocks ride along
        assert srv.stats["accepted"] == len(reqs)
        assert srv.stats["completed"] == len(reqs)
        assert srv.stats["rejected"] == 0 and srv.stats["bad_lines"] == 0


def test_wire_refuses_what_the_file_mode_refuses():
    """One validation site (serve/api.parse_request_obj): garbage JSON,
    schema violations, and duplicate in-flight ids come back as explicit
    ``error`` frames — the connection survives and a good request on the
    same socket still serves."""
    with _serving(_engine()) as srv:
        sock = socket.create_connection(srv.addr, timeout=30)
        sock.settimeout(30.0)
        f = sock.makefile("rwb")
        try:
            def ask(line):
                f.write(line if isinstance(line, bytes)
                        else net.encode_request(line))
                f.flush()
                return json.loads(f.readline())

            assert "error" in ask(b"not json\n")
            assert "must be a JSON object" in ask(b"[1, 2]\n")["error"]
            bad = ask({"id": "x", "tokens": [1], "deadline_s": 0})
            assert "deadline_s" in bad["error"]
            # a good request on the SAME connection still serves fully
            good = {"id": "ok", "tokens": [5, 6, 7], "max_new_tokens": 16}
            assert ask(good)["event"] == "accepted"
            # a duplicate id while 'ok' is in flight is refused loudly
            # (its error frame interleaves with 'ok's token stream)
            f.write(net.encode_request(good))
            f.flush()
            dup = done = None
            while dup is None or done is None:
                frame = json.loads(f.readline())
                if frame.get("event") == "error":
                    dup = frame
                elif frame.get("event") == "done":
                    done = frame
            assert "duplicate" in dup["error"]
            assert done["id"] == "ok" and done["n_generated"] == 16
        finally:
            f.close()
            sock.close()
        assert srv.stats["bad_lines"] == 3


def test_pool_tight_reject_and_client_backoff_then_succeed():
    """Honest backpressure: while a resident request holds the page pool
    under the ``min_free_blocks`` floor, a newcomer gets an explicit
    ``reject`` frame with ``retry_after_s`` — and the reference client's
    backoff retries land it once the pool frees. Nothing is buffered
    server-side, nothing is silently dropped."""
    eng = _engine(max_seqs=2, num_blocks=8)
    with _serving(eng, min_free_blocks=6, retry_after_s=0.02) as srv:
        hog = {"id": "hog", "tokens": [9] * 8, "max_new_tokens": 24,
               "seed": 0}
        sock = socket.create_connection(srv.addr, timeout=30)
        sock.settimeout(30.0)
        f = sock.makefile("rb")
        try:
            sock.sendall(net.encode_request(hog))
            # wait for the hog to be DECODING (first tokens frame) so its
            # pages are allocated and the pool really is tight
            while True:
                frame = json.loads(f.readline())
                if frame.get("event") == "tokens":
                    break
            client = net.ServeClient(*srv.addr, max_retries=40,
                                     backoff_base_s=0.01)
            done = client.request({"id": "late", "tokens": [1, 2, 3],
                                   "max_new_tokens": 4, "seed": 1})
            assert done["event"] == "done" and done["n_generated"] == 4
            assert client.rejects >= 1       # it WAS pushed back first
            assert client.retries >= 1
            while frame.get("event") != "done":   # drain the hog too
                frame = json.loads(f.readline())
        finally:
            f.close()
            sock.close()
        assert srv.stats["rejected"] >= 1
        assert srv.stats["completed"] == 2


def test_queued_deadline_expires_behind_slow_peer_with_queue_ticks():
    """A request whose ``deadline_s`` lapses while it waits behind a
    long-running resident completes over the wire with the honest
    ``timeout`` status, zero generated tokens (it never reached prefill)
    and a populated ``queue_ticks`` — the clock that proves WHERE the
    deadline died."""
    with _serving(_engine(max_seqs=1)) as srv:
        slow = socket.create_connection(srv.addr, timeout=60)
        slow.settimeout(60.0)
        f = slow.makefile("rb")
        try:
            slow.sendall(net.encode_request(
                {"id": "resident", "tokens": [4] * 6,
                 "max_new_tokens": 64, "seed": 0}))
            while True:      # resident admitted: holds the only slot
                if json.loads(f.readline()).get("event") == "tokens":
                    break
            # a deadline far below one resident's decode run: it MUST
            # lapse while 'dead' still waits for the only slot
            client = net.ServeClient(*srv.addr)
            done = client.request({"id": "dead", "tokens": [1, 2],
                                   "max_new_tokens": 8, "seed": 1,
                                   "deadline_s": 0.002})
            assert done["reason"] == "timeout"
            assert done["n_generated"] == 0 and done["tokens"] == []
            assert done["queue_ticks"] >= 1
            frame = {}
            while frame.get("event") != "done":
                frame = json.loads(f.readline())
            assert frame["n_generated"] > 0   # the resident was unharmed
        finally:
            f.close()
            slow.close()


# ------------------------------------------------------- open-loop driver
def test_drive_open_loop_completes_a_generated_workload():
    """The soak path end to end: workload_gen records → one multiplexed
    connection → every request answered, responses token-identical to
    the offline run of the same records."""
    spec = importlib.util.spec_from_file_location(
        "wg_net2", os.path.join(REPO, "scripts", "workload_gen.py"))
    wg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wg)
    records = wg.generate(requests=8, seed=2, vocab=_CFG.vocab_size,
                          prompt_max=12, out_max=8, prefix_len=4,
                          deadline_frac=0.0)
    offline = _engine(prefix_cache=True).run(
        [_as_request(dict(r, id=r["id"])) for r in records])
    with _serving(_engine(prefix_cache=True)) as srv:
        out = net.drive_open_loop(*srv.addr, records=records,
                                  tick_s=0.0, max_wall_s=90.0)
    assert set(out["responses"]) == {r["id"] for r in records}
    for r in records:
        assert out["responses"][r["id"]]["tokens"] == \
            offline[r["id"]].tokens, r["id"]
    assert out["rejects"] == 0
