"""Checkpoint-resume data seek (VERDICT r1 weak #7): fast-forward by index
arithmetic, not by replaying every consumed batch through memory.

Invariant: skip(k) then next() on a fresh iterator yields exactly what the
(k+1)-th next() yields — across epoch boundaries, for both the Python
BatchIterator and the C++ native loader."""

import numpy as np
import pytest

from distributed_lion_tpu.data.sources import BatchIterator, batch_iterator


def _blocks(n=23, t=8):
    return (np.arange(n * t).reshape(n, t) % 251).astype(np.int32)


@pytest.mark.parametrize("k", [0, 1, 3, 5, 11, 30])
def test_python_skip_matches_replay(k):
    blocks = _blocks()
    ref = batch_iterator(blocks, 4, seed=9)
    for _ in range(k):
        next(ref)
    want = next(ref)

    it = batch_iterator(blocks, 4, seed=9)
    it.skip(k)
    np.testing.assert_array_equal(next(it), want)


def test_python_skip_past_finite_epochs():
    it = BatchIterator(_blocks(), 4, seed=0, epochs=2)
    it.skip(10_000)
    with pytest.raises(StopIteration):
        next(it)


def test_trainer_uses_seek(tmp_path, monkeypatch):
    """Resume goes through skip() (no data replay) and continues the same
    data stream: train 4 steps continuously vs 2 + resume + 2."""
    import jax

    from distributed_lion_tpu.data.sources import synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    mesh = make_mesh(data=8)
    model = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)

    def cfg(outdir, steps):
        return TrainConfig(
            lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
            max_steps=steps, per_device_train_batch_size=1,
            gradient_accumulation_steps=1, block_size=32, logging_steps=1,
            save_steps=2, output_dir=outdir, seed=5,
        )

    # continuous 4-step run
    t0 = Trainer.for_gpt2(cfg(None, 4), mesh, model, seed=3)
    h0 = t0.train(batch_iterator(blocks, t0.global_train_batch(), seed=5))
    ref_losses = [h["loss"] for h in h0 if "loss" in h]
    t0.close()

    # 2 steps, checkpoint, then resume for 2 more — with replay forbidden
    out = str(tmp_path / "run")
    t1 = Trainer.for_gpt2(cfg(out, 2), mesh, model, seed=3)
    t1.train(batch_iterator(blocks, t1.global_train_batch(), seed=5))
    t1.save()
    t1.close()

    t2 = Trainer.for_gpt2(cfg(out, 4), mesh, model, seed=3)
    assert t2.step_count == 2
    it = batch_iterator(blocks, t2.global_train_batch(), seed=5)
    orig_next = type(it).__next__
    reads = {"n": 0}

    def counting_next(self):
        reads["n"] += 1
        return orig_next(self)

    monkeypatch.setattr(type(it), "__next__", counting_next)
    h2 = t2.train(it)
    resumed_losses = [h["loss"] for h in h2 if "loss" in h]
    t2.close()
    assert reads["n"] == 2  # ONLY the 2 live batches; skip() read nothing
    np.testing.assert_allclose(resumed_losses, ref_losses[2:], rtol=1e-5, atol=1e-6)


def test_native_skip_matches_replay(tmp_path):
    from distributed_lion_tpu.data.native_loader import NativeTokenLoader, native_available

    if not native_available():
        pytest.skip("no C++ toolchain")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 60000, size=23 * 8, dtype=np.uint16)
    shard = tmp_path / "s.bin"
    tokens.tofile(shard)

    ref_loader = NativeTokenLoader([shard], block_size=8)
    ref = ref_loader.batches(4, seed=9)
    batches = [next(ref) for _ in range(8)]  # crosses the 5-batch epoch edge
    ref_loader.close()

    for k in (0, 1, 4, 7):
        loader = NativeTokenLoader([shard], block_size=8)
        it = loader.batches(4, seed=9)
        it.skip(k)
        np.testing.assert_array_equal(next(it), batches[k], err_msg=f"k={k}")
        loader.close()
