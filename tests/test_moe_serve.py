"""MoE serving (ISSUE 15): capacity-aware valid-lane routing, paged MoE
decode pinned BIT-identical to the dense-KV MoE path, the lifted batched
refusals (engine batched==solo, left-padded batched generate==solo),
expert-parallel serving (ep=1 bit-identical, ep>1 / ep×tp
token-identical on the CPU mesh, NF4 expert banks), the composition pins
(MoE × prefix_cache, MoE × ngram speculation) and loud refusals (dense +
ep, llama + ep, indivisible experts, MoE × draft:<k>), the engine's MoE
routing stats, and the moe_serving evidence stage."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.models.generate import generate
from distributed_lion_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_decode,
    gpt2_decode_paged,
    gpt2_init,
    gpt2_init_cache,
)
from distributed_lion_tpu.parallel.expert import moe_ffn, moe_init
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MOE = GPT2Config.tiny(moe_experts=4)  # n_layer=2, moe_every=2: block 1 MoE


@pytest.fixture(scope="module")
def moe_params():
    return gpt2_init(jax.random.key(0), MOE)


def _requests(vocab, n=4, max_new=8, lens=(3, 9, 5, 14, 2), seed=7):
    rng = np.random.default_rng(seed)
    return [Request(req_id=i,
                    tokens=list(map(int, rng.integers(1, vocab, L))),
                    max_new_tokens=max_new, seed=i)
            for i, L in enumerate(lens[:n])]


def _engine(params, cfg=MOE, **kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    base.update(kw)
    return ServingEngine(ServeModel.for_gpt2(params, cfg),
                         ServeConfig(**base))


def _run(eng, reqs, **kw):
    return eng.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                            r.seed) for r in reqs], **kw)


# ------------------------------------------------- valid-lane routing pin
def test_pad_lanes_consume_zero_capacity_under_binding_cap():
    """THE acceptance-criterion unit pin: with a BINDING capacity (cap=2)
    a padded batch's routed output for its real tokens is bit-equal to
    the unpadded batch's — pads take no queue slot, so they never perturb
    which real tokens drop — and every pad lane's output row is exactly
    zero."""
    E, D, F = 4, 8, 16
    params = moe_init(jax.random.key(1), E, D, F)
    rng = np.random.default_rng(3)
    x_real = jnp.asarray(rng.standard_normal((10, D)), jnp.float32)
    real_pos = [0, 2, 3, 5, 6, 8, 10, 11, 13, 15]  # pads INTERLEAVED
    x_pad = jnp.asarray(rng.standard_normal((16, D)), jnp.float32)
    x_pad = x_pad.at[jnp.asarray(real_pos)].set(x_real)
    valid = np.zeros((16,), bool)
    valid[real_pos] = True

    y_ref, _ = moe_ffn(params, x_real, axis_name=None, capacity_override=2)
    y_pad, _ = moe_ffn(params, x_pad, axis_name=None, capacity_override=2,
                       valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(y_ref),
                                  np.asarray(y_pad)[real_pos])
    assert (np.asarray(y_pad)[~valid] == 0).all()
    _, _, st = moe_ffn(params, x_pad, axis_name=None, capacity_override=2,
                       valid=jnp.asarray(valid), return_stats=True)
    assert float(st["valid"]) == 10.0  # pads counted in NO column
    # the binding cap actually dropped real tokens (zero output rows) —
    # the equality pin is not vacuous: 10 tokens / 4 experts / cap 2
    # cannot all be kept
    assert np.all(np.asarray(y_ref) == 0, axis=-1).any()


def test_all_valid_mask_is_bit_identical_to_no_mask():
    """valid=all-True must be the None code path bit-for-bit (training
    never passes a mask; the decode paths always do)."""
    params = moe_init(jax.random.key(2), 4, 8, 16)
    x = jnp.asarray(np.random.default_rng(5).standard_normal((12, 8)),
                    jnp.float32)
    y0, a0 = moe_ffn(params, x, axis_name=None)
    y1, a1 = moe_ffn(params, x, axis_name=None, valid=jnp.ones((12,), bool))
    np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))
    assert float(a0) == float(a1)


def test_moe_routing_stats_against_capacity_budget():
    """return_stats measures routing load vs the capacity_factor budget
    regardless of the no-drop override: kept <= valid, kept bounded by
    E*budget, and a skewed gate shows dropped demand (valid > kept)."""
    E, D, F = 4, 8, 16
    params = moe_init(jax.random.key(3), E, D, F)
    # a zero gate ties every logit; argmax routes ALL tokens to expert 0
    params["gate"] = jnp.zeros_like(params["gate"])
    x = jnp.asarray(np.random.default_rng(6).standard_normal((16, D)),
                    jnp.float32)
    _, _, st = moe_ffn(params, x, axis_name=None, capacity_factor=1.0,
                       capacity_override=16, return_stats=True)
    valid, kept, slots = (float(st[k]) for k in
                          ("valid", "kept", "capacity_slots"))
    assert valid == 16.0 and slots == 16.0  # budget = ceil(1.0*16/4) = 4
    assert kept == 4.0  # one 4-slot expert holds everything it can
    assert valid - kept == 12.0  # the demand the budget would drop


# ------------------------------------------- paged == dense (bit-identity)
def test_paged_moe_decode_bit_identical_to_dense(moe_params):
    """The headline acceptance criterion: prefill + per-token decode
    through SHUFFLED block tables produces bit-identical logits to the
    dense KV cache at the same attended length — for a MoE config."""
    B, L, bs, nb_seq = 2, 7, 4, 4
    toks = jnp.asarray(
        np.random.default_rng(0).integers(1, MOE.vocab_size, (B, L)),
        jnp.int32)
    cache = gpt2_init_cache(MOE, B, bs * nb_seq)
    dl, cache = gpt2_decode(moe_params, toks, MOE, cache, 0)
    pages = [{k: jnp.zeros((B * nb_seq, bs, MOE.n_head, MOE.head_dim),
                           MOE.compute_dtype) for k in ("k", "v")}
             for _ in range(MOE.n_layer)]
    tables = jnp.asarray([[2, 0, 1, 3], [5, 7, 4, 6]], jnp.int32)
    pl, pages = gpt2_decode_paged(moe_params, toks, MOE, pages, tables,
                                  jnp.zeros((B,), jnp.int32))
    np.testing.assert_array_equal(np.asarray(dl), np.asarray(pl))
    t_cur = jnp.argmax(dl[:, -1], -1)
    lens = jnp.full((B,), L, jnp.int32)
    for i in range(5):
        dl, cache = gpt2_decode(moe_params, t_cur[:, None], MOE, cache,
                                L + i)
        pl, pages = gpt2_decode_paged(moe_params, t_cur[:, None], MOE,
                                      pages, tables, lens)
        np.testing.assert_array_equal(np.asarray(dl), np.asarray(pl))
        t_cur = jnp.argmax(dl[:, -1], -1)
        lens = lens + 1


def test_paged_moe_prefill_pad_tail_is_inert(moe_params):
    """The engine's bucketed right-padded prefill shape: real-position
    logits and a later decode step match an unpadded prefill bit-for-bit
    — the pad tail neither writes pages nor routes through experts."""
    L, P, bs = 5, 8, 4
    toks = jnp.asarray(
        np.random.default_rng(2).integers(1, MOE.vocab_size, (1, L)),
        jnp.int32)
    padded = jnp.concatenate([toks, jnp.zeros((1, P - L), jnp.int32)],
                             axis=1)

    def pages():
        return [{k: jnp.zeros((4, bs, MOE.n_head, MOE.head_dim),
                              MOE.compute_dtype) for k in ("k", "v")}
                for _ in range(MOE.n_layer)]

    tables = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    zero = jnp.zeros((1,), jnp.int32)
    ref, ref_pages = gpt2_decode_paged(moe_params, toks, MOE, pages(),
                                       tables, zero)
    valid = (jnp.arange(P) < L)[None, :]
    got, got_pages = gpt2_decode_paged(moe_params, padded, MOE, pages(),
                                       tables, zero, valid)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got[:, :L]))
    nxt = jnp.argmax(ref[:, L - 1], -1)[:, None]
    lens = jnp.full((1,), L, jnp.int32)
    a, _ = gpt2_decode_paged(moe_params, nxt, MOE, ref_pages, tables, lens)
    b, _ = gpt2_decode_paged(moe_params, nxt, MOE, got_pages, tables, lens)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------- lifted batch refusals
@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_moe_engine_staggered_batched_matches_solo(moe_params, sampling):
    """Continuous batching never changes an MoE request's output: the
    no-drop per-token routing means batchmates cannot displace each
    other's expert slots — staggered arrivals == solo runs."""
    samp = ({} if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    reqs = _requests(MOE.vocab_size)
    stag = _run(_engine(moe_params, **samp), reqs,
                arrivals={0: 0, 1: 1, 2: 1, 3: 4})
    for r in reqs:
        solo = _run(_engine(moe_params, **samp), [r])
        assert solo[r.req_id].tokens == stag[r.req_id].tokens, r.req_id


def test_moe_engine_matches_dense_kv_generate(moe_params):
    """The serve-vs-generate pin: the paged engine's greedy output equals
    the dense-KV ``generate`` path at matched attended length — on a MoE
    checkpoint (the claim PR 9's refusal existed to protect)."""
    bs, nblk, new = 4, 8, 8
    prompts = [list(map(int, np.random.default_rng(11).integers(
        1, MOE.vocab_size, 7))) for _ in range(3)]

    def dec(p, t, c, pos, off=None):
        return gpt2_decode(p, t, MOE, c, pos, off)

    def ic(b, m):
        return gpt2_init_cache(MOE, b, m)

    dense = np.asarray(generate(dec, ic, moe_params,
                                jnp.asarray(prompts, jnp.int32), new,
                                max_len=bs * nblk))
    eng = _engine(moe_params, block_size=bs, max_blocks_per_seq=nblk)
    done = eng.run([Request(req_id=i, tokens=list(t), max_new_tokens=new,
                            seed=0) for i, t in enumerate(prompts)])
    for i in range(len(prompts)):
        assert list(dense[i]) == done[i].tokens, i


def test_moe_batched_left_padded_generate_matches_solo(moe_params):
    """The models/generate satellite: the PR 9 left-pad refusal is lifted
    — per-row offsets mask pad lanes out of expert routing, so batched
    greedy MoE generate equals solo runs exactly."""
    rng = np.random.default_rng(13)
    lens = [3, 7, 5]
    prompts = [list(map(int, rng.integers(1, MOE.vocab_size, L)))
               for L in lens]
    T = max(lens)
    padded = np.zeros((len(prompts), T), np.int32)
    for i, p in enumerate(prompts):
        padded[i, T - len(p):] = p

    def dec(p, t, c, pos, off=None):
        return gpt2_decode(p, t, MOE, c, pos, off)

    def ic(b, m):
        return gpt2_init_cache(MOE, b, m)

    batched = np.asarray(generate(dec, ic, moe_params,
                                  jnp.asarray(padded), 8,
                                  prompt_lens=jnp.asarray(lens, jnp.int32)))
    for i, p in enumerate(prompts):
        solo = np.asarray(generate(dec, ic, moe_params,
                                   jnp.asarray([p], jnp.int32), 8))
        np.testing.assert_array_equal(batched[i], solo[0])


# -------------------------------------------------- expert-parallel serving
def test_ep1_bit_identical_to_unsharded(moe_params):
    """ep=1 runs the sharded program on a 1-expert mesh and must be the
    unsharded engine bit for bit: token streams AND every scattered k/v
    byte."""
    reqs = _requests(MOE.vocab_size)
    e0 = _engine(moe_params)
    e1 = _engine(moe_params, ep=1)
    out0, out1 = _run(e0, reqs), _run(e1, reqs)
    for r in reqs:
        assert out1[r.req_id].tokens == out0[r.req_id].tokens, r.req_id
        assert out1[r.req_id].reason == out0[r.req_id].reason
    for l0, l1 in zip(e0.pages, e1.pages):
        for k in ("k", "v"):
            np.testing.assert_array_equal(np.asarray(l0[k]),
                                          np.asarray(l1[k]))


@pytest.mark.parametrize("ep", [2, 4])
@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_ep_matches_single_device(moe_params, ep, sampling):
    """ep>1 shards the expert banks and routes tokens through the two
    all_to_all hops; the engine-level pin is token identity, greedy AND
    sampled (the per-request streams are batch- and mesh-independent)."""
    samp = ({} if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    reqs = _requests(MOE.vocab_size, n=5)
    base = _run(_engine(moe_params, **samp), reqs)
    got = _run(_engine(moe_params, ep=ep, **samp), reqs)
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id


def test_ep_tp_composes(moe_params):
    """ep × tp: Megatron-split attention + per-expert FFNs on the tensor
    axis, expert banks on the expert axis — outputs still pinned to the
    plain engine."""
    reqs = _requests(MOE.vocab_size, n=3)
    base = _run(_engine(moe_params), reqs)
    eng = _engine(moe_params, ep=2, tp=2)
    got = _run(eng, reqs)
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    # the mesh really is (data=1, tensor=2, expert=2) over 4 devices
    assert eng._mesh is not None and eng._mesh.devices.size == 4


def test_ep_expert_banks_sharded_pages_replicated(moe_params):
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.parallel.mesh import EXPERT_AXIS

    eng = _engine(moe_params, ep=2)
    w_in = eng.params["blocks"][1]["moe"]["w_in"]
    assert w_in.sharding.spec == P(EXPERT_AXIS)
    # page pools untouched by ep: kv-head axis over a size-1 tensor axis
    assert eng.pages[0]["k"].sharding.spec[2] in (None, "tensor")
    assert isinstance(eng.tables.tables, np.ndarray)


def test_nf4_ep2_matches_nf4_single_device(moe_params):
    """NF4 expert banks shard with the dense specs (shaped layout: the
    expert dim is a leading dim, 1:1 on codes and absmax) — quantized ep
    serving matches the single-device quantized engine."""
    from distributed_lion_tpu.ops.quant import QuantizedTensor

    reqs = _requests(MOE.vocab_size, n=3)
    base = _run(_engine(moe_params, quant="nf4"), reqs)
    eng = _engine(moe_params, quant="nf4", ep=2)
    got = _run(eng, reqs)
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    assert isinstance(eng.params["blocks"][1]["moe"]["w_in"],
                      QuantizedTensor)


# ------------------------------------------------------------ compositions
def test_moe_prefix_cache_shared_matches_unshared(moe_params):
    """MoE × --prefix_cache: shared prefix pages hold bit-identical k/v
    and no-drop routing is per-token, so sharing cannot change any expert
    assignment — outputs pinned to the unshared engine, and sharing
    actually happened."""
    rng = np.random.default_rng(17)
    sys_p = list(map(int, rng.integers(1, MOE.vocab_size, 13)))
    prompts = [sys_p + list(map(int, rng.integers(1, MOE.vocab_size, 3)))
               for _ in range(5)]
    reqs = [Request(req_id=i, tokens=list(t), max_new_tokens=6, seed=i)
            for i, t in enumerate(prompts)]
    base = _run(_engine(moe_params, num_blocks=64), reqs)
    eng = _engine(moe_params, num_blocks=64, prefix_cache=True)
    got = _run(eng, reqs)
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    assert eng.stats["prefix_hits"] > 0


@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_moe_ngram_speculation_matches_plain(moe_params, sampling):
    """MoE × ngram speculation: the verify window is a wider no-drop
    dispatch with its tail valid-masked, and rollback over MoE pages is
    attention-side only — speculative output pinned to the plain engine,
    with acceptances actually earned on repetitive traffic."""
    samp = ({} if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    rng = np.random.default_rng(19)
    motif = list(map(int, rng.integers(1, MOE.vocab_size, 4)))
    reqs = [Request(req_id=i, tokens=motif * 4, max_new_tokens=10, seed=i)
            for i in range(3)]
    base = _run(_engine(moe_params, max_blocks_per_seq=16, **samp), reqs)
    eng = _engine(moe_params, max_blocks_per_seq=16, speculate="ngram:4",
                  **samp)
    got = _run(eng, reqs)
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    if sampling == "greedy":
        assert eng.stats["spec_accepted"] > 0


def test_moe_prefix_and_ep_compose_together(moe_params):
    """The full stack: prefix sharing × expert parallelism on one MoE
    engine still reproduces the plain engine's streams."""
    rng = np.random.default_rng(23)
    sys_p = list(map(int, rng.integers(1, MOE.vocab_size, 9)))
    prompts = [sys_p + list(map(int, rng.integers(1, MOE.vocab_size, 2)))
               for _ in range(4)]
    reqs = [Request(req_id=i, tokens=list(t), max_new_tokens=5, seed=i)
            for i, t in enumerate(prompts)]
    base = _run(_engine(moe_params, num_blocks=64), reqs)
    eng = _engine(moe_params, num_blocks=64, prefix_cache=True, ep=2)
    got = _run(eng, reqs)
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id


# ---------------------------------------------------------------- refusals
def test_serve_ep_refuses_dense_checkpoint():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="MoE checkpoint"):
        _engine(params, cfg, ep=2)


def test_serve_ep_refuses_indivisible_experts(moe_params):
    with pytest.raises(ValueError, match="divisible"):
        _engine(moe_params, ep=3)


def test_serve_ep_refuses_more_ranks_than_devices():
    cfg = GPT2Config.tiny(n_head=16, d_model=256, moe_experts=16)
    params = gpt2_init(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="devices"):
        _engine(params, cfg, ep=16)


def test_serve_ep_refuses_llama():
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_init

    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), cfg)
    model = ServeModel.for_llama(params, cfg)
    with pytest.raises(ValueError, match="MoE checkpoint"):
        ServingEngine(model, ServeConfig(max_seqs=2, block_size=4,
                                         max_blocks_per_seq=4, ep=2))


# ----------------------------------------------------------- routing stats
def test_engine_moe_stats_accumulate(moe_params):
    """ServeConfig.moe_stats: the engine folds per-dispatch routing-load
    scalars into stats — valid tokens counted, kept <= valid, slots > 0 —
    and the default engine pays nothing (keys absent)."""
    reqs = _requests(MOE.vocab_size)
    eng = _engine(moe_params, moe_stats=True)
    _run(eng, reqs)
    assert eng.stats["moe_valid_tokens"] > 0
    assert 0 < eng.stats["moe_kept_tokens"] <= eng.stats["moe_valid_tokens"]
    assert eng.stats["moe_capacity_slots"] > 0
    plain = _engine(moe_params)
    _run(plain, reqs)
    assert "moe_valid_tokens" not in plain.stats


def test_engine_moe_stats_accumulate_under_speculation(moe_params):
    """Regression (review round): the speculative VERIFY dispatch must
    feed the routing-stats counters too — with ngram speculation armed,
    decode-side stats keep growing after admissions, not just the
    prefill contribution."""
    rng = np.random.default_rng(31)
    motif = list(map(int, rng.integers(1, MOE.vocab_size, 4)))
    eng = _engine(moe_params, moe_stats=True, speculate="ngram:2",
                  max_blocks_per_seq=16)
    for i in range(3):
        eng.submit(Request(req_id=i, tokens=motif * 4, max_new_tokens=12,
                           seed=i))
    while eng.pending:
        eng.step()
    after_fill = eng.stats["moe_valid_tokens"]
    assert after_fill > 0  # prefill contributed
    for _ in range(3):
        eng.step()
    assert eng.stats["moe_valid_tokens"] > after_fill  # verify did too


def test_moe_stats_flag_inert_on_dense_checkpoint():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    eng = _engine(params, cfg, moe_stats=True)
    _run(eng, _requests(cfg.vocab_size, n=2))
    assert "moe_valid_tokens" not in eng.stats  # no MoE blocks to measure


# ------------------------------------------------- the evidence artifact
def _load_ce():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_moe", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    return ce


def test_banked_artifact_passes_moe_serving_stage():
    """The committed CPU artifact (captured under DLION_PLATFORM=cpu8 so
    the ep>=2 legs exist) satisfies the ISSUE 15+16 moe_serving stage:
    strict schema, all ten identity markers, dense + moe + moe_ep>=2
    matrix rows with measured tokens/s/chip and [0,1] capacity columns,
    and at least one batch-sharded row strictly above the replicated row
    at a matched (batch, ep) — the gate runbook stage 5m re-judges after
    the on-chip recapture."""
    ce = _load_ce()
    assert ce.moe_serving_ok()
    with open(ce.SERVE_ARTIFACT) as f:
        doc = json.load(f)
    sec = doc["moe_serving"]
    configs = {r["config"] for r in sec["rows"]}
    assert {"dense", "moe"} <= configs
    assert any(r["ep"] >= 2 for r in sec["rows"])
    for r in sec["rows"]:
        if r["experts"]:
            assert 0.0 <= r["capacity_utilization"] <= 1.0
            assert 0.0 <= r["dropped_rate"] <= 1.0
    # ISSUE 16: the banked matrix carries the throughput-lever evidence —
    # EVERY batch-sharded row beats its replicated twin per chip
    pairs = 0
    for r in sec["rows"]:
        if r["sharding"] != "batch":
            continue
        twins = [x for x in sec["rows"] if x["sharding"] == "replicated"
                 and x["ep"] == r["ep"] and x["batch"] == r["batch"]]
        assert twins, r
        for x in twins:
            assert r["tokens_per_sec_per_chip"] \
                > x["tokens_per_sec_per_chip"], (r, x)
        pairs += 1
    assert pairs >= 1


def test_moe_serving_stage_rejects_bad_artifacts(tmp_path):
    ce = _load_ce()
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "serving.json"

    def reject(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        p.write_text(json.dumps(doc))
        assert not ce.moe_serving_ok(str(p))

    # artifact predates ISSUE 15 entirely (also a schema violation now)
    reject(lambda d: d.pop("moe_serving"))
    # each identity marker flips the stage
    for k in ce.MOE_SERVE_MARKERS:
        reject(lambda d, k=k: d["moe_serving"]["markers"].update({k: False}))
    # matrix coverage: no expert-parallel row / no dense baseline
    reject(lambda d: d["moe_serving"].update(
        rows=[r for r in d["moe_serving"]["rows"] if r["ep"] < 2]))
    reject(lambda d: d["moe_serving"].update(
        rows=[r for r in d["moe_serving"]["rows"]
              if r["config"] != "dense"]))
    # throughput floor on a MoE row
    def slow(d):
        for r in d["moe_serving"]["rows"]:
            if r["experts"]:
                r["tokens_per_sec_per_chip"] = 1.0
                break
    reject(slow)
    # schema: capacity column outside [0, 1] (validate_metrics delegation)
    def bad_util(d):
        for r in d["moe_serving"]["rows"]:
            if r["experts"]:
                r["capacity_utilization"] = 1.5
                break
    reject(bad_util)
    # ISSUE 16: no batch-sharded row at all — 'throughput lever' unmeasured
    reject(lambda d: d["moe_serving"].update(
        rows=[r for r in d["moe_serving"]["rows"]
              if r["sharding"] != "batch"]))
    # batch-sharded rows that tie (not STRICTLY beat) the replicated twin
    def lever_lost(d):
        rows = d["moe_serving"]["rows"]
        for r in rows:
            if r["sharding"] != "batch":
                continue
            for x in rows:
                if (x["sharding"] == "replicated" and x["ep"] == r["ep"]
                        and x["batch"] == r["batch"]):
                    r["tokens_per_sec_per_chip"] = \
                        x["tokens_per_sec_per_chip"]
    reject(lever_lost)
    # schema: the sharding / beats_dense_per_chip columns are mandatory
    def bad_sharding(d):
        d["moe_serving"]["rows"][0]["sharding"] = "sideways"
    reject(bad_sharding)
    def no_beats_col(d):
        d["moe_serving"]["rows"][0].pop("beats_dense_per_chip")
    reject(no_beats_col)
    # the untouched artifact still passes from the tmp copy
    p.write_text(json.dumps(good))
    assert ce.moe_serving_ok(str(p))
