"""Cross-step DCN overlap (ISSUE 8): the pipelined level-2 hier vote.

The tentpole contract, pinned here:

- the launch/consume split of the hier election (collectives.hier_launch /
  hier_consume) is bit-identical to an INDEPENDENT numpy
  majority-of-majorities reference at depth 0, with and without health
  masks — the "depth-0 == today's hier wire" pin that survives the
  refactor;
- ``dcn_pipeline_depth=0`` is byte-for-byte the default hier wire across
  vote_buckets {1,4} × det/stoch × guard off/enforce × XLA/Pallas;
- at depth d the signs APPLIED at step t are exactly the signs the
  synchronous wire elects at step t−d (ballots are params-independent —
  momentum is a pure function of the grad sequence — so the shifted-delta
  identity is exact), and the first d steps apply no update;
- the elected-sign cache under ``vote_every`` × depth trails the
  synchronous cache by exactly d steps;
- a group fully quarantined at EITHER end of a tally's flight abstains
  from the stale election (the launch-mask ∩ current-mask rule);
- the ``dcn_delay`` link emulator charges the synchronous wire the full
  injected round trip while depth ≥ 1 demonstrably hides part of it
  (measured via collectives.DCN_WAIT — wall-clock-free, so the assertion
  survives a loaded CI box), and is timing-only (elections unchanged);
- the in-flight ring rides checkpoints (tests/test_crash_resume.py holds
  the resume cells) and misconfiguration fails loudly at build time.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.ops.codec import (
    a2a_chunk_bytes,
    hier_chunk_slot_bytes,
    hier_ring_slot_bytes,
    vote_chunk_elems,
)
from distributed_lion_tpu.optim import (
    distributed_lion,
    expand_worker_state,
    init_global_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.lion import LionState
from distributed_lion_tpu.parallel import collectives
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train import resilience


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(data=4, devices=jax.devices()[:4])


# ------------------------------------------------- independent reference
def _ref_hier(ballots: np.ndarray, g: int, alive=None) -> np.ndarray:
    """Majority-of-majorities over [W, n] bool ballots, straight from the
    definition (no packing, no rings): level-1 ties → −1 inside each
    g-worker group (healthy members only), a group with no healthy member
    abstains at level 2, level-2 ties → −1 over the participating groups."""
    w, n = ballots.shape
    alive = np.ones(w, bool) if alive is None else np.asarray(alive, bool)
    signs = np.where(ballots, 1, -1) * alive[:, None]
    verdicts, counted = [], []
    for k in range(w // g):
        grp = signs[k * g:(k + 1) * g]
        verdicts.append(grp.sum(0) > 0)
        counted.append(alive[k * g:(k + 1) * g].any())
    verdicts = np.stack(verdicts)
    counted = np.asarray(counted)
    return verdicts[counted].sum(0) * 2 > counted.sum()


def _vote(mesh, ballots, wire, alive=None):
    def body(b, *a):
        return collectives.majority_vote(b[0], "data", wire,
                                         a[0] if a else None)

    args = (ballots,) if alive is None else (ballots, alive)
    specs = (P("data"),) if alive is None else (P("data"), P())
    return np.asarray(shard_map(body, mesh=mesh, in_specs=specs,
                                out_specs=P(), check_vma=False)(*args))


@pytest.mark.parametrize("g", [2, 4, 8])
@pytest.mark.parametrize("n", [7, 64, 1003])
def test_hier_depth0_matches_reference(mesh8, g, n):
    """The refactored (launch/consume-split) hier election == the
    independent majority-of-majorities reference, masked and unmasked —
    the depth-0 bit-identity pin the ISSUE-8 refactor must not move."""
    rng = np.random.default_rng(5)
    ballots = jnp.asarray(rng.integers(0, 2, size=(8, n)).astype(bool))
    got = _vote(mesh8, ballots, f"hier:{g}")
    np.testing.assert_array_equal(got, _ref_hier(np.asarray(ballots), g))
    # masked: one quarantined worker, and one fully-dead group
    for alive in (np.array([True] * 7 + [False]),
                  np.array([False] * g + [True] * (8 - g))):
        got = _vote(mesh8, ballots, f"hier:{g}", jnp.asarray(alive))
        np.testing.assert_array_equal(
            got, _ref_hier(np.asarray(ballots), g, alive))


def test_mid_flight_quarantine_gates_stale_tally(mesh8):
    """The launch-mask ∩ current-mask rule: a group fully quarantined at
    EITHER end of the flight abstains from the stale election. Drives
    hier_launch/hier_consume directly with different masks at each end."""
    g, n = 4, 257
    rng = np.random.default_rng(9)
    ballots = jnp.asarray(rng.integers(0, 2, size=(8, n)).astype(bool))
    all_alive = np.ones(8, bool)
    g1_dead = np.array([True] * 4 + [False] * 4)

    def run(launch_alive, consume_alive):
        def body(b, la, ca):
            slot = collectives.hier_launch(b[0], "data", 8, g, la)
            return collectives.hier_consume(slot, n, "data", 8, g, ca)

        return np.asarray(shard_map(
            body, mesh=mesh8, in_specs=(P("data"), P(), P()),
            out_specs=P(), check_vma=False,
        )(ballots, jnp.asarray(launch_alive), jnp.asarray(consume_alive)))

    ref_excluded = _ref_hier(np.asarray(ballots), g, g1_dead)
    # dead at launch, revived before consume: still excluded (its launch
    # verdict was cast with zero healthy members — garbage forever)
    np.testing.assert_array_equal(run(g1_dead, all_alive), ref_excluded)
    # healthy at launch, fully quarantined before consume: excluded too
    np.testing.assert_array_equal(run(all_alive, g1_dead), ref_excluded)
    # healthy at both ends == the unmasked election
    np.testing.assert_array_equal(run(all_alive, all_alive),
                                  _ref_hier(np.asarray(ballots), g))


# ----------------------------------------------------- optimizer matrix
def _toy_problem(world=8, n=40):
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (n,)), "b": jnp.zeros((3,))}
    grads = {
        "w": jax.random.normal(jax.random.key(1), (world, n)),
        "b": jax.random.normal(jax.random.key(2), (world, 3)),
    }
    return params, grads


def _run_steps(opt, params, grads_per_step, mesh, world, rng=None,
               has_elected=False, depth=0, guard=False):
    """Drive opt.step under shard_map over a SEQUENCE of per-step grads;
    returns the param trajectory (host copies) + final state."""
    state = init_global_state(opt, params, world, rng=rng)
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(
        count=P(),
        exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None if rng is None else P(),
        elected=P() if has_elected else None,
        health=P() if guard else None,
        prev_ballot=P("data") if guard else None,
        dcn_ring=P("data") if depth else None,
    )
    g_spec = jax.tree.map(lambda _: P("data"), grads_per_step[0])

    @jax.jit
    def step(params, grads, state):
        def body(p, g, st):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            outs = opt.step(p, g, st)
            return outs[0], expand_worker_state(outs[1])

        return shard_map(
            body, mesh=mesh, in_specs=(p_spec, g_spec, st_spec),
            out_specs=(p_spec, st_spec), check_vma=False,
        )(params, grads, state)

    traj = [jax.device_get(params)]
    p, st = params, state
    for g in grads_per_step:
        p, st = step(p, g, st)
        traj.append(jax.device_get(p))
    return traj, st


def _grad_seq(steps, world=8, n=40):
    return [{
        "w": jax.random.normal(jax.random.key(100 + i), (world, n)),
        "b": jax.random.normal(jax.random.key(200 + i), (world, 3)),
    } for i in range(steps)]


def _assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


@pytest.mark.parametrize("stoch", [False, True], ids=["det", "stoch"])
@pytest.mark.parametrize("buckets", [1, 4])
@pytest.mark.parametrize("guard", ["off", "enforce"])
def test_depth0_bit_identical_to_default_wire(mesh8, buckets, stoch, guard):
    """Acceptance cell: an EXPLICIT dcn_pipeline_depth=0 is byte-for-byte
    the default hier wire across vote_buckets × det/stoch × guard (XLA
    path; the Pallas cell is below — its gate only admits det × guard
    combinations it compiled before this PR)."""
    params, _ = _toy_problem()
    gseq = _grad_seq(3)
    kw = dict(learning_rate=0.01, weight_decay=0.01, wire="hier:4",
              vote_buckets=buckets, guard=guard,
              max_grad_norm=1.0 if stoch else None)
    rng = jax.random.key(7) if stoch else None
    base, base_st = _run_steps(distributed_lion(**kw), params, gseq, mesh8,
                               8, rng=rng, guard=guard != "off")
    expl, expl_st = _run_steps(distributed_lion(dcn_pipeline_depth=0, **kw),
                               params, gseq, mesh8, 8, rng=rng,
                               guard=guard != "off")
    for a, b in zip(base, expl):
        _assert_trees_equal(a, b)
    _assert_trees_equal(base_st.exp_avg, expl_st.exp_avg)


def test_depth0_bit_identical_pallas(mesh8):
    """The Pallas window path at depth 0 (its gate) still matches the XLA
    default wire — and a depth > 0 build routes to the XLA path instead of
    the fused kernels, bit-identical to an explicit kernel='xla' build."""
    params, _ = _toy_problem(n=300)
    gseq = _grad_seq(3, n=300)
    base, _ = _run_steps(
        distributed_lion(learning_rate=0.01, wire="hier:4", kernel="xla"),
        params, gseq, mesh8, 8)
    pall, _ = _run_steps(
        distributed_lion(learning_rate=0.01, wire="hier:4", kernel="pallas",
                         dcn_pipeline_depth=0, vote_buckets=4),
        params, gseq, mesh8, 8)
    for a, b in zip(base, pall):
        _assert_trees_equal(a, b)
    d_pall, _ = _run_steps(
        distributed_lion(learning_rate=0.01, wire="hier:4", kernel="pallas",
                         dcn_pipeline_depth=1),
        params, gseq, mesh8, 8, depth=1)
    d_xla, _ = _run_steps(
        distributed_lion(learning_rate=0.01, wire="hier:4", kernel="xla",
                         dcn_pipeline_depth=1),
        params, gseq, mesh8, 8, depth=1)
    for a, b in zip(d_pall, d_xla):
        _assert_trees_equal(a, b)


@pytest.mark.parametrize("depth,buckets", [(1, 1), (2, 3)])
def test_staleness_shift_is_exact(mesh8, depth, buckets):
    """The semantics pin: Lion's ballots are params-independent (momentum
    is a pure function of the grad sequence), so with weight_decay=0 and a
    constant lr the signs applied at depth-d step t are EXACTLY the signs
    the synchronous wire applies at step t−d — param deltas shift by d
    steps, bit-for-bit — and the first d steps apply no update at all."""
    params, _ = _toy_problem()
    gseq = _grad_seq(6)
    kw = dict(learning_rate=0.01, weight_decay=0.0, wire="hier:4",
              vote_buckets=buckets)
    t0, _ = _run_steps(distributed_lion(**kw), params, gseq, mesh8, 8)
    td, _ = _run_steps(distributed_lion(dcn_pipeline_depth=depth, **kw),
                       params, gseq, mesh8, 8, depth=depth)
    for t in range(depth):  # cold start: no update (wd=0 → params frozen)
        _assert_trees_equal(td[t + 1], td[t])
    for t in range(depth, 6):
        d_now = jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b),
                             td[t + 1], td[t])
        d_ref = jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b),
                             t0[t - depth + 1], t0[t - depth])
        _assert_trees_equal(d_now, d_ref)


def test_lazy_cache_trails_by_depth(mesh8):
    """vote_every × depth composition: the elected-sign cache at depth d
    after step t equals the synchronous lazy cache after step t−d (the
    consumed election lands in slot (t−d) mod K), and cold-start slots
    stay at their zero init."""
    params, _ = _toy_problem()
    gseq = _grad_seq(9)
    kw = dict(learning_rate=0.01, weight_decay=0.0, wire="hier:4",
              vote_every=4)

    def caches(depth):
        state = init_global_state(distributed_lion(
            dcn_pipeline_depth=depth, **kw), params, 8)
        opt = distributed_lion(dcn_pipeline_depth=depth, **kw)
        p_spec = jax.tree.map(lambda _: P(), params)
        st_spec = LionState(
            count=P(),
            exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
            rng=None, elected=P(),
            dcn_ring=P("data") if depth else None)
        g_spec = jax.tree.map(lambda _: P("data"), gseq[0])

        @jax.jit
        def step(params, grads, state):
            def body(p, g, st):
                st = squeeze_worker_state(st)
                g = jax.tree.map(lambda x: x[0], g)
                outs = opt.step(p, g, st)
                return outs[0], expand_worker_state(outs[1])

            return shard_map(
                body, mesh=mesh8, in_specs=(p_spec, g_spec, st_spec),
                out_specs=(p_spec, st_spec), check_vma=False,
            )(params, grads, state)

        out, p, st = [], params, state
        for g in gseq:
            p, st = step(p, g, st)
            out.append(np.asarray(jax.device_get(st.elected)))
        return out

    c0 = caches(0)
    c2 = caches(2)
    zero = np.zeros_like(c0[0])
    np.testing.assert_array_equal(c2[0], zero)  # nothing landed yet
    np.testing.assert_array_equal(c2[1], zero)
    for t in range(2, 9):
        np.testing.assert_array_equal(c2[t], c0[t - 2])


# -------------------------------------------------- the dcn_delay link
def test_dcn_delay_charges_sync_and_depth_hides(mesh4):
    """The link emulator: at depth 0 every step pays ~the full injected
    round trip at the consume gate (DCN_WAIT records it); at depth 1 the
    steps of compute inside the flight window count toward the deadline,
    so the residual wait measurably shrinks. Wait-based, not wall-based —
    immune to CI box noise — and the fault is timing-only: the parameter
    trajectory is bit-identical armed vs unarmed."""
    params, _ = _toy_problem(world=4, n=20_000)
    gseq = _grad_seq(6, world=4, n=20_000)
    delay = 0.08
    kw = dict(learning_rate=0.01, wire="hier:2")

    def run(depth, armed):
        resilience.inject_fault("dcn_delay", delay if armed else None)
        collectives.dcn_link_reset()
        try:
            traj, _ = _run_steps(
                distributed_lion(dcn_pipeline_depth=depth, **kw), params,
                gseq, mesh4, 4, depth=depth)
            waits = collectives.DCN_WAIT.pop()
            return traj, sum(waits.values())
        finally:
            resilience.inject_fault("dcn_delay", None)
            collectives.dcn_link_reset()

    t0_armed, wait0 = run(0, True)
    t0_plain, _ = run(0, False)
    for a, b in zip(t0_armed, t0_plain):  # timing-only
        _assert_trees_equal(a, b)
    # the synchronous wire pays ~the full round trip every step (first
    # consume may ride the compile window; demand 4 of 6)
    assert wait0 >= 4 * delay, wait0
    _, wait1 = run(1, True)
    # depth 1 hides at least the per-step compute behind the flight; even
    # on a trivial toy problem the steady-state residual is (L−c)/2 < L,
    # so demand a ≥25% cut with headroom for a loaded box
    assert wait1 <= 0.75 * wait0, (wait0, wait1)


# ------------------------------------------------- byte conservation
@pytest.mark.parametrize("depth", [0, 1, 2])
@pytest.mark.parametrize("ve,buckets", [(1, 1), (1, 4), (4, 1)])
def test_hier_depth_wire_bytes_drift_zero(mesh8, depth, ve, buckets):
    """ISSUE 8 satellite: the overlapped leg moves exactly the same bytes
    every step — one launch + one consume — so the trace-time measured
    ledger equals codec's analytic accounting EXACTLY for hier ×
    dcn_pipeline_depth {0,1,2} × vote_every {1,4} (and the accounting
    itself is depth-invariant). Abstract eval only: no compile."""
    from distributed_lion_tpu.ops.codec import wire_bytes_per_param
    from distributed_lion_tpu.train import telemetry

    params, grads = _toy_problem()
    n = sum(p.size for p in jax.tree.leaves(params))
    opt = distributed_lion(0.01, wire="hier:4", vote_every=ve,
                           vote_buckets=buckets, dcn_pipeline_depth=depth)
    state = init_global_state(opt, params, 8)
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(
        count=P(), exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None, elected=P() if ve > 1 else None,
        dcn_ring=P("data") if depth else None)
    g_spec = jax.tree.map(lambda _: P("data"), grads)

    def step(params, grads, state):
        def body(p, g, st):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            p2, st2 = opt.step(p, g, st)
            return p2, expand_worker_state(st2)

        return shard_map(body, mesh=mesh8, in_specs=(p_spec, g_spec, st_spec),
                         out_specs=(p_spec, st_spec), check_vma=False,
                         )(params, grads, state)

    measured = telemetry.measure_step_wire(step, params, grads, state)
    acct = wire_bytes_per_param(n, 8, "hier:4", vote_every=ve,
                                vote_buckets=buckets,
                                dcn_pipeline_depth=depth)
    assert measured["bytes_per_step"] == acct["bytes_per_step"], (
        measured, acct)
    assert measured["dcn_bytes_per_step"] == acct["dcn_bytes_per_step"]
    # the accounting itself must be depth-invariant (bytes never change;
    # only the latency eligibility flag does)
    base = wire_bytes_per_param(n, 8, "hier:4", vote_every=ve,
                                vote_buckets=buckets)
    assert acct["bytes_per_step"] == base["bytes_per_step"]
    assert acct["dcn_bytes_per_step"] == base["dcn_bytes_per_step"]
    assert acct["dcn_overlap_frac"] == (1.0 if depth else 0.0)


# --------------------------------------------------------- ring layout
def test_ring_slot_bytes_layout():
    w, g = 8, 4
    for n in (7, 64, 1003, 123_457):
        for buckets in (1, 3, 4):
            from distributed_lion_tpu.ops.codec import bucket_bounds

            per = [hier_chunk_slot_bytes(size, w, g)
                   for _, size in bucket_bounds(n, buckets, w, f"hier:{g}")]
            assert hier_ring_slot_bytes(n, w, g, buckets) == sum(per)
            # each segment: [G] mask + [G, chunk/8] stack
            for (_, size), seg in zip(
                    bucket_bounds(n, buckets, w, f"hier:{g}"), per):
                assert seg == (w // g) * (1 + a2a_chunk_bytes(size, g))
    # lazy refresh lays the ring out for the PADDED rotating slice
    assert hier_ring_slot_bytes(1003, w, g, 1, vote_every=4) == \
        hier_ring_slot_bytes(vote_chunk_elems(1003, 4), w, g, 1)
    with pytest.raises(ValueError, match="does not divide"):
        hier_ring_slot_bytes(100, 8, 3)


def test_ring_rides_state_with_expected_shape(mesh8):
    opt = distributed_lion(wire="hier:4", dcn_pipeline_depth=3,
                           vote_buckets=2)
    params, _ = _toy_problem()
    n = sum(p.size for p in jax.tree.leaves(params))
    state = init_global_state(opt, params, 8)
    assert state.dcn_ring.shape == (8, 3, hier_ring_slot_bytes(n, 8, 4, 2))
    assert state.dcn_ring.dtype == jnp.uint8
    # depth 0: no ring state at all
    assert init_global_state(
        distributed_lion(wire="hier:4"), params, 8).dcn_ring is None


# ---------------------------------------------------------- validation
def test_depth_validation():
    with pytest.raises(ValueError, match="must be >= 0"):
        distributed_lion(wire="hier:4", dcn_pipeline_depth=-1)
    with pytest.raises(ValueError, match="no such leg"):
        distributed_lion(wire="sign_psum", dcn_pipeline_depth=1)
    with pytest.raises(ValueError, match="no such leg"):
        distributed_lion(wire="packed_a2a", dcn_pipeline_depth=2)
    with pytest.raises(ValueError, match="no wire"):
        distributed_lion(axis_name=None, wire="hier:2",
                         dcn_pipeline_depth=1)


def test_trainer_depth_validation():
    from distributed_lion_tpu.train.loop import TrainConfig, make_optimizer

    with pytest.raises(ValueError, match="nothing to overlap"):
        make_optimizer(TrainConfig(wire="packed_a2a", dcn_pipeline_depth=1))
    with pytest.raises(ValueError, match="unresolved 'auto'"):
        # the unresolved auto sentinel must not silently decide staleness
        make_optimizer(TrainConfig(dcn_pipeline_depth=1))
    with pytest.raises(ValueError, match="no vote collective"):
        make_optimizer(TrainConfig(lion=False, async_grad=False,
                                   dcn_pipeline_depth=1))
