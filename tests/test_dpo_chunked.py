"""Chunked-vocab DPO: the four scoring passes (policy/ref × chosen/rejected)
stream their label logprobs through ops/xent's chunked logsumexp instead of
materializing [B, T, V] f32 log_softmax — the largest activation saving of
any workload (DPO holds TWO models and scores TWO sequences each). Exact
same math as the dense path (reference semantics: dpo_llama2.py:192-223);
these tests pin loss, gradients, trajectory, and the CLI flag end-to-end."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_hidden, llama_init
from distributed_lion_tpu.models.lora import LoraConfig, lora_apply_fn, lora_init
from distributed_lion_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from distributed_lion_tpu.train.dpo import (
    make_dpo_loss_fn,
    sequence_logprob,
    sequence_logprob_chunked,
)
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _rand_batch(rng, gb, T, vocab):
    b = {}
    for side in ("chosen", "rejected"):
        b[side] = rng.integers(0, vocab, size=(gb, T)).astype(np.int32)
        mask = np.zeros((gb, T), np.float32)
        for r in range(gb):
            start = int(rng.integers(2, T // 2))
            stop = int(rng.integers(T // 2 + 1, T))
            mask[r, start:stop] = 1.0
        b[f"{side}_mask"] = mask
    return b


def test_sequence_logprob_chunked_matches_dense():
    """−nll-from-hidden == gather-from-log_softmax, values AND gradients
    (hidden and head), at a vocab that doesn't divide the chunk count."""
    rng = np.random.default_rng(0)
    B, T, D, V = 2, 10, 8, 37
    hidden = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
    head = jnp.asarray(rng.normal(size=(D, V)), jnp.float32)
    tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    mask = jnp.asarray((rng.random((B, T)) > 0.4), jnp.float32)

    def dense(hidden, head):
        logits = jnp.einsum("btd,dv->btv", hidden, head)
        return sequence_logprob(logits, tokens, mask).sum()

    def chunked(hidden, head):
        return sequence_logprob_chunked(hidden, head, tokens, mask,
                                        n_chunks=4, emb_layout="dv").sum()

    v_d, g_d = jax.value_and_grad(dense, argnums=(0, 1))(hidden, head)
    v_c, g_c = jax.value_and_grad(chunked, argnums=(0, 1))(hidden, head)
    np.testing.assert_allclose(v_d, v_c, rtol=1e-5, atol=1e-5)
    for a, b in zip(g_d, g_c):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def _pieces():
    model_cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(0), model_cfg)
    lcfg = LoraConfig(r=4, alpha=8)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    return model_cfg, base, lcfg, adapters


def _loss_fns(model_cfg, base, lcfg, vocab_chunks):
    """(dense, chunked) DPO loss fns over the same frozen base."""
    pol_dense = lora_apply_fn(
        lambda p, t: llama_apply(p, t, model_cfg), base, lcfg)
    dense = make_dpo_loss_fn(
        policy_apply=pol_dense,
        ref_apply=lambda t: llama_apply(base, t, model_cfg), beta=0.1)

    def hidden_head(p, t):
        return llama_hidden(p, t, model_cfg), p["lm_head"]

    pol_chunked = lora_apply_fn(hidden_head, base, lcfg)
    chunked = make_dpo_loss_fn(
        policy_apply=pol_chunked,
        ref_apply=lambda t: hidden_head(base, t), beta=0.1,
        vocab_chunks=vocab_chunks)
    return dense, chunked


def test_dpo_loss_and_grads_match_dense():
    model_cfg, base, lcfg, adapters = _pieces()
    dense, chunked = _loss_fns(model_cfg, base, lcfg, vocab_chunks=4)
    assert getattr(chunked, "_vocab_chunked") is True
    batch = jax.tree.map(jnp.asarray,
                         _rand_batch(np.random.default_rng(1), 2, 32,
                                     model_cfg.vocab_size))

    (l_d, m_d), g_d = jax.value_and_grad(
        lambda a: dense(a, batch, None), has_aux=True)(adapters)
    (l_c, m_c), g_c = jax.value_and_grad(
        lambda a: chunked(a, batch, None), has_aux=True)(adapters)
    np.testing.assert_allclose(l_d, l_c, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(m_d["reward_margin"], m_c["reward_margin"],
                               rtol=1e-4, atol=1e-5)
    # adapter grads flow through bf16 compute; the chunked scan reorders
    # the backward sums, so leaves agree to bf16 resolution (~1%), while
    # loss/metrics (f32 reductions) pin at 1e-5 above
    for a, b in zip(jax.tree.leaves(g_d), jax.tree.leaves(g_c)):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        denom = np.abs(a).max() + 1e-12
        assert np.abs(a - b).max() / denom < 2e-2


def _train(mesh, sp, vocab_chunks, steps=6):
    model_cfg, base, lcfg, adapters = _pieces()
    seq_axis = SEQ_AXIS if sp > 1 else None
    kw = {} if seq_axis is None else {"seq_axis": seq_axis}

    if vocab_chunks > 0:
        def fwd(p, t):
            return llama_hidden(p, t, model_cfg, **kw), p["lm_head"]
        ref_fwd = lambda t: fwd(base, t)  # noqa: E731
    else:
        def fwd(p, t):
            return llama_apply(p, t, model_cfg, **kw)
        ref_fwd = lambda t: fwd(base, t)  # noqa: E731
    loss_fn = make_dpo_loss_fn(
        policy_apply=lora_apply_fn(fwd, base, lcfg), ref_apply=ref_fwd,
        beta=0.1, seq_axis=seq_axis, vocab_chunks=vocab_chunks)

    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=1e-3, weight_decay=0.0,
        warmup_steps=2, max_steps=steps, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=64, logging_steps=1,
        eval_steps=1000, save_steps=1000, seed=0,
        vocab_chunks=vocab_chunks,
    )
    spec = P(DATA_AXIS, SEQ_AXIS) if sp > 1 else None
    trainer = Trainer(cfg, mesh, apply_fn=None, params=adapters,
                      loss_fn=loss_fn, batch_spec=spec)
    rng = np.random.default_rng(2)
    batches = [_rand_batch(rng, trainer.global_train_batch(), 64,
                           LlamaConfig.tiny().vocab_size)
               for _ in range(steps)]
    history = trainer.train(iter(batches), max_steps=steps)
    losses = [h["loss"] for h in history if "loss" in h]
    trainer.close()
    return losses


def test_dpo_chunked_trajectory_matches_dense():
    """Full vote-Lion DPO training with vocab_chunks reproduces the dense
    trajectory (same data, same world)."""
    mesh = make_mesh(data=2, devices=jax.devices()[:2])
    np.testing.assert_allclose(
        _train(mesh, sp=1, vocab_chunks=0),
        _train(mesh, sp=1, vocab_chunks=4), rtol=2e-3, atol=2e-3)


def test_dpo_chunked_seq_parallel_matches_dense_dp():
    """Chunked logprobs compose with the seq-axis boundary protocol: the
    dp×sp chunked trajectory == pure-dp dense trajectory."""
    mesh_sp = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    mesh_dp = make_mesh(data=2, devices=jax.devices()[:2])
    np.testing.assert_allclose(
        _train(mesh_sp, sp=4, vocab_chunks=4),
        _train(mesh_dp, sp=1, vocab_chunks=0), rtol=2e-2, atol=2e-2)


def test_run_dpo_cli_vocab_chunks_smoke(tmp_path):
    from distributed_lion_tpu.cli.run_dpo import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic",
        "--num_train_samples", "48", "--size_valid_set", "8",
        "--max_length", "96", "--max_prompt_length", "48",
        "--lion", "--async_grad", "--max_steps", "2", "--warmup_steps", "1",
        "--per_device_train_batch_size", "1",
        "--gradient_accumulation_steps", "1", "--logging_steps", "1",
        "--eval_steps", "1000", "--save_steps", "1000", "--eval_iters", "1",
        "--vocab_chunks", "4",
        "--output_dir", str(tmp_path / "dpo_vc"),
    ])
    assert (tmp_path / "dpo_vc" / "metrics.jsonl").exists()
