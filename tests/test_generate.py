"""KV-cache decode and generation (SURVEY §4 unit style): the incremental
decode path must match the full forward position-for-position, and the
jitted scan generation must be deterministic under greedy sampling."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.models.generate import generate, sample_logits
from distributed_lion_tpu.models.gpt2 import (
    GPT2Config, gpt2_apply, gpt2_decode, gpt2_init, gpt2_init_cache,
)
from distributed_lion_tpu.models.llama import (
    LlamaConfig, llama_apply, llama_decode, llama_init, llama_init_cache,
)


def _tokens(vocab, b, t, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, vocab, (b, t)), jnp.int32
    )


def test_gpt2_decode_matches_apply():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    toks = _tokens(cfg.vocab_size, 2, 12)
    full = gpt2_apply(params, toks, cfg)

    cache = gpt2_init_cache(cfg, 2, 16)
    # prefill with the first 8, then decode one token at a time
    pre, cache = gpt2_decode(params, toks[:, :8], cfg, cache, 0)
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :8]),
                               rtol=2e-2, atol=2e-2)
    for i in range(8, 12):
        step, cache = gpt2_decode(params, toks[:, i:i + 1], cfg, cache, i)
        np.testing.assert_allclose(np.asarray(step[:, 0]), np.asarray(full[:, i]),
                                   rtol=2e-2, atol=2e-2)


def test_llama_decode_matches_apply():
    cfg = LlamaConfig.tiny()  # GQA: 4 heads, 2 kv heads
    params = llama_init(jax.random.key(1), cfg)
    toks = _tokens(cfg.vocab_size, 2, 10)
    full = llama_apply(params, toks, cfg)

    cache = llama_init_cache(cfg, 2, 12)
    pre, cache = llama_decode(params, toks[:, :6], cfg, cache, 0)
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :6]),
                               rtol=2e-2, atol=2e-2)
    for i in range(6, 10):
        step, cache = llama_decode(params, toks[:, i:i + 1], cfg, cache, i)
        np.testing.assert_allclose(np.asarray(step[:, 0]), np.asarray(full[:, i]),
                                   rtol=2e-2, atol=2e-2)


def test_generate_greedy_deterministic():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(2), cfg)
    prompt = _tokens(cfg.vocab_size, 2, 5, seed=3)
    decode = partial(_gpt2_decode_fn, cfg)
    init_cache = partial(gpt2_init_cache, cfg)

    out1 = generate(decode, init_cache, params, prompt, 8)
    out2 = generate(decode, init_cache, params, prompt, 8)
    assert out1.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert int(np.asarray(out1).max()) < cfg.vocab_size
    # first generated token == argmax of the full forward's last position
    full = gpt2_apply(params, prompt, cfg)
    np.testing.assert_array_equal(
        np.asarray(out1[:, 0]), np.asarray(jnp.argmax(full[:, -1], -1))
    )


def test_generate_eos_pads():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(2), cfg)
    prompt = _tokens(cfg.vocab_size, 2, 5, seed=3)
    decode = partial(_gpt2_decode_fn, cfg)
    init_cache = partial(gpt2_init_cache, cfg)
    greedy = np.asarray(generate(decode, init_cache, params, prompt, 8))
    # declare the first greedily-emitted token of row 0 to be EOS: everything
    # after it in that row must be pad (99)
    eos = int(greedy[0, 0])
    out = np.asarray(generate(decode, init_cache, params, prompt, 8,
                              eos_id=eos, pad_id=99))
    row = out[0]
    assert row[0] == eos and (row[1:] == 99).all()


def test_sample_logits_top_k_restricts_support():
    logits = jnp.asarray([[0.0, 5.0, 4.0, -1.0]])
    for seed in range(20):
        t = sample_logits(logits, jax.random.key(seed), temperature=1.0, top_k=2)
        assert int(t[0]) in (1, 2)
    assert int(sample_logits(logits, jax.random.key(0), temperature=0.0)[0]) == 1


def _gpt2_decode_fn(cfg, params, tokens, cache, pos):
    return gpt2_decode(params, tokens, cfg, cache, pos)


def test_generate_cli_smoke(capsys):
    from distributed_lion_tpu.cli.run_generate import main

    text = main(["--model_family", "gpt2", "--model_name", "tiny",
                 "--prompt", "ab", "--max_new_tokens", "4",
                 "--temperature", "0"])
    assert isinstance(text, str)
    assert "ab" in capsys.readouterr().out


def test_generate_cli_roundtrips_exported_model(tmp_path):
    """Train-export-generate cycle: a model saved with utils.serialization
    reloads byte-identically through the CLI path."""
    from distributed_lion_tpu.cli.run_generate import main
    from distributed_lion_tpu.utils.serialization import load_pytree, save_pytree

    cfg = GPT2Config.tiny(vocab_size=259)  # byte tokenizer id space
    params = gpt2_init(jax.random.key(7), cfg)
    path = tmp_path / "model.npz"
    save_pytree(path, params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(load_pytree(path))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    text = main(["--model_path", str(path), "--model_family", "gpt2",
                 "--model_name", "tiny", "--prompt", "hi",
                 "--max_new_tokens", "3", "--temperature", "0"])
    assert isinstance(text, str)


def test_top_p_nucleus_filtering():
    """top_p keeps exactly the smallest head-mass prefix: with probs
    (.5, .3, .15, .05) and top_p=.7 only tokens {0, 1} can be sampled;
    top_p→tiny degrades to greedy (the top token always survives)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.models.generate import sample_logits

    probs = jnp.array([[0.5, 0.3, 0.15, 0.05]])
    logits = jnp.log(probs)
    draws = [int(sample_logits(logits, jax.random.key(s), 1.0, None, 0.7)[0])
             for s in range(64)]
    assert set(draws) <= {0, 1}, set(draws)
    assert len(set(draws)) == 2  # both survivors actually get sampled
    tiny = [int(sample_logits(logits, jax.random.key(s), 1.0, None, 1e-6)[0])
            for s in range(8)]
    assert set(tiny) == {0}
    # top_p=1.0 keeps everything: all four ids reachable
    full = [int(sample_logits(logits, jax.random.key(s), 1.0, None, 1.0)[0])
            for s in range(200)]
    assert set(full) == {0, 1, 2, 3}, set(full)


def test_sample_logits_top_k_ge_vocab_keeps_everything():
    """top_k >= vocab filters nothing: the draw is bit-identical to the
    unfiltered draw under the same key (load-bearing once the serving
    engine samples per-tick with caller-provided top_k)."""
    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    for s in range(12):
        key = jax.random.key(s)
        plain = int(sample_logits(logits, key, 1.0)[0])
        assert int(sample_logits(logits, key, 1.0, top_k=4)[0]) == plain
        assert int(sample_logits(logits, key, 1.0, top_k=400)[0]) == plain


def test_sample_logits_top_p_one_keeps_everything():
    """top_p=1.0 keeps the full support (exclusive-cumulative mass before
    the last token is < 1.0): bit-identical to the unfiltered draw."""
    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    for s in range(12):
        key = jax.random.key(s)
        assert int(sample_logits(logits, key, 1.0, None, 1.0)[0]) == \
            int(sample_logits(logits, key, 1.0)[0])


def test_generate_pad_id_equals_eos_id():
    """pad_id == eos_id must not re-trigger/flicker the finished mask:
    after the first EOS the row is eos forever (the pad IS eos), and the
    mask never un-finishes."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(2), cfg)
    prompt = _tokens(cfg.vocab_size, 2, 5, seed=3)
    decode = partial(_gpt2_decode_fn, cfg)
    init_cache = partial(gpt2_init_cache, cfg)
    greedy = np.asarray(generate(decode, init_cache, params, prompt, 8))
    eos = int(greedy[0, 0])
    out = np.asarray(generate(decode, init_cache, params, prompt, 8,
                              eos_id=eos, pad_id=eos))
    assert (out[0] == eos).all(), out[0]


def test_generate_max_new_tokens_1():
    """max_new_tokens=1 is a zero-length scan: shape [B, 1] and the one
    token equals the prefill logits' argmax."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(2), cfg)
    prompt = _tokens(cfg.vocab_size, 2, 5, seed=3)
    decode = partial(_gpt2_decode_fn, cfg)
    init_cache = partial(gpt2_init_cache, cfg)
    out = np.asarray(generate(decode, init_cache, params, prompt, 1))
    assert out.shape == (2, 1)
    full = gpt2_apply(params, prompt, cfg)
    np.testing.assert_array_equal(out[:, 0],
                                  np.asarray(jnp.argmax(full[:, -1], -1)))


def test_batched_left_padded_generate_matches_solo():
    """ISSUE 9 satellite: variable-length prompts batch into one
    left-padded generate call (per-row position offsets + pad masking) and
    each row generates exactly what a solo run of its prompt does — for
    BOTH families (llama exercises per-row rotary gathers)."""
    from distributed_lion_tpu.models.llama import (
        llama_decode, llama_init, llama_init_cache,
    )

    cases = [
        ("gpt2", GPT2Config.tiny(), gpt2_init,
         lambda cfg: (lambda p, t, c, pos, off=None:
                      gpt2_decode(p, t, cfg, c, pos, off)),
         gpt2_init_cache),
        ("llama", LlamaConfig.tiny(), llama_init,
         lambda cfg: (lambda p, t, c, pos, off=None:
                      llama_decode(p, t, cfg, c, pos, off)),
         llama_init_cache),
    ]
    rng = np.random.default_rng(1)
    for fam, cfg, init, mk_dec, init_cache in cases:
        params = init(jax.random.key(2), cfg)
        dec = mk_dec(cfg)
        ic = partial(init_cache, cfg)
        prompts = [list(map(int, rng.integers(1, cfg.vocab_size, n)))
                   for n in (3, 7, 5)]
        T = max(len(p) for p in prompts)
        batch = np.zeros((len(prompts), T), np.int32)
        for i, p in enumerate(prompts):
            batch[i, T - len(p):] = p  # left-pad: real tokens right-aligned
        lens = jnp.asarray([len(p) for p in prompts], jnp.int32)
        out = np.asarray(generate(dec, ic, params, jnp.asarray(batch), 6,
                                  prompt_lens=lens))
        for i, p in enumerate(prompts):
            solo = np.asarray(generate(dec, ic, params,
                                       jnp.asarray([p], jnp.int32), 6))
            np.testing.assert_array_equal(out[i], solo[0], err_msg=f"{fam}:{i}")


def test_generate_cli_multi_prompt(tmp_path, capsys):
    """run_generate batches several --prompt values (and --prompt_file
    lines) through ONE left-padded generate call; per-prompt output lines
    match the single-prompt invocations."""
    from distributed_lion_tpu.cli.run_generate import main

    pf = tmp_path / "prompts.txt"
    pf.write_text("hello\n\nworld\n")
    texts = main(["--model_family", "gpt2", "--model_name", "tiny",
                  "--prompt", "ab", "cdef", "--prompt_file", str(pf),
                  "--max_new_tokens", "4", "--temperature", "0"])
    assert isinstance(texts, list) and len(texts) == 4
    capsys.readouterr()
    for prompt, text in zip(("ab", "cdef", "hello", "world"), texts):
        solo = main(["--model_family", "gpt2", "--model_name", "tiny",
                     "--prompt", prompt, "--max_new_tokens", "4",
                     "--temperature", "0"])
        assert solo == text, prompt
    # --prompt_file ALONE must serve exactly the file's prompts — no
    # default "Hello" sneaking into the batch
    only_file = main(["--model_family", "gpt2", "--model_name", "tiny",
                      "--prompt_file", str(pf), "--max_new_tokens", "4",
                      "--temperature", "0"])
    assert isinstance(only_file, list) and len(only_file) == 2
    assert only_file == texts[2:]


def test_top_p_degenerate_values_fall_back_to_greedy():
    import jax
    import jax.numpy as jnp

    from distributed_lion_tpu.models.generate import sample_logits

    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    for s in range(8):
        assert int(sample_logits(logits, jax.random.key(s), 1.0,
                                 None, 0.0)[0]) == 0
        assert int(sample_logits(logits, jax.random.key(s), 1.0,
                                 0, None)[0]) == 0
