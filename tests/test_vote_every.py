"""vote_every lazy sign refresh: the sub-bit wire (VERDICT r1 item 3).

BASELINE.md's comm budget: ≤ 1/32 of a bf16 gradient all-reduce = 0.5
bit/param. ``packed_a2a`` alone is ~2 bits/param/optimizer-step; with
``vote_every=4`` each step votes a quarter of the coordinates → ≤ 0.5
bit/param/step, replicas still bit-identical (the elected cache holds only
voted, shared results). These tests pin: the accounting, replica
consistency, the K=1 equivalence, cold-start masking, and convergence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lion_tpu.ops.codec import wire_bytes_per_param
from distributed_lion_tpu.optim import (
    distributed_lion,
    expand_worker_state,
    init_global_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.parallel.mesh import make_mesh


def _run_steps(opt, params, grads_per_worker, n_steps, mesh, world):
    """Drive opt.step under shard_map for n_steps; grads_per_worker is a
    [world, ...] stacked pytree reused every step."""
    state = init_global_state(opt, params, world)
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = type(state)(
        count=P(),
        exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None,
        elected=None if state.elected is None else P(),
    )
    g_spec = jax.tree.map(lambda _: P("data"), grads_per_worker)

    @jax.jit
    def step(params, grads, state):
        def body(p, g, st):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            p_new, st_new = opt.step(p, g, st)
            return p_new, expand_worker_state(st_new)

        return shard_map(
            body, mesh=mesh, in_specs=(p_spec, g_spec, st_spec),
            out_specs=(p_spec, st_spec), check_vma=False,
        )(params, grads, state)

    for _ in range(n_steps):
        params, state = step(params, grads_per_worker, state)
    return params, state


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8)


def _toy_problem(world=8, n=40):
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (n,)), "b": jnp.zeros((3,))}
    grads = {
        "w": jax.random.normal(jax.random.key(1), (world, n)),
        "b": jax.random.normal(jax.random.key(2), (world, 3)),
    }
    return params, grads


@pytest.mark.parametrize("wire", ["sign_psum", "packed_a2a"])
def test_vote_every_replicas_consistent(mesh8, wire):
    params, grads = _toy_problem()
    opt = distributed_lion(learning_rate=0.01, wire=wire, vote_every=4)
    p, st = _run_steps(opt, params, grads, n_steps=6, mesh=mesh8, world=8)
    # params stay replicated: every device holds identical values
    for leaf in jax.tree.leaves(p):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    assert st.elected is not None and st.elected.dtype == jnp.uint8


def test_vote_every_one_matches_plain(mesh8):
    """K=1 must be the plain voted optimizer bit-for-bit."""
    params, grads = _toy_problem()
    p1, _ = _run_steps(distributed_lion(learning_rate=0.01), params, grads, 5, mesh8, 8)
    p2, _ = _run_steps(distributed_lion(learning_rate=0.01, vote_every=1),
                       params, grads, 5, mesh8, 8)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), p1, p2)


def test_vote_every_cold_start_mask(mesh8):
    """During the first K-1 steps, not-yet-voted coordinates must not move
    (beyond weight decay, which is off here)."""
    params, grads = _toy_problem(n=40)
    opt = distributed_lion(learning_rate=0.01, vote_every=4)
    p, _ = _run_steps(opt, params, grads, n_steps=1, mesh=mesh8, world=8)
    n = 40 + 3
    from distributed_lion_tpu.ops.codec import vote_chunk_elems

    chunk = vote_chunk_elems(n, 4)
    # ballot order is jax.tree.leaves order (dict keys sorted: b before w)
    flat0 = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(params)])
    flat1 = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(p)])
    moved = flat0 != flat1
    # only slot-0 coordinates may move on step 0
    assert moved[:min(chunk, n)].any()
    assert not moved[chunk:].any()


def test_vote_every_accounting_meets_budget():
    acct = wire_bytes_per_param(124_000_000, 8, "packed_a2a", vote_every=4)
    assert acct["bits_per_param"] <= 0.5 + 1e-6
    assert acct["vs_bf16_allreduce"] <= 1 / 32 + 1e-9
    # and the amortized view under the canonical accum=8 config
    acct2 = wire_bytes_per_param(124_000_000, 8, "packed_a2a", accum_steps=8)
    assert acct2["bits_per_param_per_microbatch"] <= 0.5 + 1e-6
    assert acct2["vs_bf16_allreduce_equal_tokens"] <= 1 / 32 + 1e-9
    # sign_psum per-step is honestly ~8 bits/param — no overclaim
    acct3 = wire_bytes_per_param(124_000_000, 8, "sign_psum")
    assert 7.9 <= acct3["bits_per_param"] <= 8.1


def test_vote_every_trainer_converges(mesh8):
    """End-to-end: tiny GPT-2, vote_every=4 + packed_a2a (the ≤0.5 bit/param
    config), loss decreases and the comm report shows the budget met."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    cfg = TrainConfig(
        lion=True, async_grad=True, wire="packed_a2a", vote_every=4,
        learning_rate=3e-3, warmup_steps=2, max_steps=30,
        per_device_train_batch_size=2, gradient_accumulation_steps=1,
        block_size=32, logging_steps=5, output_dir=None,
    )
    model_cfg = GPT2Config.tiny()
    trainer = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    acct = trainer.comm_stats()
    assert acct["comm_bits_per_param"] <= 0.5 + 1e-6
    # memorizable corpus: few distinct blocks
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  model_cfg.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, losses
    trainer.close()


def test_vote_every_checkpoint_resume(tmp_path, mesh8):
    """The packed elected-sign cache survives checkpoint/resume: a 2+2-step
    resumed run equals a continuous 4-step run (same data stream)."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    model = GPT2Config.tiny(compute_dtype=jnp.float32)
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)

    def cfg(outdir, steps):
        return TrainConfig(
            lion=True, async_grad=True, wire="packed_a2a", vote_every=4,
            learning_rate=1e-3, warmup_steps=1, max_steps=steps,
            per_device_train_batch_size=1, gradient_accumulation_steps=1,
            block_size=32, logging_steps=1, save_steps=2,
            output_dir=outdir, seed=5,
        )

    t0 = Trainer.for_gpt2(cfg(None, 4), mesh8, model, seed=3)
    h0 = t0.train(batch_iterator(blocks, t0.global_train_batch(), seed=5))
    ref = [h["loss"] for h in h0 if "loss" in h]
    params_ref = jax.tree.map(np.asarray, jax.device_get(t0.params))
    t0.close()

    out = str(tmp_path / "run")
    t1 = Trainer.for_gpt2(cfg(out, 2), mesh8, model, seed=3)
    t1.train(batch_iterator(blocks, t1.global_train_batch(), seed=5))
    t1.save()
    t1.close()

    t2 = Trainer.for_gpt2(cfg(out, 4), mesh8, model, seed=3)
    assert t2.step_count == 2
    assert t2.state.elected is not None  # cache restored, not re-zeroed
    h2 = t2.train(batch_iterator(blocks, t2.global_train_batch(), seed=5))
    resumed = [h["loss"] for h in h2 if "loss" in h]
    params_res = jax.tree.map(np.asarray, jax.device_get(t2.params))
    t2.close()

    np.testing.assert_allclose(resumed, ref[2:], rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(params_ref), jax.tree.leaves(params_res)):
        np.testing.assert_array_equal(a, b)
