"""graft-check tier 2 (analysis/trace_check.py) + the runtime retrace
guard (train/loop --retrace_guard).

The contract pinned here is the static counterpart of PR 2's
``comm_drift_bytes == 0``: the collective-primitive inventory of the
ACTUAL compiled train step — call sites, axis names, operand element
counts — exactly matches the wire recipe's expected set for all 4 wires ×
vote_buckets {1, 4} (and a lazy vote_every=4 cell), the step carries zero
host callbacks, donation survives lowering, and bf16 param leaves are
never upcast to f32. Plus: the retrace guard catches an injected
recompile, and elections stay bit-identical with the analysis features
enabled."""

import numpy as np
import pytest
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.analysis import trace_check
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.parallel import collectives
from distributed_lion_tpu.parallel.mesh import DATA_AXIS, make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer

MODEL = GPT2Config.tiny(vocab_size=512, n_layer=2, n_head=4, d_model=128,
                        n_ctx=64)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8, devices=jax.devices()[:8])


def _trainer(mesh, **kw):
    cfg = TrainConfig(
        lion=True, async_grad=True, wire=kw.pop("wire", "sign_psum"),
        vote_every=kw.pop("vote_every", 1),
        vote_buckets=kw.pop("vote_buckets", 1),
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        block_size=32, logging_steps=1, warmup_steps=1, max_steps=100,
        learning_rate=1e-3, output_dir=None, **kw)
    return Trainer.for_gpt2(cfg, mesh, MODEL)


def _batch(tr, block=32, fill=0):
    return np.full((tr.global_train_batch(), block), fill, np.int32)


# ------------------------------------------------- the wire-recipe contract
@pytest.mark.parametrize("wire", ["sign_psum", "packed_allgather",
                                  "packed_a2a", "hier:4"])
@pytest.mark.parametrize("vote_buckets", [1, 4])
def test_collective_inventory_matches_wire_recipe(mesh8, wire, vote_buckets):
    """All 4 wires x vote_buckets {1,4}: the compiled step's large-operand
    collective inventory IS the wire recipe's expected set — no extra
    collective, no missing bucket, no axis surprise — and the step holds
    zero host callbacks, donation survives lowering, and no bf16 param
    leaf is upcast."""
    tr = _trainer(mesh8, wire=wire, vote_buckets=vote_buckets)
    rep = trace_check.check_trainer(tr, _batch(tr))
    tr.close()
    assert rep["inventory_ok"], (rep["expected"], rep["observed"])
    assert rep["host_callbacks"] == []
    assert rep["donation_ok"], rep["donation"]
    assert rep["upcast_ok"], rep["param_upcasts"]
    assert rep["ok"]
    # per-bucket structure: one call-site group per bucket
    per_bucket = {"sign_psum": 1, "packed_allgather": 1,
                  "packed_a2a": 2, "hier:4": 3}[wire]
    assert len(rep["observed"]) == per_bucket * vote_buckets


@pytest.mark.parametrize("depth", [0, 1])
def test_hier_dcn_depth_inventory_invariant(mesh8, depth):
    """ISSUE 8: the hier wire's collective inventory is DEPTH-invariant —
    at any --dcn_pipeline_depth every step runs exactly one launch (legs
    1+2) and one consume (leg 3), so the expected set equals the
    synchronous wire's: no duplicate DCN collective, ICI legs unchanged,
    and zero host callbacks (the dcn_delay emulator is only traced when
    the fault is armed)."""
    tr = _trainer(mesh8, wire="hier:4", vote_buckets=2,
                  dcn_pipeline_depth=depth)
    rep = trace_check.check_trainer(tr, _batch(tr))
    tr.close()
    assert rep["ok"], (rep["expected"], rep["observed"],
                       rep["host_callbacks"])
    assert rep["expected"] == [list(c) for c in trace_check.expected_wire_calls(
        tr.n_params, 8, "hier:4", vote_buckets=2, dcn_pipeline_depth=0)]
    assert len(rep["observed"]) == 3 * 2  # 3 ppermute sites x 2 buckets


def test_hier_duplicate_dcn_collective_detected(mesh8):
    """The failure mode the depth cells exist to catch: a broken pipeline
    that consumes BOTH a fresh and a stale election per step (e.g. a
    cold-start implemented as a traced second election instead of the
    valid-mask) doubles leg-3 ring call sites — the contract must FAIL it,
    not average it away."""
    from functools import partial as _partial

    from distributed_lion_tpu.ops.codec import hier_chunk_slot_bytes

    # n large enough that the DCN/elected legs' chunk/8 operands clear
    # SCALAR_MAX (tiny ballots would file them as scalar reductions)
    n, g = 8192, 4

    @_partial(jax.shard_map, mesh=mesh8, in_specs=(P("data"), P()),
              out_specs=P(), check_vma=False)
    def broken(b, ring):
        slot = collectives.hier_launch(b[0], DATA_AXIS, 8, g)
        fresh = collectives.hier_consume(slot, n, DATA_AXIS, 8, g)
        stale = collectives.hier_consume(ring[0], n, DATA_AXIS, 8, g)
        return fresh & stale

    ring = jnp.zeros((8, hier_chunk_slot_bytes(n, 8, g)), jnp.uint8)
    ballots = jnp.zeros((8, n), jnp.bool_)
    calls, callbacks = trace_check.collective_calls(broken, ballots, ring)
    observed = sorted(c.key for c in calls
                      if c.nelems > trace_check.SCALAR_MAX)
    expected = trace_check.expected_wire_calls(n, 8, f"hier:{g}",
                                               dcn_pipeline_depth=1)
    assert not callbacks
    assert observed != expected  # the duplicate consume must surface
    assert len(observed) == len(expected) + 1


def test_lazy_vote_inventory(mesh8):
    """vote_every=4: the wire recipe's expected set follows the rotating
    1/K slice (codec.vote_chunk_elems), not the full ballot."""
    tr = _trainer(mesh8, wire="packed_a2a", vote_every=4, vote_buckets=4)
    rep = trace_check.check_trainer(tr, _batch(tr))
    tr.close()
    assert rep["ok"], (rep["expected"], rep["observed"],
                       rep["host_callbacks"], rep["param_upcasts"])


def test_contract_fails_on_wrong_recipe(mesh8):
    """The check can actually FAIL: judging a sign_psum step against the
    packed_allgather recipe must not pass (guards against a vacuous
    matcher)."""
    tr = _trainer(mesh8, wire="sign_psum", vote_buckets=1)
    args = (tr.params, tr.state, tr.vote_health, tr._frozen_arg(),
            _batch(tr), jax.random.key(0))
    rep = trace_check.check_step(
        tr._train_step_core, args, n_params=tr.n_params, world=tr.world,
        wire="packed_allgather", vote_every=1, vote_buckets=1)
    tr.close()
    assert not rep["inventory_ok"]


def test_host_callback_detected(mesh8):
    """A debug/callback primitive smuggled into a shard_map'd step is
    reported (and fails the contract)."""

    @partial(jax.shard_map, mesh=mesh8, in_specs=P(), out_specs=P(),
             check_vma=False)
    def f(x):
        jax.debug.print("sneaky {}", x.sum())
        return collectives.vote_total(x > 0, DATA_AXIS, "sign_psum")

    calls, callbacks = trace_check.collective_calls(
        f, jnp.zeros((1024,), jnp.float32))
    assert callbacks, "debug print must surface as a host callback"
    assert any(c.prim == "psum" for c in calls)


def test_param_upcast_detected():
    """A step that wholesale-upcasts bf16 params to f32 is flagged; the
    same math kept in bf16 is not."""
    params = {"w": jnp.zeros((256,), jnp.bfloat16)}

    def bad(params, x):
        return (params["w"].astype(jnp.float32) * x).sum()

    def good(params, x):
        return (params["w"] * x.astype(jnp.bfloat16)).sum()

    assert trace_check.param_upcasts(
        bad, (params, jnp.ones((256,), jnp.float32))) == [(256,)]
    assert trace_check.param_upcasts(
        good, (params, jnp.ones((256,), jnp.float32))) == []


# ------------------------------------------------------- the retrace guard
def _iter_of(tr, block, n=8, fill=1):
    def gen():
        while True:
            yield _batch(tr, block, fill)
    return gen()


def test_retrace_guard_catches_injected_recompile_error(mesh8):
    tr = _trainer(mesh8, retrace_guard="error")
    tr.train(_iter_of(tr, 32), max_steps=2)
    with pytest.raises(RuntimeError, match="RETRACE"):
        # a narrower batch = a new abstract signature = a recompile; the
        # guard refuses BEFORE jax pays for the second specialization
        tr.train(_iter_of(tr, 16), max_steps=1)
    with pytest.raises(RuntimeError, match="RETRACE"):
        # the refused signature was NOT adopted: a caller that catches and
        # re-dispatches the same shapes is refused again, not silently
        # recompiled on the retry
        tr.train(_iter_of(tr, 16), max_steps=1)
    tr.close()


def test_retrace_guard_warn_counts_and_logs_metric(mesh8, capsys):
    tr = _trainer(mesh8, retrace_guard="warn")
    tr.train(_iter_of(tr, 32), max_steps=1)
    assert tr.retrace_count == 0
    hist = tr.train(_iter_of(tr, 16), max_steps=1)
    assert tr.retrace_count == 1
    assert "RETRACE" in capsys.readouterr().out
    assert any(h.get("retraces") == 1 for h in hist)
    # same shapes again: no further retrace
    tr.train(_iter_of(tr, 16), max_steps=1)
    assert tr.retrace_count == 1
    # alternating BACK to an already-compiled signature costs jax nothing
    # (both specializations are cached) and must not re-warn forever
    tr.train(_iter_of(tr, 32), max_steps=1)
    assert tr.retrace_count == 1
    tr.close()


def test_retrace_guard_rejects_bad_mode(mesh8):
    with pytest.raises(ValueError, match="retrace_guard"):
        _trainer(mesh8, retrace_guard="loud")


def test_elections_bit_identical_with_analysis_features(mesh8):
    """--retrace_guard (the analysis subsystem's only runtime hook) is
    purely observational: losses and params are bit-identical to a guard-
    off run over the same batches."""
    runs = {}
    for mode in ("off", "error"):
        tr = _trainer(mesh8, wire="packed_a2a", vote_buckets=4,
                      retrace_guard=mode)
        hist = tr.train(_iter_of(tr, 32), max_steps=3)
        runs[mode] = (hist, jax.device_get(tr.params))
        tr.close()
    losses = {m: [h["loss"] for h in runs[m][0] if "loss" in h]
              for m in runs}
    assert losses["off"] == losses["error"]
    for a, b in zip(jax.tree.leaves(runs["off"][1]),
                    jax.tree.leaves(runs["error"][1])):
        assert np.array_equal(a, b)
