"""Vote-health telemetry (ISSUE 2): the observability contract.

What these pin:

- telemetry is OBSERVATIONAL — elections/params/momentum are bit-identical
  to telemetry-off on the XLA path, for every wire cadence in the PR-1
  matrix (vote_buckets {1,4} x vote_every {1,4} x det/stoch), and the
  VoteHealth accumulator itself is bit-identical across vote_buckets
  (bucketing changes when bytes move, never what telemetry sees);
- the Pallas stats kernel (ops/pallas_lion.bucket_vote_stats) bins margins
  exactly like the jnp reference and produces bitwise-equal accumulators;
- measured wire counters (parallel/collectives.WIRE_TALLY, captured from
  the live operand shapes at trace time) equal ops/codec's analytic
  bytes-received accounting EXACTLY — drift == 0 in-process — for every
  wire x vote_every x vote_buckets, including hier's DCN leg;
- the anomaly layer: an injected NaN trips the sentinel, writes a crash
  bundle naming the poisoned leaf, and (with --trace_on_anomaly) captures
  a trace window before raising;
- MetricsLogger emits STRICT JSON for non-finite floats (null + "<k>_repr")
  and scripts/validate_metrics.py is the CI check for that contract.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.ops.codec import wire_bytes_per_param
from distributed_lion_tpu.optim import (
    distributed_lion,
    expand_worker_state,
    init_global_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.lion import LionState
from distributed_lion_tpu.parallel import collectives
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N = 43  # ragged on purpose: with vote_every=4 the last rotation slot is
# pure alignment padding (zero real coordinates) — the voted_steps
# normalization must keep hist mass at exactly 1.0 through it


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8)


def _toy():
    params = {"w": jax.random.normal(jax.random.key(0), (40,)),
              "b": jnp.zeros((3,))}
    grads = {"w": jax.random.normal(jax.random.key(1), (8, 40)),
             "b": jax.random.normal(jax.random.key(2), (8, 3))}
    return params, grads


def _run(mesh, telemetry_on, wire="sign_psum", buckets=1, ve=1, stoch=False,
         kern="xla", steps=5):
    """Drive opt.step under shard_map with the trainer's fold wiring."""
    params, grads = _toy()
    opt = distributed_lion(
        0.01, weight_decay=0.01, wire=wire, vote_buckets=buckets,
        vote_every=ve, max_grad_norm=1.0 if stoch else None, kernel=kern,
        telemetry=telemetry_on)
    rng = jax.random.key(7) if stoch else None
    state = init_global_state(opt, params, 8, rng=rng)
    vh = telemetry.init_vote_health(N, ve) if telemetry_on else {}
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(
        count=P(), exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None if rng is None else P(), elected=P() if ve > 1 else None)
    g_spec = jax.tree.map(lambda _: P("data"), grads)
    vh_spec = jax.tree.map(lambda _: P(), vh)

    @jax.jit
    def step(params, grads, state, vh):
        def body(p, g, st, v):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            if telemetry_on:
                p2, st2, frame = opt.step(p, g, st)
                v = telemetry.fold(v, frame, "data", 8, N)
            else:
                p2, st2 = opt.step(p, g, st)
            return p2, expand_worker_state(st2), v

        return shard_map(
            body, mesh=mesh, in_specs=(p_spec, g_spec, st_spec, vh_spec),
            out_specs=(p_spec, st_spec, vh_spec), check_vma=False,
        )(params, grads, state, vh)

    p, st, v = params, state, vh
    for _ in range(steps):
        p, st, v = step(p, grads, st, v)
    return p, st, v


def _eq(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


# ----------------------------------------------------- observational contract
@pytest.mark.parametrize("stoch", [False, True],
                         ids=["deterministic", "stochastic"])
@pytest.mark.parametrize("ve", [1, 4])
def test_vote_health_bucket_invariant_and_elections_unperturbed(
        mesh8, ve, stoch):
    """The satellite matrix: across vote_buckets {1,4} the accumulator is
    BIT-identical (same elections, same tallies, just pipelined wires), and
    params/momentum with telemetry on equal the telemetry-off run exactly —
    telemetry must not perturb the PR-1-pinned elections."""
    p_off, st_off, _ = _run(mesh8, False, ve=ve, stoch=stoch)
    runs = {b: _run(mesh8, True, ve=ve, stoch=stoch, buckets=b)
            for b in (1, 4)}
    _eq(runs[1][2], runs[4][2])                    # vh bitwise across B
    _eq(p_off, runs[1][0])                         # params untouched
    _eq(st_off.exp_avg, runs[1][1].exp_avg)        # momentum untouched
    d = telemetry.drain(runs[1][2], margin_exact=True)
    # sign_psum moves the exact tally: every voted coordinate lands in a
    # margin bin, so mass == 1 even through the zero-coordinate lazy slot
    assert abs(d["hist_mass"] - 1.0) < 1e-4
    assert d["voted_per_step"] > 0
    assert 0.0 <= d["disagree_frac"] <= 1.0
    if stoch:
        assert 0.0 < d["stoch_flip_frac"] < 1.0
    else:
        assert d["stoch_flip_frac"] == 0.0
    if ve > 1:
        assert d["valid_frac"] < 1.0  # cold-start sparsity is visible
    else:
        assert d["valid_frac"] == 1.0


def test_lazy_cold_start_counts_no_flips(mesh8):
    """Under vote_every=K, slots 1..K-1 first vote against the cache's
    zero-init bytes; counting those as flips would fake a ~0.5 flip rate
    for a perfectly stable election. The frame's flip_valid gate must keep
    the first full rotation out of the flip statistics entirely."""
    _, _, vh4 = _run(mesh8, True, ve=4, steps=4)  # counts 0..3: all cold
    d = telemetry.drain(vh4, margin_exact=True)
    assert d["flip_rate"] == 0.0
    assert int(np.asarray(vh4.flip_steps)) == 0
    _, _, vh6 = _run(mesh8, True, ve=4, steps=6)  # counts 4, 5 are warm
    assert int(np.asarray(vh6.flip_steps)) == 2


def test_proxy_wire_hist_zeroed_not_faked(mesh8):
    """packed_a2a ships a ±1 verdict proxy — magnitude never crosses the
    wire, so the margin histogram must be zeroed (margin_exact=0), not
    populated with fake unanimous margins; disagreement (which needs only
    the election) still reports."""
    p_off, _, _ = _run(mesh8, False, wire="packed_a2a")
    p_on, _, vh = _run(mesh8, True, wire="packed_a2a")
    _eq(p_off, p_on)
    d = telemetry.drain(vh, margin_exact=False)
    assert d["hist_mass"] == 0.0 and d["margin_exact"] == 0
    assert 0.0 < d["disagree_frac"] < 1.0


def test_pallas_telemetry_matches_xla_and_bucket_invariant(mesh8):
    """The Pallas window path: one step from identical state produces a
    BITWISE-equal accumulator to the XLA path (same ballots, same totals,
    same binning), and the accumulator stays bucket-invariant over multiple
    steps. Params are compared to telemetry-off within a few f32 ulps only:
    in interpret mode the fused-apply kernel inlines into the surrounding
    XLA graph, and telemetry's extra consumers of ballots/totals can shift
    fma fusion by 1-2 ulps (elections — the integer totals — are exact; on
    hardware the kernel is opaque and the wobble disappears)."""
    _, _, v_x = _run(mesh8, True, kern="xla", buckets=1, steps=1)
    _, _, v_p = _run(mesh8, True, kern="pallas", buckets=3, steps=1)
    _eq(v_x, v_p)
    r1 = _run(mesh8, True, kern="pallas", buckets=1)
    r3 = _run(mesh8, True, kern="pallas", buckets=3)
    _eq(r1[2], r3[2])
    p_off, _, _ = _run(mesh8, False, kern="pallas", buckets=3)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), p_off, r3[0])


def test_bucket_vote_stats_kernel_matches_reference():
    """The Pallas stats kernel must bin margins exactly like
    telemetry.margin_hist and count disagreements exactly — at ragged sizes
    spanning multiple grid blocks."""
    from distributed_lion_tpu.ops.pallas_lion import bucket_vote_stats

    rng = np.random.default_rng(3)
    for n in (5, 128, 1003, 70_000):
        ballots = jnp.asarray(
            rng.choice([-1, 1], size=(n,)).astype(np.int8))
        totals = jnp.asarray(rng.integers(-8, 9, size=(n,)).astype(np.int32))
        hist, dis = bucket_vote_stats(ballots, totals, 8, telemetry.NBINS,
                                      interpret=True)
        ref_hist = telemetry.margin_hist(totals, 8)
        np.testing.assert_array_equal(np.asarray(hist), np.asarray(ref_hist))
        ref_dis = int(np.sum((np.asarray(ballots) > 0)
                             != (np.asarray(totals) > 0)))
        assert int(dis) == ref_dis
        assert int(np.asarray(hist).sum()) == n


# ------------------------------------------------------- measured wire ledger
@pytest.mark.parametrize("wire", ["sign_psum", "packed_allgather",
                                  "packed_a2a", "hier:4"])
@pytest.mark.parametrize("ve,buckets", [(1, 1), (1, 4), (4, 1), (4, 3)])
def test_measured_wire_equals_analytic_exactly(mesh8, wire, ve, buckets):
    """The drift==0 satellite: the trace-time wire ledger (live operand
    shapes at the collective call sites) equals ops/codec's analytic
    bytes-received accounting EXACTLY — per optimizer step, through lazy
    slicing and bucket splits, including hier's DCN leg. Abstract eval
    only: no compile, no execution."""
    params, grads = _toy()
    opt = distributed_lion(0.01, wire=wire, vote_every=ve,
                           vote_buckets=buckets)
    state = init_global_state(opt, params, 8)
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(
        count=P(), exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None, elected=P() if ve > 1 else None)
    g_spec = jax.tree.map(lambda _: P("data"), grads)

    def step(params, grads, state):
        def body(p, g, st):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            p2, st2 = opt.step(p, g, st)
            return p2, expand_worker_state(st2)

        return shard_map(body, mesh=mesh8, in_specs=(p_spec, g_spec, st_spec),
                         out_specs=(p_spec, st_spec), check_vma=False,
                         )(params, grads, state)

    measured = telemetry.measure_step_wire(step, params, grads, state)
    acct = wire_bytes_per_param(N, 8, wire, vote_every=ve,
                                vote_buckets=buckets)
    assert measured["bytes_per_step"] == acct["bytes_per_step"], (
        measured, acct)
    assert measured["dcn_bytes_per_step"] == acct.get(
        "dcn_bytes_per_step", 0)
    assert measured["calls_per_step"] >= 1


def test_wire_tally_inert_outside_capture(mesh8):
    """Recording outside a capture is a no-op sink — running a vote must
    not leak entries or fail."""
    ballots = jnp.ones((64,), jnp.bool_)

    def f(b):
        return collectives.majority_vote_bucketed(b[0], "data",
                                                  "sign_psum", 2)

    out = shard_map(f, mesh=mesh8, in_specs=(P("data"),), out_specs=P(),
                    check_vma=False)(jnp.tile(ballots, (8, 1)))
    assert np.asarray(out).all()
    with collectives.WIRE_TALLY.capture() as entries:
        jax.eval_shape(
            lambda b: shard_map(f, mesh=mesh8, in_specs=(P("data"),),
                                out_specs=P(), check_vma=False)(b),
            jnp.tile(ballots, (8, 1)))
    assert len(entries) == 2  # one record per bucket collective


# --------------------------------------------------------- trainer end-to-end
def _tiny_trainer_cfg(**kw):
    from distributed_lion_tpu.train.loop import TrainConfig

    base = dict(lion=True, async_grad=True, wire="sign_psum", vote_every=1,
                vote_buckets=2, learning_rate=1e-3, warmup_steps=1,
                max_steps=4, per_device_train_batch_size=1,
                gradient_accumulation_steps=1, block_size=32,
                logging_steps=2, output_dir=None,
                resume_from_checkpoint=False)
    base.update(kw)
    return TrainConfig(**base)


def test_trainer_telemetry_end_to_end(mesh8, tmp_path):
    """The acceptance criterion, at the trainer: telemetry-on logs the
    vote-health block and the measured-wire cross-check (drift == 0), the
    loss trajectory is IDENTICAL to telemetry-off (elections unperturbed
    end-to-end), and the JSONL it writes is strict-valid."""
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config

    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    losses = {}
    for tel in (True, False):
        cfg = _tiny_trainer_cfg(
            telemetry=tel, output_dir=str(tmp_path / f"t{tel}"))
        tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
        blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                      model_cfg.vocab_size, seed=4)
        hist = tr.train(batch_iterator(blocks, tr.global_train_batch(),
                                       seed=0), max_steps=4)
        losses[tel] = [h["loss"] for h in hist if "loss" in h]
        if tel:
            rows = [h for h in hist if "vote/hist_mass" in h]
            assert rows, "telemetry produced no vote-health rows"
            r = rows[-1]
            assert abs(r["vote/hist_mass"] - 1.0) < 1e-4
            assert r["vote/margin_exact"] == 1
            assert len(r["vote/margin_hist"]) == telemetry.NBINS
            assert r["comm_drift_bytes"] == 0
            assert (r["comm_measured_bytes_per_step"]
                    == r["comm_bytes_per_step"])
            # one collective per bucket on this cfg (vote_buckets=2)
            assert r["comm_measured_calls_per_step"] == 2
            assert tr.telemetry_summary() is not None
            jsonl = tmp_path / "tTrue" / "metrics.jsonl"
            rc = subprocess.run(
                [sys.executable, os.path.join(REPO, "scripts",
                                              "validate_metrics.py"),
                 str(jsonl)], capture_output=True, text=True)
            assert rc.returncode == 0, rc.stdout + rc.stderr
        else:
            assert tr.telemetry_summary() is None
        tr.close()
    assert losses[True] == losses[False]


def test_trainer_telemetry_guards(mesh8):
    from distributed_lion_tpu.train.loop import make_optimizer

    with pytest.raises(ValueError, match="telemetry"):
        make_optimizer(_tiny_trainer_cfg(lion=False, async_grad=False,
                                         telemetry=True))
    with pytest.raises(ValueError, match="vote axis|election"):
        distributed_lion(axis_name=None, telemetry=True)


def test_nan_sentinel_writes_crash_bundle_naming_leaf(mesh8, tmp_path):
    """The injected-NaN acceptance test: poisoning one param leaf trips the
    sentinel, raises FloatingPointError, and the crash bundle names exactly
    the poisoned leaf with strict-JSON contents."""
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    cfg = _tiny_trainer_cfg(vote_buckets=1, max_steps=3, logging_steps=1,
                            nan_sentinel=True, output_dir=str(tmp_path),
                            save_steps=10**6)
    tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    tr.params["wte"] = tr.params["wte"].at[0, 0].set(float("nan"))
    blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                  model_cfg.vocab_size, seed=4)
    with pytest.raises(FloatingPointError, match="non-finite"):
        tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                 max_steps=3)
    crash_root = tmp_path / "crash"
    bundles = sorted(crash_root.iterdir())
    assert len(bundles) == 1
    with open(bundles[0] / "bundle.json") as f:
        bundle = json.load(f)  # strict JSON or this raises
    assert any("wte" in k for k in bundle["nonfinite_params"]), bundle
    assert bundle["reason"].startswith("non-finite")
    assert bundle["config"]["nan_sentinel"] is True
    assert bundle["metrics_window"], "recent metrics window missing"
    tr.close()


def test_trace_on_anomaly_captures_window_then_raises(mesh8, tmp_path):
    """--trace_on_anomaly: the sentinel arms a profiler window at the trip
    instead of raising immediately; the trace lands inside the crash bundle
    and the run still ends in FloatingPointError."""
    import glob

    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    cfg = _tiny_trainer_cfg(vote_buckets=1, max_steps=8, logging_steps=1,
                            nan_sentinel=True, trace_on_anomaly=True,
                            output_dir=str(tmp_path), save_steps=10**6)
    tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    tr.params["wte"] = tr.params["wte"].at[0, 0].set(float("nan"))
    blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                  model_cfg.vocab_size, seed=4)
    with pytest.raises(FloatingPointError):
        tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                 max_steps=8)
    traces = glob.glob(str(tmp_path / "crash" / "*" / "trace" / "**" / "*"),
                       recursive=True)
    assert any(os.path.isfile(f) for f in traces), "no anomaly trace files"
    tr.close()


def test_trace_on_anomaly_mid_profile_window(mesh8, tmp_path):
    """A --profile_dir window can be mid-capture when the sentinel trips:
    the anomaly handler must flush the open jax profiler session before
    arming its own window, or start_trace raises RuntimeError and neither
    trace survives."""
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    cfg = _tiny_trainer_cfg(vote_buckets=1, max_steps=10, logging_steps=1,
                            nan_sentinel=True, trace_on_anomaly=True,
                            output_dir=str(tmp_path / "out"),
                            profile_dir=str(tmp_path / "prof"),
                            profile_start_step=0, profile_num_steps=50,
                            save_steps=10**6)
    tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    tr.params["wte"] = tr.params["wte"].at[0, 0].set(float("nan"))
    blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                  model_cfg.vocab_size, seed=4)
    with pytest.raises(FloatingPointError):  # NOT RuntimeError
        tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                 max_steps=10)
    tr.close()


def test_host_step_skew_single_process():
    assert telemetry.host_step_skew(123) is None


# ----------------------------------------------------- strict-JSON satellites
def test_metrics_logger_nonfinite_is_strict_json(tmp_path):
    from distributed_lion_tpu.train.metrics import MetricsLogger

    logger = MetricsLogger(str(tmp_path))
    logger.log(1, {"loss": float("nan"), "aux": float("inf"),
                   "hist": [1.0, float("-inf")], "ok": 2.0})
    logger.close()
    lines = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
    rec = json.loads(lines[-1], parse_constant=lambda s: pytest.fail(
        f"bare {s} token in output"))
    assert rec["train/loss"] is None and rec["train/loss_repr"] == "nan"
    assert rec["train/aux"] is None and rec["train/aux_repr"] == "inf"
    assert rec["train/hist"] == [1.0, None]
    assert rec["train/ok"] == 2.0


def test_validate_metrics_rejects_bare_nan(tmp_path):
    good = tmp_path / "good.jsonl"
    good.write_text('{"step": 1, "loss": null, "loss_repr": "nan"}\n')
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"step": 1, "loss": NaN}\n{"step": 2, "loss": 1.0}\n')
    script = os.path.join(REPO, "scripts", "validate_metrics.py")
    ok = subprocess.run([sys.executable, script, str(good)],
                       capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    fail = subprocess.run([sys.executable, script, str(bad)],
                          capture_output=True, text=True)
    assert fail.returncode == 1
    assert "NaN" in fail.stdout or "constant" in fail.stdout
