"""Profiling/tracing subsystem: trace capture window, step timer, comm report.

The reference has no profiling (SURVEY §5); these cover the framework-native
subsystem: jax.profiler trace files actually land on disk for the configured
step window, StepTimer percentiles behave, and the analytic wire accounting
matches ops/codec (BASELINE.md's ≤1/32-of-bf16 budget is judged on it).
"""

import glob
import os

import numpy as np
import pytest

from distributed_lion_tpu.train.profiling import StepProfiler, StepTimer, comm_report


def test_step_timer_stats():
    t = StepTimer(window=8)
    assert t.tick() is None  # first call only arms the clock
    for _ in range(10):
        assert t.tick() >= 0.0
    s = t.stats()
    assert set(s) == {"step_time_ema_s", "step_time_p50_s", "step_time_p95_s"}
    assert s["step_time_p95_s"] >= s["step_time_p50_s"] >= 0.0
    assert len(t._samples) == 8  # sliding window bounded


def test_step_timer_math_regression(monkeypatch):
    """The deque(maxlen) satellite must not change the numbers: feed a
    deterministic clock and pin EMA + window eviction + percentiles against
    hand-computed values (list.pop(0) -> deque changed complexity, not
    math)."""
    import distributed_lion_tpu.train.profiling as prof

    now = [0.0]
    monkeypatch.setattr(prof.time, "perf_counter", lambda: now[0])
    t = StepTimer(ema_alpha=0.5, window=4)
    assert t.tick() is None
    # dts: 1, 2, 3, 4, 5, 6 with window 4 -> keeps [3, 4, 5, 6]
    expected_ema = None
    for dt in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
        now[0] += dt
        got = t.tick()
        assert got == pytest.approx(dt)
        expected_ema = dt if expected_ema is None else (
            0.5 * dt + 0.5 * expected_ema)
    assert list(t._samples) == [3.0, 4.0, 5.0, 6.0]
    s = t.stats()
    assert s["step_time_ema_s"] == pytest.approx(expected_ema)
    assert s["step_time_p50_s"] == pytest.approx(
        float(np.percentile([3.0, 4.0, 5.0, 6.0], 50)))
    assert s["step_time_p95_s"] == pytest.approx(
        float(np.percentile([3.0, 4.0, 5.0, 6.0], 95)))
    # multi-step dispatch divides the interval by n_steps
    now[0] += 8.0
    assert t.tick(n_steps=4) == pytest.approx(2.0)


def test_peak_hbm_is_max_over_all_local_devices(monkeypatch):
    """peak_hbm_gb must report the WORST local device (an OOM is decided by
    the max, not device 0), and the per-device view must expose every
    device for the telemetry report."""
    import jax

    from distributed_lion_tpu.train.profiling import (
        peak_hbm_gb,
        peak_hbm_per_device,
    )

    class _Dev:
        def __init__(self, peak):
            self._peak = peak

        def memory_stats(self):
            return {"peak_bytes_in_use": self._peak}

    devs = [_Dev(1 * 2**30), _Dev(3 * 2**30), _Dev(2 * 2**30)]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    assert peak_hbm_per_device() == [1.0, 3.0, 2.0]
    assert peak_hbm_gb() == 3.0  # device 1, not device 0

    class _NoStats:
        def memory_stats(self):
            return None

    monkeypatch.setattr(jax, "local_devices", lambda: [devs[0], _NoStats()])
    assert peak_hbm_per_device() is None  # partial stats -> honest None
    assert peak_hbm_gb() is None


def test_profiler_inactive_without_dir():
    p = StepProfiler(None)
    p.maybe_start(10)
    assert not p._active
    with p.annotate(10):
        pass
    p.maybe_stop(13)
    p.close()


def test_profiler_writes_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    trace_dir = str(tmp_path / "trace")
    p = StepProfiler(trace_dir, start_step=2, num_steps=2)
    x = jnp.ones((8, 8))
    for step in range(6):
        p.maybe_start(step)
        with p.annotate(step):
            x = (x @ x.T) / 65.0
        p.maybe_stop(step + 1, sync=x)
    assert not p._active  # stopped itself at the window end
    produced = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in produced), "no trace files written"
    p.close()


def test_profiler_starts_on_resumed_run(tmp_path):
    # a checkpoint-resumed run enters past start_step; the window must still
    # fire (anchored at the first step seen) and capture exactly num_steps
    import jax.numpy as jnp

    p = StepProfiler(str(tmp_path / "t"), start_step=10, num_steps=2)
    x = jnp.ones((4, 4))
    p.maybe_start(500)
    assert p._active and p.stop_step == 502
    for step in (500, 501):
        with p.annotate(step):
            x = x @ x
    p.maybe_stop(502, sync=x)
    assert not p._active and p._done
    p.maybe_start(503)  # one-shot: never restarts
    assert not p._active


def test_comm_report_sign_psum_vs_reference():
    n, w = 124_000_000, 8
    r = comm_report(n, w, "sign_psum", steps_per_sec=2.0)
    # int8 on-fabric reduce: 1 byte/param received, independent of W
    assert r["comm_bytes_per_step"] == n
    assert r["comm_bits_per_param"] == pytest.approx(8.0)
    assert r["vs_bf16_allreduce"] == pytest.approx(0.5)
    # reference ships W x int64-packed tensors = 8 bits/param x W received
    # (w*n bytes); the on-fabric psum receives n bytes -> 1/W of that
    assert r["vs_reference_wire"] == pytest.approx(1 / w, rel=1e-6)
    assert r["comm_mbytes_per_sec"] == pytest.approx(2 * n / 1e6)


def test_comm_report_packed_allgather_hits_baseline_budget():
    n, w = 124_000_000, 8
    r = comm_report(n, w, "packed_allgather")
    # true 1-bit wire: W * n/8 bytes -> W bits/param; at W=8 that is 1
    # byte/param... the BASELINE budget (<=1/32 of bf16) applies per-vote:
    assert r["comm_bits_per_param"] == pytest.approx(w * 1.0)
    per_worker_bits = r["comm_bits_per_param"] / w
    assert per_worker_bits / 16.0 <= 1 / 8  # 1 bit vs bf16's 16
