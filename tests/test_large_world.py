"""Large-world vote: W > 127 promotes the ballot accumulator to int32.

collectives.vote_total uses int8 ballots only while |sum| <= 127
(sign_psum) / group tallies fit (hier); at W=130 the tally must promote —
run in a subprocess because conftest pins this process to 8 devices.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap


def test_world_130_int32_promotion():
    code = textwrap.dedent("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import distributed_lion_tpu  # publishes jax.shard_map on old jax
        import numpy as np, jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from distributed_lion_tpu.parallel.collectives import (
            majority_vote, vote_total)

        W = 130
        assert len(jax.devices()) >= W
        mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
        votes = np.random.default_rng(0).random((W, 64)) < 0.5

        def body(v):
            t = vote_total(v[0], "data", "sign_psum")
            return t[None], majority_vote(v[0], "data", "hier:13")[None]

        f = shard_map(body, mesh=mesh, in_specs=P("data"),
                      out_specs=(P("data"), P("data")))
        totals, hier = f(jnp.asarray(votes))
        count = votes.sum(0)
        np.testing.assert_array_equal(np.asarray(totals[0]), count * 2 - W)
        assert np.asarray(totals).dtype == np.int32
        # hier at W=130 g=13: majority-of-majorities is replica-consistent
        h = np.asarray(hier)
        for w in range(1, W):
            np.testing.assert_array_equal(h[0], h[w])
        print("OK")
    """)
    env = dict(os.environ)
    env.update({"XLA_FLAGS": "--xla_force_host_platform_device_count=130",
                "PYTHONPATH": "."})
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300,
        env=env,
        cwd=".",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
