"""TP-sharded serving (ISSUE 13): the tp=1 sharded engine pinned
BIT-identical to the single-device engine (token streams AND the raw page
pools, bytewise), tp>1 pinned token-identical on the CPU mesh, quantized
TP serving, page-pool sharding, and the loud refusals (indivisible heads,
missing devices, draft-model speculation under TP)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.models.llama import LlamaConfig, llama_init
from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)


def _gpt2():
    cfg = GPT2Config.tiny()
    return cfg, gpt2_init(jax.random.key(0), cfg)


def _requests(vocab, n=4, max_new=8, lens=(3, 9, 5, 14, 2)):
    rng = np.random.default_rng(7)
    return [Request(req_id=i,
                    tokens=list(map(int, rng.integers(1, vocab, L))),
                    max_new_tokens=max_new, seed=i)
            for i, L in enumerate(lens[:n])]


def _engine(params, cfg, family="gpt2", **kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    base.update(kw)
    model = (ServeModel.for_gpt2(params, cfg) if family == "gpt2"
             else ServeModel.for_llama(params, cfg))
    return ServingEngine(model, ServeConfig(**base))


# ------------------------------------------------------- tp=1: bitwise pin
def test_tp1_bit_identical_to_single_device():
    """The sharded program on a 1-mesh IS the single-device engine: same
    token streams AND bytewise-equal page pools after the same workload —
    the psum over a size-1 axis is the identity and nothing else differs."""
    cfg, params = _gpt2()
    reqs = _requests(cfg.vocab_size)
    e0 = _engine(params, cfg)
    e1 = _engine(params, cfg, tp=1)
    out0 = e0.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                           r.seed) for r in reqs])
    out1 = e1.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                           r.seed) for r in reqs])
    for r in reqs:
        assert out1[r.req_id].tokens == out0[r.req_id].tokens, r.req_id
        assert out1[r.req_id].reason == out0[r.req_id].reason
    # the strong form: every k/v byte the two engines ever scattered
    for l0, l1 in zip(e0.pages, e1.pages):
        for k in ("k", "v"):
            np.testing.assert_array_equal(np.asarray(l0[k]),
                                          np.asarray(l1[k]))


# ----------------------------------------------------- tp>1: token identity
@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_tp_matches_single_device(tp, sampling):
    """tp>1 divides the head dimension across the CPU mesh; the partial
    row-parallel sums reduce in a different order than one device's
    matmul, so the pin is the engine-level one every serving claim uses:
    identical emitted token streams, greedy AND sampled."""
    cfg, params = _gpt2()
    samp = (dict(temperature=0.0) if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    reqs = _requests(cfg.vocab_size, n=5)
    base = _engine(params, cfg, **samp).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    got = _engine(params, cfg, tp=tp, **samp).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id


def test_llama_tp2_matches_single_device():
    """GQA: tiny llama has 4 query / 2 kv heads — tp=2 leaves one kv head
    per rank in the page-pool shard and the repeat factor intact."""
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), cfg)
    reqs = _requests(cfg.vocab_size, n=3, lens=(3, 7, 11))
    base = _engine(params, cfg, family="llama").run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    got = _engine(params, cfg, family="llama", tp=2).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id


# -------------------------------------------------------- sharded layouts
def test_tp_pages_and_params_sharded():
    cfg, params = _gpt2()
    eng = _engine(params, cfg, tp=2)
    assert eng.pages[0]["k"].sharding.spec == P(None, None, TENSOR_AXIS,
                                                None)
    qkv = eng.params["blocks"][0]["attn"]["qkv"]
    assert qkv.sharding.spec == P(None, None, TENSOR_AXIS)
    # replicated leaves really are replicated (embeddings, norms)
    assert eng.params["wte"].sharding.spec == P()
    # host-side tables stay plain numpy — allocation never recompiles
    assert isinstance(eng.tables.tables, np.ndarray)


def test_nf4_tp2_matches_nf4_single_device():
    """Quantized leaves shard with the SAME specs as their dense twins
    (shaped layout, ops/quant) — NF4 serving composes with TP and the
    outputs match the single-device NF4 engine."""
    cfg, params = _gpt2()
    reqs = _requests(cfg.vocab_size, n=3)
    kw = dict(quant="nf4", quant_block=16)
    base = _engine(params, cfg, **kw).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    eng = _engine(params, cfg, tp=2, **kw)
    got = eng.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                           r.seed) for r in reqs])
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    from distributed_lion_tpu.ops.quant import QuantizedTensor

    assert isinstance(eng.params["blocks"][0]["attn"]["qkv"],
                      QuantizedTensor)


# --------------------------------------------------------------- refusals
def test_tp_refuses_indivisible_heads():
    cfg, params = _gpt2()  # 4 heads
    with pytest.raises(ValueError, match="divisible"):
        _engine(params, cfg, tp=3)


def test_tp_refuses_more_ranks_than_devices():
    cfg, params = _gpt2()
    # conftest provides 8 virtual CPU devices; 8 does not divide 4 heads,
    # so ask for a divisor of the heads that still exceeds the devices
    cfg16 = GPT2Config.tiny(n_head=16, d_model=256)
    params16 = gpt2_init(jax.random.key(0), cfg16)
    with pytest.raises(ValueError, match="devices"):
        _engine(params16, cfg16, tp=16)
    del params


def test_tp_quant_block_that_cannot_shard_is_refused():
    cfg, params = _gpt2()  # d_model 64: one 64-element block per last dim
    with pytest.raises(ValueError, match="quant"):
        _engine(params, cfg, tp=2, quant="nf4")


def test_tp_refuses_draft_model_speculation():
    cfg, params = _gpt2()
    model = ServeModel.for_gpt2(params, cfg)
    draft = ServeModel.for_gpt2(params, cfg)
    with pytest.raises(ValueError, match="serve_tp"):
        ServingEngine(model, ServeConfig(max_seqs=2, block_size=4,
                                         max_blocks_per_seq=8, tp=2,
                                         speculate="draft:2"),
                      draft_model=draft)


# ------------------------------------------------------------ composition
def test_tp_speculative_ngram_matches_plain():
    """ngram speculation under TP: the verify window is just a wider
    decode tick and shards identically — outputs pinned to the plain
    single-device engine (the stream is the acceptance rule)."""
    cfg, params = _gpt2()
    rng = np.random.default_rng(3)
    motif = list(map(int, rng.integers(1, cfg.vocab_size, 4)))
    prompts = [motif * 4 for _ in range(3)]
    reqs = [Request(req_id=i, tokens=list(t), max_new_tokens=10, seed=i)
            for i, t in enumerate(prompts)]
    base = _engine(params, cfg, max_blocks_per_seq=16).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    eng = _engine(params, cfg, max_blocks_per_seq=16, tp=2,
                  speculate="ngram:4")
    got = eng.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                           r.seed) for r in reqs])
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    assert eng.stats["spec_accepted"] > 0  # the drafter actually earned


def test_tp_prefix_cache_composes():
    """TP × prefix sharing: the two levers multiply — sharded pools,
    shared pages, outputs still pinned to the plain engine."""
    cfg, params = _gpt2()
    rng = np.random.default_rng(5)
    sys_p = list(map(int, rng.integers(1, cfg.vocab_size, 13)))
    prompts = [sys_p + list(map(int, rng.integers(1, cfg.vocab_size, 3)))
               for _ in range(5)]
    reqs = [Request(req_id=i, tokens=list(t), max_new_tokens=6, seed=i)
            for i, t in enumerate(prompts)]
    base = _engine(params, cfg, num_blocks=64).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    eng = _engine(params, cfg, num_blocks=64, tp=2, prefix_cache=True)
    got = eng.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                           r.seed) for r in reqs])
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    assert eng.stats["prefix_hits"] > 0


def test_tp_one_decode_dispatch_per_tick():
    """The sharded tick is still ONE dispatch advancing every slot — the
    host's per-tick work stays table math + one token-array read."""
    cfg, params = _gpt2()
    eng = _engine(params, cfg, tp=2)
    for r in _requests(cfg.vocab_size, n=3, max_new=4):
        eng.submit(r)
    eng.step()  # admissions + first decode tick
    t0 = eng.stats["decode_ticks"]
    eng.step()
    assert eng.stats["decode_ticks"] == t0 + 1


def test_tp_serve_config_survives_jit_cache():
    """Two engines at different tp degrees coexist (separate meshes and
    compiled programs) — outputs of each still match the baseline."""
    cfg, params = _gpt2()
    reqs = _requests(cfg.vocab_size, n=2)
    base = _engine(params, cfg).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs])
    for tp in (1, 2):
        got = _engine(params, cfg, tp=tp).run(
            [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
             for r in reqs])
        for r in reqs:
            assert got[r.req_id].tokens == base[r.req_id].tokens
