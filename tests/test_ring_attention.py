"""Ring + Ulysses attention: exactness vs single-device full attention,
and gradient flow through the ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.ops.attention import attention_xla
from distributed_lion_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from distributed_lion_tpu.parallel.ring_attention import ring_attention, ulysses_attention


def _qkv(B=2, H=4, T=64, hd=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, T, hd)).astype(np.float32))
    return mk(), mk(), mk()


def _seq_mesh(s=4):
    return make_mesh(data=1, tensor=1, seq=s, devices=jax.devices()[:s])


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_matches_full_attention(impl):
    mesh = _seq_mesh(4)
    q, k, v = _qkv()
    expected = attention_xla(q, k, v, causal=True)

    def f(q, k, v):
        return impl(q, k, v, SEQ_AXIS)

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, None, SEQ_AXIS), P(None, None, SEQ_AXIS), P(None, None, SEQ_AXIS)),
            out_specs=P(None, None, SEQ_AXIS),
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5)


def test_ring_gradients_flow():
    mesh = _seq_mesh(4)
    q, k, v = _qkv(T=32)

    def loss_sharded(q, k, v):
        def f(q, k, v):
            return ring_attention(q, k, v, SEQ_AXIS)

        out = jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, None, SEQ_AXIS),) * 3,
            out_specs=P(None, None, SEQ_AXIS),
            check_vma=False,
        )(q, k, v)
        return (out.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_xla(q, k, v, causal=True).astype(jnp.float32) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_sharded, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-4)


def test_ulysses_rejects_bad_head_count():
    mesh = _seq_mesh(4)
    q, k, v = _qkv(H=2)  # 2 heads < 4-way seq axis

    def f(q, k, v):
        return ulysses_attention(q, k, v, SEQ_AXIS)

    with pytest.raises(ValueError):
        jax.jit(
            jax.shard_map(
                f, mesh=mesh,
                in_specs=(P(None, None, SEQ_AXIS),) * 3,
                out_specs=P(None, None, SEQ_AXIS),
                check_vma=False,
            )
        )(q, k, v)
