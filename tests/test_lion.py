"""Local Lion unit tests: hand-computed algebra parity with the reference's
update_fn (distributed_lion.py:47-59) and ctor validation (:149-150)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.optim import lion


def _hand_step(p, g, m, lr, wd, b1, b2):
    p = p * (1 - lr * wd)
    u = np.sign(b1 * m + (1 - b1) * g)
    p = p - lr * u
    m = b2 * m + (1 - b2) * g
    return p, m


def test_single_step_matches_hand_algebra():
    rng = np.random.default_rng(1)
    p0 = rng.normal(size=(5, 3)).astype(np.float32)
    g0 = rng.normal(size=(5, 3)).astype(np.float32)
    m0 = rng.normal(size=(5, 3)).astype(np.float32)

    opt = lion(learning_rate=0.01, b1=0.9, b2=0.99, weight_decay=0.1)
    state = opt.init({"w": jnp.asarray(p0)})
    state = state._replace(exp_avg={"w": jnp.asarray(m0)})
    new_p, new_state = jax.jit(opt.step)({"w": jnp.asarray(p0)}, {"w": jnp.asarray(g0)}, state)

    exp_p, exp_m = _hand_step(p0, g0, m0, 0.01, 0.1, 0.9, 0.99)
    np.testing.assert_allclose(np.asarray(new_p["w"]), exp_p, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_state.exp_avg["w"]), exp_m, rtol=1e-6)
    assert int(new_state.count) == 1


def test_state_is_momentum_only_and_lazy_zero():
    # Parity: the only state is exp_avg initialized to zeros (ref :185-186).
    opt = lion()
    params = {"a": jnp.ones((3,)), "b": {"c": jnp.ones((2, 2), jnp.bfloat16)}}
    st = opt.init(params)
    assert jax.tree.all(jax.tree.map(lambda m: (m == 0).all(), st.exp_avg))
    assert st.exp_avg["b"]["c"].dtype == jnp.bfloat16  # momentum in param dtype


def test_two_steps_momentum_carries():
    opt = lion(learning_rate=0.1, weight_decay=0.0)
    p = {"w": jnp.zeros((4,))}
    g = {"w": jnp.ones((4,))}
    p1, st = opt.step(p, g, opt.init(p))
    # step 1: m=0 → u=sign(0.1*g)=1 → p1 = -0.1
    np.testing.assert_allclose(np.asarray(p1["w"]), -0.1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(st.exp_avg["w"]), 0.01, rtol=1e-6)
    p2, st2 = opt.step(p1, g, st)
    np.testing.assert_allclose(np.asarray(p2["w"]), -0.2, rtol=1e-6)
    assert int(st2.count) == 2


def test_validation_matches_reference():
    with pytest.raises(ValueError):
        lion(learning_rate=0.0)
    with pytest.raises(ValueError):
        lion(b1=1.5)
    with pytest.raises(ValueError):
        lion(b2=-0.1)


def test_bf16_params_stay_bf16_under_f32_schedule():
    # Regression: a float32 LR schedule must not promote bf16 params.
    sched = lambda count: jnp.asarray(1e-3, jnp.float32) * jnp.ones((), jnp.float32)
    opt = lion(learning_rate=sched, weight_decay=0.1)
    p = {"w": jnp.ones((4,), jnp.bfloat16)}
    p1, st = opt.step(p, {"w": jnp.ones((4,), jnp.bfloat16)}, opt.init(p))
    assert p1["w"].dtype == jnp.bfloat16
    assert st.exp_avg["w"].dtype == jnp.bfloat16


def test_schedule_callable():
    sched = lambda count: 0.1 * (count + 1)
    opt = lion(learning_rate=sched)
    p = {"w": jnp.zeros((2,))}
    p1, st = opt.step(p, {"w": jnp.ones((2,))}, opt.init(p))
    np.testing.assert_allclose(np.asarray(p1["w"]), -0.1, rtol=1e-6)
