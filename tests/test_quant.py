"""Quantization tests: NF4/int8 round-trip error, packing, tree targeting."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    dequantize_tree,
    maybe_dequant,
    quantize_int8,
    quantize_nf4,
    quantize_tree,
)


def test_nf4_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32) * 0.02)
    qt = quantize_nf4(w)
    assert qt.codes.dtype == jnp.uint8
    assert qt.codes.size == w.size // 2  # 2 codes per byte → 0.5 B/param
    deq = dequantize(qt, jnp.float32)
    assert deq.shape == w.shape
    # NF4 relative error for gaussian weights: well under absmax/2 per block
    err = np.abs(np.asarray(deq) - np.asarray(w))
    assert err.max() < 0.02 * 0.5
    # correlation stays near 1
    c = np.corrcoef(np.asarray(deq).ravel(), np.asarray(w).ravel())[0, 1]
    assert c > 0.98


def test_int8_roundtrip_tighter_than_nf4():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(64, 64)).astype(np.float32))
    err8 = np.abs(np.asarray(dequantize(quantize_int8(w), jnp.float32)) - np.asarray(w)).max()
    err4 = np.abs(np.asarray(dequantize(quantize_nf4(w), jnp.float32)) - np.asarray(w)).max()
    assert err8 < err4


def test_nonmultiple_block_padding():
    w = jnp.asarray(np.random.default_rng(2).normal(size=(7, 13)).astype(np.float32))
    deq = dequantize(quantize_nf4(w, block=64), jnp.float32)
    assert deq.shape == (7, 13)


def test_quantize_tree_targets_large_2d_only():
    tree = {
        "big": jnp.ones((128, 64)),
        "norm": jnp.ones((64,)),
        "small": jnp.ones((4, 4)),
    }
    q = quantize_tree(tree, "nf4", min_size=1024)
    assert isinstance(q["big"], QuantizedTensor)
    assert not isinstance(q["norm"], QuantizedTensor)
    assert not isinstance(q["small"], QuantizedTensor)
    dense = dequantize_tree(q)
    assert dense["big"].shape == (128, 64)


def test_shaped_layout_selected_and_rank_aligned():
    """Aligned shapes get the shaped (TP-shardable) layout: codes/absmax
    keep the dense rank; odd shapes fall back to flat."""
    w = jnp.ones((128, 64))
    qt = quantize_nf4(w, block=16)
    assert qt.layout == "shaped"
    assert qt.codes.shape == (128, 32)      # last dim / 2
    assert qt.absmax.shape == (128, 4)      # last dim / block
    q8 = quantize_int8(w, block=16)
    assert q8.layout == "shaped" and q8.codes.shape == (128, 64)
    assert quantize_nf4(jnp.ones((7, 13)), block=64).layout == "flat"
    # 3-D (GPT-2's stacked qkv) keeps rank too
    q3 = quantize_nf4(jnp.ones((8, 3, 64)), block=16)
    assert q3.layout == "shaped" and q3.codes.shape == (8, 3, 32)


def test_shaped_matches_flat_numerics():
    """For aligned shapes the shaped layout is a pure re-layout: identical
    dequantized values to the flat path (row-major blocks never straddled
    rows when last%block==0)."""
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(32, 128)).astype(np.float32))
    shaped = quantize_nf4(w, block=32)
    assert shaped.layout == "shaped"
    flat = QuantizedTensor(
        *_flat_quant_nf4(np.asarray(w), 32), (32, 128), "nf4", 32, "flat")
    np.testing.assert_array_equal(
        np.asarray(dequantize(shaped, jnp.float32)),
        np.asarray(dequantize(flat, jnp.float32)))


def _flat_quant_nf4(w, block):
    """Reference flat packing in numpy (the pre-round-3 storage layout)."""
    from distributed_lion_tpu.ops.quant import NF4_LEVELS

    flat = w.reshape(-1).astype(np.float32)
    blocks = flat.reshape(-1, block)
    absmax = np.abs(blocks).max(1)
    scaled = blocks / np.maximum(absmax, 1e-12)[:, None]
    mids = (NF4_LEVELS[1:] + NF4_LEVELS[:-1]) / 2.0
    codes4 = np.searchsorted(mids, scaled).astype(np.uint8).reshape(-1)
    packed = (codes4[0::2] | (codes4[1::2] << 4)).astype(np.uint8)
    return jnp.asarray(packed), jnp.asarray(absmax)


def test_sharded_dequant_matches_dense_slice():
    """shard_map over a column-sharded shaped QuantizedTensor: each rank's
    local dequant == the corresponding columns of the full dequant (the
    invariant TP's maybe_dequant relies on)."""
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("tensor",))
    w = jnp.asarray(np.random.default_rng(4).normal(size=(32, 64)).astype(np.float32))
    qt = quantize_nf4(w, block=16)
    spec = P(None, "tensor")
    qt_sharded = jax.tree.map(
        lambda c: jax.device_put(c, NamedSharding(mesh, spec)), qt)

    def local_dequant(q):
        return dequantize(q, jnp.float32)

    out = shard_map(local_dequant, mesh=mesh, in_specs=spec,
                    out_specs=spec)(qt_sharded)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dequantize(qt, jnp.float32)))


def test_maybe_dequant_passthrough():
    w = jnp.ones((4, 4))
    assert maybe_dequant(w, jnp.float32) is w


def test_quantized_tensor_is_pytree():
    qt = quantize_nf4(jnp.ones((64, 64)))
    moved = jax.tree.map(lambda x: x, qt)
    assert isinstance(moved, QuantizedTensor)
    assert moved.shape == (64, 64)
