"""Quantization tests: NF4/int8 round-trip error, packing, tree targeting."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    dequantize_tree,
    maybe_dequant,
    quantize_int8,
    quantize_nf4,
    quantize_tree,
)


def test_nf4_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32) * 0.02)
    qt = quantize_nf4(w)
    assert qt.codes.dtype == jnp.uint8
    assert qt.codes.size == w.size // 2  # 2 codes per byte → 0.5 B/param
    deq = dequantize(qt, jnp.float32)
    assert deq.shape == w.shape
    # NF4 relative error for gaussian weights: well under absmax/2 per block
    err = np.abs(np.asarray(deq) - np.asarray(w))
    assert err.max() < 0.02 * 0.5
    # correlation stays near 1
    c = np.corrcoef(np.asarray(deq).ravel(), np.asarray(w).ravel())[0, 1]
    assert c > 0.98


def test_int8_roundtrip_tighter_than_nf4():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(64, 64)).astype(np.float32))
    err8 = np.abs(np.asarray(dequantize(quantize_int8(w), jnp.float32)) - np.asarray(w)).max()
    err4 = np.abs(np.asarray(dequantize(quantize_nf4(w), jnp.float32)) - np.asarray(w)).max()
    assert err8 < err4


def test_nonmultiple_block_padding():
    w = jnp.asarray(np.random.default_rng(2).normal(size=(7, 13)).astype(np.float32))
    deq = dequantize(quantize_nf4(w, block=64), jnp.float32)
    assert deq.shape == (7, 13)


def test_quantize_tree_targets_large_2d_only():
    tree = {
        "big": jnp.ones((128, 64)),
        "norm": jnp.ones((64,)),
        "small": jnp.ones((4, 4)),
    }
    q = quantize_tree(tree, "nf4", min_size=1024)
    assert isinstance(q["big"], QuantizedTensor)
    assert not isinstance(q["norm"], QuantizedTensor)
    assert not isinstance(q["small"], QuantizedTensor)
    dense = dequantize_tree(q)
    assert dense["big"].shape == (128, 64)


def test_maybe_dequant_passthrough():
    w = jnp.ones((4, 4))
    assert maybe_dequant(w, jnp.float32) is w


def test_quantized_tensor_is_pytree():
    qt = quantize_nf4(jnp.ones((64, 64)))
    moved = jax.tree.map(lambda x: x, qt)
    assert isinstance(moved, QuantizedTensor)
    assert moved.shape == (64, 64)
