"""Data pipeline tests: group_texts parity (run_clm.py:509-522), streaming
packing, batch iteration, tokenizer round-trip."""

import numpy as np
import pytest

from distributed_lion_tpu.data.packing import group_texts, pack_token_stream
from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.data.tokenizer import ByteTokenizer


def test_group_texts_drop_remainder():
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10]]  # 10 tokens, block 4 → 2 blocks
    blocks = group_texts(docs, 4)
    assert blocks.shape == (2, 4)
    np.testing.assert_array_equal(blocks, [[1, 2, 3, 4], [5, 6, 7, 8]])  # 9,10 dropped


def test_group_texts_empty_and_exact():
    assert group_texts([[1]], 4).shape == (0, 4)
    assert group_texts([[1, 2, 3, 4]], 4).shape == (1, 4)


def test_pack_token_stream_matches_group_texts():
    docs = [list(range(i, i + 7)) for i in range(0, 70, 7)]
    streamed = np.stack(list(pack_token_stream(iter(docs), 8, buffer_blocks=2)))
    np.testing.assert_array_equal(streamed, group_texts(docs, 8))


def test_batch_iterator_shuffles_and_drops_last():
    blocks = np.arange(70).reshape(10, 7).astype(np.int32)
    it = batch_iterator(blocks, global_batch=4, seed=0, epochs=1)
    batches = list(it)
    assert len(batches) == 2  # 10 blocks / 4 → 2, last 2 dropped
    first_epoch_rows = np.concatenate(batches)[:, 0] // 7
    assert not np.array_equal(first_epoch_rows, np.arange(8)), "batches were not shuffled"
    it2 = batch_iterator(blocks, global_batch=4, seed=0, epochs=2)
    assert len(list(it2)) == 4


def test_batch_iterator_rejects_small_dataset():
    with pytest.raises(ValueError):
        next(batch_iterator(np.zeros((2, 4), np.int32), global_batch=4))


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "Distributed Lion über TPU — 1-bit votes!"
    ids = tok.encode(text, add_bos=True, add_eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == text
    assert tok.vocab_size == 259


def test_synthetic_dataset_in_vocab():
    blocks = synthetic_lm_dataset(8, 32, vocab_size=100)
    assert blocks.shape == (8, 32)
    assert blocks.min() >= 0 and blocks.max() < 100
