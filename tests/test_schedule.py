"""Schedule parity with transformers.get_cosine_schedule_with_warmup
(the scheduler every reference entry point uses, run_clm.py:582)."""

import math

import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.train.schedule import (
    constant_schedule,
    cosine_schedule_with_warmup,
    linear_schedule_with_warmup,
)


def _hf_cosine(step, warmup, total, num_cycles=0.5):
    if step < warmup:
        return step / max(1, warmup)
    progress = (step - warmup) / max(1, total - warmup)
    return max(0.0, 0.5 * (1.0 + math.cos(math.pi * num_cycles * 2.0 * progress)))


def test_cosine_matches_hf_formula():
    peak, warmup, total = 1e-4, 2000, 100_000  # canonical config README.md:25-27
    sched = cosine_schedule_with_warmup(peak, warmup, total)
    for step in [0, 1, 100, 1999, 2000, 2001, 50_000, 99_999, 100_000]:
        np.testing.assert_allclose(
            float(sched(jnp.asarray(step))), peak * _hf_cosine(step, warmup, total),
            rtol=1e-5, atol=1e-9, err_msg=f"step={step}",
        )


def test_linear_and_constant():
    lin = linear_schedule_with_warmup(1.0, 10, 110)
    assert float(lin(5)) == 0.5
    np.testing.assert_allclose(float(lin(60)), 0.5, rtol=1e-6)
    assert float(lin(110)) == 0.0
    assert float(constant_schedule(0.3)(12345)) == np.float32(0.3)
