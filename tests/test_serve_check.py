"""graft-check for the serving plane (analysis/serve_check, ISSUE 19):
the jaxpr contract holds on real matrix cells, an injected extra
collective / host callback / recompile each FAILS loudly, the tick-level
retrace guard warns/raises without perturbing token streams, and the
banked ``runs/static/serve_check.json`` artifact is schema-gated so a
corrupted (or forged-ok) report cannot pass ``check_evidence
static_serve``."""

import copy
import importlib.util
import json
import os
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.analysis import serve_check
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
    dispatch_signature,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "runs", "static", "serve_check.json")


def _load_validate_metrics():
    spec = importlib.util.spec_from_file_location(
        "dlt_vm_for_serve_check",
        os.path.join(REPO, "scripts", "validate_metrics.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ the matrix
def test_matrix_covers_every_config_axis():
    """The committed matrix spans every serving lever the engine ships:
    tp {0,1,2}, ep {1,2}, ep_batch, both weight formats, speculation."""
    cells = serve_check.MATRIX
    assert {c.get("tp", 0) for c in cells} >= {0, 1, 2}
    assert {c.get("ep", 0) for c in cells} >= {0, 1, 2}
    assert any(c.get("ep_batch") for c in cells)
    assert any(c.get("ep_batch") and c.get("tp") for c in cells)
    assert any(c.get("quant") == "nf4" for c in cells)
    assert any(c.get("quant") == "nf4" and c.get("tp") for c in cells)
    assert any(c.get("quant") == "nf4" and c.get("ep") for c in cells)
    assert any(c.get("speculate") for c in cells)
    assert any(c.get("speculate") and c.get("moe") for c in cells)


def test_validator_cell_list_matches_live_matrix():
    """The stdlib validator's hardcoded cell list (it must stay
    importable without jax) cannot drift from the live matrix."""
    vm = _load_validate_metrics()
    assert sorted(vm._SERVE_CHECK_CELLS) == sorted(
        c["name"] for c in serve_check.MATRIX)


def test_dense_tp2_inventory_is_two_psums_per_layer():
    cell = {"name": "dense_tp2_bf16", "moe": False, "tp": 2}
    rep = serve_check.check_cell(cell)
    assert rep["ok"], rep
    decode = rep["dispatches"]["decode"]
    # 2 layers x (attention exit + MLP exit), operand [B=4, S=1, D=64]
    assert decode["observed"] == [["psum", ("tensor",), 256]] * 4
    assert decode["host_callbacks"] == []
    assert decode["donation_ok"] and decode["upcast_ok"]
    # every power-of-two bucket traced: 4, 8, 16
    assert {k for k in rep["dispatches"] if k.startswith("prefill:")} == \
        {"prefill:4", "prefill:8", "prefill:16"}
    assert rep["dispatches"]["cow"]["observed"] == []


def test_moe_ep2_batch_inventory_and_specs():
    cell = {"name": "moe_ep2_batch_bf16", "moe": True, "ep": 2,
            "ep_batch": True}
    rep = serve_check.check_cell(cell)
    assert rep["ok"], rep
    assert rep["ep_batch_specs_ok"]
    decode = rep["dispatches"]["decode"]
    # one MoE block (layer 1), two all_to_all hops of the [E=4, cap=2,
    # D=64] dispatch buffer (batch is sharded: B_local = 4/2)
    assert decode["observed"] == [["all_to_all", ("expert",), 512]] * 2


def test_moe_ep1_cell_puts_nothing_on_the_wire():
    """ep=1 binds the mesh but the static ``ep > 1`` gate keeps every
    all_to_all out of the program — zero fabric traffic, pinned."""
    rep = serve_check.check_cell({"name": "moe_ep1_bf16", "moe": True,
                                  "ep": 1})
    assert rep["ok"], rep
    for name, d in rep["dispatches"].items():
        assert d["observed"] == [], (name, d["observed"])


def test_speculate_cell_traces_the_verify_window():
    rep = serve_check.check_cell({"name": "dense_tp0_ngram", "moe": False,
                                  "speculate": "ngram:3"})
    assert rep["ok"], rep
    assert "verify" in rep["dispatches"]
    assert rep["dispatches"]["verify"]["host_callbacks"] == []


# ------------------------------------------------- injected violations
def test_injected_extra_psum_fails_naming_the_primitive():
    """An extra collective smuggled into the decode dispatch (the exact
    failure mode the inventory exists to catch: a sharding change that
    starts paying a hop the config doesn't buy) fails the cell and names
    the primitive."""
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.parallel.mesh import EXPERT_AXIS

    cell = {"name": "moe_ep2_bf16", "moe": True, "ep": 2}
    eng, scfg = serve_check.build_engine(cell)
    mcfg = serve_check._model_cfg(True)
    reg = eng._dispatches["decode"]
    orig = reg["jitted"]
    leak_fn = jax.shard_map(
        lambda x: jax.lax.psum(x, EXPERT_AXIS), mesh=eng._mesh,
        in_specs=(P(),), out_specs=P(), check_vma=False)

    def bad(params, pages, *rest):
        (tok, st), pg = orig(params, pages, *rest)
        leak = leak_fn(jnp.zeros((128,), jnp.float32))
        return (tok + leak.sum().astype(tok.dtype), st), pg

    reg["jitted"] = bad
    rep = serve_check.check_dispatch(eng, mcfg, scfg, "decode")
    assert not rep["ok"] and not rep["inventory_ok"]
    assert any(u[0] == "psum" for u in rep["unexpected"]), rep["unexpected"]


def test_injected_host_callback_fails():
    cell = {"name": "dense_tp0_bf16", "moe": False}
    eng, scfg = serve_check.build_engine(cell)
    mcfg = serve_check._model_cfg(False)
    reg = eng._dispatches["decode"]
    orig = reg["jitted"]

    def bad(params, pages, *rest):
        (tok, st), pg = orig(params, pages, *rest)
        jax.debug.print("tick {}", tok.sum())
        return (tok, st), pg

    reg["jitted"] = bad
    rep = serve_check.check_dispatch(eng, mcfg, scfg, "decode")
    assert not rep["ok"] and rep["host_callbacks"]


# ------------------------------------------------------- compile budget
def test_compile_counts_hold_the_bucket_budget():
    rep = serve_check.check_compile_budget(
        {"name": "dense_tp0_bf16", "moe": False})
    assert rep["ok"], rep
    # ONE decode program; one prefill per power-of-two bucket {4, 8, 16}
    assert rep["counts"]["decode"] == 1
    assert rep["counts"]["prefill"] == 3 == rep["budget"]["prefill"]


# --------------------------------------------------------- retrace guard
def _tiny_engine(**kw):
    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

    cfg = GPT2Config.tiny(vocab_size=128, n_ctx=64)
    params = gpt2_init(jax.random.key(0), cfg)
    scfg = ServeConfig(max_seqs=4, block_size=4, max_blocks_per_seq=4,
                       **kw)
    return ServingEngine(ServeModel.for_gpt2(params, cfg), scfg), cfg


def _workload(vocab, seed=0):
    return [Request(req_id=i, tokens=[1 + (i + j + seed) % (vocab - 1)
                                      for j in range(n)],
                    max_new_tokens=4, seed=i)
            for i, n in enumerate((1, 3, 7, 14))]


def test_retrace_guard_error_raises_on_injected_recompile():
    """A dispatch whose operand signature exceeds the compile budget (an
    injected shape drift — exactly what would silently retrace) raises
    BEFORE lowering under --serve_retrace_guard error."""
    eng, cfg = _tiny_engine(retrace_guard="error")
    eng.run(_workload(cfg.vocab_size))  # legit workload: within budget
    novel = (jnp.zeros((8, 4), jnp.int32),)  # decode budget (1) is spent
    with pytest.raises(RuntimeError, match="retrace"):
        eng._guard("decode", novel)


def test_retrace_guard_warn_counts_and_warns():
    eng, cfg = _tiny_engine(retrace_guard="warn")
    eng.run(_workload(cfg.vocab_size))
    assert eng.stats["serve_retraces"] == 0  # legit workload is silent
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng._guard("decode", (jnp.zeros((8, 4), jnp.int32),))
    assert eng.stats["serve_retraces"] == 1
    assert any("retrace" in str(w.message) for w in caught)


def test_retrace_guard_prefill_budget_is_per_bucket():
    """Three distinct prefill signatures (one per power-of-two bucket)
    are the budget, not a violation — the guard mirrors compile_budget,
    not dispatch count."""
    eng, cfg = _tiny_engine(retrace_guard="error")
    eng.run(_workload(cfg.vocab_size))  # hits buckets 4, 8 and 16
    assert eng.compile_counts()["prefill"] == 3
    assert eng.stats["serve_retraces"] == 0


def test_retrace_guard_off_is_bit_identical():
    eng_off, cfg = _tiny_engine(retrace_guard="off")
    eng_err, _ = _tiny_engine(retrace_guard="error")
    out_off = eng_off.run(_workload(cfg.vocab_size))
    out_err = eng_err.run(_workload(cfg.vocab_size))
    assert set(out_off) == set(out_err)
    for rid in out_off:
        assert out_off[rid].tokens == out_err[rid].tokens
        assert out_off[rid].reason == out_err[rid].reason
    assert "serve_retraces" not in eng_off.stats


def test_retrace_guard_rejects_unknown_mode():
    with pytest.raises(ValueError, match="retrace_guard"):
        _tiny_engine(retrace_guard="loud")


def test_dispatch_signature_is_shape_and_dtype():
    a = (jnp.zeros((4, 2), jnp.int32), jnp.uint32(0))
    b = (jnp.ones((4, 2), jnp.int32), jnp.uint32(9))  # values differ
    c = (jnp.zeros((4, 3), jnp.int32), jnp.uint32(0))  # shape differs
    assert dispatch_signature(a) == dispatch_signature(b)
    assert dispatch_signature(a) != dispatch_signature(c)


# ------------------------------------------------------ banked artifact
def _banked():
    with open(ARTIFACT) as f:
        return json.load(f)


def test_banked_artifact_validates_clean():
    vm = _load_validate_metrics()
    assert os.path.exists(ARTIFACT), "run `python -m " \
        "distributed_lion_tpu.analysis serve-check --json-out " \
        "runs/static/serve_check.json`"
    assert vm.validate_json_doc(ARTIFACT) == []


def _corrupt(doc, mode):
    """Five forgeries, every one leaving ``ok`` flags true — the schema
    re-derives the verdicts, so forged flags cannot pass."""
    cell = next(c for c in doc["cells"] if c["cell"] == "dense_tp2_bf16")
    if mode == "extra_collective":
        cell["dispatches"]["decode"]["observed"].append(
            ["psum", ["tensor"], 4096])
    elif mode == "missing_cell":
        doc["cells"] = [c for c in doc["cells"]
                        if c["cell"] != "moe_ep2_batch_tp2_bf16"]
    elif mode == "host_callback":
        cell["dispatches"]["decode"]["host_callbacks"] = ["pure_callback"]
    elif mode == "donation_lost":
        cell["dispatches"]["decode"]["donation"] = {
            "aliased_outputs": 0, "buffer_donors": 0}
    elif mode == "over_budget":
        doc["compile"][0]["counts"]["prefill"] = 9
    else:
        raise AssertionError(mode)
    return doc


@pytest.mark.parametrize("mode", ["extra_collective", "missing_cell",
                                  "host_callback", "donation_lost",
                                  "over_budget"])
def test_stage_rejects_corrupt_artifact(mode, tmp_path):
    vm = _load_validate_metrics()
    doc = _corrupt(copy.deepcopy(_banked()), mode)
    bad = tmp_path / "serve_check.json"
    bad.write_text(json.dumps(doc))
    assert vm.validate_json_doc(str(bad)), mode
    # and the evidence stage itself says MISSING for the same file
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_evidence.py"),
         "static_serve", str(bad)], capture_output=True).returncode
    assert rc != 0, mode


def test_evidence_stage_accepts_banked_artifact():
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_evidence.py"),
         "static_serve"], capture_output=True).returncode
    assert rc == 0
