"""Fused Pallas Lion kernels: numerical equivalence with the XLA path
(interpreter mode on CPU), both wire formats, padding edges."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.ops.pallas_lion import fused_apply, fused_ballots
from distributed_lion_tpu.optim import distributed_lion, init_global_state
from distributed_lion_tpu.optim.sharded import make_sharded_step, shard_state
from distributed_lion_tpu.parallel import make_mesh


def test_fused_ballots_matches_reference_encoding():
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.normal(size=(1000,)).astype(np.float32))  # non-multiple of tile
    m = jnp.asarray(rng.normal(size=(1000,)).astype(np.float32))
    out = fused_ballots(g, m, 0.9, interpret=True)
    assert out.dtype == jnp.int8 and out.shape == (1000,)
    u = 0.9 * np.asarray(m) + 0.1 * np.asarray(g)
    np.testing.assert_array_equal(np.asarray(out), np.where(u > 0, 1, -1))


def test_fused_ballots_zero_votes_minus_one():
    out = fused_ballots(jnp.zeros((8,)), jnp.zeros((8,)), 0.9, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), -1)


def test_fused_apply_matches_hand_algebra():
    rng = np.random.default_rng(1)
    n, lr, wd, b2 = 777, 0.01, 0.1, 0.99
    p = jnp.asarray(rng.normal(size=(n,)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(n,)).astype(np.float32))
    m = jnp.asarray(rng.normal(size=(n,)).astype(np.float32))
    tot = jnp.asarray(rng.integers(-8, 9, size=(n,)).astype(np.int32))
    p_new, m_new = fused_apply(p, g, m, tot, lr, wd, b2, interpret=True)
    s = np.where(np.asarray(tot) > 0, 1.0, -1.0)  # tie (0) → −1
    np.testing.assert_allclose(np.asarray(p_new), np.asarray(p) * (1 - lr * wd) - lr * s, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(m_new), b2 * np.asarray(m) + 0.01 * np.asarray(g), rtol=1e-5)


def test_fused_apply_bf16_params():
    p = jnp.ones((256,), jnp.bfloat16)
    g = jnp.ones((256,), jnp.bfloat16)
    m = jnp.zeros((256,), jnp.bfloat16)
    p_new, m_new = fused_apply(p, g, m, jnp.ones((256,), jnp.int32), 0.5, 0.0, 0.9,
                               interpret=True)
    assert p_new.dtype == jnp.bfloat16 and m_new.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(p_new, np.float32), 0.5)


@pytest.mark.parametrize("wire", ["sign_psum", "packed_allgather"])
def test_pallas_step_equals_xla_step(wire):
    """kernel='pallas' (interpreted) and kernel='xla' produce identical
    trajectories over several steps on the 8-device mesh."""
    mesh = make_mesh(data=8)
    rng = np.random.default_rng(7)
    params = {
        "w": jnp.asarray(rng.normal(size=(33, 7)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(130,)).astype(np.float32)),
    }
    grads = {
        "w": jnp.asarray(rng.normal(size=(8, 33, 7)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(8, 130)).astype(np.float32)),
    }
    results = []
    for kern in ("pallas", "xla"):
        opt = distributed_lion(learning_rate=0.02, weight_decay=0.05, wire=wire, kernel=kern)
        state = shard_state(init_global_state(opt, params, 8), mesh)
        step = make_sharded_step(opt, mesh)
        p = params
        for _ in range(3):
            p, state = step(p, grads, state)
        results.append((p, state))
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(results[0][0][k]), np.asarray(results[1][0][k])
        )
        np.testing.assert_allclose(
            np.asarray(results[0][1].exp_avg[k]),
            np.asarray(results[1][1].exp_avg[k]),
            rtol=1e-6,
        )


def test_kernel_mode_validation():
    with pytest.raises(ValueError):
        distributed_lion(kernel="cuda")
