"""Serving subsystem (ISSUE 9): paged-KV decode bit-identical to the dense
cache, continuous batching identical to solo runs, host-side page
allocator invariants, NF4 frozen-weight serving, fairness cap, the
request-file API, and the banked serving evidence artifact."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import (
    GPT2Config, gpt2_decode, gpt2_decode_paged, gpt2_init, gpt2_init_cache,
)
from distributed_lion_tpu.models.llama import (
    LlamaConfig, llama_decode, llama_decode_paged, llama_init,
    llama_init_cache,
)
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
    weight_bytes,
)
from distributed_lion_tpu.serve.kv_cache import BlockTables

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tokens(vocab, b, t, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(1, vocab, (b, t)), jnp.int32)


# ------------------------------------------------------- paged == dense
@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_decode_bit_identical_to_dense(family):
    """Prefill + per-token decode through SHUFFLED block tables produces
    bit-identical logits to the dense KV cache at the same attended
    length — the paged layout is pure indirection, never arithmetic."""
    if family == "gpt2":
        cfg = GPT2Config.tiny()
        params = gpt2_init(jax.random.key(0), cfg)
        dec, icache, decp, kv = gpt2_decode, gpt2_init_cache, \
            gpt2_decode_paged, cfg.n_head
    else:
        cfg = LlamaConfig.tiny()  # GQA: pages hold kv heads un-repeated
        params = llama_init(jax.random.key(0), cfg)
        dec, icache, decp, kv = llama_decode, llama_init_cache, \
            llama_decode_paged, cfg.n_kv_head
    B, L, bs, nb_seq = 2, 7, 4, 4          # both caches attend 16 slots
    toks = _tokens(cfg.vocab_size, B, L)
    cache = icache(cfg, B, bs * nb_seq)
    dl, cache = dec(params, toks, cfg, cache, 0)
    pages = [{k: jnp.zeros((B * nb_seq, bs, kv, cfg.head_dim),
                           cfg.compute_dtype) for k in ("k", "v")}
             for _ in range(cfg.n_layer)]
    # interleaved/shuffled page ownership: the gather must reassemble
    # purely via the table, not via any layout assumption
    tables = jnp.asarray([[2, 0, 1, 3], [5, 7, 4, 6]], jnp.int32)
    pl, pages = decp(params, toks, cfg, pages, tables,
                     jnp.zeros((B,), jnp.int32))
    np.testing.assert_array_equal(np.asarray(dl), np.asarray(pl))
    t_cur = jnp.argmax(dl[:, -1], -1)
    lens = jnp.full((B,), L, jnp.int32)
    for i in range(5):
        dl, cache = dec(params, t_cur[:, None], cfg, cache, L + i)
        pl, pages = decp(params, t_cur[:, None], cfg, pages, tables, lens)
        np.testing.assert_array_equal(np.asarray(dl), np.asarray(pl))
        t_cur = jnp.argmax(dl[:, -1], -1)
        lens = lens + 1


def test_paged_prefill_valid_mask_drops_pad_tail():
    """A right-padded prefill (the engine's bucketed shape) must write
    exactly the real tokens' pages: logits at real positions match an
    unpadded prefill bit-for-bit, and a later decode step agrees too."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(1), cfg)
    L, P, bs = 5, 8, 4
    toks = _tokens(cfg.vocab_size, 1, L, seed=2)
    padded = jnp.concatenate(
        [toks, jnp.zeros((1, P - L), jnp.int32)], axis=1)

    def pages():
        return [{k: jnp.zeros((4, bs, cfg.n_head, cfg.head_dim),
                              cfg.compute_dtype) for k in ("k", "v")}
                for _ in range(cfg.n_layer)]

    tables = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    zero = jnp.zeros((1,), jnp.int32)
    ref, ref_pages = gpt2_decode_paged(params, toks, cfg, pages(), tables, zero)
    valid = (jnp.arange(P) < L)[None, :]
    got, got_pages = gpt2_decode_paged(params, padded, cfg, pages(), tables,
                                       zero, valid)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got[:, :L]))
    nxt = jnp.argmax(ref[:, L - 1], -1)[:, None]
    lens = jnp.full((1,), L, jnp.int32)
    a, _ = gpt2_decode_paged(params, nxt, cfg, ref_pages, tables, lens)
    b, _ = gpt2_decode_paged(params, nxt, cfg, got_pages, tables, lens)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------- multi-token window commit
def test_multi_token_scatter_matches_sequential():
    """The speculative-verify window commit (ISSUE 11): one [B, S] window
    scatter through ``paged_scatter_kv`` is bit-identical to S sequential
    single-token scatters — same (page, offset) cells, same values,
    masked tails and sentinel rows dropping identically — including
    shuffled tables and rows whose windows straddle a page boundary."""
    from distributed_lion_tpu.ops.attention import paged_scatter_kv

    rng = np.random.default_rng(0)
    NB, bs, KV, hd, B, S = 6, 4, 2, 8, 3, 5
    pool = jnp.asarray(rng.standard_normal((NB, bs, KV, hd)), jnp.float32)
    # row 0: shuffled pages mid-sequence; row 1: window crosses into a
    # fresh page; row 2: SENTINEL table row (inactive slot — every write
    # must drop)
    tables = jnp.asarray([[4, 1, 3], [2, 0, 5], [NB, NB, NB]], jnp.int32)
    pos = jnp.asarray([1, 6, 0], jnp.int32)
    new = jnp.asarray(rng.standard_normal((B, S, KV, hd)), jnp.float32)
    # per-row valid COUNTS, the verify-window shape: arange(S) < counts
    counts = jnp.asarray([5, 3, 4], jnp.int32)
    valid = jnp.arange(S)[None, :] < counts[:, None]

    window = paged_scatter_kv(pool, tables, pos, new, valid)

    seq = pool
    for s in range(S):
        seq = paged_scatter_kv(seq, tables, pos + s, new[:, s:s + 1],
                               valid[:, s:s + 1])
    np.testing.assert_array_equal(np.asarray(window), np.asarray(seq))
    # the sentinel row and the masked tails never touched the pool:
    # replaying only the valid in-range writes reproduces it too
    redo = pool
    for b in range(B - 1):          # row 2 is all-sentinel: contributes 0
        for s in range(int(counts[b])):
            redo = paged_scatter_kv(redo, tables[b:b + 1], pos[b:b + 1] + s,
                                    new[b:b + 1, s:s + 1])
    np.testing.assert_array_equal(np.asarray(window), np.asarray(redo))


def test_block_tables_shrink_is_exact_inverse_of_grow():
    """``BlockTables.shrink`` — the speculative rollback primitive — is
    the exact inverse of ``grow``: after an optimistic grow for k draft
    tokens and a rollback to the accepted length, the tables, owned
    counts AND the LIFO free-list order are bit-identical to having grown
    to the accepted length directly (what a token-by-token run holds)."""
    import copy

    def state(bt):
        return (bt.tables.copy(), bt.owned.copy(), list(bt._free))

    ref = BlockTables(num_blocks=12, block_size=4, max_seqs=3,
                      max_blocks_per_seq=4)
    # interleaved multi-slot history so page ownership is shuffled
    assert ref.grow(0, 6) and ref.grow(1, 3) and ref.grow(2, 9)
    spec = copy.deepcopy(ref)

    # token-by-token: slot 0 advances to 9 total entries (one new page)
    assert ref.grow(0, 9)
    # speculative: slot 0 optimistically grows for a k=7 window (to 13 →
    # two extra pages), then a partial accept rolls back to 9
    assert spec.grow(0, 13)
    assert spec.owned[0] > ref.owned[0]
    freed = spec.shrink(0, 9)
    assert freed == 1
    for a, b in zip(state(spec), state(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # shrink to a length needing all owned pages (or more) is a no-op
    assert spec.shrink(0, 9) == 0 and spec.shrink(0, 100) == 0
    for a, b in zip(state(spec), state(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # full-round-trip: rollback to the pre-speculation state frees in
    # reverse allocation order, so a subsequent grow reuses the SAME pages
    before = state(spec)
    assert spec.grow(0, 16)
    spec.shrink(0, 9)
    for a, b in zip(state(spec), before):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------- refcounts / CoW / prefix cache
def test_block_tables_share_refcounts_and_cow():
    """ISSUE 13: grow mints ref-1 pages; share bumps refs; shrink/free
    over shared pages release refs without freeing; cow swaps in a fresh
    private page and the original survives for its other holders."""
    bt = BlockTables(num_blocks=8, block_size=4, max_seqs=3,
                     max_blocks_per_seq=4)
    assert bt.grow(0, 8)                       # slot 0: 2 pages
    run = [int(bt.tables[0, 0]), int(bt.tables[0, 1])]
    assert all(bt.refs[p] == 1 for p in run)
    bt.share(1, run)                           # slot 1 shares both
    assert all(bt.refs[p] == 2 for p in run)
    assert bt.grow(1, 12)                      # + 1 private page
    free_before = bt.free_blocks
    assert bt.shrink(1, 8) == 1                # private page freed...
    assert bt.free_blocks == free_before + 1
    assert bt.shrink(1, 4) == 0                # ...shared page only deref'd
    assert bt.refs[run[1]] == 1 and bt.free_blocks == free_before + 1
    # cow: slot 1's remaining shared page becomes private
    bt.share(2, [run[0]])
    assert bt.refs[run[0]] == 3
    pair = bt.cow(2, 0)
    assert pair is not None and pair[0] == run[0]
    assert bt.refs[run[0]] == 2 and bt.refs[pair[1]] == 1
    assert int(bt.tables[2, 0]) == pair[1]
    # evicting the sharer frees only what nobody else holds
    assert bt.free_slot(2) == 1                # the cow'd private page
    assert bt.free_slot(1) == 0                # run[0] still owned by slot 0
    assert bt.free_slot(0) == 2                # now both physically free
    assert bt.free_blocks == bt.num_blocks


def test_block_tables_refcount_fuzz_vs_reference():
    """Property fuzz: random grow/shrink/share/cow/free sequences against
    a dict-based reference counter — refcounts agree exactly, the free
    list never holds a live page or a duplicate, and pages are conserved
    (free + live == pool) at every step."""
    rng = np.random.default_rng(42)
    bt = BlockTables(num_blocks=24, block_size=4, max_seqs=4,
                     max_blocks_per_seq=6)
    refs = {}          # page -> count (the reference counter)
    slot_pages = {s: [] for s in range(4)}
    cache_refs = []    # pages the "cache" holds a ref on

    def check():
        live = {p for p, c in refs.items() if c > 0}
        flat = [p for grp in bt._free for p in grp]
        free = set(flat)
        assert len(flat) == len(free), "duplicate page on free list"
        assert not (live & free), "live page on the free list"
        assert live | free == set(range(bt.num_blocks)), "page leaked"
        for p in range(bt.num_blocks):
            assert bt.refs[p] == refs.get(p, 0), f"refcount drift page {p}"

    for _ in range(600):
        op = rng.choice(["grow", "shrink", "free", "share", "cow",
                         "cache_ref", "cache_drop", "crash"])
        s = int(rng.integers(0, 4))
        if op == "crash":
            # mid-fuzz replica crash (ISSUE 14): a random subset of slots
            # — the dead replica's residents — mass-free at once, the way
            # a migration releases them. Pages the SURVIVORS still hold
            # (other slots' shared runs, the cache's refs) must survive
            # the mass free; the post-op check pins exact refcounts, no
            # live page on the free list, and page conservation.
            victims = [v for v in range(4) if rng.integers(0, 2)]
            for v in victims:
                for p in slot_pages[v]:
                    refs[p] -= 1
                bt.free_slot(v)
                slot_pages[v] = []
            check()
            continue
        if op == "grow":
            n = int(rng.integers(1, bt.max_blocks_per_seq * bt.block_size))
            before = [int(p) for p in bt.tables[s, :bt.owned[s]]]
            if bt.grow(s, n):
                now = [int(p) for p in bt.tables[s, :bt.owned[s]]]
                for p in now[len(before):]:
                    refs[p] = refs.get(p, 0) + 1
                slot_pages[s] = now
        elif op == "shrink":
            n = int(rng.integers(0, bt.max_blocks_per_seq * bt.block_size))
            keep = bt.blocks_for(n)
            dropped = slot_pages[s][keep:] if keep < len(slot_pages[s]) \
                else []
            bt.shrink(s, n)
            for p in dropped:
                refs[p] -= 1
            slot_pages[s] = slot_pages[s][:min(keep, len(slot_pages[s]))]
        elif op == "free":
            for p in slot_pages[s]:
                refs[p] -= 1
            bt.free_slot(s)
            slot_pages[s] = []
        elif op == "share":
            donor = int(rng.integers(0, 4))
            if slot_pages[s] or not slot_pages[donor]:
                continue
            k = int(rng.integers(1, len(slot_pages[donor]) + 1))
            run = slot_pages[donor][:k]
            bt.share(s, run)
            for p in run:
                refs[p] += 1
            slot_pages[s] = list(run)
        elif op == "cow":
            shared = [i for i, p in enumerate(slot_pages[s])
                      if refs.get(p, 0) > 1]
            if not shared:
                continue
            i = shared[0]
            pair = bt.cow(s, i * bt.block_size)
            if pair is None:
                continue
            old, new = pair
            refs[old] -= 1
            refs[new] = refs.get(new, 0) + 1
            slot_pages[s][i] = new
        elif op == "cache_ref":
            if not slot_pages[s]:
                continue
            p = slot_pages[s][0]
            bt.add_ref(p)
            refs[p] += 1
            cache_refs.append(p)
        elif op == "cache_drop":
            if not cache_refs:
                continue
            p = cache_refs.pop()
            bt.release_page(p)
            refs[p] -= 1
        check()
    # drain everything: the pool must come back whole
    for s in range(4):
        for p in slot_pages[s]:
            refs[p] -= 1
        bt.free_slot(s)
    for p in cache_refs:
        refs[p] -= 1
        bt.release_page(p)
    check()
    assert bt.free_blocks == bt.num_blocks


def test_paged_copy_then_scatter_matches_scatter_after_deep_copy():
    """The CoW device primitive: copying a page with paged_copy_pages and
    then multi-token-scattering into the copy is bit-identical to a host
    deep copy followed by the same scatter — including sentinel-padded
    copy rows (dropped) and a window straddling the copied page."""
    from distributed_lion_tpu.ops.attention import (
        paged_copy_pages,
        paged_scatter_kv,
    )

    rng = np.random.default_rng(8)
    NB, bs, KV, hd = 6, 4, 2, 8
    pool = jnp.asarray(rng.standard_normal((NB, bs, KV, hd)), jnp.float32)
    layers = [{"k": pool, "v": pool * 2.0}]
    # copy page 1 -> 4, sentinel-pad the rest of the copy list
    src = jnp.asarray([1, NB, NB], jnp.int32)
    dst = jnp.asarray([4, NB, NB], jnp.int32)
    copied = paged_copy_pages(layers, src, dst)
    ref = {k: np.asarray(layers[0][k]).copy() for k in ("k", "v")}
    for k in ref:
        ref[k][4] = ref[k][1]
    for k in ("k", "v"):
        np.testing.assert_array_equal(np.asarray(copied[0][k]), ref[k])
    # scatter a 3-token window into the COPIED page (table points at 4)
    tables = jnp.asarray([[0, 4, 2]], jnp.int32)
    pos = jnp.asarray([5], jnp.int32)  # straddles pages 1->2 of the row
    new = jnp.asarray(rng.standard_normal((1, 3, KV, hd)), jnp.float32)
    got = paged_scatter_kv(copied[0]["k"], tables, pos, new)
    want = paged_scatter_kv(jnp.asarray(ref["k"]), tables, pos, new)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_prefix_cache_match_register_reclaim():
    from distributed_lion_tpu.serve.kv_cache import PrefixCache

    bt = BlockTables(num_blocks=16, block_size=4, max_seqs=4,
                     max_blocks_per_seq=4)
    pc = PrefixCache(bt)
    prompt = list(range(100, 110))             # 10 tokens: 2 full + 2 tail
    assert bt.grow(0, len(prompt) + 1)
    assert pc.register(0, prompt) == 3         # 2 full + 1 partial page
    row = [int(p) for p in bt.tables[0, :3]]
    assert all(bt.refs[p] == 2 for p in row)   # slot + cache
    # identical prompt: shares both full pages AND the partial's prefix
    pages, covered = pc.match(list(prompt))
    assert pages == row and covered == 9       # capped at L-1
    # shared-prefix-different-tail: full pages only
    pages, covered = pc.match(prompt[:8] + [999, 998])
    assert pages == row[:2] and covered == 8
    # divergence inside the first page: no hit
    assert pc.match([1, 2, 3, 4, 5, 6, 7, 8]) == ([], 0)
    # eviction of the chain root drops the descendants too — no leaks
    bt.free_slot(0)
    freed = pc.reclaim(bt.num_blocks)
    assert freed == 3 and bt.free_blocks == bt.num_blocks
    assert pc.match(list(prompt)) == ([], 0)


def _shared_workload(cfg, n=8, seed=11, max_new=6):
    rng = np.random.default_rng(seed)
    sys_p = list(map(int, rng.integers(1, cfg.vocab_size, 13)))
    prompts = [sys_p + list(map(int, rng.integers(1, cfg.vocab_size, 3)))
               for _ in range(n - 3)]
    prompts += [list(sys_p) for _ in range(3)]  # fully identical prompts
    return [Request(req_id=i, tokens=list(t), max_new_tokens=max_new,
                    seed=i) for i, t in enumerate(prompts)]


@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_shared_prefix_engine_matches_unshared(sampling):
    """THE prefix-sharing pin (ISSUE 13): a shared-system-prompt workload
    through the prefix-cache engine produces outputs identical to the
    unshared engine — greedy and sampled — while allocating strictly
    fewer physical pages and actually hitting the cache."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    samp = (dict(temperature=0.0) if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    reqs = _shared_workload(cfg)
    plain = _engine(params, cfg, num_blocks=64, **samp)
    shared = _engine(params, cfg, num_blocks=64, prefix_cache=True, **samp)
    base = plain.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                              r.seed) for r in reqs])
    got = shared.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                              r.seed) for r in reqs])
    for r in reqs:
        assert got[r.req_id].tokens == base[r.req_id].tokens, r.req_id
        assert got[r.req_id].reason == base[r.req_id].reason
    assert shared.stats["prefix_hits"] > 0
    assert shared.stats["cow_copies"] > 0
    assert shared.tables.pages_allocated < plain.tables.pages_allocated
    # pool accounting after drain: only cache-held pages remain physical,
    # and every live ref belongs to the cache
    assert all(s is None for s in shared.slots)
    assert (shared.tables.physical_pages + shared.tables.free_blocks
            == shared.tables.num_blocks)
    assert int(shared.tables.refs.sum()) == shared.tables.physical_pages


def test_shared_prefix_staggered_matches_solo():
    """Continuous batching × prefix sharing: staggered arrivals through
    the shared engine still equal solo runs of each request (the cache
    only changes which PHYSICAL pages hold the same bytes)."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    reqs = _shared_workload(cfg, n=5)
    shared = _engine(params, cfg, num_blocks=64, prefix_cache=True)
    got = shared.run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs],
        arrivals={0: 0, 1: 1, 2: 1, 3: 3, 4: 5})
    for r in reqs:
        solo = _engine(params, cfg, num_blocks=64, prefix_cache=True).run(
            [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)])
        assert got[r.req_id].tokens == solo[r.req_id].tokens, r.req_id


def test_evicting_sharer_frees_zero_physical_pages():
    """Overflow-evicting a request whose pages are all shared hands back
    refs, not pages — the engine's freed_pages ledger records what
    physically returned (the satellite's accounting pin)."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    eng = _engine(params, cfg, num_blocks=64, prefix_cache=True)
    prompt = list(range(1, 14))                # 13 tokens: 3 full + tail
    first = eng.run([Request("a", list(prompt), 4, 0)])
    assert first["a"].reason == "length"
    freed_before = eng.stats["freed_pages"]
    phys_before = eng.tables.physical_pages
    # the second identical request shares the cached run; evict it right
    # after admit by giving it a 1-token budget (finishes at prefill)
    out = eng.run([Request("b", list(prompt), 1, 0)])
    assert out["b"].reason == "length"
    # b's only private page was its CoW'd boundary page (cache keeps the
    # original), so at most ONE physical page came back — and none of the
    # shared run did
    freed_b = eng.stats["freed_pages"] - freed_before
    assert freed_b <= 1, freed_b
    assert eng.tables.physical_pages == phys_before
    assert eng.stats["prefix_hits"] >= 1


def test_prefix_cache_reclaims_under_pool_pressure():
    """A pool exhausted by CACHED pages is not full: admission reclaims
    LRU chains instead of rejecting/overflowing, and the request that
    triggered the reclaim completes normally."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    # pool of 8 pages, block 4: one 13-token prompt + gen occupies ~4,
    # all cache-registered after it drains; a second DISJOINT prompt then
    # needs more pages than remain un-cached
    eng = _engine(params, cfg, max_seqs=1, block_size=4,
                  max_blocks_per_seq=8, num_blocks=8, prefix_cache=True)
    rng = np.random.default_rng(2)
    p1 = list(map(int, rng.integers(1, cfg.vocab_size, 13)))
    p2 = list(map(int, rng.integers(1, cfg.vocab_size, 14)))
    out1 = eng.run([Request("a", p1, 4, 0)])
    assert out1["a"].reason == "length"
    assert eng.tables.physical_pages > 0       # the cache holds a's pages
    out2 = eng.run([Request("b", p2, 4, 0)])
    assert out2["b"].reason == "length"        # not overflow/rejected
    assert eng.stats["reclaimed_pages"] > 0
    # outputs unaffected by the eviction dance
    plain = _engine(params, cfg, max_seqs=1, block_size=4,
                    max_blocks_per_seq=8, num_blocks=8)
    assert plain.run([Request("b", list(p2), 4, 0)])["b"].tokens \
        == out2["b"].tokens


def test_cow_under_pool_pressure_after_reclaim_unshares():
    """Regression (review round): when the CoW fallback's reclaim drops
    the cache's own ref on the page being CoW'd, the page is PRIVATE now
    and needs no copy — the old unconditional cow retry tripped its
    shared-page precondition and crashed the engine on exactly the
    pool-pressure path the fallback exists to handle."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    # pool of 3 pages, page-aligned 8-token prompt: request a registers 2
    # cached pages; the identical request b shares both, takes the last
    # free page, and its boundary CoW finds the pool dry
    eng = _engine(params, cfg, max_seqs=1, block_size=4,
                  max_blocks_per_seq=4, num_blocks=3, prefix_cache=True)
    prompt = list(range(1, 9))
    out_a = eng.run([Request("a", list(prompt), 2, 0)])
    out_b = eng.run([Request("b", list(prompt), 2, 0)])  # crashed before
    assert out_b["b"].reason == out_a["a"].reason == "length"
    assert out_b["b"].tokens == out_a["a"].tokens  # same seed, greedy
    # outputs still match the unshared engine on the same pool geometry
    plain = _engine(params, cfg, max_seqs=1, block_size=4,
                    max_blocks_per_seq=4, num_blocks=3)
    assert plain.run([Request("b", list(prompt), 2, 0)])["b"].tokens \
        == out_b["b"].tokens


def test_request_file_prefix_group_roundtrip(tmp_path):
    """serve/api: the optional prefix_group tag is validated strictly and
    echoed on the response record."""
    from distributed_lion_tpu.serve import api

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    inp = tmp_path / "requests.jsonl"
    inp.write_text(
        '{"id": "a", "tokens": [1, 2, 3], "max_new_tokens": 2, '
        '"prefix_group": "sys-v1"}\n'
        '{"id": "b", "tokens": [4, 5], "max_new_tokens": 2}\n')
    out = tmp_path / "responses.jsonl"
    records = api.serve_request_file(
        _engine(params, cfg, prefix_cache=True), str(inp), str(out))
    assert records[0]["prefix_group"] == "sys-v1"
    assert "prefix_group" not in records[1]
    # strict validation: wrong type and empty string both refuse loudly
    for bad in ('{"id": "x", "tokens": [1], "prefix_group": 7}\n',
                '{"id": "x", "tokens": [1], "prefix_group": ""}\n'):
        p = tmp_path / "bad.jsonl"
        p.write_text(bad)
        with pytest.raises(ValueError, match="prefix_group"):
            api.load_request_file(str(p))


def test_run_serve_builds_prefix_cache_and_ep_with_moe(monkeypatch):
    """cli satellite (ISSUE 15): --prefix_cache AND --serve_ep now build
    for MoE checkpoints — the old loud refusals are replaced by the
    pinned equivalences (tests/test_moe_serve.py); this pins the CLI
    surface actually reaches the composed engine."""
    import distributed_lion_tpu.cli.run_generate as rg
    from distributed_lion_tpu.cli.run_serve import (
        ServeArguments,
        build_engine,
    )

    cfg = GPT2Config.tiny(moe_experts=2)
    params = gpt2_init(jax.random.key(0), cfg)
    monkeypatch.setattr(rg, "build",
                        lambda a: (None, cfg, params, None, None))
    eng = build_engine(rg.GenerateArguments(),
                       ServeArguments(prefix_cache=True, serve_ep=2))[1]
    assert eng.prefix is not None and eng.cfg.ep == 2


# ------------------------------------------------------- host allocator
def test_block_tables_alloc_free_invariants():
    bt = BlockTables(num_blocks=8, block_size=4, max_seqs=3,
                     max_blocks_per_seq=4)
    assert bt.free_blocks == 8 and bt.max_tokens_per_seq == 16
    assert bt.grow(0, 5)            # 2 pages
    assert bt.owned[0] == 2 and bt.free_blocks == 6
    assert bt.grow(0, 5)            # idempotent: no new pages
    assert bt.free_blocks == 6
    assert bt.grow(1, 16)           # 4 pages — slot 1 maxes its table
    assert not bt.grow(1, 17)       # beyond the table width
    assert bt.free_blocks == 2
    # all-or-nothing: slot 2 wants 3 pages, pool has 2 — NOTHING allocates
    assert not bt.grow(2, 12)
    assert bt.owned[2] == 0 and bt.free_blocks == 2
    assert bt.find_free_slot() == 2
    freed = bt.free_slot(1)
    assert freed == 4 and bt.free_blocks == 6
    assert (bt.tables[1] == bt.sentinel).all()
    assert bt.grow(2, 12)           # now it fits


# ------------------------------------------- continuous batching == solo
def _tiny_requests(cfg, n=5, seed=3, max_new=8):
    rng = np.random.default_rng(seed)
    lens = (3, 9, 5, 14, 2, 7, 11)[:n]
    return [Request(req_id=f"r{i}",
                    tokens=list(map(int, rng.integers(1, cfg.vocab_size, L))),
                    max_new_tokens=max_new, seed=i)
            for i, L in enumerate(lens)]


def _engine(params, cfg, **kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    base.update(kw)
    return ServingEngine(ServeModel.for_gpt2(params, cfg), ServeConfig(**base))


@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_staggered_continuous_batching_matches_solo(sampling):
    """The acceptance pin: a continuous-batching run with staggered
    arrivals produces per-request outputs identical to solo runs — slots,
    neighbors, and arrival order must not leak into any request (per-slot
    PRNG keys are (request seed, token index), batch-independent)."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    samp = (dict(temperature=0.0) if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    reqs = _tiny_requests(cfg)
    batched = _engine(params, cfg, **samp).run(
        [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
         for r in reqs],
        arrivals={"r0": 0, "r1": 1, "r2": 1, "r3": 3, "r4": 5})
    for r in reqs:
        solo = _engine(params, cfg, **samp).run(
            [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)])
        assert batched[r.req_id].tokens == solo[r.req_id].tokens, r.req_id
        assert batched[r.req_id].reason == solo[r.req_id].reason


def test_engine_greedy_matches_dense_generate():
    """Greedy decode through the paged engine == the dense-KV generate at
    MATCHED attended length (max_len == pages-per-seq * block_size):
    bit-identical logits imply identical tokens."""
    from functools import partial

    from distributed_lion_tpu.models.generate import generate

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(4), cfg)
    prompts = [list(map(int, r)) for r in np.asarray(
        _tokens(cfg.vocab_size, 3, 6, seed=9))]
    new = 8
    dense = np.asarray(generate(
        partial(lambda c, p, t, k, pos, off=None:
                gpt2_decode(p, t, c, k, pos, off), cfg),
        partial(gpt2_init_cache, cfg), params,
        jnp.asarray(prompts, jnp.int32), new, max_len=4 * 8))
    eng = _engine(params, cfg, block_size=4, max_blocks_per_seq=8)
    done = eng.run([Request(req_id=i, tokens=t, max_new_tokens=new, seed=0)
                    for i, t in enumerate(prompts)])
    for i in range(len(prompts)):
        assert list(dense[i]) == done[i].tokens, i


def test_engine_eos_evicts_and_frees_pages():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    reqs = _tiny_requests(cfg, n=2, max_new=16)
    # learn each request's first greedy token, then declare it EOS
    first = {r.req_id: _engine(params, cfg).run(
        [Request(r.req_id, list(r.tokens), 1, 0)])[r.req_id].tokens[0]
        for r in reqs}
    eos = first[reqs[0].req_id]
    eng = _engine(params, cfg, eos_id=eos)
    done = eng.run([Request(r.req_id, list(r.tokens), 16, 0) for r in reqs])
    assert done[reqs[0].req_id].reason == "eos"
    assert done[reqs[0].req_id].tokens[-1] == eos
    # every page returned to the pool after the workload drains
    assert eng.tables.free_blocks == eng.cfg.resolved_num_blocks()
    assert all(s is None for s in eng.slots)


def test_engine_overflow_truncates_loudly():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    eng = _engine(params, cfg, max_seqs=2, block_size=4, max_blocks_per_seq=2)
    toks = list(map(int, np.asarray(_tokens(cfg.vocab_size, 1, 5, seed=1))[0]))
    done = eng.run([Request("big", toks, 64, 0)])
    assert done["big"].reason == "overflow"
    # the cache holds 8 slots: 5 prompt + 3 decode writes → 4 generated
    # tokens (the overflowing write is the one that could not fit)
    assert len(done["big"].tokens) == 4
    assert eng.tables.free_blocks == eng.cfg.resolved_num_blocks()


def test_engine_refuses_geometry_past_position_budget():
    """A page horizon beyond the model's trained position budget (gpt2's
    learned wpe rows) must fail at build, not alias silently at slot 129."""
    cfg = GPT2Config.tiny()  # n_ctx = 128
    params = gpt2_init(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="position budget"):
        _engine(params, cfg, block_size=16, max_blocks_per_seq=16)


def test_moe_checkpoints_serve_through_the_paged_engine():
    """ISSUE 15: the PR 9 refusals are LIFTED — valid-lane masked,
    no-drop MoE routing makes pad lanes consume zero expert capacity, so
    ServeModel build, gpt2_decode_paged and the left-padded gpt2_decode
    offset path all serve MoE checkpoints (the equivalence pins live in
    tests/test_moe_serve.py; this pins that no refusal remains)."""
    cfg = GPT2Config.tiny(moe_experts=2)
    params = gpt2_init(jax.random.key(0), cfg)
    model = ServeModel.for_gpt2(params, cfg)
    eng = ServingEngine(model, ServeConfig(max_seqs=2, block_size=4,
                                           max_blocks_per_seq=4))
    done = eng.run([Request("m", [1, 2, 3], 4, 0)])
    assert len(done["m"].tokens) == 4
    pages = [{k: jnp.zeros((4, 4, cfg.n_head, cfg.head_dim),
                           cfg.compute_dtype) for k in ("k", "v")}
             for _ in range(cfg.n_layer)]
    logits, _ = gpt2_decode_paged(params, jnp.ones((1, 4), jnp.int32), cfg,
                                  pages,
                                  jnp.asarray([[0, 1, 2, 3]], jnp.int32),
                                  jnp.zeros((1,), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()
    logits, _ = gpt2_decode(params, jnp.ones((2, 4), jnp.int32), cfg,
                            gpt2_init_cache(cfg, 2, 8), 0,
                            jnp.asarray([0, 1], jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


def test_engine_rejects_impossible_prompt():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    eng = _engine(params, cfg, max_seqs=2, block_size=4, max_blocks_per_seq=2)
    toks = list(map(int, np.asarray(_tokens(cfg.vocab_size, 1, 8, seed=1))[0]))
    done = eng.run([Request("toolong", toks, 4, 0)])  # 8 == cap, no room
    assert done["toolong"].reason == "rejected"
    assert done["toolong"].tokens == []


def test_prefill_fairness_cap():
    """A small cap admits one prompt per tick (the decode batch keeps
    moving); an uncapped engine admits the whole burst at tick 0 — and
    the cap never changes WHAT is generated, only when."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    reqs = _tiny_requests(cfg, n=4, max_new=4)

    def run(cap):
        eng = _engine(params, cfg, max_seqs=4, prefill_cap_tokens=cap)
        out = eng.run([Request(r.req_id, list(r.tokens), 4, r.seed)
                       for r in reqs])
        return eng.stats, out

    s_small, out_small = run(4)        # one 4/8/16-token bucket per tick
    s_big, out_big = run(1 << 30)
    assert s_big["ticks"] < s_small["ticks"]
    for r in reqs:
        assert out_small[r.req_id].tokens == out_big[r.req_id].tokens


def test_nf4_engine_serves_and_shrinks_weights():
    """quant='nf4' serves from packed codes (ops/quant) — outputs stay
    plausible (right count, in-vocab) and the weight tree actually
    shrinks below a third of the bf16 bytes."""
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    eng = _engine(params, cfg, quant="nf4")
    n_params = sum(x.size for x in jax.tree.leaves(params))
    assert weight_bytes(eng.params) * 3 < 2 * n_params
    done = eng.run([Request("q", [1, 2, 3, 4], 6, 0)])
    assert len(done["q"].tokens) == 6
    assert all(0 <= t < cfg.vocab_size for t in done["q"].tokens)


def test_engine_journal_spans(tmp_path):
    """serve/admit, serve/prefill, serve/decode_tick, serve/evict ride
    the installed run journal (PR 7), schema-valid."""
    import importlib.util

    from distributed_lion_tpu.train import journal as journal_mod

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    jrnl = journal_mod.Journal(str(tmp_path))
    journal_mod.install(jrnl)
    try:
        _engine(params, cfg).run(
            [Request("a", [1, 2, 3], 3, 0), Request("b", [4, 5], 3, 0)])
    finally:
        journal_mod.uninstall(jrnl)
        jrnl.close()
    names = {r["name"] for r in jrnl.tail() if r["kind"] == "span"}
    assert {"serve/admit", "serve/prefill", "serve/decode_tick",
            "serve/evict"} <= names, names
    spec = importlib.util.spec_from_file_location(
        "vm_serve", os.path.join(REPO, "scripts", "validate_metrics.py"))
    vm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vm)
    assert vm.validate_journal_file(
        str(tmp_path / "journal_rank0.jsonl")) == []


# ------------------------------------------------------------------ api
def test_request_file_roundtrip(tmp_path):
    from distributed_lion_tpu.serve import api

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    reqs = [{"id": "a", "tokens": [1, 2, 3], "max_new_tokens": 4},
            {"id": "b", "tokens": [9, 8], "max_new_tokens": 4,
             "arrival_tick": 2, "seed": 5}]
    inp = tmp_path / "requests.jsonl"
    inp.write_text("".join(json.dumps(r) + "\n" for r in reqs))
    out = tmp_path / "responses.jsonl"
    records = api.serve_request_file(_engine(params, cfg), str(inp), str(out))
    got = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert got == records
    assert [r["id"] for r in got] == ["a", "b"]
    assert all(r["n_generated"] == 4 for r in got)
    # a request with neither tokens nor prompt+tokenizer fails LOUDLY
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"id": "x", "max_new_tokens": 2}\n')
    with pytest.raises(ValueError, match="tokens"):
        api.load_request_file(str(bad))


def test_run_serve_cli_smoke(tmp_path, capsys):
    from distributed_lion_tpu.cli.run_serve import main

    reqs = tmp_path / "requests.jsonl"
    reqs.write_text('{"id": "r1", "prompt": "ab", "max_new_tokens": 3}\n')
    out = tmp_path / "responses.jsonl"
    records = main(["--model_family", "gpt2", "--model_name", "tiny",
                    "--requests", str(reqs), "--out", str(out),
                    "--temperature", "0", "--max_seqs", "2",
                    "--block_size", "4"])
    assert len(records) == 1 and records[0]["n_generated"] == 3
    assert json.loads(out.read_text())["id"] == "r1"


# ------------------------------------------------- the evidence artifact
def test_banked_serving_artifact_passes_stage():
    """The committed CPU smoke artifact satisfies the serving evidence
    stage (schema + bit-identity markers + tokens/s floor at every
    required batch + the NF4 byte story) — the same gate the runbook's
    on-chip recapture must clear."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_serve", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    assert os.path.exists(ce.SERVE_ARTIFACT), "banked artifact missing"
    assert ce.serving_ok()
    with open(ce.SERVE_ARTIFACT) as f:
        doc = json.load(f)
    assert {r["batch"] for r in doc["decode"]} >= set(ce.SERVE_BATCHES)


def test_serving_stage_rejects_bad_artifacts(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_serve2", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    # flipped bit-identity marker
    doc = json.loads(json.dumps(good))
    doc["bit_identity"]["paged_vs_dense"] = False
    p = tmp_path / "serving.json"
    p.write_text(json.dumps(doc))
    assert not ce.serving_ok(str(p))
    # missing required batch row
    doc = json.loads(json.dumps(good))
    doc["decode"] = [r for r in doc["decode"] if r["batch"] != 128]
    p.write_text(json.dumps(doc))
    assert not ce.serving_ok(str(p))
    # throughput floor
    doc = json.loads(json.dumps(good))
    doc["decode"][0]["tokens_per_sec_per_chip"] = 1.0
    p.write_text(json.dumps(doc))
    assert not ce.serving_ok(str(p))
    # quantization story: nf4 bytes not actually small
    doc = json.loads(json.dumps(good))
    for r in doc["decode"]:
        r["weight_bytes_nf4"] = r["weight_bytes_bf16"]
    p.write_text(json.dumps(doc))
    assert not ce.serving_ok(str(p))
    # schema violation (NaN token) caught via validate_metrics delegation
    p.write_text(json.dumps(good).replace(
        str(good["decode"][0]["ms_per_tick"]), "NaN", 1))
    assert not ce.serving_ok(str(p))


def test_banked_artifact_passes_tp_serving_stage():
    """The committed CPU artifact (captured under DLION_PLATFORM=cpu8 so
    the tp>1 legs exist) satisfies the ISSUE 13 tp_serving stage: strict
    schema, all five identity markers, a tp>=2 row above the tokens/s
    floor, and prefix_mem_ratio <= 0.15 on the 256-request
    shared-system-prompt workload — the gate runbook stage 5k re-judges
    after the on-chip recapture."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_tp", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    assert ce.tp_serving_ok()
    with open(ce.SERVE_ARTIFACT) as f:
        doc = json.load(f)
    sec = doc["tp_serving"]
    assert any(r["tp"] >= 2 for r in sec["rows"])
    assert sec["prefix"]["requests"] >= 256
    assert sec["prefix"]["prefix_mem_ratio"] <= 0.15


def test_tp_serving_stage_rejects_bad_artifacts(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_tp2", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "serving.json"

    def reject(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        p.write_text(json.dumps(doc))
        assert not ce.tp_serving_ok(str(p))

    # artifact predates ISSUE 13 entirely (also a schema violation now)
    reject(lambda d: d.pop("tp_serving"))
    # each identity marker flips the stage
    for k in ("tp1_vs_unsharded", "tpN_vs_unsharded",
              "shared_vs_unshared_greedy", "shared_vs_unshared_sampled",
              "shared_vs_unshared_speculative"):
        reject(lambda d, k=k: d["tp_serving"]["markers"].update({k: False}))
    # no multi-chip row / throughput floor / memory story
    reject(lambda d: d["tp_serving"].update(
        rows=[r for r in d["tp_serving"]["rows"] if r["tp"] < 2]))
    reject(lambda d: d["tp_serving"]["rows"][0].update(
        tokens_per_sec_per_chip=1.0))
    reject(lambda d: d["tp_serving"]["prefix"].update(
        prefix_mem_ratio=0.5))
    reject(lambda d: d["tp_serving"]["prefix"].update(requests=8))
    # strict schema: a non-int page count (validate_metrics delegation)
    reject(lambda d: d["tp_serving"]["prefix"].update(
        physical_pages="many"))
    # the untouched artifact still passes from the tmp copy
    p.write_text(json.dumps(good))
    assert ce.tp_serving_ok(str(p))


def test_banked_artifact_passes_speculative_stage():
    """The committed CPU artifact also satisfies the ISSUE 11 speculative
    stage (strict frontier schema, both live-recomputed identity markers,
    a baseline + both drafters on both workloads, ngram accept_rate > 0
    on the repetitive traffic) — the gate runbook stage 5j re-judges after
    the on-chip recapture."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_spec", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    assert ce.speculative_ok()


def test_speculative_stage_rejects_bad_artifacts(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_spec2", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "serving.json"

    def reject(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        p.write_text(json.dumps(doc))
        assert not ce.speculative_ok(str(p))

    # artifact predates ISSUE 11 entirely
    reject(lambda d: d.pop("speculative"))
    # a flipped live-recomputed identity marker
    reject(lambda d: d["speculative"]["markers"].update(
        greedy_vs_plain=False))
    reject(lambda d: d["speculative"]["markers"].update(
        sampled_vs_stream=False))
    # schema: accept_rate outside [0, 1] (validate_metrics delegation)
    reject(lambda d: d["speculative"]["frontier"][1].update(
        accept_rate=1.5))
    # frontier coverage: no non-speculative baseline to read against /
    # a drafter missing on one workload
    reject(lambda d: d["speculative"].update(frontier=[
        r for r in d["speculative"]["frontier"] if r["drafter"] != "none"]))
    reject(lambda d: d["speculative"].update(frontier=[
        r for r in d["speculative"]["frontier"]
        if not (r["drafter"] == "ngram" and r["workload"] == "random")]))
    # the n-gram drafter must EARN accept_rate > 0 on repetitive traffic
    def zero_ngram(d):
        for r in d["speculative"]["frontier"]:
            if r["drafter"] == "ngram" and r["workload"] == "repetitive":
                r["accept_rate"] = 0.0
    reject(zero_ngram)
    # the untouched artifact still passes from the tmp copy
    p.write_text(json.dumps(good))
    assert ce.speculative_ok(str(p))