"""Distributed vote-Lion property tests on 8 virtual devices (SURVEY §4):
(a) W=1 ≡ local Lion; (b) replica consistency; (c) permutation invariance;
(d) wire paths agree; (e) tie→−1; (f) stochastic path; (g) drop-out vote."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lion_tpu.optim import distributed_lion, init_global_state, lion
from distributed_lion_tpu.optim.sharded import make_sharded_step, shard_state
from distributed_lion_tpu.parallel import collectives, make_mesh
from distributed_lion_tpu.parallel.mesh import DATA_AXIS


def _params():
    rng = np.random.default_rng(7)
    return {
        "w": jnp.asarray(rng.normal(size=(4, 6)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(5,)).astype(np.float32)),
    }


def _stacked_grads(world, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(world, 4, 6)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(world, 5)).astype(np.float32)),
    }


def _run_steps(mesh, opt, params, stacked_grads, state, n=1):
    step = make_sharded_step(opt, mesh)
    for _ in range(n):
        params, state = step(params, stacked_grads, state)
    return params, state


@pytest.mark.parametrize("wire", ["sign_psum", "packed_allgather", "packed_a2a"])
def test_world1_matches_local(wire):
    mesh = make_mesh(data=1, devices=jax.devices()[:1])
    params = _params()
    grads = _stacked_grads(1)
    opt = distributed_lion(learning_rate=0.01, weight_decay=0.1, wire=wire)
    state = shard_state(init_global_state(opt, params, world=1), mesh)
    new_p, _ = _run_steps(mesh, opt, params, grads, state)

    # Local Lion on the same (single-worker) gradients. With W=1 the vote of
    # one worker IS its sign (grads here are nonzero, so sign∈{±1} and the
    # >0 encoding agrees with true sign).
    lopt = lion(learning_rate=0.01, weight_decay=0.1)
    local_g = jax.tree.map(lambda g: g[0], grads)
    exp_p, _ = lopt.step(params, local_g, lopt.init(params))
    for k in params:
        np.testing.assert_allclose(np.asarray(new_p[k]), np.asarray(exp_p[k]), rtol=1e-6)


@pytest.mark.parametrize("wire", ["sign_psum", "packed_allgather", "packed_a2a"])
def test_replica_consistency_and_vote_semantics(wire):
    """All workers apply the identical elected update; the election matches a
    numpy majority vote of the per-worker signs."""
    mesh = make_mesh(data=8)
    params = _params()
    grads = _stacked_grads(8)
    lr = 0.01
    opt = distributed_lion(learning_rate=lr, weight_decay=0.0, wire=wire)
    state = shard_state(init_global_state(opt, params, world=8), mesh)
    new_p, new_state = _run_steps(mesh, opt, params, grads, state)

    for k in params:
        votes = np.asarray(grads[k]) > 0          # m=0 → u=(1-b1)*g → vote g>0
        count = votes.sum(axis=0)
        elected = np.where(count * 2 > 8, 1.0, -1.0)   # tie→−1
        exp = np.asarray(params[k]) - lr * elected
        np.testing.assert_allclose(np.asarray(new_p[k]), exp, rtol=1e-6)
        # momentum is per-worker, from LOCAL grads
        exp_m = 0.01 * np.asarray(grads[k])
        np.testing.assert_allclose(np.asarray(new_state.exp_avg[k]), exp_m, rtol=1e-6)


def test_wire_paths_agree():
    mesh = make_mesh(data=8)
    params = _params()
    grads = _stacked_grads(8, seed=11)
    outs = []
    for wire in ("sign_psum", "packed_allgather", "packed_a2a"):
        opt = distributed_lion(learning_rate=0.05, wire=wire)
        state = shard_state(init_global_state(opt, params, world=8), mesh)
        new_p, _ = _run_steps(mesh, opt, params, grads, state, n=3)
        outs.append(new_p)
    for k in params:
        for other in outs[1:]:
            np.testing.assert_array_equal(np.asarray(outs[0][k]), np.asarray(other[k]))


@pytest.mark.parametrize("world", [2, 3, 5, 6, 7])
def test_wire_paths_agree_odd_worlds(world):
    """Flat wires elect identically at non-power-of-two worlds — exercises
    packed_a2a's uneven chunk padding and packed_allgather's bit trimming."""
    mesh = make_mesh(data=world, devices=jax.devices()[:world])
    params = _params()
    grads = _stacked_grads(world, seed=world)
    outs = []
    for wire in ("sign_psum", "packed_allgather", "packed_a2a",
                 f"hier:{world}"):  # g=W degenerates to the flat vote
        opt = distributed_lion(learning_rate=0.05, wire=wire)
        state = shard_state(init_global_state(opt, params, world=world), mesh)
        new_p, _ = _run_steps(mesh, opt, params, grads, state, n=2)
        outs.append(new_p)
    for k in params:
        for other in outs[1:]:
            np.testing.assert_array_equal(np.asarray(outs[0][k]),
                                          np.asarray(other[k]))


def test_stochastic_composes_with_every_wire():
    """Stochastic binarization draws ballots from (rng, count, worker) only
    — the wire moves them. With identical draws, every flat wire (and hier
    at its degenerate group sizes) elects identically; hier:4 stays
    replica-consistent."""
    mesh = make_mesh(data=8)
    params = _params()
    grads = _stacked_grads(8, seed=13)
    outs = {}
    for wire in ("sign_psum", "packed_allgather", "packed_a2a",
                 "hier:1", "hier:8", "hier:4"):
        opt = distributed_lion(learning_rate=0.05, wire=wire,
                               max_grad_norm=1.0)
        state = shard_state(
            init_global_state(opt, params, world=8, rng=jax.random.key(42)),
            mesh)
        new_p, _ = _run_steps(mesh, opt, params, grads, state, n=2)
        outs[wire] = new_p
    for k in params:
        base = np.asarray(outs["sign_psum"][k])
        for wire in ("packed_allgather", "packed_a2a", "hier:1", "hier:8"):
            np.testing.assert_array_equal(base, np.asarray(outs[wire][k]),
                                          err_msg=wire)
        # hier:4 may differ (majority-of-majorities) but must be replicated
        leaf = outs["hier:4"][k]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_permutation_invariance():
    mesh = make_mesh(data=8)
    params = _params()
    grads = _stacked_grads(8, seed=5)
    perm = np.random.default_rng(0).permutation(8)
    permuted = jax.tree.map(lambda g: g[perm], grads)
    opt = distributed_lion(learning_rate=0.01)
    p1, _ = _run_steps(mesh, opt, params, grads,
                       shard_state(init_global_state(opt, params, 8), mesh))
    p2, _ = _run_steps(mesh, opt, params, permuted,
                       shard_state(init_global_state(opt, params, 8), mesh))
    for k in params:
        np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))


def test_tie_elects_minus_one():
    """Even world, 50/50 split → vote False → update −1 → p increases by lr
    (torch.mode smaller-value tie rule, SURVEY §2.3 step 6)."""
    mesh = make_mesh(data=8)
    params = {"w": jnp.zeros((4,))}
    half = np.ones((8, 4), np.float32)
    half[:4] *= -1.0  # 4 workers vote −, 4 vote +
    grads = {"w": jnp.asarray(half)}
    opt = distributed_lion(learning_rate=0.5, weight_decay=0.0)
    state = shard_state(init_global_state(opt, params, 8), mesh)
    new_p, _ = _run_steps(mesh, opt, params, grads, state)
    np.testing.assert_allclose(np.asarray(new_p["w"]), 0.5)  # p - lr*(−1)


def test_stochastic_binarization_unbiased_and_divergent():
    """Stochastic votes: per-worker draws differ, and the mean elected
    direction tracks the gradient sign for strong signals."""
    mesh = make_mesh(data=8)
    n = 4096
    params = {"w": jnp.zeros((n,))}
    # strong positive signal on all workers → P(vote +) well above 1/2
    grads = {"w": jnp.full((8, n), -0.8, jnp.float32)}
    opt = distributed_lion(learning_rate=1.0, max_grad_norm=1.0)
    state = shard_state(
        init_global_state(opt, params, 8, rng=jax.random.key(0)), mesh
    )
    new_p, _ = _run_steps(mesh, opt, params, grads, state)
    # u = 0.1*(-0.8) = −0.08, r = (1+1/0.9)*1 ≈ 2.111, P(+) ≈ 0.481 →
    # per-worker votes are near-coin-flips but the MAJORITY of 8 still
    # leans −; just assert both outcomes occur (stochasticity) and that the
    # update is ±lr exactly.
    vals = np.unique(np.asarray(new_p["w"]))
    assert set(vals).issubset({-1.0, 1.0})
    assert len(vals) == 2, "stochastic path produced deterministic output"


def test_stochastic_requires_rng():
    opt = distributed_lion(max_grad_norm=1.0)
    with pytest.raises(ValueError):
        opt.init({"w": jnp.zeros((2,))})


def test_axis_none_falls_back_to_local():
    # Parity with the reference's uninitialized-dist fallback (:165-166).
    opt = distributed_lion(learning_rate=0.1, axis_name=None)
    p = {"w": jnp.zeros((2,))}
    p1, _ = opt.step(p, {"w": jnp.ones((2,))}, opt.init(p))
    np.testing.assert_allclose(np.asarray(p1["w"]), -0.1, rtol=1e-6)


def test_dropout_robust_training_converges():
    """Algorithm-level drop-out robustness, end to end (SURVEY §5): optimize
    a quadratic with vote-Lion while 3 of 8 voters abstain every step —
    the surviving majority's votes still drive the params to the optimum.
    (The reference only *claims* this; its fixed-world all_gather would hang.)"""
    mesh = make_mesh(data=8)
    world = 8
    target = jnp.asarray(np.random.default_rng(0).normal(size=(64,)).astype(np.float32))
    params = jnp.zeros((64,))
    lr, b1, b2 = 0.05, 0.9, 0.99
    alive = np.ones((world, 1), bool)
    alive[5:] = False  # workers 5,6,7 dropped out

    def step(p, m, alive_l, noise_key):
        # per-worker noisy gradient of 0.5*||p - target||^2
        widx = jax.lax.axis_index(DATA_AXIS)
        g = (p - target) + 0.1 * jax.random.normal(
            jax.random.fold_in(noise_key, widx), p.shape
        )
        u = b1 * m + (1 - b1) * g
        elected = collectives.masked_majority_vote_psum(u > 0, alive_l[0], DATA_AXIS)
        p = p - lr * jnp.where(elected, 1.0, -1.0)
        return p, b2 * m + (1 - b2) * g

    run = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False,
    ))
    m = jnp.zeros((world, 64))
    key = jax.random.key(1)
    loss0 = float(jnp.mean((params - target) ** 2))
    for i in range(200):
        params, m = run(params, m, jnp.asarray(alive), jax.random.fold_in(key, i))
    loss1 = float(jnp.mean((params - target) ** 2))
    assert loss1 < loss0 * 0.05, (loss0, loss1)


def test_dropout_robust_masked_vote():
    """Masked vote: dead workers abstain and the survivors' majority wins
    (the algorithm-level drop-out robustness the reference only claims)."""
    mesh = make_mesh(data=8)

    def f(votes, alive):
        return collectives.masked_majority_vote_psum(votes[0], alive[0], DATA_AXIS)

    votes = np.zeros((8, 4), bool)
    votes[:3] = True  # 3 True, 5 False → False wins alive; kill 4 False voters
    alive = np.ones((8, 1), bool)
    alive[3:7] = False
    out = jax.shard_map(
        f, mesh=mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(),
        check_vma=False,
    )(jnp.asarray(votes), jnp.asarray(alive))
    # survivors: workers 0,1,2 (True) and 7 (False) → 3 vs 1 → True elected
    assert np.asarray(out).all()
