"""Chunked-vocab cross entropy (ops/xent): exact parity with the dense
log_softmax path — values, accuracy metric, AND gradients — plus the
Trainer integration (`--vocab_chunks`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_hidden, gpt2_init
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
from distributed_lion_tpu.ops.xent import (
    chunked_clm_loss_and_metrics,
    chunked_softmax_xent,
)


@pytest.mark.parametrize("n_chunks,v", [
    (1, 101), (3, 101), (8, 101),
    (7, 10),   # padding spills across several chunks; some chunks all-pad
    (16, 17),  # nearly every chunk is padding
])
def test_xent_matches_dense(n_chunks, v):
    rng = np.random.default_rng(0)
    n, d = 17, 16
    hidden = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(v, d)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)

    nll, correct = chunked_softmax_xent(hidden, emb, labels, n_chunks)
    logits = hidden @ emb.T
    ref_nll = -jax.nn.log_softmax(logits)[jnp.arange(n), labels]
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref_nll),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(correct),
                                  np.asarray(logits.argmax(-1) == labels))


def test_xent_grads_match_dense():
    rng = np.random.default_rng(1)
    n, d, v = 11, 8, 37
    hidden = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(v, d)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)

    def chunked(h, e):
        return chunked_softmax_xent(h, e, labels, 4)[0].mean()

    def dense(h, e):
        return (-jax.nn.log_softmax(h @ e.T)[jnp.arange(n), labels]).mean()

    gh1, ge1 = jax.grad(chunked, argnums=(0, 1))(hidden, emb)
    gh2, ge2 = jax.grad(dense, argnums=(0, 1))(hidden, emb)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ge1), np.asarray(ge2), rtol=1e-4, atol=1e-5)


def test_chunked_clm_matches_dense_loss():
    model = GPT2Config.tiny(compute_dtype=jnp.float32)
    params = gpt2_init(jax.random.key(0), model)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, model.vocab_size, (2, 24)), jnp.int32)
    hidden, _ = gpt2_hidden(params, tokens, model)
    loss_c, m_c = chunked_clm_loss_and_metrics(hidden, params["wte"], tokens, 4)
    loss_d, m_d = clm_loss_and_metrics(gpt2_apply(params, tokens, model), tokens)
    np.testing.assert_allclose(float(loss_c), float(loss_d), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(m_c["accuracy"]), float(m_d["accuracy"]),
                               rtol=1e-6, atol=1e-6)


def test_trainer_vocab_chunks_matches_dense():
    """5 training steps with --vocab_chunks ≡ the dense-loss run (f32)."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    model = GPT2Config.tiny(compute_dtype=jnp.float32)
    mesh = make_mesh(data=8)

    def run(vocab_chunks):
        cfg = TrainConfig(
            lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
            max_steps=5, per_device_train_batch_size=2,
            gradient_accumulation_steps=1, block_size=32, logging_steps=1,
            output_dir=None, vocab_chunks=vocab_chunks,
        )
        t = Trainer.for_gpt2(cfg, mesh, model, seed=3)
        blocks = synthetic_lm_dataset(max(64, t.global_train_batch() * 2), 32,
                                      model.vocab_size, seed=7)
        hist = t.train(batch_iterator(blocks, t.global_train_batch(), seed=0))
        losses = [h["loss"] for h in hist if "loss" in h]
        params = jax.tree.map(np.asarray, jax.device_get(t.params))
        t.close()
        return losses, params

    losses_d, params_d = run(0)
    losses_c, params_c = run(4)
    np.testing.assert_allclose(losses_c, losses_d, rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(params_d), jax.tree.leaves(params_c)):
        assert np.abs(a - b).max() <= 2 * 1e-3 * 5 + 1e-6  # ballot-flip envelope


def test_llama_chunked_matches_dense():
    """llama_hidden + chunked xent == llama_apply + dense loss (untied head,
    lm_head [d, V] transposed into the emb contract)."""
    from distributed_lion_tpu.models.llama import (
        LlamaConfig, llama_apply, llama_hidden, llama_init,
    )

    model = LlamaConfig.tiny(compute_dtype=jnp.float32)
    params = llama_init(jax.random.key(0), model)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, model.vocab_size, (2, 24)), jnp.int32)
    hidden = llama_hidden(params, tokens, model)
    loss_c, m_c = chunked_clm_loss_and_metrics(
        hidden, params["lm_head"], tokens, 4, emb_layout="dv")
    loss_d, m_d = clm_loss_and_metrics(llama_apply(params, tokens, model), tokens)
    np.testing.assert_allclose(float(loss_c), float(loss_d), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(m_c["accuracy"]), float(m_d["accuracy"]),
                               rtol=1e-6, atol=1e-6)


def test_chunked_seq_parallel_matches_dense_seq_loss():
    """chunked_clm_loss_seq_parallel == clm_loss_seq_parallel (values,
    metrics, AND grads) under a 4-way seq mesh — the long-context x
    huge-vocab composition (round 3)."""
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from distributed_lion_tpu.models.llama import (
        LlamaConfig, llama_apply, llama_hidden, llama_init,
    )
    from distributed_lion_tpu.models.loss import clm_loss_seq_parallel
    from distributed_lion_tpu.ops.xent import chunked_clm_loss_seq_parallel

    model = LlamaConfig.tiny(compute_dtype=jnp.float32)
    params = llama_init(jax.random.key(0), model)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, model.vocab_size, (2, 64)),
        jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))

    def dense(params, tokens):
        logits = llama_apply(params, tokens, model, seq_axis="seq")
        loss, m = clm_loss_seq_parallel(logits, tokens, "seq")
        return loss, m

    def chunked(params, tokens):
        hidden = llama_hidden(params, tokens, model, seq_axis="seq")
        loss, m = chunked_clm_loss_seq_parallel(
            hidden, params["lm_head"], tokens, 4, "seq", emb_layout="dv")
        return loss, m

    def run(fn):
        def body(params, tokens):
            (loss, m), g = jax.value_and_grad(
                lambda p, t: fn(p, t), has_aux=True)(params, tokens)
            # the train loop's seq-axis grad reduction
            g = jax.lax.psum(g, "seq")
            return m["loss"], m["accuracy"], g

        out = shard_map(
            body, mesh=mesh, in_specs=(P(), P(None, "seq")),
            out_specs=(P(), P(), P()), check_vma=False,
        )(params, tokens)
        return jax.tree.map(np.asarray, jax.device_get(out))

    loss_d, acc_d, g_d = run(dense)
    loss_c, acc_c, g_c = run(chunked)
    np.testing.assert_allclose(loss_c, loss_d, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(acc_c, acc_d, rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g_d), jax.tree.leaves(g_c)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
