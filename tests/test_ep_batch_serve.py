"""Batch-sharded expert-parallel decode (ISSUE 16): the engine's decode /
prefill / verify batch sharded over the EXPERT mesh axis — ep as a
throughput lever, not just an HBM lever.

Pins, all on the 8-device CPU mesh:

- ep_batch at ep=1 is BIT-identical to the replicated engine, including
  the KV page pool bytes (the sharding is a pure re-schedule);
- ep ∈ {2, 4} and ep×tp are token-identical to the unsharded engine,
  greedy and sampled, composing with --prefix_cache and ngram
  speculation;
- ragged occupancy (some groups with empty slots) stays identical — the
  valid-lane mask, not slot packing, carries correctness;
- the two-microbatch overlap split (--serve_ep_overlap) is
  bit-identical to the unsplit tick;
- the routing stats ep ∈ {1, 2} are bit-equal to the unsharded engine
  (psummed counters + the static stats_lanes prefill budget);
- the training-side --ep_dcn_pipeline ring crash-resumes bit-identical;
- every infeasible configuration is refused loudly at build time.
"""

import numpy as np
import pytest

import jax

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)

MOE = GPT2Config.tiny(moe_experts=4)


@pytest.fixture(scope="module")
def moe_params():
    return gpt2_init(jax.random.key(0), MOE)


def _requests(n=4, max_new=8, lens=(3, 9, 5, 14), seed=7):
    rng = np.random.default_rng(seed)
    return [Request(req_id=i,
                    tokens=list(map(int, rng.integers(1, MOE.vocab_size, L))),
                    max_new_tokens=max_new, seed=i)
            for i, L in enumerate(lens[:n])]


def _engine(params, **kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    base.update(kw)
    return ServingEngine(ServeModel.for_gpt2(params, MOE), ServeConfig(**base))


def _run(eng, reqs):
    done = eng.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                            r.seed) for r in reqs])
    return {r.req_id: done[r.req_id].tokens for r in reqs}


@pytest.fixture(scope="module")
def baseline(moe_params):
    reqs = _requests()
    return reqs, _run(_engine(moe_params), reqs)


def test_ep_batch_ep1_bit_identical_with_pages(moe_params, baseline):
    """ep_batch over an axis of size 1 is the SAME program modulo a
    trivial shard_map — tokens AND the full KV page pool must match
    bit for bit."""
    reqs, base = baseline
    ref = _engine(moe_params)
    got = _engine(moe_params, ep=1, ep_batch=True)
    assert _run(ref, reqs) == base
    assert _run(got, reqs) == base
    for lr, lg in zip(ref.pages, got.pages):
        for k in lr:
            np.testing.assert_array_equal(np.asarray(lr[k]),
                                          np.asarray(lg[k]))


@pytest.mark.parametrize("ep", [2, 4])
def test_ep_batch_token_identical(moe_params, baseline, ep):
    reqs, base = baseline
    assert _run(_engine(moe_params, ep=ep, ep_batch=True), reqs) == base


def test_ep_batch_with_tp(moe_params, baseline):
    reqs, base = baseline
    assert _run(_engine(moe_params, ep=2, tp=2, ep_batch=True), reqs) == base


def test_ep_batch_sampled(moe_params):
    """Seeded sampling rides per-slot fold_in keys that never see the
    mesh — temperature/top_k outputs are identical under the sharding."""
    reqs = _requests()
    samp = dict(temperature=0.9, top_k=40)
    base = _run(_engine(moe_params, **samp), reqs)
    assert _run(_engine(moe_params, ep=2, ep_batch=True, **samp),
                reqs) == base


def test_ep_batch_ragged_occupancy(moe_params):
    """3 requests on a 4-slot, 2-group engine: one group decodes with an
    empty slot. The valid-lane mask keeps the live rows identical."""
    reqs = _requests(n=3)
    base = _run(_engine(moe_params), reqs)
    assert _run(_engine(moe_params, ep=2, ep_batch=True), reqs) == base


def test_ep_batch_prefix_cache(moe_params):
    rng = np.random.default_rng(23)
    sys_p = list(map(int, rng.integers(1, MOE.vocab_size, 9)))
    reqs = [Request(req_id=i, tokens=sys_p + list(
        map(int, rng.integers(1, MOE.vocab_size, 2))),
        max_new_tokens=5, seed=i) for i in range(4)]
    base = _run(_engine(moe_params, num_blocks=64), reqs)
    got = _run(_engine(moe_params, num_blocks=64, prefix_cache=True,
                       ep=2, ep_batch=True), reqs)
    assert got == base


def test_ep_batch_ngram_speculation(moe_params):
    motif = list(map(int,
                     np.random.default_rng(19).integers(1, MOE.vocab_size,
                                                        4)))
    reqs = [Request(req_id=i, tokens=motif * 4, max_new_tokens=10, seed=i)
            for i in range(3)]
    base = _run(_engine(moe_params, max_blocks_per_seq=16), reqs)
    got = _run(_engine(moe_params, max_blocks_per_seq=16,
                       speculate="ngram:4", ep=2, ep_batch=True), reqs)
    assert got == base


def test_ep_overlap_bit_identical(moe_params, baseline):
    """The two-microbatch split is a pure re-schedule: attention is
    row-local and inference routing is no-drop (exact per token), so
    half-batch dispatch order cannot change a single token."""
    reqs, base = baseline
    assert _run(_engine(moe_params, ep=2, ep_batch=True, ep_overlap=True),
                reqs) == base


@pytest.mark.parametrize("ep", [1, 2])
def test_moe_stats_bit_equal_under_sharding(moe_params, ep):
    """The routing-load counters psum over the expert axis (each shard
    tallies only its own rows) and the batch-1 prefill budget uses the
    static true lane width, not ep x lanes — the aggregated stats must
    equal the unsharded engine's exactly."""
    reqs = _requests()
    e0 = _engine(moe_params, moe_stats=True)
    _run(e0, reqs)
    e1 = _engine(moe_params, moe_stats=True, ep=ep, ep_batch=True)
    _run(e1, reqs)
    for k in ("moe_valid_tokens", "moe_kept_tokens", "moe_capacity_slots"):
        assert e0.stats[k] == e1.stats[k], (k, e0.stats, e1.stats)


def test_ep_batch_refusals(moe_params):
    with pytest.raises(ValueError, match="serve_ep_batch"):
        _engine(moe_params, ep_batch=True)  # no expert axis
    with pytest.raises(ValueError, match="max_seqs"):
        _engine(moe_params, max_seqs=6, ep=4, ep_batch=True)
    with pytest.raises(ValueError, match="num_blocks"):
        _engine(moe_params, ep=4, ep_batch=True, num_blocks=66)
    with pytest.raises(ValueError, match="serve_ep_overlap"):
        _engine(moe_params, ep=4, ep_batch=True, ep_overlap=True)  # 1 slot
    with pytest.raises(ValueError, match="even"):
        _engine(moe_params, max_seqs=3, ep_overlap=True)


def test_ep_dcn_pipeline_ring_crash_resume(tmp_path):
    """Training satellite: the --ep_dcn_pipeline balance ring is live
    optimizer state — a run killed after a mid-flight save must resume
    bit-identical (losses, params, ring) to an uninterrupted run."""
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    model = GPT2Config.tiny(n_layer=4, moe_experts=4)
    mesh = make_mesh(data=2, expert=2, devices=jax.devices()[:4])

    def cfg(outdir=None):
        return TrainConfig(
            lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
            max_steps=4, per_device_train_batch_size=1,
            gradient_accumulation_steps=1, block_size=32, logging_steps=1,
            save_steps=2, output_dir=outdir, seed=5,
            expert_parallel=2, ep_dcn_pipeline=2)

    blocks = synthetic_lm_dataset(32, 32, model.vocab_size, seed=1)

    def losses(hist):
        return [x["loss"] for x in hist if "loss" in x]

    t_ref = Trainer.for_gpt2(cfg(), mesh, model, seed=3)
    ref = losses(t_ref.train(batch_iterator(blocks, t_ref.global_train_batch(),
                                            seed=5)))
    ref_params = jax.device_get(t_ref.params)
    ref_ring = np.asarray(jax.device_get(t_ref.state.moe_ring))
    t_ref.close()
    assert np.any(ref_ring != 0.0)  # the ring really is in flight

    out = str(tmp_path / "run")
    t1 = Trainer.for_gpt2(cfg(out), mesh, model, seed=3)
    part1 = losses(t1.train(batch_iterator(blocks, t1.global_train_batch(),
                                           seed=5), max_steps=2))
    t1.close()
    t2 = Trainer.for_gpt2(cfg(out), mesh, model, seed=3)
    assert t2.step_count == 2
    part2 = losses(t2.train(batch_iterator(blocks, t2.global_train_batch(),
                                           seed=5)))
    got_params = jax.device_get(t2.params)
    got_ring = np.asarray(jax.device_get(t2.state.moe_ring))
    t2.close()

    np.testing.assert_array_equal(part1 + part2, ref)
    jax.tree.map(np.testing.assert_array_equal, got_params, ref_params)
    np.testing.assert_array_equal(got_ring, ref_ring)

    # a depth toggle on resume is refused loudly (the in-flight ring
    # cannot be remapped)
    import dataclasses
    with pytest.raises(ValueError, match="ep_dcn_pipeline"):
        Trainer.for_gpt2(dataclasses.replace(cfg(out), ep_dcn_pipeline=1),
                         mesh, model, seed=3)


def test_ep_dcn_pipeline_refusals():
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    mesh = make_mesh(data=8)
    model = GPT2Config.tiny(n_layer=4, moe_experts=4)

    def cfg(**kw):
        base = dict(lion=True, async_grad=True, learning_rate=1e-3,
                    warmup_steps=1, max_steps=2,
                    per_device_train_batch_size=1,
                    gradient_accumulation_steps=1, block_size=32,
                    logging_steps=1, output_dir=None, seed=5)
        base.update(kw)
        return TrainConfig(**base)

    with pytest.raises(ValueError, match=">= 0"):
        Trainer.for_gpt2(cfg(ep_dcn_pipeline=-1), mesh, model)
    with pytest.raises(ValueError, match="moe_ring"):
        Trainer.for_gpt2(cfg(ep_dcn_pipeline=2, lion=False,
                             async_grad=False), mesh, model)
    with pytest.raises(ValueError, match="dense"):
        Trainer.for_gpt2(cfg(ep_dcn_pipeline=0), mesh, GPT2Config.tiny())
