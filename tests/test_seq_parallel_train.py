"""Sequence-parallel TRAINING (not just the ring-attention op): the
dp×sp train step must reproduce the pure-dp trajectory exactly — same data
rows, same vote world, tokens merely sharded across the seq axis."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
from distributed_lion_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def test_sp_forward_matches_single_device():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 64)), jnp.int32)
    expected = gpt2_apply(params, toks, cfg)

    mesh = make_mesh(data=1, seq=4, devices=jax.devices()[:4])

    def f(p, t):
        return gpt2_apply(p, t, cfg, seq_axis=SEQ_AXIS)

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
                      out_specs=P(None, SEQ_AXIS), check_vma=False)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-2, atol=2e-2)


def test_llama_sp_forward_matches_single_device():
    """Llama SP: rotary offsets per shard + ring attention == dense."""
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init

    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(1), cfg)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 64)), jnp.int32)
    expected = llama_apply(params, toks, cfg)

    mesh = make_mesh(data=1, seq=4, devices=jax.devices()[:4])

    def f(p, t):
        return llama_apply(p, t, cfg, seq_axis=SEQ_AXIS)

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
                      out_specs=P(None, SEQ_AXIS), check_vma=False)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-2, atol=2e-2)


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
        warmup_steps=5, max_steps=20, per_device_train_batch_size=4,
        gradient_accumulation_steps=1, block_size=32, logging_steps=5,
        eval_steps=10**6, save_steps=10**6, seed=0, output_dir=None,
    )
    base.update(kw)
    return TrainConfig(**base)


def test_sp_gradients_match_pure_dp():
    """dp=2 × sp=4 vs dp=2 after ONE step: each voter's Lion momentum is
    (1-β₂)·grad, so momentum equality ⇔ the seq-psum of shard gradients
    equals the full-sequence gradient (catches a missing/extra psum or
    broken boundary labels outright; tolerance covers bf16 noise between
    ring and dense attention orderings)."""
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)

    t_sp = Trainer.for_gpt2(_cfg(), make_mesh(data=2, seq=4), model_cfg)
    t_dp = Trainer.for_gpt2(_cfg(), make_mesh(data=2, devices=jax.devices()[:2]),
                            model_cfg)
    assert t_sp.global_train_batch() == t_dp.global_train_batch() == 8
    t_sp.train(batch_iterator(blocks, 8, seed=1), max_steps=1)
    t_dp.train(batch_iterator(blocks, 8, seed=1), max_steps=1)
    for a, b in zip(jax.tree.leaves(t_sp.state.exp_avg),
                    jax.tree.leaves(t_dp.state.exp_avg)):
        a, b = np.asarray(a), np.asarray(b)
        denom = np.maximum(np.abs(b).max(), 1e-8)
        np.testing.assert_allclose(a / denom, b / denom, atol=6e-2)
    t_sp.close()
    t_dp.close()


def test_ulysses_sp_forward_matches_single_device():
    """seq_impl='ulysses' (all_to_all to head sharding) == dense forward."""
    cfg = GPT2Config.tiny(seq_impl="ulysses")
    params = gpt2_init(jax.random.key(2), cfg)
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 256, (2, 64)), jnp.int32)
    expected = gpt2_apply(params, toks, cfg)

    mesh = make_mesh(data=1, seq=4, devices=jax.devices()[:4])

    def f(p, t):
        return gpt2_apply(p, t, cfg, seq_axis=SEQ_AXIS)

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
                      out_specs=P(None, SEQ_AXIS), check_vma=False)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-2, atol=2e-2)


def test_ulysses_sp_training_matches_pure_dp():
    """Full vote-Lion train step with the Ulysses seq impl: momentum after
    one step matches pure-dp (same invariant as the ring test above)."""
    model_cfg = GPT2Config.tiny(seq_impl="ulysses")
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)

    t_sp = Trainer.for_gpt2(_cfg(), make_mesh(data=2, seq=4), model_cfg)
    t_dp = Trainer.for_gpt2(_cfg(), make_mesh(data=2, devices=jax.devices()[:2]),
                            model_cfg)
    t_sp.train(batch_iterator(blocks, 8, seed=1), max_steps=1)
    t_dp.train(batch_iterator(blocks, 8, seed=1), max_steps=1)
    for a, b in zip(jax.tree.leaves(t_sp.state.exp_avg),
                    jax.tree.leaves(t_dp.state.exp_avg)):
        a, b = np.asarray(a), np.asarray(b)
        denom = np.maximum(np.abs(b).max(), 1e-8)
        np.testing.assert_allclose(a / denom, b / denom, atol=6e-2)
    t_sp.close()
    t_dp.close()


def test_dp_sp_adamw_trajectory_matches_pure_dp():
    """With the continuous AdamW optimizer (no sign discretization to
    amplify bf16 noise), the dp×sp run reproduces the pure-dp parameter
    trajectory over 20 steps."""
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)
    kw = dict(lion=False, async_grad=False, learning_rate=1e-3)

    t_sp = Trainer.for_gpt2(_cfg(**kw), make_mesh(data=2, seq=4), model_cfg)
    t_sp.train(batch_iterator(blocks, 8, seed=1), max_steps=20)
    t_dp = Trainer.for_gpt2(_cfg(**kw), make_mesh(data=2, devices=jax.devices()[:2]),
                            model_cfg)
    t_dp.train(batch_iterator(blocks, 8, seed=1), max_steps=20)

    for a, b in zip(jax.tree.leaves(t_sp.params), jax.tree.leaves(t_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=2e-2)
    t_sp.close()
    t_dp.close()


def test_sp_vote_lion_loss_decreases():
    """End-to-end: vote-Lion training under dp×sp converges."""
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)
    t = Trainer.for_gpt2(_cfg(max_steps=40), make_mesh(data=2, seq=4), model_cfg)
    h = t.train(batch_iterator(blocks, 8, seed=1), max_steps=40)
    losses = [x["loss"] for x in h if "loss" in x]
    assert losses[-1] < losses[0] - 0.3, losses
    t.close()


def test_sp_eval_matches_dp_eval():
    """Boundary-label ppermute: eval loss/accuracy under sp=4 equals the
    unsharded eval on the same blocks."""
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model_cfg.vocab_size)
    m_sp = Trainer.for_gpt2(_cfg(per_device_eval_batch_size=4),
                            make_mesh(data=2, seq=4), model_cfg)
    m_dp = Trainer.for_gpt2(_cfg(per_device_eval_batch_size=4),
                            make_mesh(data=2, devices=jax.devices()[:2]), model_cfg)
    e_sp = m_sp.evaluate(blocks)
    e_dp = m_dp.evaluate(blocks)
    np.testing.assert_allclose(e_sp["eval/loss"], e_dp["eval/loss"], rtol=2e-3)
    np.testing.assert_allclose(e_sp["eval/accuracy"], e_dp["eval/accuracy"], rtol=2e-3)
    m_sp.close()
    m_dp.close()
