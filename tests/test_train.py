"""End-to-end train-loop tests on 8 virtual devices (SURVEY §4 integration):
loss goes down under vote-Lion; non-async AdamW path works; checkpoint
save/resume is exact; CLI smoke."""

import dataclasses

import jax
import numpy as np
import pytest

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.parallel import make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _tiny_cfg(**kw):
    base = dict(
        lion=True,
        async_grad=True,
        learning_rate=3e-3,
        weight_decay=0.0,
        warmup_steps=5,
        max_steps=40,
        per_device_train_batch_size=2,
        gradient_accumulation_steps=2,
        per_device_eval_batch_size=2,
        block_size=32,
        logging_steps=10,
        eval_steps=1000,
        save_steps=1000,
        eval_iters=2,
        seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _run(cfg, steps=40, model_kw=None, mesh=None):
    mesh = mesh or make_mesh(data=8)
    model_cfg = GPT2Config.tiny(**(model_kw or {}))
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(512, cfg.block_size, model_cfg.vocab_size)
    it = batch_iterator(blocks, trainer.global_train_batch(), seed=0)
    history = trainer.train(it, max_steps=steps)
    trainer.close()
    return trainer, history, blocks


def test_loss_decreases_under_vote_lion():
    cfg = _tiny_cfg()
    trainer, history, _ = _run(cfg)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, f"loss did not fall: {losses}"


def test_vote_lion_loss_parity_with_single_worker():
    """BASELINE.md discipline (a): 8-worker majority-vote Lion tracks
    single-worker Lion's loss curve at equal global batch. The algorithms
    differ (majority of per-worker signs vs sign of pooled momentum) so the
    match is statistical, not exact — final losses within 15%."""
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)

    def final_loss(mesh, world):
        # equal global batch: world * per_device * accum = 16 in both runs
        cfg = _tiny_cfg(per_device_train_batch_size=16 // world // 2,
                        gradient_accumulation_steps=2, max_steps=60)
        t = Trainer.for_gpt2(cfg, mesh, model_cfg)
        assert t.global_train_batch() == 16
        h = t.train(batch_iterator(blocks, 16, seed=3), max_steps=60)
        t.close()
        return [x["loss"] for x in h if "loss" in x][-1]

    loss_vote = final_loss(make_mesh(data=8), 8)
    loss_single = final_loss(make_mesh(data=1, devices=jax.devices()[:1]), 1)
    assert abs(loss_vote - loss_single) / loss_single < 0.15, (loss_vote, loss_single)


def test_adamw_non_async_path():
    cfg = _tiny_cfg(lion=False, async_grad=False, learning_rate=1e-3)
    trainer, history, _ = _run(cfg, steps=20)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0]


def test_lion_non_async_path():
    """--lion without --async_grad: DDP-style pmean'd grads feeding the vote
    (unanimous since all workers agree) — regression for a stacked-momentum
    shape bug in this branch."""
    cfg = _tiny_cfg(async_grad=False)
    trainer, history, _ = _run(cfg, steps=20)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0]
    # params must keep their original rank (no spurious leading axis)
    assert trainer.params["wte"].ndim == 2


def test_async_without_lion_refused():
    with pytest.raises(ValueError):
        _run(_tiny_cfg(lion=False, async_grad=True), steps=1)


def test_eval_reports_perplexity():
    cfg = _tiny_cfg()
    trainer, _, blocks = _run(cfg, steps=10)
    # re-open trainer state is closed; evaluate directly on a fresh trainer
    mesh = make_mesh(data=8)
    t2 = Trainer.for_gpt2(cfg, mesh, GPT2Config.tiny())
    m = t2.evaluate(blocks[:64])
    assert np.isfinite(m["eval/loss"])
    np.testing.assert_allclose(m["eval/perplexity"], np.exp(m["eval/loss"]), rtol=1e-5)
    t2.close()


def test_checkpoint_resume_exact(tmp_path):
    """Train 10 steps, checkpoint, resume into a fresh trainer → parameters
    and per-worker momentum match a continuous 20-step run exactly."""
    mesh = make_mesh(data=8)
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)

    # continuous run: 20 steps
    cfg_c = _tiny_cfg(max_steps=20)
    t_cont = Trainer.for_gpt2(cfg_c, mesh, model_cfg)
    it = batch_iterator(blocks, t_cont.global_train_batch(), seed=9)
    t_cont.train(it, max_steps=20)

    # checkpointed run: 10 steps, save, new trainer resumes, 10 more
    cfg_a = _tiny_cfg(max_steps=20, output_dir=str(tmp_path / "run"), save_steps=10**9)
    t1 = Trainer.for_gpt2(cfg_a, mesh, model_cfg)
    it1 = batch_iterator(blocks, t1.global_train_batch(), seed=9)
    t1.train(it1, max_steps=10)
    t1.save()
    t1.close()

    t2 = Trainer.for_gpt2(cfg_a, mesh, model_cfg)
    assert t2.step_count == 10, "did not resume from checkpoint"
    # fresh iterator, same seed: the trainer fast-forwards past consumed batches
    it2 = batch_iterator(blocks, t2.global_train_batch(), seed=9)
    t2.train(it2, max_steps=10)

    for a, b in zip(jax.tree.leaves(t_cont.params), jax.tree.leaves(t2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(t_cont.state.exp_avg), jax.tree.leaves(t2.state.exp_avg)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t2.close()
    t_cont.close()


def test_checkpoint_resume_exact_under_tp_vocab(tmp_path):
    """Resume with TENSOR-SHARDED params (incl. the vocab-row-sharded tied
    embedding of --tp_vocab): Orbax must restore every shard to its rank and
    the continued trajectory must equal the uninterrupted one."""
    mesh = make_mesh(data=4, tensor=2)
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)
    kw = dict(max_steps=12, tp_vocab=True)

    t_cont = Trainer.for_gpt2(_tiny_cfg(**kw), mesh, model_cfg)
    t_cont.train(batch_iterator(blocks, t_cont.global_train_batch(), seed=9),
                 max_steps=12)

    cfg_a = _tiny_cfg(output_dir=str(tmp_path / "run"), save_steps=10**9, **kw)
    t1 = Trainer.for_gpt2(cfg_a, mesh, model_cfg)
    t1.train(batch_iterator(blocks, t1.global_train_batch(), seed=9),
             max_steps=6)
    t1.save()
    t1.close()

    t2 = Trainer.for_gpt2(cfg_a, mesh, model_cfg)
    assert t2.step_count == 6, "did not resume from checkpoint"
    # restored wte must still be vocab-row-sharded, not gathered
    assert (t2.params["wte"].addressable_shards[0].data.shape[0]
            == model_cfg.vocab_size // 2)
    t2.train(batch_iterator(blocks, t2.global_train_batch(), seed=9),
             max_steps=6)

    for a, b in zip(jax.tree.leaves(t_cont.params), jax.tree.leaves(t2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t2.close()
    t_cont.close()


def test_clip_by_global_norm():
    from distributed_lion_tpu.train.loop import clip_by_global_norm

    big = {"a": np.full((4,), 3.0, np.float32), "b": np.full((4,), 4.0, np.float32)}
    clipped = clip_by_global_norm(jax.tree.map(jax.numpy.asarray, big), 1.0)
    gn = np.sqrt(sum(np.sum(np.square(np.asarray(g))) for g in jax.tree.leaves(clipped)))
    np.testing.assert_allclose(gn, 1.0, rtol=1e-5)
    # direction preserved
    np.testing.assert_allclose(
        np.asarray(clipped["b"]) / np.asarray(clipped["a"]), 4.0 / 3.0, rtol=1e-5
    )
    # below-threshold grads untouched
    small = jax.tree.map(lambda g: jax.numpy.asarray(g) * 0.01, big)
    same = clip_by_global_norm(small, 1.0)
    for a, b in zip(jax.tree.leaves(small), jax.tree.leaves(same)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grad_clip_trains():
    """HF-Trainer-style global-norm clipping (grad_clip_norm) composes with
    the vote path and training still converges."""
    cfg = _tiny_cfg(grad_clip_norm=1.0)
    trainer, history, _ = _run(cfg, steps=20)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0]


def test_grad_clip_under_tensor_parallel_is_uniform():
    """Under TP the grads inside shard_map are sharded over the tensor axis;
    the clip norm must be psum'd across it so every shard scales by the SAME
    factor. Regression: dp=4 x tp=2 with clipping matches the replicated
    semantics — params stay identical across TP ranks (they would drift
    immediately if the two halves of a weight were scaled differently)."""
    mesh = make_mesh(data=4, tensor=2)
    cfg = _tiny_cfg(grad_clip_norm=0.5)
    trainer, history, _ = _run(cfg, steps=12, mesh=mesh)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0]
    # replicated-per-TP-rank invariant: fully-replicated leaves (layer norms,
    # biases) must be bitwise identical on every device
    ln = trainer.params["ln_f"]["scale"]
    shards = [np.asarray(s.data) for s in ln.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_remat_off_matches_remat_on():
    """remat is a perf knob, not a SEMANTICS knob — but it IS a fusion
    boundary, so its numerics guarantee is compute-dtype-limited and this
    test pins both halves of that claim precisely.

    With f32 compute, grads agree to f32 reassociation noise (~1e-10 at
    these magnitudes — pinned tight, so a real math divergence in the
    checkpoint wrapper is caught immediately). With bf16 compute — the
    model default, and what the sweep's remat leg runs — jax.checkpoint's
    optimization barriers change which intermediates XLA keeps in f32
    registers vs rounds through bf16 storage, so grads legitimately differ
    by a few bf16 ULPs (measured ~6e-5 peak at these scales; this is the
    failure the old one-tolerance test tripped on, not a remat bug). The
    bf16 leg bounds that divergence instead of denying it; the loss itself
    must still match at f32 tightness in both."""
    import dataclasses

    import jax.numpy as jnp

    from distributed_lion_tpu.models.gpt2 import gpt2_apply, gpt2_init

    tol = {jnp.float32: dict(rtol=1e-5, atol=1e-6),
           jnp.bfloat16: dict(rtol=1e-2, atol=2e-4)}
    for compute_dtype, t in tol.items():
        cfg_on = dataclasses.replace(GPT2Config.tiny(remat=True),
                                     compute_dtype=compute_dtype)
        cfg_off = dataclasses.replace(cfg_on, remat=False)
        params = gpt2_init(jax.random.key(0), cfg_on)
        tokens = np.random.default_rng(0).integers(
            0, cfg_on.vocab_size, (2, 16)).astype(np.int32)

        def loss(p, cfg):
            return jnp.mean(gpt2_apply(p, tokens, cfg) ** 2)

        l_on, g_on = jax.value_and_grad(loss)(params, cfg_on)
        l_off, g_off = jax.value_and_grad(loss)(params, cfg_off)
        np.testing.assert_allclose(np.asarray(l_on), np.asarray(l_off),
                                   rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g_on), jax.tree.leaves(g_off)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), **t)


def test_remat_policy_elections_pinned():
    """``TrainConfig.remat_policy`` (the last VERDICT lever: '' honors the
    model config, 'full' | 'dots' overrides it at Trainer build) is a perf
    knob UNDER THE VOTE, and this is the election-level version of the
    PR 6 remat-equivalence precedent. At f32 compute, remat reassociates
    grads at ~1e-10 — far from any sign boundary at these magnitudes — so
    every election agrees, and because Lion applies the ELECTED SIGN times
    lr (magnitudes never reach the params), agreeing elections make the
    whole trajectory bit-identical: losses, packed elected cache, params.
    At bf16 compute (the sweep's dots leg dtype) jax.checkpoint's fusion
    barriers round a few intermediates through bf16 storage, so near-tie
    coordinates may legitimately flip — and one flipped election moves a
    param by 2*lr, which re-rounds downstream bf16 grads, so flips
    COMPOUND across cycles (measured: 0.5% of cache bits after the first
    vote cycle, 24% after six — trajectory chaos, not remat error). The
    bounded half therefore pins the per-cycle claim where it is honest:
    first-cycle elected-cache disagreement under 2% of bits (ballots
    computed on identical params, so only genuine remat ULP flips), and
    trajectory-level tracking as a 24-step final-loss gap under 0.05."""
    import jax.numpy as jnp

    def run(policy, compute_dtype, steps):
        cfg = _tiny_cfg(vote_every=4, max_steps=steps, remat_policy=policy)
        trainer, history, _ = _run(
            cfg, steps=steps,
            model_kw=dict(remat=True, compute_dtype=compute_dtype))
        losses = [h["loss"] for h in history if "loss" in h]
        elected = np.asarray(jax.device_get(trainer.state.elected))
        return losses, elected, jax.tree.leaves(trainer.params)

    # f32: strict — bit-identical elections => bit-identical trajectory
    l_full, e_full, p_full = run("full", jnp.float32, 24)
    l_dots, e_dots, p_dots = run("dots", jnp.float32, 24)
    assert l_full == l_dots, f"f32 losses diverged: {l_full} vs {l_dots}"
    np.testing.assert_array_equal(e_full, e_dots)
    for a, b in zip(p_full, p_dots):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # bf16 first vote cycle: only remat ULP flips (measured ~0.5%)
    _, e_full, _ = run("full", jnp.bfloat16, 4)
    _, e_dots, _ = run("dots", jnp.bfloat16, 4)
    xor = np.bitwise_xor(e_full.view(np.uint8), e_dots.view(np.uint8))
    frac = np.unpackbits(xor).mean()
    assert frac < 0.02, f"bf16 first-cycle election disagreement {frac:.4f}"

    # bf16 trajectory: flips compound but the loss must track
    l_full, _, _ = run("full", jnp.bfloat16, 24)
    l_dots, _, _ = run("dots", jnp.bfloat16, 24)
    assert abs(l_full[-1] - l_dots[-1]) < 0.05, (
        f"bf16 final loss gap {abs(l_full[-1] - l_dots[-1]):.4f}")


def test_chunked_steps_match_single_exact():
    """steps_per_call>1 (lax.scan of the train step, one dispatch per K
    steps) is a latency knob, not a numerics knob: identical params after
    identical batches/keys, and log/eval/save boundaries are still hit."""
    mesh = make_mesh(data=8)
    model_cfg = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)

    cfg_k = _tiny_cfg(steps_per_call=4, max_steps=40)
    tk = Trainer.for_gpt2(cfg_k, mesh, model_cfg)
    hk = tk.train(batch_iterator(blocks, tk.global_train_batch(), seed=0), max_steps=40)

    cfg_1 = _tiny_cfg(steps_per_call=1, max_steps=40)
    t1 = Trainer.for_gpt2(cfg_1, mesh, model_cfg)
    t1.train(batch_iterator(blocks, t1.global_train_batch(), seed=0), max_steps=40)

    assert tk.step_count == t1.step_count == 40
    for a, b in zip(jax.tree.leaves(tk.params), jax.tree.leaves(t1.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # logging boundary (logging_steps=10) crossed by chunked advances
    assert [h["step"] for h in hk if "loss" in h] == [12, 20, 32, 40]


def test_cli_smoke(tmp_path, capsys):
    from distributed_lion_tpu.cli.run_clm import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--synthetic_blocks", "256",
        "--lion", "--async_grad", "--max_steps", "5", "--warmup_steps", "1",
        "--per_device_train_batch_size", "1", "--gradient_accumulation_steps", "1",
        "--block_size", "32", "--logging_steps", "1", "--eval_steps", "1000",
        "--save_steps", "1000", "--eval_iters", "1",
        "--output_dir", str(tmp_path / "cli_out"),
    ])
    out = capsys.readouterr().out
    assert "loss" in out
    assert (tmp_path / "cli_out" / "metrics.jsonl").exists()


def test_mom_dtype_bf16_trains_and_halves_state():
    """--mom_dtype bfloat16: per-worker momentum stored in bf16 — half the
    optimizer-state HBM — and training still converges."""
    import jax.numpy as jnp

    cfg = _tiny_cfg(mom_dtype="bfloat16")
    trainer, history, _ = _run(cfg, steps=20)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0]
    for m in jax.tree.leaves(trainer.state.exp_avg):
        assert m.dtype == jnp.bfloat16


def test_build_mesh_orders_distributed_init_before_cache(monkeypatch):
    """jax.distributed.initialize() must run before anything touches the
    XLA backend; the compile-cache gate probes jax.default_backend(), so
    build_mesh must call multihost_initialize FIRST (a wrong order trains N
    silently-disconnected replicas on multi-host launches)."""
    from distributed_lion_tpu.cli import run_clm
    from distributed_lion_tpu.parallel import mesh as mesh_mod

    calls = []
    monkeypatch.setattr(mesh_mod, "multihost_initialize",
                        lambda: calls.append("multihost"))
    monkeypatch.setattr(run_clm, "enable_compilation_cache",
                        lambda: calls.append("cache"))
    run_clm.build_mesh()
    assert calls == ["multihost", "cache"]


def test_multihost_initialize_raises_loudly_when_backend_up(monkeypatch):
    """With coordinator env vars set and a failed init that is NOT a benign
    double-initialize, multihost_initialize must raise (not silently run as
    a disconnected replica)."""
    import pytest as _pytest

    from distributed_lion_tpu.parallel import mesh as mesh_mod

    monkeypatch.setenv("COORDINATOR_ADDRESS", "127.0.0.1:9999")

    class _FakeDist:
        @staticmethod
        def initialize():
            raise RuntimeError(
                "jax.distributed.initialize() must be called before any JAX "
                "calls that might initialise the XLA backend.")

    monkeypatch.setattr(mesh_mod.jax, "distributed", _FakeDist)
    with _pytest.raises(RuntimeError, match="disconnected replica"):
        mesh_mod.multihost_initialize()

    class _FakeDouble:
        @staticmethod
        def initialize():
            raise RuntimeError("should only be called once")

    monkeypatch.setattr(mesh_mod.jax, "distributed", _FakeDouble)
    mesh_mod.multihost_initialize()  # benign: returns quietly


def test_force_cpu_platform_appends_device_count(monkeypatch):
    """cpu8 must APPEND the virtual-device flag to existing XLA_FLAGS — a
    setdefault would silently drop it and run 1-device benches as 'cpu8'."""
    from distributed_lion_tpu.parallel import mesh as mesh_mod

    monkeypatch.setenv("DLION_PLATFORM", "cpu8")
    monkeypatch.setenv("XLA_FLAGS", "--xla_cpu_enable_fast_math=false")
    recorded = {}
    monkeypatch.setattr(
        mesh_mod.jax.config, "update",
        lambda k, v: recorded.__setitem__(k, v))
    assert mesh_mod.force_cpu_platform() is True
    import os as _os

    flags = _os.environ["XLA_FLAGS"]
    assert "--xla_cpu_enable_fast_math=false" in flags
    assert "xla_force_host_platform_device_count=8" in flags
    assert recorded == {"jax_platforms": "cpu"}

    monkeypatch.setenv("DLION_PLATFORM", "tpu")
    assert mesh_mod.force_cpu_platform() is False


def test_bf16_param_small_lr_lion_warns(capsys):
    """Lion's fixed ±lr rounds to a NO-OP on bf16 params with |p| > ~lr·256
    (bf16 ULP) — the trainer must warn loudly rather than silently freeze
    most coordinates (scripts/loss_parity.py trains f32 masters for this
    reason)."""
    import jax.numpy as jnp

    mesh = make_mesh(data=8)
    cfg = _tiny_cfg(learning_rate=1e-4)
    model_cfg = dataclasses.replace(GPT2Config.tiny(),
                                    param_dtype=jnp.bfloat16)
    t = Trainer.for_gpt2(cfg, mesh, model_cfg)
    t.close()
    assert "below bf16 ULP" in capsys.readouterr().out
    # f32 params at the same lr: no warning
    t2 = Trainer.for_gpt2(cfg, mesh, GPT2Config.tiny())
    t2.close()
    assert "below bf16 ULP" not in capsys.readouterr().out
