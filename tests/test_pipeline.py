"""Pipeline parallelism: forward parity, autodiff, stacking round-trip.

Net-new vs the reference (data-parallel only, SURVEY §2.7). Invariants:
pipelined forward == sequential layer stack bit-for-bit, jax.grad through
the ppermute schedule == sequential grads, stack/unstack round-trips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from distributed_lion_tpu.parallel.pipeline import (
    from_last_stage,
    from_microbatches,
    pipeline_apply,
    stack_stage_params,
    to_microbatches,
    unstack_stage_params,
)

N_STAGES = 4
N_LAYER = 8


def _layer_params(key, n_layer, d):
    keys = jax.random.split(key, n_layer)
    return [
        {"w": jax.random.normal(k, (d, d)) * 0.3, "b": jnp.zeros((d,))}
        for k in keys
    ]


def _layer_fn(p, x):
    return x + jnp.tanh(x @ p["w"] + p["b"])


def _sequential(layers, x):
    for p in layers:
        x = _layer_fn(p, x)
    return x


@pytest.fixture(scope="module")
def pipe_mesh():
    devs = np.array(jax.devices()[:N_STAGES]).reshape(N_STAGES)
    return Mesh(devs, ("pipe",))


def _run_pipeline(mesh, stacked, xm):
    def body(stage_params, xm):
        local = jax.tree.map(lambda a: a[0], stage_params)  # [1, L/S,...] -> [L/S,...]
        return pipeline_apply(_layer_fn, local, xm, axis_name="pipe")

    return shard_map(
        body, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P("pipe")
    )(stacked, xm)


def test_stack_unstack_roundtrip():
    layers = _layer_params(jax.random.key(0), N_LAYER, 6)
    stacked = stack_stage_params(layers, N_STAGES)
    assert jax.tree.leaves(stacked)[0].shape[:2] == (N_STAGES, N_LAYER // N_STAGES)
    back = unstack_stage_params(stacked, N_LAYER)
    for a, b in zip(layers, back):
        np.testing.assert_array_equal(a["w"], b["w"])


def test_stack_requires_divisibility():
    with pytest.raises(ValueError):
        stack_stage_params(_layer_params(jax.random.key(0), 6, 4), 4)


def test_forward_matches_sequential(pipe_mesh):
    d, n_micro, mb = 6, 8, 2
    layers = _layer_params(jax.random.key(1), N_LAYER, d)
    stacked = stack_stage_params(layers, N_STAGES)
    x = jax.random.normal(jax.random.key(2), (n_micro * mb, d))
    xm = to_microbatches(x, n_micro)

    acc = _run_pipeline(pipe_mesh, stacked, xm)
    # out_specs=P('pipe') stacks the per-stage [n_micro, mb, d] buffers along
    # axis 0: [S*n_micro, mb, d]; last stage's slice is the real one
    acc = np.asarray(acc).reshape(N_STAGES, n_micro, mb, d)
    got = from_microbatches(jnp.asarray(acc[-1]))
    want = _sequential(layers, x)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)
    # earlier stages' buffers are zeros (never written)
    assert np.all(acc[:-1] == 0)


def test_from_last_stage_broadcasts(pipe_mesh):
    def body(x):
        stage = jax.lax.axis_index("pipe")
        val = jnp.where(stage == N_STAGES - 1, x * 7.0, jnp.zeros_like(x))
        return from_last_stage(val, "pipe")[None]

    x = jnp.ones((3,))
    out = shard_map(body, mesh=pipe_mesh, in_specs=(P(),), out_specs=P("pipe"))(x)
    np.testing.assert_allclose(np.asarray(out), 7.0)  # every stage got it


def test_grads_match_sequential(pipe_mesh):
    d, n_micro, mb = 4, 4, 2
    layers = _layer_params(jax.random.key(3), N_LAYER, d)
    stacked = stack_stage_params(layers, N_STAGES)
    x = jax.random.normal(jax.random.key(4), (n_micro * mb, d))
    xm = to_microbatches(x, n_micro)
    target = jax.random.normal(jax.random.key(5), (n_micro * mb, d))

    def pipe_loss(stacked, xm):
        def body(stage_params, xm):
            local = jax.tree.map(lambda a: a[0], stage_params)
            acc = pipeline_apply(_layer_fn, local, xm, axis_name="pipe")
            y = from_last_stage(acc, "pipe")
            loss = jnp.mean((from_microbatches(y) - target) ** 2)
            return loss[None]

        return shard_map(
            body, mesh=pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P("pipe")
        )(stacked, xm).mean()

    def seq_loss(stacked, xm):
        layers_l = unstack_stage_params(stacked, N_LAYER)
        y = _sequential(layers_l, from_microbatches(xm))
        return jnp.mean((y - target) ** 2)

    g_pipe = jax.grad(pipe_loss)(stacked, xm)
    g_seq = jax.grad(seq_loss)(stacked, xm)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_microbatch_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    np.testing.assert_array_equal(from_microbatches(to_microbatches(x, 4)), x)
    with pytest.raises(ValueError):
        to_microbatches(x, 5)
