"""Fleet-restart persistence (ISSUE 20 layer c): the recovery shadow +
prefix chains survive a FULL fleet stop under a sha256 manifest.
Restore is token-identical with prefill tokens saved by the warm-started
page pool; torn/truncated generations are detected by the manifest and
skipped loudly; the ``--resume_fleet`` CLI path rides the same plane —
plus the banked ``fleet_resilience`` evidence section and its
check_evidence stage."""

import importlib.util
import json
import os
import pathlib

import jax
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.serve import fleet_state
from distributed_lion_tpu.serve.engine import (
    RecoveryRecord,
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)
from distributed_lion_tpu.serve.replica_plane import ServingFleet
from distributed_lion_tpu.train import journal as journal_mod
from distributed_lion_tpu.train.resilience import MANIFEST, sha256_file

_CFG = GPT2Config.tiny()
_PARAMS = gpt2_init(jax.random.key(0), _CFG)
_MODEL = ServeModel.for_gpt2(_PARAMS, _CFG)


def _factory(**kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8,
                prefix_cache=True, num_blocks=64)
    base.update(kw)

    def factory():
        return ServingEngine(_MODEL, ServeConfig(**base))

    return factory


def _reqs(n=4, max_new=12):
    rng = np.random.default_rng(31)
    shared = [int(t) for t in rng.integers(1, _CFG.vocab_size, 8)]
    out = []
    for i in range(n):
        tail = [int(t) for t in rng.integers(1, _CFG.vocab_size, 2 + i)]
        out.append(Request(req_id=f"s{i}", tokens=shared + tail,
                           max_new_tokens=max_new, seed=i,
                           prefix_group="sys"))
    return out


def _clone(reqs):
    return [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed,
                    prefix_group=r.prefix_group) for r in reqs]


@pytest.fixture
def jrnl(tmp_path):
    j = journal_mod.Journal(str(tmp_path / "jrnl"))
    journal_mod.install(j)
    yield j
    journal_mod.uninstall(j)
    j.close()


def _drain(fleet, done):
    ticks = 0
    while fleet.has_work():
        for c in fleet.step():
            done[c.req_id] = c
        ticks += 1
        assert ticks < 400
    return done


# ----------------------------------------------------- the restart identity
@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
def test_full_stop_resume_token_identical_with_prefill_saved(
        tmp_path, sampling):
    """THE acceptance pin: stop a fleet mid-decode (the saved state is
    all that survives), resume a FRESH fleet from disk — every in-flight
    request finishes token-identically, and the persisted chains prime
    the new page pool so the restored requests' shared prefixes HIT
    instead of cold prefilling (prefill tokens saved > 0)."""
    samp = (dict(temperature=0.0) if sampling == "greedy"
            else dict(temperature=0.8, top_k=20))
    reqs = _reqs()
    base = _factory(**samp)().run(_clone(reqs))
    sdir = str(tmp_path / "state")

    fleet_a = ServingFleet(_factory(**samp), replicas=2, state_dir=sdir)
    done = {}
    for r in _clone(reqs):
        fleet_a.submit(r)
    for _ in range(4):                  # mid-decode, nothing finished
        for c in fleet_a.step():
            done[c.req_id] = c
    fleet_a.save_state()
    inflight = {r.req_id for r in fleet_a.export_records()}
    assert inflight                     # the stop really cut work short
    # fleet_a is now abandoned — a kill -9 of the parent process

    fleet_b = ServingFleet(_factory(**samp), replicas=2)
    state = fleet_state.load_fleet_state(sdir, now=0.0)
    out = fleet_state.resume_into(fleet_b, state)
    assert out["restored"] == len(inflight)
    assert out["chains_primed"] >= 1
    _drain(fleet_b, done)
    for r in reqs:
        assert done[r.req_id].tokens == base[r.req_id].tokens, \
            (sampling, r.req_id)
        assert done[r.req_id].reason == base[r.req_id].reason
    saved = sum(rep.engine.stats["shared_tokens"]
                for rep in fleet_b.replicas if rep.engine is not None)
    assert saved > 0                    # the warm start did real work


def test_resumed_deadline_travels_as_remaining_seconds(tmp_path):
    """A deadline persists as remaining wall seconds and re-stamps on
    the restorer's clock — and one that lapsed while the fleet was down
    restores already-expired, completing as an honest timeout."""
    import time

    sdir = str(tmp_path / "state")
    recs = [RecoveryRecord("live", [1, 2, 3], [7], seed=0, budget=6,
                           deadline_at=1000.0 + 30.0),
            RecoveryRecord("lapsed", [4, 5], [], seed=1, budget=6,
                           deadline_at=1000.0 - 2.0)]   # died while down
    fleet_state.save_fleet_state(sdir, recs, chains=[], tick=3,
                                 now=1000.0)
    state = fleet_state.load_fleet_state(sdir, now=50.0)
    by_id = {r.req_id: r for r in state["records"]}
    assert by_id["live"].deadline_at == pytest.approx(80.0)
    assert by_id["live"].committed == [7]
    assert by_id["lapsed"].deadline_at == pytest.approx(48.0)
    # now against the engine's REAL clock: the lapsed one restores
    # already-expired, the live one has 30s of runway
    eng = _factory()()
    fleet_state.resume_into(
        eng, fleet_state.load_fleet_state(sdir, now=time.monotonic()))
    done = {}
    while eng.has_work():
        for c in eng.step():
            done[c.req_id] = c
    assert done["lapsed"].reason == "timeout"
    assert done["live"].reason != "timeout"


# ------------------------------------------------------ manifest integrity
def test_torn_state_file_skipped_loudly_with_fallback(tmp_path, jrnl):
    sdir = tmp_path / "state"
    recs = [RecoveryRecord("a", [1, 2], [9, 9], seed=0, budget=8)]
    fleet_state.save_fleet_state(str(sdir), recs, [[1, 2]], tick=4,
                                 now=0.0)
    fleet_state.save_fleet_state(
        str(sdir), recs + [RecoveryRecord("b", [3], [], seed=1, budget=8)],
        [[1, 2]], tick=8, now=0.0)
    newest = sdir / "fleet-00000008.json"
    torn = newest.read_bytes()[:20]
    newest.write_bytes(torn)            # a torn write after the manifest
    state = fleet_state.load_fleet_state(str(sdir), now=0.0)
    assert state["tick"] == 4           # fell back a generation
    assert [r.req_id for r in state["records"]] == ["a"]
    events = [r for r in jrnl.tail() if r.get("kind") == "event"]
    corrupt = [r for r in events if r["name"] == "fleet_state_corrupt"]
    assert len(corrupt) == 1 and "torn" in corrupt[0]["reason"]
    assert corrupt[0]["path"].endswith("fleet-00000008.json")
    restored = [r for r in events if r["name"] == "fleet_state_restored"]
    assert restored and restored[0]["tick"] == 4

    # flip a byte (size intact): the sha256 catches what size cannot
    older = sdir / "fleet-00000004.json"
    raw = bytearray(older.read_bytes())
    raw[5] ^= 0xFF
    older.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="no valid fleet state"):
        fleet_state.load_fleet_state(str(sdir), now=0.0)
    with pytest.raises(FileNotFoundError, match="manifest"):
        fleet_state.load_fleet_state(str(tmp_path / "nowhere"), now=0.0)


def test_persist_cadence_prunes_and_manifest_verifies(tmp_path, jrnl):
    sdir = tmp_path / "state"
    fleet = ServingFleet(_factory(), replicas=2, state_dir=str(sdir),
                         persist_every=3)
    done = _drain_with(fleet, _clone(_reqs(max_new=16)))
    assert len(done) == 4
    assert fleet.stats["state_saves"] >= 2
    states = sorted(p.name for p in sdir.glob("fleet-*.json"))
    assert 1 <= len(states) <= 2        # pruned to the newest two
    man = json.loads((sdir / MANIFEST).read_text())
    assert sorted(man["files"]) == states
    for name, meta in man["files"].items():
        p = sdir / name
        assert p.stat().st_size == meta["bytes"]
        assert sha256_file(p) == meta["sha256"]
    assert not list(sdir.glob("*.tmp"))  # atomic writes left no debris
    saves = [r for r in jrnl.tail() if r.get("name") == "fleet_state_saved"]
    assert len(saves) == fleet.stats["state_saves"]


def _drain_with(fleet, todo):
    done = {}
    for r in todo:
        fleet.submit(r)
    ticks = 0
    while fleet.has_work():
        for c in fleet.step():
            done[c.req_id] = c
        ticks += 1
        assert ticks < 400
    return done


# ----------------------------------------------------------------- the CLI
def test_run_serve_cli_saves_at_drain_and_resumes(tmp_path, capsys):
    """``--fleet_state_dir`` banks state at drain (chains included);
    ``--resume_fleet`` restores it, primes the pool, and a follow-up
    request sharing the persisted prefix serves token-identically to a
    cold run — the warm start changes cost, never outputs."""
    from distributed_lion_tpu.cli.run_serve import main

    sdir = tmp_path / "state"
    shared = [11, 12, 13, 14, 15, 16, 17, 18]
    first = tmp_path / "first.jsonl"
    first.write_text("".join(
        json.dumps({"id": f"a{i}", "tokens": shared + [30 + i],
                    "max_new_tokens": 4, "seed": i,
                    "prefix_group": "sys"}) + "\n" for i in range(2)))
    nxt = tmp_path / "next.jsonl"
    nxt.write_text(json.dumps(
        {"id": "b0", "tokens": shared + [60, 61], "max_new_tokens": 5,
         "seed": 7, "prefix_group": "sys"}) + "\n")
    base = ["--model_family", "gpt2", "--model_name", "tiny",
            "--temperature", "0", "--max_seqs", "2", "--block_size", "4",
            "--prefix_cache", "--fleet_state_dir", str(sdir)]
    out = tmp_path / "r1.jsonl"
    main(base + ["--requests", str(first), "--out", str(out)])
    assert (sdir / MANIFEST).is_file()  # the drain save happened
    capsys.readouterr()
    warm = main(base + ["--resume_fleet", "--requests", str(nxt),
                        "--out", str(tmp_path / "r2.jsonl")])
    resumed = json.loads(capsys.readouterr().out.splitlines()[0])
    assert resumed["resumed"] == 0      # the first run drained fully...
    assert resumed["chains_primed"] >= 1   # ...but its chains warm-start
    cold = main(["--model_family", "gpt2", "--model_name", "tiny",
                 "--temperature", "0", "--max_seqs", "2",
                 "--block_size", "4", "--prefix_cache",
                 "--requests", str(nxt),
                 "--out", str(tmp_path / "r3.jsonl")])
    assert [r["tokens"] for r in warm] == [r["tokens"] for r in cold]
    with pytest.raises(ValueError, match="resume_fleet"):
        main(base[:-2] + ["--resume_fleet", "--requests", str(nxt),
                          "--out", str(tmp_path / "r4.jsonl")])


# ----------------------------------------------- banked evidence + stage
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_ce():
    spec = importlib.util.spec_from_file_location(
        "ce_fp", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    return ce


def test_banked_artifact_passes_fleet_resilience_stage():
    """The committed CPU artifact satisfies the ISSUE 20 stage: strict
    schema, all six markers, >= 3 distinct SIGKILL cut points (plus a
    sampled cut) with zero loss on real declared-dead processes, a
    restart leg that restored in-flight work with prefill tokens saved,
    and a fully-served socket soak pinned by its wire-byte digest — the
    gate runbook stage 5o re-judges after the on-chip recapture."""
    ce = _load_ce()
    assert ce.fleet_resilience_ok()
    with open(ce.SERVE_ARTIFACT) as f:
        doc = json.load(f)
    sec = doc["fleet_resilience"]
    assert len({r["kill_tick"] for r in sec["kill_matrix"]}) >= 3
    assert any(r["sampling"] == "stochastic" for r in sec["kill_matrix"])
    assert all(r["tokens_lost"] == 0 and r["declared_dead"] == 1
               for r in sec["kill_matrix"])
    assert sec["restart"]["prefill_tokens_saved"] > 0
    assert sec["socket_soak"]["completed"] == sec["socket_soak"]["requests"]
    assert len(sec["socket_soak"]["stream_sha256"]) == 64


def test_fleet_resilience_stage_rejects_bad_artifacts(tmp_path):
    ce = _load_ce()
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "serving.json"

    def reject(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        p.write_text(json.dumps(doc))
        assert not ce.fleet_resilience_ok(str(p))

    # artifact predates ISSUE 20 entirely (also a schema violation now)
    reject(lambda d: d.pop("fleet_resilience"))
    # each marker flips the stage
    for k in ("sigkill_identity", "sigkill_zero_token_loss",
              "process_isolated", "restart_identity",
              "restart_prefill_saved", "socket_soak_served"):
        reject(lambda d, k=k: d["fleet_resilience"]["markers"].update(
            {k: False}))
    # a kill row that lost tokens / diverged / never declared the death
    reject(lambda d: d["fleet_resilience"]["kill_matrix"][0].update(
        tokens_lost=2))
    reject(lambda d: d["fleet_resilience"]["kill_matrix"][1].update(
        identical=False))
    reject(lambda d: d["fleet_resilience"]["kill_matrix"][0].update(
        declared_dead=0))
    reject(lambda d: [r.update(migrated=0)
                      for r in d["fleet_resilience"]["kill_matrix"]])
    # too few cut points / greedy-only identity
    reject(lambda d: d["fleet_resilience"].update(
        kill_matrix=d["fleet_resilience"]["kill_matrix"][:1]))
    reject(lambda d: [r.update(sampling="greedy")
                      for r in d["fleet_resilience"]["kill_matrix"]])
    # the restart leg must have interrupted real work and saved prefill
    reject(lambda d: d["fleet_resilience"]["restart"].update(
        inflight_at_stop=0))
    reject(lambda d: d["fleet_resilience"]["restart"].update(
        prefill_tokens_saved=0))
    # a soak that dropped a request
    reject(lambda d: d["fleet_resilience"]["socket_soak"].update(
        completed=d["fleet_resilience"]["socket_soak"]["requests"] - 1))
    # strict schema: a malformed byte-determinism pin
    reject(lambda d: d["fleet_resilience"]["socket_soak"].update(
        stream_sha256="nope"))
    # the untouched artifact still passes from the tmp copy
    p.write_text(json.dumps(good))
    assert ce.fleet_resilience_ok(str(p))
