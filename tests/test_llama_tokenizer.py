"""Native Llama-family tokenizers: SentencePiece BPE (tokenizer.model) and
HF fast tokenizer.json — the formats the reference reaches through
AutoTokenizer (/root/reference/sft_llama2.py:157-158).

The tokenizer.json path is pinned token-for-token against the real HF
``tokenizers`` library (installed in this image). The SentencePiece path is
pinned against hand-computed merges on a tiny model built with the module's
own proto writer (round-tripped through parse_model_proto, so the wire
format itself is exercised)."""

from __future__ import annotations

import json
import os

import pytest

from distributed_lion_tpu.data.spm import (
    SentencePieceTokenizer, parse_model_proto, write_model_proto,
    _BYTE, _CONTROL, _NORMAL, _UNKNOWN, _USER_DEFINED,
)


def _tiny_sp_pieces():
    """Llama-shaped piece table: <unk>/<s>/</s>, 256 byte pieces, then
    BPE pieces with descending scores (score = -merge_rank)."""
    pieces = [("<unk>", 0.0, _UNKNOWN), ("<s>", 0.0, _CONTROL),
              ("</s>", 0.0, _CONTROL)]
    pieces += [(f"<0x{b:02X}>", 0.0, _BYTE) for b in range(256)]
    for ch in ["▁", "h", "e", "l", "o", "w", "r", "d"]:
        pieces.append((ch, -50.0, _NORMAL))  # base symbols, worst score
    merged = [("he", -1.0), ("ll", -2.0), ("hell", -3.0), ("hello", -4.0),
              ("▁hello", -5.0), ("wo", -6.0), ("wor", -7.0), ("worl", -8.0),
              ("world", -9.0), ("▁world", -10.0)]
    pieces += [(p, s, _NORMAL) for p, s in merged]
    return pieces


@pytest.fixture(scope="module")
def sp_tok(tmp_path_factory):
    blob = write_model_proto(_tiny_sp_pieces())
    d = tmp_path_factory.mktemp("sp")
    with open(d / "tokenizer.model", "wb") as f:
        f.write(blob)
    return SentencePieceTokenizer.load(str(d))


def test_proto_roundtrip():
    pieces = _tiny_sp_pieces()
    proto = parse_model_proto(write_model_proto(
        pieces, add_dummy_prefix=False, pad_id=-1, unk_id=0))
    assert proto["pieces"] == [(p, pytest.approx(s), t) for p, s, t in pieces]
    assert proto["model_type"] == 2
    assert proto["add_dummy_prefix"] is False
    assert proto["pad_id"] == -1  # negative int32 survives sign extension
    assert (proto["unk_id"], proto["bos_id"], proto["eos_id"]) == (0, 1, 2)


def test_sp_merge_order_and_dummy_prefix(sp_tok):
    ids = sp_tok.encode("hello world")
    pieces = [sp_tok.id_to_piece[i] for i in ids]
    # dummy prefix + whitespace escape: "▁hello" and "▁world" both exist
    assert pieces == ["▁hello", "▁world"]
    assert sp_tok.decode(ids) == "hello world"


def test_sp_bos_eos(sp_tok):
    ids = sp_tok.encode("hello", add_bos=True, add_eos=True)
    assert ids[0] == sp_tok.bos_id == 1
    assert ids[-1] == sp_tok.eos_id == 2
    # control pieces never decode into text
    assert sp_tok.decode(ids) == "hello"


def test_sp_byte_fallback(sp_tok):
    # '☃' has no piece → its UTF-8 bytes (e2 98 83) fall back to <0xXX>
    ids = sp_tok.encode("hello☃")
    pieces = [sp_tok.id_to_piece[i] for i in ids]
    assert pieces[:2] == ["▁hello"] or pieces[0] == "▁hello"
    assert pieces[-3:] == ["<0xE2>", "<0x98>", "<0x83>"]
    assert sp_tok.decode(ids) == "hello☃"


def test_sp_partial_merges(sp_tok):
    # "hold" shares letters but no full piece: h+o+l+d with no pair in vocab
    ids = sp_tok.encode("hold")
    pieces = [sp_tok.id_to_piece[i] for i in ids]
    assert pieces == ["▁", "h", "o", "l", "d"]


def test_sp_leftmost_tie_and_score_priority():
    # two competing merges with distinct scores: higher score wins first,
    # changing the result vs rank-order ("ab" then "bc" can't both fire)
    base = [("<unk>", 0.0, _UNKNOWN), ("<s>", 0.0, _CONTROL),
            ("</s>", 0.0, _CONTROL)]
    syms = [(c, -50.0, _NORMAL) for c in ["a", "b", "c"]]
    tok_hi_bc = SentencePieceTokenizer(parse_model_proto(write_model_proto(
        base + syms + [("ab", -2.0, _NORMAL), ("bc", -1.0, _NORMAL)],
        add_dummy_prefix=False)))
    pieces = [tok_hi_bc.id_to_piece[i] for i in tok_hi_bc.encode("abc")]
    assert pieces == ["a", "bc"]  # bc outranks ab
    tok_hi_ab = SentencePieceTokenizer(parse_model_proto(write_model_proto(
        base + syms + [("ab", -1.0, _NORMAL), ("bc", -2.0, _NORMAL)],
        add_dummy_prefix=False)))
    pieces = [tok_hi_ab.id_to_piece[i] for i in tok_hi_ab.encode("abc")]
    assert pieces == ["ab", "c"]


def test_sp_user_defined_matched_before_bpe():
    base = [("<unk>", 0.0, _UNKNOWN), ("<s>", 0.0, _CONTROL),
            ("</s>", 0.0, _CONTROL), ("<tool>", 0.0, _USER_DEFINED)]
    syms = [(c, -50.0, _NORMAL) for c in
            ["▁", "x", "y", "<", ">", "t", "o", "l"]]
    tok = SentencePieceTokenizer(parse_model_proto(write_model_proto(
        base + syms, add_dummy_prefix=False)))
    pieces = [tok.id_to_piece[i] for i in tok.encode("x<tool>y")]
    assert pieces == ["x", "<tool>", "y"]


def test_sp_control_never_matched_from_text(sp_tok):
    # literal "<s>" in raw text must NOT produce the control id
    ids = sp_tok.encode("<s>")
    assert sp_tok.bos_id not in ids


def test_sp_unigram_rejected():
    blob = write_model_proto(_tiny_sp_pieces(), model_type=1)
    with pytest.raises(ValueError, match="BPE"):
        SentencePieceTokenizer(parse_model_proto(blob))


def test_sp_empty_and_space_only(sp_tok):
    assert sp_tok.encode("") == []
    ids = sp_tok.encode(" ")
    assert sp_tok.decode(ids) in (" ", "")  # dummy-prefix strip


def test_sp_negative_special_ids():
    """int32 -1 ids (disabled specials) arrive 64-bit sign-extended on the
    wire; all four must come back as -1, not ~2^64, and encode must not
    emit a disabled bos/eos."""
    blob = write_model_proto(_tiny_sp_pieces(), bos_id=-1, eos_id=-1,
                             unk_id=0, pad_id=-1)
    tok = SentencePieceTokenizer(parse_model_proto(blob))
    assert tok.bos_id == -1 and tok.eos_id == -1
    assert tok.pad_id == 0  # disabled pad/eos fall back to a valid id
    ids = tok.encode("hello", add_bos=True, add_eos=True)
    assert all(0 <= i < tok.vocab_size for i in ids)


# ---------------------------------------------------------- tokenizer.json

SAMPLES = [
    "hello world",
    "Question: What's 2+2?\nAnswer: 4",
    "  leading spaces and   runs",
    "unicode: déjà vu ☃ 日本語",
    "numbers 1234567 and punct!!! ...",
    "tabs\tand\nnewlines\r\n",
]


@pytest.fixture(scope="module")
def trained_json(tmp_path_factory):
    """Train a small real byte-level BPE with the HF tokenizers library."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<|endoftext|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [s * 3 for s in SAMPLES] + [
        "the quick brown fox jumps over the lazy dog " * 5]
    tok.train_from_iterator(corpus, trainer)
    d = tmp_path_factory.mktemp("tj")
    path = os.path.join(str(d), "tokenizer.json")
    tok.save(path)
    return path, tok


def test_tokenizer_json_parity_bytelevel(trained_json):
    from distributed_lion_tpu.data.hf_tokenizer_json import TokenizerJSON

    path, hf = trained_json
    ours = TokenizerJSON.load(path)
    for s in SAMPLES:
        assert ours.encode(s) == hf.encode(s).ids, s
        assert ours.decode(ours.encode(s)) == s


def test_tokenizer_json_llama3_style_split(trained_json, tmp_path):
    """Llama-3's shape: Sequence[Split(tiktoken regex), ByteLevel(no regex)]."""
    from tokenizers import Tokenizer, pre_tokenizers, Regex

    path, _ = trained_json
    with open(path, encoding="utf-8") as f:
        spec = json.load(f)
    llama3_pat = (
        r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}|"
        r" ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+")
    hf = Tokenizer.from_str(json.dumps(spec))
    hf.pre_tokenizer = pre_tokenizers.Sequence([
        pre_tokenizers.Split(Regex(llama3_pat), behavior="isolated"),
        pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=False),
    ])
    p2 = tmp_path / "tokenizer.json"
    hf.save(str(p2))

    from distributed_lion_tpu.data.hf_tokenizer_json import TokenizerJSON

    ours = TokenizerJSON.load(str(p2))
    for s in SAMPLES:
        assert ours.encode(s) == hf.encode(s).ids, s


def test_tokenizer_json_added_tokens(trained_json, tmp_path):
    from tokenizers import Tokenizer
    from tokenizers.processors import TemplateProcessing  # noqa: F401

    path, hf = trained_json
    hf.add_special_tokens(["<|special|>"])
    p2 = tmp_path / "tokenizer.json"
    hf.save(str(p2))

    from distributed_lion_tpu.data.hf_tokenizer_json import TokenizerJSON

    ours = TokenizerJSON.load(str(p2))
    s = "hello <|special|> world"
    assert ours.encode(s) == hf.encode(s).ids
    # specials are dropped on decode
    assert "<|special|>" not in ours.decode(ours.encode(s))


def test_tokenizer_json_prefix_space_decode_parity(trained_json, tmp_path):
    """With ByteLevel add_prefix_space=true, decode must NOT strip a
    genuine leading space — the tokenizers ByteLevel decoder maps chars
    back to bytes verbatim (decode(encode(' hi')) keeps the space)."""
    import json as _json

    from tokenizers import Tokenizer, decoders, pre_tokenizers

    path, _ = trained_json
    with open(path, encoding="utf-8") as f:
        spec = _json.load(f)
    hf = Tokenizer.from_str(_json.dumps(spec))
    hf.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=True)
    hf.decoder = decoders.ByteLevel()  # the shape real checkpoints ship
    p2 = tmp_path / "tokenizer.json"
    hf.save(str(p2))

    from distributed_lion_tpu.data.hf_tokenizer_json import TokenizerJSON

    ours = TokenizerJSON.load(str(p2))
    for s in (" hi", "hi", "  two"):
        assert ours.encode(s) == hf.encode(s).ids, s
        assert ours.decode(ours.encode(s)) == hf.decode(
            hf.encode(s).ids, skip_special_tokens=True), s


def test_tokenizer_json_rejects_unknown_shapes(tmp_path):
    from distributed_lion_tpu.data.hf_tokenizer_json import TokenizerJSON

    with pytest.raises(ValueError, match="model type"):
        TokenizerJSON({"model": {"type": "Unigram"}})
    with pytest.raises(ValueError, match="normalizer"):
        TokenizerJSON({"model": {"type": "BPE", "vocab": {}, "merges": []},
                       "normalizer": {"type": "NFKC"}})


# ------------------------------------------------------------- dispatching

def test_load_tokenizer_dispatch(tmp_path, trained_json, capsys):
    from distributed_lion_tpu.data.tokenizer import (
        ByteTokenizer, load_tokenizer)

    # directory with tokenizer.model → SP
    blob = write_model_proto(_tiny_sp_pieces())
    spdir = tmp_path / "llama2ckpt"
    spdir.mkdir()
    (spdir / "tokenizer.model").write_bytes(blob)
    tok = load_tokenizer(str(spdir))
    assert isinstance(tok, SentencePieceTokenizer)
    assert tok.vocab_size == len(_tiny_sp_pieces())

    # sp: prefix on a bare file
    tok = load_tokenizer("sp:" + str(spdir / "tokenizer.model"))
    assert isinstance(tok, SentencePieceTokenizer)

    # directory with tokenizer.json → TokenizerJSON
    from distributed_lion_tpu.data.hf_tokenizer_json import TokenizerJSON

    path, _ = trained_json
    tok = load_tokenizer(os.path.dirname(path))
    assert isinstance(tok, TokenizerJSON)

    # unresolvable spec → ByteTokenizer + loud warning on stderr
    tok = load_tokenizer(str(tmp_path / "nonexistent-model"))
    assert isinstance(tok, ByteTokenizer)
    assert "WARNING" in capsys.readouterr().err
