"""Fixture: the control plane's one forbidden shortcut — deciding
membership INSIDE the jitted step. A worker-drop/rejoin is a host-side
mask transition between dispatches (train/control_plane.py consumes the
fault registry at the boundary); host-reading the alive mask or the
membership schedule inside the compiled step would stall the device
pipeline every step to ask a question whose answer only changes at
boundaries. Never imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def membership_step(params, grads, alive, schedule_step):
    widx = lax.axis_index("data")  # graft: disable=DLT005
    ballot = sum(jnp.sum(jnp.sign(g)) for g in jax.tree.leaves(grads))
    tally = lax.psum(jnp.where(alive[widx], ballot, 0), "data")  # graft: disable=DLT005
    if int(schedule_step) >= 0:     # DLT001: host sync — the membership
        # schedule is host state; consult it at the dispatch boundary
        alive = alive.at[2].set(False)
    mask = np.asarray(alive)        # DLT001: device→host copy per step
    return jax.tree.map(lambda p: p * (tally * mask.mean()), params)


def boundary_membership(plane, step):
    # NOT traced scope: membership transitions belong here — the control
    # plane consumes the fault registry between dispatches and the mask
    # is pushed as device state the NEXT step consumes
    return plane.membership_due(step)
