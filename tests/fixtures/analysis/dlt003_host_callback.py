"""Fixture: DLT003 — host callbacks inside traced scope."""
import jax


@jax.jit
def step(params, batch):
    loss = (params * batch).sum()
    print("loss is", loss)             # DLT003: trace-time only
    jax.debug.print("loss {}", loss)   # DLT003: per-step host callback
    return loss


def report(history):
    print("final loss", history[-1])  # NOT traced: host logging is fine
