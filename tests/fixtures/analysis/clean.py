"""Fixture: a file every graft-check tier-1 rule must pass."""
import json

import jax
from jax import lax

DATA_AXIS = "data"  # module constant assignment, not a call-site literal


@jax.jit
def step(params, grads):
    votes = jax.tree.map(lambda g: g > 0, grads)
    total = lax.psum(
        jax.tree.leaves(votes)[0].astype(jax.numpy.int8), DATA_AXIS)
    return jax.tree.map(lambda p: p - 0.1, params), total


def save_metrics(path, record):
    with open(path, "w") as f:
        json.dump(record, f, allow_nan=False)


def guarded(path):
    try:
        return path.read_bytes()
    except OSError:
        return None
