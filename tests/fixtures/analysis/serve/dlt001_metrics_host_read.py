"""Fixture: the metrics plane's one forbidden shortcut — a lifecycle
hook that reaches INTO the jitted decode tick and host-reads device
values to stamp a latency (the observability twin of the per-token EOS
branch: an `int(tok)` / `float(logit)` inside the compiled tick forces a
device→host round trip per token, so "turning metrics on" would change
the dispatch pattern the plane exists to observe). The real plane
(serve/metrics.py) never touches a device value: every stamp rides host
work the tick loop already does — submit bookkeeping, the one
`np.asarray` host read per tick at the dispatch boundary, completion
assembly — which is what keeps metrics-on byte-identical to metrics-off
(the `metrics_inert` marker of serving.json's slo section). Never
imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py), alongside the other serve/ fixtures."""
import jax
import jax.numpy as jnp


@jax.jit
def metered_decode_tick(params, lens, last_tok, metrics):
    logits = (params["w"] * last_tok[:, None]).sum(-1)
    tok = jnp.argmax(logits, axis=-1)
    # DLT001: stamping TTFT from a device scalar inside the tick —
    # the hook must read the tick's ONE host array, not the device
    metrics.on_first_token(int(tok[0]))
    if float(logits.max()) > 0:    # DLT001: host-side gauge branch
        metrics.set_gauges(active=float(lens.sum()))
    return tok, lens + 1


def host_metrics_hooks(metrics, toks, wall_ms):
    # NOT traced scope: the real hook sites — the per-tick host array
    # and a host wall clock are already host scalars, so the plane adds
    # zero syncs
    metrics.on_decode_tick(wall_ms, len(toks))
    return [int(t) for t in toks]
