"""Fixture: the serving engine's one forbidden shortcut — PER-TOKEN host
reads inside the jitted decode tick (the classic serving pitfall: an
`int(token)` / EOS branch inside the compiled tick forces a device→host
round trip per generated token and serializes the whole rolling batch).
The real engine (serve/engine.py) samples the whole tick's tokens on
device and the host reads ONE array per tick, at the dispatch boundary.
Never imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py). Lives under fixtures/analysis/serve/ the
way the DLT009 fixture lives under train/ — the fixture tree mirrors the
package tree it pins."""
import jax
import jax.numpy as jnp


@jax.jit
def decode_tick(params, pages, tables, lens, last_tok):
    logits = (params["w"] * last_tok[:, None]).sum(-1)
    tok = jnp.argmax(logits, axis=-1)
    first = int(tok[0])            # DLT001: per-token host read in the tick
    if float(logits.max()) > 0:    # DLT001: host-side EOS branch in the tick
        lens = lens + 1
    return tok, first, lens


def host_tick_loop(engine, toks):
    # NOT traced scope: reading the tick's WHOLE token array once per
    # dispatch is the engine's documented sync point
    return [int(t) for t in toks]
