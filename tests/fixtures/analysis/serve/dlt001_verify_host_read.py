"""Fixture: the speculative decoder's one forbidden shortcut — PER-DRAFT-
TOKEN host reads inside the jitted verify dispatch (the classic
speculative pitfall: an `int(accept[i])` / per-token acceptance branch
inside the compiled verify loop forces a device→host round trip per
proposed token, which erases exactly the dispatch amortization
speculation exists to buy). The real Speculator (serve/speculate.py)
scores all k proposed tokens per slot in ONE jitted call and the host
reads ONE (tokens, accept-counts) pair per tick, at the dispatch
boundary — acceptance/rollback are then pure host-side block-table math.
Never imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py). Lives under fixtures/analysis/serve/
beside the DLT001 decode-tick fixture — the fixture tree mirrors the
package tree it pins."""
import jax
import jax.numpy as jnp


@jax.jit
def verify_dispatch(params, pages, tables, lens, window, vcounts):
    logits = (params["w"] * window[..., None]).sum(-1)
    tok = jnp.argmax(logits, axis=-1)
    accepted = 0
    for i in range(int(vcounts[0])):   # DLT001: per-draft-token host read
        if int(tok[0, i]) == 0:        # DLT001: host acceptance branch
            break
        accepted += 1
    return tok, accepted


def host_commit(tables, accepts):
    # NOT traced scope: committing the accepted prefix from the tick's
    # ONE drained accept-count array is the documented sync point
    return [tables.commit(s, int(a)) for s, a in enumerate(accepts)]
