"""DLT011 fixture: direct wall-clock reads in serve/ outside the
``time_fn`` seam. The serve plane injects time (``ServeMetrics`` /
``ServingEngine`` / ``ServingFleet`` take ``time_fn=time.monotonic``) so
deadline and latency math is testable without sleeping; a raw
``time.time()`` in tick code bypasses the seam. The default-parameter
REFERENCE stays legal — the rule matches CALLS — and ``time.sleep`` is
pacing, not a clock read."""

import time


def deadline_at(req):
    return time.monotonic() + req.deadline_s        # DLT011


def tick_ms():
    t0 = time.time()                                # DLT011
    return (time.perf_counter() - t0) * 1e3         # DLT011


class Plane:
    def __init__(self, time_fn=time.monotonic):  # legal: the seam itself
        self._now = time_fn

    def pace(self):
        time.sleep(0.01)  # legal: not a clock read
        return self._now()

    def display_only(self):
        # a human-facing wall timestamp can opt out, visibly:
        return time.time()  # graft: disable=DLT011
