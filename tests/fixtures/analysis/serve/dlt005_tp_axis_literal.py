"""Fixture: DLT005 in serve-layer SHARDING code — hardcoded mesh-axis
string literals where the parallel.mesh constants belong. The TP serving
engine (serve/engine.py) threads TENSOR_AXIS from parallel/mesh through
its shard_map specs and psum exits; a literal "tensor" here silently
decouples from the mesh axis-naming convention (rename the axis once and
the serve path keeps compiling against a ghost name). Never imported;
parsed by graft-check's tier-1 tests (tests/test_analysis_lint.py)."""
from functools import partial

import jax
from jax.sharding import PartitionSpec as P


def pages_spec(n_layer):
    # DLT005: the page pool's kv-head axis named by a raw string literal
    spec = P(None, None, "tensor", None)
    return [{"k": spec, "v": spec} for _ in range(n_layer)]


def sharded_decode_tick(mesh, fn, param_specs, pages_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=(param_specs, pages_specs),
                         out_specs=P("tensor"),      # DLT005
                         check_vma=False)


def tp_degree(axis_name="tensor"):                   # DLT005: literal default
    return axis_name
