"""Fixture: the TP serving engine's forbidden shape — PER-TOKEN host
reads inside the SHARD_MAP'd decode tick. Under tensor parallelism the
cost is worse than the single-device version of this pitfall
(fixtures/analysis/serve/dlt001_decode_tick_host_read.py): an
`int(token)` inside the sharded tick forces every rank of the slice to
round-trip the host per generated token, serializing the whole mesh, not
just one chip. The real engine (serve/engine._jit_paged) keeps the one
host read per tick at the dispatch boundary, outside traced scope.
Never imported; parsed by graft-check's tier-1 tests."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.shard_map, mesh=None, in_specs=None, out_specs=None)
def sharded_decode_tick(params, pages, tables, lens, last_tok):
    logits = (params["w"] * last_tok[:, None]).sum(-1)
    tok = jnp.argmax(logits, axis=-1)
    first = int(tok[0])           # DLT001: per-token host read in the tick
    if float(logits.max()) > 0:   # DLT001: host-side branch on device data
        lens = lens + 1
    return tok, first, lens


def host_tick_loop(engine, toks):
    # NOT traced scope: one whole-batch token-array read per dispatch is
    # the engine's documented sync point — identical at any tp degree
    return [int(t) for t in toks]
