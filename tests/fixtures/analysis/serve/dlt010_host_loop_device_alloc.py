"""DLT010 fixture: device-array construction inside a host-side serve/
loop. Every iteration pays a fresh host->device transfer (and, for a
shape that varies with the request, a fresh lowering) — the engine idiom
is numpy/table math in the loop body with ONE jnp conversion at the
dispatch boundary (engine._dispatch_prefill). Comprehensions stay legal:
they are the one-shot construction idiom (kv_cache.init_pages)."""

import jax
import jax.numpy as jnp
import numpy as np


def admission_loop(pending):
    out = []
    for req in pending:  # host-side statement loop
        toks = jnp.asarray(req.tokens)      # DLT010: per-request transfer
        pad = jnp.zeros((4,), jnp.int32)    # DLT010: per-iteration alloc
        out.append((toks, pad))
    return out


def drain(queue):
    while queue:
        item = queue.pop()
        yield jax.device_put(item)          # DLT010: device_put in a loop


def legal_shapes(reqs):
    # one-shot construction via comprehension (the init_pages idiom) and
    # numpy accumulation with ONE conversion at the dispatch boundary
    pages = [jnp.zeros((2, 2)) for _ in range(4)]
    batch = np.stack([np.asarray(r.tokens) for r in reqs])
    return pages, jnp.asarray(batch)


def justified(pending):
    for req in pending:
        # a load-bearing per-request transfer can opt out, visibly:
        yield jnp.asarray(req.tokens)  # graft: disable=DLT010
