"""Fixture: DLT005 in serve-layer EXPERT-AXIS sharding code — hardcoded
mesh-axis string literals where the parallel.mesh constants belong. The
MoE serving engine (serve/engine.py, ISSUE 15) threads EXPERT_AXIS from
parallel/mesh through its shard_map specs and the model hook's
``ep_axis``; a literal "expert" here silently decouples from the mesh
axis-naming convention (rename the axis once and the MoE serve path keeps
compiling against a ghost name while the all_to_alls ride nothing).
Never imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py)."""

import jax
from jax.sharding import PartitionSpec as P


def expert_bank_specs(n_experts):
    # DLT005: the expert-bank leading dim named by a raw string literal
    return {"w_in": P("expert"), "w_out": P("expert")}


def sharded_moe_tick(mesh, fn, param_specs, pages_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=(param_specs, pages_specs),
                         out_specs=P(), check_vma=False)


def ep_degree(axis_name="expert"):                   # DLT005: literal default
    return axis_name
