"""Fixture: the migration path's one forbidden shortcut — PER-TOKEN host
reads inside a jitted migration re-prefill (replaying a migrated
request's committed history by host-reading each token's logits/draw
inside the compiled dispatch would pay len(committed) device→host round
trips per migration and serialize the survivor's whole rolling batch).
The real path (serve/engine._admit via serve/replica_plane) prefills the
committed history as ONE bucketed dispatch and host-reads exactly one
sampled token at the dispatch boundary — the resumed stream's first draw.
Never imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py), path-scoped under fixtures/analysis/serve/
like the other serving fixtures."""
import jax
import jax.numpy as jnp


@jax.jit
def migration_reprefill(params, pages, tables, committed, start):
    logits = (params["w"] * committed[:, None]).sum(-1)
    resumed = int(committed[0])   # DLT001: per-committed-token host read
    #                               inside the jitted re-prefill
    if float(logits.max()) > 0:   # DLT001: host-side resume branch in the
        start = start + 1         # compiled dispatch
    return logits, start, resumed


def host_migration(fleet, record):
    # NOT traced scope: the recovery record is host state — building the
    # resumption Request (prompt + committed + seed) is pure list math,
    # and the one host read happens at the prefill dispatch boundary
    return record.to_request()


def boundary_faults(tick):
    # NOT traced scope: the serve fault schedule is consumed between
    # fleet ticks (resilience.consume_due), never inside a dispatch
    sink = jnp.zeros((int(tick),))
    return sink
