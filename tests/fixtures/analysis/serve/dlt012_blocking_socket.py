"""Fixture: DLT012 — blocking socket/pipe reads without a deadline seam
in a serve/ module. The three naive calls below block forever on a dead
peer; the bounded/non-blocking variants show the legal seams (an
explicit socket timeout, the BlockingIOError non-blocking idiom), and
the last shows the suppression syntax."""

import os


def naive_server(sock):
    conn, peer = sock.accept()          # DLT012: unbounded accept
    data = conn.recv(4096)              # DLT012: unbounded recv
    return peer, data


def naive_pipe_reader(fd):
    return os.read(fd, 65536)           # DLT012: unbounded pipe read


def bounded_server(sock, wait_s=5.0):
    sock.settimeout(wait_s)             # the seam: a bounded socket
    conn, _ = sock.accept()
    return conn.recv(4096)


def nonblocking_accept(sock):
    try:
        return sock.accept()            # the other seam: non-blocking
    except BlockingIOError:
        return None


def justified(sock):
    # a deliberate block (e.g. a child worker whose ONLY job is waiting
    # on its parent) documents itself and suppresses the rule
    return sock.recv(1)  # graft: disable=DLT012
