"""Fixture: DLT005 in batch-sharded expert-parallel decode code —
hardcoded mesh-axis string literals where the parallel.mesh constants
belong. ISSUE 16 shards the engine's decode/prefill/verify BATCH over
the expert axis (slots ``P(EXPERT_AXIS)``, page pools
``P(EXPERT_AXIS, None, TENSOR_AXIS, None)``) and threads the same
constant into the training wire's balance-ring psum; a literal "expert"
in any of these specs silently decouples from the mesh axis-naming
convention — rename the axis once and the batch sharding keeps compiling
against a ghost name while every shard quietly decodes the full batch
again. Never imported; parsed by graft-check's tier-1 tests
(tests/test_analysis_lint.py)."""

import jax
from jax.sharding import PartitionSpec as P


def batch_sharded_specs(n_rest):
    # DLT005: the slot/batch dim of the decode operands named by a raw
    # string literal instead of parallel.mesh.EXPERT_AXIS
    return [P("expert")] * n_rest


def pool_spec():
    # DLT005: the page-pool block dim literal-named
    return P("expert", None, "tensor", None)


def balance_psum(tallies, axis="expert"):             # DLT005: literal default
    return jax.lax.psum(tallies, axis)
