"""Fixture: DLT004 — a raw typed PRNG key reaching serialization (the
resilience PR's latent bug: stochastic-mode checkpoints failed to save)."""
import jax


def save_state_bad(manager, step, state):
    # DLT004: an rng leaf in the payload, no key_data/pack shim in scope
    manager.save(step, {"params": state.params, "rng": state.rng})


def save_state_good(manager, step, state):
    # shimmed with key_data: not flagged
    manager.save(step, {"params": state.params,
                        "rng": jax.random.key_data(state.rng)})
