"""Fixture: DLT008 — mutable default arguments."""


def accumulate(x, acc=[]):      # DLT008
    acc.append(x)
    return acc


def configure(overrides={}):    # DLT008
    return dict(overrides)


def fresh(x, acc=None):         # not flagged: the None idiom
    acc = acc or []
    acc.append(x)
    return acc
