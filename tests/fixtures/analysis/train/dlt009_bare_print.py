"""DLT009 fixture: bare ``print()`` in a module under a ``train/``
directory (this fixture lives under ``fixtures/analysis/train/`` so the
path-scoped rule applies to it exactly as it does to the real
``distributed_lion_tpu/train/`` modules). Console output here must route
through ``train/journal.emit`` — mirrored to stdout, recorded in the run
journal — so the control plane consumes one event stream."""


def report_progress(step, loss):
    print(f"step {step}: loss {loss:.3f}")  # ← DLT009: bypasses the journal
    return loss


def warn_operator(msg):
    print(f"WARNING: {msg}")  # ← DLT009: an event the journal never sees
    # justified escape hatch exercised below: the suppression syntax works
    # for DLT009 exactly as for every other rule
    print("low-level diagnostics")  # graft: disable=DLT009
