"""Fixture: DLT001 — host-sync calls inside traced scope. Never imported;
parsed by graft-check's tier-1 tests (tests/test_analysis_lint.py)."""
import jax
import numpy as np
from jax import lax


@jax.jit
def step(params, batch):
    loss = (params["w"] * batch).sum()
    bad1 = float(loss)            # DLT001: host sync in jitted fn
    bad2 = loss.item()            # DLT001
    bad3 = np.asarray(loss)       # DLT001
    return bad1 + bad2 + bad3.sum()


def outer(xs):
    def body(carry, x):           # traced: passed to lax.scan by name
        carry = carry + x
        host = jax.device_get(carry)   # DLT001
        return carry, host

    return lax.scan(body, 0.0, xs)


def host_side(metrics):
    # NOT traced scope: float() on host values is fine here
    return {k: float(v) for k, v in metrics.items()}
