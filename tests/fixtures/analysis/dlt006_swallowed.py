"""Fixture: DLT006 — swallowed exceptions (broad except, inert body)."""


def commit(path, data):
    try:
        path.write_bytes(data)
    except Exception:      # DLT006: the failure vanishes
        pass


def drain(futures):
    for f in futures:
        try:
            f.result()
        except Exception:  # DLT006: inert continue
            continue


def logged(path):
    try:
        return path.read_bytes()
    except Exception as e:  # not flagged: the handler DOES something
        print(f"read failed: {e}")
        raise


class Holder:
    def __del__(self):
        try:
            self.close()
        except Exception:  # not flagged: finalizers must not raise
            pass
