"""Fixture: the vote guard's one forbidden shortcut — host-syncing the
health mask / guard observations INSIDE the jitted step (the quarantine
decision belongs to the host machine, one dispatch behind; a step-side
read stalls the device pipeline every step). Never imported; parsed by
graft-check's tier-1 tests (tests/test_analysis_lint.py)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def guarded_step(params, grads, health):
    widx = lax.axis_index("data")  # graft: disable=DLT005
    onehot = jnp.arange(health.shape[0]) == widx
    nonfinite = sum(jnp.sum(~jnp.isfinite(g)) for g in jax.tree.leaves(grads))
    obs = lax.psum(jnp.where(onehot, nonfinite, 0), "data")  # graft: disable=DLT005
    if float(obs.sum()) > 0:            # DLT001: host sync in the step
        health = jnp.zeros_like(health)
    mask = np.asarray(health)           # DLT001: device→host copy per step
    return jax.tree.map(lambda p: p * mask.mean(), params)


def host_quarantine(obs):
    # NOT traced scope: the state machine reads the returned arrays one
    # dispatch behind — this is where device_get belongs
    return {k: np.asarray(jax.device_get(v)) for k, v in obs.items()}
