"""Fixture: DLT007 — non-strict json.dump/dumps."""
import json


def write_metrics(path, record):
    with open(path, "w") as f:
        json.dump(record, f)                     # DLT007


def row(record):
    return json.dumps(record, allow_nan=True)    # DLT007: explicit True


def strict_row(record):
    return json.dumps(record, allow_nan=False)   # not flagged
