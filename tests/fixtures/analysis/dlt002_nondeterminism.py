"""Fixture: DLT002 — nondeterminism baked in at trace time."""
import random
import time

import jax
import numpy as np


@jax.jit
def step(params):
    noise = random.random()       # DLT002: traced once, constant every step
    t0 = time.time()              # DLT002
    jitter = np.random.randn()    # DLT002
    return params * noise + t0 + jitter


def host_timer():
    return time.time()  # NOT traced: wall-clock on the host is fine
