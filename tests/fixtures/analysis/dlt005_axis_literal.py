"""Fixture: DLT005 — hardcoded mesh-axis-name string literals."""
from jax import lax
from jax.sharding import PartitionSpec as P


def vote(ballots):
    return lax.psum(ballots, "data")      # DLT005: literal axis name


def specs():
    return P("data", None)                # DLT005


def make_opt(axis_name="data"):           # DLT005: literal default
    return axis_name


# the string in a plain comparison or docstring is not an axis *usage*
def describe(name):
    return name == "data axis"
