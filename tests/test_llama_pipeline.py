"""Trainable pipeline parallelism for the Llama family (round-3 unlock):
real Llama blocks (RMSNorm/RoPE/SwiGLU/GQA) as GPipe stages, full vote-Lion
training over a dp x pp mesh.

Same load-bearing invariant as tests/test_pipeline_train.py: pipelining is a
pure re-schedule — dp=2 x pp=4 must reproduce the dp=2 trajectory at equal
global batch (only device placement changes)."""

import jax
import numpy as np
import pytest

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer

MODEL = LlamaConfig.tiny(n_layer=4, compute_dtype=np.float32)


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=5, per_device_train_batch_size=4,
        gradient_accumulation_steps=1, block_size=32, logging_steps=1,
        output_dir=None, seed=7,
    )
    base.update(kw)
    return TrainConfig(**base)


def _train(mesh, cfg, n_steps=5):
    trainer = Trainer.for_llama(cfg, mesh, MODEL, seed=123)
    blocks = synthetic_lm_dataset(
        max(64, trainer.global_train_batch() * 2), cfg.block_size,
        MODEL.vocab_size, seed=11,
    )
    hist = trainer.train(
        batch_iterator(blocks, trainer.global_train_batch(), seed=0),
        max_steps=n_steps,
    )
    params = jax.tree.map(np.asarray, jax.device_get(trainer.params))
    trainer.close()
    return [h["loss"] for h in hist if "loss" in h], params


def test_llama_pp_forward_matches_sequential():
    """Pipeline forward loss == plain forward loss on identical params."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.models.llama_pipe import (
        llama_pipeline_param_specs,
        llama_pipeline_params,
        make_llama_pipeline_loss,
    )
    from distributed_lion_tpu.models.loss import clm_loss_and_metrics

    pp = 4
    params = llama_init(jax.random.key(0), MODEL)
    tokens = np.random.default_rng(0).integers(
        0, MODEL.vocab_size, size=(4, 32)).astype(np.int32)

    mesh = make_mesh(data=1, pipe=pp, devices=jax.devices()[:pp])
    loss_fn = make_llama_pipeline_loss(MODEL, n_micro=2)
    pparams = llama_pipeline_params(params, pp)

    def body(pp_params, toks):
        loss, m = loss_fn(pp_params, toks, None)
        return m["loss"]

    loss_pp = shard_map(
        body, mesh=mesh,
        in_specs=(llama_pipeline_param_specs(), P()),
        out_specs=P(), check_vma=False,
    )(pparams, tokens)

    loss_seq, _ = clm_loss_and_metrics(
        llama_apply(params, tokens, MODEL), tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_seq),
                               rtol=2e-4, atol=2e-4)


def test_llama_pp_roundtrip_params():
    from distributed_lion_tpu.models.llama_pipe import (
        llama_pipeline_params, llama_unpipeline_params)

    params = llama_init(jax.random.key(1), MODEL)
    back = llama_unpipeline_params(
        llama_pipeline_params(params, 4), MODEL.n_layer)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "mesh_kw,cfg_kw",
    [
        pytest.param(dict(data=2, pipe=4),
                     dict(pipeline_parallel=4, pipeline_microbatches=2),
                     id="dp2xpp4"),
        pytest.param(dict(data=2, tensor=2, pipe=2),
                     dict(tensor_parallel=2, pipeline_parallel=2,
                          pipeline_microbatches=2),
                     id="dp2xtp2xpp2"),
    ],
)
def test_llama_pipelined_mesh_trajectory_matches_dp(mesh_kw, cfg_kw):
    """dp×pp — and dp×tp×pp, Megatron sharding inside the Llama stages —
    ≡ dp=2 at equal global batch."""
    from distributed_lion_tpu.models.llama_pipe import llama_unpipeline_params

    losses_dp, params_dp = _train(
        make_mesh(data=2, devices=jax.devices()[:2]), _cfg())
    losses_pp, params_pp = _train(make_mesh(**mesh_kw), _cfg(**cfg_kw))
    np.testing.assert_allclose(losses_pp, losses_dp, rtol=1e-4, atol=1e-4)
    restored = llama_unpipeline_params(params_pp, MODEL.n_layer)
    envelope = 2 * 1e-3 * 5  # 2·lr·n_steps ballot-flip envelope
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(restored)):
        assert np.abs(a.astype(np.float64) - b.astype(np.float64)).max() \
            <= envelope


def test_llama_pp_guards():
    mesh = make_mesh(data=2, pipe=4)
    with pytest.raises(ValueError, match="divisible"):
        Trainer.for_llama(_cfg(pipeline_parallel=4), mesh,
                          LlamaConfig.tiny(n_layer=3))
    with pytest.raises(NotImplementedError, match="tp_vocab"):
        Trainer.for_llama(_cfg(pipeline_parallel=2, tensor_parallel=2,
                               tp_vocab=True),
                          make_mesh(data=2, tensor=2, pipe=2), MODEL)


@pytest.mark.parametrize("chunks", [0, 4], ids=["dense", "chunked"])
def test_llama_sp_pp_trajectory_matches_dp(chunks):
    """dp=2 x sp=2 x pp=2 ≡ dp=2: ring attention inside every pipeline
    tick, rope offsets per seq shard, seq-parallel CE at the last stage —
    dense AND chunked (dv-layout) heads."""
    from distributed_lion_tpu.models.llama_pipe import llama_unpipeline_params

    losses_dp, params_dp = _train(
        make_mesh(data=2, devices=jax.devices()[:2]), _cfg(vocab_chunks=chunks))
    losses_sp, params_sp = _train(
        make_mesh(data=2, seq=2, pipe=2),
        _cfg(seq_parallel=2, pipeline_parallel=2, pipeline_microbatches=2,
             vocab_chunks=chunks))
    np.testing.assert_allclose(losses_sp, losses_dp, rtol=1e-4, atol=1e-4)
    restored = llama_unpipeline_params(params_sp, MODEL.n_layer)
    envelope = 2 * 1e-3 * 5
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(restored)):
        assert np.abs(a.astype(np.float64) - b.astype(np.float64)).max() \
            <= envelope


def test_run_clm_cli_llama_pp_smoke():
    from distributed_lion_tpu.cli.run_clm import main

    main([
        "--model_family", "llama", "--model_name", "tiny", "--lion",
        "--async_grad", "--dataset", "synthetic", "--max_steps", "2",
        "--per_device_train_batch_size", "2",
        "--gradient_accumulation_steps", "1", "--block_size", "32",
        "--pipeline_parallel", "2", "--pipeline_microbatches", "2",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000",
    ])


def test_llama_pp_chunked_head_matches_dense():
    """pp × vocab_chunks on the untied lm_head (dv layout)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.models.llama_pipe import (
        llama_pipeline_param_specs,
        llama_pipeline_params,
        make_llama_pipeline_loss,
    )
    from distributed_lion_tpu.models.loss import clm_loss_and_metrics

    pp = 4
    params = llama_init(jax.random.key(0), MODEL)
    tokens = np.random.default_rng(0).integers(
        0, MODEL.vocab_size, size=(4, 32)).astype(np.int32)
    mesh = make_mesh(data=1, pipe=pp, devices=jax.devices()[:pp])
    loss_fn = make_llama_pipeline_loss(MODEL, n_micro=2, vocab_chunks=4)
    pparams = llama_pipeline_params(params, pp)

    def body(pp_params, toks):
        loss, m = loss_fn(pp_params, toks, None)
        return m["loss"]

    loss_pp = shard_map(
        body, mesh=mesh,
        in_specs=(llama_pipeline_param_specs(), P()),
        out_specs=P(), check_vma=False,
    )(pparams, tokens)
    loss_seq, _ = clm_loss_and_metrics(
        llama_apply(params, tokens, MODEL), tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_seq),
                               rtol=2e-4, atol=2e-4)


def test_llama_tp_sp_pp_full_composition_matches_dp():
    """The Llama twin of the full-mesh pin: tp=2 x sp=2 x pp=2 + chunked
    dv-head CE ≡ plain single-device training (rotary offsets composing
    with Megatron sharding inside ring-attention GPipe ticks)."""
    from distributed_lion_tpu.models.llama_pipe import llama_unpipeline_params

    losses_dp, params_dp = _train(
        make_mesh(data=1, devices=jax.devices()[:1]),
        _cfg(vocab_chunks=4, per_device_train_batch_size=8))
    losses_x, params_x = _train(
        make_mesh(data=1, tensor=2, seq=2, pipe=2),
        _cfg(tensor_parallel=2, seq_parallel=2, pipeline_parallel=2,
             pipeline_microbatches=2, vocab_chunks=4,
             per_device_train_batch_size=8))
    np.testing.assert_allclose(losses_x, losses_dp, rtol=1e-4, atol=1e-4)
    restored = llama_unpipeline_params(params_x, MODEL.n_layer)
    envelope = 2 * 1e-3 * 5
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(restored)):
        assert np.abs(a.astype(np.float64) - b.astype(np.float64)).max() \
            <= envelope
