"""Vote guard (ISSUE 5): Byzantine-tolerant elections, worker quarantine,
degraded-mode training.

The tentpole contracts, pinned here:

- **masked elections** — with a health mask, every wire excludes quarantined
  ballots from the tally and shrinks the majority threshold to the healthy
  quorum (numpy reference model per wire, including hier's
  majority-of-majorities with group-level abstention);
- **all-healthy bit-identity** — guard 'enforce' with an all-True mask
  produces bit-identical params AND momentum to guard 'off' across all four
  wires × vote_buckets {1, 4} × det/stoch, on the XLA and Pallas paths (the
  acceptance criterion);
- **ballot-health signals** — per-worker nonfinite / frozen-ballot /
  outlier-disagreement detection from inside the jitted step;
- **the quarantine state machine** — strikes, cooldown, readmission
  healing, quorum refusal (host-side, train/vote_guard.py);
- **degraded-mode training** — with one poisoned worker, '--vote_guard
  enforce' tracks a clean W−1 run while guard-off demonstrably degrades
  (flipped ballot) or silently poisons momentum forever (NaN grads — the
  motivating latent bug);
- **quarantine × resilience** — the mask round-trips through checkpoints
  exactly; elastic resume heals quarantined momenta before the remap.
"""

import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.data.sources import (
    batch_iterator,
    synthetic_lm_dataset,
)
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.optim import (
    distributed_lion,
    expand_worker_state,
    heal_worker_momentum,
    init_global_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.lion import LionState
from distributed_lion_tpu.parallel import collectives
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train import resilience
from distributed_lion_tpu.train.loop import TrainConfig, Trainer
from distributed_lion_tpu.train.vote_guard import VoteGuard

WIRES = ["sign_psum", "packed_allgather", "packed_a2a", "hier:4"]


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8)


@pytest.fixture(autouse=True)
def _clean_faults():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


# ------------------------------------------------------- masked elections
def _ref_masked_election(ballots: np.ndarray, alive: np.ndarray,
                         wire: str) -> np.ndarray:
    """Numpy reference: the healthy-quorum majority each wire must
    implement. Flat wires: elected ⇔ healthy True-votes form a strict
    majority of the healthy quorum (tie → −1). hier: the same rule inside
    each group, then a strict majority of the groups that still hold a
    healthy member (a fully-quarantined group abstains)."""
    kind, group = wire.split(":") if ":" in wire else (wire, None)
    if kind != "hier":
        count = ballots[alive].sum(0)
        return count * 2 > alive.sum()
    g = int(group)
    w = ballots.shape[0]
    verdicts, galive = [], []
    for k in range(w // g):
        rows = slice(k * g, (k + 1) * g)
        a = alive[rows]
        tally = (np.where(ballots[rows], 1, -1)
                 * a[:, None].astype(int)).sum(0)
        verdicts.append(tally > 0)
        galive.append(bool(a.any()))
    verdicts = np.stack(verdicts)
    galive = np.asarray(galive)
    count = verdicts[galive].sum(0)
    return count * 2 > galive.sum()


@pytest.mark.parametrize("wire", WIRES)
def test_masked_election_matches_reference(mesh8, wire):
    """Quarantined ballots leave the tally; the threshold shrinks to the
    healthy quorum — per wire, at a ragged ballot size, with two sick
    workers (one of them the whole of no group: hier's group abstention
    needs a fully-sick group, covered by the second mask)."""
    n = 203
    rng = np.random.default_rng(3)
    ballots = rng.integers(0, 2, size=(8, n)).astype(bool)
    for sick in ([2, 5], [4, 5, 6, 7]):  # the 2nd kills hier group 1 of 2
        alive = np.ones(8, bool)
        alive[sick] = False

        def body(b, a):
            return collectives.majority_vote(b[0], "data", wire, a)

        got = np.asarray(shard_map(
            body, mesh=mesh8, in_specs=(P("data"), P()), out_specs=P(),
            check_vma=False,
        )(jnp.asarray(ballots), jnp.asarray(alive)))
        np.testing.assert_array_equal(
            got, _ref_masked_election(ballots, alive, wire), err_msg=wire)


@pytest.mark.parametrize("wire", WIRES)
def test_masked_all_healthy_bit_identical_collective(mesh8, wire):
    """An all-True mask must be a bitwise no-op at the collective level —
    including the bucketed form."""
    n = 1003
    rng = np.random.default_rng(11)
    ballots = jnp.asarray(rng.integers(0, 2, size=(8, n)).astype(bool))
    alive = jnp.ones((8,), jnp.bool_)

    def run(a, buckets):
        def body(b):
            return collectives.majority_vote_bucketed(
                b[0], "data", wire, buckets, a)

        return np.asarray(shard_map(
            body, mesh=mesh8, in_specs=(P("data"),), out_specs=P(),
            check_vma=False,
        )(ballots))

    np.testing.assert_array_equal(run(alive, 1), run(None, 1))
    np.testing.assert_array_equal(run(alive, 4), run(None, 4))


# --------------------------------------------------- optimizer bit-identity
def _toy_problem(world=8, n=40, vary_steps=0):
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (n,)), "b": jnp.zeros((3,))}
    grads = {
        "w": jax.random.normal(jax.random.key(1), (world, n)),
        "b": jax.random.normal(jax.random.key(2), (world, 3)),
    }
    return params, grads


def _run_steps(opt, params, grads_fn, n_steps, mesh, world, rng=None,
               has_elected=False, guard_on=False, sick=None):
    """Drive opt.step under shard_map (test_vote_buckets idiom, extended
    with guard state and per-step grads via ``grads_fn(step)``)."""
    state = init_global_state(opt, params, world, rng=rng)
    if sick is not None and state.health is not None:
        h = np.ones(world, bool)
        h[sick] = False
        state = state._replace(health=jnp.asarray(h))
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(
        count=P(),
        exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None if rng is None else P(),
        elected=P() if has_elected else None,
        health=P() if guard_on else None,
        prev_ballot=P("data") if guard_on else None,
    )
    g_spec = jax.tree.map(lambda _: P("data"), grads_fn(0))

    @jax.jit
    def step(params, grads, state):
        def body(p, g, st):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            outs = opt.step(p, g, st)
            p_new, st_new = outs[0], expand_worker_state(outs[1])
            return p_new, st_new, (outs[-1] if guard_on else {})

        return shard_map(
            body, mesh=mesh, in_specs=(p_spec, g_spec, st_spec),
            out_specs=(p_spec, st_spec, P()), check_vma=False,
        )(params, grads, state)

    gf = None
    for t in range(n_steps):
        params, state, gf = step(params, grads_fn(t), state)
    return params, state, gf


def _assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


@pytest.mark.parametrize("wire", WIRES)
@pytest.mark.parametrize("stochastic", [False, True],
                         ids=["deterministic", "stochastic"])
@pytest.mark.parametrize("buckets", [1, 4])
def test_guard_all_healthy_bit_identical(mesh8, wire, stochastic, buckets):
    """The acceptance criterion: 'enforce' with an all-healthy mask is
    bit-identical to guard 'off' in params AND momentum, across all four
    wires × vote_buckets {1, 4} × det/stoch (XLA path)."""
    params, grads = _toy_problem()
    kw = dict(learning_rate=0.01, weight_decay=0.01, wire=wire,
              vote_buckets=buckets,
              max_grad_norm=1.0 if stochastic else None)
    rng = jax.random.key(7) if stochastic else None
    runs = {}
    for guard in ("off", "enforce"):
        opt = distributed_lion(guard=guard, **kw)
        runs[guard] = _run_steps(opt, params, lambda t: grads, 3, mesh8, 8,
                                 rng=rng, guard_on=guard != "off")
    _assert_trees_equal(runs["off"][0], runs["enforce"][0])
    _assert_trees_equal(runs["off"][1].exp_avg, runs["enforce"][1].exp_avg)


@pytest.mark.parametrize("buckets", [1, 4])
@pytest.mark.parametrize("wire", ["sign_psum", "packed_a2a"])
def test_guard_all_healthy_bit_identical_pallas(mesh8, wire, buckets):
    """Same contract on the Pallas window path (the mask zeroes the bucket
    ballot before it reaches the wire; kernels untouched)."""
    params, grads = _toy_problem(n=300)
    runs = {}
    for guard in ("off", "enforce"):
        opt = distributed_lion(learning_rate=0.02, weight_decay=0.05,
                               wire=wire, kernel="pallas",
                               vote_buckets=buckets, guard=guard)
        runs[guard] = _run_steps(opt, params, lambda t: grads, 3, mesh8, 8,
                                 guard_on=guard != "off")
    _assert_trees_equal(runs["off"][0], runs["enforce"][0])
    _assert_trees_equal(runs["off"][1].exp_avg, runs["enforce"][1].exp_avg)


def test_guard_lazy_vote_every_bit_identical(mesh8):
    """Guard × lazy refresh: the per-slot prev-ballot cache must not
    disturb the rotating-slice election (elected cache compared too)."""
    params, grads = _toy_problem()
    runs = {}
    for guard in ("off", "enforce"):
        opt = distributed_lion(learning_rate=0.01, wire="sign_psum",
                               vote_every=4, guard=guard)
        runs[guard] = _run_steps(opt, params, lambda t: grads, 5, mesh8, 8,
                                 has_elected=True, guard_on=guard != "off")
    _assert_trees_equal(runs["off"][0], runs["enforce"][0])
    np.testing.assert_array_equal(np.asarray(runs["off"][1].elected),
                                  np.asarray(runs["enforce"][1].elected))


def test_masked_optimizer_election_excludes_sick_worker(mesh8):
    """Semantics, not just identity: with worker 0 quarantined, the
    elections must equal those of an election among workers 1..7 alone
    (verified against the numpy healthy-majority over the actual ballots:
    ballot = b1*m + (1-b1)*g > 0, m = 0 at the first step)."""
    params, grads = _toy_problem()
    b1 = 0.9
    opt = distributed_lion(learning_rate=0.01, b1=b1, wire="sign_psum",
                           guard="enforce")
    p1, _, _ = _run_steps(opt, params, lambda t: grads, 1, mesh8, 8,
                          guard_on=True, sick=[0])
    flat_g = np.concatenate([np.asarray(grads["w"]),
                             np.asarray(grads["b"])], axis=1)
    ballots = (1 - b1) * flat_g > 0  # m == 0 at step 0
    alive = np.ones(8, bool)
    alive[0] = False
    expect = _ref_masked_election(ballots, alive, "sign_psum")
    flat_p0 = np.concatenate([np.asarray(params["w"]),
                              np.asarray(params["b"])])
    flat_p1 = np.concatenate([np.asarray(p1["w"]), np.asarray(p1["b"])])
    # Lion: p1 = p0*(1-lr*wd) - lr*sign → the update's sign IS the election
    got = (flat_p1 - flat_p0 * (1 - 0.01 * 0.01)) < 0
    np.testing.assert_array_equal(got, expect)


# ------------------------------------------------------------ guard signals
def _varied_grads(world, n, t, poison=None, kind=None):
    """Per-step-varying random grads (so honest ballots actually flip),
    with optional worker poisoning."""
    g = {
        "w": jax.random.normal(jax.random.key(100 + t), (world, n)),
        "b": jax.random.normal(jax.random.key(200 + t), (world, 3)),
    }
    if poison is None:
        return g

    def _p(x):
        x = np.array(x)  # writable copy (np.asarray of a jax array is RO)
        if kind == "nan":
            x[poison] = np.nan
        elif kind == "zero":
            x[poison] = 0.0
        return jnp.asarray(x)

    return jax.tree.map(_p, g)


def test_guard_frame_nonfinite_names_worker(mesh8):
    opt = distributed_lion(learning_rate=0.01, wire="sign_psum",
                           guard="observe")
    params, _ = _toy_problem()
    _, _, gf = _run_steps(
        opt, params, lambda t: _varied_grads(8, 40, t, poison=3, kind="nan"),
        2, mesh8, 8, guard_on=True)
    nf = np.asarray(gf["nonfinite"])
    assert nf[3] > 0 and (nf[[i for i in range(8) if i != 3]] == 0).all()


def test_guard_frame_frozen_ballot_names_worker(mesh8):
    """A zero-grad worker's ballot freezes at sign(m) — zero bit flips vs
    the previous vote, while honest workers (fresh random grads each step)
    keep flipping bits."""
    opt = distributed_lion(learning_rate=0.01, wire="sign_psum",
                           guard="observe")
    params, _ = _toy_problem()
    _, _, gf = _run_steps(
        opt, params, lambda t: _varied_grads(8, 40, t, poison=2,
                                             kind="zero"),
        3, mesh8, 8, guard_on=True)
    flips = np.asarray(gf["flips"])
    assert bool(np.asarray(gf["flip_valid"]))
    assert flips[2] == 0
    assert (flips[[i for i in range(8) if i != 2]] > 0).all()


def test_guard_enforce_sanitizes_momentum(mesh8):
    """enforce: nonfinite grads are zeroed out of the momentum update (the
    reference-lineage latent bug: one NaN batch used to poison exp_avg
    forever); observe keeps the raw semantics."""
    params, _ = _toy_problem()
    for guard, finite in (("enforce", True), ("observe", False)):
        opt = distributed_lion(learning_rate=0.01, wire="sign_psum",
                               guard=guard)
        _, st, _ = _run_steps(
            opt, params,
            lambda t: _varied_grads(8, 40, t, poison=1, kind="nan"),
            2, mesh8, 8, guard_on=True)
        mom = np.asarray(st.exp_avg["w"])
        assert np.isfinite(mom).all() == finite


# ----------------------------------------------------------- state machine
def _obs(world, nonfinite=(), frozen=(), disagree=None, voted=1):
    o = {
        "guard_nonfinite": np.zeros(world, np.int32),
        "guard_frozen": np.zeros(world, np.int32),
        "guard_disagree": (np.full(world, 0.25)
                           if disagree is None else np.asarray(disagree)),
        "guard_voted_steps": np.asarray(voted, np.int32),
    }
    for w in nonfinite:
        o["guard_nonfinite"][w] = 1
    for w in frozen:
        o["guard_frozen"][w] = 1
    return o


def test_state_machine_strikes_quarantine_cooldown_readmit():
    g = VoteGuard(4, "enforce", strike_threshold=2, cooldown_steps=10)
    ev = g.update(1, _obs(4, nonfinite=[2]), 1)
    assert not ev.quarantined and g.strikes[2] == 1
    ev = g.update(2, _obs(4, nonfinite=[2]), 1)
    assert ev.quarantined == [2] and ev.mask_changed
    assert not g.healthy[2] and g.healthy_count() == 3
    # still sick while quarantined: no further transitions until cooldown
    ev = g.update(5, _obs(4, nonfinite=[2]), 1)
    assert not ev.quarantined and not ev.readmitted
    # cooldown elapsed → readmission probe
    ev = g.update(12, _obs(4), 1)
    assert ev.readmitted == [2] and g.healthy[2]
    assert g.quarantine_events == 1 and g.readmit_events == 1


def test_state_machine_strike_decay_forgives_transients():
    g = VoteGuard(4, "enforce", strike_threshold=3, cooldown_steps=10)
    g.update(1, _obs(4, nonfinite=[0]), 1)
    g.update(2, _obs(4), 1)   # clean window: decay
    g.update(3, _obs(4), 1)   # back to zero
    assert g.strikes[0] == 0 and g.healthy.all()


def test_state_machine_outlier_rule():
    g = VoteGuard(4, "enforce", strike_threshold=1, cooldown_steps=10)
    # honest cluster ~0.26, one voter at 0.43 (the measured flipped-worker
    # signature): both arms fire
    ev = g.update(1, _obs(4, disagree=[0.26, 0.43, 0.25, 0.27]), 1)
    assert ev.quarantined == [1]
    # noise-dominated election: EVERYONE near 0.5 — the relative arm must
    # hold fire
    g2 = VoteGuard(4, "enforce", strike_threshold=1, cooldown_steps=10)
    ev = g2.update(1, _obs(4, disagree=[0.49, 0.51, 0.48, 0.5]), 1)
    assert not ev.quarantined


def test_state_machine_observe_mode_and_quorum():
    g = VoteGuard(4, "observe", strike_threshold=1, cooldown_steps=1000)
    for step, w in ((1, 0), (2, 1)):
        ev = g.update(step, _obs(4, nonfinite=[0, 1]), 1)
    assert g.healthy_count() == 2 and not g.quorum_ok()  # auto quorum = 3
    assert any("[observe] would have" in line for ev2 in [ev]
               for line in ev2.logs) or g.quarantine_events == 2
    rep = g.sick_report()
    assert set(rep["sick_workers"]) == {"0", "1"}


def test_state_machine_adopt_mask_and_validation():
    g = VoteGuard(4, "enforce")
    g.adopt_mask([True, False, True, True], step=7)
    assert not g.healthy[1] and g.quarantined_at[1] == 7
    with pytest.raises(ValueError):
        g.adopt_mask([True, True], step=0)
    with pytest.raises(ValueError):
        VoteGuard(4, "nonsense")
    with pytest.raises(ValueError):
        VoteGuard(4, "enforce", min_quorum=9)


def test_heal_worker_momentum_mean_of_healthy():
    exp_avg = {"w": jnp.asarray(np.arange(8, dtype=np.float32)
                                .reshape(4, 2))}
    healthy = np.array([True, False, True, True])
    healed = heal_worker_momentum(exp_avg, healthy, [1])
    got = np.asarray(healed["w"])
    expect = np.asarray(exp_avg["w"]).copy()
    expect[1] = expect[[0, 2, 3]].mean(0)
    np.testing.assert_allclose(got, expect)
    # untouched rows bit-identical
    np.testing.assert_array_equal(got[[0, 2, 3]],
                                  np.asarray(exp_avg["w"])[[0, 2, 3]])


# ------------------------------------------------- trainer: degraded mode
def _trainer_cfg(world_bs, steps, guard="off", poison="", outdir=None,
                 **kw):
    base = dict(
        lion=True, async_grad=True, wire="sign_psum", vote_every=1,
        vote_buckets=1, learning_rate=5e-3, lr_scheduler_type="constant",
        warmup_steps=0, max_steps=steps, weight_decay=0.0,
        per_device_train_batch_size=world_bs, gradient_accumulation_steps=1,
        block_size=32, logging_steps=1, output_dir=outdir, vote_guard=guard,
        guard_strikes=2, guard_cooldown=1000, inject_poison=poison,
    )
    base.update(kw)
    return TrainConfig(**base)


def _train(cfg, world, steps, model, seed=4):
    mesh = make_mesh(data=world, devices=jax.devices()[:world])
    tr = Trainer.for_gpt2(cfg, mesh, model)
    blocks = synthetic_lm_dataset(96, 32, model.vocab_size, seed=seed)
    hist = tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                    max_steps=steps)
    losses = [h["loss"] for h in hist if "loss" in h]
    return tr, losses


def test_poisoned_enforce_tracks_clean_w_minus_1(mesh8):
    """The acceptance pin: one flipped-ballot worker at W=4. Guard-off
    degrades the whole run; 'enforce' quarantines the adversary and tracks
    a clean W−1 (= 3 healthy voters, same global batch) run's loss. The
    W−1 leg uses bs 8 × 3 workers = bs 6 × 4 workers, so all three legs
    consume identical batches."""
    model = GPT2Config.tiny()
    steps = 40

    def tail(x):
        return float(np.mean(x[-10:]))

    _, clean = _train(_trainer_cfg(8, steps), 3, steps, model)
    tr_e, enf = _train(_trainer_cfg(6, steps, guard="enforce",
                                    poison="flipped_ballot:1"),
                       4, steps, model)
    rep = tr_e._guard.sick_report()
    tr_e.close()
    tr_o, off = _train(_trainer_cfg(6, steps, poison="flipped_ballot:1"),
                       4, steps, model)
    tr_o.close()
    # the adversary was identified and quarantined (outlier disagreement)
    assert rep["healthy_mask"] == [True, False, True, True]
    assert rep["sick_workers"]["1"]["outlier"] > 0
    gap_enforce = abs(tail(enf) - tail(clean))
    gap_off = abs(tail(off) - tail(clean))
    # enforce tracks clean W−1 within tolerance; guard-off demonstrably
    # degrades (measured: ~0.24 vs ~0.49 nats — the margins below leave
    # headroom for cross-version jitter while keeping the ordering strict)
    assert gap_enforce < 0.35, (gap_enforce, gap_off)
    assert gap_off > gap_enforce + 0.1, (gap_enforce, gap_off)


def test_nan_worker_poisons_momentum_only_without_guard(mesh8):
    """The motivating latent bug, end-to-end: a NaN-grad worker under guard
    'off' carries NaN momentum forever (invisible to the loss); 'enforce'
    quarantines it and keeps every momentum finite."""
    model = GPT2Config.tiny()
    tr_off, losses_off = _train(
        _trainer_cfg(2, 8, poison="nan_grads:3"), 8, 8, model)
    off_finite = all(np.isfinite(np.asarray(m)).all()
                     for m in jax.tree.leaves(tr_off.state.exp_avg))
    tr_off.close()
    tr_enf, losses_enf = _train(
        _trainer_cfg(2, 8, guard="enforce", poison="nan_grads:3"),
        8, 8, model)
    enf_finite = all(np.isfinite(np.asarray(m)).all()
                     for m in jax.tree.leaves(tr_enf.state.exp_avg))
    mask = np.asarray(tr_enf.state.health)
    tr_enf.close()
    assert not off_finite          # silently poisoned...
    assert all(np.isfinite(losses_off))  # ...while the loss looks fine
    assert enf_finite
    np.testing.assert_array_equal(mask, [True] * 3 + [False] + [True] * 4)


def test_readmission_probe_heals_and_requarantines(mesh8):
    """Short cooldown: the poisoned worker is quarantined, readmitted as a
    probe (momentum healed from the healthy mean), found still sick and
    re-quarantined — and every momentum stays finite throughout."""
    model = GPT2Config.tiny()
    tr, _ = _train(_trainer_cfg(2, 14, guard="enforce",
                                poison="nan_grads:1", guard_cooldown=4),
                   4, 14, model)
    g = tr._guard
    finite = all(np.isfinite(np.asarray(m)).all()
                 for m in jax.tree.leaves(tr.state.exp_avg))
    tr.close()
    assert g.quarantine_events >= 2 and g.readmit_events >= 1
    assert not g.healthy[1]
    assert finite


def test_min_quorum_refusal(mesh8):
    """Quorum floor: quarantining the only 'sick' worker below an absurd
    min_quorum must refuse loudly, not degrade silently."""
    model = GPT2Config.tiny()
    with pytest.raises(RuntimeError, match="quorum"):
        _train(_trainer_cfg(2, 10, guard="enforce", poison="nan_grads:0",
                            min_quorum=4), 4, 10, model)


def test_observe_mode_keeps_elections_untouched(mesh8):
    """Observe mode is purely observational: a poisoned run under
    'observe' must produce the SAME losses as guard 'off' (bit-identical
    elections), while still reporting what enforce would have done."""
    model = GPT2Config.tiny()
    tr_obs, obs = _train(_trainer_cfg(2, 8, guard="observe",
                                      poison="nan_grads:2"), 4, 8, model)
    rep = tr_obs._guard.sick_report()
    tr_obs.close()
    tr_off, off = _train(_trainer_cfg(2, 8, poison="nan_grads:2"),
                         4, 8, model)
    tr_off.close()
    np.testing.assert_array_equal(obs, off)
    assert "2" in rep["sick_workers"]


def test_guard_chunked_dispatch_counts_every_step(mesh8):
    """steps_per_call > 1: the guard's observations are SUMMED over the
    scanned chunk (not meaned like loss), so the host strike counter sees
    every poisoned step and the quarantine still lands."""
    model = GPT2Config.tiny()
    tr, losses = _train(_trainer_cfg(2, 9, guard="enforce",
                                     poison="nan_grads:2",
                                     steps_per_call=3, guard_strikes=3),
                        4, 9, model)
    mask = np.asarray(tr.state.health)
    rep = tr._guard.sick_report()
    tr.close()
    assert not mask[2]
    # 3 poisoned steps arrive in ONE chunk observation — enough strikes at
    # once to quarantine on the first applied window
    assert rep["sick_workers"]["2"]["nonfinite"] >= 3
    assert len(losses) >= 1


# ------------------------------------------------- sentinel interaction
def test_sentinel_bundle_names_sick_worker(mesh8, tmp_path):
    """Satellite: the crash bundle (and the trip reason) name the sick
    WORKER, not just the poisoned leaves — the guard's counters feed the
    sentinel."""
    model = GPT2Config.tiny()
    with pytest.raises(FloatingPointError, match="sick workers"):
        _train(_trainer_cfg(2, 8, guard="observe", poison="nan_grads:3",
                            nan_sentinel=True, outdir=str(tmp_path)),
               4, 8, model)
    bundles = sorted(pathlib.Path(tmp_path).glob("crash/step_*/bundle.json"))
    assert bundles
    bundle = json.loads(bundles[0].read_text())
    assert "3" in bundle["guard"]["sick_workers"]
    assert bundle["guard"]["sick_workers"]["3"]["nonfinite"] > 0


def test_sentinel_enforce_degraded_mode_survives(mesh8, tmp_path):
    """Under 'enforce' the sentinel must NOT kill a degraded-mode run: the
    sick worker's NaN is excluded from the healthy grad-norm and handled by
    quarantine instead."""
    model = GPT2Config.tiny()
    tr, losses = _train(_trainer_cfg(2, 8, guard="enforce",
                                     poison="nan_grads:3",
                                     nan_sentinel=True,
                                     outdir=str(tmp_path)), 4, 8, model)
    mask = np.asarray(tr.state.health)
    tr.close()
    assert len(losses) == 8 and all(np.isfinite(losses))
    assert not mask[3]
    assert not list(pathlib.Path(tmp_path).glob("crash/*"))


# --------------------------------------------- quarantine × resilience
def test_checkpoint_restores_quarantine_mask_exactly(mesh8, tmp_path):
    """A checkpoint saved with a quarantined worker restores the health
    mask (and the guard machine's view of it) exactly."""
    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(_trainer_cfg(2, 6, guard="enforce",
                                poison="nan_grads:2", outdir=out,
                                save_steps=6), 4, 6, model)
    saved_mask = np.asarray(tr.state.health)
    tr.close()
    assert not saved_mask[2]
    resilience.clear_faults()  # the resumed run is clean — mask persists
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr2 = Trainer.for_gpt2(_trainer_cfg(2, 12, guard="enforce",
                                        outdir=out, save_steps=6), mesh,
                           model)
    assert tr2.step_count == 6
    np.testing.assert_array_equal(np.asarray(tr2.state.health), saved_mask)
    np.testing.assert_array_equal(tr2._guard.healthy, saved_mask)
    tr2.close()


def test_guard_toggle_across_checkpoint(mesh8, tmp_path):
    """has_guard meta: a guard-on checkpoint restores into a guard-off run
    (fields stripped) and a guard-off checkpoint into a guard-on run
    (fresh all-healthy state attached)."""
    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(_trainer_cfg(2, 4, guard="enforce", outdir=out,
                                save_steps=4), 4, 4, model)
    tr.close()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr2 = Trainer.for_gpt2(_trainer_cfg(2, 8, outdir=out, save_steps=4),
                           mesh, model)
    assert tr2.step_count == 4 and tr2.state.health is None
    tr2.close()
    out2 = str(tmp_path / "run2")
    tr3, _ = _train(_trainer_cfg(2, 4, outdir=out2, save_steps=4), 4, 4,
                    model)
    tr3.close()
    tr4 = Trainer.for_gpt2(_trainer_cfg(2, 8, guard="enforce", outdir=out2,
                                        save_steps=4), mesh, model)
    assert tr4.step_count == 4
    assert np.asarray(tr4.state.health).all()
    tr4.close()


def test_elastic_resume_heals_quarantined_momentum(mesh8, tmp_path):
    """--elastic_resume W→W′ with a quarantined worker: only HEALTHY
    momenta enter the remap — the sick worker's row is re-averaged from
    the healthy mean first (pinned numerically against the manual
    heal+remap)."""
    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    mesh4 = make_mesh(data=4, devices=jax.devices()[:4])
    tr = Trainer.for_gpt2(_trainer_cfg(2, 4, guard="enforce", outdir=out,
                                       save_steps=4), mesh4, model)
    blocks = synthetic_lm_dataset(96, 32, model.vocab_size, seed=4)
    tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
             max_steps=4)
    # poison worker 1's momentum with garbage and quarantine it, then save:
    # the garbage must NOT leak through the elastic remap
    garbage = jax.tree.map(
        lambda m: jnp.asarray(np.asarray(m)).at[1].set(1e9),
        tr.state.exp_avg)
    mask = jnp.asarray([True, False, True, True])
    tr.state = tr.state._replace(exp_avg=garbage, health=mask)
    tr.step_count += 1  # force a distinct save step
    tr.save()
    expect = jax.device_get(jax.tree.map(
        lambda m: np.asarray(m), heal_worker_momentum(
            garbage, np.array([True, False, True, True]), [1])))
    tr.close()

    mesh2 = make_mesh(data=2, devices=jax.devices()[:2])
    tr2 = Trainer.for_gpt2(_trainer_cfg(4, 10, guard="enforce", outdir=out,
                                        save_steps=100,
                                        elastic_resume=True), mesh2, model)
    got = jax.device_get(tr2.state.exp_avg)
    # W=4 → W'=2 group re-average of the HEALED stack
    jax.tree.map(
        lambda g, e: np.testing.assert_allclose(
            np.asarray(g),
            np.asarray(e).reshape((2, 2) + np.asarray(e).shape[1:])
            .mean(1).astype(np.asarray(g).dtype), rtol=1e-5, atol=1e-9),
        got, expect)
    # fresh all-healthy guard state at W'
    assert np.asarray(tr2.state.health).tolist() == [True, True]
    assert not np.any(np.asarray(jax.tree.leaves(got)[0]) > 1e8)
    tr2.close()


# ----------------------------------------------------------- validation
def test_guard_validation():
    with pytest.raises(ValueError):
        distributed_lion(guard="sometimes")
    with pytest.raises(ValueError):
        distributed_lion(axis_name=None, guard="enforce")
    with pytest.raises(ValueError, match="vote_guard"):
        from distributed_lion_tpu.train.loop import make_optimizer

        make_optimizer(TrainConfig(lion=False, async_grad=False,
                                   vote_guard="enforce"))
    with pytest.raises(ValueError):
        resilience.parse_poison("bad_kind:1")
    with pytest.raises(ValueError):
        resilience.parse_poison("nan_grads:x")
    assert resilience.parse_poison("nan_grads:2") == ("nan_grads", 2, 0)
    assert (resilience.parse_poison("flipped_ballot:0:100")
            == ("flipped_ballot", 0, 100))


def test_guard_metrics_are_strict_json(mesh8, tmp_path):
    """The guard's logged metrics (guard_healthy etc.) must pass the
    strict-JSON validator — the [W] observation vectors never reach the
    log."""
    import subprocess
    import sys

    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(_trainer_cfg(2, 4, guard="enforce", outdir=out), 4, 4,
                   model)
    tr.close()
    proc = subprocess.run(
        [sys.executable, "scripts/validate_metrics.py",
         f"{out}/metrics.jsonl"],
        capture_output=True, text=True,
        cwd=str(pathlib.Path(__file__).resolve().parent.parent))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(line)
            for line in open(f"{out}/metrics.jsonl") if line.strip()]
    assert any("train/guard_healthy" in r for r in rows)


def test_sharded_step_wrapper_supports_guard(mesh8):
    """The standalone shard_map wrapper (optim.sharded — users who bring
    their own loop) must carry the guard state and return the guard frame;
    all-healthy results stay bit-identical to the guard-off wrapper."""
    from distributed_lion_tpu.optim.sharded import (
        make_sharded_step,
        shard_state,
    )

    params, grads = _toy_problem()
    outs = {}
    for guard in ("off", "enforce"):
        opt = distributed_lion(learning_rate=0.01, guard=guard)
        state = shard_state(init_global_state(opt, params, 8), mesh8)
        step = make_sharded_step(opt, mesh8, has_guard=guard != "off")
        if guard == "off":
            p, st = step(params, grads, state)
            outs[guard] = (p, st)
        else:
            p, st, gf = step(params, grads, state)
            outs[guard] = (p, st)
            assert np.asarray(gf["nonfinite"]).shape == (8,)
            assert np.asarray(st.health).all()
    _assert_trees_equal(outs["off"][0], outs["enforce"][0])
    _assert_trees_equal(outs["off"][1].exp_avg, outs["enforce"][1].exp_avg)
