"""HF-checkpoint ingestion: logit parity against the torch models.

The strongest possible offline check: build a randomly-initialized HF
GPT2LMHeadModel / LlamaForCausalLM (transformers is baked in; construction
from a config touches no network), ``save_pretrained`` it locally, import
with models/hf_import, and demand the JAX model's logits match the torch
model's on the same tokens. This pins every layout decision — Conv1D vs
Linear orientation, q|k|v packing, the RoPE half-rotation → interleaved
permutation, GQA head mapping, tied vs untied heads, eps plumbing.
"""

import dataclasses

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from distributed_lion_tpu.models.hf_import import (  # noqa: E402
    detect_family,
    gpt2_from_hf,
    llama_from_hf,
    load_state_dict,
)


@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    cfg = transformers.GPT2Config(
        vocab_size=256, n_layer=2, n_head=4, n_embd=64, n_positions=128
    )
    model = transformers.GPT2LMHeadModel(cfg).eval()
    d = tmp_path_factory.mktemp("hf_gpt2")
    model.save_pretrained(d)
    return str(d), model


@pytest.fixture(scope="module")
def llama_dir(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=256, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, hidden_size=64, intermediate_size=128,
        max_position_embeddings=128,
    )
    model = transformers.LlamaForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_llama")
    model.save_pretrained(d)
    return str(d), model


def test_gpt2_logit_parity(gpt2_dir):
    from distributed_lion_tpu.models.gpt2 import gpt2_apply

    path, hf_model = gpt2_dir
    params, cfg = gpt2_from_hf(path)
    assert cfg.n_layer == 2 and cfg.n_head == 4 and cfg.d_model == 64
    assert cfg.vocab_size == 256 and cfg.n_ctx == 128

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 16), dtype=np.int64)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.numpy()
    cfg = dataclasses.replace(cfg, compute_dtype=jnp.float32, remat=False)
    got = np.asarray(gpt2_apply(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_llama_logit_parity(llama_dir):
    from distributed_lion_tpu.models.llama import llama_apply

    path, hf_model = llama_dir
    params, cfg = llama_from_hf(path)
    assert cfg.n_layer == 2 and cfg.n_head == 4 and cfg.n_kv_head == 2
    assert cfg.d_model == 64 and cfg.d_ff == 128 and cfg.vocab_size == 256
    assert cfg.rms_eps == hf_model.config.rms_norm_eps

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 16), dtype=np.int64)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.numpy()
    cfg = dataclasses.replace(cfg, compute_dtype=jnp.float32, remat=False)
    got = np.asarray(llama_apply(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_detect_family(gpt2_dir, llama_dir):
    assert detect_family(gpt2_dir[0]) == "gpt2"
    assert detect_family(llama_dir[0]) == "llama"


def test_load_state_dict_formats(tmp_path, gpt2_dir):
    # safetensors dir already covered; exercise the .npz branch round-trip
    sd = load_state_dict(gpt2_dir[0])
    npz = tmp_path / "m.npz"
    np.savez(npz, **sd)
    rt = load_state_dict(str(npz))
    assert set(rt) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(rt[k], sd[k])


def test_gpt2_import_trains(gpt2_dir):
    """The imported checkpoint drops into the Trainer (the reference's
    finetune-from-pretrained path, run_clm.py:425-444)."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    path, _ = gpt2_dir
    params, model_cfg = gpt2_from_hf(path)
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=2, per_device_train_batch_size=1,
        gradient_accumulation_steps=1, block_size=32, logging_steps=1,
        output_dir=None,
    )
    trainer = Trainer.for_gpt2(cfg, make_mesh(), model_cfg, initial_params=params)
    blocks = synthetic_lm_dataset(
        max(64, trainer.global_train_batch()), cfg.block_size, model_cfg.vocab_size
    )
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0))
    assert hist and np.isfinite(hist[-1]["loss"])
    trainer.close()
