"""Native (C++) BPE merge core + corpus tokenization CLI.

The C++ core (native/bpe_core.cc) must match the Python ``_bpe`` path
token-for-token — same best-pair selection, same left-to-right collapse,
same byte<->unicode lowering — and ``cli.tokenize_corpus`` must produce
byte-identical shards at any worker count (the reference's
``datasets.map(num_proc=N)`` + group_texts caching, run_clm.py:463-544).
"""

from __future__ import annotations

import json
import os
import pathlib

import numpy as np
import pytest

from distributed_lion_tpu.data.bpe import BPETokenizer, train_bpe

CORPUS = [
    "The quick brown fox jumps over the lazy dog. " * 5,
    "Ünïcödé tèxt — em-dash, 中文字符, emoji 🎉🎊, tabs\t\tand\nnewlines " * 3,
    "def f(x):\n    return x ** 2  # code-ish 12345 67890 " * 4,
    "it's we've they'll don't I'm o'clock 'quoted' ",
]

TRICKY = [
    "",
    " ",
    "   leading and trailing   ",
    "a",
    "completely unseen wörds żółć flambé 999!?!?",
    "\n\n\n",
    "🎉" * 10,
    "mixedCASE WordBoundaries123abc",
]


@pytest.fixture(scope="module")
def tok():
    return train_bpe(CORPUS, vocab_size=400)


def _fresh(tok, native: bool) -> BPETokenizer:
    merges = [list(k) for k, _ in sorted(tok.ranks.items(), key=lambda kv: kv[1])]
    t = BPETokenizer(tok.vocab, merges)
    if not native:
        t._native = False
    return t


def test_native_core_builds(tok):
    from distributed_lion_tpu import native

    assert native.bpe_available()
    assert _fresh(tok, native=True)._native_core() is not None


def test_native_matches_python_token_for_token(tok):
    nat, py = _fresh(tok, True), _fresh(tok, False)
    for text in CORPUS + TRICKY:
        assert nat.encode(text) == py.encode(text), text[:40]
        assert (nat.encode(text, add_bos=True, add_eos=True)
                == py.encode(text, add_bos=True, add_eos=True))


def test_native_roundtrip_decode(tok):
    nat = _fresh(tok, True)
    for text in CORPUS:
        assert nat.decode(nat.encode(text)) == text


def test_native_fuzz_parity(tok):
    rng = np.random.default_rng(0)
    nat, py = _fresh(tok, True), _fresh(tok, False)
    alphabet = list("abcdefgh ABC.,!?'\n\t0123456789éü中🎉")
    for _ in range(50):
        n = int(rng.integers(0, 80))
        text = "".join(rng.choice(alphabet) for _ in range(n))
        assert nat.encode(text) == py.encode(text), repr(text)


def test_partial_byte_coverage_refused(tok):
    """A vocab that doesn't cover all 256 byte values must NOT get the
    native path (silent byte-dropping); it pins to the Python fallback."""
    merges = [list(k) for k, _ in sorted(tok.ranks.items(), key=lambda kv: kv[1])]
    vocab = dict(tok.vocab)
    # remove one single-char byte token and re-densify ids
    victim = next(t for t in vocab if len(t) == 1)
    del vocab[victim]
    dense = {t: i for i, t in enumerate(vocab)}
    t = BPETokenizer(dense, [m for m in merges
                             if victim not in m and "".join(m) in dense])
    assert t._native_core() is None


def test_native_core_tolerates_id_gaps(tok):
    """A vocab with holes in its id space (tokenizer.json files whose added
    tokens start past the last BPE id) still gets the native path — holes
    lower to empty, unreachable blobs — and matches the Python merge loop
    token-for-token."""
    merges = [list(k) for k, _ in sorted(tok.ranks.items(),
                                         key=lambda kv: kv[1])]
    gapped = dict(tok.vocab)
    gapped["<|added|>"] = max(gapped.values()) + 17  # hole before this id
    t = BPETokenizer(gapped, merges, specials=["<|added|>"])
    assert t._native_core() is not None
    text = "the quick brown fox! 1234"
    t_py = BPETokenizer(gapped, merges, specials=["<|added|>"])
    t_py._native = False
    assert t.encode(text) == t_py.encode(text)


def test_jsonl_robustness(tok, tmp_path):
    """Valid-JSON non-object lines and non-string fields are skipped, not
    fatal."""
    from distributed_lion_tpu.cli.tokenize_corpus import _iter_docs

    p = tmp_path / "weird.jsonl"
    with open(p, "w", encoding="utf-8") as f:
        f.write("123\n")
        f.write('"plain string"\n')
        f.write('{"text": 42}\n')
        f.write('{"text": null}\n')
        f.write('{"text": "good doc"}\n')
        f.write("not json at all {{{\n")
    assert list(_iter_docs([str(p)], "text")) == ["good doc"]


def test_env_kill_switch(tok, monkeypatch):
    monkeypatch.setenv("DLION_NATIVE_BPE", "0")
    t = _fresh(tok, True)
    assert t._native_core() is None  # falls back to the Python path
    assert t.encode("hello world") == _fresh(tok, False).encode("hello world")


# ------------------------------------------------------------- corpus CLI
def _write_corpus(root: pathlib.Path) -> None:
    (root / "a.txt").write_text(CORPUS[0], encoding="utf-8")
    (root / "b.txt").write_text(CORPUS[1], encoding="utf-8")
    with open(root / "c.jsonl", "w", encoding="utf-8") as f:
        f.write(json.dumps({"text": CORPUS[2]}) + "\n")
        f.write("\n")  # blank line skipped
        f.write(json.dumps({"other": "ignored"}) + "\n")
        f.write(json.dumps({"text": CORPUS[3]}) + "\n")


def test_tokenize_corpus_end_to_end(tok, tmp_path):
    from distributed_lion_tpu.cli.tokenize_corpus import main

    tok.save(str(tmp_path / "tok"))
    _write_corpus(tmp_path)
    out = tmp_path / "bins"
    main([
        "--text", str(tmp_path / "*.*"), "--tokenizer", f"bpe:{tmp_path/'tok'}",
        "--output_dir", str(out), "--num_proc", "1", "--shard_tokens", "200",
    ])
    meta = json.loads((out / "meta.json").read_text())
    assert meta["dtype"] == "uint16" and meta["n_docs"] == 4
    stream = np.concatenate([
        np.fromfile(out / s, np.uint16) for s in meta["shards"]
    ])
    assert stream.size == meta["n_tokens"]
    # the stream is the eos-joined concatenation of the docs in input order
    ref = []
    for doc in [CORPUS[0], CORPUS[1], CORPUS[2], CORPUS[3]]:
        ref.extend(tok.encode(doc, add_eos=True))
    np.testing.assert_array_equal(stream, np.asarray(ref, np.uint16))
    # shard size respected (all but the last full)
    sizes = [np.fromfile(out / s, np.uint16).size for s in meta["shards"]]
    assert all(s == 200 for s in sizes[:-1]) and len(sizes) >= 2


def test_tokenize_corpus_parallel_deterministic(tok, tmp_path):
    from distributed_lion_tpu.cli.tokenize_corpus import main

    tok.save(str(tmp_path / "tok"))
    _write_corpus(tmp_path)
    outs = []
    for np_, name in ((1, "seq"), (2, "par")):
        out = tmp_path / name
        main([
            "--text", str(tmp_path / "*.*"),
            "--tokenizer", f"bpe:{tmp_path/'tok'}",
            "--output_dir", str(out), "--num_proc", str(np_),
        ])
        meta = json.loads((out / "meta.json").read_text())
        outs.append(np.concatenate([
            np.fromfile(out / s, np.uint16) for s in meta["shards"]
        ]))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_corpus_to_training_end_to_end(tok, tmp_path):
    """The full data path: text corpus → tokenize_corpus shards (C++ BPE)
    → run_clm on bin: via the C++ mmap loader → vote-Lion training steps."""
    from distributed_lion_tpu.cli.run_clm import main as run_clm_main
    from distributed_lion_tpu.cli.tokenize_corpus import main as tok_main

    tok.save(str(tmp_path / "tok"))
    # enough text for a few 32-token blocks
    big = tmp_path / "corpus"
    big.mkdir()
    for i in range(4):
        (big / f"doc{i}.txt").write_text(CORPUS[i % len(CORPUS)] * 3,
                                         encoding="utf-8")
    out = tmp_path / "bins"
    tok_main([
        "--text", str(big / "*.txt"), "--tokenizer", f"bpe:{tmp_path/'tok'}",
        "--output_dir", str(out), "--num_proc", "1",
    ])
    run_clm_main([
        "--model_name", "tiny", "--dataset", f"bin:{out}/shard_*.bin",
        "--vocab_size", str(tok.vocab_size), "--lion", "--async_grad",
        "--max_steps", "2", "--per_device_train_batch_size", "1",
        "--gradient_accumulation_steps", "1", "--block_size", "32",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000",
    ])


def test_tokenized_bins_feed_token_dataset(tok, tmp_path):
    from distributed_lion_tpu.cli.tokenize_corpus import main
    from distributed_lion_tpu.data.sources import TokenDataset

    tok.save(str(tmp_path / "tok"))
    _write_corpus(tmp_path)
    out = tmp_path / "bins"
    main([
        "--text", str(tmp_path / "*.txt"), "--tokenizer", f"bpe:{tmp_path/'tok'}",
        "--output_dir", str(out), "--num_proc", "1",
    ])
    meta = json.loads((out / "meta.json").read_text())
    ds = TokenDataset.from_bin(out / meta["shards"][0], block_size=16)
    assert len(ds) > 0 and ds.blocks.shape[1] == 16
    # first block must replay the first doc's tokens
    first_doc = tok.encode(CORPUS[0], add_eos=True)
    np.testing.assert_array_equal(np.asarray(ds.blocks[0], np.int32),
                                  np.asarray(first_doc[:16], np.int32))
