"""Run journal (ISSUE 7): the step-time observability contract.

What these pin:

- the journal is OBSERVATIONAL — losses/params are bit-identical
  journal-on vs journal-off across vote_buckets {1,4} on BOTH kernel
  paths (XLA and Pallas): every span is host wall time around a host
  region, nothing reaches the traced step;
- per-event overhead is bounded (the recorder must be cheap enough to
  ride every dispatch);
- the JSONL sink rotates atomically and recovers from a crash mid-write
  (injected through the PR-3 fault registry): the torn record is the only
  loss, every surviving file passes the strict journal schema;
- the offline analyzer (cli/run_analyze, stdlib-only by file path)
  attributes ≥95% of measured step wall to named buckets on a real
  trainer leg, closes the wall identity, merges deliberately clock-skewed
  multi-host journals onto one timeline and reports step-skew
  percentiles;
- the caller-thread ckpt spans cross-check the existing ckpt_stall_s
  ledger; committer-thread spans are excluded from step-wall attribution;
- crash bundles carry journal_tail.jsonl; preemption drains and guard
  quarantine transitions land as events.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train import journal, resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "distributed_lion_tpu")


def _load_by_path(name, rel):
    spec = importlib.util.spec_from_file_location(name,
                                                  os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# stdlib-only contract: both load by FILE PATH, no package import, no jax
run_analyze = _load_by_path("journal_run_analyze",
                            "distributed_lion_tpu/cli/run_analyze.py")
validate_metrics = _load_by_path("journal_validate_metrics",
                                 "scripts/validate_metrics.py")


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8)


def _tiny_cfg(**kw):
    from distributed_lion_tpu.train.loop import TrainConfig

    base = dict(lion=True, async_grad=True, wire="sign_psum", vote_every=1,
                vote_buckets=1, learning_rate=1e-3, warmup_steps=1,
                max_steps=3, per_device_train_batch_size=1,
                gradient_accumulation_steps=1, block_size=32,
                logging_steps=1, output_dir=None, save_steps=10**6,
                resume_from_checkpoint=False)
    base.update(kw)
    return TrainConfig(**base)


def _train(mesh, cfg, steps=3, seed=4):
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    tr = Trainer.for_gpt2(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                  model_cfg.vocab_size, seed=seed)
    hist = tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                    max_steps=steps)
    return tr, hist


# ------------------------------------------------------ observational contract
@pytest.mark.parametrize("kern", ["xla", "pallas"])
@pytest.mark.parametrize("buckets", [1, 4])
def test_bit_identity_journal_on_vs_off(mesh8, tmp_path, kern, buckets):
    """The acceptance pin: elections/params/losses are BIT-identical with
    the journal on vs off, for vote_buckets {1,4} x XLA/Pallas — the
    journal records host wall time only and can never move an election."""
    runs = {}
    for on in (False, True):
        cfg = _tiny_cfg(kernel=kern, vote_buckets=buckets, journal=on,
                        output_dir=str(tmp_path / f"{kern}{buckets}{on}"))
        tr, hist = _train(mesh8, cfg)
        runs[on] = ([h["loss"] for h in hist if "loss" in h],
                    jax.device_get(tr.params))
        tr.close()
    assert runs[True][0] == runs[False][0]
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), runs[True][1], runs[False][1])


# --------------------------------------------------- recorder micro-contracts
def test_event_overhead_bounded(tmp_path):
    """The recorder rides every dispatch: per-event cost (serialize +
    buffered write + ring append) must stay well under a millisecond even
    on a loaded CI box."""
    j = journal.Journal(str(tmp_path), ring=64)
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        j.event("step_log", step=i, steps_per_sec=123.456)
    dt = time.perf_counter() - t0
    j.close()
    assert dt / n < 1e-3, f"{dt / n * 1e6:.1f} us/event"
    assert len(j.tail()) == 64  # ring stayed bounded


def test_rotation_and_crash_mid_write_recovery(tmp_path):
    """Atomic rotation + torn-write recovery: rotate at a tiny max_bytes,
    then tear a write mid-line through the PR-3 fault registry. The torn
    record is the ONLY loss — every file (rotated + live) passes the
    strict journal schema, and a re-opened journal truncates the torn tail
    and keeps appending."""
    d = str(tmp_path)
    try:
        j = journal.Journal(d, max_bytes=700, ring=16)
        for i in range(12):
            j.event("filler", step=i, pad="x" * 80)
        rotated = [f for f in os.listdir(d) if f.startswith("journal_rank0.")
                   and f != "journal_rank0.jsonl"]
        assert rotated, "tiny max_bytes produced no rotation"
        resilience.inject_fault("journal_torn_write", 1)
        j.event("doomed", step=99)          # torn on disk, sink disabled
        j.event("ring_only", step=100)      # ring keeps recording
        assert any(r["name"] == "ring_only" for r in j.tail())
        j.close()
        raw = open(os.path.join(d, "journal_rank0.jsonl"), "rb").read()
        assert not raw.endswith(b"\n")      # the tear is really on disk
        # recovery: a fresh journal truncates the torn tail and appends
        j2 = journal.Journal(d, ring=16)
        j2.event("after_recovery", step=101)
        j2.close()
        names = []
        for f in sorted(os.listdir(d)):
            errs = validate_metrics.validate_journal_file(os.path.join(d, f))
            assert errs == [], (f, errs)
            with open(os.path.join(d, f)) as fh:
                names += [json.loads(line)["name"] for line in fh]
        assert "after_recovery" in names and "journal_recovered" in names
        assert "doomed" not in names        # torn record stayed dead
    finally:
        resilience.clear_faults()


def test_emitter_mirrors_and_records(tmp_path, capsys):
    """journal.emit: byte-for-byte the old print to stdout, PLUS a log
    record in the active journal; inert (print-only) with none active."""
    journal.emit("[x] no journal yet")
    assert capsys.readouterr().out == "[x] no journal yet\n"
    j = journal.Journal(str(tmp_path))
    journal.install(j)
    try:
        journal.emit("[x] hello")
        journal.event("side_event", k=1)
        assert capsys.readouterr().out == "[x] hello\n"
        recs = j.tail()
        assert any(r["kind"] == "log" and r["msg"] == "[x] hello"
                   for r in recs)
        assert any(r["name"] == "side_event" for r in recs)
    finally:
        journal.uninstall(j)
        j.close()
    journal.emit("[x] after uninstall")  # must not raise or record


# ------------------------------------------------------------------- analyzer
def test_trainer_leg_attribution_coverage(mesh8, tmp_path):
    """THE acceptance criterion at test scale: a real journal-on trainer
    leg (with async checkpoints, so the ckpt bucket is exercised)
    attributes >=95% of measured step wall to the named buckets, closes
    the wall identity, and its files pass the strict schema + the
    check_evidence journal stage."""
    cfg = _tiny_cfg(journal=True, output_dir=str(tmp_path), save_steps=2,
                    max_steps=6, logging_steps=2)
    tr, _ = _train(mesh8, cfg, steps=6)
    ckpt_spans = []
    committer_spans = []
    tr.close()  # drains the last async save — its spans + stall included
    stall = tr.checkpointer.total_stall_s
    report = run_analyze.analyze_dir(str(tmp_path))
    assert report is not None and report["schema_errors"] == 0
    att = report["attribution"]
    assert att["closes"], att
    assert att["steps"] == 6
    assert att["coverage"] >= 0.95, att
    assert att["buckets"]["dispatch"]["s"] > 0
    assert att["buckets"]["logging"]["s"] > 0
    # the validator accepts what the trainer wrote
    jdir = os.path.join(str(tmp_path), "journal")
    for f in os.listdir(jdir):
        assert validate_metrics.validate_journal_file(
            os.path.join(jdir, f)) == []
    # ckpt span cross-check: caller-thread ckpt spans ~ the stall ledger
    # (same blocked regions, measured by the same clock); committer spans
    # exist and are excluded from attribution
    for f in os.listdir(jdir):
        with open(os.path.join(jdir, f)) as fh:
            for line in fh:
                r = json.loads(line)
                if r.get("kind") != "span" or \
                        not str(r["name"]).startswith("ckpt"):
                    continue
                (committer_spans if r.get("thread") == "committer"
                 else ckpt_spans).append(r)
    assert committer_spans, "async commit produced no committer spans"
    span_s = sum(r["dur"] for r in ckpt_spans)
    assert abs(span_s - stall) <= 0.05 + 0.25 * stall, (span_s, stall)
    # the check_evidence stage consumes exactly this directory shape
    ce = _load_by_path("journal_check_evidence", "scripts/check_evidence.py")
    assert ce.journal_ok(str(tmp_path))


def test_analyzer_merges_skewed_multi_host_journals(tmp_path):
    """Synthetic two-rank journals with DELIBERATE clock skew: the ranks'
    monotonic epochs differ by ~4900s (different boot times), related only
    through the meta wall anchors. The merge must put both on one
    timeline, the attribution must sum to the measured step wall, and the
    step-skew percentiles must report the real ~30ms arrival spread — not
    the 4900s monotonic gap."""
    def rec(**kw):
        return json.dumps(kw, allow_nan=False)

    r0 = [rec(kind="meta", name="journal_start", t=100.0, rank=0,
              wall=1000.0, pid=1, version=1),
          rec(kind="event", name="train_start", t=100.0, rank=0, step=0),
          rec(kind="span", name="data_wait", t=100.1, rank=0, dur=0.1,
              step=0),
          rec(kind="span", name="dispatch", t=100.7, rank=0, dur=0.6,
              step=0),
          rec(kind="span", name="device_wait", t=100.9, rank=0, dur=0.2,
              step=1),
          rec(kind="span", name="logging_drain", t=100.95, rank=0,
              dur=0.05, step=1),
          rec(kind="span", name="ckpt/drain", t=100.99, rank=0, dur=0.04,
              step=1),
          # committer-thread span overlapping the step wall: EXCLUDED
          rec(kind="span", name="ckpt/digest", t=100.99, rank=0, dur=0.5,
              step=1, thread="committer"),
          rec(kind="event", name="step_log", t=100.96, rank=0, step=1),
          rec(kind="event", name="train_end", t=101.0, rank=0, step=2)]
    r1 = [rec(kind="meta", name="journal_start", t=5000.0, rank=1,
              wall=1000.02, pid=2, version=1),
          rec(kind="event", name="step_log", t=5000.97, rank=1, step=1)]
    (tmp_path / "journal_rank0.jsonl").write_text("\n".join(r0) + "\n")
    (tmp_path / "journal_rank1.jsonl").write_text("\n".join(r1) + "\n")
    report = run_analyze.analyze_dir(str(tmp_path))
    assert report["ranks"] == [0, 1] and report["schema_errors"] == 0
    att = report["attribution"]
    assert att["rank"] == 0 and att["closes"]
    assert att["wall_s"] == pytest.approx(1.0)
    assert att["buckets"]["data"]["s"] == pytest.approx(0.1)
    assert att["buckets"]["dispatch"]["s"] == pytest.approx(0.6)
    assert att["buckets"]["device"]["s"] == pytest.approx(0.2)
    assert att["buckets"]["logging"]["s"] == pytest.approx(0.05)
    assert att["buckets"]["ckpt"]["s"] == pytest.approx(0.04)  # no committer
    named = sum(v["s"] for v in att["buckets"].values())
    assert named + att["other_s"] + att["unattributed_s"] == pytest.approx(
        att["wall_s"], abs=1e-6)
    # rank0 logged step 1 at wall 1000.96, rank1 at 1000.02+0.97=1000.99:
    # 30ms of real skew, 4900s of monotonic-epoch difference corrected away
    skew = report["step_skew"]
    assert skew["steps_compared"] == 1
    assert skew["max_s"] == pytest.approx(0.03, abs=1e-6)


def test_analyzer_latest_leg_window_and_overlap_detection(tmp_path):
    """Journals append across watcher re-fires: attribution must cover the
    LATEST train_start..train_end leg, not the union plus the dead
    inter-run gap (which would sink coverage below the evidence gate
    forever). And 'closes' must actually catch the one failure the
    residual arithmetic can see: overlapping spans driving unattributed
    negative."""
    def rec(**kw):
        return json.dumps(kw, allow_nan=False)

    rows = [rec(kind="meta", name="journal_start", t=0.0, rank=0,
                wall=1000.0, version=1),
            # leg 1 (a dropped window), then a 90s dead gap, then leg 2
            rec(kind="event", name="train_start", t=0.0, rank=0, step=0),
            rec(kind="span", name="dispatch", t=9.0, rank=0, dur=9.0,
                step=0),
            rec(kind="event", name="train_end", t=10.0, rank=0, step=9),
            rec(kind="event", name="train_start", t=100.0, rank=0, step=9),
            rec(kind="span", name="dispatch", t=100.9, rank=0, dur=0.9,
                step=9),
            rec(kind="event", name="step_log", t=100.95, rank=0, step=12),
            rec(kind="event", name="train_end", t=101.0, rank=0, step=12)]
    (tmp_path / "journal_rank0.jsonl").write_text("\n".join(rows) + "\n")
    att = run_analyze.analyze_dir(str(tmp_path))["attribution"]
    assert att["wall_s"] == pytest.approx(1.0)      # leg 2 only, no gap
    assert att["steps"] == 3
    assert att["buckets"]["dispatch"]["s"] == pytest.approx(0.9)
    assert att["closes"] and att["coverage"] >= 0.89
    # overlap: two spans claiming the same wall → unattributed negative
    rows += [rec(kind="span", name="device_wait", t=100.9, rank=0, dur=0.9,
                 step=12)]
    (tmp_path / "journal_rank0.jsonl").write_text("\n".join(rows) + "\n")
    att = run_analyze.analyze_dir(str(tmp_path))["attribution"]
    assert att["unattributed_s"] < 0 and not att["closes"]


def test_analyzer_baseline_diff_names_regressing_bucket(tmp_path):
    """--baseline: the bucket whose wall share GREW the most vs the bench
    row's journal_attribution is named; artifacts predating the journal
    diff to None instead of erroring."""
    base = {"value": 1.0, "journal_attribution": {
        "buckets": {b: {"s": 0.0, "frac": f} for b, f in
                    [("device", 0.8), ("dispatch", 0.1), ("data", 0.02),
                     ("ckpt", 0.02), ("logging", 0.06)]}}}
    bpath = tmp_path / "BENCH_base.json"
    bpath.write_text(json.dumps(base))
    cur = {"rank": 0, "wall_s": 1.0, "steps": 10, "closes": True,
           "other_s": 0.0, "unattributed_s": 0.0, "coverage": 1.0,
           "buckets": {b: {"s": f, "frac": f} for b, f in
                       [("device", 0.6), ("dispatch", 0.1), ("data", 0.22),
                        ("ckpt", 0.02), ("logging", 0.06)]}}
    diff = run_analyze.diff_vs_baseline(
        cur, run_analyze.load_baseline_attribution(str(bpath)))
    assert diff["regressing_bucket"] == "data"
    assert diff["frac_delta"]["data"] == pytest.approx(0.2)
    old = tmp_path / "BENCH_old.json"
    old.write_text(json.dumps({"value": 1.0}))
    assert run_analyze.load_baseline_attribution(str(old)) is None


# ------------------------------------------------------- subsystem event hooks
def test_crash_bundle_carries_journal_tail(mesh8, tmp_path):
    """An anomaly carries its own timeline: the NaN sentinel's crash
    bundle gains journal_tail.jsonl — the ring buffer's last records, in
    the same strict schema the live journal writes."""
    cfg = _tiny_cfg(journal=True, nan_sentinel=True, max_steps=3,
                    output_dir=str(tmp_path))
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    tr.params["wte"] = tr.params["wte"].at[0, 0].set(float("nan"))
    blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                  model_cfg.vocab_size, seed=4)
    with pytest.raises(FloatingPointError):
        tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                 max_steps=3)
    tr.close()
    bundles = sorted((tmp_path / "crash").iterdir())
    tail = bundles[0] / "journal_tail.jsonl"
    assert tail.exists()
    assert validate_metrics.validate_journal_file(str(tail)) == []
    kinds = {json.loads(line)["kind"] for line in open(tail)}
    assert "span" in kinds  # the timeline really is in the bundle


def test_preempt_drain_event_recorded(mesh8, tmp_path):
    """resilience.PreemptionGuard journals the drain (signal→boundary
    latency) when the trainer reaches the next dispatch boundary."""
    cfg = _tiny_cfg(journal=True, max_steps=8, output_dir=str(tmp_path),
                    save_steps=10**6)
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    model_cfg = GPT2Config.tiny()
    tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    tr._preempt_guard.trigger()
    blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                  model_cfg.vocab_size, seed=4)
    tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
             max_steps=8)
    assert tr.preempted
    tr.close()
    events = []
    jdir = tmp_path / "journal"
    for f in os.listdir(jdir):
        with open(jdir / f) as fh:
            events += [json.loads(line) for line in fh]
    drain = [r for r in events if r["name"] == "preempt_drain"]
    assert len(drain) == 1
    assert drain[0]["signal_to_boundary_s"] >= 0
    end = [r for r in events if r["name"] == "train_end"]
    assert end and end[0]["preempted"] is True


class _FakeJournal:
    def __init__(self):
        self.records_ = []

    def event(self, name, **fields):
        self.records_.append({"kind": "event", "name": name, **fields})

    def record(self, rec):
        self.records_.append(dict(rec))


def test_vote_guard_journals_transitions():
    """Quarantine/readmission transitions land as events — the state
    machine as a stream, not scraped log lines."""
    from distributed_lion_tpu.train.vote_guard import VoteGuard

    jr = _FakeJournal()
    g = VoteGuard(4, "enforce", strike_threshold=1, cooldown_steps=2,
                  journal=jr)
    obs = {"guard_nonfinite": np.array([0, 1, 0, 0]),
           "guard_frozen": np.zeros(4), "guard_disagree": np.zeros(4),
           "guard_voted_steps": np.array(1)}
    g.update(10, obs, 1)
    q = [r for r in jr.records_ if r["name"] == "guard_quarantine"]
    assert q and q[0]["worker"] == 1 and q[0]["step"] == 10
    clean = {"guard_nonfinite": np.zeros(4), "guard_frozen": np.zeros(4),
             "guard_disagree": np.zeros(4),
             "guard_voted_steps": np.array(1)}
    g.update(13, clean, 1)  # cooldown elapsed → readmission probe
    r = [x for x in jr.records_ if x["name"] == "guard_readmit"]
    assert r and r[0]["worker"] == 1


def test_autotune_trial_records_span():
    """run_trial_child journals one autotune/trial span per candidate —
    including the timeout path, where the span carries the error row."""
    from distributed_lion_tpu.ops.autotune import run_trial_child

    jr = _FakeJournal()
    out = run_trial_child({"knob": "lion_row_block",
                           "candidate": {"row_block": 128},
                           "info": {"n": 256}, "_test_sleep_s": 30},
                          timeout_s=0.5, journal=jr)
    assert "timeout" in out["error"]
    spans = [r for r in jr.records_ if r.get("name") == "autotune/trial"]
    assert len(spans) == 1
    assert spans[0]["knob"] == "lion_row_block"
    assert "timeout" in spans[0]["error"]
    assert spans[0]["dur"] >= 0.4
