"""Sequence-parallel SFT: the LoRA/frozen-base train step with tokens
sharded over the 'seq' axis (ring attention, boundary-label ppermute) must
reproduce the pure-dp trajectory — same rows, same vote world, tokens
merely split across devices. Net-new vs the reference (data-parallel only,
truncation at 1024 — SURVEY §5 long-context)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
from distributed_lion_tpu.models.lora import LoraConfig, apply_adapters, lora_init
from distributed_lion_tpu.models.loss import (
    clm_loss_and_metrics,
    clm_loss_seq_parallel,
)
from distributed_lion_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
        warmup_steps=2, max_steps=8, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=64, logging_steps=1,
        eval_steps=1000, save_steps=1000, seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _sft_pieces():
    model_cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(0), model_cfg)
    lcfg = LoraConfig(r=4, alpha=8)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    return model_cfg, base, lcfg, adapters


def _train(mesh, sp, steps=8):
    model_cfg, base, lcfg, adapters = _sft_pieces()
    cfg = _cfg()
    if sp > 1:
        def loss_fn(params, batch, dropout_key):
            effective = apply_adapters(base, params, lcfg)
            logits = llama_apply(effective, batch, model_cfg, seq_axis=SEQ_AXIS)
            return clm_loss_seq_parallel(logits, batch, SEQ_AXIS)

        trainer = Trainer(cfg, mesh, apply_fn=None, params=adapters,
                          loss_fn=loss_fn, batch_spec=P(DATA_AXIS, SEQ_AXIS))
    else:
        def loss_fn(params, batch, dropout_key):
            effective = apply_adapters(base, params, lcfg)
            logits = llama_apply(effective, batch, model_cfg)
            return clm_loss_and_metrics(logits, batch, None)

        trainer = Trainer(cfg, mesh, apply_fn=None, params=adapters,
                          loss_fn=loss_fn)

    rng = np.random.default_rng(7)
    rows = rng.integers(0, model_cfg.vocab_size,
                        size=(steps, trainer.global_train_batch(), 64),
                        ).astype(np.int32)
    history = trainer.train(iter(list(rows)), max_steps=steps)
    losses = [h["loss"] for h in history if "loss" in h]
    trainer.close()
    return losses


def test_sft_sp_trajectory_matches_pure_dp():
    mesh_sp = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    mesh_dp = make_mesh(data=2, devices=jax.devices()[:2])
    losses_sp = _train(mesh_sp, sp=4)
    losses_dp = _train(mesh_dp, sp=1)
    assert len(losses_sp) == len(losses_dp) > 0
    np.testing.assert_allclose(losses_sp, losses_dp, rtol=2e-2, atol=2e-2)


def test_sft_tp_sp_trajectory_matches_pure_dp():
    """dp=2 x tp=2 x sp=2 SFT (sharded frozen base + ring attention) must
    reproduce the dp=2 trajectory — the long-context multi-chip QLoRA shape
    (round-3 composition unlock; mirrors cli/run_sft's tp x sp wiring)."""
    from distributed_lion_tpu.models.lora import lora_adapter_specs
    from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS
    from distributed_lion_tpu.parallel.tensor_parallel import (
        llama_param_specs, validate_tp)

    model_cfg, base, lcfg, adapters = _sft_pieces()
    cfg_dp = _cfg()
    mesh_dp = make_mesh(data=2, devices=jax.devices()[:2])

    def dp_loss(params, batch, dropout_key):
        effective = apply_adapters(base, params, lcfg)
        logits = llama_apply(effective, batch, model_cfg)
        return clm_loss_and_metrics(logits, batch, None)

    tr_dp = Trainer(cfg_dp, mesh_dp, apply_fn=None, params=adapters,
                    loss_fn=dp_loss)

    validate_tp(model_cfg, 2, "llama")
    base_specs = llama_param_specs(model_cfg)
    adapters2 = lora_init(jax.random.key(1), base, lcfg)
    adapter_specs = lora_adapter_specs(adapters2, base_specs, TENSOR_AXIS)
    mesh_tpsp = make_mesh(data=2, tensor=2, seq=2, devices=jax.devices()[:8])

    def tpsp_loss(params, frozen, batch, dropout_key):
        effective = apply_adapters(frozen, params, lcfg, tp_axis=TENSOR_AXIS,
                                   base_specs=base_specs)
        logits = llama_apply(effective, batch, model_cfg,
                             tp_axis=TENSOR_AXIS, seq_axis=SEQ_AXIS)
        return clm_loss_seq_parallel(logits, batch, SEQ_AXIS)

    tr_tpsp = Trainer(_cfg(tensor_parallel=2, seq_parallel=2), mesh_tpsp,
                      apply_fn=None, params=adapters2,
                      param_specs=adapter_specs, loss_fn=tpsp_loss,
                      frozen_params=base, frozen_specs=base_specs,
                      batch_spec=P(DATA_AXIS, SEQ_AXIS))

    rng = np.random.default_rng(7)
    steps = 6
    rows = rng.integers(0, model_cfg.vocab_size,
                        size=(steps, tr_dp.global_train_batch(), 64),
                        ).astype(np.int32)
    h_dp = tr_dp.train(iter(list(rows)), max_steps=steps)
    h_tpsp = tr_tpsp.train(iter(list(rows)), max_steps=steps)
    l_dp = [h["loss"] for h in h_dp if "loss" in h]
    l_tpsp = [h["loss"] for h in h_tpsp if "loss" in h]
    tr_dp.close()
    tr_tpsp.close()
    assert len(l_dp) == len(l_tpsp) > 0
    np.testing.assert_allclose(l_tpsp, l_dp, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("vocab_chunks", ["0", "4"])
def test_run_sft_cli_tp_sp_smoke(vocab_chunks):
    """CLI wiring: --tensor_parallel 2 --seq_parallel 2 (+ NF4 base) runs,
    with both the dense and the chunked-vocab seq head."""
    from distributed_lion_tpu.cli.run_sft import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--seq_length", "64",
        "--num_train_samples", "32", "--size_valid_set", "8",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000", "--tensor_parallel", "2", "--seq_parallel", "2",
        "--quant", "nf4", "--quant_block", "16",
        "--vocab_chunks", vocab_chunks,
    ])


@pytest.mark.parametrize("vocab_chunks", ["0", "4"])
def test_run_sft_cli_seq_parallel_smoke(vocab_chunks):
    """sp-only CLI: dense and chunked-vocab seq heads both run."""
    from distributed_lion_tpu.cli.run_sft import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--seq_length", "64",
        "--num_train_samples", "32", "--size_valid_set", "8",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000", "--seq_parallel", "4", "--vocab_chunks", vocab_chunks,
    ])


def _dpo_batches(steps, gb, T, vocab, seed=0):
    """Random chosen/rejected pairs with realistic prompt/padding masks that
    CROSS shard boundaries (prompt lengths straddle T/sp multiples)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        b = {}
        for side in ("chosen", "rejected"):
            toks = rng.integers(0, vocab, size=(gb, T)).astype(np.int32)
            mask = np.zeros((gb, T), np.float32)
            for r in range(gb):
                start = int(rng.integers(3, T // 2))     # prompt end
                stop = int(rng.integers(T // 2 + 1, T))  # padding start
                mask[r, start:stop] = 1.0
            b[side] = toks
            b[f"{side}_mask"] = mask
        out.append(b)
    return out


def _train_dpo(mesh, sp, steps=6):
    from distributed_lion_tpu.train.dpo import make_dpo_loss_fn

    model_cfg, base, lcfg, adapters = _sft_pieces()
    from distributed_lion_tpu.models.lora import lora_apply_fn

    seq_axis = SEQ_AXIS if sp > 1 else None
    pol = lora_apply_fn(
        lambda p, t: llama_apply(p, t, model_cfg, seq_axis=seq_axis),
        base, lcfg)
    loss_fn = make_dpo_loss_fn(
        policy_apply=pol,
        ref_apply=lambda t: llama_apply(base, t, model_cfg, seq_axis=seq_axis),
        beta=0.1, seq_axis=seq_axis,
    )
    cfg = _cfg(learning_rate=1e-3)
    spec = P(DATA_AXIS, SEQ_AXIS) if sp > 1 else None
    trainer = Trainer(cfg, mesh, apply_fn=None, params=adapters,
                      loss_fn=loss_fn, batch_spec=spec)
    model_cfg_vocab = model_cfg.vocab_size
    batches = _dpo_batches(steps, trainer.global_train_batch(), 64,
                           model_cfg_vocab)
    history = trainer.train(iter(batches), max_steps=steps)
    losses = [h["loss"] for h in history if "loss" in h]
    trainer.close()
    return losses


def test_dpo_sp_trajectory_matches_pure_dp():
    mesh_sp = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    mesh_dp = make_mesh(data=2, devices=jax.devices()[:2])
    losses_sp = _train_dpo(mesh_sp, sp=4)
    losses_dp = _train_dpo(mesh_dp, sp=1)
    assert len(losses_sp) == len(losses_dp) > 0
    np.testing.assert_allclose(losses_sp, losses_dp, rtol=2e-2, atol=2e-2)


def test_run_dpo_cli_seq_parallel_smoke():
    from distributed_lion_tpu.cli.run_dpo import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--max_length", "64",
        "--num_train_samples", "32", "--size_valid_set", "4",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000", "--seq_parallel", "4",
    ])


def test_run_sft_sp_guards():
    import pytest

    from distributed_lion_tpu.cli.run_sft import main

    common = [
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "1", "--seq_length", "64",
        "--seq_parallel", "4",
    ]
    with pytest.raises(NotImplementedError, match="packing"):
        main(common + ["--packing", "false"])
    with pytest.raises(ValueError, match="divide evenly"):
        # 62 stays under tiny's n_ctx (no clamp) and 62 % 4 != 0
        main([a if a != "64" else "62" for a in common])
