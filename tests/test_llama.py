"""Llama model tests: shapes, causality, GQA, RoPE properties."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.models.llama import (
    LlamaConfig,
    apply_rope,
    llama_apply,
    llama_init,
    rope_angles,
)


def test_forward_shapes():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), cfg)
    logits = llama_apply(params, jnp.zeros((2, 16), jnp.int32), cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(1), cfg)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(1, 10)).astype(np.int32)
    toks2 = toks.copy()
    toks2[0, -1] = (toks2[0, -1] + 1) % cfg.vocab_size
    l1 = llama_apply(params, jnp.asarray(toks), cfg)
    l2 = llama_apply(params, jnp.asarray(toks2), cfg)
    np.testing.assert_array_equal(np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]))


def test_gqa_head_counts():
    cfg = LlamaConfig.tiny()  # 4 heads, 2 kv heads
    params = llama_init(jax.random.key(0), cfg)
    attn = params["blocks"][0]["attn"]
    assert attn["wq"].shape == (64, 4 * 16)
    assert attn["wk"].shape == (64, 2 * 16)
    assert attn["wv"].shape == (64, 2 * 16)


def test_rope_preserves_norm_and_relativity():
    cos, sin = rope_angles(8, 16, 10000.0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 2, 8, 16)), jnp.float32)
    rot = apply_rope(x, cos, sin)
    # rotation preserves per-pair norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(rot), axis=-1),
        rtol=1e-5,
    )
    # position 0 is the identity rotation
    np.testing.assert_allclose(np.asarray(rot[:, :, 0]), np.asarray(x[:, :, 0]), rtol=1e-6)


def test_llama3_config():
    cfg = LlamaConfig.llama3_8b()
    assert cfg.n_kv_head == 8 and cfg.rope_theta == 500000.0
