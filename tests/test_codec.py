"""Codec round-trip + wire-format tests (SURVEY §4 unit tests; mirrors the
reference's pack/unpack/pad-trim at distributed_lion.py:14-31, 75-88)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.ops.codec import (
    pack_signs,
    packed_size,
    unpack_signs,
    wire_bytes_per_param,
)


@pytest.mark.parametrize("shape", [(1,), (7,), (8,), (9,), (130,), (3, 5), (4, 8, 2)])
def test_roundtrip_lossless(shape):
    rng = np.random.default_rng(0)
    votes = jnp.asarray(rng.integers(0, 2, size=shape).astype(bool))
    packed = pack_signs(votes)
    assert packed.dtype == jnp.uint8, "wire format must be a REAL uint8 (the reference ships int64)"
    assert packed.shape == (packed_size(int(np.prod(shape))),)
    restored = unpack_signs(packed, shape)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(votes))


def test_padding_bits_are_zero_and_trimmed():
    votes = jnp.ones((9,), bool)  # pads 7 zero bits
    packed = pack_signs(votes)
    assert int(packed[1]) == 1  # only bit 0 of the second byte set
    assert unpack_signs(packed, (9,)).all()


def test_wire_accounting_beats_baseline():
    n, w = 124_000_000, 4
    psum = wire_bytes_per_param(n, w, "sign_psum")
    packed = wire_bytes_per_param(n, w, "packed_allgather")
    # packed path: 1 bit/param/worker → w/8 bytes... per-worker receive w*n/8
    assert packed["bytes_per_step"] == w * packed_size(n)
    # reference ships 8x more (int64 lanes)
    assert packed["reference_bytes_per_step"] == 8 * packed["bytes_per_step"]
    # BASELINE.md: ≤ 1/32 of bf16 grad all-reduce → packed path at W=4 is 1/4 byte/param vs 2
    assert packed["vs_bf16_allreduce"] <= 1 / 4
    assert psum["bits_per_param"] == 8.0
    # two-phase a2a wire: ~2 bits/param and INDEPENDENT of world size
    for w2 in (4, 64, 512):
        a2a = wire_bytes_per_param(n, w2, "packed_a2a")
        assert a2a["bits_per_param"] <= 2.0
        assert a2a["vs_bf16_allreduce"] <= 1 / 8


def test_unknown_wire_raises():
    with pytest.raises(ValueError):
        wire_bytes_per_param(8, 2, "carrier_pigeon")


def test_world1_wire_bytes_are_zero():
    """One voter -> every wire short-circuits: a single-chip run must not
    log phantom collective traffic."""
    for wire in ("sign_psum", "packed_allgather", "packed_a2a"):
        assert wire_bytes_per_param(1000, 1, wire)["bytes_per_step"] == 0
