"""Trainable pipeline parallelism (VERDICT r1 item 4): real GPT-2 blocks as
stages, full vote-Lion training over a dp x pp mesh.

The load-bearing invariant: pipelining is a pure re-schedule — a dp=2 x pp=4
run must produce the same losses/params as the dp=2 run with the same global
batch, because every microbatch passes through the same blocks in the same
order; only the device placement changes.
"""

import dataclasses

import jax
import numpy as np
import pytest

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=5, per_device_train_batch_size=4,
        gradient_accumulation_steps=1, block_size=32, logging_steps=1,
        output_dir=None, seed=7,
    )
    base.update(kw)
    return TrainConfig(**base)


MODEL = GPT2Config.tiny(n_layer=4)


def _train(mesh, cfg, n_steps=5, model=None):
    model = model or MODEL
    trainer = Trainer.for_gpt2(cfg, mesh, model, seed=123)
    blocks = synthetic_lm_dataset(
        max(64, trainer.global_train_batch() * 2), cfg.block_size,
        model.vocab_size, seed=11,
    )
    hist = trainer.train(
        batch_iterator(blocks, trainer.global_train_batch(), seed=0),
        max_steps=n_steps,
    )
    params = jax.tree.map(np.asarray, jax.device_get(trainer.params))
    trainer.close()
    return [h["loss"] for h in hist if "loss" in h], params


def test_pp_forward_matches_sequential():
    """Pipeline forward loss == plain forward loss on identical params."""
    from distributed_lion_tpu.models.gpt2_pipe import (
        make_pipeline_loss,
        pipeline_param_specs,
        pipeline_params,
    )
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    pp = 4
    mesh = make_mesh(data=2, pipe=pp)
    params = gpt2_init(jax.random.key(0), MODEL)
    tokens = np.random.default_rng(0).integers(
        0, MODEL.vocab_size, size=(8, 32)).astype(np.int32)

    from distributed_lion_tpu.models.loss import clm_loss_and_metrics

    logits = gpt2_apply(params, tokens, MODEL)
    ref_loss, _ = clm_loss_and_metrics(logits, tokens)

    loss_fn = make_pipeline_loss(MODEL, n_micro=2)
    pparams = pipeline_params(params, pp)
    pspecs = pipeline_param_specs()

    @jax.jit
    def run(pparams, tokens):
        def body(p, t):
            loss, _ = loss_fn(p, t, None)
            # per-data-shard loss over equal token counts → pmean = global
            return jax.lax.pmean(loss, "data")
        return shard_map(
            body, mesh=mesh, in_specs=(pspecs, P("data")), out_specs=P(),
            check_vma=False,
        )(pparams, tokens)

    got = float(run(pparams, tokens))
    np.testing.assert_allclose(got, float(ref_loss), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "mesh_kw,cfg_kw",
    [
        pytest.param(dict(data=2, pipe=4),
                     dict(pipeline_parallel=4, pipeline_microbatches=2),
                     id="dp2xpp4"),
        pytest.param(dict(data=2, tensor=2, pipe=2),
                     dict(tensor_parallel=2, pipeline_parallel=2,
                          pipeline_microbatches=2),
                     id="dp2xtp2xpp2"),
    ],
)
def test_pipelined_mesh_matches_pure_dp(mesh_kw, cfg_kw):
    """dp×pp — and the classic large-model mesh dp×tp×pp (Megatron
    sharding INSIDE each GPipe stage) — must train identically to pure
    dp=2 at the same global batch/data/seed: both are pure re-schedules.

    Run in f32 compute: pipelining/tp-psum reorder bf16 matmul tiles, and
    the vote's sign threshold amplifies that noise into ±2·lr param flips
    on near-zero ballots — in f32 the reordering noise is below any ballot
    margin, so the schedules must agree to tight tolerance."""
    devs = jax.devices()
    mesh_dp = make_mesh(data=2, devices=devs[:2])
    mesh_x = make_mesh(**mesh_kw)

    model_f32 = dataclasses.replace(MODEL, compute_dtype=jax.numpy.float32)
    losses_dp, params_dp = _train(mesh_dp, _cfg(), n_steps=5, model=model_f32)
    losses_x, params_x = _train(mesh_x, _cfg(**cfg_kw), n_steps=5,
                                model=model_f32)

    np.testing.assert_allclose(losses_x, losses_dp, rtol=1e-4, atol=1e-4)
    # Param comparison, modulo sign-of-zero ballots: coordinates whose
    # gradient is EXACTLY zero by symmetry (e.g. k-bias under softmax shift
    # invariance) vote on the sign of fp noise, which any schedule change
    # may flip — each flip moves a param by ±2·lr. So: every coordinate must
    # be within the 5-step ballot-flip envelope, and the flipped fraction
    # must be small (the informative coordinates agree exactly).
    from distributed_lion_tpu.models.gpt2_pipe import unpipeline_params

    restored = unpipeline_params(params_x, MODEL.n_layer)
    total = mismatched = 0
    envelope = 2 * 1e-3 * 5  # 2·lr·n_steps
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(restored)):
        d = np.abs(a.astype(np.float64) - b.astype(np.float64))
        assert d.max() <= envelope, d.max()
        mismatched += int((d > 1e-6).sum())
        total += d.size
    assert mismatched / total < 0.02, f"{mismatched}/{total} params flipped"


def test_pp_loss_decreases():
    mesh = make_mesh(data=2, pipe=4)
    cfg = _cfg(pipeline_parallel=4, pipeline_microbatches=4,
               learning_rate=3e-3, max_steps=30)
    trainer = Trainer.for_gpt2(cfg, mesh, MODEL, seed=1)
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  MODEL.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, losses
    trainer.close()


def test_pp_guards():
    mesh = make_mesh(data=2, pipe=4)
    with pytest.raises(ValueError, match="divisible"):
        Trainer.for_gpt2(_cfg(pipeline_parallel=4), mesh,
                         GPT2Config.tiny(n_layer=3))
    with pytest.raises(ValueError, match="dropout"):
        Trainer.for_gpt2(_cfg(pipeline_parallel=4), mesh,
                         dataclasses.replace(MODEL, dropout=0.1))
    with pytest.raises(ValueError, match="not divisible by pipeline_microbatches"):
        Trainer.for_gpt2(_cfg(pipeline_parallel=4, per_device_train_batch_size=3,
                              pipeline_microbatches=2), mesh, MODEL)


def test_tp_pp_loss_decreases():
    mesh = make_mesh(data=2, tensor=2, pipe=2)
    cfg = _cfg(tensor_parallel=2, pipeline_parallel=2,
               pipeline_microbatches=4, learning_rate=3e-3, max_steps=30)
    trainer = Trainer.for_gpt2(cfg, mesh, MODEL, seed=1)
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  MODEL.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, losses
    trainer.close()


def test_pp_chunked_head_matches_dense():
    """pp × vocab_chunks: the chunked last-stage head computes the same
    loss as the dense pipelined head and the sequential model."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.models.gpt2_pipe import (
        make_pipeline_loss,
        pipeline_param_specs,
        pipeline_params,
    )
    from distributed_lion_tpu.models.loss import clm_loss_and_metrics

    pp = 4
    mesh = make_mesh(data=2, pipe=pp)
    params = gpt2_init(jax.random.key(0), MODEL)
    tokens = np.random.default_rng(0).integers(
        0, MODEL.vocab_size, size=(8, 32)).astype(np.int32)
    ref_loss, _ = clm_loss_and_metrics(gpt2_apply(params, tokens, MODEL),
                                       tokens)

    loss_fn = make_pipeline_loss(MODEL, n_micro=2, vocab_chunks=4)
    pparams = pipeline_params(params, pp)

    @jax.jit
    def run(pparams, tokens):
        def body(p, t):
            loss, _ = loss_fn(p, t, None)
            return jax.lax.pmean(loss, "data")
        return shard_map(
            body, mesh=mesh, in_specs=(pipeline_param_specs(), P("data")),
            out_specs=P(), check_vma=False,
        )(pparams, tokens)

    got = float(run(pparams, tokens))
    np.testing.assert_allclose(got, float(ref_loss), rtol=2e-5, atol=2e-5)


def test_tp_pp_chunked_trains():
    """The full composition dp×tp×pp×vocab_chunks runs and learns."""
    mesh = make_mesh(data=2, tensor=2, pipe=2)
    cfg = _cfg(tensor_parallel=2, pipeline_parallel=2,
               pipeline_microbatches=2, vocab_chunks=4,
               learning_rate=3e-3, max_steps=30)
    trainer = Trainer.for_gpt2(cfg, mesh, MODEL, seed=1)
    blocks = synthetic_lm_dataset(trainer.global_train_batch() * 2, 32,
                                  MODEL.vocab_size, seed=3)
    hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(),
                                        seed=0))
    losses = [h["loss"] for h in hist if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, losses
    trainer.close()


@pytest.mark.parametrize("chunks", [0, 4], ids=["dense", "chunked"])
def test_sp_pp_chunked_trajectory_matches_dp(chunks):
    """dp=2 x sp=2 x pp=2 (dense AND chunked seq-parallel heads) ≡ dp=2:
    long-context pipelined training — ring attention inside every pipeline
    tick, wpe offset per seq shard, boundary labels via ppermute feeding
    the CE at the last stage."""
    from distributed_lion_tpu.models.gpt2_pipe import unpipeline_params

    model_f32 = dataclasses.replace(MODEL, compute_dtype=jax.numpy.float32)
    losses_dp, params_dp = _train(
        make_mesh(data=2, devices=jax.devices()[:2]),
        _cfg(vocab_chunks=chunks), n_steps=5, model=model_f32)
    losses_sp, params_sp = _train(
        make_mesh(data=2, seq=2, pipe=2),
        _cfg(seq_parallel=2, pipeline_parallel=2, pipeline_microbatches=2,
             vocab_chunks=chunks),
        n_steps=5, model=model_f32)
    np.testing.assert_allclose(losses_sp, losses_dp, rtol=1e-4, atol=1e-4)
    restored = unpipeline_params(params_sp, MODEL.n_layer)
    envelope = 2 * 1e-3 * 5
    total = mismatched = 0
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(restored)):
        d = np.abs(a.astype(np.float64) - b.astype(np.float64))
        assert d.max() <= envelope, d.max()
        mismatched += int((d > 1e-6).sum())
        total += d.size
    assert mismatched / total < 0.02, f"{mismatched}/{total} params flipped"


def test_tp_sp_pp_full_composition_matches_dp():
    """The whole mesh at once — tp=2 x sp=2 x pp=2 (+ chunked CE) ≡ plain
    single-device training: Megatron sharding inside GPipe stages whose
    attention rings over the seq axis, streamed CE at the last stage."""
    from distributed_lion_tpu.models.gpt2_pipe import unpipeline_params

    model_f32 = dataclasses.replace(MODEL, compute_dtype=jax.numpy.float32)
    losses_dp, params_dp = _train(
        make_mesh(data=1, devices=jax.devices()[:1]),
        _cfg(vocab_chunks=4, per_device_train_batch_size=8),
        n_steps=5, model=model_f32)
    losses_x, params_x = _train(
        make_mesh(data=1, tensor=2, seq=2, pipe=2),
        _cfg(tensor_parallel=2, seq_parallel=2, pipeline_parallel=2,
             pipeline_microbatches=2, vocab_chunks=4,
             per_device_train_batch_size=8),
        n_steps=5, model=model_f32)
    np.testing.assert_allclose(losses_x, losses_dp, rtol=1e-4, atol=1e-4)
    restored = unpipeline_params(params_x, MODEL.n_layer)
    envelope = 2 * 1e-3 * 5
    total = mismatched = 0
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(restored)):
        d = np.abs(a.astype(np.float64) - b.astype(np.float64))
        assert d.max() <= envelope, d.max()
        mismatched += int((d > 1e-6).sum())
        total += d.size
    assert mismatched / total < 0.02, f"{mismatched}/{total} params flipped"


def test_checkpoint_resume_exact_under_tp_pp(tmp_path):
    """Train, checkpoint, resume under the dp×tp×pp mesh → params and
    per-worker momentum match a continuous run exactly (Orbax round-trips
    the stacked tp/pipe-sharded stage leaves)."""
    mesh = make_mesh(data=2, tensor=2, pipe=2)
    model_f32 = dataclasses.replace(MODEL, compute_dtype=jax.numpy.float32)
    kw = dict(tensor_parallel=2, pipeline_parallel=2, pipeline_microbatches=2)
    blocks = synthetic_lm_dataset(256, 32, MODEL.vocab_size, seed=0)

    cfg_c = _cfg(max_steps=10, **kw)
    t_cont = Trainer.for_gpt2(cfg_c, mesh, model_f32, seed=5)
    t_cont.train(batch_iterator(blocks, t_cont.global_train_batch(), seed=9),
                 max_steps=10)

    cfg_a = _cfg(max_steps=10, output_dir=str(tmp_path / "run"),
                 save_steps=10**9, **kw)
    t1 = Trainer.for_gpt2(cfg_a, mesh, model_f32, seed=5)
    t1.train(batch_iterator(blocks, t1.global_train_batch(), seed=9),
             max_steps=5)
    t1.save()
    t1.close()

    t2 = Trainer.for_gpt2(cfg_a, mesh, model_f32, seed=5)
    assert t2.step_count == 5, "did not resume from checkpoint"
    t2.train(batch_iterator(blocks, t2.global_train_batch(), seed=9),
             max_steps=5)
    for a, b in zip(jax.tree.leaves(t_cont.params), jax.tree.leaves(t2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(t_cont.state.exp_avg),
                    jax.tree.leaves(t2.state.exp_avg)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t2.close()
    t_cont.close()


def test_sp_pipeline_oversized_total_sequence_fails_loudly():
    """ADVICE r3: calling make_pipeline_loss directly with seq_axis and a
    TOTAL sequence (T_local x seq shards) past n_ctx must raise at trace
    time — without the guard the wpe dynamic_slice clamps silently and
    later seq shards duplicate positional rows. (The Trainer path already
    refuses this at config time via validate_seq_block; this pins the
    model-level guard for callers that bypass the Trainer.)"""
    from distributed_lion_tpu.models.gpt2_pipe import (
        make_pipeline_loss,
        pipeline_param_specs,
        pipeline_params,
    )
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    pp, sp = 2, 2
    mesh = make_mesh(data=2, seq=sp, pipe=pp)
    model = GPT2Config.tiny(n_layer=pp)  # n_ctx=128
    params = gpt2_init(jax.random.key(0), model)
    # the shard_map in_spec splits dim 1 over the 2-way seq axis, so
    # T_local = n_ctx: fits per shard, but total = 2*n_ctx overflows wpe
    tokens = np.zeros((8, 2 * model.n_ctx), np.int32)

    loss_fn = make_pipeline_loss(model, n_micro=2, seq_axis="seq",
                                 vocab_chunks=0, axis_name="pipe")
    pparams = pipeline_params(params, pp)
    pspecs = pipeline_param_specs()

    def run(pparams, tokens):
        def body(p, t):
            loss, _ = loss_fn(p, t, None)
            return jax.lax.pmean(loss, "data")
        return shard_map(
            body, mesh=mesh, in_specs=(pspecs, P("data", "seq")),
            out_specs=P(), check_vma=False,
        )(pparams, tokens)

    with pytest.raises(ValueError, match="exceeds n_ctx"):
        jax.jit(run)(pparams, tokens)
