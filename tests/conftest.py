"""Test harness: 8 virtual CPU devices so the full mesh / shard_map / vote
path runs without TPU hardware (SURVEY §4: distributed tests without a
cluster). Must set env BEFORE jax is imported anywhere."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU plugin and overrides
# JAX_PLATFORMS from the environment; the config knob still wins if set
# before first backend use.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Publish jax.shard_map on jax versions that predate it, BEFORE test modules
# that do `from jax import shard_map` at module scope are collected.
from distributed_lion_tpu import compat as _compat  # noqa: E402

_compat.install()
