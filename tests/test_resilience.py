"""Resilience subsystem (ISSUE 3): async atomic checkpoints, the
fault-injection recovery matrix, preemption drain, and elastic world-size
resume.

The recovery invariant under test everywhere: whatever the failure (crash
mid-save, torn leaf file, corrupted manifest, preemption), resume lands on
the newest GOOD checkpoint and the continued trajectory is bit-identical to
an uninterrupted run — per-worker momenta are the algorithm's whole state,
so "mostly restored" is silent corruption."""

import os
import shutil
import signal
import time

import numpy as np
import pytest

import jax

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.optim import remap_worker_momentum
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train import resilience
from distributed_lion_tpu.train.checkpoint import (
    MANIFESTS_STAMP,
    Checkpointer,
    latest_valid_step_in,
    verify_step_dir,
)
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


@pytest.fixture(autouse=True)
def _clean_faults():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def _cfg(outdir, steps, **kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=steps, per_device_train_batch_size=1,
        gradient_accumulation_steps=1, block_size=32, logging_steps=1,
        save_steps=2, output_dir=outdir, seed=5,
    )
    base.update(kw)
    return TrainConfig(**base)


def _model():
    return GPT2Config.tiny()


def _blocks(model):
    return synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)


def _train(cfg, mesh, model, blocks, seed=3):
    t = Trainer.for_gpt2(cfg, mesh, model, seed=seed)
    h = t.train(batch_iterator(blocks, t.global_train_batch(), seed=5))
    return t, h


def _losses(history):
    return [h["loss"] for h in history if "loss" in h]


# --------------------------------------------------------------------------
# Manifest + commit marker + verified autodetect
# --------------------------------------------------------------------------

def test_commit_writes_manifest_marker_and_verifies(tmp_path):
    ck = Checkpointer(tmp_path / "ck", async_save=False)
    ck.save(3, {"a": np.arange(16, dtype=np.float32)},
            meta={"world": 8, "tag": "periodic"})
    sdir = tmp_path / "ck" / "3"
    assert (sdir / "manifest.json").exists()
    assert (sdir / "COMMITTED").exists()
    assert (tmp_path / "ck" / MANIFESTS_STAMP).exists()
    assert verify_step_dir(sdir)
    assert ck.latest_valid_step() == 3
    assert ck.manifest_meta(3) == {"world": 8, "tag": "periodic"}
    assert latest_valid_step_in(tmp_path / "ck") == 3
    ck.close()


def test_corruption_matrix_falls_back_to_newest_good(tmp_path):
    """One committed history {2, 4}; each corruption of step 4 (torn leaf,
    corrupted manifest, deleted commit marker) must fall back to 2."""
    src = tmp_path / "src"
    ck = Checkpointer(src, async_save=False)
    for step in (2, 4):
        ck.save(step, {"a": np.full(32, step, np.float32)})
    assert ck.latest_valid_step() == 4
    ck.close()

    for name, corrupt in (
        ("torn", lambda d: resilience.tear_leaf_file(d, 4)),
        ("manifest", lambda d: resilience.corrupt_manifest(d, 4)),
        ("uncommitted", lambda d: resilience.delete_commit_marker(d, 4)),
    ):
        dst = tmp_path / name
        shutil.copytree(src, dst)
        corrupt(dst)
        ck2 = Checkpointer(dst, async_save=False)
        assert not verify_step_dir(dst / "4"), name
        assert ck2.latest_valid_step() == 2, name
        assert latest_valid_step_in(dst) == 2, name
        ck2.close()


def test_purge_steps_after_fallback_unblocks_saves(tmp_path):
    """Orbax silently drops a save at a step BELOW an existing newer step —
    so after falling back past a torn checkpoint, post-resume progress
    could never checkpoint again (caught by driving the CLI: resume 1450
    past torn 1488 → save(1460) vanished). purge_steps_after removes every
    newer step — hash-valid ones too: once resumed below them they are an
    abandoned future the deterministic replay re-creates."""
    ck = Checkpointer(tmp_path / "ck", async_save=False)
    for step in (2, 4, 6):
        ck.save(step, {"a": np.full(32, step, np.float32)})
    resilience.tear_leaf_file(tmp_path / "ck", 6)
    assert ck.latest_valid_step() == 4
    # resume fell back to 2 (say step 4 failed to restore transiently):
    # BOTH newer steps go — the valid-but-abandoned 4 and the torn 6
    assert ck.purge_steps_after(2) == [4, 6]
    assert ck.manager.all_steps() == [2]
    # the post-fallback save now lands and commits
    ck.save(3, {"a": np.full(32, 3, np.float32)})
    assert ck.latest_valid_step() == 3
    # idempotent: nothing newer left
    assert ck.purge_steps_after(3) == []
    ck.close()


def test_legacy_unstamped_dir_is_grandfathered(tmp_path):
    """A sync-era directory (no manifests) must keep resuming: marker-less
    steps are valid there, and opening it with integrity on must NOT stamp
    it retroactively."""
    ck = Checkpointer(tmp_path / "ck", async_save=False, integrity=False)
    ck.save(5, {"a": np.zeros(8, np.float32)})
    ck.close()
    assert not (tmp_path / "ck" / MANIFESTS_STAMP).exists()

    ck2 = Checkpointer(tmp_path / "ck", async_save=False, integrity=True)
    assert not (tmp_path / "ck" / MANIFESTS_STAMP).exists()
    assert ck2.latest_valid_step() == 5
    assert latest_valid_step_in(tmp_path / "ck") == 5
    ck2.close()


def test_save_retries_transient_io_failures(tmp_path):
    resilience.inject_fault("ckpt_save_raise", 2)
    ck = Checkpointer(tmp_path / "ck", async_save=False,
                      max_retries=3, retry_backoff_s=0.01)
    ck.save(1, {"a": np.ones(4, np.float32)})
    assert ck.latest_valid_step() == 1
    # charges exhausted by the retries
    assert resilience.fault("ckpt_save_raise") == 0
    ck.close()


def test_save_raises_after_retry_budget(tmp_path):
    resilience.inject_fault("ckpt_save_raise", 99)
    ck = Checkpointer(tmp_path / "ck", async_save=False,
                      max_retries=2, retry_backoff_s=0.01)
    with pytest.raises(OSError, match="injected"):
        ck.save(1, {"a": np.ones(4, np.float32)})
    ck.close()


# --------------------------------------------------------------------------
# Async overlap: the save must not block the step loop
# --------------------------------------------------------------------------

def test_async_save_returns_before_commit(tmp_path):
    resilience.inject_fault("ckpt_slow_commit", 0.8)
    payload = {"a": np.arange(1024, dtype=np.float32)}

    sync = Checkpointer(tmp_path / "sync", async_save=False)
    t0 = time.monotonic()
    sync.save(0, payload)
    sync_dur = time.monotonic() - t0
    sync.close()
    assert sync_dur >= 0.8  # the sync baseline eats the commit inline

    a = Checkpointer(tmp_path / "async", async_save=True)
    t0 = time.monotonic()
    a.save(0, payload)
    async_dur = time.monotonic() - t0
    assert async_dur < 0.5  # returned while the commit still runs
    a.close()  # close() drains; the checkpoint must still be committed
    assert latest_valid_step_in(tmp_path / "async") == 0
    assert a.total_stall_s >= 0.5  # the drain was accounted, just not inline


def test_ckpt_stall_metric_async_below_sync_baseline(tmp_path):
    """Acceptance: at a save boundary the async path never blocks the step
    loop on serialization — the ckpt_stall_s metric stays below the
    synchronous baseline at identical save cadence + injected commit cost.
    One save (step 2) with the run continuing past it: the sync run pays
    the full commit inline before step 3 can dispatch; the async run pays
    only the save initiation, the commit drains behind steps 3+ / close()."""
    mesh = make_mesh(data=8)
    model = _model()
    blocks = _blocks(model)

    resilience.inject_fault("ckpt_slow_commit", 1.2)
    ts, h_sync = _train(_cfg(str(tmp_path / "sync"), 3, async_ckpt=False),
                        mesh, model, blocks)
    sync_total = ts.checkpointer.total_stall_s  # before close() drains more
    ts.close()
    t_sync = [h["ckpt_stall_s"] for h in h_sync if "ckpt_stall_s" in h]

    ta, h_async = _train(_cfg(str(tmp_path / "async"), 3, async_ckpt=True),
                         mesh, model, blocks)
    async_total = ta.checkpointer.total_stall_s
    ta.close()
    t_async = [h["ckpt_stall_s"] for h in h_async if "ckpt_stall_s" in h]

    # the metric reaches the log stream (the step-3 row pops the boundary)
    assert t_sync and t_async
    assert max(t_sync) >= 1.2   # sync ate the slow commit inline
    assert max(t_async) < 0.6   # async boundary = initiation only
    assert sync_total >= 1.2
    assert async_total < sync_total - 0.5
    # close() drained the async commit: both checkpoints are committed
    for d in ("sync", "async"):
        assert latest_valid_step_in(tmp_path / d / "checkpoints") == 2


# --------------------------------------------------------------------------
# Crash mid-save: recovery resumes from the last GOOD step, bit-identical
# --------------------------------------------------------------------------

def test_crash_mid_save_recovers_bit_identical(tmp_path):
    mesh = make_mesh(data=8)
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    # uninterrupted reference
    t_ref, h_ref = _train(_cfg(None, 6), mesh, model, blocks)
    ref_losses = _losses(h_ref)
    ref_params = jax.device_get(t_ref.params)
    ref_mom = jax.device_get(t_ref.state.exp_avg)
    t_ref.close()

    # phase 1: clean save at step 2
    t1, _ = _train(_cfg(out, 2), mesh, model, blocks)
    t1.close()

    # phase 2: the save at step 4 dies mid-commit (after Orbax finalize,
    # before the manifest lands) and the process "crashes"
    resilience.inject_fault("ckpt_crash_before_manifest")
    t2, _ = _train(_cfg(out, 4), mesh, model, blocks)
    t2.close()
    resilience.clear_faults()
    assert latest_valid_step_in(os.path.join(out, "checkpoints")) == 2

    # recovery: resumes from 2 (not the torn 4), replays to 6
    t3 = Trainer.for_gpt2(_cfg(out, 6), mesh, model, seed=3)
    assert t3.step_count == 2
    h3 = t3.train(batch_iterator(blocks, t3.global_train_batch(), seed=5))
    got_losses = _losses(h3)
    np.testing.assert_array_equal(got_losses, ref_losses[2:])
    got_params = jax.device_get(t3.params)
    got_mom = jax.device_get(t3.state.exp_avg)
    t3.close()
    jax.tree.map(np.testing.assert_array_equal, got_params, ref_params)
    jax.tree.map(np.testing.assert_array_equal, got_mom, ref_mom)


# --------------------------------------------------------------------------
# Preemption drain
# --------------------------------------------------------------------------

def test_preemption_drains_saves_and_resumes(tmp_path):
    mesh = make_mesh(data=8)
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    t_ref, h_ref = _train(_cfg(None, 6, save_steps=100), mesh, model, blocks)
    ref_losses = _losses(h_ref)
    t_ref.close()

    t1 = Trainer.for_gpt2(_cfg(out, 6, save_steps=100), mesh, model, seed=3)
    it = batch_iterator(blocks, t1.global_train_batch(), seed=5)

    class SignallingIter:
        """Delivers a real SIGTERM while fetching the 3rd batch — the
        guard's flag is then observed at that dispatch's boundary."""

        def __init__(self, inner):
            self.inner, self.n = inner, 0

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == 3:
                signal.raise_signal(signal.SIGTERM)
            return next(self.inner)

        def skip(self, k):
            self.inner.skip(k)

    h1 = t1.train(SignallingIter(it))
    assert t1.preempted
    assert t1.step_count == 3  # stopped at the dispatch that saw the flag
    ck_dir = os.path.join(out, "checkpoints")
    assert latest_valid_step_in(ck_dir) == 3  # drained AND committed
    ck = Checkpointer(ck_dir, async_save=False)
    assert ck.manifest_meta(3)["tag"] == "preempt"
    ck.close()
    t1.close()

    # the watcher's restart: a plain resume continues the exact trajectory
    t2 = Trainer.for_gpt2(_cfg(out, 6, save_steps=100), mesh, model, seed=3)
    assert t2.step_count == 3
    assert not t2.preempted
    h2 = t2.train(batch_iterator(blocks, t2.global_train_batch(), seed=5))
    t2.close()
    np.testing.assert_array_equal(_losses(h1) + _losses(h2), ref_losses)


def test_on_preempt_off_ignores_sigterm(tmp_path):
    mesh = make_mesh(data=8)
    model = _model()
    blocks = _blocks(model)
    prev = signal.signal(signal.SIGTERM, lambda *a: None)
    try:
        t = Trainer.for_gpt2(
            _cfg(None, 2, save_steps=100, on_preempt="off"),
            mesh, model, seed=3)
        assert t._preempt_guard is None
        t.train(batch_iterator(blocks, t.global_train_batch(), seed=5))
        assert t.step_count == 2 and not t.preempted
        t.close()
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_on_preempt_validated():
    mesh = make_mesh(data=8)
    with pytest.raises(ValueError, match="on_preempt"):
        Trainer.for_gpt2(_cfg(None, 2, on_preempt="panic"), mesh, _model(),
                         seed=3)


# --------------------------------------------------------------------------
# Elastic world-size resume
# --------------------------------------------------------------------------

def test_remap_worker_momentum_unit():
    rng = np.random.default_rng(0)
    tree = {"w": rng.normal(size=(4, 3, 2)).astype(np.float32),
            "b": rng.normal(size=(4, 5)).astype(np.float32)}

    same = remap_worker_momentum(tree, 4, 4)
    assert same is tree  # W' == W: identity, bit-exact by construction

    down = remap_worker_momentum(tree, 4, 2)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(down[k]),
            tree[k].reshape((2, 2) + tree[k].shape[1:]).mean(axis=1),
            rtol=1e-6)

    one = remap_worker_momentum(tree, 4, 1)
    for k in tree:
        np.testing.assert_allclose(np.asarray(one[k]),
                                   tree[k].mean(axis=0, keepdims=True),
                                   rtol=1e-6)

    up = remap_worker_momentum({"w": tree["w"][:2]}, 2, 4)
    np.testing.assert_array_equal(np.asarray(up["w"]),
                                  np.repeat(tree["w"][:2], 2, axis=0))

    # coprime worlds: mean broadcast
    odd = remap_worker_momentum(tree, 4, 3)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(odd[k]),
            np.broadcast_to(tree[k].mean(axis=0, keepdims=True),
                            (3,) + tree[k].shape[1:]),
            rtol=1e-6)

    # every case preserves the cross-worker mean (the vote center)
    for newW, mapped in ((4, same), (2, down), (1, one), (3, odd)):
        for k in tree:
            np.testing.assert_allclose(np.asarray(mapped[k]).mean(axis=0),
                                       tree[k].mean(axis=0), rtol=1e-5,
                                       err_msg=f"W'={newW} leaf {k}")


def _elastic_cfg(outdir, steps, world, **kw):
    # same GLOBAL batch at every world size so the data stream is identical
    return _cfg(outdir, steps, per_device_train_batch_size=8 // world,
                elastic_resume=True, **kw)


@pytest.mark.parametrize("w_from,w_to", [(4, 2), (2, 4), (4, 1)])
def test_elastic_resume_remaps_momenta(tmp_path, w_from, w_to):
    devices = jax.devices()
    mesh_from = make_mesh(data=w_from, devices=devices[:w_from])
    mesh_to = make_mesh(data=w_to, devices=devices[:w_to])
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    t1, _ = _train(_elastic_cfg(out, 2, w_from), mesh_from, model, blocks)
    mom_from = jax.device_get(t1.state.exp_avg)
    t1.close()

    t2 = Trainer.for_gpt2(_elastic_cfg(out, 4, w_to), mesh_to, model, seed=3)
    assert t2.step_count == 2
    mom_to = jax.device_get(t2.state.exp_avg)
    expect = jax.device_get(remap_worker_momentum(mom_from, w_from, w_to))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        mom_to, expect)
    # and the resumed run actually trains at the new world size
    h = t2.train(batch_iterator(blocks, t2.global_train_batch(), seed=5))
    assert t2.step_count == 4 and _losses(h)
    t2.close()


def test_elastic_round_trip_same_world_exact(tmp_path):
    devices = jax.devices()
    mesh = make_mesh(data=4, devices=devices[:4])
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    t1, _ = _train(_elastic_cfg(out, 2, 4), mesh, model, blocks)
    mom = jax.device_get(t1.state.exp_avg)
    params = jax.device_get(t1.params)
    t1.close()

    t2 = Trainer.for_gpt2(_elastic_cfg(out, 4, 4), mesh, model, seed=3)
    assert t2.step_count == 2
    jax.tree.map(np.testing.assert_array_equal,
                 jax.device_get(t2.state.exp_avg), mom)
    jax.tree.map(np.testing.assert_array_equal,
                 jax.device_get(t2.params), params)
    t2.close()


def test_elastic_resume_with_telemetry_restores_step(tmp_path):
    """Code-review fix: a telemetry-on checkpoint's payload contains the
    vote_health accumulator, and Orbax rejects restore templates missing a
    saved key — the elastic template must include (then discard) it, or
    every candidate fails and training silently restarts from 0."""
    devices = jax.devices()
    mesh4 = make_mesh(data=4, devices=devices[:4])
    mesh2 = make_mesh(data=2, devices=devices[:2])
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    t1, _ = _train(_elastic_cfg(out, 2, 4, telemetry=True), mesh4, model,
                   blocks)
    t1.close()

    t2 = Trainer.for_gpt2(_elastic_cfg(out, 4, 2, telemetry=True), mesh2,
                          model, seed=3)
    assert t2.step_count == 2  # resumed, not silently restarted
    # the accumulator starts fresh (old-world denominators don't apply)
    assert int(jax.device_get(t2.vote_health.steps)) == 0
    t2.close()


def test_resume_exhaustion_is_loud_not_step_zero(tmp_path, monkeypatch):
    """Code-review fix: when every VERIFIED checkpoint fails to restore
    (structure mismatch — e.g. an Orbax 'Dict key mismatch' on older
    checkpoints), resume must raise — a silent restart from step 0
    underneath higher-numbered steps also could never save (Orbax drops
    saves below existing steps). The restore failure is injected at
    _restore_step because the installed Orbax is lenient about the natural
    triggers (it ignores template shape changes and extra leaves)."""
    mesh = make_mesh(data=8)
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    t1, _ = _train(_cfg(out, 2), mesh, model, blocks)
    t1.close()

    def boom(self, step, meta, ckpt_world):
        raise KeyError("Dict key mismatch (injected)")

    monkeypatch.setattr(Trainer, "_restore_step", boom)
    with pytest.raises(RuntimeError, match="failed to restore"):
        Trainer.for_gpt2(_cfg(out, 4), mesh, model, seed=3)


def test_preempt_guard_second_sigterm_escalates():
    """Code-review fix: the guard must absorb only the FIRST SIGTERM (the
    drain request); a second delivery means the loop is wedged — the guard
    restores the previous disposition and re-delivers so `timeout` and
    operators can still kill the process."""
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda *a: hits.append("prev"))
    try:
        guard = resilience.PreemptionGuard()
        signal.raise_signal(signal.SIGTERM)
        assert guard.should_stop() and hits == []  # first: absorbed
        signal.raise_signal(signal.SIGTERM)
        assert hits == ["prev"]  # second: handed to the prior handler
        assert signal.getsignal(signal.SIGTERM) is not guard._on_signal
        guard.close()
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_world_mismatch_without_flag_is_loud(tmp_path):
    devices = jax.devices()
    mesh4 = make_mesh(data=4, devices=devices[:4])
    mesh2 = make_mesh(data=2, devices=devices[:2])
    model = _model()
    blocks = _blocks(model)
    out = str(tmp_path / "run")

    t1, _ = _train(_cfg(out, 2, per_device_train_batch_size=2), mesh4,
                   model, blocks)
    t1.close()
    with pytest.raises(ValueError, match="elastic_resume"):
        Trainer.for_gpt2(_cfg(out, 4, per_device_train_batch_size=4), mesh2,
                         model, seed=3)
