"""Control plane (ISSUE 10): live elasticity — unified worker lifecycle,
leave/join without a restart.

The tentpole contracts, pinned here:

- **one lifecycle** — healthy → suspect → quarantined → departed →
  rejoining → healthy, with the plane's authority over the guard: a
  departed worker NEVER auto-readmits (the cooldown pin), repeated
  quarantines escalate to departure, a failed rejoin probe departs again;
- **live leave** — an injected ``worker_drop`` is a mask transition at
  the next dispatch boundary: training continues at W−1 and a run
  departed from step 0 is BIT-identical to a from-scratch W−1 masked run
  (the degraded-phase acceptance pin);
- **live join** — ``worker_rejoin`` re-absorbs the worker in-run:
  momentum healed from the healthy mean, ballot history reset, probation
  window; the full drop→rejoin run completes without restart and its
  post-rejoin loss tracks the clean curve within a pre-registered bound;
- **depth refusal** — in-run rejoin at ``--dcn_pipeline_depth > 0`` is
  refused loudly, and the elastic-resume refusal (PR 8) gets its missing
  direct test;
- **control plane × checkpoints** — crash-resume mid-degradation restores
  the departed set (manifest meta ``cp_departed``) and continues
  bit-identically; a ``--control_plane`` toggle on resume is tolerated
  like the guard toggle;
- **journal** — worker_left / worker_rejoined / membership_transition
  events ride the run journal and cli/run_analyze surfaces the timeline.
"""

import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lion_tpu.data.sources import (
    batch_iterator,
    synthetic_lm_dataset,
)
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train import resilience
from distributed_lion_tpu.train.control_plane import (
    DEPART_AFTER_QUARANTINES,
    ControlPlane,
)
from distributed_lion_tpu.train.loop import TrainConfig, Trainer
from distributed_lion_tpu.train.vote_guard import VoteGuard


@pytest.fixture(autouse=True)
def _clean_faults():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def _obs(world, nonfinite=(), disagree=None):
    o = {
        "guard_nonfinite": np.zeros(world, np.int32),
        "guard_frozen": np.zeros(world, np.int32),
        "guard_disagree": (np.full(world, 0.25)
                           if disagree is None else np.asarray(disagree)),
        "guard_voted_steps": np.asarray(1, np.int32),
    }
    for w in nonfinite:
        o["guard_nonfinite"][w] = 1
    return o


def _plane(world=4, strikes=2, cooldown=3, probe=4, depth=0):
    return ControlPlane(
        VoteGuard(world, "enforce", strike_threshold=strikes,
                  cooldown_steps=cooldown),
        world, rejoin_probe_steps=probe, dcn_pipeline_depth=depth)


# ----------------------------------------------------------- parsing
def test_parse_membership_validation():
    assert (resilience.parse_membership("worker_drop:2")
            == ("worker_drop", 2, 0))
    assert (resilience.parse_membership("worker_drop:0:7")
            == ("worker_drop", 0, 7))
    assert (resilience.parse_membership("worker_rejoin:1:9")
            == ("worker_rejoin", 1, 9))
    assert resilience.parse_membership_specs(
        "worker_drop:2:3, worker_rejoin:2:9") == [
            ("worker_drop", 2, 3), ("worker_rejoin", 2, 9)]
    for bad in ("worker_vanish:1", "worker_drop:x", "worker_drop:-1",
                "worker_rejoin:2",  # rejoin REQUIRES an explicit step
                "worker_drop:1:2:3"):
        with pytest.raises(ValueError):
            resilience.parse_membership(bad)


# ------------------------------------------------------ lifecycle units
def test_drop_is_departed_and_never_auto_readmits():
    cp = _plane(cooldown=2)
    resilience.inject_fault("membership", [("worker_drop", 1, 3)])
    ev = cp.membership_due(2)
    assert not ev.left and cp.lifecycle()[1] == "healthy"
    ev = cp.membership_due(3)
    assert ev.left == [(1, "injected_drop")] and ev.mask_changed
    assert cp.lifecycle()[1] == "departed"
    assert not cp.alive_mask()[1]
    # far past the guard cooldown: a departed worker must NOT readmit
    for step in range(4, 20):
        ev = cp.observe(step, _obs(4), 1)
        assert not ev.readmitted and not cp.alive_mask()[1], step
    assert cp.lifecycle()[1] == "departed"
    # the registry entry was consumed exactly once
    assert resilience.fault("membership") == []


def test_rejoin_heals_resets_and_promotes_after_probe():
    cp = _plane(probe=3)
    resilience.inject_fault("membership", [("worker_drop", 2, 0),
                                           ("worker_rejoin", 2, 5)])
    cp.membership_due(0)
    assert cp.lifecycle()[2] == "departed"
    ev = cp.membership_due(5)
    assert ev.rejoined == [2] and ev.heal == [2] and ev.reset_ballot == [2]
    assert ev.mask_changed and cp.alive_mask()[2]
    assert cp.lifecycle()[2] == "rejoining"
    # clean probation: rejoining → healthy once the window elapses
    cp.observe(6, _obs(4), 1)
    assert cp.lifecycle()[2] == "rejoining"
    cp.observe(8, _obs(4), 1)
    assert cp.lifecycle()[2] == "healthy"
    assert cp.rejoin_events == 1 and cp.left_events == 1


def test_probe_failure_departs_instead_of_cooldown_loop():
    cp = _plane(strikes=2, probe=50)
    resilience.inject_fault("membership", [("worker_drop", 3, 0),
                                           ("worker_rejoin", 3, 2)])
    cp.membership_due(0)
    cp.membership_due(2)
    assert cp.lifecycle()[3] == "rejoining"
    # the first window after a rejoin is stale (covers the masked
    # dispatch) and must be discarded even if it flags the rejoiner
    ev = cp.observe(3, _obs(4, nonfinite=[3]), 1)
    assert cp.guard.strikes[3] == 0 and cp.lifecycle()[3] == "rejoining"
    # still sick: strikes inside the probation window → straight back to
    # departed (cause probe_failed), never the quarantine/readmit cycle
    cp.observe(4, _obs(4, nonfinite=[3]), 1)
    ev = cp.observe(5, _obs(4, nonfinite=[3]), 1)
    assert ev.left == [(3, "probe_failed")]
    assert cp.lifecycle()[3] == "departed"


def test_same_boundary_drop_then_rejoin_heals():
    """The documented ordering rule: drops apply before rejoins at the
    same boundary, so a same-step drop+rejoin pair heals the worker even
    when the schedule lists the rejoin first."""
    cp = _plane(probe=2)
    resilience.inject_fault("membership", [("worker_rejoin", 2, 5),
                                           ("worker_drop", 2, 5)])
    ev = cp.membership_due(5)
    assert ev.left == [(2, "injected_drop")] and ev.rejoined == [2]
    assert cp.alive_mask()[2] and cp.lifecycle()[2] == "rejoining"
    assert cp.left_events == 1 and cp.rejoin_events == 1


def test_repeated_quarantines_escalate_to_departed():
    cp = _plane(strikes=1, cooldown=2)
    step = 0
    for cycle in range(DEPART_AFTER_QUARANTINES):
        step += 1
        ev = cp.observe(step, _obs(4, nonfinite=[0]), 1)
        assert ev.quarantined == [0], cycle
        if cycle < DEPART_AFTER_QUARANTINES - 1:
            assert cp.lifecycle()[0] == "quarantined"
            step += 2  # cooldown elapses → readmission probe
            ev = cp.observe(step, _obs(4), 1)
            assert ev.readmitted == [0] and ev.heal == [0]
    # the Nth quarantine is evidence of a dead worker, not a noisy one
    assert cp.lifecycle()[0] == "departed"
    assert dict(cp.departed)[0] == "guard_strikes"
    # rejoin wipes the quarantine history: after a clean probation, ONE
    # later transient quarantine enters the normal cooldown/readmit cycle
    # — it must not re-cross the stale pre-departure count and instantly
    # re-depart the worker
    resilience.inject_fault("membership", [("worker_rejoin", 0, step + 1)])
    cp.membership_due(step + 1)
    assert cp.quarantine_counts[0] == 0
    cp.observe(step + 2, _obs(4), 1)   # stale-window amnesty consumed
    step += 5                          # past rejoining_until (= +1 + probe 4)
    cp.observe(step, _obs(4), 1)       # probation elapses clean
    assert cp.lifecycle()[0] == "healthy"
    ev = cp.observe(step + 1, _obs(4, nonfinite=[0]), 1)
    assert ev.quarantined == [0] and not ev.left
    assert cp.lifecycle()[0] == "quarantined"  # NOT departed


def test_rejoin_at_depth_refused_and_validation():
    cp = _plane(depth=1)
    resilience.inject_fault("membership", [("worker_drop", 1, 0)])
    cp.membership_due(0)  # drops are fine at depth > 0
    resilience.inject_fault("membership", [("worker_rejoin", 1, 1)])
    with pytest.raises(RuntimeError, match="DCN tally ring"):
        cp.membership_due(1)
    with pytest.raises(ValueError, match="VoteGuard"):
        ControlPlane(None, 4)
    with pytest.raises(ValueError, match="world"):
        ControlPlane(VoteGuard(8, "enforce"), 4)
    # rejoining a worker that never left is a no-op with a log, not a crash
    cp2 = _plane()
    resilience.inject_fault("membership", [("worker_rejoin", 0, 0)])
    ev = cp2.membership_due(0)
    assert not ev.rejoined and any("never left" in line for line in ev.logs)


def test_adopt_restores_probation_and_history():
    """Crash mid-probation: adopt() restores the rejoiner's probation
    window and the quarantine history from the manifest meta, so the
    probe-fail rule survives the restart (a still-sick rejoiner departs
    on its first re-strike, like the uninterrupted run). Wrong-length
    lists (elastic world change) are ignored."""
    cp = _plane(probe=10)
    resilience.inject_fault("membership", [("worker_drop", 1, 0),
                                           ("worker_rejoin", 1, 4)])
    cp.membership_due(0)
    cp.membership_due(4)
    cp.quarantine_counts[3] = 2
    saved = ([bool(b) for b in cp.alive_mask()], sorted(cp.departed),
             [int(x) for x in cp.rejoining_until],
             [int(x) for x in cp.quarantine_counts])
    cp2 = _plane(probe=10)
    cp2.adopt(saved[0], 6, departed=saved[1], sched_through=4,
              rejoining_until=saved[2], quarantine_counts=saved[3])
    assert cp2.lifecycle()[1] == "rejoining"
    assert cp2.quarantine_counts[3] == 2
    cp2.observe(7, _obs(4, nonfinite=[1]), 1)
    ev = cp2.observe(8, _obs(4, nonfinite=[1]), 1)
    assert ev.left == [(1, "probe_failed")]
    cp3 = _plane()
    cp3.adopt([True] * 4, 6, rejoining_until=[9] * 8,
              quarantine_counts=[1] * 8)
    assert (cp3.rejoining_until == -1).all()
    assert (cp3.quarantine_counts == 0).all()


# ----------------------------------------------------- trainer plumbing
def _trainer_cfg(world_bs, steps, outdir=None, **kw):
    base = dict(
        lion=True, async_grad=True, wire="sign_psum", vote_every=1,
        vote_buckets=1, learning_rate=5e-3, lr_scheduler_type="constant",
        warmup_steps=0, max_steps=steps, weight_decay=0.0,
        per_device_train_batch_size=world_bs, gradient_accumulation_steps=1,
        block_size=32, logging_steps=1, output_dir=outdir,
        guard_strikes=2, guard_cooldown=1000,
    )
    base.update(kw)
    return TrainConfig(**base)


def _train(cfg, world, steps, model, seed=4, trainer=None):
    mesh = make_mesh(data=world, devices=jax.devices()[:world])
    tr = trainer if trainer is not None else Trainer.for_gpt2(cfg, mesh,
                                                              model)
    blocks = synthetic_lm_dataset(96, 32, model.vocab_size, seed=seed)
    hist = tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                    max_steps=steps)
    losses = [h["loss"] for h in hist if "loss" in h]
    return tr, losses


def _assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def test_trainer_flag_validation():
    model = GPT2Config.tiny()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="control_plane"):
        Trainer.for_gpt2(_trainer_cfg(2, 4, inject_membership=
                                      "worker_drop:1"), mesh, model)
    # an out-of-world worker fails at CONSTRUCTION, not at its due step
    with pytest.raises(ValueError, match="outside world"):
        Trainer.for_gpt2(_trainer_cfg(2, 4, control_plane=True,
                                      inject_membership="worker_drop:7:500"),
                         mesh, model)
    with pytest.raises(ValueError, match="observe"):
        Trainer.for_gpt2(_trainer_cfg(2, 4, control_plane=True,
                                      vote_guard="observe"), mesh, model)
    with pytest.raises(ValueError, match="AdamW|election"):
        Trainer.for_gpt2(_trainer_cfg(2, 4, lion=False, async_grad=False,
                                      control_plane=True), mesh, model)


def test_control_plane_auto_arms_enforce():
    model = GPT2Config.tiny()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr = Trainer.for_gpt2(_trainer_cfg(2, 4, control_plane=True), mesh,
                          model)
    assert tr.cfg.vote_guard == "enforce"
    assert tr._cplane is not None and tr._guard is not None
    assert np.asarray(tr.state.health).all()
    tr.close()


def test_drop_at_zero_bit_identical_to_masked_from_scratch():
    """The degraded-phase acceptance pin: a W=4 run whose worker 2
    departed before the first dispatch is BIT-identical — losses, params,
    momenta, health mask — to a from-scratch W−1 masked run (the PR 5
    masked-election machinery driven by hand). 'Worker left' IS a mask
    transition, nothing more."""
    model = GPT2Config.tiny()
    steps = 8
    tr_a, losses_a = _train(
        _trainer_cfg(6, steps, control_plane=True,
                     inject_membership="worker_drop:2:0"),
        4, steps, model)
    assert tr_a._cplane.lifecycle()[2] == "departed"
    resilience.clear_faults()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr_b = Trainer.for_gpt2(_trainer_cfg(6, steps, vote_guard="enforce"),
                            mesh, model)
    mask = [True, True, False, True]
    tr_b.state = tr_b.state._replace(health=jnp.asarray(mask))
    tr_b._guard.adopt_mask(mask, step=0)
    _, losses_b = _train(None, 4, steps, model, trainer=tr_b)
    assert losses_a == losses_b
    _assert_trees_equal(tr_a.params, tr_b.params)
    _assert_trees_equal(tr_a.state.exp_avg, tr_b.state.exp_avg)
    np.testing.assert_array_equal(np.asarray(tr_a.state.health),
                                  np.asarray(tr_b.state.health))
    tr_a.close()
    tr_b.close()


# the pre-registered post-rejoin parity bound at this reduced scale: the
# drop/rejoin run's tail-mean loss must track the always-healthy run
# within this many nats (the W−1 degraded phase is a BENIGN quorum change
# — 3 honest voters instead of 4 — so the bound mirrors the PR 5
# enforce-tracks-clean margin at the same tiny scale; measured gap is
# well under half of it). The full-scale bound for the banked artifact
# lives in scripts/bench_elasticity.py, pre-registered there.
REJOIN_PARITY_BOUND_NATS = 0.35


def test_drop_rejoin_completes_and_tracks_clean():
    """The headline scenario: W=4, worker 2 drops at step 3 and rejoins at
    step 9. The run must (a) complete without restart or stall, (b) end
    all-healthy with the rejoiner promoted after probation, (c) keep every
    momentum finite, and (d) track the clean always-healthy curve within
    the pre-registered bound over the tail."""
    model = GPT2Config.tiny()
    steps = 30

    def tail(x):
        return float(np.mean(x[-8:]))

    tr, losses = _train(
        _trainer_cfg(6, steps, control_plane=True, rejoin_probe_steps=4,
                     inject_membership="worker_drop:2:3,worker_rejoin:2:9"),
        4, steps, model)
    assert len(losses) == steps and all(np.isfinite(losses))
    assert tr._cplane.left_events == 1 and tr._cplane.rejoin_events == 1
    assert np.asarray(tr.state.health).all()
    assert tr._cplane.lifecycle() == ["healthy"] * 4
    assert all(np.isfinite(np.asarray(m)).all()
               for m in jax.tree.leaves(tr.state.exp_avg))
    tr.close()
    resilience.clear_faults()
    _, clean = _train(_trainer_cfg(6, steps, control_plane=True),
                      4, steps, model)
    gap = abs(tail(losses) - tail(clean))
    assert gap < REJOIN_PARITY_BOUND_NATS, (gap, losses[-8:], clean[-8:])


def test_drop_quorum_refusal_names_the_plane():
    model = GPT2Config.tiny()
    with pytest.raises(RuntimeError, match="control plane.*quorum"):
        _train(_trainer_cfg(
            6, 8, control_plane=True,
            inject_membership="worker_drop:1:0,worker_drop:2:2"),
            4, 8, model)


# --------------------------------------------------------- depth refusals
def test_elastic_resume_refuses_depth_direct(tmp_path):
    """The missing PR 8 direct test: a depth>0 checkpoint resumed at a
    DIFFERENT world with --elastic_resume must refuse loudly (the DCN
    ring's chunk ownership is a function of W)."""
    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(_trainer_cfg(6, 4, outdir=out, save_steps=4,
                                wire="hier:2", dcn_pipeline_depth=1),
                   4, 4, model)
    tr.close()
    mesh2 = make_mesh(data=2, devices=jax.devices()[:2])
    with pytest.raises(NotImplementedError, match="DCN pipeline"):
        Trainer.for_gpt2(_trainer_cfg(12, 8, outdir=out, save_steps=4,
                                      wire="hier:2", dcn_pipeline_depth=1,
                                      elastic_resume=True), mesh2, model)


def test_inject_rejoin_refused_at_depth_construction():
    """The in-run twin of the elastic rule, failing at CONSTRUCTION (not
    steps into the run): a scheduled worker_rejoin cannot compose with
    --dcn_pipeline_depth > 0."""
    model = GPT2Config.tiny()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="worker_rejoin.*dcn_pipeline"
                                         "|dcn_pipeline.*rejoin"):
        Trainer.for_gpt2(_trainer_cfg(
            6, 8, control_plane=True, wire="hier:2", dcn_pipeline_depth=1,
            inject_membership="worker_drop:2:0,worker_rejoin:2:4"),
            mesh, model)


# --------------------------------------------- control plane × checkpoints
def test_crash_resume_mid_degradation_bit_identical(tmp_path):
    """Crash-resume while degraded: the checkpoint carries the W−1 mask
    (LionState.health) plus the departed set (manifest meta cp_departed);
    the resumed run must NOT auto-readmit the departed worker and must
    continue bit-identically to the uninterrupted run."""
    model = GPT2Config.tiny()
    spec = "worker_drop:2:2"
    # uninterrupted baseline: 8 steps, drop at 2
    tr_full, losses_full = _train(
        _trainer_cfg(6, 8, control_plane=True, inject_membership=spec),
        4, 8, model)
    tr_full.close()
    resilience.clear_faults()
    # interrupted: train to 4 (saves at 4), tear down, resume, finish
    out = str(tmp_path / "run")
    tr1, losses1 = _train(
        _trainer_cfg(6, 8, control_plane=True, inject_membership=spec,
                     outdir=out, save_steps=4),
        4, 4, model)
    assert tr1._cplane.lifecycle()[2] == "departed"
    tr1.close()
    resilience.clear_faults()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr2 = Trainer.for_gpt2(
        _trainer_cfg(6, 8, control_plane=True, inject_membership=spec,
                     outdir=out, save_steps=4), mesh, model)
    assert tr2.step_count == 4
    # the departed set survived the restart — no quarantine/cooldown
    # masquerade (a cooldown would readmit a worker the run knew was GONE)
    assert tr2._cplane.lifecycle()[2] == "departed"
    assert dict(tr2._cplane.departed)[2] == "resumed"
    _, losses2 = _train(None, 4, 8, model, trainer=tr2)
    assert losses1 + losses2 == losses_full
    _assert_trees_equal(tr2.params, tr_full.params)
    _assert_trees_equal(tr2.state.exp_avg, tr_full.state.exp_avg)
    np.testing.assert_array_equal(np.asarray(tr2.state.health),
                                  [True, True, False, True])
    tr2.close()


def test_resume_after_consumed_rejoin_does_not_replay(tmp_path):
    """The consumed-schedule watermark (manifest meta cp_sched_through):
    a resume whose checkpoint postdates the scheduled rejoin must NOT
    replay the drop+rejoin pair at the resume boundary (a replay would
    re-depart and re-heal the worker — overwriting its momentum with the
    healthy mean and double-counting events). The resumed run continues
    bit-identically to the uninterrupted one."""
    model = GPT2Config.tiny()
    spec = "worker_drop:2:2,worker_rejoin:2:4"
    tr_full, losses_full = _train(
        _trainer_cfg(6, 12, control_plane=True, rejoin_probe_steps=2,
                     inject_membership=spec),
        4, 12, model)
    assert tr_full._cplane.left_events == 1
    tr_full.close()
    resilience.clear_faults()
    out = str(tmp_path / "run")
    tr1, losses1 = _train(
        _trainer_cfg(6, 12, control_plane=True, rejoin_probe_steps=2,
                     inject_membership=spec, outdir=out, save_steps=8),
        4, 8, model)
    assert tr1._cplane.rejoin_events == 1  # consumed before the save
    tr1.close()
    resilience.clear_faults()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr2 = Trainer.for_gpt2(
        _trainer_cfg(6, 12, control_plane=True, rejoin_probe_steps=2,
                     inject_membership=spec, outdir=out, save_steps=8),
        mesh, model)
    assert tr2.step_count == 8
    # the already-consumed entries were dropped from the registry
    assert resilience.fault("membership") == []
    _, losses2 = _train(None, 4, 12, model, trainer=tr2)
    # no replay: zero leave/rejoin events in the resumed segment, and the
    # trajectory matches the uninterrupted run bit-for-bit
    assert tr2._cplane.left_events == 0 and tr2._cplane.rejoin_events == 0
    assert losses1 + losses2 == losses_full
    _assert_trees_equal(tr2.params, tr_full.params)
    _assert_trees_equal(tr2.state.exp_avg, tr_full.state.exp_avg)
    tr2.close()


def test_control_plane_toggle_on_resume_tolerated(tmp_path):
    """The PR 5 guard-toggle semantics extended to the plane: a plane-on
    checkpoint (with a departed worker) resumes into a plane-off guard
    run — the mask survives, the departed worker degrades to plain
    quarantine — and a guard-only checkpoint resumes into a plane-on run
    with nobody departed."""
    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(_trainer_cfg(6, 4, control_plane=True, outdir=out,
                                save_steps=4,
                                inject_membership="worker_drop:1:0"),
                   4, 4, model)
    tr.close()
    resilience.clear_faults()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    tr2 = Trainer.for_gpt2(_trainer_cfg(6, 8, vote_guard="enforce",
                                        outdir=out, save_steps=4),
                           mesh, model)
    assert tr2.step_count == 4 and tr2._cplane is None
    np.testing.assert_array_equal(np.asarray(tr2.state.health),
                                  [True, False, True, True])
    assert not tr2._guard.healthy[1]
    tr2.close()
    out2 = str(tmp_path / "run2")
    tr3, _ = _train(_trainer_cfg(6, 4, vote_guard="enforce", outdir=out2,
                                 save_steps=4), 4, 4, model)
    tr3.close()
    tr4 = Trainer.for_gpt2(_trainer_cfg(6, 8, control_plane=True,
                                        outdir=out2, save_steps=4),
                           mesh, model)
    assert tr4.step_count == 4 and tr4._cplane is not None
    assert tr4._cplane.departed == {}
    assert np.asarray(tr4.state.health).all()
    tr4.close()


# ------------------------------------------------------------- journal
def test_journal_membership_events_and_timeline(tmp_path):
    """The satellite: worker_left / worker_rejoined / membership_transition
    ride the PR-7 journal with cause + step + mask before/after, and
    cli/run_analyze surfaces the timeline alongside step attribution."""
    import importlib.util
    import os

    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(
        _trainer_cfg(6, 12, control_plane=True, rejoin_probe_steps=2,
                     journal=True, outdir=out,
                     inject_membership="worker_drop:2:3,worker_rejoin:2:7"),
        4, 12, model)
    tr.close()
    events = []
    for p in sorted(pathlib.Path(out, "journal").glob("journal_rank*")):
        for line in p.read_text().splitlines():
            if line.strip():
                events.append(json.loads(line))
    names = [e.get("name") for e in events if e.get("kind") == "event"]
    assert "worker_left" in names and "worker_rejoined" in names
    assert "membership_transition" in names
    left = next(e for e in events if e.get("name") == "worker_left")
    assert left["worker"] == 2 and left["cause"] == "injected_drop"
    assert left["mask_before"] == [True] * 4
    assert left["mask_after"] == [True, True, False, True]
    # run_analyze (stdlib-only, by file path — the check_evidence contract)
    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "dlt_run_analyze_cp", os.path.join(
            repo, "distributed_lion_tpu", "cli", "run_analyze.py"))
    ra = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ra)
    report = ra.analyze_dir(out)
    timeline = report["membership"]
    assert [r["event"] for r in timeline].count("worker_left") == 1
    assert [r["event"] for r in timeline].count("worker_rejoined") == 1
    steps = {r["event"]: r["step"] for r in timeline
             if r["event"].startswith("worker_")}
    assert steps["worker_left"] == 3 and steps["worker_rejoined"] == 7
    rendered = ra.render(report)
    assert "membership timeline" in rendered
    assert "worker 2: worker_left (injected_drop)" in rendered


# ------------------------------------------------- the evidence artifact
REPO = pathlib.Path(__file__).resolve().parent.parent


def _check_evidence():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ce_elastic", str(REPO / "scripts" / "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    return ce


def test_banked_elasticity_artifact_passes_stage():
    """The committed CPU artifact satisfies the elasticity evidence stage
    (schema + survival facts + both bit-identity markers + timeline
    events + the pre-registered parity pass) — the same gate the
    runbook's on-chip recapture (stage 5i) must clear."""
    ce = _check_evidence()
    assert pathlib.Path(ce.ELASTICITY_ARTIFACT).exists(), \
        "banked artifact missing"
    assert ce.elasticity_ok()
    with open(ce.ELASTICITY_ARTIFACT) as f:
        doc = json.load(f)
    assert doc["survive"]["final_alive"] == doc["meta"]["world"]


def test_elasticity_stage_rejects_bad_artifacts(tmp_path):
    ce = _check_evidence()
    with open(ce.ELASTICITY_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "elasticity.json"

    def variant(**mutate):
        doc = json.loads(json.dumps(good))
        for dotted, v in mutate.items():
            sec, key = dotted.split("__")
            doc[sec][key] = v
        p.write_text(json.dumps(doc))
        return str(p)

    # run didn't survive / restarted mid-way
    assert not ce.elasticity_ok(variant(survive__completed=False))
    # nonfinite state leaked through
    assert not ce.elasticity_ok(variant(survive__finite=False))
    # the rejoiner never came back (final quorum below W)
    assert not ce.elasticity_ok(variant(survive__final_alive=3))
    # a second spurious departure
    assert not ce.elasticity_ok(variant(survive__left_events=2))
    # degraded phase diverged from the masked-from-scratch reference
    assert not ce.elasticity_ok(variant(bit_identity__degraded_vs_masked=False))
    assert not ce.elasticity_ok(variant(bit_identity__drop_deterministic=False))
    # post-rejoin parity bound failed
    assert not ce.elasticity_ok(variant(parity__pass=False))
    # timeline lost the rejoin event (the run_analyze leg didn't close)
    doc = json.loads(json.dumps(good))
    doc["timeline"] = [r for r in doc["timeline"]
                       if r["event"] != "worker_rejoined"]
    p.write_text(json.dumps(doc))
    assert not ce.elasticity_ok(str(p))
    # schema violation (NaN token) caught via validate_metrics delegation
    p.write_text(json.dumps(good).replace(
        str(good["parity"]["rejoin_gap_nats"]), "NaN", 1))
    assert not ce.elasticity_ok(str(p))
    # strict schema: a timeline row without its quorum fields
    doc = json.loads(json.dumps(good))
    del doc["timeline"][0]["alive"]
    p.write_text(json.dumps(doc))
    assert not ce.elasticity_ok(str(p))
    # a present-but-wrong-type section fails the schema (and must be
    # judged false, never crash the evidence check)
    doc = json.loads(json.dumps(good))
    doc["survive"] = []
    p.write_text(json.dumps(doc))
    assert not ce.elasticity_ok(str(p))


def test_membership_timeline_dedupes_across_ranks():
    """Every rank's trainer journals the same global transition; the
    merged multi-host timeline must show each transition once (rank=N
    restricts to that rank's records, like the other analyzers)."""
    import importlib.util
    import os

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "dlt_run_analyze_ranks", os.path.join(
            repo, "distributed_lion_tpu", "cli", "run_analyze.py"))
    ra = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ra)
    ev = [{"kind": "event", "name": "worker_left", "rank": r, "t": 1.0,
           "step": 3, "worker": 2, "cause": "injected_drop", "alive": 3,
           "world": 4} for r in range(4)]
    ev += [{"kind": "event", "name": "worker_rejoined", "rank": r,
            "t": 2.0, "step": 9, "worker": 2, "cause": "rejoin",
            "alive": 4, "world": 4} for r in range(4)]
    merged = ra.membership_timeline(ev)
    assert [r["event"] for r in merged] == ["worker_left",
                                            "worker_rejoined"]
    assert len(ra.membership_timeline(ev, rank=1)) == 2
    assert ra.membership_timeline(ev, rank=7) == []


def test_membership_metrics_are_strict_json(tmp_path):
    """The plane's cp_* scalars ride the strict-JSON metrics stream."""
    import subprocess
    import sys

    model = GPT2Config.tiny()
    out = str(tmp_path / "run")
    tr, _ = _train(_trainer_cfg(6, 4, control_plane=True, outdir=out,
                                inject_membership="worker_drop:3:1"),
                   4, 4, model)
    tr.close()
    proc = subprocess.run(
        [sys.executable, "scripts/validate_metrics.py",
         f"{out}/metrics.jsonl"],
        capture_output=True, text=True,
        cwd=str(pathlib.Path(__file__).resolve().parent.parent))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(line)
            for line in open(f"{out}/metrics.jsonl") if line.strip()]
    assert any(r.get("train/cp_departed") == 1 for r in rows)
