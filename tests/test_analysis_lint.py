"""graft-check tier 1 (analysis/lint.py): every rule has a fixture file
proving it fires, the suppression syntax works, traced-scope detection has
the documented boundary, and — the CI pin — the package itself lints
clean (zero findings), so any future violation of a codified pitfall
fails tier-1 instead of waiting for a chip run."""

import importlib.util
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "distributed_lion_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

# load lint.py by FILE PATH, the way dependency-light scripts must be able
# to (scripts/check_evidence.py runs on boxes without jax; importing the
# package would pull in compat -> jax)
_spec = importlib.util.spec_from_file_location(
    "graft_lint", os.path.join(PKG, "analysis", "lint.py"))
lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lint)


# ------------------------------------------------------------------ fixtures
RULE_FIXTURES = {
    "DLT001": ("dlt001_host_sync.py", 4),
    "DLT002": ("dlt002_nondeterminism.py", 3),
    "DLT003": ("dlt003_host_callback.py", 2),
    "DLT004": ("dlt004_prng_save.py", 1),
    "DLT005": ("dlt005_axis_literal.py", 3),
    "DLT006": ("dlt006_swallowed.py", 2),
    "DLT007": ("dlt007_json.py", 2),
    "DLT008": ("dlt008_mutable_default.py", 2),
    # the DLT009 fixture sits under fixtures/analysis/train/ so the
    # path-scoped rule (bare print under a train//data/ directory) applies
    # to it the same way it applies to distributed_lion_tpu/train/
    "DLT009": (os.path.join("train", "dlt009_bare_print.py"), 2),
    # DLT010/DLT011 are serve/-scoped the same way (host-loop hygiene for
    # the serving plane, ISSUE 19)
    "DLT010": (os.path.join("serve", "dlt010_host_loop_device_alloc.py"),
               3),
    "DLT011": (os.path.join("serve", "dlt011_wall_clock.py"), 3),
    # DLT012 (ISSUE 20): blocking socket/pipe reads need a deadline seam
    # in serve/ — the process-isolated fleet's heartbeat verdicts depend
    # on reads that return
    "DLT012": (os.path.join("serve", "dlt012_blocking_socket.py"), 3),
}


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_fires_on_its_fixture(rule):
    """Each rule fires exactly the marked number of times on its fixture —
    and nothing else fires there (single-rule fixtures keep failures
    attributable)."""
    fixture, expected = RULE_FIXTURES[rule]
    findings = lint.lint_file(os.path.join(FIXTURES, fixture))
    assert [f.rule for f in findings] == [rule] * expected, (
        f"{fixture}: {[str(f) for f in findings]}")


def test_every_documented_rule_has_a_fixture():
    assert set(RULE_FIXTURES) == set(lint.RULES)


def test_clean_fixture_has_zero_findings():
    assert lint.lint_file(os.path.join(FIXTURES, "clean.py")) == []


# -------------------------------------------------------------- suppressions
def test_line_suppression():
    src = (
        "import json\n"
        "def f(r):\n"
        "    return json.dumps(r)  # graft: disable=DLT007\n"
    )
    assert lint.lint_source(src) == []


def test_file_suppression():
    src = (
        "# graft: disable-file=DLT008\n"
        "def f(x, acc=[]):\n"
        "    return acc\n"
        "def g(x, acc=[]):\n"
        "    return acc\n"
    )
    assert lint.lint_source(src) == []


def test_suppression_in_string_or_docstring_is_inert():
    """Suppressions live in COMMENT tokens only: a module that merely
    DOCUMENTS the syntax in a docstring (as analysis/lint.py itself does)
    must not silently disable rules on itself."""
    src = (
        '"""Docs: suppress with `# graft: disable-file=DLT006`."""\n'
        "def f(p):\n"
        "    try:\n"
        "        p.unlink()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    assert [f.rule for f in lint.lint_source(src)] == ["DLT006"]
    quoted = 'x = "# graft: disable=DLT008"\ndef f(a=[]):\n    return a\n'
    assert [f.rule for f in lint.lint_source(quoted)] == ["DLT008"]


def test_suppression_is_rule_specific():
    src = (
        "import json\n"
        "def f(r, acc=[]):  # graft: disable=DLT007\n"
        "    return json.dumps(r)\n"
    )
    # the DLT008 on line 2 is NOT covered by the DLT007 suppression; the
    # DLT007 itself is on line 3, not the suppressed line
    rules = [f.rule for f in lint.lint_source(src)]
    assert "DLT008" in rules and "DLT007" in rules


# ------------------------------------------------------- traced-scope bounds
def test_partial_shard_map_decorator_is_traced():
    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.shard_map, mesh=None, in_specs=None, out_specs=None)\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    assert [f.rule for f in lint.lint_source(src)] == ["DLT001"]


def test_nested_function_inherits_traced_scope():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(xs):\n"
        "    def micro(x):\n"
        "        return x.item()\n"
        "    return micro(xs)\n"
    )
    assert [f.rule for f in lint.lint_source(src)] == ["DLT001"]


def test_host_code_is_not_traced_scope():
    src = (
        "def log(metrics):\n"
        "    print('loss', float(metrics['loss']))\n"
    )
    assert lint.lint_source(src) == []


def test_lint_paths_under_hidden_ancestor(tmp_path):
    """The hidden-component skip applies BELOW the lint root only: a repo
    checked out under a hidden ancestor (~/.cache, a .worktrees dir) must
    still lint — an empty file list reading 'clean' is a false-green CI
    gate."""
    root = tmp_path / ".hidden" / "pkg"
    root.mkdir(parents=True)
    (root / "bad.py").write_text("def f(x, acc=[]):\n    return acc\n")
    assert [f.rule for f in lint.lint_paths([root])] == ["DLT008"]
    # hidden children below the root are still skipped
    sub = root / ".venv"
    sub.mkdir()
    (sub / "x.py").write_text("def g(a=[]):\n    return a\n")
    assert [f.rule for f in lint.lint_paths([root])] == ["DLT008"]


# --------------------------------------------------------------- the CI pins
def test_package_lints_clean():
    """THE tier-1 pin: zero graft-check findings over the whole package.
    A new violation of any codified pitfall fails here, with the rule and
    line in the assertion message."""
    findings = lint.lint_paths([PKG])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exit_codes(tmp_path):
    """python -m distributed_lion_tpu.analysis: exit 0 on a clean tree,
    1 with findings — the contract scripts/ci_static.sh and the runbook's
    static stage rely on."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, "-m", "distributed_lion_tpu.analysis", PKG],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x, acc=[]):\n    return acc\n")
    r = subprocess.run(
        [sys.executable, "-m", "distributed_lion_tpu.analysis", str(bad)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert r.returncode == 1 and "DLT008" in r.stdout


def test_lint_runs_standalone_without_package():
    """lint.py is pure stdlib AND directly runnable by path — the no-jax
    contract (scripts/ci_static.sh uses exactly this invocation)."""
    r = subprocess.run(
        [sys.executable, os.path.join(PKG, "analysis", "lint.py"), PKG],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_guard_fixture_and_guard_modules_clean():
    """ISSUE 5 satellite: the vote guard's step-side code must stay free
    of host syncs — the quarantine decision runs on the host one dispatch
    behind, never inside the compiled step. The fixture shows the
    forbidden shape (DLT001 fires on a step that host-reads the health
    mask / guard observations); the guard's real modules lint clean by
    file path."""
    findings = lint.lint_file(
        os.path.join(FIXTURES, "guard_step_host_sync.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001"], (
        [str(f) for f in findings])
    for rel in ("train/vote_guard.py", "optim/distributed_lion.py",
                "parallel/collectives.py"):
        path = os.path.join(PKG, rel)
        assert lint.lint_file(path) == [], rel


def test_control_plane_fixture_and_modules_clean():
    """ISSUE 10 satellite: membership is a host-side decision — the
    control plane consumes drop/rejoin signals at dispatch boundaries and
    the step only consumes the pushed mask. The path-scoped fixture under
    fixtures/analysis/control_plane/ shows the forbidden shape (DLT001
    fires twice on a step that host-reads the membership schedule / alive
    mask); the real control-plane modules lint zero-finding by file
    path."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "control_plane", "dlt001_membership_host_read.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001"], (
        [str(f) for f in findings])
    for rel in ("train/control_plane.py", "train/vote_guard.py",
                "train/resilience.py", "train/loop.py"):
        path = os.path.join(PKG, rel)
        assert lint.lint_file(path) == [], rel


def test_serve_fixture_and_serve_modules_clean():
    """ISSUE 9 satellite: the serving engine's decode tick must never
    host-read per token — the classic serving pitfall (an `int(token)` /
    EOS branch inside the jitted tick serializes the rolling batch). The
    path-scoped fixture under fixtures/analysis/serve/ shows the
    forbidden shape (DLT001 fires twice); the real serving modules lint
    clean by file path — the engine's ONE host read per tick happens at
    the dispatch boundary, outside traced scope."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt001_decode_tick_host_read.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001"], (
        [str(f) for f in findings])
    for rel in ("serve/engine.py", "serve/kv_cache.py", "serve/api.py",
                "ops/attention.py", "cli/run_serve.py"):
        path = os.path.join(PKG, rel)
        assert lint.lint_file(path) == [], rel


def test_tp_serve_fixtures_and_serve_parallel_modules_clean():
    """ISSUE 13 satellite: TP serving code must (a) never hardcode a
    mesh-axis string literal — the engine threads parallel.mesh's
    TENSOR_AXIS through its shard_map specs and the models' psum exits
    (DLT005 fires 3× on the fixture showing the forbidden shape), and
    (b) never host-read per token inside the SHARD_MAP'd decode tick —
    worse than the single-device pitfall, it serializes the whole slice
    (DLT001 fires 2× on its fixture). Every module under serve/ and
    parallel/ lints zero-finding by file path."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt005_tp_axis_literal.py"))
    assert [f.rule for f in findings] == ["DLT005"] * 3, (
        [str(f) for f in findings])
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt001_sharded_tick_host_read.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001"], (
        [str(f) for f in findings])
    for sub in ("serve", "parallel"):
        base = os.path.join(PKG, sub)
        for name in sorted(os.listdir(base)):
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            assert lint.lint_file(path) == [], f"{sub}/{name}"


def test_expert_axis_fixture_and_moe_serve_modules_clean():
    """ISSUE 15 satellite: MoE serving code must never hardcode the
    expert mesh-axis string literal — the engine threads parallel.mesh's
    EXPERT_AXIS through its (data=1, expert=ep, tensor=tp) shard_map mesh
    and the model hooks' ``ep_axis``, and parallel/expert.moe_ffn binds
    whatever axis name the caller passes (DLT005 fires 3× on the fixture
    showing the forbidden shape). parallel/expert.py and every serve-path
    module the MoE route touches lint zero-finding by file path."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt005_expert_axis_literal.py"))
    assert [f.rule for f in findings] == ["DLT005"] * 3, (
        [str(f) for f in findings])
    for rel in ("parallel/expert.py", "models/gpt2.py",
                "models/generate.py", "serve/engine.py",
                "serve/speculate.py", "serve/kv_cache.py",
                "cli/run_serve.py"):
        path = os.path.join(PKG, rel)
        assert lint.lint_file(path) == [], rel


def test_ep_batch_axis_fixture_and_touched_modules_clean():
    """ISSUE 16 satellite: the batch-sharded decode path (slots
    ``P(EXPERT_AXIS)``, page pools sharded on their block dim) and the
    training balance-ring psum must never hardcode the mesh-axis string —
    the fixture shows the forbidden shapes (DLT005 fires 4×: slot spec,
    pool spec with both axes literal-named, psum default). Every module
    ISSUE 16 touched lints zero-finding by file path."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt005_ep_batch_axis_literal.py"))
    assert [f.rule for f in findings] == ["DLT005"] * 4, (
        [str(f) for f in findings])
    for rel in ("parallel/expert.py", "parallel/mesh.py",
                "models/gpt2.py", "serve/engine.py", "serve/speculate.py",
                "train/loop.py", "cli/run_serve.py", "optim/lion.py",
                "optim/distributed_lion.py"):
        path = os.path.join(PKG, rel)
        assert lint.lint_file(path) == [], rel


def test_migration_fixture_and_replica_plane_clean():
    """ISSUE 14 satellite: a migration re-prefill must never host-read
    per committed token — replaying a migrated request's history with an
    `int(tok)`/logits branch inside the jitted dispatch pays
    len(committed) round trips per migration and serializes the
    survivor's batch. The fixture shows the forbidden shape (DLT001 fires
    twice); serve/replica_plane.py (pure host-side scheduling) and the
    engine's resumption path lint zero-finding by file path — the real
    re-prefill is ONE bucketed dispatch with one boundary host read."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt001_migration_host_read.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001"], (
        [str(f) for f in findings])
    for rel in ("serve/replica_plane.py", "serve/engine.py"):
        path = os.path.join(PKG, rel)
        assert lint.lint_file(path) == [], rel


def test_speculate_fixture_and_module_clean():
    """ISSUE 11 satellite: the speculative verify dispatch must never
    host-read per DRAFT token — an `int(accept[i])` acceptance branch
    inside the jitted verify loop pays one device→host round trip per
    proposed token and erases the dispatch amortization speculation
    exists to buy. The fixture shows the forbidden shape (DLT001 fires
    twice); serve/speculate.py lints zero-finding by file path — its one
    host read per tick (tokens + accept counts) happens at the dispatch
    boundary, and accept/rollback are pure host block-table math."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt001_verify_host_read.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001"], (
        [str(f) for f in findings])
    assert lint.lint_file(os.path.join(PKG, "serve", "speculate.py")) == []


def test_blocking_io_fixture_and_net_modules_clean():
    """ISSUE 20 satellite: the serving plane's socket/pipe transports
    must never block unboundedly — a dead peer behind an unbounded
    recv/accept wedges every request in the host loop, and the
    process-isolated fleet's heartbeat verdicts depend on reads that
    return. The fixture shows the forbidden shapes (DLT012 fires 3×:
    accept, recv, os.read — and shows the two legal seams plus the
    suppression); every code path in the new socket front and the pipe
    transport lints zero-finding by file path."""
    findings = lint.lint_file(
        os.path.join(FIXTURES, "serve", "dlt012_blocking_socket.py"))
    assert [f.rule for f in findings] == ["DLT012"] * 3, (
        [str(f) for f in findings])
    for rel in ("serve/net.py", "serve/fleet_proc.py",
                "serve/replica_worker.py", "serve/fleet_state.py",
                "serve/replica_plane.py"):
        assert lint.lint_file(os.path.join(PKG, rel)) == [], rel


def test_metrics_fixture_and_metrics_module_clean():
    """ISSUE 17 satellite: the metrics plane must never host-read a
    device value — a lifecycle hook stamping TTFT from `int(tok[0])`
    inside the jitted tick would add the per-token sync the plane exists
    to observe, and "metrics on" would no longer be observationally
    free. The fixture shows the forbidden shape (DLT001 fires three
    times); serve/metrics.py lints zero-finding by file path — every
    stamp rides host work the tick loop already does — and the engine's
    instrumented tick loop stays clean too."""
    findings = lint.lint_file(os.path.join(
        FIXTURES, "serve", "dlt001_metrics_host_read.py"))
    assert [f.rule for f in findings] == ["DLT001", "DLT001", "DLT001"], (
        [str(f) for f in findings])
    for rel in ("serve/metrics.py", "serve/engine.py",
                "serve/replica_plane.py"):
        assert lint.lint_file(os.path.join(PKG, rel)) == [], rel
