"""Tensor parallelism: TP forward == single-device forward; dp×tp vote-Lion
training matches pure-dp training on the same global batch."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS, make_mesh
from distributed_lion_tpu.parallel.tensor_parallel import gpt2_param_specs, validate_tp
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def test_tp_forward_matches_single_device():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)), jnp.int32)
    expected = gpt2_apply(params, toks, cfg)

    mesh = make_mesh(data=1, tensor=4, devices=jax.devices()[:4])
    specs = gpt2_param_specs(cfg)

    def f(p, t):
        return gpt2_apply(p, t, cfg, tp_axis=TENSOR_AXIS)

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
                      check_vma=False)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-2, atol=2e-2)


def test_dp_tp_training_runs_and_learns():
    model_cfg = GPT2Config.tiny()
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
        warmup_steps=5, max_steps=30, per_device_train_batch_size=2,
        gradient_accumulation_steps=2, block_size=32, logging_steps=10,
        eval_steps=10**6, save_steps=10**6, output_dir=None,
    )
    mesh = make_mesh(data=4, tensor=2, devices=jax.devices())
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(512, 32, model_cfg.vocab_size)
    it = batch_iterator(blocks, trainer.global_train_batch(), seed=0)
    history = trainer.train(it, max_steps=30)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0] - 0.3, f"dp×tp loss did not fall: {losses}"
    # TP-sharded weights really are sharded over the tensor axis
    qkv = trainer.params["blocks"][0]["attn"]["qkv"]
    assert qkv.sharding.spec == P(None, None, TENSOR_AXIS)
    # replicated leaves must not drift across tensor ranks: grads of LN /
    # embeddings are completed by the copy_to_tp_region backward psum —
    # without it each tensor rank votes on its own partial grad (regression
    # for the missing Megatron f-operator)
    for leaf in (trainer.params["ln_f"]["scale"], trainer.params["wte"]):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    trainer.close()


def test_llama_tp_forward_matches_single_device():
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
    from distributed_lion_tpu.parallel.tensor_parallel import llama_param_specs

    cfg = LlamaConfig.tiny()  # 4 heads, 2 kv heads → tp=2 divides both
    params = llama_init(jax.random.key(0), cfg)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 16)), jnp.int32)
    expected = llama_apply(params, toks, cfg)

    mesh = make_mesh(data=1, tensor=2, devices=jax.devices()[:2])
    specs = llama_param_specs(cfg)

    def f(p, t):
        return llama_apply(p, t, cfg, tp_axis=TENSOR_AXIS)

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
                      check_vma=False)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-2, atol=2e-2)


def test_gpt2_lora_targets_stacked_qkv():
    from distributed_lion_tpu.models.lora import LoraConfig, lora_apply_fn, lora_init, merge_lora

    cfg = GPT2Config.tiny()
    base = gpt2_init(jax.random.key(0), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=("qkv",))
    adapters = lora_init(jax.random.key(1), base, lcfg)
    assert len(adapters) == cfg.n_layer
    ab = adapters["blocks/0/attn/qkv"]
    assert ab["A"].shape == (64, 4) and ab["B"].shape == (4, 3, 64)
    # identity at init, merge consistent with wrapped apply after perturbation
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 256, (1, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: gpt2_apply(p, t, cfg), base, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)), np.asarray(gpt2_apply(base, toks, cfg)),
        rtol=1e-5, atol=1e-5,
    )
    adapters = jax.tree.map(lambda x: x + 0.01, adapters)
    merged = merge_lora(base, adapters, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)),
        np.asarray(gpt2_apply(merged, toks, cfg)),
        rtol=2e-2, atol=2e-2,
    )


def test_validate_tp_rejects_indivisible():
    import pytest

    with pytest.raises(ValueError):
        validate_tp(GPT2Config.tiny(), 3, "gpt2")
