"""Full-parameter Llama CLM pretraining via run_clm --model_family llama.

The reference's run_clm is architecture-agnostic (AutoModelForCausalLM,
run_clm.py:425-444) — ours must train the Llama family too, composing with
the same dp/tp/sp axes as GPT-2.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.llama import LlamaConfig
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _cfg(**kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
        warmup_steps=5, max_steps=20, per_device_train_batch_size=2,
        gradient_accumulation_steps=2, block_size=32, logging_steps=5,
        eval_steps=10**6, save_steps=10**6, seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _run(cfg, mesh=None, steps=20, model_kw=None):
    mesh = mesh or make_mesh(data=8)
    model_cfg = LlamaConfig.tiny(**(model_kw or {}))
    trainer = Trainer.for_llama(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(512, cfg.block_size, model_cfg.vocab_size)
    it = batch_iterator(blocks, trainer.global_train_batch(), seed=0)
    history = trainer.train(it, max_steps=steps)
    trainer.close()
    return trainer, [h["loss"] for h in history if "loss" in h]


def test_llama_vote_lion_loss_decreases():
    _, losses = _run(_cfg())
    assert losses[-1] < losses[0]


def test_llama_tp_matches_pure_dp():
    """dp=4 x tp=2 reproduces the dp=4 loss trajectory (full-param TP)."""
    t_tp, l_tp = _run(_cfg(), mesh=make_mesh(data=4, tensor=2), steps=10)
    _, l_dp = _run(_cfg(), mesh=make_mesh(data=4, devices=jax.devices()[:4]),
                   steps=10)
    np.testing.assert_allclose(l_tp, l_dp, rtol=2e-2, atol=2e-2)
    # TP-replicated leaves stay bit-identical across ranks
    ln = t_tp.params["ln_f"]["scale"]
    shards = [np.asarray(s.data) for s in ln.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_llama_sp_matches_pure_dp():
    """dp=2 x sp=4 reproduces the dp=2 trajectory (full-param seq parallel)."""
    _, l_sp = _run(_cfg(), mesh=make_mesh(data=2, seq=4), steps=8)
    _, l_dp = _run(_cfg(), mesh=make_mesh(data=2, devices=jax.devices()[:2]),
                   steps=8)
    np.testing.assert_allclose(l_sp, l_dp, rtol=2e-2, atol=2e-2)


def test_llama_vocab_chunks_matches_dense():
    """Chunked-vocab CE on the Llama path: same math as the dense loss (the
    first logged loss is bit-close); the later trajectory stays within the
    sign-vote bf16 drift envelope the other equivalence tests use."""
    _, dense = _run(_cfg(), steps=8)
    _, chunked = _run(_cfg(vocab_chunks=4), steps=8)
    np.testing.assert_allclose(chunked[0], dense[0], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(chunked, dense, rtol=2e-2, atol=2e-2)


def test_run_clm_llama_cli_and_hf_export(tmp_path):
    transformers = pytest.importorskip("transformers")
    from distributed_lion_tpu.cli.run_clm import main

    exp = tmp_path / "hf"
    main([
        "--model_family", "llama", "--model_name", "tiny", "--dataset",
        "synthetic", "--lion", "--async_grad", "--max_steps", "2",
        "--per_device_train_batch_size", "1", "--gradient_accumulation_steps",
        "1", "--block_size", "32", "--logging_steps", "10", "--eval_steps",
        "1000", "--save_steps", "1000", "--hf_export", str(exp),
        "--param_dtype", "float32",
    ])
    model = transformers.LlamaForCausalLM.from_pretrained(str(exp))
    assert model.config.num_hidden_layers == 2


def test_model_path_family_detection_precedes_guards(tmp_path):
    """--model_path's detected family drives the guards: a Llama checkpoint
    with --dropout (default --model_family gpt2) is refused up front instead
    of silently training dropout-free."""
    pytest.importorskip("transformers")
    from distributed_lion_tpu.cli.run_clm import main
    from distributed_lion_tpu.models.hf_export import llama_to_hf
    from distributed_lion_tpu.models.llama import llama_init

    cfg = LlamaConfig.tiny()
    llama_to_hf(llama_init(jax.random.key(0), cfg), cfg, str(tmp_path / "ck"))
    with pytest.raises(ValueError, match="dropout"):
        main(["--model_path", str(tmp_path / "ck"), "--dataset", "synthetic",
              "--lion", "--async_grad", "--max_steps", "1", "--dropout", "0.1"])
    # and without dropout the detected-family run trains
    main(["--model_path", str(tmp_path / "ck"), "--dataset", "synthetic",
          "--lion", "--async_grad", "--max_steps", "1", "--block_size", "32",
          "--per_device_train_batch_size", "1",
          "--gradient_accumulation_steps", "1", "--logging_steps", "10",
          "--eval_steps", "1000", "--save_steps", "1000"])


def test_llama_family_guards():
    from distributed_lion_tpu.cli.run_clm import main

    common = ["--model_family", "llama", "--model_name", "tiny", "--dataset",
              "synthetic", "--lion", "--async_grad", "--max_steps", "1"]
    with pytest.raises(NotImplementedError, match="GPT-2 only"):
        main(common + ["--moe_experts", "2"])
    with pytest.raises(ValueError, match="dropout"):
        main(common + ["--dropout", "0.1"])
    with pytest.raises(ValueError, match="model_name"):
        main([a if a != "tiny" else "gpt2_124m" for a in common])
