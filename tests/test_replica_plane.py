"""Elastic serving (ISSUE 14): replica lifecycle + live request
migration. Migration identity pinned token-identical (greedy/sampled ×
prefix_cache on/off × speculative), the serve fault matrix
(crash/drain/slow/rejoin) with zero accepted-token loss, per-request
deadlines and retry budgets ending in honest timeout/failed statuses,
journal events + the run_analyze replica timeline, and the banked
serve_resilience evidence stage."""

import importlib.util
import json
import os
import time

import jax
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)
from distributed_lion_tpu.serve.replica_plane import ServingFleet
from distributed_lion_tpu.train import resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CFG = GPT2Config.tiny()
_PARAMS = gpt2_init(jax.random.key(0), _CFG)
_MODEL = ServeModel.for_gpt2(_PARAMS, _CFG)


def _factory(**kw):
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    base.update(kw)

    def factory():
        return ServingEngine(_MODEL, ServeConfig(**base))

    return factory


def _reqs(n=6, max_new=10, seed=3, **kw):
    rng = np.random.default_rng(seed)
    lens = (3, 9, 5, 14, 6, 4, 7, 11)[:n]
    return [Request(req_id=i,
                    tokens=list(map(int, rng.integers(1, _CFG.vocab_size,
                                                      L))),
                    max_new_tokens=max_new, seed=i, **kw)
            for i, L in enumerate(lens)]


def _clone(reqs):
    return [Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed,
                    prefix_group=r.prefix_group, deadline_s=r.deadline_s)
            for r in reqs]


@pytest.fixture(autouse=True)
def _clean_serve_faults():
    resilience.inject_fault("serve", [])
    yield
    resilience.inject_fault("serve", [])


def _fleet_run(specs, reqs, arrivals=None, replicas=2, eng_kw=None, **kw):
    if specs:
        resilience.inject_fault("serve", resilience.parse_serve_specs(specs))
    fleet = ServingFleet(_factory(**(eng_kw or {})), replicas=replicas,
                         **kw)
    done = fleet.run(_clone(reqs), arrivals or {})
    return fleet, done


ARRIVALS = {0: 0, 1: 0, 2: 1, 3: 2, 4: 3, 5: 6}


# ----------------------------------------------------------- fault grammar
def test_parse_serve_fault_grammar():
    assert resilience.parse_serve_fault("replica_crash:1:7") == \
        ("replica_crash", 1, 7, 0)
    assert resilience.parse_serve_fault("replica_drain:0") == \
        ("replica_drain", 0, 0, 0)
    assert resilience.parse_serve_fault("replica_drain:0:4") == \
        ("replica_drain", 0, 4, 0)
    # slow_tick's third field is MILLISECONDS, normalized to arg (due
    # tick 0) so the schedule pops uniformly through consume_due
    assert resilience.parse_serve_fault("slow_tick:1:250") == \
        ("slow_tick", 1, 0, 250)
    assert resilience.parse_serve_fault("replica_rejoin:2:9") == \
        ("replica_rejoin", 2, 9, 0)
    assert resilience.parse_serve_specs(
        "replica_crash:0:2, replica_rejoin:0:5") == [
        ("replica_crash", 0, 2, 0), ("replica_rejoin", 0, 5, 0)]
    for bad in ("replica_crash:0", "replica_rejoin:1", "slow_tick:1",
                "nonsense:0:1", "replica_crash:x:1", "replica_crash:-1:1",
                "replica_crash:0:1:2"):
        with pytest.raises(ValueError, match="serve fault"):
            resilience.parse_serve_fault(bad)


def test_consume_due_pops_only_due_entries():
    resilience.inject_fault("serve", [("replica_crash", 0, 2, 0),
                                      ("replica_rejoin", 0, 5, 0)])
    assert resilience.consume_due("serve", 1) == []
    assert resilience.consume_due("serve", 2) == [("replica_crash", 0, 2, 0)]
    assert resilience.fault("serve") == [("replica_rejoin", 0, 5, 0)]
    assert resilience.consume_due("serve", 9) == [("replica_rejoin", 0, 5, 0)]


# ------------------------------------------------------ recovery records
def test_recovery_record_resumes_token_identically():
    """THE migration primitive: a request cut mid-decode and re-admitted
    from its RecoveryRecord on a FRESH engine continues the exact same
    stream — the record is prompt + committed + seed and the pinned
    per-request keys do the rest."""
    reqs = _reqs()
    base = _factory()().run(_clone(reqs))
    for cut in (1, 2, 4):
        a = _factory()()
        for r in _clone(reqs):
            a.submit(r)
        done = {}
        for _ in range(cut):
            for c in a.step():
                done[c.req_id] = c
        recs = a.export_records()
        for rec in recs:
            assert rec.tokens == reqs[rec.req_id].tokens  # original prompt
            assert rec.budget == 10
        b = _factory()()
        for rec in recs:
            b.submit(rec.to_request())
        ticks = 0
        while b.has_work():
            for c in b.step():
                done[c.req_id] = c
            ticks += 1
            assert ticks < 200
        for r in reqs:
            assert done[r.req_id].tokens == base[r.req_id].tokens, \
                (cut, r.req_id)
            assert done[r.req_id].reason == base[r.req_id].reason
        assert b.stats["resumed_requests"] > 0


def test_migration_at_page_horizon_matches_overflow():
    """Edge regression: a request crash-migrated when its history sits at
    (or past) the page-table horizon must reproduce the uninterrupted
    run's overflow eviction — same tokens AND same 'overflow' reason (the
    naive admit rule would have 'rejected' it, silently changing the
    status and, one tick earlier, dropping the final token)."""
    def eng():
        return ServingEngine(_MODEL, ServeConfig(max_seqs=2, block_size=4,
                                                 max_blocks_per_seq=2))

    toks = list(map(int, np.random.default_rng(1).integers(
        1, _CFG.vocab_size, 5)))
    base = eng().run([Request("big", list(toks), 64, 0)])["big"]
    assert base.reason == "overflow"
    for cut in range(1, 6):
        a = eng()
        a.submit(Request("big", list(toks), 64, 0))
        done = {}
        for _ in range(cut):
            for c in a.step():
                done[c.req_id] = c
        if "big" not in done:
            b = eng()
            for rec in a.export_records():
                b.submit(rec.to_request())
            while b.has_work():
                for c in b.step():
                    done[c.req_id] = c
        assert done["big"].tokens == base.tokens, cut
        assert done["big"].reason == "overflow", cut


# --------------------------------------------------- migration identity
@pytest.mark.parametrize("sampling", ["greedy", "stochastic"])
@pytest.mark.parametrize("prefix_cache", [False, True])
def test_crash_migration_identity(sampling, prefix_cache):
    """THE acceptance pin: a request crash-migrated at any tick yields
    the token-identical output stream of the never-migrated run — greedy
    and sampled, prefix_cache on and off (with the cache, the survivor
    re-prefills only the uncovered suffix; the outputs cannot tell)."""
    samp = (dict(temperature=0.0) if sampling == "greedy"
            else dict(temperature=0.9, top_k=40))
    eng_kw = dict(prefix_cache=prefix_cache, **samp)
    reqs = _reqs()
    base = _factory(**eng_kw)().run(_clone(reqs), dict(ARRIVALS))
    for crash_tick in (2, 5):
        fleet, done = _fleet_run(f"replica_crash:0:{crash_tick}", reqs,
                                 dict(ARRIVALS), eng_kw=eng_kw)
        for r in reqs:
            assert done[r.req_id].tokens == base[r.req_id].tokens, \
                (sampling, prefix_cache, crash_tick, r.req_id)
            assert done[r.req_id].reason == base[r.req_id].reason
        assert fleet.stats["replica_crashes"] == 1
        assert fleet.lifecycle()[0] == "departed"


def test_crash_migration_identity_speculative():
    """Migration × speculation: the ngram drafter's history re-syncs from
    the committed tokens on the survivor and the verify stream is the
    same pinned stream — outputs identical to the plain engine."""
    reqs = _reqs()
    base = _factory()().run(_clone(reqs), dict(ARRIVALS))
    fleet, done = _fleet_run("replica_crash:0:3", reqs, dict(ARRIVALS),
                             eng_kw=dict(speculate="ngram:4"))
    for r in reqs:
        assert done[r.req_id].tokens == base[r.req_id].tokens, r.req_id
    assert fleet.stats["migrations"] > 0


def test_crash_mid_decode_loses_zero_accepted_tokens():
    """Zero-loss accounting, stated directly: every token the dead
    replica had committed by the crash tick appears in the final output
    (identity implies it, but the ledger must SAY so: the re-prefilled
    committed history is at least as long as what was accepted)."""
    reqs = _reqs()
    fleet, done = _fleet_run("replica_crash:0:4", reqs, dict(ARRIVALS))
    base = _factory()().run(_clone(reqs), dict(ARRIVALS))
    assert fleet.stats["migrations"] > 0
    lost = sum(max(len(base[r.req_id].tokens) - len(done[r.req_id].tokens),
                   0) for r in reqs)
    assert lost == 0
    # the survivor really did resume mid-stream (not just restart):
    rep1 = fleet.replicas[1]
    assert rep1.engine is not None
    assert rep1.engine.stats["resumed_tokens"] > 0


def test_migrated_sharer_does_not_free_survivor_shared_pages():
    """Under --prefix_cache, a migrated request shares the survivor's
    cached pages like any other sharer; after the workload drains, the
    survivor's pool accounting must be exact — every live ref belongs to
    the cache, pages conserved (the engine-level twin of the mid-fuzz
    crash op in tests/test_serve.py)."""
    reqs = _reqs()
    fleet, done = _fleet_run("replica_crash:0:3", reqs, dict(ARRIVALS),
                             eng_kw=dict(prefix_cache=True, num_blocks=64))
    surv = fleet.replicas[1].engine
    assert surv is not None and all(s is None for s in surv.slots)
    bt = surv.tables
    assert bt.physical_pages + bt.free_blocks == bt.num_blocks
    assert int(bt.refs.sum()) == bt.physical_pages


# ------------------------------------------------------------ fault matrix
def test_drain_stops_admission_and_finishes_residents():
    reqs = _reqs()
    resilience.inject_fault("serve",
                            resilience.parse_serve_specs("replica_drain:0:3"))
    fleet = ServingFleet(_factory(), replicas=2)
    todo = _clone(reqs)
    done = {}
    arrivals = dict(ARRIVALS)
    seen_draining = probed = False
    while todo or fleet.has_work():
        while todo and arrivals.get(todo[0].req_id, 0) <= fleet.tick_no:
            fleet.submit(todo.pop(0))
        for c in fleet.step():
            done[c.req_id] = c
        if fleet.lifecycle()[0] == "draining" and not probed:
            seen_draining = probed = True
            fleet.submit(Request("probe", [1, 2, 3], 2, 0))
        if probed:
            # a draining replica admits NOTHING new
            assert "probe" not in fleet.replicas[0].assigned
    assert seen_draining
    assert fleet.lifecycle()[0] == "departed"
    assert "probe" in done  # served by the OTHER replica
    base = _factory()().run(_clone(reqs), dict(ARRIVALS))
    for r in reqs:
        assert done[r.req_id].tokens == base[r.req_id].tokens
    # the drained replica's residents finished in place: nothing failed,
    # nothing timed out, and migrations only ever moved PENDING requests
    assert fleet.stats["failed"] == 0 and fleet.stats["timeouts"] == 0


def test_slow_replica_detected_and_routed_around():
    reqs = _reqs(n=8, max_new=8)
    arrivals = {i: i for i in range(len(reqs))}
    fleet, done = _fleet_run("slow_tick:0:40", reqs, arrivals,
                             slow_min_ticks=3)
    assert fleet.stats["slow_detected"] >= 1
    r0, r1 = fleet.replicas
    assert r0.admissions < r1.admissions  # new work routed around
    base = _factory()().run(_clone(reqs))
    for r in reqs:  # outputs unaffected — slowness changes placement only
        assert done[r.req_id].tokens == base[r.req_id].tokens


def test_rejoin_serves_from_fresh_pool():
    reqs = _reqs(n=8, max_new=8)
    arrivals = {i: i for i in range(len(reqs))}
    resilience.inject_fault("serve", resilience.parse_serve_specs(
        "replica_crash:0:2,replica_rejoin:0:4"))
    fleet = ServingFleet(_factory(), replicas=2, rejoin_probe_ticks=2)
    todo = _clone(reqs)
    done = {}
    probation_admissions = None
    while todo or fleet.has_work():
        while todo and arrivals.get(todo[0].req_id, 0) <= fleet.tick_no:
            fleet.submit(todo.pop(0))
        for c in fleet.step():
            done[c.req_id] = c
        if fleet.lifecycle()[0] == "rejoining":
            # probation gates ROUTING: the healthy peer is admitting, so
            # the unprobed fresh engine gets no new work yet
            probation_admissions = fleet.replicas[0].engine.stats[
                "prefill_dispatches"]
            assert probation_admissions == 0
    assert probation_admissions is not None  # probation was observed
    assert fleet.stats["replica_rejoins"] == 1
    rep0 = fleet.replicas[0]
    assert rep0.engine is not None
    # after probation the fresh engine's stats count post-rejoin work
    assert rep0.engine.stats["prefill_dispatches"] > 0
    assert fleet.lifecycle() == ["healthy", "healthy"]
    base = _factory()().run(_clone(reqs))
    for r in reqs:
        assert done[r.req_id].tokens == base[r.req_id].tokens


def test_retry_budget_exhaustion_fails_loudly():
    """A request whose every home crashes exhausts its retry budget and
    completes as ``failed`` with its partial output attached — never
    silent loss, never an infinite requeue loop."""
    reqs = _reqs()
    base = _factory()().run(_clone(reqs))
    fleet, done = _fleet_run(
        "replica_crash:0:2,replica_rejoin:0:4,replica_crash:1:3,"
        "replica_crash:0:7", reqs, max_retries=0)
    assert fleet.stats["failed"] > 0
    failed = [c for c in done.values() if c.reason == "failed"]
    assert failed
    for c in failed:  # partial output = a prefix of the true stream
        assert c.tokens == base[c.req_id].tokens[:len(c.tokens)]
    # every request completed with SOME honest status
    assert set(done) == {r.req_id for r in reqs}


def test_fleet_refuses_unroutable_queue():
    """All replicas dead, no scheduled rejoin: the fleet refuses loudly
    instead of spinning forever."""
    reqs = _reqs(n=4)
    resilience.inject_fault("serve", resilience.parse_serve_specs(
        "replica_crash:0:1,replica_crash:1:2"))
    fleet = ServingFleet(_factory(), replicas=2, max_retries=5)
    with pytest.raises(RuntimeError, match="no admitting replica"):
        fleet.run(_clone(reqs))


def test_prefix_group_affinity_routing():
    """Requests of one prefix_group land on ONE replica (its prefix
    cache accumulates their shared pages); untagged requests still
    balance by load."""
    fleet = ServingFleet(_factory(prefix_cache=True), replicas=2)
    rng = np.random.default_rng(7)
    sys_p = list(map(int, rng.integers(1, _CFG.vocab_size, 9)))
    fleet.submit(Request("u0", [1, 2, 3], 6, 0))
    fleet.submit(Request("u1", [4, 5], 6, 0))
    fleet.step()
    fleet.submit(Request("g0", list(sys_p), 6, 0, prefix_group="sys"))
    fleet.step()
    home = fleet._home["sys"]
    assert "g0" in fleet.replicas[home].assigned
    for i in (1, 2):
        fleet.submit(Request(f"g{i}", list(sys_p), 6, i,
                             prefix_group="sys"))
        fleet.step()
        assert f"g{i}" in fleet.replicas[home].assigned
    while fleet.has_work():
        fleet.step()
    # affinity did what it exists for: the home replica's cache served
    # the group's shared prefix from one physical copy
    assert fleet.replicas[home].engine.stats["prefix_hits"] >= 2


# ---------------------------------------------------------------- deadlines
def test_pending_request_past_deadline_times_out_without_prefill():
    eng = _factory()()
    eng.submit(Request("d", [1, 2, 3], 8, 0, deadline_s=1e-6))
    time.sleep(0.01)
    done = {c.req_id: c for c in eng.step()}
    assert done["d"].reason == "timeout" and done["d"].tokens == []
    assert eng.stats["prefill_dispatches"] == 0  # expired before admit
    assert eng.stats["timeouts"] == 1


def test_deadline_times_out_mid_decode_under_slow_tick(tmp_path):
    """The satellite pin: a request with a wall-clock deadline on a
    slow-ticking replica is evicted MID-decode with the honest timeout
    status and its partial output — journaled like any other evict."""
    from distributed_lion_tpu.train import journal as journal_mod

    resilience.inject_fault("serve",
                            resilience.parse_serve_specs("slow_tick:0:60"))
    jrnl = journal_mod.Journal(str(tmp_path))
    journal_mod.install(jrnl)
    try:
        fleet = ServingFleet(_factory(), replicas=1)
        done = fleet.run([Request("slow", [1, 2, 3, 4], 64, 0,
                                  deadline_s=0.3)])
    finally:
        journal_mod.uninstall(jrnl)
        jrnl.close()
    c = done["slow"]
    assert c.reason == "timeout"
    assert 0 < len(c.tokens) < 64  # started decoding, then cut off
    evicts = [r for r in jrnl.tail() if r.get("name") == "serve/evict"]
    assert any(r.get("reason") == "timeout" for r in evicts)
    # the fleet puts the RESIDENT deadline miss on the replica timeline
    # too — an incident report must not omit it
    touts = [r for r in jrnl.tail() if r.get("name") == "request_timeout"]
    assert touts and touts[0]["req_id"] == "slow" \
        and "replica" in touts[0] and touts[0]["committed"] == len(c.tokens)


def test_api_deadline_validation_and_echo(tmp_path):
    from distributed_lion_tpu.serve import api

    inp = tmp_path / "requests.jsonl"
    inp.write_text(
        '{"id": "a", "tokens": [1, 2, 3], "max_new_tokens": 2, '
        '"deadline_s": 30.0}\n'
        '{"id": "b", "tokens": [4, 5], "max_new_tokens": 2}\n')
    out = tmp_path / "responses.jsonl"
    records = api.serve_request_file(_factory()(), str(inp), str(out))
    assert records[0]["deadline_s"] == 30.0
    assert "deadline_s" not in records[1]
    for bad in ('{"id": "x", "tokens": [1], "deadline_s": 0}\n',
                '{"id": "x", "tokens": [1], "deadline_s": -1}\n',
                '{"id": "x", "tokens": [1], "deadline_s": true}\n',
                '{"id": "x", "tokens": [1], "deadline_s": "fast"}\n'):
        p = tmp_path / "bad.jsonl"
        p.write_text(bad)
        with pytest.raises(ValueError, match="deadline_s"):
            api.load_request_file(str(p))


# ------------------------------------------------- journal + run_analyze
def test_journal_events_and_replica_timeline(tmp_path):
    from distributed_lion_tpu.train import journal as journal_mod

    jrnl = journal_mod.Journal(str(tmp_path))
    journal_mod.install(jrnl)
    try:
        reqs = _reqs(n=8, max_new=8)
        _fleet_run("replica_crash:0:2,replica_rejoin:0:5", reqs,
                   {i: i for i in range(len(reqs))})
    finally:
        journal_mod.uninstall(jrnl)
        jrnl.close()
    events = [r for r in jrnl.tail() if r["kind"] == "event"]
    names = {r["name"] for r in events}
    assert {"replica_left", "replica_rejoined", "request_migrated"} <= names
    left = next(r for r in events if r["name"] == "replica_left")
    assert left["cause"] == "injected_crash" and "residents" in left \
        and left["alive"] == 1 and left["world"] == 2
    mig = next(r for r in events if r["name"] == "request_migrated")
    for k in ("req_id", "from_replica", "to_replica", "committed",
              "attempt", "tick", "latency_ticks"):
        assert k in mig, k
    # the journal file passes the strict schema...
    spec = importlib.util.spec_from_file_location(
        "vm_rp", os.path.join(REPO, "scripts", "validate_metrics.py"))
    vm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vm)
    assert vm.validate_journal_file(
        str(tmp_path / "journal_rank0.jsonl")) == []
    # ...and run_analyze renders the replica timeline beside membership
    spec = importlib.util.spec_from_file_location(
        "ra_rp", os.path.join(REPO, "distributed_lion_tpu", "cli",
                              "run_analyze.py"))
    ra = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ra)
    report = ra.analyze_dir(str(tmp_path))
    rows = report["replicas"]
    assert [r for r in rows if r["event"] == "replica_left"]
    assert [r for r in rows if r["event"] == "request_migrated"]
    rendered = ra.render(report)
    assert "replica timeline:" in rendered
    assert "replica 0: replica_left" in rendered


# ---------------------------------------------------------------- the CLI
def test_run_serve_cli_fleet_smoke(tmp_path):
    from distributed_lion_tpu.cli.run_serve import main

    reqs = tmp_path / "requests.jsonl"
    reqs.write_text(
        '{"id": "r1", "prompt": "ab", "max_new_tokens": 3, '
        '"deadline_s": 60.0}\n'
        '{"id": "r2", "prompt": "cd", "max_new_tokens": 3, '
        '"arrival_tick": 2}\n')
    out = tmp_path / "responses.jsonl"
    records = main(["--model_family", "gpt2", "--model_name", "tiny",
                    "--requests", str(reqs), "--out", str(out),
                    "--temperature", "0", "--max_seqs", "2",
                    "--block_size", "4", "--replicas", "2",
                    "--inject_serve", "replica_crash:0:1"])
    assert [r["id"] for r in records] == ["r1", "r2"]
    assert all(r["n_generated"] == 3 for r in records)
    assert records[0]["deadline_s"] == 60.0
    # identical to the single-engine run of the same file
    solo = main(["--model_family", "gpt2", "--model_name", "tiny",
                 "--requests", str(reqs), "--out", str(out),
                 "--temperature", "0", "--max_seqs", "2",
                 "--block_size", "4"])
    assert [r["tokens"] for r in records] == [r["tokens"] for r in solo]
    with pytest.raises(ValueError, match="replicas"):
        main(["--model_family", "gpt2", "--model_name", "tiny",
              "--requests", str(reqs), "--inject_serve",
              "replica_crash:0:1"])


# ------------------------------------------------- the evidence artifact
def _load_ce():
    spec = importlib.util.spec_from_file_location(
        "ce_rp", os.path.join(REPO, "scripts", "check_evidence.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    return ce


def test_banked_artifact_passes_serve_resilience_stage():
    """The committed CPU artifact satisfies the ISSUE 14 stage: strict
    schema, all eight markers, >= 3 crash cut points with zero loss and
    real migrations, slow-replica p99 above its clean peer — the gate
    runbook stage 5l re-judges after the on-chip recapture."""
    ce = _load_ce()
    assert ce.serve_resilience_ok()
    with open(ce.SERVE_ARTIFACT) as f:
        doc = json.load(f)
    sec = doc["serve_resilience"]
    assert len(sec["crash_matrix"]) >= 3
    assert all(r["tokens_lost"] == 0 for r in sec["crash_matrix"])
    assert sec["slow"]["p99_ms_slow_replica"] > \
        sec["slow"]["p99_ms_clean_replica"]


def test_serve_resilience_stage_rejects_bad_artifacts(tmp_path):
    ce = _load_ce()
    with open(ce.SERVE_ARTIFACT) as f:
        good = json.load(f)
    p = tmp_path / "serving.json"

    def reject(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        p.write_text(json.dumps(doc))
        assert not ce.serve_resilience_ok(str(p))

    # artifact predates ISSUE 14 entirely (also a schema violation now)
    reject(lambda d: d.pop("serve_resilience"))
    # each identity/behavior marker flips the stage
    for k in ("migrated_identity_greedy", "migrated_identity_sampled",
              "migrated_identity_speculative",
              "migrated_identity_prefix_cache", "zero_token_loss",
              "drain_completes_residents", "slow_detected_and_routed",
              "rejoin_serves"):
        reject(lambda d, k=k: d["serve_resilience"]["markers"].update(
            {k: False}))
    # a crash row that lost tokens / was not identical / never migrated
    reject(lambda d: d["serve_resilience"]["crash_matrix"][0].update(
        tokens_lost=3))
    reject(lambda d: d["serve_resilience"]["crash_matrix"][1].update(
        identical=False))
    reject(lambda d: [r.update(migrated=0)
                      for r in d["serve_resilience"]["crash_matrix"]])
    # too few cut points ('crash at any tick' needs a matrix, not a point)
    reject(lambda d: d["serve_resilience"].update(
        crash_matrix=d["serve_resilience"]["crash_matrix"][:1]))
    # the slow leg's measured story must hold
    reject(lambda d: d["serve_resilience"]["slow"].update(
        p99_ms_slow_replica=0.0))
    # strict schema: a non-int loss count (validate_metrics delegation)
    reject(lambda d: d["serve_resilience"]["crash_matrix"][0].update(
        tokens_lost="none"))
    # the untouched artifact still passes from the tmp copy
    p.write_text(json.dumps(good))
    assert ce.serve_resilience_ok(str(p))
