"""Megatron-style vocab-parallel cross entropy (ops/xent.tp_vocab_xent).

The lm_head's vocab columns shard over the tensor axis; the full [N, V]
logits never exist on one device. Must match the dense log_softmax + gather
exactly — values, gradients, argmax tie rule — and the for_llama --tp_vocab
path must reproduce the replicated-head TP trajectory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_lion_tpu.ops.xent import tp_vocab_xent

TP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:TP]), ("tensor",))


def _dense(hidden, head, labels):
    logits = (hidden @ head).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[..., 0]
    return nll, logits.argmax(-1) == labels


def _sharded(hidden, head, labels):
    def body(h, hd, lab):
        return tp_vocab_xent(h, hd, lab, "tensor")

    f = shard_map(body, mesh=_mesh(),
                  in_specs=(P(), P(None, "tensor"), P()),
                  out_specs=(P(), P()), check_vma=False)
    return f(hidden, head, labels)


def _data(n=37, d=16, v=64, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    hidden = jax.random.normal(k1, (n, d), jnp.float32)
    head = jax.random.normal(k2, (d, v), jnp.float32)
    labels = jnp.asarray(
        np.random.default_rng(seed).integers(0, v, n), jnp.int32)
    return hidden, head, labels


def test_matches_dense_values():
    hidden, head, labels = _data()
    nll_d, cor_d = _dense(hidden, head, labels)
    nll_s, cor_s = _sharded(hidden, head, labels)
    np.testing.assert_allclose(np.asarray(nll_s), np.asarray(nll_d),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(cor_s), np.asarray(cor_d))


def test_matches_dense_gradients_up_to_leaf_scale():
    """Gradients under the framework's TP convention: jax.grad runs INSIDE
    the shard_map body (as in the train step), where psum transposes and
    the copy_to_tp_region boundary each contribute a factor of W — so every
    leaf's gradient equals the dense gradient times a CONSTANT positive
    per-leaf power of W. Sign-based vote-Lion is exactly invariant to a
    constant per-leaf scale (which is why TP is Lion-only in train/loop.py);
    here we pin that the direction matches dense exactly and the scale is
    one uniform constant per leaf."""
    hidden, head, labels = _data(seed=1)

    def dense_loss(h, hd):
        return _dense(h, hd, labels)[0].mean()

    def body(h, hd, lab):
        def loss(h, hd):
            return tp_vocab_xent(h, hd, lab, "tensor")[0].mean()

        gh, ghd = jax.grad(loss, argnums=(0, 1))(h, hd)
        return gh, ghd  # gh complete+replicated; ghd this rank's shard

    f = shard_map(body, mesh=_mesh(),
                  in_specs=(P(), P(None, "tensor"), P()),
                  out_specs=(P(), P(None, "tensor")), check_vma=False)
    gh_s, ghd_s = f(hidden, head, labels)
    gh_d, ghd_d = jax.grad(dense_loss, argnums=(0, 1))(hidden, head)
    for a, b in ((gh_s, gh_d), (ghd_s, ghd_d)):
        a, b = np.asarray(a), np.asarray(b)
        big = np.abs(b) > 1e-4 * np.abs(b).max()
        ratios = a[big] / b[big]
        scale = np.median(ratios)
        assert scale > 0
        # a single constant scale for the whole leaf, and it is a power of W
        np.testing.assert_allclose(ratios, scale, rtol=1e-4)
        assert abs(np.log(scale) / np.log(TP) - round(np.log(scale) / np.log(TP))) < 1e-4
        np.testing.assert_allclose(a[big] / scale, b[big], rtol=1e-4, atol=1e-5)


def test_argmax_tie_rule():
    """Dense argmax picks the lowest index on exact ties — including ties
    that span different ranks' vocab shards."""
    hidden = jnp.zeros((2, 4), jnp.float32)
    head = jnp.zeros((4, 64), jnp.float32)  # ALL logits equal → argmax = 0
    labels = jnp.asarray([0, 17], jnp.int32)
    _, cor_d = _dense(hidden, head, labels)
    _, cor_s = _sharded(hidden, head, labels)
    np.testing.assert_array_equal(np.asarray(cor_s), np.asarray(cor_d))
    assert bool(cor_s[0]) and not bool(cor_s[1])


def test_for_llama_tp_vocab_matches_replicated_head():
    """dp=4 x tp=2 with --tp_vocab reproduces the replicated-head TP
    trajectory; the lm_head leaf is actually sharded."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.llama import LlamaConfig
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    def run(tp_vocab):
        cfg = TrainConfig(
            lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
            warmup_steps=2, max_steps=8, per_device_train_batch_size=2,
            gradient_accumulation_steps=1, block_size=32, logging_steps=2,
            eval_steps=1000, save_steps=1000, seed=0, tp_vocab=tp_vocab,
        )
        mesh = make_mesh(data=4, tensor=2)
        trainer = Trainer.for_llama(cfg, mesh, LlamaConfig.tiny())
        blocks = synthetic_lm_dataset(512, 32, 256)
        hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(),
                                            seed=1), max_steps=8)
        losses = [h["loss"] for h in hist if "loss" in h]
        head = trainer.params["lm_head"]
        trainer.close()
        return losses, head

    l_vp, head_vp = run(True)
    l_rep, _ = run(False)
    np.testing.assert_allclose(l_vp, l_rep, rtol=2e-2, atol=2e-2)
    # sharded head: each device holds a [d, V/2] slice
    shard_shape = head_vp.addressable_shards[0].data.shape
    assert shard_shape == (head_vp.shape[0], head_vp.shape[1] // 2)


def test_tp_vocab_guards():
    from distributed_lion_tpu.models.llama import LlamaConfig
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    base = dict(lion=True, async_grad=True, max_steps=1)
    with pytest.raises(ValueError, match="tensor_parallel"):
        Trainer.for_llama(TrainConfig(tp_vocab=True, **base),
                          make_mesh(data=8), LlamaConfig.tiny())
    with pytest.raises(NotImplementedError, match="alternative head"):
        Trainer.for_llama(TrainConfig(tp_vocab=True, vocab_chunks=4, **base),
                          make_mesh(data=4, tensor=2), LlamaConfig.tiny())
    with pytest.raises(ValueError, match="divisible"):
        Trainer.for_llama(TrainConfig(tp_vocab=True, **base),
                          make_mesh(data=4, tensor=2),
                          LlamaConfig.tiny(vocab_size=257))
    # stochastic binarization is magnitude-dependent → refused under TP
    with pytest.raises(NotImplementedError, match="stochastic"):
        Trainer.for_llama(TrainConfig(max_grad_norm=1.0, **base),
                          make_mesh(data=4, tensor=2), LlamaConfig.tiny())
