"""Megatron-style vocab-parallel cross entropy (ops/xent.tp_vocab_xent).

The lm_head's vocab columns shard over the tensor axis; the full [N, V]
logits never exist on one device. Must match the dense log_softmax + gather
exactly — values, gradients, argmax tie rule — and the for_llama --tp_vocab
path must reproduce the replicated-head TP trajectory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_lion_tpu.ops.xent import tp_vocab_xent

TP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:TP]), ("tensor",))


def _dense(hidden, head, labels):
    logits = (hidden @ head).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[..., 0]
    return nll, logits.argmax(-1) == labels


def _sharded(hidden, head, labels):
    def body(h, hd, lab):
        return tp_vocab_xent(h, hd, lab, "tensor")

    f = shard_map(body, mesh=_mesh(),
                  in_specs=(P(), P(None, "tensor"), P()),
                  out_specs=(P(), P()), check_vma=False)
    return f(hidden, head, labels)


def _data(n=37, d=16, v=64, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    hidden = jax.random.normal(k1, (n, d), jnp.float32)
    head = jax.random.normal(k2, (d, v), jnp.float32)
    labels = jnp.asarray(
        np.random.default_rng(seed).integers(0, v, n), jnp.int32)
    return hidden, head, labels


def test_matches_dense_values():
    hidden, head, labels = _data()
    nll_d, cor_d = _dense(hidden, head, labels)
    nll_s, cor_s = _sharded(hidden, head, labels)
    np.testing.assert_allclose(np.asarray(nll_s), np.asarray(nll_d),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(cor_s), np.asarray(cor_d))


def test_matches_dense_gradients():
    """Gradients are EXACT: jax.grad runs INSIDE the shard_map body (as in
    the train step), where the Megatron f/g custom-vjp pairing
    (copy_to_tp_region at entry, reduce_from_tp_region inside the loss)
    makes every cotangent count each contribution exactly once — raw psums
    would over-count by W per crossing (tensor_parallel.py docstring)."""
    hidden, head, labels = _data(seed=1)

    def dense_loss(h, hd):
        return _dense(h, hd, labels)[0].mean()

    def body(h, hd, lab):
        def loss(h, hd):
            return tp_vocab_xent(h, hd, lab, "tensor")[0].mean()

        gh, ghd = jax.grad(loss, argnums=(0, 1))(h, hd)
        return gh, ghd  # gh complete+replicated; ghd this rank's shard

    f = shard_map(body, mesh=_mesh(),
                  in_specs=(P(), P(None, "tensor"), P()),
                  out_specs=(P(), P(None, "tensor")), check_vma=False)
    gh_s, ghd_s = f(hidden, head, labels)
    gh_d, ghd_d = jax.grad(dense_loss, argnums=(0, 1))(hidden, head)
    np.testing.assert_allclose(np.asarray(gh_s), np.asarray(gh_d),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ghd_s), np.asarray(ghd_d),
                               rtol=1e-4, atol=1e-5)


def test_argmax_tie_rule():
    """Dense argmax picks the lowest index on exact ties — including ties
    that span different ranks' vocab shards."""
    hidden = jnp.zeros((2, 4), jnp.float32)
    head = jnp.zeros((4, 64), jnp.float32)  # ALL logits equal → argmax = 0
    labels = jnp.asarray([0, 17], jnp.int32)
    _, cor_d = _dense(hidden, head, labels)
    _, cor_s = _sharded(hidden, head, labels)
    np.testing.assert_array_equal(np.asarray(cor_s), np.asarray(cor_d))
    assert bool(cor_s[0]) and not bool(cor_s[1])


def test_for_llama_tp_vocab_matches_replicated_head():
    """dp=4 x tp=2 with --tp_vocab reproduces the replicated-head TP
    trajectory; the lm_head leaf is actually sharded."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.llama import LlamaConfig
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    def run(tp_vocab):
        cfg = TrainConfig(
            lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
            warmup_steps=2, max_steps=8, per_device_train_batch_size=2,
            gradient_accumulation_steps=1, block_size=32, logging_steps=2,
            eval_steps=1000, save_steps=1000, seed=0, tp_vocab=tp_vocab,
        )
        mesh = make_mesh(data=4, tensor=2)
        trainer = Trainer.for_llama(cfg, mesh, LlamaConfig.tiny())
        blocks = synthetic_lm_dataset(512, 32, 256)
        hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(),
                                            seed=1), max_steps=8)
        losses = [h["loss"] for h in hist if "loss" in h]
        head = trainer.params["lm_head"]
        trainer.close()
        return losses, head

    l_vp, head_vp = run(True)
    l_rep, _ = run(False)
    np.testing.assert_allclose(l_vp, l_rep, rtol=2e-2, atol=2e-2)
    # sharded head: each device holds a [d, V/2] slice
    shard_shape = head_vp.addressable_shards[0].data.shape
    assert shard_shape == (head_vp.shape[0], head_vp.shape[1] // 2)


def test_vocab_parallel_embed_matches_dense():
    """Megatron VocabParallelEmbedding == plain table lookup."""
    from distributed_lion_tpu.models.gpt2 import vocab_parallel_embed

    wte = jax.random.normal(jax.random.key(0), (64, 8), jnp.float32)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (3, 10)),
                         jnp.int32)
    dense = wte[tokens]

    def body(w, t):
        return vocab_parallel_embed(w, t, "tensor")

    out = shard_map(body, mesh=_mesh(), in_specs=(P("tensor"), P()),
                    out_specs=P(), check_vma=False)(wte, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=1e-6, atol=1e-6)


def test_for_gpt2_tp_vocab_matches_replicated_head():
    """GPT-2 (tied embedding): dp=4 x tp=2 --tp_vocab reproduces the
    replicated-embedding TP trajectory; wte is actually row-sharded."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    def run(tp_vocab):
        cfg = TrainConfig(
            lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
            warmup_steps=2, max_steps=8, per_device_train_batch_size=2,
            gradient_accumulation_steps=1, block_size=32, logging_steps=2,
            eval_steps=1000, save_steps=1000, seed=0, tp_vocab=tp_vocab,
        )
        mesh = make_mesh(data=4, tensor=2)
        trainer = Trainer.for_gpt2(cfg, mesh, GPT2Config.tiny())
        blocks = synthetic_lm_dataset(512, 32, 256)
        hist = trainer.train(batch_iterator(blocks, trainer.global_train_batch(),
                                            seed=1), max_steps=8)
        losses = [h["loss"] for h in hist if "loss" in h]
        wte = trainer.params["wte"]
        trainer.close()
        return losses, wte

    l_vp, wte_vp = run(True)
    l_rep, _ = run(False)
    np.testing.assert_allclose(l_vp, l_rep, rtol=2e-2, atol=2e-2)
    shard_shape = wte_vp.addressable_shards[0].data.shape
    assert shard_shape == (wte_vp.shape[0] // 2, wte_vp.shape[1])


def test_tp_gradients_exact_vs_pure_dp():
    """The f/g custom-vjp pairing makes FULL-MODEL TP gradients equal the
    pure-dp gradients (per-leaf median ratio 1.0) — with raw psum exits the
    ratios were depth-dependent mixed powers of W with sign flips. One
    vote-Lion step: momentum = (1-β₂)·grad, so momentum ratios ARE grad
    ratios."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    def momenta(mesh):
        cfg = TrainConfig(
            lion=True, async_grad=True, learning_rate=3e-3, weight_decay=0.0,
            warmup_steps=2, max_steps=2, per_device_train_batch_size=2,
            gradient_accumulation_steps=1, block_size=32, logging_steps=10,
            eval_steps=1000, save_steps=1000, seed=0,
        )
        t = Trainer.for_gpt2(cfg, mesh, GPT2Config.tiny())
        blocks = synthetic_lm_dataset(256, 32, 256)
        t.train(batch_iterator(blocks, t.global_train_batch(), seed=1),
                max_steps=1)
        m = jax.tree.map(lambda x: np.asarray(x), t.state.exp_avg)
        t.close()
        return m

    m_dp = momenta(make_mesh(data=2, devices=jax.devices()[:2]))
    m_tp = momenta(make_mesh(data=2, tensor=2, devices=jax.devices()[:4]))
    for a, b in zip(jax.tree.leaves(m_dp), jax.tree.leaves(m_tp)):
        a0, b0 = a[0], b[0]  # worker 0's momentum
        big = np.abs(a0) > 1e-6  # above bf16 noise floor
        if big.sum() < 8:
            continue
        med = float(np.median(b0[big] / a0[big]))
        assert abs(med - 1.0) < 1e-2, med


def test_tp_vocab_guards():
    from distributed_lion_tpu.models.llama import LlamaConfig
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    base = dict(lion=True, async_grad=True, max_steps=1)
    with pytest.raises(ValueError, match="tensor_parallel"):
        Trainer.for_llama(TrainConfig(tp_vocab=True, **base),
                          make_mesh(data=8), LlamaConfig.tiny())
    with pytest.raises(NotImplementedError, match="alternative head"):
        Trainer.for_llama(TrainConfig(tp_vocab=True, vocab_chunks=4, **base),
                          make_mesh(data=4, tensor=2), LlamaConfig.tiny())
    with pytest.raises(ValueError, match="divisible"):
        Trainer.for_llama(TrainConfig(tp_vocab=True, **base),
                          make_mesh(data=4, tensor=2),
                          LlamaConfig.tiny(vocab_size=257))
