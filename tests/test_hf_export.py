"""HF-format export: trained pytrees load back into the torch models.

The inverse of tests/test_hf_import.py and the closing step of every
reference workload (save_model / save merged, run_clm.py:611-622,
sft_llama2.py:183-199): export our params with models/hf_export, load them
with ``from_pretrained`` (local dir, no network), and demand the torch
model's logits match ours — pinning the Conv1D orientation, q|k|v
flattening, RoPE interleaved→half-rotation inverse, and tied-head handling.
"""

import dataclasses

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from distributed_lion_tpu.models.hf_export import gpt2_to_hf, llama_to_hf  # noqa: E402
from distributed_lion_tpu.models.hf_import import (  # noqa: E402
    gpt2_from_hf,
    llama_from_hf,
)


def _tokens(vocab, rng_seed=0, shape=(2, 16)):
    rng = np.random.default_rng(rng_seed)
    return rng.integers(0, vocab, size=shape, dtype=np.int64)


def test_gpt2_export_torch_parity(tmp_path):
    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init

    cfg = GPT2Config.tiny(remat=False, compute_dtype=jnp.float32)
    params = gpt2_init(jax.random.key(0), cfg)
    gpt2_to_hf(params, cfg, str(tmp_path / "export"))

    hf_model = transformers.GPT2LMHeadModel.from_pretrained(
        str(tmp_path / "export")).eval()
    tokens = _tokens(cfg.vocab_size)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = np.asarray(gpt2_apply(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_gpt2_roundtrip_exact(tmp_path):
    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(1), cfg)
    gpt2_to_hf(params, cfg, str(tmp_path / "rt"))
    back, cfg2 = gpt2_from_hf(str(tmp_path / "rt"))
    assert (cfg2.n_layer, cfg2.n_head, cfg2.d_model, cfg2.vocab_size,
            cfg2.n_ctx) == (cfg.n_layer, cfg.n_head, cfg.d_model,
                            cfg.vocab_size, cfg.n_ctx)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_llama_export_torch_parity_untied(tmp_path):
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init

    cfg = LlamaConfig.tiny(remat=False, compute_dtype=jnp.float32)
    params = llama_init(jax.random.key(2), cfg)
    llama_to_hf(params, cfg, str(tmp_path / "export"))

    hf_model = transformers.LlamaForCausalLM.from_pretrained(
        str(tmp_path / "export")).eval()
    tokens = _tokens(cfg.vocab_size, rng_seed=3)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = np.asarray(llama_apply(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_llama_roundtrip_tied_head(tmp_path):
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_init

    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(4), cfg)
    params["lm_head"] = jnp.asarray(np.asarray(params["wte"]).T)  # tie
    llama_to_hf(params, cfg, str(tmp_path / "tied"))
    import json
    hf_cfg = json.loads((tmp_path / "tied" / "config.json").read_text())
    assert hf_cfg["tie_word_embeddings"] is True
    back, cfg2 = llama_from_hf(str(tmp_path / "tied"))
    assert cfg2.n_kv_head == cfg.n_kv_head
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_survives_roundtrip(tmp_path):
    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

    cfg = GPT2Config.tiny(param_dtype=jnp.bfloat16)
    params = gpt2_init(jax.random.key(5), cfg)
    gpt2_to_hf(params, cfg, str(tmp_path / "bf16"))
    back, _ = gpt2_from_hf(str(tmp_path / "bf16"), param_dtype=jnp.bfloat16)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(
            np.asarray(a).view(np.uint16), np.asarray(b).view(np.uint16))


def test_moe_export_refused(tmp_path):
    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

    cfg = GPT2Config.tiny(moe_experts=2)
    params = gpt2_init(jax.random.key(6), cfg)
    with pytest.raises(ValueError, match="MoE"):
        gpt2_to_hf(params, cfg, str(tmp_path / "moe"))


def test_run_clm_hf_export_flag(tmp_path):
    """run_clm --hf_export writes a from_pretrained-loadable directory."""
    from distributed_lion_tpu.cli.run_clm import main

    out = tmp_path / "out"
    exp = tmp_path / "hf"
    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--block_size", "32",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000", "--output_dir", str(out), "--hf_export", str(exp),
        "--param_dtype", "float32",
    ])
    model = transformers.GPT2LMHeadModel.from_pretrained(str(exp))
    assert model.config.n_layer == 2
    card = (exp / "README.md").read_text()
    assert "Distributed Lion" in card and "| wire |" in card


def test_run_sft_merged_hf_output(tmp_path):
    """run_sft --merged_output <dir> lands the merged model in HF format
    (the reference's merge_and_unload → save flow)."""
    from distributed_lion_tpu.cli.run_sft import main

    merged = tmp_path / "merged_hf"
    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--seq_length", "64",
        "--num_train_samples", "32", "--size_valid_set", "4",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000", "--merged_output", str(merged),
    ])
    model = transformers.LlamaForCausalLM.from_pretrained(str(merged))
    assert model.config.num_hidden_layers == 2


def test_run_generate_from_hf_dir(tmp_path, capsys):
    """run_generate consumes an exported HF directory directly (family
    auto-detected), closing the train → export → use cycle."""
    from distributed_lion_tpu.cli.run_generate import main
    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(9), cfg)
    gpt2_to_hf(params, cfg, str(tmp_path / "hf"))
    main([
        "--model_path", str(tmp_path / "hf"), "--model_family", "llama",
        "--prompt", "ab", "--max_new_tokens", "4", "--temperature", "0",
    ])
    outerr = capsys.readouterr()
    assert "detected from checkpoint" in outerr.out  # llama -> gpt2 autocorrect


def test_run_dpo_merged_hf_output(tmp_path):
    """run_dpo --merged_output <dir> lands the merged policy in HF format."""
    from distributed_lion_tpu.cli.run_dpo import main

    merged = tmp_path / "dpo_hf"
    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--max_length", "64",
        "--num_train_samples", "32", "--size_valid_set", "4",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000", "--merged_output", str(merged),
    ])
    model = transformers.LlamaForCausalLM.from_pretrained(str(merged))
    assert model.config.num_hidden_layers == 2


def test_lora_peft_export_parity(tmp_path):
    """Export base + trained-style adapters; load with the REAL peft library
    (PeftModel.from_pretrained over our exported base) and demand its logits
    match our apply_adapters forward — pinning the A/B transposes, the
    q-projection RoPE un-permute, and the alpha/r scaling convention."""
    peft = pytest.importorskip("peft")

    from distributed_lion_tpu.models.hf_export import llama_to_hf, lora_to_peft
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
    from distributed_lion_tpu.models.lora import (
        LoraConfig,
        apply_adapters,
        lora_init,
    )

    cfg = LlamaConfig.tiny(remat=False, compute_dtype=jnp.float32)
    base = llama_init(jax.random.key(10), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=("wq", "wk", "wv", "wo"))
    adapters = lora_init(jax.random.key(11), base, lcfg)
    # B inits to zero (identity adapter); randomize so the delta is live
    adapters = jax.tree.map(
        lambda x: jax.random.normal(jax.random.key(12), x.shape) * 0.1,
        adapters)

    llama_to_hf(base, cfg, str(tmp_path / "base"))
    lora_to_peft(adapters, cfg, lcfg, str(tmp_path / "adapter"))

    hf_base = transformers.LlamaForCausalLM.from_pretrained(
        str(tmp_path / "base")).eval()
    pm = peft.PeftModel.from_pretrained(hf_base, str(tmp_path / "adapter")).eval()

    tokens = _tokens(cfg.vocab_size, rng_seed=13)
    with torch.no_grad():
        ref = pm(torch.from_numpy(tokens)).logits.numpy()

    effective = apply_adapters(base, adapters, lcfg)
    got = np.asarray(llama_apply(effective, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)


def test_peft_adapter_roundtrip_exact(tmp_path):
    """lora_to_peft → peft_to_lora is the identity on adapters (A/B values,
    rope permutes cancel) and recovers r/alpha/targets."""
    from distributed_lion_tpu.models.hf_export import lora_to_peft
    from distributed_lion_tpu.models.hf_import import peft_to_lora
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_init
    from distributed_lion_tpu.models.lora import LoraConfig, lora_init

    cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(20), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=("wq", "wk", "wv", "wo"))
    adapters = lora_init(jax.random.key(21), base, lcfg)
    adapters = jax.tree.map(
        lambda x: jax.random.normal(jax.random.key(22), x.shape) * 0.1,
        adapters)
    lora_to_peft(adapters, cfg, lcfg, str(tmp_path / "pf"))
    back, lcfg2 = peft_to_lora(str(tmp_path / "pf"), cfg)
    assert (lcfg2.r, lcfg2.alpha) == (4, 8)
    assert set(back) == set(adapters)
    for k in adapters:
        for ab in ("A", "B"):
            np.testing.assert_allclose(np.asarray(back[k][ab]),
                                       np.asarray(adapters[k][ab]),
                                       rtol=1e-6, atol=1e-7)


def test_import_real_peft_checkpoint(tmp_path):
    """An adapter SAVED BY the torch peft library imports into our pytree
    with forward parity — continuing HF-trained LoRA on TPU."""
    peft = pytest.importorskip("peft")

    from distributed_lion_tpu.models.hf_export import llama_to_hf
    from distributed_lion_tpu.models.hf_import import peft_to_lora
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
    from distributed_lion_tpu.models.lora import apply_adapters

    cfg = LlamaConfig.tiny(remat=False, compute_dtype=jnp.float32)
    base = llama_init(jax.random.key(30), cfg)
    llama_to_hf(base, cfg, str(tmp_path / "base"))
    hf_base = transformers.LlamaForCausalLM.from_pretrained(
        str(tmp_path / "base"))
    pc = peft.LoraConfig(r=4, lora_alpha=8,
                         target_modules=["q_proj", "k_proj", "v_proj"],
                         task_type="CAUSAL_LM", lora_dropout=0.0)
    pm = peft.get_peft_model(hf_base, pc)
    # randomize lora_B (init is zero → identity) so the delta is live
    with torch.no_grad():
        for n, p in pm.named_parameters():
            if "lora_B" in n:
                p.copy_(torch.randn_like(p) * 0.1)
    pm.save_pretrained(str(tmp_path / "adapter"))

    adapters, lcfg = peft_to_lora(str(tmp_path / "adapter"), cfg)
    tokens = _tokens(cfg.vocab_size, rng_seed=31)
    with torch.no_grad():
        ref = pm(torch.from_numpy(tokens)).logits.numpy()
    effective = apply_adapters(base, adapters, lcfg)
    got = np.asarray(llama_apply(effective, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)


def test_sft_merged_model_exports(tmp_path):
    """The reference's closing flow: LoRA-SFT → merge → save (sft_llama2.py:
    183-199) lands in an HF-loadable directory."""
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_init
    from distributed_lion_tpu.models.lora import (
        LoraConfig,
        lora_init,
        merge_lora,
    )

    cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(7), cfg)
    lcfg = LoraConfig(r=4, alpha=8)
    adapters = lora_init(jax.random.key(8), base, lcfg)
    merged = merge_lora(base, adapters, lcfg)
    llama_to_hf(merged, cfg, str(tmp_path / "merged"))
    back, _ = llama_from_hf(str(tmp_path / "merged"))
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(back)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_run_sft_adapter_chain(tmp_path):
    """run_sft --adapter_output then run_sft --adapter_path: the PEFT
    checkpoint round-trips through the CLI surface."""
    from distributed_lion_tpu.cli.run_sft import main

    common = [
        "--model_name", "tiny", "--dataset", "synthetic", "--lion",
        "--async_grad", "--max_steps", "2", "--per_device_train_batch_size",
        "1", "--gradient_accumulation_steps", "1", "--seq_length", "64",
        "--num_train_samples", "32", "--size_valid_set", "0",
        "--logging_steps", "10", "--eval_steps", "1000", "--save_steps",
        "1000",
    ]
    main(common + ["--adapter_output", str(tmp_path / "a1"), "--lora_r", "4"])
    main(common + ["--adapter_path", str(tmp_path / "a1"),
                   "--adapter_output", str(tmp_path / "a2")])
    import json
    cfg2 = json.loads((tmp_path / "a2" / "adapter_config.json").read_text())
    assert cfg2["r"] == 4  # checkpoint's r carried through, not the CLI default


def test_vote_trained_roundtrip_decode_bit_identical(tmp_path):
    """ISSUE 9 satellite (ROADMAP item 4's explicit ask): train a tiny
    model WITH the vote wire, export via models/hf_export, re-import via
    models/hf_import, and pin greedy decode bit-identical native vs
    round-tripped — and dense-KV vs paged-KV decode bit-identical at
    temperature 0 on the round-tripped weights. The full
    train → export → import → serve cycle, pinned at the bit level."""
    from functools import partial

    import jax.numpy as jnp

    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.generate import generate
    from distributed_lion_tpu.models.gpt2 import (
        GPT2Config,
        gpt2_decode,
        gpt2_init_cache,
    )
    from distributed_lion_tpu.parallel import make_mesh
    from distributed_lion_tpu.serve.engine import (
        Request,
        ServeConfig,
        ServeModel,
        ServingEngine,
    )
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    cfg = TrainConfig(
        lion=True, async_grad=True,  # the vote wire (8 workers)
        learning_rate=3e-3, weight_decay=0.0, warmup_steps=2, max_steps=8,
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        per_device_eval_batch_size=1, block_size=32, logging_steps=100,
        eval_steps=1000, save_steps=1000, eval_iters=1, seed=0,
    )
    mesh = make_mesh(data=8)
    model_cfg = GPT2Config.tiny()
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(256, cfg.block_size, model_cfg.vocab_size)
    trainer.train(batch_iterator(blocks, trainer.global_train_batch(), seed=0),
                  max_steps=8)
    params = trainer.params
    trainer.close()

    gpt2_to_hf(params, model_cfg, str(tmp_path / "export"))
    back, cfg2 = gpt2_from_hf(str(tmp_path / "export"))

    dec = partial(
        lambda c, p, t, k, pos, off=None: gpt2_decode(p, t, c, k, pos, off),
        model_cfg)
    ic = partial(gpt2_init_cache, model_cfg)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(1, model_cfg.vocab_size, (2, 6)),
        jnp.int32)
    native = np.asarray(generate(dec, ic, params, prompt, 8, max_len=32))
    rt = np.asarray(generate(dec, ic, back, prompt, 8, max_len=32))
    np.testing.assert_array_equal(native, rt)

    # dense-KV vs paged-KV at temperature 0 on the round-tripped weights
    # (matched attended length: 8 pages x 4 = the dense max_len above)
    engine = ServingEngine(
        ServeModel.for_gpt2(back, cfg2),
        ServeConfig(max_seqs=2, block_size=4, max_blocks_per_seq=8))
    done = engine.run([
        Request(req_id=i, tokens=[int(t) for t in row], max_new_tokens=8,
                seed=0)
        for i, row in enumerate(np.asarray(prompt))])
    for i in range(prompt.shape[0]):
        assert list(native[i]) == done[i].tokens, i
