"""C++ native data loader: build, mmap shards, prefetch batch semantics.

Covers the framework-native replacement for the reference's HF-datasets
input pipeline (run_clm.py:316-381): same [global_batch, block] int32
contract as the Python batch_iterator, deterministic shuffle, drop-last.
"""

import pathlib

import numpy as np
import pytest

from distributed_lion_tpu.data.native_loader import NativeTokenLoader, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain available"
)


def _write_bin(tmp_path, name, tokens, dtype=np.uint16):
    p = tmp_path / name
    np.asarray(tokens, dtype).tofile(p)
    return p


def test_blocks_and_random_access(tmp_path):
    toks = np.arange(35, dtype=np.uint16)  # block 8 -> 4 blocks, 3-token tail dropped
    p = _write_bin(tmp_path, "a.bin", toks)
    dl = NativeTokenLoader([p], block_size=8)
    assert len(dl) == 4
    np.testing.assert_array_equal(dl.read_block(0), np.arange(8))
    np.testing.assert_array_equal(dl.read_block(3), np.arange(24, 32))
    with pytest.raises(IndexError):
        dl.read_block(4)
    dl.close()


def test_multi_shard_per_shard_tail_drop(tmp_path):
    # shard 1: 10 tokens (1 block of 8 + tail 2), shard 2: 17 tokens (2 blocks + 1)
    p1 = _write_bin(tmp_path, "s1.bin", np.arange(10))
    p2 = _write_bin(tmp_path, "s2.bin", np.arange(100, 117))
    dl = NativeTokenLoader([p1, p2], block_size=8)
    assert len(dl) == 3
    np.testing.assert_array_equal(dl.read_block(0), np.arange(8))
    # shard boundary: block 1 starts at shard 2's first token, tail of s1 dropped
    np.testing.assert_array_equal(dl.read_block(1), np.arange(100, 108))
    np.testing.assert_array_equal(dl.read_block(2), np.arange(108, 116))
    dl.close()


def test_uint32_dtype(tmp_path):
    toks = np.array([0, 70_000, 123_456, 7], np.uint32)
    p = _write_bin(tmp_path, "u32.bin", toks, np.uint32)
    dl = NativeTokenLoader([p], block_size=2, dtype=np.uint32)
    assert dl.read_block(0)[1] == 70_000
    dl.close()


def test_epoch_covers_each_block_once(tmp_path):
    n_blocks, block, batch = 12, 4, 3
    p = _write_bin(tmp_path, "e.bin", np.arange(n_blocks * block) % 251)
    dl = NativeTokenLoader([p], block_size=block)
    got = list(dl.batches(batch, seed=7, epochs=1))
    assert len(got) == n_blocks // batch
    for b in got:
        assert b.shape == (batch, block) and b.dtype == np.int32
    # every block appears exactly once across the epoch
    served = np.concatenate(got).reshape(-1, block)
    ref = dl.read_blocks(0, n_blocks)
    assert {tuple(r) for r in served} == {tuple(r) for r in ref}
    dl.close()


def test_shuffle_deterministic_and_seed_sensitive(tmp_path):
    p = _write_bin(tmp_path, "d.bin", np.arange(160) % 251)
    a = np.stack(list(NativeTokenLoader([p], 8).batches(2, seed=3, epochs=1)))
    b = np.stack(list(NativeTokenLoader([p], 8).batches(2, seed=3, epochs=1)))
    c = np.stack(list(NativeTokenLoader([p], 8).batches(2, seed=4, epochs=1)))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_drop_last(tmp_path):
    p = _write_bin(tmp_path, "dl.bin", np.arange(10 * 4) % 251)  # 10 blocks
    dl = NativeTokenLoader([p], 4)
    got = list(dl.batches(3, epochs=1))  # 10 // 3 = 3 batches, 1 block dropped
    assert len(got) == 3
    dl.close()


def test_infinite_epochs_keeps_yielding(tmp_path):
    p = _write_bin(tmp_path, "inf.bin", np.arange(8 * 4) % 251)
    dl = NativeTokenLoader([p], 4)
    it = dl.batches(8, epochs=None)  # one batch per epoch
    for _ in range(5):  # crosses several epoch boundaries
        assert next(it).shape == (8, 4)
    dl.close()


def test_block_range_holdout(tmp_path):
    n_blocks, block = 10, 4
    p = _write_bin(tmp_path, "r.bin", np.arange(n_blocks * block) % 251)
    dl = NativeTokenLoader([p], block)
    # train on blocks [2, 10): validation blocks 0-1 never served
    got = np.concatenate(list(dl.batches(2, seed=1, epochs=2, block_range=(2, 10))))
    held_out = {tuple(dl.read_block(i)) for i in range(2)}
    assert held_out.isdisjoint({tuple(r) for r in got})
    assert len(got) == 2 * 8  # 4 batches x 2 blocks per epoch, 2 epochs
    dl.close()


def test_errors(tmp_path):
    with pytest.raises(OSError):
        NativeTokenLoader([tmp_path / "missing.bin"], 8)
    p = _write_bin(tmp_path, "tiny.bin", np.arange(4))
    with pytest.raises(OSError):  # zero full blocks
        NativeTokenLoader([p], 8)
    dl = NativeTokenLoader([p], 2)
    with pytest.raises(RuntimeError):  # batch > num blocks
        dl.batches(99)
    dl.close()


# --------------------------------------------------- shard robustness (ISSUE 5)
def test_corrupt_shard_skipped_loudly(tmp_path, capsys):
    """A misaligned (torn-write) shard is SKIPPED with a warning and a
    counter instead of killing the run; the survivors still serve blocks."""
    good = _write_bin(tmp_path, "good.bin", np.arange(16))
    bad = tmp_path / "torn.bin"
    bad.write_bytes(b"\x01\x02\x03")  # 3 bytes: not a uint16 multiple
    dl = NativeTokenLoader([bad, good], block_size=8)
    assert len(dl) == 2
    np.testing.assert_array_equal(dl.read_block(0), np.arange(8))
    assert dl.health_metrics() == {"skipped_shards": 1,
                                   "shard_read_retries": 0}
    assert "skipping corrupt" in capsys.readouterr().out
    dl.close()


def test_all_shards_corrupt_raises(tmp_path):
    from distributed_lion_tpu.data.native_loader import CorruptShardError

    bad = tmp_path / "torn.bin"
    bad.write_bytes(b"\x01")
    with pytest.raises(CorruptShardError):
        NativeTokenLoader([bad], block_size=8)


def test_missing_shard_retried_then_skipped(tmp_path, monkeypatch):
    """Transient I/O earns the backoff schedule: a shard that appears
    between attempts is admitted (retry actually re-probes)."""
    import distributed_lion_tpu.data.native_loader as nl

    good = _write_bin(tmp_path, "good.bin", np.arange(16))
    flaky = tmp_path / "flaky.bin"
    calls = {"n": 0}
    real_validate = nl._validate_shard

    def heal_on_second_try(path, dtype_bytes):
        if pathlib.Path(path).name == "flaky.bin":
            calls["n"] += 1
            if calls["n"] < 2:
                raise OSError("transient")
            np.arange(16, dtype=np.uint16).tofile(flaky)
        return real_validate(path, dtype_bytes)

    monkeypatch.setattr(nl, "_validate_shard", heal_on_second_try)
    monkeypatch.setattr(nl, "SHARD_BACKOFF_S", 0.001)
    dl = NativeTokenLoader([flaky, good], block_size=8)
    assert dl.health_metrics() == {"skipped_shards": 0,
                                   "shard_read_retries": 1}
    assert len(dl) == 4  # both shards admitted
    assert dl.shards == [str(flaky), str(good)]  # served fleet, in order
    dl.close()


def test_health_metrics_ride_the_batch_iterator(tmp_path):
    p = _write_bin(tmp_path, "h.bin", np.arange(64))
    dl = NativeTokenLoader([p], block_size=8)
    it = dl.batches(2, seed=0)
    assert it.health_metrics() == {"skipped_shards": 0,
                                   "shard_read_retries": 0}
    next(it)
    dl.close()


def test_read_block_out_of_range_fails_fast(tmp_path):
    """Deterministic failures (index out of range) must NOT burn the
    transient-I/O backoff schedule or inflate the retry counter."""
    import time as _time

    p = _write_bin(tmp_path, "r.bin", np.arange(32))
    dl = NativeTokenLoader([p], block_size=8)
    t0 = _time.monotonic()
    with pytest.raises(IndexError):
        dl.read_block(99)
    assert _time.monotonic() - t0 < 0.05
    assert dl.health_metrics()["shard_read_retries"] == 0
    dl.close()
