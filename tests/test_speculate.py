"""Speculative decode (ISSUE 11): draft/verify/commit on the paged KV
cache pinned IDENTICAL to the non-speculative engine — greedy speculative
output bit-identical to plain paged decode (gpt2 AND llama), sampled
output token-identical to the same per-request PRNG stream, across both
drafters × k ∈ {2, 4} — plus the rollback state-equality pin (len/last/
table/free-list after a partial accept == what a token-by-token run
holds), drafter protocol/grammar guards, and the speculative evidence
stage."""

import json
import os

import jax
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
from distributed_lion_tpu.models.llama import LlamaConfig, llama_init
from distributed_lion_tpu.serve.engine import (
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)
from distributed_lion_tpu.serve.speculate import (
    NGramDrafter,
    Speculator,
    build_speculator,
    ngram_propose,
    parse_speculate,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(family):
    if family == "gpt2":
        cfg = GPT2Config.tiny()
        return ServeModel.for_gpt2(gpt2_init(jax.random.key(0), cfg), cfg)
    cfg = LlamaConfig.tiny()
    return ServeModel.for_llama(llama_init(jax.random.key(0), cfg), cfg)


_MODELS = {}


def _cached_model(family):
    # one init + one ServeModel per family for the whole module: the pins
    # compare ENGINES, not inits, and tier-1 wall time is budgeted
    if family not in _MODELS:
        _MODELS[family] = _model(family)
    return _MODELS[family]


def _engine(family, **kw):
    model = _cached_model(family)
    base = dict(max_seqs=4, block_size=4, max_blocks_per_seq=8)
    draft = kw.pop("draft_model", None)
    if kw.get("speculate", "").startswith("draft") and draft is None:
        # self-drafting smoke: the target IS its own draft model — perfect
        # greedy acceptance, which exercises full-window commit + the
        # bonus-token path; the ngram legs exercise partial/zero accepts
        draft = _cached_model(family)
    base.update(kw)
    return ServingEngine(model, ServeConfig(**base), draft_model=draft)


def _workload(family, n=4, max_new=10):
    """Mixed traffic: two repetitive prompts (n-gram signal — repeated
    motifs make the suffix drafter actually propose) + two random ones
    (zero-signal slots ride the same verify dispatch)."""
    vocab = _cached_model(family).cfg.vocab_size
    rng = np.random.default_rng(11)
    motif = list(map(int, rng.integers(1, vocab, 5)))
    prompts = [motif * 2, motif * 3 + motif[:2],
               list(map(int, rng.integers(1, vocab, 6))),
               list(map(int, rng.integers(1, vocab, 3)))][:n]
    return [Request(req_id=f"r{i}", tokens=list(p), max_new_tokens=max_new,
                    seed=i) for i, p in enumerate(prompts)]


def _run(engine, reqs, **kw):
    return engine.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                               r.seed) for r in reqs], **kw)


# --------------------------------------------------- the headline pins
_PLAIN = {}


def _plain_out(family, samp_key, samp):
    if (family, samp_key) not in _PLAIN:
        _PLAIN[(family, samp_key)] = _run(_engine(family, **samp),
                                          _workload(family))
    return _PLAIN[(family, samp_key)]


@pytest.mark.parametrize("family", ["gpt2", "llama"])
@pytest.mark.parametrize("drafter", ["ngram", "draft"])
@pytest.mark.parametrize("k", [2, 4])
def test_speculative_greedy_bit_identical_to_plain(family, drafter, k):
    """THE acceptance pin: greedy speculative decode — both drafters,
    k ∈ {2,4}, both families — produces exactly the non-speculative
    engine's tokens and finish reasons. The drafter changes how fast the
    stream is emitted, never what it says."""
    plain = _plain_out(family, "greedy", dict(temperature=0.0))
    eng = _engine(family, speculate=f"{drafter}:{k}")
    out = _run(eng, _workload(family))
    for rid in plain:
        assert out[rid].tokens == plain[rid].tokens, rid
        assert out[rid].reason == plain[rid].reason, rid
    assert eng.stats["spec_rounds"] > 0
    if drafter == "draft":
        # self-draft smoke: the draft model IS the target, so every greedy
        # proposal must be accepted — the full-window/bonus-token path
        assert eng.stats["spec_accepted"] == eng.stats["spec_proposed"] > 0


@pytest.mark.parametrize("family", ["gpt2", "llama"])
@pytest.mark.parametrize("drafter", ["ngram", "draft"])
@pytest.mark.parametrize("k", [2, 4])
def test_speculative_sampled_token_identical_to_stream(family, drafter, k):
    """Sampled serving (temperature/top_k) under speculation is pinned
    token-identical to the same per-request fold_in(seed, token_index)
    stream the plain engine draws from — acceptance replays the pinned
    draw at every window position, so rejection can starve speedup but
    never change an output."""
    samp = dict(temperature=0.9, top_k=40)
    plain = _plain_out(family, "sampled", samp)
    out = _run(_engine(family, speculate=f"{drafter}:{k}", **samp),
               _workload(family))
    for rid in plain:
        assert out[rid].tokens == plain[rid].tokens, rid
        assert out[rid].reason == plain[rid].reason, rid


def test_speculative_staggered_arrivals_match_plain():
    """Continuous batching composes with speculation: staggered arrivals
    through the speculative tick still reproduce the plain engine's
    per-request outputs (slots join/leave mid-round; admit-tick prefills
    and verify windows interleave)."""
    reqs = _workload("gpt2")
    arrivals = {"r0": 0, "r1": 2, "r2": 2, "r3": 5}
    plain = _run(_engine("gpt2"), reqs, arrivals=arrivals)
    out = _run(_engine("gpt2", speculate="ngram:4"), reqs,
               arrivals=arrivals)
    for rid in plain:
        assert out[rid].tokens == plain[rid].tokens, rid


def test_ngram_accepts_on_repetitive_traffic():
    """The n-gram drafter must actually EARN accepts on repetitive
    prompts (the bench frontier's accept_rate > 0 claim is mechanism,
    not luck): a strongly periodic greedy stream yields nonzero
    acceptance with zero extra device dispatches."""
    vocab = _cached_model("gpt2").cfg.vocab_size
    rng = np.random.default_rng(5)
    motif = list(map(int, rng.integers(1, vocab, 4)))
    reqs = [Request(req_id=i, tokens=motif * 4, max_new_tokens=12, seed=0)
            for i in range(2)]
    eng = _engine("gpt2", speculate="ngram:4")
    _run(eng, reqs)
    assert eng.stats["spec_proposed"] > 0
    assert eng.stats["spec_accepted"] > 0


# -------------------------------------------- rollback state equality
class _ScriptedDrafter:
    """Deterministic partial-accept harness: proposes [true_next,
    corrupted, true, ...] from a pre-recorded plain-run stream, so every
    round accepts exactly the scripted prefix and rolls back the rest —
    the rollback path is exercised on EVERY tick, not when an n-gram
    happens to miss."""

    name = "scripted"

    def __init__(self, k, script, wrong_at=1):
        self.k, self.script, self.wrong_at = k, dict(script), wrong_at

    def admit(self, slot, tokens, n_committed=0):
        pass

    def evict(self, slot):
        pass

    def commit(self, slot, cache_len):
        pass

    def propose(self, active, slots, desired):
        drafts = np.zeros((len(slots), self.k), np.int32)
        counts = np.zeros((len(slots),), np.int32)
        for i in active:
            s = slots[i]
            true = self.script[s.req.req_id]
            done = len(s.gen)
            cont = true[done:done + int(desired[i])]
            for j, t in enumerate(cont):
                # corrupt every wrong_at-th draft (never a real token id:
                # vocab-1 xor keeps it in range but wrong)
                drafts[i, j] = t if (j + 1) % (self.wrong_at + 1) else \
                    (t + 1) % 256 or 1
            counts[i] = len(cont)
        return drafts, counts


def _alloc_state(bt):
    # bt._free became a list of PER-GROUP lists in the batch-sharded-ep
    # PR; list(bt._free) is now a SHALLOW copy whose inner lists keep
    # mutating as the run continues — every snapshot silently showed the
    # plain run's FINAL free list. Copy the inner lists too.
    return (bt.tables.copy(), bt.owned.copy(), [list(f) for f in bt._free])


def test_partial_accept_rollback_matches_token_by_token():
    """After EVERY speculative tick with a partial accept, the engine's
    visible state — gen stream, cache_len, last_tok, the slot's block
    table row, owned counts AND the allocator free list — equals the
    state the plain token-by-token engine holds at the same generated
    length. Single active request, so the equality is exact page ids,
    not just counts (multi-slot ticks batch their optimistic grows, which
    permutes which physical page serves which slot — pure indirection)."""
    req = _workload("gpt2", n=1, max_new=9)[0]

    plain = _engine("gpt2")
    plain.submit(Request(req.req_id, list(req.tokens), req.max_new_tokens,
                         req.seed))
    snaps = {}
    done = []
    while plain.has_work():
        done += plain.step()
        s = plain.slots[0]
        if s is not None:
            snaps[len(s.gen)] = (_alloc_state(plain.tables), s.cache_len,
                                 s.last_tok, list(s.gen))
    script = {req.req_id: done[0].tokens}

    spec = _engine("gpt2")
    spec._speculator = Speculator(
        spec, _ScriptedDrafter(k=3, script=script), k=3)
    spec.submit(Request(req.req_id, list(req.tokens), req.max_new_tokens,
                        req.seed))
    out = []
    while spec.has_work():
        out += spec.step()
        s = spec.slots[0]
        if s is None:
            continue
        alloc, cache_len, last, gen = snaps[len(s.gen)]
        assert (s.cache_len, s.last_tok, list(s.gen)) == (cache_len, last,
                                                          gen)
        tables, owned, free = _alloc_state(spec.tables)
        np.testing.assert_array_equal(tables, alloc[0])
        np.testing.assert_array_equal(owned, alloc[1])
        assert free == alloc[2]
    assert out[0].tokens == done[0].tokens
    st = spec.stats
    # the scripted drafter guarantees partial accepts happened: some
    # proposals accepted, some rejected — both halves of commit ran
    assert 0 < st["spec_accepted"] < st["spec_proposed"]


def test_constrained_pool_overflow_matches_plain():
    """Regression (the WITHIN-tick pin): on a symmetric workload under a
    tight explicit num_blocks pool, the speculative tick must
    overflow-evict the SAME requests with the SAME outputs as the plain
    engine. The original single-phase optimistic grow let an
    earlier-indexed slot take up to k draft pages before a later slot
    reserved its one mandatory write, flipping which request overflowed.
    The two-phase grow (mandatory writes first — the plain tick's exact
    loop — then drafts from the leftover pool only) pins the overflow
    rule identical; pool sizes below/at/above exhaustion all covered.
    (Asymmetric workloads, where cross-tick progress differs by design,
    get the weaker-but-unconditional pin in
    test_asymmetric_pool_overflow_stays_prefix_consistent.)"""
    vocab = _cached_model("gpt2").cfg.vocab_size
    rng = np.random.default_rng(11)
    motif = list(map(int, rng.integers(1, vocab, 5)))
    reqs = [Request("r0", motif * 2, 12, 0), Request("r1", motif * 2, 12, 1)]

    def run(speculate, nb):
        eng = _engine("gpt2", max_seqs=2, num_blocks=nb,
                      speculate=speculate)
        out = _run(eng, reqs)
        return {rid: (c.reason, list(c.tokens)) for rid, c in out.items()}

    for nb in (8, 10, 12):
        plain, spec = run("", nb), run("ngram:4", nb)
        assert plain == spec, f"num_blocks={nb}: {plain} vs {spec}"
        if nb == 8:  # the tight pool actually exercises the contention
            assert any(r == "overflow" for r, _ in plain.values())


def test_asymmetric_pool_overflow_stays_prefix_consistent():
    """The unconditional exhaustion invariant: on an ASYMMETRIC workload
    (one repetitive high-accept prompt + one random zero-signal prompt)
    a tight pool may overflow-evict a DIFFERENT request under speculation
    — the eviction is a race against pool exhaustion and speculation
    changes per-tick progress, not the stream — but every request's
    output in either run must be a PREFIX of its output in the other
    (both emit the same pinned per-request stream), and any request that
    completes (eos/length) in both runs must be identical."""
    vocab = _cached_model("gpt2").cfg.vocab_size
    rng = np.random.default_rng(11)
    motif = list(map(int, rng.integers(1, vocab, 4)))
    reqs = [Request("rep", motif * 4, 40, 0),
            Request("rand", list(map(int, rng.integers(1, vocab, 16))),
                    40, 1)]

    def run(speculate, nb):
        eng = _engine("gpt2", max_seqs=2, num_blocks=nb,
                      max_blocks_per_seq=16, speculate=speculate)
        return _run(eng, reqs)

    for nb in (12, 16, 32):
        plain, spec = run("", nb), run("ngram:4", nb)
        for rid in ("rep", "rand"):
            p, s = plain[rid], spec[rid]
            short, long_ = sorted((list(p.tokens), list(s.tokens)), key=len)
            assert long_[:len(short)] == short, \
                f"num_blocks={nb} {rid}: outputs not prefix-consistent"
            if p.reason != "overflow" and s.reason != "overflow":
                assert (p.reason, list(p.tokens)) == (s.reason,
                                                      list(s.tokens)), \
                    f"num_blocks={nb} {rid}: completed outputs differ"


def test_eos_inside_accepted_prefix_truncates_exactly():
    """An EOS token landing INSIDE the accepted prefix must finish the
    request exactly where the token-by-token run would — trailing
    accepted drafts after the EOS are discarded, never emitted."""
    req = _workload("gpt2", n=1, max_new=10)[0]
    base = _run(_engine("gpt2"), [req])[req.req_id]
    eos = base.tokens[4]  # pretend the 5th greedy token is EOS
    plain = _run(_engine("gpt2", eos_id=eos), [req])[req.req_id]
    assert plain.reason == "eos" and len(plain.tokens) <= len(base.tokens)

    spec = _engine("gpt2", eos_id=eos)
    spec._speculator = Speculator(
        spec, _ScriptedDrafter(k=4, script={req.req_id: base.tokens},
                               wrong_at=10), k=4)
    out = _run(spec, [req])[req.req_id]
    assert out.tokens == plain.tokens and out.reason == "eos"


# ------------------------------------------------- grammar and guards
def test_parse_speculate_grammar():
    assert parse_speculate("ngram:4") == ("ngram", 4)
    assert parse_speculate("draft:2") == ("draft", 2)
    with pytest.raises(ValueError, match="unknown drafter"):
        parse_speculate("medusa:4")
    with pytest.raises(ValueError, match="integer draft length"):
        parse_speculate("ngram")
    with pytest.raises(ValueError, match="integer draft length"):
        parse_speculate("ngram:x")
    with pytest.raises(ValueError, match=r"\[1, 16\]"):
        parse_speculate("ngram:0")
    with pytest.raises(ValueError, match=r"\[1, 16\]"):
        parse_speculate("draft:99")


def test_ngram_propose_suffix_lookup():
    # longest suffix [7,8] recurs at index 1; continuation follows it
    assert ngram_propose([5, 7, 8, 9, 4, 7, 8], 3) == [9, 4, 7]
    assert ngram_propose([5, 7, 8, 9, 4, 7, 8], 1) == [9]
    # no earlier occurrence of any suffix → no proposal
    assert ngram_propose([1, 2, 3, 4], 4) == []
    # the MOST RECENT earlier occurrence wins (prefer fresh context)
    assert ngram_propose([1, 2, 9, 1, 2, 5, 1, 2], 2) == [5, 1]
    # degenerate inputs propose nothing
    assert ngram_propose([], 4) == []
    assert ngram_propose([3], 4) == []
    assert ngram_propose([1, 2, 3], 0) == []


def test_ngram_incremental_index_matches_reference():
    """NGramDrafter's incremental suffix index proposes EXACTLY what the
    naive full-history rescan (ngram_propose, the reference) would, across
    random low-vocab histories grown token by token — the engine's shape:
    admit a prompt, then gen grows between proposes."""

    class _Req:
        def __init__(self, toks):
            self.tokens = toks

    class _Slot:
        def __init__(self, toks):
            self.req = _Req(toks)
            self.gen = []

    rng = np.random.default_rng(13)
    for _ in range(20):
        vocab = int(rng.integers(2, 6))  # tiny vocab → dense collisions
        prompt = list(map(int, rng.integers(0, vocab,
                                            int(rng.integers(1, 12)))))
        d = NGramDrafter(k=4)
        slot = _Slot(prompt)
        d.admit(0, list(prompt))
        for _ in range(30):
            slot.gen.append(int(rng.integers(0, vocab)))
            desired = np.array([int(rng.integers(0, 5))], np.int32)
            drafts, counts = d.propose([0], [slot], desired)
            ref = ngram_propose(prompt + slot.gen, int(desired[0]))
            assert int(counts[0]) == len(ref)
            assert list(map(int, drafts[0, :counts[0]])) == ref
        d.evict(0)
        assert not d._hist and not d._index  # eviction drops the state


def test_draft_spec_requires_draft_model():
    with pytest.raises(ValueError, match="needs a draft model"):
        ServingEngine(_cached_model("gpt2"),
                      ServeConfig(max_seqs=2, block_size=4,
                                  max_blocks_per_seq=4, speculate="draft:2"))


def test_cli_draft_without_path_refused(tmp_path):
    """`--speculate draft:<k>` with no --draft_model_path must refuse at
    the CLI: run_generate.build treats model_path=None as random-init
    smoke mode, so without the guard the user gets a random-weights
    drafter whose proposals all reject — every tick silently pays the
    draft dispatch plus the k+1-wide verify for nothing."""
    from distributed_lion_tpu.cli.run_serve import main

    reqs = tmp_path / "requests.jsonl"
    reqs.write_text('{"id": "r1", "prompt": "ab", "max_new_tokens": 2}\n')
    with pytest.raises(ValueError, match="draft_model_path"):
        main(["--model_family", "gpt2", "--model_name", "tiny",
              "--requests", str(reqs), "--out", str(tmp_path / "o.jsonl"),
              "--speculate", "draft:2"])


def test_draft_model_vocab_mismatch_refused():
    gpt2 = _cached_model("gpt2")
    other = _model("llama")  # vocab 256 too? ensure mismatch via config
    if other.cfg.vocab_size == gpt2.cfg.vocab_size:
        import dataclasses

        cfg = dataclasses.replace(GPT2Config.tiny(), vocab_size=128)
        other = ServeModel.for_gpt2(gpt2_init(jax.random.key(1), cfg), cfg)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(gpt2, ServeConfig(max_seqs=2, block_size=4,
                                        max_blocks_per_seq=4,
                                        speculate="draft:2"),
                      draft_model=other)


def test_moe_draft_speculation_refused_ngram_composes():
    """ISSUE 15: ngram speculation composes with MoE (speculative==plain
    pinned in tests/test_moe_serve.py), but draft:<k> keeps its loud
    refusal naming the mirror-pool residual — the draft mirror's own page
    pool has no sharded budget under expert parallelism."""
    cfg = GPT2Config.tiny(moe_experts=2)
    params = gpt2_init(jax.random.key(0), cfg)
    model = ServeModel.for_gpt2(params, cfg)
    with pytest.raises(ValueError, match="mirror"):
        ServingEngine(model, ServeConfig(max_seqs=2, block_size=4,
                                         max_blocks_per_seq=4,
                                         speculate="draft:2"),
                      draft_model=model)
    # ngram builds (and the equivalence pin lives in test_moe_serve)
    eng = ServingEngine(model, ServeConfig(max_seqs=2, block_size=4,
                                           max_blocks_per_seq=4,
                                           speculate="ngram:2"))
    assert eng._speculator is not None


def test_draft_cache_desync_is_loud():
    """A drafter bookkeeping bug (draft mirror length != target cache
    length) raises, never silently serves from a skewed cache."""
    eng = _engine("gpt2", speculate="draft:2")
    reqs = _workload("gpt2", n=1)
    eng.submit(Request(reqs[0].req_id, list(reqs[0].tokens), 6, 0))
    eng.step()
    drafter = eng._speculator.drafter
    drafter.len[0] += 1  # corrupt the mirror
    with pytest.raises(RuntimeError, match="desync"):
        eng.step()


def test_run_serve_cli_speculate_smoke(tmp_path):
    from distributed_lion_tpu.cli.run_serve import main

    reqs = tmp_path / "requests.jsonl"
    reqs.write_text(
        '{"id": "r1", "prompt": "abab", "max_new_tokens": 4}\n')
    out = tmp_path / "responses.jsonl"
    records = main(["--model_family", "gpt2", "--model_name", "tiny",
                    "--requests", str(reqs), "--out", str(out),
                    "--temperature", "0", "--max_seqs", "2",
                    "--block_size", "4", "--speculate", "ngram:2"])
    assert len(records) == 1 and records[0]["n_generated"] == 4


def test_speculative_journal_spans(tmp_path):
    from distributed_lion_tpu.train import journal

    j = journal.Journal(str(tmp_path))
    journal.install(j)
    try:
        eng = _engine("gpt2", speculate="ngram:2")
        _run(eng, _workload("gpt2", n=2, max_new=4))
    finally:
        journal.uninstall(j)
        j.close()
    names = {r["name"] for r in j.tail() if r["kind"] == "span"}
    assert {"serve/draft", "serve/verify", "serve/commit"} <= names


# -------------------------------------------- prefix sharing (ISSUE 13)
def test_speculative_rollback_over_shared_pages_pinned():
    """Speculation × prefix sharing: requests sharing a cached prompt
    prefix draft/verify/commit with rollback shrinking REFS, never
    freeing pages a neighbor or the cache still holds — outputs pinned
    to the plain unshared engine (greedy AND sampled) and the pool
    conserved after the workload drains."""
    vocab = _cached_model("gpt2").cfg.vocab_size
    rng = np.random.default_rng(23)
    motif = list(map(int, rng.integers(1, vocab, 4)))
    sys_p = motif * 3                       # 12 tokens: repetitive AND shared
    prompts = [sys_p + list(map(int, rng.integers(1, vocab, 2)))
               for _ in range(4)] + [list(sys_p)]
    reqs = [Request(req_id=f"r{i}", tokens=list(p), max_new_tokens=8,
                    seed=i) for i, p in enumerate(prompts)]
    for samp in (dict(temperature=0.0), dict(temperature=0.9, top_k=40)):
        plain = _run(_engine("gpt2", num_blocks=96, max_blocks_per_seq=16,
                             **samp),
                     [Request(r.req_id, list(r.tokens), r.max_new_tokens,
                              r.seed) for r in reqs])
        eng = _engine("gpt2", num_blocks=96, max_blocks_per_seq=16,
                      prefix_cache=True, speculate="ngram:4", **samp)
        out = _run(eng, [Request(r.req_id, list(r.tokens),
                                 r.max_new_tokens, r.seed) for r in reqs])
        for r in reqs:
            assert out[r.req_id].tokens == plain[r.req_id].tokens, r.req_id
            assert out[r.req_id].reason == plain[r.req_id].reason
        assert eng.stats["spec_rounds"] > 0
        assert eng.stats["prefix_hits"] > 0
        # rollback + eviction left the pool conserved: every live ref is
        # the cache's, free + physical == pool, and no slot holds pages
        assert all(s is None for s in eng.slots)
        assert (eng.tables.physical_pages + eng.tables.free_blocks
                == eng.tables.num_blocks)
        assert int(eng.tables.refs.sum()) == eng.tables.physical_pages


def test_speculative_shared_partial_accept_state_matches_unshared():
    """A partial accept over a table row whose PREFIX pages are shared:
    shrink hands back only the private tail pages (the shared run's refs
    are untouched), leaving len/last/table state equal to the unshared
    engine's on the same stream."""
    vocab = _cached_model("gpt2").cfg.vocab_size
    rng = np.random.default_rng(29)
    sys_p = list(map(int, rng.integers(1, vocab, 9)))
    reqs = [Request(req_id=f"r{i}", tokens=sys_p + [int(t)],
                    max_new_tokens=6, seed=i)
            for i, t in enumerate(rng.integers(1, vocab, 3))]
    plain_eng = _engine("gpt2", num_blocks=96, max_blocks_per_seq=16)
    plain = _run(plain_eng, [Request(r.req_id, list(r.tokens),
                                     r.max_new_tokens, r.seed)
                             for r in reqs])
    eng = _engine("gpt2", num_blocks=96, max_blocks_per_seq=16,
                  prefix_cache=True, speculate="ngram:2")
    out = _run(eng, [Request(r.req_id, list(r.tokens), r.max_new_tokens,
                             r.seed) for r in reqs])
    for r in reqs:
        assert out[r.req_id].tokens == plain[r.req_id].tokens, r.req_id
    # the cached run survived every rollback/evict cycle intact
    run, covered = eng.prefix.match(sys_p + [int(vocab - 1)])
    assert covered >= 8 and len(run) >= 2
