"""Evidence-window capture semantics (ADVICE r3): a window where every
config failed fast still writes the last config's ERROR row — that must
NOT mark the stage captured, or the re-arming TPU watcher
(scripts/tpu_watch_loop.sh) exits with no real data for it."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "check_evidence", os.path.join(REPO, "scripts", "check_evidence.py"))
ce = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ce)


def _write(tmp_path, lines):
    p = tmp_path / "w.jsonl"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


MARKER = '"attn": "flash@512x1024@512x512"'


def test_all_error_window_is_not_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "error": "rc=1: tunnel died"}',
        '{"attn": "flash@512x1024@512x512", "error": "rc=1: tunnel died"}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")




def test_marker_error_row_is_not_captured_even_with_banked_results(tmp_path):
    """The files are append-mode across watcher re-fires: a PREVIOUS
    window's banked result rows must not combine with THIS window's error
    marker to fake a capture (code-review r4 finding on the file-global
    any-result check)."""
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "tokens_per_sec_per_chip": 98099.3}',
        '{"attn": "flash@512x1024@512x512", "error": "OOM"}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_marker_result_row_is_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "error": "transient"}',
        '{"attn": "flash@512x1024@512x512", "tokens_per_sec_per_chip": 97000.0}',
    ])
    assert ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_missing_marker_is_not_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "tokens_per_sec_per_chip": 98099.3}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_missing_file_is_not_captured(tmp_path):
    assert not ce._window_captured(str(tmp_path / "nope.jsonl"), MARKER,
                                   "tokens_per_sec_per_chip")


def test_sweep_skip_keys_round_trip(tmp_path, monkeypatch):
    """bench_sweep's per-config resume: result rows (old round-3 schema and
    new backend-carrying schema) produce skip keys; error rows don't."""
    import importlib.util
    import json as _json

    p = tmp_path / "sweep.jsonl"
    p.write_text("\n".join([
        # round-3 row (no backend/block fields)
        _json.dumps({"remat": "noremat", "batch_per_dev": 4,
                     "attn": "flash@512x1024", "accum": 16, "dtype": "bf16",
                     "vocab_chunks": 8, "mom_dtype": "bfloat16",
                     "ms_per_step": 668.1, "loss": 9.045,
                     "tokens_per_sec_per_chip": 98099.3}),
        # round-4 row
        _json.dumps({"remat": "noremat", "batch_per_dev": 2,
                     "attn": "flash@512x1024", "accum": 16, "dtype": "bf16",
                     "vocab_chunks": 8, "mom_dtype": "bfloat16",
                     "vocab_pad": 0, "block": 2048,
                     "tokens_per_sec_per_chip": 50000.0, "backend": "tpu"}),
        # error row: must be retried, not skipped
        _json.dumps({"remat": "noremat", "batch_per_dev": 8,
                     "attn": "flash@512x1024", "accum": 8, "dtype": "bf16",
                     "error": "timeout"}),
    ]) + "\n")
    monkeypatch.setenv("SWEEP_SKIP_FILE", str(p))
    spec = importlib.util.spec_from_file_location(
        "bench_sweep", os.path.join(REPO, "scripts", "bench_sweep.py"))
    bs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bs)
    keys = bs._captured_keys()
    assert ("noremat", 4, "flash@512x1024", 16, "bf16", 8, "bfloat16",
            0, 1024) in keys
    assert ("noremat", 2, "flash@512x1024", 16, "bf16", 8, "bfloat16",
            0, 2048) in keys
    assert len(keys) == 2  # the error row contributed nothing


def test_sweep_row_promotable_rule():
    """bench.sweep_row_promotable: the ONE eligibility rule shared by
    _best_sweep_row and the runbook winner promotion."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    ok = {"tokens_per_sec_per_chip": 98099.3}
    assert b.sweep_row_promotable(ok)                       # legacy row
    assert b.sweep_row_promotable({**ok, "backend": "tpu"})
    assert not b.sweep_row_promotable({**ok, "backend": "cpu"})
    assert not b.sweep_row_promotable({**ok, "block": 2048})  # not anchor
    assert not b.sweep_row_promotable({"error": "boom"})
