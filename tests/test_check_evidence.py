"""Evidence-window capture semantics (ADVICE r3): a window where every
config failed fast still writes the last config's ERROR row — that must
NOT mark the stage captured, or the re-arming TPU watcher
(scripts/tpu_watch_loop.sh) exits with no real data for it."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "check_evidence", os.path.join(REPO, "scripts", "check_evidence.py"))
ce = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ce)


def _write(tmp_path, lines):
    p = tmp_path / "w.jsonl"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


# structural marker (advisor r4: substring needles were coupled to dict
# insertion order / separator spacing)
MARKER = {"attn": "flash@512x1024@512x512"}


def test_all_error_window_is_not_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "error": "rc=1: tunnel died"}',
        '{"attn": "flash@512x1024@512x512", "error": "rc=1: tunnel died"}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")




def test_marker_error_row_is_not_captured_even_with_banked_results(tmp_path):
    """The files are append-mode across watcher re-fires: a PREVIOUS
    window's banked result rows must not combine with THIS window's error
    marker to fake a capture (code-review r4 finding on the file-global
    any-result check)."""
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "tokens_per_sec_per_chip": 98099.3}',
        '{"attn": "flash@512x1024@512x512", "error": "OOM"}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_marker_result_row_is_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "error": "transient"}',
        '{"attn": "flash@512x1024@512x512", "tokens_per_sec_per_chip": 97000.0}',
    ])
    assert ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_missing_marker_is_not_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "tokens_per_sec_per_chip": 98099.3}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_missing_file_is_not_captured(tmp_path):
    assert not ce._window_captured(str(tmp_path / "nope.jsonl"), MARKER,
                                   "tokens_per_sec_per_chip")


def test_sweep_skip_keys_round_trip(tmp_path, monkeypatch):
    """bench_sweep's per-config resume: result rows (old round-3 schema and
    new backend-carrying schema) produce skip keys; error rows don't."""
    import importlib.util
    import json as _json

    p = tmp_path / "sweep.jsonl"
    p.write_text("\n".join([
        # round-3 row (no backend/block fields)
        _json.dumps({"remat": "noremat", "batch_per_dev": 4,
                     "attn": "flash@512x1024", "accum": 16, "dtype": "bf16",
                     "vocab_chunks": 8, "mom_dtype": "bfloat16",
                     "ms_per_step": 668.1, "loss": 9.045,
                     "tokens_per_sec_per_chip": 98099.3}),
        # round-4 row
        _json.dumps({"remat": "noremat", "batch_per_dev": 2,
                     "attn": "flash@512x1024", "accum": 16, "dtype": "bf16",
                     "vocab_chunks": 8, "mom_dtype": "bfloat16",
                     "vocab_pad": 0, "block": 2048,
                     "tokens_per_sec_per_chip": 50000.0, "backend": "tpu"}),
        # error row: must be retried, not skipped
        _json.dumps({"remat": "noremat", "batch_per_dev": 8,
                     "attn": "flash@512x1024", "accum": 8, "dtype": "bf16",
                     "error": "timeout"}),
    ]) + "\n")
    monkeypatch.setenv("SWEEP_SKIP_FILE", str(p))
    spec = importlib.util.spec_from_file_location(
        "bench_sweep", os.path.join(REPO, "scripts", "bench_sweep.py"))
    bs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bs)
    keys = bs._captured_keys()
    assert ("noremat", 4, "flash@512x1024", 16, "bf16", 8, "bfloat16",
            0, 1024, 1) in keys
    assert ("noremat", 2, "flash@512x1024", 16, "bf16", 8, "bfloat16",
            0, 2048, 1) in keys
    assert len(keys) == 2  # the error row contributed nothing


def test_marker_matches_any_field_order(tmp_path):
    """The structural compare must be immune to key order and spacing —
    the exact failure mode of the old substring needles."""
    path = _write(tmp_path, [
        '{"tokens_per_sec_per_chip": 97000.0,   '
        '"attn":"flash@512x1024@512x512"}',
    ])
    assert ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_marker_default_fill(tmp_path):
    """Round-3 rows omit block=1024; the sweep2 marker must still match
    them via _MARKER_DEFAULTS, while block=2048 rows must not."""
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024@512x512", "tokens_per_sec_per_chip": 1.0}',
    ])
    assert ce._window_captured(path, ce.SWEEP2_MARKER,
                               "tokens_per_sec_per_chip")
    path2 = _write(tmp_path, [
        '{"attn": "flash@512x1024@512x512", "block": 2048, '
        '"batch_per_dev": 2, "tokens_per_sec_per_chip": 1.0}',
    ])
    assert not ce._window_captured(path2, ce.SWEEP2_MARKER,
                                   "tokens_per_sec_per_chip")
    assert ce._window_captured(path2, ce.SWEEP3_MARKER,
                               "tokens_per_sec_per_chip")


def _leg_lines(mode, steps=2000, dtype="float32", loss=5.0, seed=0,
               n_params=12_700_000):
    import json as _json
    rows = [_json.dumps({"meta": True, "mode": mode, "param_dtype": dtype,
                         "steps": steps, "workers": 8, "seed": seed,
                         "n_params": n_params})]
    for s in range(0, steps, 10):
        rows.append(_json.dumps({"step": s, "loss": loss}))
    rows.append(_json.dumps({"step": steps - 1, "loss": loss}))
    return rows


def test_parity_numeric_criterion(tmp_path):
    """parity_mad/parity_pass: identical curves PASS, curves offset by more
    than PARITY_EPS_NATS FAIL, and a config mismatch is UNCOMPUTABLE."""
    d = tmp_path / "legs"
    d.mkdir()
    (d / "local.jsonl").write_text("\n".join(_leg_lines("local")) + "\n")
    (d / "vote.jsonl").write_text(
        "\n".join(_leg_lines("vote", loss=5.0 + 0.01)) + "\n")
    assert abs(ce.parity_mad(str(d), "vote") - 0.01) < 1e-9
    (d / "lazy.jsonl").write_text(
        "\n".join(_leg_lines("lazy", loss=5.0 + ce.PARITY_EPS_NATS * 2))
        + "\n")
    assert ce.parity_mad(str(d), "lazy") > ce.PARITY_EPS_NATS
    # config mismatch (different seed) → UNCOMPUTABLE, not a bogus number
    (d / "vote.jsonl").write_text(
        "\n".join(_leg_lines("vote", seed=1)) + "\n")
    assert ce.parity_mad(str(d), "vote") is None
    # bf16-stamped leg is unqualified regardless of curve
    (d / "vote.jsonl").write_text(
        "\n".join(_leg_lines("vote", dtype="bfloat16")) + "\n")
    assert ce.parity_mad(str(d), "vote") is None


def test_parity_strict_requires_numeric_pass(tmp_path, monkeypatch):
    """ISSUE 6 satellite: the parity:vote / parity:lazy stages require the
    pre-registered criterion to PASS — a present-but-diverged leg reads
    MISSING. The watcher's automation check still judges presence (a
    deterministic FAIL needs a human, not an infinite re-fire loop)."""
    monkeypatch.setattr(ce, "REPO", str(tmp_path))
    d = tmp_path / "runs" / "parity"
    d.mkdir(parents=True)
    (d / "local.jsonl").write_text("\n".join(_leg_lines("local")) + "\n")
    # within EPS → strict stage captured
    (d / "vote.jsonl").write_text(
        "\n".join(_leg_lines("vote", loss=5.0 + ce.PARITY_EPS_NATS / 2))
        + "\n")
    assert ce.parity("vote") and ce.parity_strict("vote")
    # present but diverged → presence yes, strict NO
    (d / "vote.jsonl").write_text(
        "\n".join(_leg_lines("vote", loss=5.0 + ce.PARITY_EPS_NATS * 3))
        + "\n")
    assert ce.parity("vote")
    assert not ce.parity_strict("vote")
    # local is the baseline leg: presence-only semantics
    assert ce.parity_strict("local")
    # absent lazy leg: both read missing
    assert not ce.parity("lazy") and not ce.parity_strict("lazy")


def test_autotune_stage(tmp_path, monkeypatch):
    """The 'autotune' stage: captured only when the committed tuning cache
    exists, passes the strict schema, AND carries TPU-keyed entries for
    EVERY knob (a window that dropped after the first knob must re-fire,
    not permanently skip the rest) — the CPU-produced pipeline-proof
    artifact alone must read MISSING, as must a corrupt or
    schema-violating cache."""
    import json as _json

    KNOBS = ("flash_tiles", "splash_tiles", "lion_row_block",
             "vocab_chunks", "vote_buckets")
    cache = tmp_path / "tuning_cache.json"
    monkeypatch.setattr(ce, "TUNE_CACHE", str(cache))
    assert not ce.autotune_ok()                       # absent
    entry = {"value": {"x": 512}, "ms": 1.0}
    cache.write_text(_json.dumps({
        "format": "dlt-tune-cache-v1",
        "entries": {f"cpu|{k}|N10|float32": entry for k in KNOBS}}))
    assert not ce.autotune_ok()                       # cpu-keyed only
    cache.write_text(_json.dumps({
        "format": "dlt-tune-cache-v1",
        "entries": {"TPU v5 lite|lion_row_block|N10|float32": entry}}))
    assert not ce.autotune_ok()                       # one knob ≠ complete
    cache.write_text(_json.dumps({
        "format": "dlt-tune-cache-v1",
        "entries": {f"TPU v5 lite|{k}|N10|float32": entry for k in KNOBS}}))
    assert ce.autotune_ok()                           # all knobs: captured
    cache.write_text(_json.dumps({
        "format": "dlt-tune-cache-v1",
        "entries": {"TPU v5 lite|lion_row_block|N10|float32":
                    {"value": {}, "ms": 1.0}}}))
    assert not ce.autotune_ok()                       # schema violation
    cache.write_text("{torn")
    assert not ce.autotune_ok()                       # corrupt


def test_parity_short_leg_unqualified(tmp_path):
    d = tmp_path / "legs"
    d.mkdir()
    (d / "local.jsonl").write_text(
        "\n".join(_leg_lines("local", steps=500)) + "\n")
    assert ce._load_leg(str(d), "local") is not None
    assert not ce._leg_ok(ce._load_leg(str(d), "local"))


def test_validate_rows_never_mark_capture(tmp_path):
    """SFT7B_VALIDATE pipeline rows carry the real result key but must
    satisfy neither the capture marker nor the skip-key resume."""
    import importlib.util
    import json as _json

    row = {"seq_len": 2048, "tokens_per_sec_per_chip": 5.0,
           "validate": True, "quant": "nf4", "batch_per_dev": 1,
           "accum": 1, "remat_policy": "dots", "vocab_chunks": 8}
    path = _write(tmp_path, [_json.dumps(row)])
    assert not ce._window_captured(path, ce.SFT7B_MARKER,
                                   "tokens_per_sec_per_chip")
    import os as _os
    _os.environ["SFT7B_SKIP_FILE"] = path
    try:
        spec = importlib.util.spec_from_file_location(
            "bench_sft_7b", os.path.join(REPO, "scripts", "bench_sft_7b.py"))
        b7 = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(b7)
        assert b7._captured_keys() == set()
    finally:
        del _os.environ["SFT7B_SKIP_FILE"]


def test_dpo_stage_and_tpu_guard(tmp_path, monkeypatch):
    import json as _json

    monkeypatch.setattr(ce, "OUT", str(tmp_path))
    assert not ce.dpo()
    p = tmp_path / "dpo.jsonl"
    p.write_text(_json.dumps({"backend": "cpu",
                              "tokens_per_sec_per_chip": 7.6}) + "\n")
    assert ce.dpo()                  # evidence stage: any backend
    assert not ce.dpo(tpu_only=True)  # runbook guard: chip rows only
    p.write_text(p.read_text() + _json.dumps(
        {"backend": "tpu", "tokens_per_sec_per_chip": 900.0}) + "\n")
    assert ce.dpo(tpu_only=True)


def test_conv_dual_directory(tmp_path, monkeypatch):
    import json as _json

    monkeypatch.setattr(ce, "REPO", str(tmp_path))
    rows = [_json.dumps({"step": s, "train/loss": 5.0})
            for s in range(0, 2000, 25)]
    rows.append(_json.dumps({"step": 1999, "eval/loss": 5.0,
                             "eval/accuracy": 0.3}))
    d = tmp_path / "runs" / "convergence_cpu"
    d.mkdir(parents=True)
    (d / "metrics.jsonl").write_text("\n".join(rows) + "\n")
    assert ce.conv()                       # fallback dir satisfies conv
    assert not ce.conv("convergence")      # the runbook's conv_full doesn't
    # eval-less curve must not count
    (d / "metrics.jsonl").write_text("\n".join(rows[:-1]) + "\n")
    assert not ce.conv()


def test_overlap_stage_needs_all_three_bucket_rows(tmp_path, monkeypatch):
    """The vote-wire overlap ablation is captured only when buckets
    {1, 4, 16} ALL hold result rows — a lone B=1 anchor (or a window that
    errored on the pipelined legs) must not mark the stage done."""
    import json as _json

    monkeypatch.setattr(ce, "OUT", str(tmp_path))
    assert not ce.overlap()
    base = {"remat": "noremat", "batch_per_dev": 4, "attn": "flash@512x1024",
            "accum": 16, "dtype": "bf16", "vocab_chunks": 8,
            "mom_dtype": "bfloat16", "vocab_pad": 0,
            "tokens_per_sec_per_chip": 98000.0, "ms_per_step": 668.0,
            "backend": "tpu"}
    p = tmp_path / "overlap.jsonl"
    # B=1 rows omit the field (bench_sweep default-elision) — the marker's
    # _MARKER_DEFAULTS fill must still match them
    rows = [_json.dumps(base),
            _json.dumps({**base, "vote_buckets": 4, "ms_per_step": 640.0})]
    p.write_text("\n".join(rows) + "\n")
    assert not ce.overlap()  # 16 missing
    rows.append(_json.dumps({**base, "vote_buckets": 16,
                             "ms_per_step": 645.0, "error": "x"}))
    p.write_text("\n".join(rows) + "\n")
    assert ce.overlap()


def test_bench_overlap_from_ablation(tmp_path, monkeypatch):
    """bench.overlap_from_ablation: measured comm_overlap_frac =
    (ms[1] − min_B ms[B]) / ms[1] over TPU rows of one config; CPU rows and
    slower-than-anchor pipelined rows never produce a negative fraction."""
    import importlib.util
    import json as _json

    spec = importlib.util.spec_from_file_location(
        "bench_mod3", os.path.join(REPO, "bench.py"))
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    d = tmp_path / "SWEEP_r9_raw"
    d.mkdir()
    base = {"remat": "noremat", "batch_per_dev": 4, "attn": "flash",
            "accum": 16, "dtype": "bf16", "tokens_per_sec_per_chip": 9.0}
    rows = [
        _json.dumps({**base, "ms_per_step": 700.0}),                # B=1
        _json.dumps({**base, "ms_per_step": 630.0, "vote_buckets": 4}),
        _json.dumps({**base, "ms_per_step": 665.0, "vote_buckets": 16}),
        # a CPU-attested row must be ignored entirely
        _json.dumps({**base, "ms_per_step": 1.0, "vote_buckets": 4,
                     "backend": "cpu"}),
    ]
    (d / "overlap.jsonl").write_text("\n".join(rows) + "\n")
    import glob as _glob
    monkeypatch.setattr(
        _glob, "glob", lambda pat: [str(d / "overlap.jsonl")])
    got = b.overlap_from_ablation()
    assert abs(got["comm_overlap_frac"] - (700.0 - 630.0) / 700.0) < 1e-9
    assert set(got["ms_per_step"]) == {"1", "4", "16"}
    # pipelined slower than anchor → clipped at 0, never negative
    (d / "overlap.jsonl").write_text("\n".join([
        _json.dumps({**base, "ms_per_step": 700.0}),
        _json.dumps({**base, "ms_per_step": 800.0, "vote_buckets": 4}),
    ]) + "\n")
    assert b.overlap_from_ablation()["comm_overlap_frac"] == 0.0


def test_sweep_row_promotable_rule():
    """bench.sweep_row_promotable: the ONE eligibility rule shared by
    _best_sweep_row and the runbook winner promotion."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    ok = {"tokens_per_sec_per_chip": 98099.3}
    assert b.sweep_row_promotable(ok)                       # legacy row
    assert b.sweep_row_promotable({**ok, "backend": "tpu"})
    assert not b.sweep_row_promotable({**ok, "backend": "cpu"})
    assert not b.sweep_row_promotable({**ok, "block": 2048})  # not anchor
    # pipelined-wire ablation rows never displace the monolithic anchor
    # (the adoption probe in run_inner must carry this field too)
    assert not b.sweep_row_promotable({**ok, "vote_buckets": 4})
    assert not b.sweep_row_promotable({"error": "boom"})


def test_unpromoted_capture_cannot_clobber_promoted_artifact(tmp_path):
    """bench._record_tpu_measurement (advisor r4, medium): a debug run's
    record must not overwrite the promoted flagship artifact that future
    bare runs adopt their config from — but promoted records, and writes
    over unpromoted ones, still land."""
    import importlib.util
    import json as _json

    spec = importlib.util.spec_from_file_location(
        "bench_mod2", os.path.join(REPO, "bench.py"))
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    art = tmp_path / "last.json"
    b.LAST_TPU_ARTIFACT = str(art)
    b._record_tpu_measurement({"value": 90000.0, "promoted": True,
                               "backend": "tpu"})
    assert _json.loads(art.read_text())["value"] == 90000.0
    # unpromoted over promoted: refused
    b._record_tpu_measurement({"value": 10.0, "promoted": False,
                               "backend": "tpu"})
    assert _json.loads(art.read_text())["value"] == 90000.0
    # promoted over promoted: recorded
    b._record_tpu_measurement({"value": 95000.0, "promoted": True,
                               "backend": "tpu"})
    assert _json.loads(art.read_text())["value"] == 95000.0
    # unpromoted over unpromoted: recorded (no promoted chain to protect)
    art.write_text(_json.dumps({"value": 1.0, "promoted": False}))
    b._record_tpu_measurement({"value": 2.0, "promoted": False})
    assert _json.loads(art.read_text())["value"] == 2.0


def test_telemetry_stage_mass_conservation(tmp_path, monkeypatch):
    """The 'telemetry' stage (ISSUE 2): a vote-health row passes only when
    its margin histogram conserves the voted-coordinate count (mass ~= 1 of
    per-voted-coordinate fractions), comes from a tally wire
    (margin_exact == 1), and parses as strict JSON. A lossy histogram, a
    proxy-wire row alone, or an absent artifact must all read MISSING."""
    import json as _json

    monkeypatch.setattr(ce, "REPO", str(tmp_path))
    d = tmp_path / "runs" / "telemetry"
    d.mkdir(parents=True)
    path = d / "metrics.jsonl"

    def row(hist, exact=1, voted=124672.0):
        return _json.dumps({
            "step": 10, "train/vote/margin_hist": hist,
            "train/vote/margin_exact": exact,
            "train/vote/voted_per_step": voted,
        })

    good = row([0.25, 0.0, 0.4, 0.0, 0.2, 0.0, 0.1, 0.05])
    assert not ce.telemetry_ok()            # absent artifact
    path.write_text(row([0.1] * 8, exact=0) + "\n")
    assert not ce.telemetry_ok()            # proxy-wire rows alone: no
    path.write_text(good + "\n")
    assert ce.telemetry_ok()                # conserved mass: captured
    path.write_text(good + "\n" + row([0.2] * 8) + "\n")
    assert not ce.telemetry_ok()            # any lossy row fails the stage
    path.write_text(row([0.5, None] + [0.1] * 6) + "\n")
    assert not ce.telemetry_ok()            # null bin (NaN leaked): fail


def test_static_stage(tmp_path, monkeypatch):
    """The 'static' stage (ISSUE 4): green only when the ci_static gate
    passes AND the tier-2 jaxpr-contract report exists with ok=true — an
    absent, corrupt, or failing report reads MISSING, so the runbook
    re-captures it. The gate subprocess is stubbed (like the report path)
    so this stays a stage-logic test, independent of which ruff/shellcheck
    versions the host happens to have; the REAL gate passing over the repo
    is pinned by tests/test_analysis_lint.py."""
    import json as _json
    import subprocess as _sp

    gate_rc = {"rc": 0}
    monkeypatch.setattr(ce.subprocess, "run", lambda *a, **k: _sp.
                        CompletedProcess(a, gate_rc["rc"]))
    monkeypatch.setattr(ce, "STATIC_TIER2_REPORT",
                        str(tmp_path / "static_tier2.json"))
    assert not ce.static_ok()  # gate passes but the report is absent
    (tmp_path / "static_tier2.json").write_text(
        _json.dumps({"ok": False, "configs": []}))
    assert not ce.static_ok()  # a failing contract must not read captured
    (tmp_path / "static_tier2.json").write_text("{not json")
    assert not ce.static_ok()
    (tmp_path / "static_tier2.json").write_text(
        _json.dumps({"ok": True, "world": 8, "configs": []}))
    assert ce.static_ok()
    gate_rc["rc"] = 1
    assert not ce.static_ok()  # a red gate must not read captured either


def test_vote_guard_stage(tmp_path, monkeypatch):
    """The 'vote_guard' stage (ISSUE 5): captured only when (a) the clean
    and clean_enforce legs log BYTE-identical loss curves (all-healthy
    bit-identity) and (b) the poisoned enforce leg's tail tracks clean
    within GUARD_ENFORCE_EPS while guard-off sits GUARD_MIN_GAP further
    out. A missing leg, a bit-identity breach, a non-degrading adversary,
    or a non-rescuing guard must all read MISSING."""
    import json as _json

    monkeypatch.setattr(ce, "REPO", str(tmp_path))

    def write(leg, losses):
        d = tmp_path / "runs" / "vote_guard" / leg
        d.mkdir(parents=True, exist_ok=True)
        rows = [_json.dumps({"step": s + 1, "train/loss": v})
                for s, v in enumerate(losses)]
        (d / "metrics.jsonl").write_text("\n".join(rows) + "\n")

    clean = [5.0 - 0.05 * i for i in range(40)]
    assert not ce.vote_guard_ok()           # nothing captured
    write("clean", clean)
    write("clean_enforce", clean)
    write("poison_enforce", [v + 0.2 for v in clean])
    assert not ce.vote_guard_ok()           # poison_off leg missing
    write("poison_off", [v + 0.5 for v in clean])
    assert ce.vote_guard_ok()               # the full claim holds
    write("clean_enforce", [v + 1e-6 for v in clean])
    assert not ce.vote_guard_ok()           # bit-identity breach fails
    write("clean_enforce", clean)
    write("poison_enforce", [v + 0.6 for v in clean])
    assert not ce.vote_guard_ok()           # guard failed to rescue
    write("poison_enforce", [v + 0.2 for v in clean])
    write("poison_off", [v + 0.22 for v in clean])
    assert not ce.vote_guard_ok()           # adversary didn't degrade
    write("poison_off", [v + 0.5 for v in clean[:20]])
    assert not ce.vote_guard_ok()           # short leg (< GUARD_MIN_STEPS)


def test_journal_stage(tmp_path):
    """The 'journal' stage (ISSUE 7): captured only when a journal exists,
    parses under the strict schema, the attribution CLOSES, and >=95% of
    measured step wall lands in named buckets. Absent journals, schema
    errors, and poor coverage must all read MISSING."""
    import json as _json

    def rec(**kw):
        return _json.dumps(kw)

    def write(d, cover_frac):
        d.mkdir(parents=True, exist_ok=True)
        # a 10s window with `cover_frac` of it tiled by dispatch spans
        rows = [rec(kind="meta", name="journal_start", t=0.0, rank=0,
                    wall=100.0, version=1),
                rec(kind="event", name="train_start", t=0.0, rank=0, step=0),
                rec(kind="span", name="dispatch", t=10.0 * cover_frac,
                    rank=0, dur=10.0 * cover_frac, step=0),
                rec(kind="event", name="step_log", t=9.9, rank=0, step=9),
                rec(kind="event", name="train_end", t=10.0, rank=0, step=10)]
        (d / "journal_rank0.jsonl").write_text("\n".join(rows) + "\n")

    assert not ce.journal_ok(str(tmp_path / "missing"))   # no journal at all
    good = tmp_path / "good"
    write(good, 0.98)
    assert ce.journal_ok(str(good))
    sparse = tmp_path / "sparse"
    write(sparse, 0.5)                                    # coverage 50%
    assert not ce.journal_ok(str(sparse))
    bad = tmp_path / "bad"
    write(bad, 0.98)
    p = bad / "journal_rank0.jsonl"
    p.write_text('{"kind": "span"}\n' + p.read_text())    # schema error
    assert not ce.journal_ok(str(bad))
