"""Evidence-window capture semantics (ADVICE r3): a window where every
config failed fast still writes the last config's ERROR row — that must
NOT mark the stage captured, or the re-arming TPU watcher
(scripts/tpu_watch_loop.sh) exits with no real data for it."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "check_evidence", os.path.join(REPO, "scripts", "check_evidence.py"))
ce = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ce)


def _write(tmp_path, lines):
    p = tmp_path / "w.jsonl"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


MARKER = '"attn": "flash@512x1024@512x512"'


def test_all_error_window_is_not_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "error": "rc=1: tunnel died"}',
        '{"attn": "flash@512x1024@512x512", "error": "rc=1: tunnel died"}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")




def test_marker_error_row_is_not_captured_even_with_banked_results(tmp_path):
    """The files are append-mode across watcher re-fires: a PREVIOUS
    window's banked result rows must not combine with THIS window's error
    marker to fake a capture (code-review r4 finding on the file-global
    any-result check)."""
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "tokens_per_sec_per_chip": 98099.3}',
        '{"attn": "flash@512x1024@512x512", "error": "OOM"}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_marker_result_row_is_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "error": "transient"}',
        '{"attn": "flash@512x1024@512x512", "tokens_per_sec_per_chip": 97000.0}',
    ])
    assert ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_missing_marker_is_not_captured(tmp_path):
    path = _write(tmp_path, [
        '{"attn": "flash@512x1024", "tokens_per_sec_per_chip": 98099.3}',
    ])
    assert not ce._window_captured(path, MARKER, "tokens_per_sec_per_chip")


def test_missing_file_is_not_captured(tmp_path):
    assert not ce._window_captured(str(tmp_path / "nope.jsonl"), MARKER,
                                   "tokens_per_sec_per_chip")
