"""Hierarchical (two-level) vote wire: ``wire="hier:<g>"``.

±1 ballots are psum'd inside g-worker ICI subgroups; only the subgroups'
bit-packed 1-bit verdicts cross the group boundary (the DCN leg on a
multi-host mesh). Net-new vs the reference (whose only collective is a flat
world-wide all_gather, /root/reference/distributed_lion.py:80-81); the
hierarchy is the standard scale-out shape for meshes where intra-host ICI is
cheap and cross-host DCN is the budgeted fabric.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from distributed_lion_tpu.ops.codec import parse_wire, wire_bytes_per_param
from distributed_lion_tpu.parallel.collectives import (
    majority_vote,
    majority_vote_psum,
)

W = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:W]), ("data",))


def _vote_all(votes: np.ndarray, wire: str) -> np.ndarray:
    """Run majority_vote over the data axis; votes is [W, n] bool.
    Returns the elected bools from every worker, stacked [W, n]."""
    mesh = _mesh()

    def body(v):
        elected = majority_vote(v[0], "data", wire)
        return elected[None]

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    return np.asarray(f(jnp.asarray(votes)))


def test_parse_wire():
    assert parse_wire("hier:4") == ("hier", 4)
    assert parse_wire("sign_psum") == ("sign_psum", None)
    with pytest.raises(ValueError):
        parse_wire("hier:zero")
    with pytest.raises(ValueError):
        parse_wire("hier:0")
    with pytest.raises(ValueError):
        parse_wire("carrier_pigeon")


@pytest.mark.parametrize("g", [1, W])
def test_degenerate_groups_match_flat_vote(g):
    rng = np.random.default_rng(0)
    votes = rng.random((W, 203)) < 0.5
    flat = _vote_all(votes, "sign_psum")
    hier = _vote_all(votes, f"hier:{g}")
    np.testing.assert_array_equal(hier, flat)


def test_majority_of_majorities_semantics():
    # W=8, g=4 → 2 subgroups. Coordinate 0: ballots [+,+,+,-] [-,-,-,+]
    # → verdicts [+, -] → group-level tie → -1, though the flat vote is 4-4
    # tie → -1 as well. Coordinate 1: [+,+,-,-] [+,+,+,+] → group 0 tie → -,
    # group 1 +, tie → -1 — but the flat vote is 6-2 → +1. The hierarchy is
    # a different (documented) electorate.
    votes = np.zeros((W, 2), bool)
    votes[:, 0] = [1, 1, 1, 0, 0, 0, 0, 1]
    votes[:, 1] = [1, 1, 0, 0, 1, 1, 1, 1]
    flat = _vote_all(votes, "sign_psum")
    hier = _vote_all(votes, "hier:4")
    assert not flat[0, 0] and not hier[0, 0]
    assert flat[0, 1] and not hier[0, 1]


def test_replica_consistency_and_unanimity():
    rng = np.random.default_rng(1)
    votes = rng.random((W, 130)) < 0.5
    votes[:, :10] = True   # unanimous + must elect +
    votes[:, 10:20] = False  # unanimous - must elect -
    out = _vote_all(votes, "hier:2")
    for w in range(1, W):
        np.testing.assert_array_equal(out[0], out[w])
    assert out[0, :10].all() and not out[0, 10:20].any()


@pytest.mark.parametrize("w,g", [(2, 1), (2, 2), (4, 2), (6, 2), (6, 3),
                                 (8, 2), (8, 4)])
def test_hier_matches_numpy_oracle(w, g):
    """Fuzz: elected bits equal a numpy majority-of-majorities oracle for
    every (world, group) combination the 8-device mesh can host."""
    rng = np.random.default_rng(w * 10 + g)
    votes = rng.random((w, 97)) < 0.5
    mesh = Mesh(np.array(jax.devices()[:w]), ("data",))

    def body(v):
        return majority_vote(v[0], "data", f"hier:{g}")[None]

    out = shard_map(body, mesh=mesh, in_specs=P("data"),
                    out_specs=P("data"))(jnp.asarray(votes))
    got = np.asarray(out)[0]

    groups = votes.reshape(w // g, g, -1)
    tallies = groups.sum(1) * 2 - g            # per-group ±1 sums
    verdicts = tallies > 0                     # group tie → -1
    expected = verdicts.sum(0) * 2 > (w // g)  # group-level tie → -1
    np.testing.assert_array_equal(got, expected)
    for row in np.asarray(out)[1:]:
        np.testing.assert_array_equal(row, got)


def test_group_size_must_divide_world():
    votes = np.zeros((W, 16), bool)
    with pytest.raises(ValueError, match="divide"):
        _vote_all(votes, "hier:3")


def test_wire_accounting_hier():
    n = 124_000_000
    acct = wire_bytes_per_param(n, world_size=32, wire="hier:8")
    # DCN leg: (G−1)=3 hops × (n/g)/8 packed bytes → 3/8 bit/param crossing
    # the slow fabric — under BASELINE.md's 0.5 bit/param budget outright,
    # vs packed_allgather's 32 bits/param at the same world size.
    assert acct["hier_groups"] == 4
    assert acct["dcn_bits_per_param"] == pytest.approx(3 / 8, rel=1e-3)
    flat = wire_bytes_per_param(n, world_size=32, wire="packed_allgather")
    assert acct["dcn_bytes_per_step"] < flat["bytes_per_step"] / 32
    # composed with vote_every both legs are divided by K
    lazy = wire_bytes_per_param(n, world_size=32, wire="hier:8", vote_every=8)
    assert lazy["dcn_bits_per_param"] == pytest.approx(3 / 64, rel=1e-2)
    assert lazy["bytes_per_step"] == pytest.approx(acct["bytes_per_step"] / 8,
                                                   rel=1e-2)
    with pytest.raises(ValueError, match="divide"):
        wire_bytes_per_param(n, world_size=32, wire="hier:5")


def test_train_step_with_hier_wire():
    """End-to-end: vote-Lion training over dp=8 with the hier wire — loss
    goes down and replicas stay bit-identical."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    mesh = make_mesh(data=W)
    model_cfg = GPT2Config.tiny()
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=3e-3, warmup_steps=2,
        max_steps=24, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=32, logging_steps=4,
        eval_steps=1000, save_steps=1000, wire="hier:4", output_dir=None,
    )
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    blocks = synthetic_lm_dataset(512, cfg.block_size, model_cfg.vocab_size, seed=3)
    it = batch_iterator(blocks, trainer.global_train_batch(), seed=0)
    history = trainer.train(it, max_steps=24)
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses[-1] < losses[0]
    # replicated params must remain bit-identical across all 8 devices
    leaf = trainer.params["wte"]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    trainer.close()
