"""Crash-resume equivalence (ISSUE 3 satellite): a run killed between a
save and the next step, then resumed from the checkpoint, must produce
BIT-identical losses and elections vs. an uninterrupted run — across
``vote_buckets`` {1, 4} × deterministic/stochastic binarization.

Bitwise parameter + momentum equality is the strongest form of "elected
signs identical": Lion's update is sign-valued, so any differing election
would move some parameter by ±2·lr·step and break exact equality. The
``vote_every=4`` leg additionally compares the packed elected-sign cache
itself bit-for-bit."""

import numpy as np
import pytest

import jax

from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
from distributed_lion_tpu.models.gpt2 import GPT2Config
from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train.loop import TrainConfig, Trainer


def _cfg(outdir, steps, **kw):
    base = dict(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=steps, per_device_train_batch_size=1,
        gradient_accumulation_steps=1, block_size=32, logging_steps=1,
        save_steps=2, output_dir=outdir, seed=5,
    )
    base.update(kw)
    return TrainConfig(**base)


def _run(cfg, mesh, model, blocks):
    t = Trainer.for_gpt2(cfg, mesh, model, seed=3)
    h = t.train(batch_iterator(blocks, t.global_train_batch(), seed=5))
    return t, [x["loss"] for x in h if "loss" in x]


def _assert_resumed_matches(tmp_path, mesh, model, blocks, **kw):
    out = str(tmp_path / "run")

    t_ref, ref_losses = _run(_cfg(None, 4, **kw), mesh, model, blocks)
    ref_params = jax.device_get(t_ref.params)
    ref_mom = jax.device_get(t_ref.state.exp_avg)
    ref_elected = (None if t_ref.state.elected is None
                   else np.asarray(jax.device_get(t_ref.state.elected)))
    ref_ring = (None if t_ref.state.dcn_ring is None
                else np.asarray(jax.device_get(t_ref.state.dcn_ring)))
    t_ref.close()

    # interrupted run: checkpoint at step 2, then 'killed' between the save
    # and the next step (the loop never dispatches step 3)
    t1, part1 = _run(_cfg(out, 2, **kw), mesh, model, blocks)
    t1.close()

    t2 = Trainer.for_gpt2(_cfg(out, 4, **kw), mesh, model, seed=3)
    assert t2.step_count == 2
    h2 = t2.train(batch_iterator(blocks, t2.global_train_batch(), seed=5))
    part2 = [x["loss"] for x in h2 if "loss" in x]
    got_params = jax.device_get(t2.params)
    got_mom = jax.device_get(t2.state.exp_avg)
    got_elected = (None if t2.state.elected is None
                   else np.asarray(jax.device_get(t2.state.elected)))
    got_ring = (None if t2.state.dcn_ring is None
                else np.asarray(jax.device_get(t2.state.dcn_ring)))
    t2.close()

    np.testing.assert_array_equal(part1 + part2, ref_losses)
    jax.tree.map(np.testing.assert_array_equal, got_params, ref_params)
    jax.tree.map(np.testing.assert_array_equal, got_mom, ref_mom)
    if ref_elected is not None:
        np.testing.assert_array_equal(got_elected, ref_elected)
    if ref_ring is not None:
        np.testing.assert_array_equal(got_ring, ref_ring)


@pytest.mark.parametrize("stoch", [False, True], ids=["det", "stoch"])
@pytest.mark.parametrize("buckets", [1, 4])
def test_crash_resume_bit_identical(tmp_path, buckets, stoch):
    mesh = make_mesh(data=8)
    model = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)
    kw = {"vote_buckets": buckets}
    if stoch:
        kw["max_grad_norm"] = 1.0
    _assert_resumed_matches(tmp_path, mesh, model, blocks, **kw)


def test_crash_resume_guard_bit_identical(tmp_path):
    """Vote guard (ISSUE 5 satellite): with --vote_guard enforce the health
    mask and the per-worker prev-ballot cache are live state across the
    interruption — crash-resume equivalence must stay bit-identical with
    the guard on (all-healthy run; the masked-election path is compiled
    in)."""
    mesh = make_mesh(data=8)
    model = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)
    _assert_resumed_matches(tmp_path, mesh, model, blocks,
                            vote_guard="enforce", vote_buckets=4)


def test_crash_resume_lazy_elected_cache_bit_identical(tmp_path):
    """vote_every=4: the packed elected-sign cache is live state across the
    interruption — stale signs applied on non-vote steps must come from the
    restored cache, pinned bit-for-bit against the uninterrupted run."""
    mesh = make_mesh(data=8)
    model = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)
    _assert_resumed_matches(tmp_path, mesh, model, blocks, vote_every=4)


def test_crash_resume_dcn_ring_mid_flight_bit_identical(tmp_path):
    """ISSUE 8 satellite: hier wire at dcn_pipeline_depth=2, killed at
    step 2 — the ring holds the IN-FLIGHT level-2 tallies of steps 0 and 1,
    neither yet consumed. The resumed run's steps 3/4 consume tallies
    launched on the other side of the crash; losses, params, momenta and
    the ring itself must stay bit-identical to the uninterrupted run."""
    mesh = make_mesh(data=8)
    model = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)
    _assert_resumed_matches(tmp_path, mesh, model, blocks, wire="hier:4",
                            dcn_pipeline_depth=2)


def test_resume_depth_toggle_errors_loudly(tmp_path):
    """A checkpoint written at one --dcn_pipeline_depth must refuse to
    restore at another: the ring's slot count IS the staleness semantics —
    there is no meaning-preserving reshape — and silently reinitializing
    it would drop in-flight elections."""
    mesh = make_mesh(data=8)
    model = GPT2Config.tiny()
    blocks = synthetic_lm_dataset(64, 32, model.vocab_size, seed=1)
    out = str(tmp_path / "run")
    t1, _ = _run(_cfg(out, 2, wire="hier:4", dcn_pipeline_depth=2),
                 mesh, model, blocks)
    t1.close()
    for other in (0, 1):
        with pytest.raises(ValueError, match="dcn_pipeline_depth"):
            Trainer.for_gpt2(
                _cfg(out, 4, wire="hier:4", dcn_pipeline_depth=other),
                mesh, model, seed=3)
