"""Expert-parallel MoE: routing parity, all_to_all dispatch, capacity drops.

Invariants: the sharded (all_to_all) path equals the single-device path
token-for-token when capacity is not binding; overflowed tokens produce
zero output (residual carries them); the load-balance aux loss is ~1 at
uniform routing; everything is differentiable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from distributed_lion_tpu.parallel.expert import (
    capacity,
    moe_ffn,
    moe_init,
    moe_param_specs,
)

E, D, F = 8, 6, 12
EP = 4  # expert shards


@pytest.fixture(scope="module")
def params():
    return moe_init(jax.random.key(0), E, D, F)


@pytest.fixture(scope="module")
def ep_mesh():
    return Mesh(np.array(jax.devices()[:EP]), ("expert",))


def _dense_reference(params, x):
    """Per-token: route to argmax expert, y = p * FFN_e(x) (no capacity)."""
    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    p = jnp.take_along_axis(probs, idx[:, None], -1)[:, 0]
    h = jax.nn.gelu(
        jnp.einsum("nd,ndf->nf", x, params["w_in"][idx]) + params["b_in"][idx]
    )
    return p[:, None] * (
        jnp.einsum("nf,nfd->nd", h, params["w_out"][idx]) + params["b_out"][idx]
    )


def test_single_device_matches_dense_reference(params):
    x = jax.random.normal(jax.random.key(1), (32, D))
    y, aux = moe_ffn(params, x, capacity_factor=E * 1.0, axis_name=None)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_dense_reference(params, x)), rtol=1e-5, atol=1e-6
    )
    assert float(aux) > 0


def test_expert_parallel_matches_single_device(params, ep_mesh):
    """Tokens sharded over the expert axis + experts sharded: the two
    all_to_alls must reproduce the single-device routing exactly (capacity
    slack so per-shard drops can't differ)."""
    n_total = 64
    x = jax.random.normal(jax.random.key(2), (n_total, D))
    cf = float(E)  # capacity == n_local: nothing can drop

    def body(p, x_local):
        y, aux = moe_ffn(p, x_local, capacity_factor=cf, axis_name="expert")
        return y, aux[None]

    specs = moe_param_specs()
    y_sharded, _ = shard_map(
        body, mesh=ep_mesh, in_specs=(specs, P("expert")),
        out_specs=(P("expert"), P("expert")),
    )(params, x)

    y_single, _ = moe_ffn(params, x, capacity_factor=cf, axis_name=None)
    np.testing.assert_allclose(
        np.asarray(y_sharded), np.asarray(y_single), rtol=1e-4, atol=1e-5
    )


def test_capacity_drops_zero_out_tokens(params):
    x = jax.random.normal(jax.random.key(3), (64, D))
    y_full, _ = moe_ffn(params, x, capacity_factor=float(E), axis_name=None)
    y_tight, _ = moe_ffn(params, x, capacity_factor=0.25, axis_name=None)
    # tight capacity: some tokens dropped (zero rows), none invented
    dropped = np.all(np.asarray(y_tight) == 0, axis=-1)
    assert dropped.any()
    kept = ~dropped
    np.testing.assert_allclose(
        np.asarray(y_tight)[kept], np.asarray(y_full)[kept], rtol=1e-5, atol=1e-6
    )


def test_aux_loss_near_one_for_uniform_routing():
    # a zero gate routes every token to expert 0 -> aux = E * (1 * 1/E) ...
    # uniform probs but argmax collapses; instead use random gate over many
    # tokens: frac_tokens ~ 1/E, frac_probs ~ 1/E -> aux ~ 1
    params = moe_init(jax.random.key(7), E, D, F)
    x = jax.random.normal(jax.random.key(8), (4096, D)) * 5.0
    _, aux = moe_ffn(params, x, axis_name=None)
    assert 0.8 < float(aux) < 1.6


def test_differentiable(params):
    x = jax.random.normal(jax.random.key(9), (16, D))

    def loss(p):
        y, aux = moe_ffn(p, x, axis_name=None)
        return jnp.sum(y**2) + 0.01 * aux

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
    assert float(jnp.abs(g["gate"]).sum()) > 0


def test_capacity_formula():
    assert capacity(64, 8, 1.0) == 8
    assert capacity(64, 8, 1.25) == 10
    assert capacity(3, 8, 1.0) == 1  # floor at 1


def test_bf16_routing_no_slot_collisions():
    """bf16 cumsum can't count past 256 — routing must stay exact in int32.

    Regression: with bf16 activations and >256 tokens on one expert, a
    bf16 cumsum collides ranks and silently sums tokens into shared
    dispatch slots. Routing must match the float32 reference exactly.
    """
    n = 1024
    params = moe_init(jax.random.key(11), E, D, F, dtype=jnp.bfloat16)
    # strong gate bias: most tokens land on one expert (>256 local tokens)
    x = jax.random.normal(jax.random.key(12), (n, D), jnp.bfloat16)
    params["gate"] = params["gate"].at[:, 0].add(5.0)

    y16, _ = moe_ffn(params, x, capacity_factor=float(E), axis_name=None)
    p32 = jax.tree.map(lambda a: a.astype(jnp.float32), params)
    y32, _ = moe_ffn(p32, x.astype(jnp.float32), capacity_factor=float(E),
                     axis_name=None)
    # no dropped-vs-kept disagreement and no summed-slot corruption:
    # bf16 output tracks the float32 reference within bf16 tolerance
    np.testing.assert_allclose(
        np.asarray(y16, np.float32), np.asarray(y32), rtol=0.1, atol=0.1
    )
    # every dispatch slot holds at most one token
    logits = x.astype(jnp.float32) @ p32["gate"]
    idx = np.asarray(jnp.argmax(jax.nn.softmax(logits, -1), -1))
    assert (np.bincount(idx, minlength=E) > 256).any()  # premise holds
