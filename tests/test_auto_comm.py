"""Multi-chip default recipe: the comm sentinels (wire='auto',
vote_every=0) must resolve to the measured minimum-byte wire (packed_a2a
on big dp meshes) with the reference's STRICT every-step vote — lazy
vote_every is opt-in until the full-scale parity:lazy leg passes the
pre-registered criterion (check_evidence parity:lazy; the round-4 lazy
auto-default claimed runs/parity evidence that was never captured —
VERDICT weak #1). The recipe itself lives in ONE place,
train/loop.resolve_auto_comm; these tests pin its decision matrix and that
the Trainer applies it end to end."""

import jax
import numpy as np
import pytest

from distributed_lion_tpu.parallel.mesh import make_mesh
from distributed_lion_tpu.train.loop import (
    AUTO_BUCKET_MIN_COORDS,
    AUTO_LAZY_MIN_PARAMS,
    TrainConfig,
    Trainer,
    resolve_auto_comm,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8, devices=jax.devices()[:8])


def test_big_replicated_dp_gets_budget_recipe(mesh8):
    r = resolve_auto_comm(TrainConfig(), mesh8, 124_000_000,
                          params_replicated=True)
    # strict every-step voting until parity:lazy PASSES (lazy is opt-in)
    assert (r.wire, r.vote_every) == ("packed_a2a", 1)
    # and the full 124M-coordinate per-step ballot is big enough for the
    # pipelined (bucketed) wire — tests/test_vote_buckets.py pins the rest
    assert r.vote_buckets == 4


def test_tiny_ballot_keeps_strict_vote(mesh8):
    r = resolve_auto_comm(TrainConfig(), mesh8, AUTO_LAZY_MIN_PARAMS - 1,
                          params_replicated=True)
    assert (r.wire, r.vote_every) == ("packed_a2a", 1)


def test_sharded_params_keep_strict_vote(mesh8):
    """tp/pp/ep-sharded params make the lazy elected-sign cache unsound
    (per-rank ballots over different local shards) — auto must not pick
    vote_every > 1 there, whatever the lazy default becomes once
    parity:lazy evidence lands."""
    r = resolve_auto_comm(TrainConfig(), mesh8, 124_000_000,
                          params_replicated=False)
    assert r.vote_every == 1


def test_world_one_is_silent(mesh8):
    mesh1 = make_mesh(data=1, devices=jax.devices()[:1])
    r = resolve_auto_comm(TrainConfig(), mesh1, 124_000_000,
                          params_replicated=True)
    assert (r.wire, r.vote_every) == ("sign_psum", 1)


def test_explicit_choice_is_never_overridden(mesh8):
    cfg = TrainConfig(wire="sign_psum", vote_every=1, vote_buckets=1)
    assert resolve_auto_comm(cfg, mesh8, 124_000_000, True) is cfg
    # explicit wire/cadence with the buckets sentinel still resolvable:
    # only vote_buckets may change
    part = TrainConfig(wire="sign_psum", vote_every=1)
    r = resolve_auto_comm(part, mesh8, 124_000_000, True)
    assert (r.wire, r.vote_every, r.vote_buckets) == ("sign_psum", 1, 4)


def test_vote_buckets_threshold_boundary(mesh8):
    """The bucketed-wire auto threshold is judged on the PER-STEP ballot
    slice, exactly at AUTO_BUCKET_MIN_COORDS: at the boundary the pipeline
    arms (4 buckets), one coordinate below it stays monolithic."""
    base = dict(wire="packed_a2a", vote_every=1)
    at = resolve_auto_comm(TrainConfig(**base), mesh8,
                           AUTO_BUCKET_MIN_COORDS, params_replicated=True)
    assert at.vote_buckets == 4
    below = resolve_auto_comm(TrainConfig(**base), mesh8,
                              AUTO_BUCKET_MIN_COORDS - 1,
                              params_replicated=True)
    assert below.vote_buckets == 1


def test_vote_buckets_threshold_counts_lazy_slice(mesh8):
    """Under vote_every=K only 1/K of the ballot rides the wire per step —
    the bucket decision follows the slice (codec.vote_chunk_elems), not
    the full ballot: a 4x-threshold ballot at K=4 sits exactly at the
    boundary; 32 coordinates fewer drops the slice below it."""
    base = dict(wire="packed_a2a", vote_every=4)
    at = resolve_auto_comm(TrainConfig(**base), mesh8,
                           4 * AUTO_BUCKET_MIN_COORDS,
                           params_replicated=True)
    assert at.vote_buckets == 4
    below = resolve_auto_comm(TrainConfig(**base), mesh8,
                              4 * AUTO_BUCKET_MIN_COORDS - 32,
                              params_replicated=True)
    assert below.vote_buckets == 1


def test_vote_buckets_world_one_stays_monolithic():
    """W=1 has no wire to pipeline: even an enormous ballot keeps the
    single-collective graph."""
    mesh1 = make_mesh(data=1, devices=jax.devices()[:1])
    r = resolve_auto_comm(
        TrainConfig(wire="sign_psum", vote_every=1), mesh1,
        10 * AUTO_BUCKET_MIN_COORDS, params_replicated=True)
    assert r.vote_buckets == 1


def test_explicit_vote_buckets_one_is_preserved(mesh8):
    """--vote_buckets 1 is an operator decision, not a sentinel: auto must
    never re-bucket it however large the ballot."""
    cfg = TrainConfig(wire="packed_a2a", vote_every=1, vote_buckets=1)
    assert resolve_auto_comm(cfg, mesh8, 10 * AUTO_BUCKET_MIN_COORDS,
                             params_replicated=True) is cfg


def test_trainer_resolves_and_steps_with_auto_recipe(mesh8):
    """End to end: a Trainer built with default comm fields on a dp=8 mesh
    resolves to the budget wire and completes a train step (the same leg
    __graft_entry__._dryrun_auto_budget runs for the driver)."""
    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config

    model_cfg = GPT2Config.tiny(vocab_size=2048, n_layer=2, n_head=8,
                                d_model=768, n_ctx=64)
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=1e-3, warmup_steps=1,
        max_steps=1, per_device_train_batch_size=1,
        gradient_accumulation_steps=1, block_size=64, logging_steps=1,
        output_dir=None,
    )
    tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
    assert tr.n_params >= AUTO_LAZY_MIN_PARAMS
    assert (tr.cfg.wire, tr.cfg.vote_every) == ("packed_a2a", 1)
    blocks = synthetic_lm_dataset(max(64, tr.global_train_batch()),
                                  cfg.block_size, model_cfg.vocab_size)
    hist = tr.train(batch_iterator(blocks, tr.global_train_batch(), seed=0),
                    max_steps=1)
    tr.close()
    assert np.isfinite([h["loss"] for h in hist if "loss" in h]).all()


def test_make_optimizer_degrades_sentinels_strict():
    """Standalone make_optimizer callers (no mesh in the signature) get the
    reference's strict semantics from an unresolved cfg, not a crash."""
    from distributed_lion_tpu.train.loop import make_optimizer

    make_optimizer(TrainConfig())  # wire='auto', vote_every=0 must not raise


def test_resolve_dropout_family_defaults():
    from distributed_lion_tpu.cli.run_clm import resolve_dropout

    assert resolve_dropout(None, "gpt2", 1) == 0.1
    assert resolve_dropout(None, "llama", 1) == 0.0
    assert resolve_dropout(None, "gpt2", 2) == 0.0  # pp: unsupported
    # sp skips attention-prob dropout — 0.1 would silently be a different
    # regularizer than the HF default, so auto stays off there
    assert resolve_dropout(None, "gpt2", 1, sp=2) == 0.0
    assert resolve_dropout(0.1, "gpt2", 1, sp=2) == 0.1  # explicit opt-in
    assert resolve_dropout(0.0, "gpt2", 1) == 0.0   # explicit opt-out wins
    assert resolve_dropout(0.3, "gpt2", 1) == 0.3


def test_multihost_hier_groups_are_data_rows_per_host(monkeypatch):
    """code-review r4: hier's subgroups must be whole DATA rows sharing a
    host. data is the slowest mesh axis, so a host of L devices holds
    L // inner data rows (inner = product of model axes) — grouping by
    local_device_count alone would straddle hosts whenever inner > 1."""
    from distributed_lion_tpu.train import loop as L

    monkeypatch.setattr(L.jax, "process_count", lambda: 2)
    monkeypatch.setattr(L.jax, "local_device_count", lambda: 4)

    # dp=4 x sp=2 over 8 'devices', 2 'hosts' of 4: each host holds 2 whole
    # data rows -> hier:2, not hier:4
    mesh = make_mesh(data=4, seq=2, devices=jax.devices()[:8])
    r = resolve_auto_comm(TrainConfig(), mesh, 124_000_000,
                          params_replicated=True)
    assert r.wire == "hier:2"

    # dp=2 x tensor=2 x seq=2: inner=4 == local -> 1 data row per host,
    # no intact ICI subgroup -> fall back to the flat sub-2-bit wire
    mesh = make_mesh(data=2, tensor=2, seq=2, devices=jax.devices()[:8])
    r = resolve_auto_comm(TrainConfig(), mesh, 124_000_000,
                          params_replicated=False)
    assert r.wire == "packed_a2a"

    # pure dp over 2 hosts: groups = all 4 local devices
    mesh = make_mesh(data=8, devices=jax.devices()[:8])
    r = resolve_auto_comm(TrainConfig(), mesh, 124_000_000,
                          params_replicated=True)
    assert r.wire == "hier:4"
