"""MXU-aligned vocab padding (models/gpt2 ``vocab_pad_multiple``).

Padding the embedding table is a pure LAYOUT choice: the pad rows/columns
must be invisible to every consumer — dense loss, chunked CE, tp_vocab CE,
generation — and must receive zero loss gradient so local Lion leaves them
at exactly their zero init. These tests pin that equivalence against the
unpadded model bit-for-bit where the math allows it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_apply,
    gpt2_decode,
    gpt2_init,
    gpt2_init_cache,
)
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
from distributed_lion_tpu.ops.xent import (
    chunked_clm_loss_and_metrics,
    chunked_softmax_xent,
)

V, PAD_M = 250, 64  # padded_vocab = 256


def _cfgs():
    plain = GPT2Config.tiny(vocab_size=V)
    padded = GPT2Config.tiny(vocab_size=V, vocab_pad_multiple=PAD_M)
    return plain, padded


def test_padded_vocab_property():
    plain, padded = _cfgs()
    assert plain.padded_vocab == V
    assert padded.padded_vocab == 256
    assert GPT2Config.tiny(vocab_size=256,
                           vocab_pad_multiple=64).padded_vocab == 256
    with pytest.raises(ValueError):
        GPT2Config.tiny(vocab_pad_multiple=-1)


def test_init_pads_with_zero_rows_same_draw():
    plain, padded = _cfgs()
    key = jax.random.key(7)
    p0, p1 = gpt2_init(key, plain), gpt2_init(key, padded)
    assert p1["wte"].shape == (256, plain.d_model)
    np.testing.assert_array_equal(p0["wte"], p1["wte"][:V])
    np.testing.assert_array_equal(p1["wte"][V:], 0.0)


def test_apply_logits_exact_vs_unpadded():
    plain, padded = _cfgs()
    key = jax.random.key(7)
    p0, p1 = gpt2_init(key, plain), gpt2_init(key, padded)
    tok = jax.random.randint(jax.random.key(1), (2, 16), 0, V)
    l0 = gpt2_apply(p0, tok, plain)
    l1 = gpt2_apply(p1, tok, padded)
    assert l1.shape == l0.shape == (2, 16, V)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), atol=1e-5)


def test_pad_rows_do_not_leak_even_when_nonzero():
    """Vote-Lion's tie→−1 walks zero-grad rows; junk pad values must stay
    invisible to logits/loss (they are sliced/masked, not trusted-zero)."""
    _, padded = _cfgs()
    p = gpt2_init(jax.random.key(7), padded)
    junk = p["wte"].at[V:].set(37.0)
    tok = jax.random.randint(jax.random.key(1), (2, 16), 0, V)
    l_clean = gpt2_apply(p, tok, padded)
    l_junk = gpt2_apply({**p, "wte": junk}, tok, padded)
    np.testing.assert_array_equal(np.asarray(l_clean), np.asarray(l_junk))
    loss_c, _ = chunked_clm_loss_and_metrics(
        jax.random.normal(jax.random.key(2), (2, 16, padded.d_model)),
        junk, tok, n_chunks=4, valid_v=V)
    loss_u, _ = chunked_clm_loss_and_metrics(
        jax.random.normal(jax.random.key(2), (2, 16, padded.d_model)),
        junk[:V], tok, n_chunks=4)
    np.testing.assert_allclose(float(loss_c), float(loss_u), atol=1e-6)


def test_chunked_xent_valid_v_matches_dense_and_zero_pad_grad():
    d, n = 32, 12
    key = jax.random.key(3)
    hidden = jax.random.normal(key, (n, d))
    emb = jax.random.normal(jax.random.key(4), (256, d))
    emb = emb.at[V:].set(0.0)
    labels = jax.random.randint(jax.random.key(5), (n,), 0, V)

    def loss_pad(e):
        nll, _ = chunked_softmax_xent(hidden, e, labels, n_chunks=4, valid_v=V)
        return nll.mean()

    def loss_dense(e):
        logp = jax.nn.log_softmax(hidden @ e[:V].T, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], 1).mean()

    np.testing.assert_allclose(float(loss_pad(emb)), float(loss_dense(emb)),
                               rtol=1e-6)
    g_pad = jax.grad(loss_pad)(emb)
    g_dense = jax.grad(loss_dense)(emb)
    np.testing.assert_array_equal(np.asarray(g_pad[V:]), 0.0)
    np.testing.assert_allclose(np.asarray(g_pad[:V]), np.asarray(g_dense[:V]),
                               atol=1e-5)


def test_chunked_xent_whole_chunk_masked():
    # pad spans entire chunks: v=256 over 8 chunks of 32, valid 100 → chunks
    # 4..7 fully masked; the -inf carry guards must hold
    d, n = 16, 6
    hidden = jax.random.normal(jax.random.key(0), (n, d))
    emb = jax.random.normal(jax.random.key(1), (256, d))
    labels = jnp.arange(n, dtype=jnp.int32)
    nll, correct = chunked_softmax_xent(hidden, emb, labels, n_chunks=8,
                                        valid_v=100)
    logp = jax.nn.log_softmax(hidden @ emb[:100].T, axis=-1)
    ref = -jnp.take_along_axis(logp, labels[:, None], 1)[:, 0]
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref), rtol=1e-5)
    assert np.isfinite(np.asarray(nll)).all()


def test_decode_matches_apply_with_padding():
    _, padded = _cfgs()
    p = gpt2_init(jax.random.key(7), padded)
    tok = jax.random.randint(jax.random.key(1), (1, 12), 0, V)
    full = gpt2_apply(p, tok, padded)
    cache = gpt2_init_cache(padded, 1, 12)
    dec, _ = gpt2_decode(p, tok, padded, cache, 0)
    assert dec.shape[-1] == V
    np.testing.assert_allclose(np.asarray(full), np.asarray(dec), atol=2e-4)


def test_trainer_padded_equals_unpadded_trajectory():
    """Full vote-Lion training on the dp mesh: padded and unpadded configs
    produce the same loss stream (chunked CE path, the flagship's)."""
    import dataclasses

    from distributed_lion_tpu.data.sources import batch_iterator, synthetic_lm_dataset
    from distributed_lion_tpu.parallel import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    plain, padded = _cfgs()
    plain = dataclasses.replace(plain, remat=False, n_ctx=32)
    padded = dataclasses.replace(padded, remat=False, n_ctx=32)
    mesh = make_mesh(data=8)
    losses = {}
    for name, mc in (("plain", plain), ("padded", padded)):
        cfg = TrainConfig(
            lion=True, async_grad=True, learning_rate=1e-3, weight_decay=0.1,
            warmup_steps=0, max_steps=8, per_device_train_batch_size=1,
            gradient_accumulation_steps=1, block_size=32,
            logging_steps=1, eval_steps=1000, save_steps=1000,
            output_dir=None, vocab_chunks=4, seed=11,
        )
        trainer = Trainer.for_gpt2(cfg, mesh, mc, seed=11)
        blocks = synthetic_lm_dataset(128, 32, V, seed=0)
        it = batch_iterator(blocks, trainer.global_train_batch(), seed=0)
        history = trainer.train(it, max_steps=8)
        losses[name] = [h["loss"] for h in history if "loss" in h]
        trainer.close()
    # step 1 (pre-update) pins exact masking: an unmasked pad column would
    # shift the lse by ~log(256/250) ≈ 0.024. Later steps tolerate the fp
    # noise Lion's sign amplifies (chunk boundaries differ: ceil(250/4) vs
    # 256/4) but stay well under that bug-sized shift.
    np.testing.assert_allclose(losses["plain"][0], losses["padded"][0],
                               atol=1e-5)
    np.testing.assert_allclose(losses["plain"], losses["padded"], atol=8e-3)


def test_hf_export_slices_pad_rows(tmp_path):
    """gpt2_to_hf writes the TRUE-vocab table: the MXU pad rows never leak
    into the HF checkpoint (which must round-trip into transformers)."""
    from distributed_lion_tpu.models.hf_export import gpt2_to_hf

    _, padded = _cfgs()
    p = gpt2_init(jax.random.key(7), padded)
    out = str(tmp_path / "export")
    gpt2_to_hf(p, padded, out)
    import json
    import os

    import numpy as _np

    from safetensors.numpy import load_file

    sd = load_file(os.path.join(out, "model.safetensors"))
    assert sd["transformer.wte.weight"].shape[0] == V
    with open(os.path.join(out, "config.json")) as f:
        assert json.load(f)["vocab_size"] == V
    _np.testing.assert_array_equal(sd["transformer.wte.weight"],
                                   _np.asarray(p["wte"][:V], _np.float32))
