"""Bucketed, overlapped vote wire (the software-pipelined ballot collective).

The tentpole contract, pinned here: splitting the ballot into
``vote_buckets`` wire-aligned chunks and voting each with its own collective
(so bucket k's wire can ride behind bucket k−1's fused apply) changes WHEN
bytes move, never what is elected or how many bytes ship —

- params AND momentum are bit-identical to the monolithic vote for all four
  wires × {deterministic, stochastic} × vote_every ∈ {1, 4} on the 8-device
  CPU mesh;
- the summed per-bucket byte accounting equals the unbucketed totals exactly
  (and stays zero at world=1, commit 3d77603);
- the Pallas window path (offset-window kernels over shared per-leaf flat
  buffers) matches the XLA path and preserves the elected-sign cache through
  ``_step_pallas`` (the state-pass-through invariant).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.ops.codec import (
    bucket_alignment,
    bucket_bounds,
    wire_bytes_per_param,
)
from distributed_lion_tpu.optim import (
    distributed_lion,
    expand_worker_state,
    init_global_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.distributed_lion import _bucket_windows
from distributed_lion_tpu.optim.lion import LionState
from distributed_lion_tpu.parallel import collectives
from distributed_lion_tpu.parallel.mesh import make_mesh

WIRES = ["sign_psum", "packed_allgather", "packed_a2a", "hier:4"]


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(data=8)


# --------------------------------------------------------------- bounds math
@pytest.mark.parametrize("wire", WIRES)
@pytest.mark.parametrize("n", [1, 7, 64, 1000, 4096, 12345])
@pytest.mark.parametrize("buckets", [1, 2, 3, 4, 16, 64])
def test_bucket_bounds_tile_exactly(wire, n, buckets):
    bounds = bucket_bounds(n, buckets, 8, wire)
    assert len(bounds) <= max(buckets, 1)
    align = bucket_alignment(8, wire)
    off = 0
    for i, (start, size) in enumerate(bounds):
        assert start == off and size > 0
        if i < len(bounds) - 1:
            assert size % align == 0
        off += size
    assert off == n


def test_bucket_windows_tile_leaves():
    """The optimizer's static window decomposition must tile every bucket
    with per-leaf windows in flat order, skipping zero-size leaves."""
    sizes = [5, 0, 11, 3]
    bounds = [(0, 8), (8, 8), (16, 3)]
    windows = _bucket_windows(bounds, sizes)
    flat = 0
    for (start, size), ws in zip(bounds, windows):
        boff = 0
        for leaf, loff, take, w_boff in ws:
            assert sizes[leaf] > 0 and take > 0
            assert w_boff == boff
            assert sum(sizes[:leaf]) + loff == flat
            flat += take
            boff += take
        assert boff == size
    assert flat == sum(sizes)


# ----------------------------------------------------------- byte accounting
@pytest.mark.parametrize("wire", WIRES)
@pytest.mark.parametrize("world", [4, 8, 16])
@pytest.mark.parametrize("vote_every", [1, 4])
def test_bucketed_accounting_equals_unbucketed(wire, world, vote_every):
    """Conservation: bucket boundaries are wire-aligned, so the summed
    per-bucket bytes are EXACTLY the monolithic vote's — for every wire,
    including hier's DCN leg, at ragged ballot sizes."""
    for n in (123_457, 1_000_003, 8 * world * 64):
        base = wire_bytes_per_param(n, world, wire, vote_every=vote_every)
        for buckets in (2, 3, 4, 16):
            acct = wire_bytes_per_param(n, world, wire,
                                        vote_every=vote_every,
                                        vote_buckets=buckets)
            assert acct["bytes_per_step"] == base["bytes_per_step"], (
                wire, world, n, buckets)
            assert acct["bits_per_param"] == base["bits_per_param"]
            if "dcn_bytes_per_step" in base:
                assert (acct["dcn_bytes_per_step"]
                        == base["dcn_bytes_per_step"])
            assert 0.0 < acct["overlappable_wire_frac"] < 1.0


@pytest.mark.parametrize("wire", ["sign_psum", "packed_allgather",
                                  "packed_a2a", "hier:1"])
def test_bucketed_world1_wire_bytes_stay_zero(wire):
    """W=1 short-circuits every wire — bucketing must not resurrect phantom
    traffic (or phantom overlap) on single-chip runs."""
    for buckets in (1, 4, 16):
        acct = wire_bytes_per_param(1000, 1, wire, vote_buckets=buckets)
        assert acct["bytes_per_step"] == 0
        assert acct["overlappable_wire_frac"] == 0.0


def test_comm_report_overlap_frac():
    from distributed_lion_tpu.train.profiling import comm_report

    rep = comm_report(10_000_000, 8, "sign_psum", vote_buckets=4)
    # 4 near-equal buckets → buckets[1:] carry ~3/4 of the wire
    assert abs(rep["comm_overlap_frac"] - 0.75) < 0.01
    assert rep["vote_buckets"] == 4
    assert comm_report(10_000_000, 8, "sign_psum")["comm_overlap_frac"] == 0.0


# ------------------------------------------------------ collective bit-parity
# Only the cheapest and the trickiest wire at this level: sign_psum (the
# default) and packed_a2a (per-worker chunk padding interacts with bucket
# boundaries). hier/packed_allgather bucket-parity is covered at the
# optimizer level by the full trajectory matrix below — repeating them here
# would re-pay hier's scan-ring compiles (~11s of tier-1 wall clock) for no
# new coverage.
@pytest.mark.parametrize("wire", ["sign_psum", "packed_a2a"])
def test_majority_vote_bucketed_bit_identical(mesh8, wire):
    """Collective level: the concatenated bucketed election equals the
    one-shot vote, at a ragged ballot size."""
    n = 1003
    rng = np.random.default_rng(11)
    ballots = jnp.asarray(rng.integers(0, 2, size=(8, n)).astype(bool))

    def run(vote_buckets):
        def body(b):
            return collectives.majority_vote_bucketed(
                b[0], "data", wire, vote_buckets)

        return np.asarray(shard_map(
            body, mesh=mesh8, in_specs=(P("data"),), out_specs=P(),
            check_vma=False,
        )(ballots))

    # one bucketed config suffices: 5 buckets of the 1003-coordinate ballot
    # exercise interior + ragged-tail chunks; each extra config is a fresh
    # shard_map compile (hier's scan rings are the slow ones) in tier-1
    np.testing.assert_array_equal(run(5), run(1))


# ------------------------------------------------------ optimizer bit-parity
def _run_steps(opt, params, grads_per_worker, n_steps, mesh, world,
               rng=None, has_elected=False):
    """Drive opt.step under shard_map for n_steps (test_vote_every idiom,
    extended with stochastic rng support)."""
    state = init_global_state(opt, params, world, rng=rng)
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(
        count=P(),
        exp_avg=jax.tree.map(lambda _: P("data"), state.exp_avg),
        rng=None if rng is None else P(),
        elected=P() if has_elected else None,
    )
    g_spec = jax.tree.map(lambda _: P("data"), grads_per_worker)

    @jax.jit
    def step(params, grads, state):
        def body(p, g, st):
            st = squeeze_worker_state(st)
            g = jax.tree.map(lambda x: x[0], g)
            p_new, st_new = opt.step(p, g, st)
            return p_new, expand_worker_state(st_new)

        return shard_map(
            body, mesh=mesh, in_specs=(p_spec, g_spec, st_spec),
            out_specs=(p_spec, st_spec), check_vma=False,
        )(params, grads, state)

    for _ in range(n_steps):
        params, state = step(params, grads_per_worker, state)
    return params, state


def _toy_problem(world=8, n=40):
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (n,)), "b": jnp.zeros((3,))}
    grads = {
        "w": jax.random.normal(jax.random.key(1), (world, n)),
        "b": jax.random.normal(jax.random.key(2), (world, 3)),
    }
    return params, grads


def _assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


@pytest.mark.parametrize("wire", WIRES)
@pytest.mark.parametrize("stochastic", [False, True],
                         ids=["deterministic", "stochastic"])
@pytest.mark.parametrize("vote_every", [1, 4])
def test_bucketed_trajectory_bit_identical(mesh8, wire, stochastic,
                                           vote_every):
    """The acceptance criterion: vote_buckets > 1 produces bit-identical
    params AND momentum to vote_buckets = 1 for every wire × binarization
    mode × vote cadence (the rotating 1/K slice votes bucket-wise too)."""
    params, grads = _toy_problem()
    kw = dict(learning_rate=0.01, weight_decay=0.01, wire=wire,
              vote_every=vote_every,
              max_grad_norm=1.0 if stochastic else None)
    rng = jax.random.key(7) if stochastic else None
    steps = 5 if vote_every > 1 else 3  # cover a full rotation + reuse
    runs = {}
    for buckets in (1, 3):
        opt = distributed_lion(vote_buckets=buckets, **kw)
        runs[buckets] = _run_steps(opt, params, grads, steps, mesh8, 8,
                                   rng=rng, has_elected=vote_every > 1)
    _assert_trees_equal(runs[1][0], runs[3][0])
    _assert_trees_equal(runs[1][1].exp_avg, runs[3][1].exp_avg)
    if vote_every > 1:
        np.testing.assert_array_equal(np.asarray(runs[1][1].elected),
                                      np.asarray(runs[3][1].elected))


@pytest.mark.parametrize("wire", ["sign_psum", "packed_a2a"])
def test_pallas_bucketed_equals_xla_monolithic(mesh8, wire):
    """The Pallas window path (offset-window kernels, bucket pipeline)
    must match the XLA path's monolithic vote bit-for-bit — the cross-check
    that the persistent flat-offset layout slices exactly the coordinates
    the flat concatenate used to."""
    params, grads = _toy_problem(n=300)  # spans several (8,128) windows
    results = []
    for kern, buckets in (("pallas", 4), ("pallas", 1), ("xla", 1)):
        opt = distributed_lion(learning_rate=0.02, weight_decay=0.05,
                               wire=wire, kernel=kern, vote_buckets=buckets)
        p, st = _run_steps(opt, params, grads, 3, mesh8, 8)
        results.append((p, st))
    for other in results[1:]:
        _assert_trees_equal(results[0][0], other[0])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            results[0][1].exp_avg, other[1].exp_avg)


def test_pallas_step_preserves_elected_cache(mesh8):
    """Satellite: _step_pallas used to rebuild LionState without ``elected``
    — harmless only because the Pallas gate requires vote_every == 1. The
    invariant is 'state passes through', pinned by smuggling a cache into a
    state the Pallas path consumes."""
    params, grads = _toy_problem(n=64)
    opt = distributed_lion(learning_rate=0.01, kernel="pallas",
                           vote_buckets=2)
    state = init_global_state(opt, params, 8)
    cache = jnp.arange(16, dtype=jnp.uint8)
    state = LionState(state.count, state.exp_avg, state.rng, cache)
    p_spec = jax.tree.map(lambda _: P(), params)
    st_spec = LionState(count=P(),
                        exp_avg=jax.tree.map(lambda _: P("data"),
                                             state.exp_avg),
                        rng=None, elected=P())
    g_spec = jax.tree.map(lambda _: P("data"), grads)

    def body(p, g, st):
        st = squeeze_worker_state(st)
        g = jax.tree.map(lambda x: x[0], g)
        p_new, st_new = opt.step(p, g, st)
        return p_new, expand_worker_state(st_new)

    _, new_state = jax.jit(shard_map(
        body, mesh=mesh8, in_specs=(p_spec, g_spec, st_spec),
        out_specs=(p_spec, st_spec), check_vma=False,
    ))(params, grads, state)
    np.testing.assert_array_equal(np.asarray(new_state.elected),
                                  np.asarray(cache))


# ----------------------------------------------------------- auto resolution
def test_resolve_auto_vote_buckets(mesh8):
    from distributed_lion_tpu.train.loop import (
        AUTO_BUCKET_MIN_COORDS,
        TrainConfig,
        resolve_auto_comm,
    )

    # big replicated dp ballot → pipelined wire
    r = resolve_auto_comm(TrainConfig(), mesh8, 124_000_000,
                          params_replicated=True)
    assert r.vote_buckets == 4
    # the per-step slice (n/4 under an EXPLICIT lazy vote — auto resolves
    # vote_every to strict 1 until parity:lazy passes) is what must clear
    # the threshold — just below it stays monolithic
    r = resolve_auto_comm(TrainConfig(vote_every=4), mesh8,
                          AUTO_BUCKET_MIN_COORDS * 4 - 64,
                          params_replicated=True)
    assert r.vote_every == 4 and r.vote_buckets == 1
    # W=1: no wire, nothing to pipeline
    mesh1 = make_mesh(data=1, devices=jax.devices()[:1])
    r = resolve_auto_comm(TrainConfig(), mesh1, 124_000_000,
                          params_replicated=True)
    assert r.vote_buckets == 1
    # explicit values always respected
    cfg = TrainConfig(wire="sign_psum", vote_every=1, vote_buckets=7)
    assert resolve_auto_comm(cfg, mesh8, 124_000_000, True) is cfg
    r = resolve_auto_comm(TrainConfig(vote_buckets=2), mesh8, 1000, True)
    assert r.vote_buckets == 2


def test_make_optimizer_degrades_bucket_sentinel():
    """Standalone make_optimizer callers (no mesh) get the monolithic vote
    from an unresolved vote_buckets=0, not a crash."""
    from distributed_lion_tpu.train.loop import TrainConfig, make_optimizer

    make_optimizer(TrainConfig())  # vote_buckets=0 must not raise


def test_vote_buckets_validation():
    with pytest.raises(ValueError):
        distributed_lion(vote_buckets=0)
    with pytest.raises(ValueError):
        bucket_bounds(100, 0, 8, "sign_psum")


def test_trainer_bucketed_step_end_to_end(mesh8):
    """Smoke: a Trainer with explicit vote_buckets completes a train step,
    logs the analytic comm_overlap_frac, and matches the vote_buckets=1
    trainer's loss exactly (same seed, same data)."""
    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    model_cfg = GPT2Config.tiny()
    losses = {}
    for buckets in (1, 4):
        cfg = TrainConfig(
            lion=True, async_grad=True, wire="packed_a2a", vote_every=1,
            vote_buckets=buckets, learning_rate=1e-3, warmup_steps=1,
            max_steps=2, per_device_train_batch_size=1,
            gradient_accumulation_steps=1, block_size=32, logging_steps=1,
            output_dir=None,
        )
        tr = Trainer.for_gpt2(cfg, mesh8, model_cfg)
        assert tr.cfg.vote_buckets == buckets
        blocks = synthetic_lm_dataset(max(32, tr.global_train_batch()), 32,
                                      model_cfg.vocab_size, seed=4)
        hist = tr.train(batch_iterator(blocks, tr.global_train_batch(),
                                       seed=0), max_steps=2)
        rows = [h for h in hist if "loss" in h]
        losses[buckets] = [h["loss"] for h in rows]
        frac = rows[-1]["comm_overlap_frac"]
        assert (frac == 0.0 if buckets == 1 else 0.5 < frac < 1.0)
        tr.close()
    assert losses[1] == losses[4]
