"""scripts/validate_metrics.py single-document artifact validation: the
REAL writers (telemetry.write_crash_bundle, checkpoint.write_manifest)
produce artifacts the validator accepts, and hand-broken variants — the
bare NaN token, missing required keys, a bogus digest — are rejected. One
validator for every JSON artifact the repo writes."""

import json
import os
import pathlib
import subprocess
import sys

import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "validate_metrics.py")


def _run(*paths):
    return subprocess.run([sys.executable, SCRIPT, *map(str, paths)],
                          capture_output=True, text=True)


def test_real_crash_bundle_validates(tmp_path):
    from distributed_lion_tpu.train.telemetry import write_crash_bundle

    params = {"w": jnp.array([1.0, float("nan")])}
    crash_dir = write_crash_bundle(
        str(tmp_path), 7, "non-finite loss=nan at step 7",
        {"lion": True, "learning_rate": 1e-4}, params, {"m": params["w"]},
        [{"step": 6, "loss": 2.5}])
    bundle = pathlib.Path(crash_dir) / "bundle.json"
    r = _run(bundle)
    assert r.returncode == 0, r.stdout


def test_real_manifest_validates(tmp_path):
    from distributed_lion_tpu.train.checkpoint import write_manifest

    sdir = tmp_path / "42"
    sdir.mkdir()
    (sdir / "leaf.bin").write_bytes(b"\x00" * 64)
    write_manifest(sdir, 42, meta={"world": 8, "tag": "periodic"})
    r = _run(sdir / "manifest.json")
    assert r.returncode == 0, r.stdout


def test_nan_token_rejected_in_doc(tmp_path):
    p = tmp_path / "bundle.json"
    p.write_text('{"step": 1, "reason": "x", "config": {}, "loss": NaN}\n')
    r = _run(p)
    assert r.returncode == 1 and "NaN" in r.stdout


def test_missing_required_keys_rejected(tmp_path):
    p = tmp_path / "bundle.json"
    p.write_text('{"step": 1}\n')
    r = _run(p)
    assert r.returncode == 1 and "reason" in r.stdout


def test_bad_manifest_digest_rejected(tmp_path):
    p = tmp_path / "manifest.json"
    p.write_text(json.dumps({
        "format": 1, "step": 3,
        "files": {"leaf.bin": {"sha256": "nothex", "bytes": 64}}}) + "\n")
    r = _run(p)
    assert r.returncode == 1 and "sha256" in r.stdout


def test_unknown_json_doc_still_strict(tmp_path):
    """Any other *.json gets the strict parse + object check, nothing
    more (no schema guessing)."""
    ok = tmp_path / "meta.json"
    ok.write_text('{"tokens": 123}\n')
    assert _run(ok).returncode == 0
    bad = tmp_path / "meta2.json"
    bad.write_text('{"v": Infinity}\n')
    assert _run(bad).returncode == 1


def test_mixed_jsonl_and_doc_arguments(tmp_path):
    jl = tmp_path / "metrics.jsonl"
    jl.write_text('{"step": 1, "loss": 2.0}\n')
    doc = tmp_path / "bundle.json"
    doc.write_text('{"step": 1, "reason": "r", "config": {}}\n')
    assert _run(jl, doc).returncode == 0


# ------------------------------------------------------- run-journal schema
def test_real_journal_file_validates(tmp_path):
    """The REAL writer (train/journal.Journal) produces files the journal
    schema accepts — meta anchor, spans with dur, events, log records."""
    from distributed_lion_tpu.train.journal import Journal

    j = Journal(str(tmp_path))
    with j.span("dispatch", step=1, steps=1):
        pass
    j.event("step_log", step=1)
    j.log("[trainer] hello")
    j.close()
    r = _run(tmp_path / "journal_rank0.jsonl")
    assert r.returncode == 0, r.stdout


def test_journal_schema_rejects_bad_records(tmp_path):
    """Journal JSONL gets the journal record schema, not the metrics one:
    a span without dur, an unknown kind, a missing rank, and a bare NaN
    token are each rejected."""
    cases = {
        "no_dur": '{"kind": "span", "name": "dispatch", "t": 1.0, "rank": 0}',
        "bad_kind": '{"kind": "frame", "name": "x", "t": 1.0, "rank": 0}',
        "no_rank": '{"kind": "event", "name": "x", "t": 1.0}',
        "nan": '{"kind": "event", "name": "x", "t": NaN, "rank": 0}',
    }
    for name, line in cases.items():
        p = tmp_path / f"journal_{name}.jsonl"
        # two lines so the bad one is never the tolerated torn-tail line
        p.write_text(line + "\n"
                     + '{"kind": "event", "name": "ok", "t": 2.0, "rank": 0}'
                     + "\n")
        assert _run(p).returncode == 1, name


def test_journal_torn_last_line_tolerated(tmp_path):
    p = tmp_path / "journal_rank0.jsonl"
    p.write_text('{"kind": "meta", "name": "journal_start", "t": 1.0, '
                 '"rank": 0, "wall": 5.0}\n{"kind": "span", "na')
    assert _run(p).returncode == 0
