"""SFT + DPO workload tests: packing semantics, DPO loss math, length
filtering, and tiny end-to-end CLI runs on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lion_tpu.data.dpo import dpo_batch_iterator, prepare_dpo_batch
from distributed_lion_tpu.data.sft import (
    chars_token_ratio,
    constant_length_batches,
    prepare_sample_text,
    synthetic_qa_pairs,
)
from distributed_lion_tpu.data.tokenizer import ByteTokenizer
from distributed_lion_tpu.train.dpo import make_dpo_loss_fn, sequence_logprob


def test_prepare_sample_text_template():
    s = prepare_sample_text({"question": "Q?", "response_j": "A."})
    assert s == "Question: Q?\n\nAnswer: A."


def test_chars_token_ratio_byte_tokenizer():
    # byte tokenizer: 1 token per char → ratio 1.0
    samples = synthetic_qa_pairs(10)
    assert chars_token_ratio(samples, ByteTokenizer()) == pytest.approx(1.0)


def test_constant_length_batches_shapes_and_content():
    tok = ByteTokenizer()
    samples = synthetic_qa_pairs(20)
    gen = constant_length_batches(samples, tok, seq_length=64, infinite=False,
                                  num_sequences_buffer=2)
    rows = list(gen)
    assert rows and all(r.shape == (64,) and r.dtype == np.int32 for r in rows)
    # EOS separators present in the stream
    assert any((r == tok.eos_id).any() for r in rows)


def test_constant_length_finite_drains_all_samples():
    # Regression: finite mode must emit (nearly) all tokens, not one buffer.
    tok = ByteTokenizer()
    samples = synthetic_qa_pairs(200)
    total = sum(len(tok.encode(prepare_sample_text(s))) + 1 for s in samples)
    rows = list(constant_length_batches(samples, tok, seq_length=32,
                                        infinite=False, num_sequences_buffer=2))
    emitted = 32 * len(rows)
    assert emitted > total - 32, f"only {emitted}/{total} tokens emitted"


def test_constant_length_infinite_cycles():
    tok = ByteTokenizer()
    gen = constant_length_batches(synthetic_qa_pairs(3), tok, seq_length=32,
                                  infinite=True, num_sequences_buffer=1)
    rows = [next(gen) for _ in range(50)]  # far more than one pass of 3 samples
    assert len(rows) == 50


def test_dpo_prepare_masks_and_filtering():
    tok = ByteTokenizer()
    recs = synthetic_qa_pairs(30)
    recs.append({"question": "x" * 600, "response_j": "a", "response_k": "b"})  # prompt too long
    data = prepare_dpo_batch(recs, tok, max_length=128, max_prompt_length=64)
    assert len(data["chosen"]) == 30  # the long-prompt record was filtered
    # masks cover only completion tokens: prompt prefix is False
    first_prompt_len = len(tok.encode("Question: "))
    assert not data["chosen_mask"][:, :first_prompt_len].any()
    assert data["chosen_mask"].any(axis=1).all()


def test_sequence_logprob_hand_check():
    # vocab 4, T=3; uniform logits → logprob = -ln(4) per masked label
    logits = jnp.zeros((1, 3, 4))
    tokens = jnp.asarray([[0, 1, 2]], jnp.int32)
    mask = jnp.asarray([[False, True, True]])
    lp = sequence_logprob(logits, tokens, mask)
    np.testing.assert_allclose(float(lp[0]), -2 * np.log(4), rtol=1e-5)


def test_dpo_loss_zero_at_init_and_direction():
    """Policy == ref → logits 0 → loss = ln 2; improving chosen lowers loss."""
    def apply_const(delta):
        def f(tokens):
            base = jnp.zeros((tokens.shape[0], tokens.shape[1], 4))
            return base.at[:, :, 1].add(delta)  # favor token 1
        return f

    batch = {
        "chosen": jnp.asarray([[0, 1, 1]], jnp.int32),
        "rejected": jnp.asarray([[0, 2, 2]], jnp.int32),
        "chosen_mask": jnp.ones((1, 3), bool),
        "rejected_mask": jnp.ones((1, 3), bool),
    }
    ref = apply_const(0.0)
    loss_fn_same = make_dpo_loss_fn(lambda p, t: ref(t), ref, beta=0.1)
    loss0, m0 = loss_fn_same(None, batch, None)
    np.testing.assert_allclose(float(loss0), np.log(2), rtol=1e-5)

    pol = apply_const(1.0)  # policy now prefers token 1 (the chosen one)
    loss_fn_better = make_dpo_loss_fn(lambda p, t: pol(t), ref, beta=0.1)
    loss1, m1 = loss_fn_better(None, batch, None)
    assert float(loss1) < float(loss0)
    assert float(m1["reward_margin"]) > 0


def test_sft_cli_smoke(tmp_path):
    from distributed_lion_tpu.cli.run_sft import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--num_train_samples", "64",
        "--size_valid_set", "16", "--seq_length", "64", "--quant", "int8",
        "--lion", "--async_grad", "--max_steps", "4", "--warmup_steps", "1",
        "--per_device_train_batch_size", "1", "--gradient_accumulation_steps", "1",
        "--logging_steps", "2", "--eval_steps", "1000", "--save_steps", "1000",
        "--learning_rate", "1e-3", "--eval_iters", "1",
        "--merged_output", str(tmp_path / "merged.npz"),
        "--output_dir", str(tmp_path / "sft_out"),
    ])
    assert (tmp_path / "merged.npz").exists()


def test_dpo_cli_smoke(tmp_path):
    from distributed_lion_tpu.cli.run_dpo import main

    main([
        "--model_name", "tiny", "--dataset", "synthetic", "--num_train_samples", "96",
        "--size_valid_set", "8", "--max_length", "96", "--max_prompt_length", "48",
        "--lion", "--async_grad", "--max_steps", "3", "--warmup_steps", "1",
        "--per_device_train_batch_size", "1", "--gradient_accumulation_steps", "1",
        "--logging_steps", "1", "--eval_steps", "1000", "--save_steps", "1000",
        "--learning_rate", "1e-3", "--eval_iters", "1",
        "--output_dir", str(tmp_path / "dpo_out"),
    ])
    assert (tmp_path / "dpo_out" / "metrics.jsonl").exists()


def test_guards_match_reference():
    from distributed_lion_tpu.cli.run_sft import main

    with pytest.raises(ValueError):
        main(["--packing", "--group_by_length", "--model_name", "tiny"])
    with pytest.raises(ValueError):
        main(["--gradient_checkpointing", "--model_name", "tiny"])


def test_padded_examples_nonpacked():
    """Non-packed SFT rows (VERDICT r1 missing #4): one example per row,
    EOS-terminated, padded, loss mask excluding padding; group_by_length
    sorts by true length."""
    from distributed_lion_tpu.data.sft import (
        padded_batch_iterator,
        padded_examples,
        synthetic_qa_pairs,
    )
    from distributed_lion_tpu.data.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    recs = synthetic_qa_pairs(12)
    tokens, mask = padded_examples(recs, tok, 64)
    assert tokens.shape == (12, 64) and mask.shape == (12, 64)
    # mask covers exactly the real tokens, none of the padding
    lengths = mask.sum(1).astype(int)
    for i, rec in enumerate(recs):
        from distributed_lion_tpu.data.sft import prepare_sample_text

        true_len = min(len(tok.encode(prepare_sample_text(rec))) + 1, 64)
        assert lengths[i] == true_len
        assert (tokens[i, lengths[i] - 1] == tok.eos_id) or lengths[i] == 64

    t2, m2 = padded_examples(recs, tok, 64, group_by_length=True)
    assert list(m2.sum(1)) == sorted(m2.sum(1))  # sorted by length

    it = padded_batch_iterator(tokens, mask, 4, seed=0)
    b = next(it)
    assert b["tokens"].shape == (4, 64) and b["mask"].shape == (4, 64)
