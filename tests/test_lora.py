"""LoRA tests: init identity, merge == wrapped apply, quantized base,
gradients flow only to adapters."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
from distributed_lion_tpu.models.lora import (
    LoraConfig,
    lora_apply_fn,
    lora_init,
    merge_lora,
)
from distributed_lion_tpu.ops.quant import quantize_tree


def _setup(quant=None):
    cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(0), cfg)
    if quant:
        base = quantize_tree(base, quant, min_size=1024)
    lcfg = LoraConfig(r=4, alpha=8)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    return cfg, base, lcfg, adapters


def test_adapters_target_q_and_v():
    cfg, base, lcfg, adapters = _setup()
    keys = set(adapters)
    assert all(k.endswith("wq") or k.endswith("wv") for k in keys)
    assert len(keys) == 2 * cfg.n_layer
    a = adapters["blocks/0/attn/wq"]
    assert a["A"].shape == (64, 4) and a["B"].shape == (4, 64)


def test_fresh_adapters_are_identity():
    cfg, base, lcfg, adapters = _setup()
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)),
        np.asarray(llama_apply(base, toks, cfg)),
        rtol=1e-5, atol=1e-5,
    )


def test_merge_matches_wrapped_apply():
    cfg, base, lcfg, adapters = _setup()
    # give the adapters nonzero B so the delta is real
    adapters = jax.tree.map(lambda x: x + 0.01, adapters)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    merged = merge_lora(base, adapters, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)),
        np.asarray(llama_apply(merged, toks, cfg)),
        rtol=2e-2, atol=2e-2,  # bf16 compute tolerance
    )


def test_quantized_base_trains_only_adapters():
    cfg, base, lcfg, adapters = _setup(quant="int8")
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 256, (1, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)

    def loss(ad):
        return wrapped(ad, toks).astype(jnp.float32).mean()

    g = jax.grad(loss)(adapters)
    # gradient exists for every adapter leaf and matches its shape
    for k, ab in g.items():
        assert ab["A"].shape == adapters[k]["A"].shape
    # at init B=0 ⇒ grad(A)=0 exactly; the signal arrives through B
    assert np.abs(np.asarray(g["blocks/0/attn/wq"]["B"])).sum() > 0
