"""LoRA tests: init identity, merge == wrapped apply, quantized base,
gradients flow only to adapters."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
from distributed_lion_tpu.models.lora import (
    LoraConfig,
    lora_apply_fn,
    lora_init,
    merge_lora,
)
from distributed_lion_tpu.ops.quant import quantize_tree


def _setup(quant=None):
    cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(0), cfg)
    if quant:
        base = quantize_tree(base, quant, min_size=1024)
    lcfg = LoraConfig(r=4, alpha=8)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    return cfg, base, lcfg, adapters


def test_adapters_target_q_and_v():
    cfg, base, lcfg, adapters = _setup()
    keys = set(adapters)
    assert all(k.endswith("wq") or k.endswith("wv") for k in keys)
    assert len(keys) == 2 * cfg.n_layer
    a = adapters["blocks/0/attn/wq"]
    assert a["A"].shape == (64, 4) and a["B"].shape == (4, 64)


def test_fresh_adapters_are_identity():
    cfg, base, lcfg, adapters = _setup()
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)),
        np.asarray(llama_apply(base, toks, cfg)),
        rtol=1e-5, atol=1e-5,
    )


def test_merge_matches_wrapped_apply():
    cfg, base, lcfg, adapters = _setup()
    # give the adapters nonzero B so the delta is real
    adapters = jax.tree.map(lambda x: x + 0.01, adapters)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    merged = merge_lora(base, adapters, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)),
        np.asarray(llama_apply(merged, toks, cfg)),
        rtol=2e-2, atol=2e-2,  # bf16 compute tolerance
    )


def test_dpo_target_set_covers_mlp_and_embedding():
    """The reference's DPO adapts q/v/k/out + fc_in/fc_out + wte
    (dpo_llama2.py:192-207); our DPO_TARGET_PATTERNS must land on all four
    attention projections, the full SwiGLU MLP, and the token embedding."""
    from distributed_lion_tpu.models.lora import DPO_TARGET_PATTERNS

    cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(0), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=DPO_TARGET_PATTERNS)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    assert "wte" in adapters
    assert adapters["wte"]["A"].shape == (cfg.vocab_size, 4)
    assert adapters["wte"]["B"].shape == (4, cfg.d_model)
    per_block = {k.split("/")[-1] for k in adapters if k.startswith("blocks/0/")}
    assert per_block == {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def test_embedding_adapter_factored_matches_merged():
    """Gather-side LoRA (lora_embed): the factored wte adapter equals
    merging A@B into the embedding table."""
    from distributed_lion_tpu.models.lora import DPO_TARGET_PATTERNS

    cfg = LlamaConfig.tiny(compute_dtype=jnp.float32)
    base = llama_init(jax.random.key(0), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=DPO_TARGET_PATTERNS)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    adapters = jax.tree.map(lambda x: x + 0.01, adapters)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 256, (2, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    merged = merge_lora(base, adapters, lcfg)
    np.testing.assert_allclose(
        np.asarray(wrapped(adapters, toks)),
        np.asarray(llama_apply(merged, toks, cfg)),
        rtol=2e-4, atol=2e-4,
    )


def test_embedding_adapter_gets_gradient():
    from distributed_lion_tpu.models.lora import DPO_TARGET_PATTERNS

    cfg = LlamaConfig.tiny(compute_dtype=jnp.float32)
    base = llama_init(jax.random.key(0), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=DPO_TARGET_PATTERNS)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    toks = jnp.asarray(np.random.default_rng(4).integers(0, 256, (1, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    g = jax.grad(lambda ad: wrapped(ad, toks).astype(jnp.float32).mean())(adapters)
    # B=0 at init ⇒ signal arrives through wte's B via the gathered A rows
    assert np.abs(np.asarray(g["wte"]["B"])).sum() > 0


def test_adapter_dropout_train_vs_eval():
    """cfg.dropout armed by a dropout key (train) perturbs the adapter
    branch; without a key (eval) the forward is deterministic and matches
    dropout=0. PEFT semantics: base path never dropped (sft_llama2.py:48)."""
    cfg = LlamaConfig.tiny(compute_dtype=jnp.float32)
    base = llama_init(jax.random.key(0), cfg)
    lcfg = LoraConfig(r=4, alpha=8, dropout=0.5)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    adapters = jax.tree.map(lambda x: x + 0.05, adapters)  # nonzero branch
    toks = jnp.asarray(np.random.default_rng(5).integers(0, 256, (1, 16)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)
    eval_out = wrapped(adapters, toks)
    nodrop = lora_apply_fn(
        lambda p, t: llama_apply(p, t, cfg), base,
        LoraConfig(r=4, alpha=8, dropout=0.0))(adapters, toks)
    np.testing.assert_allclose(np.asarray(eval_out), np.asarray(nodrop),
                               rtol=1e-6, atol=1e-6)
    t1 = wrapped(adapters, toks, dropout_key=jax.random.key(2))
    t2 = wrapped(adapters, toks, dropout_key=jax.random.key(3))
    assert np.abs(np.asarray(t1) - np.asarray(eval_out)).max() > 1e-5
    assert np.abs(np.asarray(t1) - np.asarray(t2)).max() > 1e-5
    # same key ⇒ bit-identical (replica consistency across the vote world)
    t1b = wrapped(adapters, toks, dropout_key=jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t1b))


def test_embedding_adapter_peft_roundtrip(tmp_path):
    """wte adapter survives lora_to_peft → peft_to_lora (the PEFT
    Embedding layout: lora_embedding_A [r, V], lora_embedding_B [d, r])."""
    from distributed_lion_tpu.models.hf_export import lora_to_peft
    from distributed_lion_tpu.models.hf_import import peft_to_lora
    from distributed_lion_tpu.models.lora import DPO_TARGET_PATTERNS

    cfg = LlamaConfig.tiny()
    base = llama_init(jax.random.key(0), cfg)
    lcfg = LoraConfig(r=4, alpha=8, target_patterns=DPO_TARGET_PATTERNS)
    adapters = lora_init(jax.random.key(1), base, lcfg)
    adapters = jax.tree.map(lambda x: x + 0.01, adapters)
    lora_to_peft(adapters, cfg, lcfg, str(tmp_path))
    back, back_cfg = peft_to_lora(str(tmp_path), cfg)
    assert set(back) == set(adapters)
    np.testing.assert_allclose(np.asarray(back["wte"]["A"]),
                               np.asarray(adapters["wte"]["A"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(back["wte"]["B"]),
                               np.asarray(adapters["wte"]["B"]), rtol=1e-6)


def test_quantized_base_trains_only_adapters():
    cfg, base, lcfg, adapters = _setup(quant="int8")
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 256, (1, 8)), jnp.int32)
    wrapped = lora_apply_fn(lambda p, t: llama_apply(p, t, cfg), base, lcfg)

    def loss(ad):
        return wrapped(ad, toks).astype(jnp.float32).mean()

    g = jax.grad(loss)(adapters)
    # gradient exists for every adapter leaf and matches its shape
    for k, ab in g.items():
        assert ab["A"].shape == adapters[k]["A"].shape
    # at init B=0 ⇒ grad(A)=0 exactly; the signal arrives through B
    assert np.abs(np.asarray(g["blocks/0/attn/wq"]["B"])).sum() > 0
