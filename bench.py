"""Benchmark: GPT-2 124M vote-Lion training throughput + MFU on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
ALWAYS exits 0 with a parseable JSON line, even when the accelerator backend is
down — round 1 lost its perf axis to a single hanging `jax.devices()` call
(BENCH_r01.json rc=1), so the measurement now runs in a child process under a
hard timeout with bounded retries, falling back to CPU on the final attempt so
the driver always records *a* number plus diagnostics.

Anchor derivation (vs_baseline): the reference publishes no numbers
(BASELINE.md); its stated target is "GPT-2 124M on v5e-8 competitive with
8xA100". GPT-2 124M costs ~857 MFLOPs/token (6N = 744M for N=124M, plus
12*L*d*T = 113M of attention matmuls at L=12, d=768, T=1024). An A100 at 312
bf16 TFLOP/s would give ~145k tokens/s at a strong 40% MFU; under the
reference's stack (HF Trainer + DDP + a per-tensor Python-loop optimizer its
own README calls "currently slow") ~28% MFU is generous, giving the anchor
BASELINE_TOKENS_PER_SEC_PER_DEVICE = 100_000. vs_baseline > 1 therefore means
one TPU chip under this framework out-trains one A100 under the reference.

Measurement discipline: the K optimizer steps of each timed dispatch run as
ONE device program (Trainer._train_chunk, lax.scan over staged batches), and
the timer stops only after a device_get of the final chunk's loss — a value
data-dependent on every step — so queued-but-unexecuted work can't inflate
the number (remote/tunneled backends ack dispatch long before execution).
Config picked by scripts/bench_sweep.py on v5e (SWEEP_v5e.md): remat off
(124M activations fit HBM), bf16 params (the reference's canonical bf16
config), microbatch 4 with 16-step grad accumulation — small microbatches
keep attention-score traffic per pass low while accumulation amortizes the
optimizer's full-pytree ballot/vote/apply passes over 16x the tokens —
chunked-vocab CE (vocab_chunks 8: the streaming logsumexp kills the dense
[B,T,V] f32 logits round-trip), tile-tuned Pallas flash attention
(flash@512x1024 — the stock tiles LOSE to xla at T=1024, tuned tiles win),
and bf16 Lion momentum. The round-3 sweep measured the combination at
98,099 tokens/s/chip (~42.8% MFU) vs 82.8k for the round-2 xla/f32-momentum
config (scripts/SWEEP_r3_raw/sweep2.jsonl).

MFU = achieved model FLOP/s / chip peak bf16 FLOP/s, with model FLOPs/token =
6N + 12*L*d*T (fwd+bwd, PaLM appendix-B convention, attention included,
rematerialization not counted as useful work).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import time

BASELINE_TOKENS_PER_SEC_PER_DEVICE = 100_000.0
STEPS_PER_CALL = 10
TIMED_CALLS = 4

# Recorded artifact holding the last measurement on real TPU hardware with
# THIS benchmark. bench.py WRITES it after every successful TPU run and
# attaches it — clearly labeled — when the TPU backend is unreachable at run
# time and the fallback records a CPU number, so a backend outage degrades
# the evidence instead of erasing it. Reading from the artifact (not a source
# constant) keeps it from going stale as the code evolves.
LAST_TPU_ARTIFACT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "scripts", "last_tpu_measurement.json",
)


def _load_last_tpu_measurement() -> dict | None:
    try:
        with open(LAST_TPU_ARTIFACT) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def sweep_row_promotable(d: dict) -> bool:
    """The ONE eligibility rule for treating a bench_sweep row as flagship
    evidence — shared by _best_sweep_row and the runbook's winner promotion
    (tpu_runbook_auto2.sh imports it), so the rule can't drift between the
    two. Promotable = a RESULT row of the canonical T=1024 anchor workload,
    TPU-attested: rows carry backend since round 4, and the default 'tpu'
    keeps the committed round-3 rows (captured in a verified TPU window,
    scripts/SWEEP_r3_raw/log.txt) eligible while excluding any future
    CPU/fallback-produced row. The block filter keeps T=2048 long-context
    rows (sweep3) out: a different workload, not anchor-comparable. The
    vote_buckets filter keeps the overlap-ablation rows out for the same
    reason in reverse: every banked flagship row measured the monolithic
    vote, so a pipelined-wire row (same tokens, less exposed wire time)
    must not displace the anchor it is being compared against."""
    return (bool(d.get("tokens_per_sec_per_chip"))
            and d.get("backend", "tpu") == "tpu"
            and d.get("block", 1024) == 1024
            and d.get("vote_buckets", 1) == 1)


def _best_sweep_row() -> dict | None:
    """Best tokens/s row from the committed raw sweep artifact
    (scripts/SWEEP_r3_raw/sweep2.jsonl) — attached to non-TPU fallback
    records alongside last_tpu_measurement so a tunnel outage at capture
    time degrades the evidence to clearly-labeled sweep-attested numbers
    instead of erasing the axis. Read from the artifact, never a source
    constant (it cannot go stale as code evolves)."""
    import glob as _glob

    pattern = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "scripts", "SWEEP_r*_raw", "sweep*.jsonl")
    best = None
    for path in sorted(_glob.glob(pattern)):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if not sweep_row_promotable(d):
                        continue
                    tps = d["tokens_per_sec_per_chip"]
                    if (best is None
                            or tps > best["tokens_per_sec_per_chip"]):
                        best = d
                        best["source"] = os.path.relpath(
                            path, os.path.dirname(os.path.abspath(__file__)))
        except OSError:
            continue
    if best is None:
        return None
    best["note"] = ("best single-chip TPU v5e row from the committed "
                    "bench_sweep raw log (same methodology as bench.py; "
                    "sweep-attested, not driver-captured)")
    return best


def overlap_from_ablation() -> dict | None:
    """Measured vote-wire overlap from the committed buckets-ablation rows
    (scripts/SWEEP_r*_raw/overlap.jsonl, captured by the runbook's overlap
    stage: the flagship config at vote_buckets ∈ {1, 4, 16}).

    Groups TPU-attested result rows by config-minus-buckets; for a group
    holding a buckets=1 row and at least one buckets>1 row, the measured
    ``comm_overlap_frac`` is the step-time fraction the pipelined wire
    recovered: ``(ms[1] − min_B ms[B]) / ms[1]``, clipped at 0. This is a
    LOWER bound on the wire time hidden behind the fused apply (compute is
    unchanged between the rows — only when bytes move differs). Returns the
    best-covered group as {"comm_overlap_frac", "ms_per_step", "source"},
    or None when no ablation has been captured yet."""
    import glob as _glob

    pattern = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "scripts", "SWEEP_r*_raw", "overlap.jsonl")
    groups: dict = {}
    for path in sorted(_glob.glob(pattern)):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if (not d.get("ms_per_step")
                            or not d.get("tokens_per_sec_per_chip")
                            or d.get("backend", "tpu") != "tpu"):
                        continue
                    key = (d.get("remat"), d.get("batch_per_dev"),
                           d.get("attn"), d.get("accum"), d.get("dtype"),
                           d.get("vocab_chunks", 0),
                           d.get("mom_dtype", "f32"), d.get("vocab_pad", 0),
                           d.get("block", 1024))
                    b = int(d.get("vote_buckets", 1))
                    # latest capture of a (config, buckets) cell wins
                    groups.setdefault(key, {})[b] = (float(d["ms_per_step"]),
                                                     path)
        except OSError:
            continue
    best = None
    for times in groups.values():
        if 1 not in times or len(times) < 2:
            continue
        ms1 = times[1][0]
        b_min = min((ms for b, (ms, _) in times.items() if b > 1))
        frac = max(0.0, (ms1 - b_min) / ms1) if ms1 > 0 else 0.0
        if best is None or len(times) > len(best["ms_per_step"]):
            best = {
                "comm_overlap_frac": round(frac, 4),
                "ms_per_step": {str(b): ms for b, (ms, _) in
                                sorted(times.items())},
                "source": os.path.relpath(
                    next(iter(times.values()))[1],
                    os.path.dirname(os.path.abspath(__file__))),
            }
    return best


def _record_tpu_measurement(result: dict) -> None:
    prev = _load_last_tpu_measurement()
    if prev and prev.get("promoted") and not result.get("promoted"):
        # an unpromoted capture (debug run with BENCH_* overrides) must not
        # clobber the promoted flagship artifact that future bare runs adopt
        # their config from (advisor r4, medium) — the run's own JSON line
        # still prints; only the adoption store is protected
        print("note: unpromoted TPU capture not recorded over the promoted "
              "flagship artifact", file=sys.stderr)
        return
    rec = dict(result)
    rec["measured"] = time.strftime("%Y-%m-%d %H:%M:%SZ", time.gmtime())
    try:
        with open(LAST_TPU_ARTIFACT, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    except OSError:
        pass

# Peak dense bf16 FLOP/s per chip by device_kind substring (ordered: first
# match wins). Public figures from cloud.google.com/tpu/docs/system-architecture.
_PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops_per_chip(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def run_inner() -> None:
    """The actual measurement. Runs in a child process (see main)."""
    import dataclasses

    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # The axon sitecustomize force-registers the TPU plugin and
        # overrides JAX_PLATFORMS from the env; only the config knob set
        # before first backend use reliably wins (same trick as
        # tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_lion_tpu.data.sources import synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    devices = jax.devices()
    n_dev = len(devices)
    backend = devices[0].platform
    device_kind = devices[0].device_kind
    if os.environ.get("BENCH_REQUIRE_TPU") == "1" and backend != "tpu":
        # main()'s full-budget attempts are TPU measurements; on a host
        # whose backend resolves to CPU the flagship config would grind
        # until the 900s timeout (hours of work at 124M on a host core)
        # before the evidence-of-life fallback ran. Fail in seconds instead.
        print(f"BENCH_REQUIRE_TPU=1 but backend is {backend!r}; "
              "refusing the full-budget flagship config off-TPU",
              file=sys.stderr)
        raise SystemExit(2)
    mesh = make_mesh()
    # BENCH_* env knobs parameterize the ONE timed-step implementation:
    # bench.py IS the sweep harness's measurement core (scripts/
    # bench_sweep.py spawns `bench.py --inner` per config), so a sweep row
    # and a bench capture can never disagree on methodology again.
    # Unset knobs default to the recorded PROMOTED flagship config (the
    # "config" block of scripts/last_tpu_measurement.json): when the
    # TPU-window automation promotes a faster sweep config (its bench_best
    # stage runs with BENCH_PROMOTE=1), a later bare `python bench.py` —
    # the driver's own capture — measures THAT flagship, not a stale
    # built-in. Gated three ways (code-review r4): only promoted records
    # are adopted (a one-off debug run's knobs must not poison future
    # headline captures — adoption itself re-marks the new record promoted
    # so the chain survives bare re-runs); eligibility goes through the
    # ONE sweep_row_promotable rule (backend + anchor-workload block); and
    # every adopted value is validated below with a fallback to built-ins
    # (a corrupt committed artifact must not take down both full-budget
    # TPU attempts — that's the CPU fallback's failure class, not ours).
    rec_cfg = {}
    if backend == "tpu":
        rec = _load_last_tpu_measurement() or {}
        if rec.get("promoted") and isinstance(rec.get("config"), dict):
            probe = {"tokens_per_sec_per_chip": rec.get("value"),
                     "backend": rec.get("backend"),
                     "block": rec["config"].get("block", 1024),
                     "vote_buckets": rec["config"].get("vote_buckets", 1)}
            if sweep_row_promotable(probe):
                rec_cfg = rec["config"]
    env_changed: list = []  # BENCH_* overrides that CHANGED an adopted value
    def _resolve_knobs(rc):
        env_changed.clear()
        def knob(env_key, rec_key, builtin):
            v = os.environ.get(env_key)
            adopted = rc.get(rec_key, builtin)
            if v is not None and str(v) != str(adopted):
                # a knob the environment moved off the adopted config: this
                # run is a one-off variant, not the flagship — it must not
                # re-mark itself promoted below (advisor r4, medium)
                env_changed.append(env_key)
            return v if v is not None else adopted

        k = {
            "remat": str(knob("BENCH_REMAT", "remat", "noremat")),
            "dtype": str(knob("BENCH_DTYPE", "dtype", "bf16")),
            "block": int(knob("BENCH_BLOCK", "block", 1024)),
            "batch_per_dev": int(knob("BENCH_BATCH", "batch_per_dev", 4)),
            "accum": int(knob("BENCH_ACCUM", "accum", 16)),
            "vocab_chunks": int(knob("BENCH_VOCAB_CHUNKS",
                                     "vocab_chunks", 8)),
            "mom_dtype": str(knob("BENCH_MOM_DTYPE", "mom_dtype",
                                  "bfloat16")),
            # 'auto' resolves to the tile-tuned flash winner at the
            # flagship shape (T=1024 on TPU → flash@512x1024,
            # ops/attention.attention dispatch, round-3 sweep row) — the
            # flagship bench needs no explicit attn spec
            "attn": str(knob("BENCH_ATTN", "attn", "auto")),
            "vocab_pad": int(knob("BENCH_VOCAB_PAD", "vocab_pad", 0)),
            # bucketed, overlapped vote wire (optim.distributed_lion):
            # B > 1 pipelines the ballot collective with the fused apply.
            # Default 1 keeps every banked row comparable (all committed
            # sweep rows measured the monolithic vote); the overlap
            # ablation (runbook stage → overlap.jsonl) sweeps {1, 4, 16}.
            "vote_buckets": int(knob("BENCH_VOTE_BUCKETS",
                                     "vote_buckets", 1)),
            # vote-health telemetry in the timed step (train/telemetry).
            # Default ON: the added device work is one extra ballot-width
            # pass per OPTIMIZER step (margin bincount + packed-election
            # XOR, ~0.5 GB of HBM traffic at 124M coords) amortized over
            # accum microbatches of fwd/bwd — well under 1% of step time —
            # and elections are pinned bit-identical. Recorded in the row's
            # config; BENCH_TELEMETRY=0 gives the exact pre-telemetry
            # methodology for an overhead A/B, and (like any env-moved
            # knob) marks the run unpromotable.
            "telemetry": int(knob("BENCH_TELEMETRY", "telemetry", 1)),
        }
        if k["remat"] not in ("noremat", "full", "dots"):
            raise ValueError(f"bad remat {k['remat']!r}")
        if k["vote_buckets"] < 1:
            raise ValueError(f"bad vote_buckets {k['vote_buckets']!r}")
        if k["telemetry"] not in (0, 1):
            raise ValueError(f"bad telemetry {k['telemetry']!r}")
        if k["dtype"] not in ("bf16", "f32"):
            raise ValueError(f"bad dtype {k['dtype']!r}")
        from distributed_lion_tpu.ops.attention import parse_attn_spec
        parse_attn_spec(k["attn"])  # raises on a malformed spec
        return k

    try:
        k = _resolve_knobs(rec_cfg)
    except Exception as e:
        if not rec_cfg:
            raise  # malformed ENV values keep their loud failure
        print(f"recorded flagship config unusable ({e}); using built-in "
              "defaults", file=sys.stderr)
        rec_cfg = {}
        k = _resolve_knobs({})
    remat_s, dtype_s, block = k["remat"], k["dtype"], k["block"]
    batch_per_dev = k["batch_per_dev"]
    accum, vocab_chunks = k["accum"], k["vocab_chunks"]
    mom_dtype, attn_spec, vocab_pad = (k["mom_dtype"], k["attn"],
                                       k["vocab_pad"])
    vote_buckets = k["vote_buckets"]
    bench_telemetry = bool(k["telemetry"])
    steps_per_call = int(os.environ.get("BENCH_STEPS", STEPS_PER_CALL))
    timed_calls = int(os.environ.get("BENCH_CALLS", TIMED_CALLS))
    if (steps_per_call, timed_calls) != (STEPS_PER_CALL, TIMED_CALLS):
        # a shortened measurement budget (tunnel smoke runs) is just as
        # disqualifying as a config knob: a 1-step compile-adjacent number
        # must not become the promoted flagship (code-review r5)
        env_changed.append("BENCH_STEPS/BENCH_CALLS")
    model_cfg = dataclasses.replace(
        GPT2Config.gpt2_124m(), attn_impl="xla",
        remat=remat_s != "noremat",
        remat_policy="dots" if remat_s == "dots" else "full",
        param_dtype=jnp.bfloat16 if dtype_s == "bf16" else jnp.float32,
    )
    if block != model_cfg.n_ctx:
        model_cfg = dataclasses.replace(model_cfg, n_ctx=block)
    if vocab_pad:
        model_cfg = dataclasses.replace(model_cfg,
                                        vocab_pad_multiple=vocab_pad)
    from distributed_lion_tpu.ops.attention import parse_attn_spec

    attn_impl, bq, bkv, bqb, bkvb = parse_attn_spec(attn_spec)
    if attn_spec != "xla":
        model_cfg = dataclasses.replace(
            model_cfg, attn_impl=attn_impl,
            flash_block_q=bq, flash_block_kv=bkv,
            flash_block_q_bwd=bqb, flash_block_kv_bwd=bkvb)
    # provenance of what 'auto' MEANS on this device: the autotune cache
    # resolver (ops/autotune — the same lookup ops.attention's auto
    # dispatch applies at trace time) maps an auto spec to its tuned
    # explicit form; "auto" back means cache miss → heuristic dispatch.
    # Recorded in the row so a sweep/bench log is self-describing; null
    # for explicit specs (nothing was resolved).
    attn_resolved = None
    if attn_impl == "auto":
        from distributed_lion_tpu.ops.autotune import resolve_attn_spec

        attn_resolved = resolve_attn_spec(
            "auto", t=model_cfg.n_ctx,
            head_dim=model_cfg.d_model // model_cfg.n_head,
            dtype=jnp.dtype(model_cfg.compute_dtype).name)
    cfg = TrainConfig(
        lion=True,
        async_grad=True,
        # vote-health telemetry rides the timed step so BENCH_*.json tracks
        # election dynamics (flip rate, margin, disagreement) alongside the
        # throughput number — see the BENCH_TELEMETRY knob above for the
        # overhead bound and the opt-out that reproduces the pre-telemetry
        # methodology exactly.
        telemetry=bench_telemetry,
        # pin the round-3 comm methodology: every committed sweep/bench row
        # measured every-step sign_psum voting. Left at the auto sentinels,
        # a W>1 backend would resolve to packed_a2a + vote_every=4 (less
        # comm per step) and rank incomparably against the banked rows.
        # W=1 short-circuits either way; this makes multi-chip explicit.
        wire="sign_psum",
        vote_every=1,
        vote_buckets=vote_buckets,
        learning_rate=1e-4,
        weight_decay=0.1,
        warmup_steps=10,
        max_steps=10_000,
        per_device_train_batch_size=batch_per_dev,
        gradient_accumulation_steps=accum,
        block_size=model_cfg.n_ctx,
        steps_per_call=steps_per_call,
        logging_steps=10_000,
        output_dir=None,
        vocab_chunks=vocab_chunks,
        mom_dtype=mom_dtype,
    )
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    global_bs = trainer.global_train_batch()
    tokens_per_step = global_bs * cfg.block_size
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(trainer.params))
    # MFU honesty under a padded-vocab layout: the chip executes the pad
    # columns' FLOPs, but they are not useful model work — count only the
    # true-vocab parameters in the 6N model-FLOPs term
    n_pad = (model_cfg.padded_vocab - model_cfg.vocab_size) * model_cfg.d_model
    n_params -= n_pad

    blocks = synthetic_lm_dataset(
        global_bs * steps_per_call, cfg.block_size, model_cfg.vocab_size, seed=0
    )
    batches = jax.device_put(
        blocks.astype(np.int32).reshape(steps_per_call, global_bs, cfg.block_size),
        NamedSharding(mesh, P(None, "data")),
    )
    base_key = jax.random.key(0)

    # warmup/compile + honest sync
    trainer.params, trainer.state, trainer.vote_health, m = (
        trainer._train_chunk(trainer.params, trainer.state,
                             trainer.vote_health, trainer._frozen_arg(),
                             batches, base_key))
    _ = float(np.asarray(jax.device_get(m["loss"])))
    # drop the warmup window's vote stats: the recorded summary should
    # describe the TIMED steps only
    vote_health_summary = trainer.telemetry_summary(reset=True)

    # ring-only run journal (train/journal.py — no file sink) around the
    # timed window, attributed offline-style by the same analyzer the
    # runbook's journal stage uses (cli/run_analyze.attribute), so every
    # BENCH row says where its wall clock went: dispatch = host enqueue +
    # device backpressure across the timed calls, device = the final
    # drain. Host timestamps only — the timed loop is untouched beyond
    # two monotonic reads per dispatch.
    from distributed_lion_tpu.cli import run_analyze as _run_analyze
    from distributed_lion_tpu.train.journal import Journal as _Journal

    _jr = _Journal(None, ring=4096)
    _jr.event("train_start", step=0)
    t0 = time.perf_counter()
    for _i in range(timed_calls):
        with _jr.span("dispatch", step=_i * steps_per_call,
                      steps=steps_per_call):
            trainer.params, trainer.state, trainer.vote_health, m = (
                trainer._train_chunk(trainer.params, trainer.state,
                                     trainer.vote_health,
                                     trainer._frozen_arg(),
                                     batches, base_key))
    with _jr.span("device_wait", step=timed_calls * steps_per_call):
        final_loss = float(np.asarray(jax.device_get(m["loss"])))
    dt = time.perf_counter() - t0
    _jr.event("train_end", step=timed_calls * steps_per_call)
    journal_attribution = _run_analyze.attribute(_jr.records())
    vote_health_summary = trainer.telemetry_summary()

    steps = steps_per_call * timed_calls
    tokens_per_sec = tokens_per_step * steps / dt
    per_chip = tokens_per_sec / n_dev

    # Model FLOPs per token: 6N (fwd+bwd matmuls) + attention 12*L*d*T.
    flops_per_token = (
        6.0 * n_params
        + 12.0 * model_cfg.n_layer * model_cfg.d_model * cfg.block_size
    )
    peak = _peak_flops_per_chip(device_kind) if backend == "tpu" else None
    mfu = (per_chip * flops_per_token / peak) if peak else None

    on_tpu = backend == "tpu"
    mfu_str = f"MFU {mfu:.1%}, " if mfu is not None else ""
    print(
        json.dumps(
            {
                "metric": f"{mfu_str}tokens/sec/chip, GPT-2 124M vote-Lion "
                f"train step (microbatch {batch_per_dev}x{cfg.block_size}, "
                f"accum {accum}"
                + (f", vocab_chunks {vocab_chunks}" if vocab_chunks else "")
                + (f", mom_dtype {mom_dtype}" if mom_dtype else "")
                + (f", attn {attn_spec}" if attn_spec != "xla" else "")
                + (f", vocab_pad {vocab_pad}" if vocab_pad else "")
                + (f", vote_buckets {vote_buckets}"
                   if vote_buckets > 1 else "")
                + (f", remat {remat_s}" if remat_s != "noremat" else "")
                + (", f32 params" if dtype_s != "bf16" else "")
                + f", {n_dev} {device_kind} device(s), backend={backend})",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "ms_per_step": round(dt / steps * 1e3, 1),
                "loss": round(final_loss, 3),
                # the resolved knobs, persisted with the headline artifact
                # so future bare runs adopt the promoted flagship config.
                # promoted = blessed by the runbook's bench_best stage
                # (BENCH_PROMOTE=1) or itself adopted from a promoted
                # record — one-off env-tweaked runs stay unpromoted and
                # are never adopted as defaults
                "config": {
                    "attn": attn_spec, "vocab_chunks": vocab_chunks,
                    "mom_dtype": mom_dtype, "batch_per_dev": batch_per_dev,
                    "accum": accum, "vocab_pad": vocab_pad,
                    "remat": remat_s, "dtype": dtype_s, "block": block,
                    "vote_buckets": vote_buckets,
                    "telemetry": int(bench_telemetry),
                },
                "vote_buckets": vote_buckets,
                "attn_resolved": attn_resolved,
                # step-wall attribution of the timed window (run journal,
                # train/journal.py + cli/run_analyze): named buckets as
                # fractions of measured wall, so a sweep/bench row explains
                # its own ms_per_step — and run_analyze --baseline diffs a
                # later run against this row to NAME the regressing bucket
                "journal_attribution": journal_attribution,
                # election dynamics of the timed steps (train/telemetry):
                # margin histogram (fractions per voted coordinate),
                # elected-sign flip rate, worker disagreement — the
                # signals that say whether the 1-bit vote is healthy at
                # this config, now tracked per BENCH round
                "vote_health": vote_health_summary,
                # measured step-time fraction recovered by bucketing the
                # vote wire, from the committed overlap-ablation rows
                # (buckets ∈ {1,4,16}, scripts/SWEEP_r*_raw/overlap.jsonl);
                # null on CPU and until a TPU window captures the ablation
                "comm_overlap_frac": (overlap_from_ablation() or {}).get(
                    "comm_overlap_frac") if on_tpu else None,
                "promoted": (os.environ.get("BENCH_PROMOTE") == "1"
                             or (bool(rec_cfg) and not env_changed)),
                # vs_baseline is defined against the derived A100 anchor and
                # only meaningful on TPU hardware; null (not 0.0) elsewhere
                # so a fallback doesn't render as a perf failure.
                "vs_baseline": (
                    round(per_chip / BASELINE_TOKENS_PER_SEC_PER_DEVICE, 3)
                    if on_tpu
                    else None
                ),
                "mfu": round(mfu, 4) if mfu is not None else None,
                "flops_per_token": round(flops_per_token),
                "n_params": n_params,
                "backend": backend,
                "device_kind": device_kind,
                # comm budget (BASELINE.md §2: ≤0.5 bit/param): what the
                # flagship wire ships per step at the canonical W=4 world,
                # and the opt-in config that meets the budget outright
                "wire_bits_per_param": _wire_bits(n_params, accum),
            }
        ),
        flush=True,
    )


def _wire_bits(n_params: int, accum: int) -> dict:
    """Comm accounting extras for the bench record: the flagship wire's
    bits/param/step at the reference's canonical W=4 world, plus the
    budget-meeting opt-in (packed_a2a + vote_every 4, tested in
    tests/test_vote_every.py and run at scale by scripts/loss_parity.py
    --mode lazy)."""
    from distributed_lion_tpu.ops.codec import wire_bytes_per_param

    flagship = wire_bytes_per_param(n_params, 4, "sign_psum",
                                    accum_steps=accum)
    budget = wire_bytes_per_param(n_params, 4, "packed_a2a", vote_every=4,
                                  accum_steps=accum)
    return {
        "flagship(sign_psum,W=4)": round(flagship["bits_per_param"], 3),
        "budget_config(packed_a2a,vote_every=4,W=4)": round(
            budget["bits_per_param"], 3),
    }


def _extract_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                return obj
    return None


# The measurement child holds the TPU (libtpu single-client lock). If an
# outer `timeout`/driver SIGTERMs the orchestrating parent mid-attempt, an
# orphaned child would keep the chip locked and hang every later user —
# children run in their own process group, torn down on signal/exit. This
# machinery is shared: scripts/bench_sweep.py imports run_child /
# install_child_teardown so the TPU-lock-release semantics can't drift
# between the two harnesses.
_child: subprocess.Popen | None = None


def _kill_child() -> None:
    if _child is not None and _child.poll() is None:
        try:
            os.killpg(_child.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def install_child_teardown() -> None:
    """Tear the current measurement child's process group down on SIGTERM
    and at interpreter exit. Call once from the orchestrating __main__."""
    signal.signal(signal.SIGTERM, lambda s, f: (_kill_child(),
                                                sys.exit(128 + s)))
    atexit.register(_kill_child)


def run_child(cmd: list, env: dict, budget: float,
              cwd: str) -> tuple[int, str, str]:
    """Run ``cmd`` in its own process group under a hard timeout; returns
    (rc, stdout, stderr). On timeout the whole group is SIGKILLed and
    TimeoutExpired re-raised — the child can never outlive the budget."""
    global _child
    _child = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=cwd, start_new_session=True,
    )
    try:
        out, err = _child.communicate(timeout=budget)
        rc = _child.returncode
    except subprocess.TimeoutExpired:
        _kill_child()
        _child.wait()
        _child = None
        raise
    _child = None
    return rc, out, err


def _run_attempt(env: dict, budget: float) -> tuple[int, str, str]:
    here = os.path.abspath(__file__)
    return run_child([sys.executable, here, "--inner"], env, budget,
                     os.path.dirname(here))


def main() -> None:
    """Orchestrator: run the measurement in a child process under a hard
    timeout, retry on failure, fall back to CPU, and ALWAYS print one JSON
    line and exit 0. Never imports jax itself (backend init can hang)."""
    install_child_teardown()
    # a healthy TPU run needs ~2-4 min (compile + 50 fused steps); 900s is
    # ample headroom while keeping the worst-case hung-backend chain
    # (900 + 300 + CPU fallback ~400s) well inside the driver's window
    timeout_s = float(os.environ.get("BENCH_TIMEOUT_S", "900"))
    attempts = (
        # a full-budget TPU attempt, a short retry (if the backend hung once
        # it rarely recovers seconds later — don't spend a second full
        # budget), then the CPU evidence-of-life config: it exists to prove
        # the program runs, not to measure a meaningful number — full
        # flagship size would itself blow the timeout on a host CPU
        ("default", timeout_s, {"BENCH_REQUIRE_TPU": "1"}),
        ("default", min(timeout_s, 300.0), {"BENCH_REQUIRE_TPU": "1"}),
        ("cpu", timeout_s,
         {"JAX_PLATFORMS": "cpu", "BENCH_FORCE_CPU": "1",
          "BENCH_STEPS": "1", "BENCH_CALLS": "1", "BENCH_ACCUM": "1",
          # reset perf knobs too: a TPU-only attn impl, typo'd dtype, or
          # malformed int must not take down the evidence-of-life attempt
          "BENCH_ATTN": "xla", "BENCH_MOM_DTYPE": "",
          "BENCH_VOCAB_CHUNKS": "0", "BENCH_BATCH": "4",
          "BENCH_VOCAB_PAD": "0", "BENCH_REMAT": "noremat",
          "BENCH_DTYPE": "bf16", "BENCH_BLOCK": "1024",
          "BENCH_VOTE_BUCKETS": "1", "BENCH_TELEMETRY": "1",
          # an inherited TPU-only pin must not kill the evidence-of-life
          # attempt — it exists precisely for when the TPU is unreachable
          "BENCH_REQUIRE_TPU": ""}),
    )
    errors: list[str] = []
    for label, budget, env_extra in attempts:
        env = dict(os.environ)
        env.update(env_extra)
        try:
            rc, stdout, stderr = _run_attempt(env, budget)
        except subprocess.TimeoutExpired:
            errors.append(f"[{label}] timeout after {budget:.0f}s")
            continue
        result = _extract_json_line(stdout)
        if rc == 0 and result is not None:
            if result.get("backend") == "tpu":
                _record_tpu_measurement(result)
            else:
                last = _load_last_tpu_measurement()
                if last is not None:
                    result["last_tpu_measurement"] = last
                sweep = _best_sweep_row()
                if sweep is not None:
                    result["best_sweep_row"] = sweep
            print(json.dumps(result), flush=True)
            return
        tail = (stderr or stdout or "").strip().splitlines()[-8:]
        errors.append(f"[{label}] rc={rc}: " + " | ".join(tail))
    print(
        json.dumps(
            {
                "metric": "tokens/sec/chip, GPT-2 124M vote-Lion train step "
                "(ALL BACKENDS FAILED)",
                "value": 0.0,
                "unit": "tokens/s/chip",
                "vs_baseline": None,
                "error": " || ".join(errors)[-2000:],
                "last_tpu_measurement": _load_last_tpu_measurement(),
                "best_sweep_row": _best_sweep_row(),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if "--inner" in sys.argv:
        run_inner()
    else:
        main()
