"""Benchmark: GPT-2 124M vote-Lion training throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); its stated target is "GPT-2
124M on v5e-8 competitive with 8xA100 wall-clock". We anchor vs_baseline to
100_000 tokens/s per device — a stand-in for per-A100 GPT-2-small training
throughput under the reference's stack (HF Trainer + DDP + its Python-loop
optimizer, which README.md:2 admits is slow) — so vs_baseline > 1 means one
TPU chip under this framework out-trains one A100 under the reference.
"""

from __future__ import annotations

import json
import time

BASELINE_TOKENS_PER_SEC_PER_DEVICE = 100_000.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.data.sources import synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    n_dev = len(jax.devices())
    mesh = make_mesh()
    model_cfg = GPT2Config.gpt2_124m()
    batch_per_dev, accum = 8, 1
    cfg = TrainConfig(
        lion=True,
        async_grad=True,
        learning_rate=1e-4,
        weight_decay=0.1,
        warmup_steps=10,
        max_steps=10_000,
        per_device_train_batch_size=batch_per_dev,
        gradient_accumulation_steps=accum,
        block_size=model_cfg.n_ctx,
        logging_steps=10_000,
        output_dir=None,
    )
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    global_bs = trainer.global_train_batch()
    tokens_per_step = global_bs * cfg.block_size

    blocks = synthetic_lm_dataset(global_bs * 4, cfg.block_size, model_cfg.vocab_size, seed=0)
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch = jax.device_put(
        blocks[:global_bs].astype(np.int32), NamedSharding(mesh, P("data"))
    )
    base_key = jax.random.key(0)

    # warmup/compile
    trainer.params, trainer.state, m = trainer._train_step(
        trainer.params, trainer.state, batch, base_key
    )
    jax.block_until_ready(m["loss"])

    steps = 20
    t0 = time.perf_counter()
    for _ in range(steps):
        trainer.params, trainer.state, m = trainer._train_step(
            trainer.params, trainer.state, batch, base_key
        )
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = tokens_per_step * steps / dt
    per_chip = tokens_per_sec / n_dev
    print(
        json.dumps(
            {
                "metric": "tokens/sec/chip, GPT-2 124M vote-Lion train step "
                f"(bs={batch_per_dev}x{cfg.block_size}, {n_dev} device(s))",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(per_chip / BASELINE_TOKENS_PER_SEC_PER_DEVICE, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
