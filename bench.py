"""Benchmark: GPT-2 124M vote-Lion training throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); its stated target is "GPT-2
124M on v5e-8 competitive with 8xA100 wall-clock". We anchor vs_baseline to
100_000 tokens/s per device — a stand-in for per-A100 GPT-2-small training
throughput under the reference's stack (HF Trainer + DDP + its Python-loop
optimizer, which README.md:2 admits is slow) — so vs_baseline > 1 means one
TPU chip under this framework out-trains one A100 under the reference.

Measurement discipline: the K optimizer steps of each timed dispatch run as
ONE device program (Trainer._train_chunk, lax.scan over staged batches), and
the timer stops only after a device_get of the final chunk's loss — a value
data-dependent on every step — so queued-but-unexecuted work can't inflate
the number (remote/tunneled backends ack dispatch long before execution).
Config picked by scripts/bench_sweep.py on v5e: remat off (124M activations
fit HBM), XLA attention (beats Pallas flash at T=1024), bf16 params (the
reference's canonical bf16 config), microbatch 4 with 16-step grad
accumulation — small microbatches keep the f32 attention-score traffic per
pass low while accumulation amortizes the optimizer's full-pytree
ballot/vote/apply passes over 16x the tokens.
"""

from __future__ import annotations

import dataclasses
import json
import time

BASELINE_TOKENS_PER_SEC_PER_DEVICE = 100_000.0
STEPS_PER_CALL = 10
TIMED_CALLS = 4


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_lion_tpu.data.sources import synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    n_dev = len(jax.devices())
    mesh = make_mesh()
    model_cfg = dataclasses.replace(
        GPT2Config.gpt2_124m(), remat=False, attn_impl="xla",
        param_dtype=jnp.bfloat16,
    )
    batch_per_dev, accum = 4, 16
    cfg = TrainConfig(
        lion=True,
        async_grad=True,
        learning_rate=1e-4,
        weight_decay=0.1,
        warmup_steps=10,
        max_steps=10_000,
        per_device_train_batch_size=batch_per_dev,
        gradient_accumulation_steps=accum,
        block_size=model_cfg.n_ctx,
        steps_per_call=STEPS_PER_CALL,
        logging_steps=10_000,
        output_dir=None,
    )
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    global_bs = trainer.global_train_batch()
    tokens_per_step = global_bs * cfg.block_size

    blocks = synthetic_lm_dataset(
        global_bs * STEPS_PER_CALL, cfg.block_size, model_cfg.vocab_size, seed=0
    )
    batches = jax.device_put(
        blocks.astype(np.int32).reshape(STEPS_PER_CALL, global_bs, cfg.block_size),
        NamedSharding(mesh, P(None, "data")),
    )
    base_key = jax.random.key(0)

    # warmup/compile + honest sync
    trainer.params, trainer.state, m = trainer._train_chunk(
        trainer.params, trainer.state, batches, base_key
    )
    _ = float(np.asarray(jax.device_get(m["loss"])))

    t0 = time.perf_counter()
    for _ in range(TIMED_CALLS):
        trainer.params, trainer.state, m = trainer._train_chunk(
            trainer.params, trainer.state, batches, base_key
        )
    _ = float(np.asarray(jax.device_get(m["loss"])))
    dt = time.perf_counter() - t0

    steps = STEPS_PER_CALL * TIMED_CALLS
    tokens_per_sec = tokens_per_step * steps / dt
    per_chip = tokens_per_sec / n_dev
    print(
        json.dumps(
            {
                "metric": "tokens/sec/chip, GPT-2 124M vote-Lion train step "
                f"(microbatch {batch_per_dev}x{cfg.block_size}, accum {accum}, "
                f"{n_dev} device(s))",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(per_chip / BASELINE_TOKENS_PER_SEC_PER_DEVICE, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
