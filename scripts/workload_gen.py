#!/usr/bin/env python
"""Open-loop serving workload generator (ISSUE 17, ROADMAP item 2d).

Emits a request JSONL in the serve/api schema (explicit ``tokens`` ids,
so no tokenizer is needed downstream) describing an OPEN-LOOP arrival
process — arrivals do not wait for completions, which is what makes a
soak honest: a closed loop self-throttles exactly when the engine
degrades, hiding the queue growth an SLO monitor exists to see.

The process, all from ONE fixed seed (numpy default_rng — the same
workload byte-for-byte on every run/machine):

- **Poisson arrivals**: exponential inter-arrival gaps at ``--rate``
  requests per engine tick, cumulated and floored onto the integer
  ``arrival_tick`` grid the engine's run() driver consumes.
- **Burst overlay**: every ``--burst_every`` arrivals, ``--burst_size``
  extra requests land on the SAME tick — the thundering-herd shape that
  pure Poisson under-represents and admission queues die on.
- **Heavy-tail lengths**: prompt and output budgets draw from lognormal
  tails (median/sigma knobs, hard caps) — most requests short, a fat
  tail of long ones, the mix that makes prefill fairness and page-pool
  pressure real.
- **Shared-prefix populations**: ``--prefix_groups`` populations each
  share a common prompt prefix (tagged ``prefix_group``, matched by
  TOKENS by the prefix cache; the tag also drives fleet group routing).
- **Deadlines**: a ``--deadline_frac`` fraction of requests carries
  ``deadline_s`` so the timeout path is exercised, not just modeled.

    python scripts/workload_gen.py --requests 200 --seed 0 \
        --out runs/serving/requests.jsonl

The output validates under scripts/validate_metrics.py (the request
JSONL schema) and drives ``serve/api.serve_request_file``, a
ServingEngine/ServingFleet ``run()``, or scripts/bench_serve.py's slo
soak (which imports :func:`generate` by file path).

``--stream HOST:PORT`` (ISSUE 20) points the SAME generated workload at
a live ``run_serve --listen`` socket server instead of a file: requests
go out open-loop on the ``arrival_tick * --tick_s`` schedule over one
multiplexed connection (``serve/net.drive_open_loop``), rejects re-arm
with the server's ``retry_after_s`` hint plus exponential backoff, and
the summary includes ``stream_sha256`` — the digest of the first-attempt
wire bytes (``serve/net.encode_request`` canonical JSON), byte-identical
across reruns of the same seed so a soak's input is provably the same
stream, not merely the same distribution.

    python scripts/workload_gen.py --requests 50 --seed 0 \
        --stream 127.0.0.1:8151 --tick_s 0.01
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _lognormal_int(rng, median: float, sigma: float, lo: int,
                   hi: int) -> int:
    v = int(round(float(rng.lognormal(np.log(median), sigma))))
    return max(lo, min(v, hi))


def generate(requests: int = 100, seed: int = 0, rate: float = 0.5,
             burst_every: int = 25, burst_size: int = 4,
             vocab: int = 256, prompt_median: float = 12.0,
             prompt_sigma: float = 0.6, prompt_max: int = 48,
             out_median: float = 16.0, out_sigma: float = 0.7,
             out_max: int = 96, prefix_groups: int = 3,
             prefix_frac: float = 0.5, prefix_len: int = 8,
             deadline_frac: float = 0.0, deadline_s: float = 5.0
             ) -> list:
    """Build the request records (dicts in the serve/api line schema).
    Pure function of its arguments — the fixed ``seed`` pins arrivals,
    lengths, prefix membership and token ids alike."""
    if requests < 1:
        raise ValueError(f"need >= 1 request, got {requests!r}")
    if rate <= 0:
        raise ValueError(f"--rate must be > 0, got {rate!r}")
    if not 0.0 <= prefix_frac <= 1.0 or not 0.0 <= deadline_frac <= 1.0:
        raise ValueError("prefix_frac/deadline_frac must be in [0, 1]")
    rng = np.random.default_rng(int(seed))
    prefixes = [
        [int(t) for t in rng.integers(1, vocab, int(prefix_len))]
        for _ in range(max(int(prefix_groups), 0))]
    records = []
    t = 0.0
    since_burst = 0
    i = 0
    while len(records) < requests:
        t += float(rng.exponential(1.0 / rate))
        arrivals_now = 1
        since_burst += 1
        if burst_every > 0 and since_burst >= burst_every:
            since_burst = 0
            arrivals_now += int(burst_size)
        for _ in range(arrivals_now):
            if len(records) >= requests:
                break
            plen = _lognormal_int(rng, prompt_median, prompt_sigma, 1,
                                  prompt_max)
            group = None
            toks = [int(x) for x in rng.integers(1, vocab, plen)]
            if prefixes and float(rng.random()) < prefix_frac:
                g = int(rng.integers(0, len(prefixes)))
                group = f"pop{g}"
                toks = prefixes[g] + toks
            rec = {"id": f"w{i}", "tokens": toks,
                   "max_new_tokens": _lognormal_int(
                       rng, out_median, out_sigma, 1, out_max),
                   "seed": i, "arrival_tick": int(t)}
            if group is not None:
                rec["prefix_group"] = group
            if deadline_frac > 0 and float(rng.random()) < deadline_frac:
                rec["deadline_s"] = float(deadline_s)
            records.append(rec)
            i += 1
    return records


def write_jsonl(records: list, path: str) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, allow_nan=False) + "\n")
    os.replace(tmp, path)


def stream_sha256(records: list) -> str:
    """Digest of the first-attempt wire byte stream: what every rerun of
    the same generator seed must reproduce exactly. Pure function of the
    records (net.encode_request is canonical JSON — sorted keys, compact
    separators), so it can be pinned without a server."""
    from distributed_lion_tpu.serve import net
    h = hashlib.sha256()
    for rec in records:
        h.update(net.encode_request(rec))
    return h.hexdigest()


def stream(records: list, target: str, tick_s: float = 0.0,
           max_wall_s: float = 600.0) -> dict:
    """Drive ``records`` open-loop at a live socket server and return
    the drive summary + ``stream_sha256``. Raises if any request ends
    without a ``done`` frame (drive_open_loop runs to completion or
    times out honestly)."""
    from distributed_lion_tpu.serve import net
    host, _, port = target.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"--stream wants HOST:PORT, got {target!r}")
    digest = stream_sha256(records)
    out = net.drive_open_loop(host, int(port), records, tick_s=tick_s,
                              max_wall_s=max_wall_s)
    toks = sum(len(r["tokens"]) for r in
               out["responses"].values())
    return {"completed": len(out["responses"]),
            "rejects": out["rejects"], "retries": out["retries"],
            "wall_s": round(out["wall_s"], 3),
            "tokens_out": int(toks), "stream_sha256": digest}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=float, default=0.5,
                    help="mean Poisson arrivals per engine tick")
    ap.add_argument("--burst_every", type=int, default=25,
                    help="inject a burst every N arrivals (0 = never)")
    ap.add_argument("--burst_size", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--prompt_median", type=float, default=12.0)
    ap.add_argument("--prompt_sigma", type=float, default=0.6)
    ap.add_argument("--prompt_max", type=int, default=48)
    ap.add_argument("--out_median", type=float, default=16.0)
    ap.add_argument("--out_sigma", type=float, default=0.7)
    ap.add_argument("--out_max", type=int, default=96)
    ap.add_argument("--prefix_groups", type=int, default=3)
    ap.add_argument("--prefix_frac", type=float, default=0.5)
    ap.add_argument("--prefix_len", type=int, default=8)
    ap.add_argument("--deadline_frac", type=float, default=0.0)
    ap.add_argument("--deadline_s", type=float, default=5.0)
    ap.add_argument("--out", default=os.path.join(
        "runs", "serving", "requests.jsonl"))
    ap.add_argument("--stream", default="",
                    help="HOST:PORT of a run_serve --listen server: "
                         "drive the workload open-loop over a socket "
                         "instead of writing --out")
    ap.add_argument("--tick_s", type=float, default=0.0,
                    help="--stream pacing: seconds per arrival tick "
                         "(0 = send as fast as the schedule allows)")
    ap.add_argument("--stream_wall_s", type=float, default=600.0,
                    help="--stream hard wall before the drive aborts")
    args = ap.parse_args(argv)
    records = generate(
        requests=args.requests, seed=args.seed, rate=args.rate,
        burst_every=args.burst_every, burst_size=args.burst_size,
        vocab=args.vocab, prompt_median=args.prompt_median,
        prompt_sigma=args.prompt_sigma, prompt_max=args.prompt_max,
        out_median=args.out_median, out_sigma=args.out_sigma,
        out_max=args.out_max, prefix_groups=args.prefix_groups,
        prefix_frac=args.prefix_frac, prefix_len=args.prefix_len,
        deadline_frac=args.deadline_frac, deadline_s=args.deadline_s)
    if args.stream:
        summary = stream(records, args.stream, tick_s=args.tick_s,
                         max_wall_s=args.stream_wall_s)
        print(json.dumps(summary, sort_keys=True))
        return 0
    write_jsonl(records, args.out)
    last = records[-1]["arrival_tick"]
    tagged = sum(1 for r in records if "prefix_group" in r)
    toks = sum(len(r["tokens"]) for r in records)
    print(f"wrote {len(records)} requests -> {args.out} "
          f"(arrival span {last} ticks, {toks} prompt tokens, "
          f"{tagged} prefix-tagged)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
