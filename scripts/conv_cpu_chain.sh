#!/bin/bash
# Reduced-scale convergence run — the tunnel-dead fallback for VERDICT r4
# #6 (real-corpus convergence with eval accuracy/perplexity + mid-run
# checkpoint resume). Waits for the CPU parity legs to finish (one host
# core: running both at once just slows the critical path), then trains
# gpt2_small (the shared 12.7M reduced evidence preset) on the parity
# corpus through the native BPE for 2000 steps, writing eval acc/ppl to
# runs/convergence_cpu/metrics.jsonl. The first segment is deliberately
# killed by a timeout so the second segment EXERCISES run_clm's Orbax
# resume-autodetect — resume is part of the evidence, not an accident.
#
#   nohup bash scripts/conv_cpu_chain.sh > /tmp/conv_cpu_chain.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
stamp() { date -u +%FT%TZ; }

# ---- wait for the parity driver chain to release the core
while pgrep -f 'loss_parity.py --phase run' > /dev/null \
   || pgrep -f 'parity_cpu_driver.sh' > /dev/null; do
  sleep 120
done
echo "$(stamp) parity chain done; starting reduced convergence run"

if python scripts/check_evidence.py conv; then
  echo "$(stamp) convergence already captured; nothing to do"
  exit 0
fi

mkdir -p runs/convergence_cpu
if [ ! -s runs/convergence_cpu/tokens.bin ]; then
  python - <<'EOF'
import numpy as np
a = np.load("runs/parity/tokens.npy", mmap_mode="r")
assert int(np.asarray(a[:1_000_000]).max()) < 65536
np.asarray(a, dtype=np.uint16).tofile("runs/convergence_cpu/tokens.bin")
EOF
fi

run_segment() { # $1 = timeout seconds (0 = none)
  local t="$1"; shift
  local pre=(env DLION_PLATFORM=cpu)
  [ "$t" != 0 ] && pre=(timeout "$t" env DLION_PLATFORM=cpu)
  nice -n 15 "${pre[@]}" python -m distributed_lion_tpu.cli.run_clm \
    --model_name gpt2_small --dataset bin:runs/convergence_cpu/tokens.bin \
    --vocab_size 16384 --lion --async_grad \
    --wire sign_psum --vote_every 1 \
    --per_device_train_batch_size 4 --gradient_accumulation_steps 1 \
    --block_size 256 --max_steps 2000 --warmup_steps 100 \
    --learning_rate 1e-4 --weight_decay 0.1 \
    --eval_steps 250 --eval_iters 10 --logging_steps 25 \
    --save_steps 250 --save_total_limit 2 \
    --param_dtype float32 --compute_dtype bfloat16 \
    --vocab_chunks 0 --remat false \
    --output_dir runs/convergence_cpu
}

# segment 1: capped so segment 2 must resume from the Orbax checkpoint
run_segment 2700
echo "$(stamp) segment 1 done (rc=$?); resuming to completion"
for attempt in 1 2 3; do
  if run_segment 0; then
    break
  fi
  echo "$(stamp) segment attempt $attempt failed; retrying"
  sleep 60
done

if python scripts/check_evidence.py conv; then
  for p in runs/convergence_cpu/metrics.jsonl; do
    [ -e "$p" ] && git add "$p"
  done
  git commit -q -m "Capture reduced CPU convergence run (eval acc/ppl, mid-run resume)" \
    && echo "$(stamp) convergence run committed"
else
  echo "$(stamp) convergence run FAILED the evidence check"
fi
echo "$(stamp) conv chain done"
