#!/usr/bin/env python
"""JSONL schema smoke check for metrics logs — the CI guard behind
MetricsLogger's strict-JSON contract.

``json.dumps(float('nan'))`` emits the bare token ``NaN``, which is not
JSON: one diverged loss used to corrupt the whole line for every strict
consumer (jq, pandas, check_evidence). MetricsLogger now serializes
non-finite floats as ``null`` with the raw value under ``"<k>_repr"``; this
script asserts a metrics file actually honors that contract:

- every non-empty line parses as STRICT JSON (the NaN/Infinity/-Infinity
  tokens Python's json module happily reads back are rejected);
- every record is an object carrying an integer ``step``;
- every value is a JSON scalar or a flat list of JSON scalars (the shapes
  downstream tooling indexes by key).

A torn final line (a run killed mid-write) is tolerated once, at EOF —
append-mode logs legitimately end that way.

JSONL arguments whose basename starts with ``journal`` (the run journal's
``journal_rank<r>.jsonl`` files and their rotations, plus crash bundles'
``journal_tail.jsonl`` — train/journal.py) get the journal record schema
instead: every line a strict-JSON object carrying ``kind`` (meta | span |
event | log), a string ``name``, a finite number ``t`` and an integer
``rank``; span records additionally carry a finite non-negative ``dur``.
The torn-final-line tolerance applies the same way (a crash mid-write
tears at most the last record — the journal's documented durability unit).

JSONL basenames starting with ``requests``/``workload`` (the
scripts/workload_gen.py output) get the serve request line schema —
tokens-or-prompt plus typed optionals — and basenames starting with
``responses`` (``run_serve --out``) get the serve response schema:
id/reason/token accounting plus the ISSUE-17 timing columns, with
``queue_ticks``/``decode_ticks`` REQUIRED on every terminal status
including timeout/failed/overflow.

Non-JSONL arguments (``*.json``) are validated as strict single-document
JSON artifacts, so EVERY JSON artifact the repo writes passes one
validator: crash bundles (``crash/step_*/bundle.json`` — must carry
step/reason/config, telemetry.write_crash_bundle), checkpoint
manifests (``manifest.json`` — must carry format/step/files with
sha256+bytes per file, checkpoint.write_manifest), the autotune
tuning cache (``tuning_cache.json`` — full check delegated to
ops/autotune.validate_cache_doc, the cache's single schema authority),
the DCN-overlap evidence artifact (``dcn_overlap.json`` —
scripts/bench_dcn.py's ablation/frontier/parity document; the frontier
rows are strict-validated per row), the serving-bench artifact
(``serving.json`` — scripts/bench_serve.py's decode/prefill-share/
bit-identity/speculative-frontier/tp_serving/serve_resilience/
fleet_resilience/moe_serving document, per-row validated the same way
incl. accept_rate ∈ [0,1] on every frontier row, the TP-degree +
shared-prefix rows of the ISSUE 13 section, the
crash-matrix/slow/drain/rejoin rows of the ISSUE 14 replica-plane
section, the SIGKILL-kill-matrix/restart/socket-soak rows of the
ISSUE 20 process-isolated fleet section (incl. the 64-hex
``stream_sha256`` byte-determinism pin), capacity_utilization/
dropped_rate ∈ [0,1] on every dense-vs-MoE-vs-MoE+ep matrix row of the
ISSUE 15 section, and the ISSUE 17 ``slo`` section — ordered p50 <= p95 <= p99 non-negative
latency quantiles, finite goodput, required status counts), and the
live-elasticity artifact (``elasticity.json`` —
scripts/bench_elasticity.py's survive/bit-identity/timeline/parity
document; timeline rows are strict-validated per row).
The same NaN-token rejection applies: all the writers pass
``allow_nan=False`` and this script is the CI check that they keep
doing so.

    python scripts/validate_metrics.py runs/telemetry/metrics.jsonl \
        runs/telemetry/crash/step_*/bundle.json \
        runs/resilience/checkpoints/*/manifest.json

Exit 0 = every file valid. Used by tests/test_telemetry.py,
tests/test_validate_artifacts.py and the runbook's telemetry stage
(scripts/tpu_runbook_auto2.sh).
"""

from __future__ import annotations

import json
import os
import re
import sys


def _reject_constant(name: str):
    raise ValueError(f"non-finite JSON constant {name!r} (invalid JSON; "
                     "MetricsLogger must serialize it as null + _repr)")


def _scalar_ok(v) -> bool:
    return v is None or isinstance(v, (str, int, float, bool))


def validate_file(path: str) -> list[str]:
    """Return a list of violation strings (empty = valid)."""
    errors: list[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    n_records = 0
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line, parse_constant=_reject_constant)
        except ValueError as e:
            if i == len(lines) and "constant" not in str(e):
                continue  # torn last line from a mid-write kill: tolerated
            errors.append(f"{path}:{i}: {e}")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}:{i}: record is {type(rec).__name__}, "
                          "not an object")
            continue
        n_records += 1
        if not isinstance(rec.get("step"), int):
            errors.append(f"{path}:{i}: missing integer 'step'")
        for k, v in rec.items():
            if _scalar_ok(v):
                continue
            if isinstance(v, list) and all(_scalar_ok(x) for x in v):
                continue
            errors.append(f"{path}:{i}: key {k!r} holds a "
                          f"{type(v).__name__} (want scalar or flat list)")
    if n_records == 0:
        errors.append(f"{path}: no metrics records")
    return errors


_JOURNAL_KINDS = ("meta", "span", "event", "log")  # == train/journal.KINDS


def _finite_number(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and v == v and v not in (float("inf"), float("-inf")))


def validate_request_file(path: str) -> list[str]:
    """Strict-schema check for serve request JSONL (the serve/api input
    schema; scripts/workload_gen.py is the canonical writer): each line a
    strict-JSON object carrying ``tokens`` (non-empty flat int list) or
    ``prompt`` (non-empty string), with typed optionals —
    ``max_new_tokens`` positive int, ``seed`` int, ``arrival_tick``
    non-negative int, ``prefix_group`` non-empty string, ``deadline_s``
    positive finite. The same refusals serve/api.load_request_file makes
    at serve time, made BEFORE a soak burns minutes on a bad file."""
    errors: list[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    n_records = 0
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line, parse_constant=_reject_constant)
        except ValueError as e:
            if i == len(lines) and "constant" not in str(e):
                continue
            errors.append(f"{path}:{i}: {e}")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}:{i}: record is {type(rec).__name__}, "
                          "not an object")
            continue
        n_records += 1
        toks = rec.get("tokens")
        prompt = rec.get("prompt")
        if toks is not None:
            if (not isinstance(toks, list) or not toks or not all(
                    isinstance(t, int) and not isinstance(t, bool)
                    and t >= 0 for t in toks)):
                errors.append(f"{path}:{i}: 'tokens' must be a non-empty "
                              "flat list of non-negative ints")
        elif not (isinstance(prompt, str) and prompt):
            errors.append(f"{path}:{i}: request needs 'tokens' or a "
                          "non-empty 'prompt'")
        mnt = rec.get("max_new_tokens")
        if mnt is not None and not (isinstance(mnt, int)
                                    and not isinstance(mnt, bool)
                                    and mnt > 0):
            errors.append(f"{path}:{i}: 'max_new_tokens' must be a "
                          "positive int when present")
        seed = rec.get("seed")
        if seed is not None and (isinstance(seed, bool)
                                 or not isinstance(seed, int)):
            errors.append(f"{path}:{i}: 'seed' must be an int when "
                          "present")
        at = rec.get("arrival_tick")
        if at is not None and not (isinstance(at, int)
                                   and not isinstance(at, bool)
                                   and at >= 0):
            errors.append(f"{path}:{i}: 'arrival_tick' must be a "
                          "non-negative int when present")
        group = rec.get("prefix_group")
        if group is not None and (not isinstance(group, str) or not group):
            errors.append(f"{path}:{i}: 'prefix_group' must be a "
                          "non-empty string when present")
        dl = rec.get("deadline_s")
        if dl is not None and not (_finite_number(dl) and dl > 0):
            errors.append(f"{path}:{i}: 'deadline_s' must be a positive "
                          "finite number when present")
    if n_records == 0:
        errors.append(f"{path}: no request records")
    return errors


_RESPONSE_REASONS = ("eos", "length", "overflow", "rejected", "timeout",
                     "failed")


def validate_response_file(path: str) -> list[str]:
    """Strict-schema check for serve response JSONL
    (serve/api.serve_request_file / cli/run_serve --out): id + reason +
    token accounting on every line, and the ISSUE-17 timing columns —
    ``queue_ticks``/``decode_ticks`` REQUIRED on every terminal status
    (timeout/failed/overflow included: a queue-side death whose wait
    vanished from the books is the failure mode these columns exist to
    prevent), ``ttft_ticks``/``ttft_ms`` typed strictly when present
    (same discipline as ``prefix_group``)."""
    errors: list[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    n_records = 0
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line, parse_constant=_reject_constant)
        except ValueError as e:
            if i == len(lines) and "constant" not in str(e):
                continue
            errors.append(f"{path}:{i}: {e}")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}:{i}: record is {type(rec).__name__}, "
                          "not an object")
            continue
        n_records += 1
        if "id" not in rec:
            errors.append(f"{path}:{i}: missing 'id'")
        if rec.get("reason") not in _RESPONSE_REASONS:
            errors.append(f"{path}:{i}: 'reason' must be one of "
                          f"{'|'.join(_RESPONSE_REASONS)}, got "
                          f"{rec.get('reason')!r}")
        toks = rec.get("tokens")
        if not (isinstance(toks, list) and all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in toks)):
            errors.append(f"{path}:{i}: 'tokens' must be a flat int list")
        for k in ("prompt_len", "n_generated"):
            v = rec.get(k)
            if not (isinstance(v, int) and not isinstance(v, bool)
                    and v >= 0):
                errors.append(f"{path}:{i}: {k!r} must be a non-negative "
                              "int")
        for k in ("queue_ticks", "decode_ticks"):
            v = rec.get(k)
            if not (isinstance(v, int) and not isinstance(v, bool)
                    and v >= 0):
                errors.append(f"{path}:{i}: missing non-negative int "
                              f"{k!r} (timing columns are required on "
                              "every terminal status)")
        tt = rec.get("ttft_ticks")
        if tt is not None and not (isinstance(tt, int)
                                   and not isinstance(tt, bool)
                                   and tt >= 0):
            errors.append(f"{path}:{i}: 'ttft_ticks' must be a "
                          "non-negative int when present")
        tms = rec.get("ttft_ms")
        if tms is not None and not (_finite_number(tms) and tms >= 0):
            errors.append(f"{path}:{i}: 'ttft_ms' must be a non-negative "
                          "finite number when present")
        group = rec.get("prefix_group")
        if group is not None and (not isinstance(group, str) or not group):
            errors.append(f"{path}:{i}: 'prefix_group' must be a "
                          "non-empty string when present")
    if n_records == 0:
        errors.append(f"{path}: no response records")
    return errors


def validate_journal_file(path: str) -> list[str]:
    """Strict-schema check for run-journal JSONL (train/journal.py): the
    per-line single-doc + allow_nan=False discipline of validate_file, plus
    the journal record contract — kind/name/t/rank on every record, a
    finite non-negative dur on spans, scalar-or-flat-list values
    throughout. Returns violation strings (empty = valid)."""
    errors: list[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    n_records = 0
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line, parse_constant=_reject_constant)
        except ValueError as e:
            if i == len(lines) and "constant" not in str(e):
                continue  # torn last line (crash mid-write): tolerated
            errors.append(f"{path}:{i}: {e}")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}:{i}: record is {type(rec).__name__}, "
                          "not an object")
            continue
        n_records += 1
        if rec.get("kind") not in _JOURNAL_KINDS:
            errors.append(f"{path}:{i}: 'kind' must be one of "
                          f"{_JOURNAL_KINDS}, got {rec.get('kind')!r}")
        if not isinstance(rec.get("name"), str):
            errors.append(f"{path}:{i}: missing string 'name'")
        if not _finite_number(rec.get("t")):
            errors.append(f"{path}:{i}: missing finite number 't'")
        if not isinstance(rec.get("rank"), int) \
                or isinstance(rec.get("rank"), bool):
            errors.append(f"{path}:{i}: missing integer 'rank'")
        if rec.get("kind") == "span" and not (
                _finite_number(rec.get("dur")) and rec["dur"] >= 0):
            errors.append(f"{path}:{i}: span without a finite non-negative "
                          "'dur'")
        for k, v in rec.items():
            if _scalar_ok(v):
                continue
            if isinstance(v, list) and all(_scalar_ok(x) for x in v):
                continue
            errors.append(f"{path}:{i}: key {k!r} holds a "
                          f"{type(v).__name__} (want scalar or flat list)")
    if n_records == 0:
        errors.append(f"{path}: no journal records")
    return errors


# required top-level keys per known single-document artifact name.
# (tuning_cache.json is NOT listed here: it dispatches below on its
# embedded format stamp — any filename, e.g. a $DLT_TUNE_CACHE override —
# and delegates wholesale to ops/autotune.validate_cache_doc.
# dcn_overlap.json, serving.json and elasticity.json have their own
# branches too: their rows carry per-row schemas the generic
# required-keys check can't express.)
_DOC_SCHEMAS = {
    "bundle.json": ("step", "reason", "config"),
    "manifest.json": ("format", "step", "files"),
}


def _serving_errors(path: str, doc: dict) -> list[str]:
    """Strict schema of the serving-bench evidence artifact
    (scripts/bench_serve.py; judged by check_evidence's ``serving`` and
    ``speculative`` stages): decode rows each a tokens/s/chip measurement
    at one batch size carrying the NF4-vs-bf16 weight-bytes column, the
    prefill-share ablation rows, the two live-recomputed bit-identity
    markers, and the speculative-decode section (ISSUE 11) — an
    accept-rate × tokens/s/chip frontier over drafter × k plus its own
    live-recomputed identity markers (greedy speculative == plain paged
    decode; sampled speculative == the same per-request PRNG stream)."""
    errors = []
    for key in ("meta", "decode", "prefill_share", "bit_identity",
                "speculative", "tp_serving", "serve_resilience",
                "fleet_resilience", "moe_serving", "slo"):
        if key not in doc:
            errors.append(f"{path}: missing required key {key!r}")
    meta = doc.get("meta")
    if isinstance(meta, dict):
        for k in ("backend", "model", "family"):
            if not isinstance(meta.get(k), str):
                errors.append(f"{path}: meta.{k} must be a string")
    for name, row_keys in (
            ("decode", ("batch", "decode_ticks", "ms_per_tick",
                        "tokens_per_sec_per_chip", "quant",
                        "weight_bytes_bf16", "weight_bytes_nf4")),
            ("prefill_share", ("prefill_cap_tokens", "ticks",
                               "tokens_per_sec", "prefill_token_share"))):
        rows = doc.get(name)
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: {name!r} must be a non-empty list")
            continue
        for i, row in enumerate(rows):
            if not isinstance(row, dict):
                errors.append(f"{path}: {name}[{i}] is not an object")
                continue
            for k in row_keys:
                if k not in row:
                    errors.append(f"{path}: {name}[{i}] missing {k!r}")
                elif k == "quant":
                    if not isinstance(row[k], str):
                        errors.append(f"{path}: {name}[{i}].quant is not "
                                      "a string")
                elif not _finite_number(row[k]):
                    errors.append(f"{path}: {name}[{i}].{k} is not finite")
    bits = doc.get("bit_identity")
    if isinstance(bits, dict):
        for k in ("paged_vs_dense", "batched_vs_solo"):
            if not isinstance(bits.get(k), bool):
                errors.append(f"{path}: bit_identity.{k} must be a bool")
    spec = doc.get("speculative")
    if spec is not None and not isinstance(spec, dict):
        errors.append(f"{path}: 'speculative' must be an object")
    elif isinstance(spec, dict):
        marks = spec.get("markers")
        if not isinstance(marks, dict):
            errors.append(f"{path}: speculative.markers must be an object")
        else:
            for k in ("greedy_vs_plain", "sampled_vs_stream"):
                if not isinstance(marks.get(k), bool):
                    errors.append(
                        f"{path}: speculative.markers.{k} must be a bool")
        rows = spec.get("frontier")
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: speculative.frontier must be a "
                          "non-empty list")
            rows = []
        for i, row in enumerate(rows):
            where = f"{path}: speculative.frontier[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where} is not an object")
                continue
            for k in ("drafter", "workload"):
                if not isinstance(row.get(k), str):
                    errors.append(f"{where}.{k} must be a string")
            if not (isinstance(row.get("k"), int)
                    and not isinstance(row.get("k"), bool)
                    and row["k"] >= 0):
                errors.append(f"{where}.k must be a non-negative int")
            for k in ("ms_per_tick", "tokens_per_sec_per_chip",
                      "proposed", "accepted"):
                if not _finite_number(row.get(k)):
                    errors.append(f"{where}.{k} is not finite")
            ar = row.get("accept_rate")
            if not (_finite_number(ar) and 0.0 <= ar <= 1.0):
                errors.append(f"{where}.accept_rate must be a finite "
                              "number in [0, 1]")
    tps = doc.get("tp_serving")
    if tps is not None and not isinstance(tps, dict):
        errors.append(f"{path}: 'tp_serving' must be an object")
    elif isinstance(tps, dict):
        marks = tps.get("markers")
        if not isinstance(marks, dict):
            errors.append(f"{path}: tp_serving.markers must be an object")
        else:
            for k in ("tp1_vs_unsharded", "tpN_vs_unsharded",
                      "shared_vs_unshared_greedy",
                      "shared_vs_unshared_sampled",
                      "shared_vs_unshared_speculative"):
                if not isinstance(marks.get(k), bool):
                    errors.append(
                        f"{path}: tp_serving.markers.{k} must be a bool")
        rows = tps.get("rows")
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: tp_serving.rows must be a non-empty "
                          "list")
            rows = []
        for i, row in enumerate(rows):
            where = f"{path}: tp_serving.rows[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where} is not an object")
                continue
            for k in ("tp", "batch", "decode_ticks"):
                if not (isinstance(row.get(k), int)
                        and not isinstance(row.get(k), bool)
                        and row[k] >= 0):
                    errors.append(f"{where}.{k} must be a non-negative int")
            for k in ("ms_per_tick_p50", "ms_per_tick_p99",
                      "tokens_per_sec_per_chip"):
                if not _finite_number(row.get(k)):
                    errors.append(f"{where}.{k} is not finite")
        pref = tps.get("prefix")
        if not isinstance(pref, dict):
            errors.append(f"{path}: tp_serving.prefix must be an object")
        else:
            for k in ("requests", "prompt_len", "logical_pages",
                      "physical_pages", "prefix_hits", "cow_copies"):
                if not (isinstance(pref.get(k), int)
                        and not isinstance(pref.get(k), bool)
                        and pref[k] >= 0):
                    errors.append(f"{path}: tp_serving.prefix.{k} must be "
                                  "a non-negative int")
            ratio = pref.get("prefix_mem_ratio")
            if not (_finite_number(ratio) and ratio > 0):
                errors.append(f"{path}: tp_serving.prefix.prefix_mem_ratio "
                              "must be a finite positive number")
    sr = doc.get("serve_resilience")
    if sr is not None and not isinstance(sr, dict):
        errors.append(f"{path}: 'serve_resilience' must be an object")
    elif isinstance(sr, dict):
        marks = sr.get("markers")
        if not isinstance(marks, dict):
            errors.append(f"{path}: serve_resilience.markers must be an "
                          "object")
        else:
            for k in ("migrated_identity_greedy",
                      "migrated_identity_sampled",
                      "migrated_identity_speculative",
                      "migrated_identity_prefix_cache",
                      "zero_token_loss", "drain_completes_residents",
                      "slow_detected_and_routed", "rejoin_serves"):
                if not isinstance(marks.get(k), bool):
                    errors.append(
                        f"{path}: serve_resilience.markers.{k} must be a "
                        "bool")
        rows = sr.get("crash_matrix")
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: serve_resilience.crash_matrix must be "
                          "a non-empty list")
            rows = []
        for i, row in enumerate(rows):
            where = f"{path}: serve_resilience.crash_matrix[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where} is not an object")
                continue
            for k in ("crash_tick", "migrated", "tokens_lost",
                      "recovery_latency_ticks"):
                if not (isinstance(row.get(k), int)
                        and not isinstance(row.get(k), bool)
                        and row[k] >= 0):
                    errors.append(f"{where}.{k} must be a non-negative int")
            if not isinstance(row.get("identical"), bool):
                errors.append(f"{where}.identical must be a bool")
        slow = sr.get("slow")
        if not isinstance(slow, dict):
            errors.append(f"{path}: serve_resilience.slow must be an "
                          "object")
        else:
            for k in ("p99_ms_slow_replica", "p99_ms_clean_replica",
                      "p99_ms_clean_run"):
                if not _finite_number(slow.get(k)):
                    errors.append(f"{path}: serve_resilience.slow.{k} is "
                                  "not finite")
            for k in ("slow_ms", "admissions_slow", "admissions_fast"):
                if not (isinstance(slow.get(k), int)
                        and not isinstance(slow.get(k), bool)
                        and slow[k] >= 0):
                    errors.append(f"{path}: serve_resilience.slow.{k} must "
                                  "be a non-negative int")
            for k in ("detected", "identical"):
                if not isinstance(slow.get(k), bool):
                    errors.append(f"{path}: serve_resilience.slow.{k} must "
                                  "be a bool")
        for section, bool_keys in (
                ("drain", ("identical", "drained_departed")),
                ("rejoin", ("rejoined", "served_after_rejoin",
                            "identical"))):
            sec = sr.get(section)
            if not isinstance(sec, dict):
                errors.append(f"{path}: serve_resilience.{section} must be "
                              "an object")
                continue
            for k in bool_keys:
                if not isinstance(sec.get(k), bool):
                    errors.append(f"{path}: serve_resilience.{section}.{k} "
                                  "must be a bool")
    fr = doc.get("fleet_resilience")
    if fr is not None and not isinstance(fr, dict):
        errors.append(f"{path}: 'fleet_resilience' must be an object")
    elif isinstance(fr, dict):
        marks = fr.get("markers")
        if not isinstance(marks, dict):
            errors.append(f"{path}: fleet_resilience.markers must be an "
                          "object")
        else:
            for k in ("sigkill_identity", "sigkill_zero_token_loss",
                      "process_isolated", "restart_identity",
                      "restart_prefill_saved", "socket_soak_served"):
                if not isinstance(marks.get(k), bool):
                    errors.append(
                        f"{path}: fleet_resilience.markers.{k} must be a "
                        "bool")
        rows = fr.get("kill_matrix")
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: fleet_resilience.kill_matrix must be "
                          "a non-empty list")
            rows = []
        for i, row in enumerate(rows):
            where = f"{path}: fleet_resilience.kill_matrix[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where} is not an object")
                continue
            for k in ("kill_tick", "migrated", "declared_dead",
                      "tokens_lost", "completed"):
                if not (isinstance(row.get(k), int)
                        and not isinstance(row.get(k), bool)
                        and row[k] >= 0):
                    errors.append(f"{where}.{k} must be a non-negative int")
            if row.get("sampling") not in ("greedy", "stochastic"):
                errors.append(f"{where}.sampling must be "
                              "'greedy'|'stochastic'")
            for k in ("identical", "process_isolated"):
                if not isinstance(row.get(k), bool):
                    errors.append(f"{where}.{k} must be a bool")
        restart = fr.get("restart")
        if not isinstance(restart, dict):
            errors.append(f"{path}: fleet_resilience.restart must be an "
                          "object")
        else:
            for k in ("inflight_at_stop", "restored", "chains_primed",
                      "resumed_from_tick", "prefill_tokens_saved"):
                if not (isinstance(restart.get(k), int)
                        and not isinstance(restart.get(k), bool)
                        and restart[k] >= 0):
                    errors.append(f"{path}: fleet_resilience.restart.{k} "
                                  "must be a non-negative int")
            if not isinstance(restart.get("identical"), bool):
                errors.append(f"{path}: fleet_resilience.restart."
                              "identical must be a bool")
        soak = fr.get("socket_soak")
        if not isinstance(soak, dict):
            errors.append(f"{path}: fleet_resilience.socket_soak must be "
                          "an object")
        else:
            for k in ("requests", "completed", "rejects", "retries",
                      "tokens_out"):
                if not (isinstance(soak.get(k), int)
                        and not isinstance(soak.get(k), bool)
                        and soak[k] >= 0):
                    errors.append(f"{path}: fleet_resilience.socket_soak."
                                  f"{k} must be a non-negative int")
            for k in ("wall_s", "goodput_tokens_per_s"):
                if not _finite_number(soak.get(k)):
                    errors.append(f"{path}: fleet_resilience.socket_soak."
                                  f"{k} is not finite")
            sha = soak.get("stream_sha256")
            if not (isinstance(sha, str)
                    and re.fullmatch(r"[0-9a-f]{64}", sha)):
                errors.append(f"{path}: fleet_resilience.socket_soak."
                              "stream_sha256 must be a 64-hex-char "
                              "sha256 digest")
    moe = doc.get("moe_serving")
    if moe is not None and not isinstance(moe, dict):
        errors.append(f"{path}: 'moe_serving' must be an object")
    elif isinstance(moe, dict):
        marks = moe.get("markers")
        if not isinstance(marks, dict):
            errors.append(f"{path}: moe_serving.markers must be an object")
        else:
            for k in ("paged_vs_dense", "batched_vs_solo",
                      "batched_generate_vs_solo", "ep1_vs_unsharded",
                      "epN_vs_unsharded", "ep_tp_vs_unsharded",
                      "ep_batch1_vs_unsharded", "ep_batchN_vs_unsharded",
                      "ep_batch_tp_vs_unsharded",
                      "ep_batch_overlap_vs_unsharded"):
                if not isinstance(marks.get(k), bool):
                    errors.append(
                        f"{path}: moe_serving.markers.{k} must be a bool")
        rows = moe.get("rows")
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: moe_serving.rows must be a non-empty "
                          "list")
            rows = []
        for i, row in enumerate(rows):
            where = f"{path}: moe_serving.rows[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where} is not an object")
                continue
            if not isinstance(row.get("config"), str):
                errors.append(f"{where}.config must be a string")
            for k in ("experts", "ep", "batch", "decode_ticks"):
                if not (isinstance(row.get(k), int)
                        and not isinstance(row.get(k), bool)
                        and row[k] >= 0):
                    errors.append(f"{where}.{k} must be a non-negative int")
            for k in ("ms_per_tick", "tokens_per_sec_per_chip"):
                if not _finite_number(row.get(k)):
                    errors.append(f"{where}.{k} is not finite")
            if row.get("sharding") not in ("none", "replicated", "batch"):
                errors.append(f"{where}.sharding must be one of "
                              "'none' | 'replicated' | 'batch'")
            if not isinstance(row.get("beats_dense_per_chip"), bool):
                errors.append(f"{where}.beats_dense_per_chip must be a "
                              "bool")
            for k in ("capacity_utilization", "dropped_rate"):
                v = row.get(k)
                if not (_finite_number(v) and 0.0 <= v <= 1.0):
                    errors.append(f"{where}.{k} must be a finite number "
                                  "in [0, 1]")
    slo = doc.get("slo")
    if slo is not None and not isinstance(slo, dict):
        errors.append(f"{path}: 'slo' must be an object")
    elif isinstance(slo, dict):
        marks = slo.get("markers")
        if not isinstance(marks, dict):
            errors.append(f"{path}: slo.markers must be an object")
        else:
            for k in ("metrics_inert", "zero_token_loss",
                      "responses_timed"):
                if not isinstance(marks.get(k), bool):
                    errors.append(f"{path}: slo.markers.{k} must be a bool")
        for k in ("requests", "tokens_out", "tokens_lost", "ticks",
                  "breaches"):
            if not (isinstance(slo.get(k), int)
                    and not isinstance(slo.get(k), bool)
                    and slo[k] >= 0):
                errors.append(f"{path}: slo.{k} must be a non-negative int")
        targets = slo.get("targets")
        if not isinstance(targets, dict):
            errors.append(f"{path}: slo.targets must be an object")
        else:
            for k in ("ttft_ms", "tok_ms"):
                if not (_finite_number(targets.get(k)) and targets[k] > 0):
                    errors.append(f"{path}: slo.targets.{k} must be a "
                                  "finite positive number")
            p = targets.get("p99")
            if not (_finite_number(p) and 0.0 < p < 1.0):
                errors.append(f"{path}: slo.targets.p99 must be a finite "
                              "number in (0, 1)")
        # percentile sketches must be non-negative AND ordered: a banked
        # p50 > p99 means the sketch (or the banking code) is lying, and
        # a latency can never be negative — both shapes the slo stage
        # must refuse, not average over
        for sec in ("ttft_ms", "tok_ms"):
            q = slo.get(sec)
            if not isinstance(q, dict):
                errors.append(f"{path}: slo.{sec} must be an object")
                continue
            bad = False
            for k in ("p50", "p95", "p99"):
                v = q.get(k)
                if not (_finite_number(v) and v >= 0):
                    errors.append(f"{path}: slo.{sec}.{k} must be a "
                                  "non-negative finite number")
                    bad = True
            if not bad and not (q["p50"] <= q["p95"] <= q["p99"]):
                errors.append(f"{path}: slo.{sec} percentiles must be "
                              "ordered p50 <= p95 <= p99")
        gp = slo.get("goodput_tokens_per_sec")
        if not (_finite_number(gp) and gp >= 0):
            errors.append(f"{path}: slo.goodput_tokens_per_sec must be a "
                          "non-negative finite number")
        counts = slo.get("status_counts")
        if not isinstance(counts, dict):
            errors.append(f"{path}: slo.status_counts must be an object")
        else:
            for k in ("eos", "length", "overflow", "timeout", "failed"):
                if k not in counts:
                    errors.append(f"{path}: slo.status_counts missing "
                                  f"{k!r}")
            for k, v in counts.items():
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    errors.append(f"{path}: slo.status_counts.{k} must be "
                                  "a non-negative int")
    return errors


def _dcn_overlap_errors(path: str, doc: dict) -> list[str]:
    """Strict schema of the DCN-overlap evidence artifact
    (scripts/bench_dcn.py; judged by check_evidence's ``dcn_overlap``
    stage): the four evidence sections present, ablation rows carrying
    finite timings, and every frontier row a
    bits-per-param × steps-to-loss point (``steps_to_loss`` null = the
    target was never reached within the leg's budget — allowed, but the
    key must exist so a silently-dropped measurement can't masquerade as
    a complete table)."""
    errors = []
    for key in ("meta", "bit_identity", "ablation", "overlap", "frontier",
                "parity"):
        if key not in doc:
            errors.append(f"{path}: missing required key {key!r}")
    for name, row_keys in (("ablation",
                            ("depth", "ms_per_step",
                             "dcn_wait_ms_per_step")),
                           ("frontier",
                            ("wire", "bits_per_param", "steps_to_loss",
                             "target_loss", "final_loss"))):
        rows = doc.get(name)
        if not isinstance(rows, list) or not rows:
            errors.append(f"{path}: {name!r} must be a non-empty list")
            continue
        for i, row in enumerate(rows):
            if not isinstance(row, dict):
                errors.append(f"{path}: {name}[{i}] is not an object")
                continue
            for k in row_keys:
                if k not in row:
                    errors.append(f"{path}: {name}[{i}] missing {k!r}")
                elif k != "steps_to_loss" and not (
                        isinstance(row[k], str) if k == "wire"
                        else _finite_number(row[k])):
                    errors.append(f"{path}: {name}[{i}].{k} is not "
                                  f"{'a string' if k == 'wire' else 'finite'}")
    for section, key in (("overlap", "pass"), ("parity", "pass")):
        sec = doc.get(section)
        if isinstance(sec, dict) and not isinstance(sec.get(key), bool):
            errors.append(f"{path}: {section}.{key} must be a bool")
    return errors


def _elasticity_errors(path: str, doc: dict) -> list[str]:
    """Strict schema of the live-elasticity evidence artifact
    (scripts/bench_elasticity.py; judged by check_evidence's
    ``elasticity`` stage): the headline drop/rejoin scenario's survival
    facts, the two degraded-phase bit-identity markers, the journal-read
    membership timeline (per-row validated — every row one control-plane
    event with step/cause/quorum), and the pre-registered post-rejoin
    parity judgement."""
    errors = []
    for key in ("meta", "survive", "bit_identity", "timeline", "parity"):
        if key not in doc:
            errors.append(f"{path}: missing required key {key!r}")
        elif key != "timeline" and not isinstance(doc[key], dict):
            # a present-but-wrong-type section must fail the strict
            # schema, not slip past the per-field checks (which would let
            # check_evidence's judgement crash on it downstream)
            errors.append(f"{path}: {key!r} must be an object")
    meta = doc.get("meta")
    if isinstance(meta, dict):
        if not isinstance(meta.get("backend"), str):
            errors.append(f"{path}: meta.backend must be a string")
        for k in ("world", "steps", "drop_worker", "drop_step",
                  "rejoin_step"):
            if not isinstance(meta.get(k), int):
                errors.append(f"{path}: meta.{k} must be an integer")
    sv = doc.get("survive")
    if isinstance(sv, dict):
        for k in ("completed", "finite"):
            if not isinstance(sv.get(k), bool):
                errors.append(f"{path}: survive.{k} must be a bool")
        for k in ("steps", "left_events", "rejoin_events", "final_alive"):
            if not isinstance(sv.get(k), int):
                errors.append(f"{path}: survive.{k} must be an integer")
        lc = sv.get("final_lifecycle")
        if not (isinstance(lc, list) and lc
                and all(isinstance(s, str) for s in lc)):
            errors.append(f"{path}: survive.final_lifecycle must be a "
                          "non-empty list of state names")
    bits = doc.get("bit_identity")
    if isinstance(bits, dict):
        for k in ("degraded_vs_masked", "drop_deterministic"):
            if not isinstance(bits.get(k), bool):
                errors.append(f"{path}: bit_identity.{k} must be a bool")
    rows = doc.get("timeline")
    if not isinstance(rows, list) or not rows:
        errors.append(f"{path}: 'timeline' must be a non-empty list")
    else:
        for i, row in enumerate(rows):
            if not isinstance(row, dict):
                errors.append(f"{path}: timeline[{i}] is not an object")
                continue
            if not isinstance(row.get("event"), str):
                errors.append(f"{path}: timeline[{i}].event must be a "
                              "string")
            for k in ("step", "alive", "world"):
                if not isinstance(row.get(k), int):
                    errors.append(f"{path}: timeline[{i}].{k} must be an "
                                  "integer")
    par = doc.get("parity")
    if isinstance(par, dict):
        if not isinstance(par.get("pass"), bool):
            errors.append(f"{path}: parity.pass must be a bool")
        if not isinstance(par.get("scale"), str):
            errors.append(f"{path}: parity.scale must be a string")
        for k in ("bound_nats", "rejoin_gap_nats", "tail_frac"):
            if not _finite_number(par.get(k)):
                errors.append(f"{path}: parity.{k} is not finite")
    return errors


_SHA256 = re.compile(r"^[0-9a-f]{64}$")
_TUNE_CACHE_FORMAT = "dlt-tune-cache-v1"  # == ops/autotune.CACHE_FORMAT


def _tuning_cache_errors(path: str, doc) -> list[str]:
    """Full strict-schema check for the autotune cache artifact, delegated
    to the single source of truth — ops/autotune.validate_cache_doc —
    loaded by FILE PATH (autotune is stdlib-only at import, like
    train/resilience) so this validator stays jax-free."""
    import importlib.util

    at_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "distributed_lion_tpu", "ops", "autotune.py")
    try:
        spec = importlib.util.spec_from_file_location("dlt_autotune_vm",
                                                      at_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception as e:
        return [f"{path}: cannot load autotune validator ({e})"]
    return [f"{path}: {e}" for e in mod.validate_cache_doc(doc)]


# the serve-plane graft-check matrix (analysis/serve_check.MATRIX): the
# banked artifact must carry every cell — a missing cell means a config
# axis silently dropped out of the contract. Kept as a literal so this
# validator stays importable on boxes without jax
# (tests/test_serve_check.py pins it against the live MATRIX).
_SERVE_CHECK_FORMAT = "dlt-serve-check-v1"
_SERVE_CHECK_CELLS = (
    "dense_tp0_bf16", "dense_tp0_nf4", "dense_tp1_bf16", "dense_tp2_bf16",
    "dense_tp2_nf4", "dense_tp0_ngram", "moe_ep1_bf16", "moe_ep2_bf16",
    "moe_ep2_batch_bf16", "moe_ep2_batch_tp2_bf16", "moe_ep2_nf4",
    "moe_ep2_ngram",
)


def _serve_check_errors(path: str, doc: dict) -> list[str]:
    """Strict schema of the serve-plane graft-check artifact
    (``python -m distributed_lion_tpu.analysis serve-check --json-out``;
    gated by check_evidence's ``static_serve`` stage). The deep fields
    are RE-DERIVED, not trusted: a forged ``ok: true`` over a mismatched
    inventory, a present host callback, lost donation, or an over-budget
    compile count is rejected from the document alone."""
    errors = []
    if doc.get("format") != _SERVE_CHECK_FORMAT:
        errors.append(f"{path}: format must be {_SERVE_CHECK_FORMAT!r}")
    if doc.get("ok") is not True:
        errors.append(f"{path}: top-level ok must be true")
    if not isinstance(doc.get("world"), int) or doc.get("world", 0) < 4:
        errors.append(f"{path}: world must be an int >= 4 (full matrix)")
    for k in ("backend", "jax"):
        if not isinstance(doc.get(k), str):
            errors.append(f"{path}: {k!r} must be a string")
    cells = doc.get("cells")
    if not isinstance(cells, list) or not cells:
        errors.append(f"{path}: 'cells' must be a non-empty list")
        cells = []
    names = [c.get("cell") for c in cells if isinstance(c, dict)]
    for want in _SERVE_CHECK_CELLS:
        if want not in names:
            errors.append(f"{path}: matrix cell {want!r} missing")
    for cell in cells:
        if not isinstance(cell, dict):
            errors.append(f"{path}: cell entry is not an object")
            continue
        cname = cell.get("cell", "?")
        if cell.get("ok") is not True:
            errors.append(f"{path}: cells[{cname}].ok must be true")
        disp = cell.get("dispatches")
        if not isinstance(disp, dict) or not disp:
            errors.append(f"{path}: cells[{cname}].dispatches must be a "
                          "non-empty object")
            continue
        need = {"decode", "cow"}
        if cell.get("speculate"):
            need.add("verify")
        if not any(d.startswith("prefill:") for d in disp):
            errors.append(f"{path}: cells[{cname}] has no prefill bucket "
                          "dispatch")
        for d in sorted(need - set(disp)):
            errors.append(f"{path}: cells[{cname}] missing dispatch "
                          f"{d!r}")
        for dname, rep in disp.items():
            if not isinstance(rep, dict):
                errors.append(f"{path}: cells[{cname}].{dname} is not an "
                              "object")
                continue
            where = f"cells[{cname}].{dname}"
            if rep.get("ok") is not True:
                errors.append(f"{path}: {where}.ok must be true")
            obs, exp = rep.get("observed"), rep.get("expected")
            if not isinstance(obs, list) or not isinstance(exp, list):
                errors.append(f"{path}: {where} observed/expected must be "
                              "lists")
            elif obs != exp:  # re-derived, not trusted from ok flags
                errors.append(f"{path}: {where} collective inventory "
                              f"mismatch: observed {obs} != expected "
                              f"{exp}")
            if rep.get("host_callbacks") != []:
                errors.append(f"{path}: {where} has host callbacks "
                              f"{rep.get('host_callbacks')}")
            don = rep.get("donation")
            if not isinstance(don, dict) or (
                    don.get("aliased_outputs", 0)
                    + don.get("buffer_donors", 0)) <= 0:
                errors.append(f"{path}: {where} page-pool donation absent "
                              f"({don})")
            if rep.get("weight_upcasts") or rep.get("param_upcasts"):
                errors.append(f"{path}: {where} carries weight upcasts")
    compiles = doc.get("compile")
    if not isinstance(compiles, list) or not compiles:
        errors.append(f"{path}: 'compile' must be a non-empty list")
        compiles = []
    for comp in compiles:
        if not isinstance(comp, dict):
            errors.append(f"{path}: compile entry is not an object")
            continue
        cname = comp.get("cell", "?")
        counts, budget = comp.get("counts"), comp.get("budget")
        if not isinstance(counts, dict) or not isinstance(budget, dict):
            errors.append(f"{path}: compile[{cname}] counts/budget must "
                          "be objects")
            continue
        if counts.get("prefill", 0) <= 0:
            errors.append(f"{path}: compile[{cname}] measured no prefill "
                          "compiles — workload did not run")
        for k, v in counts.items():  # re-derived over-budget check
            # v == -1 is the "cache size unreadable" sentinel — rejected:
            # an unmeasurable count cannot evidence the budget
            if not isinstance(v, int) or v < 0 or v > budget.get(k, 0):
                errors.append(f"{path}: compile[{cname}] {k}={v} exceeds "
                              f"budget {budget.get(k, 0)}")
    return errors


def validate_json_doc(path: str) -> list[str]:
    """Strict single-document JSON artifact check (crash bundles,
    checkpoint manifests, and any other ``*.json`` the repo writes):
    strict parse (NaN/Infinity tokens rejected), a top-level object, and —
    for the known artifact names — the writer's required keys with sane
    shapes. Returns violation strings (empty = valid)."""
    errors: list[str] = []
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    try:
        doc = json.loads(raw, parse_constant=_reject_constant)
    except ValueError as e:
        return [f"{path}: {e}"]
    if not isinstance(doc, dict):
        return [f"{path}: document is {type(doc).__name__}, not an object"]
    name = os.path.basename(path)
    if name == "dcn_overlap.json":
        return _dcn_overlap_errors(path, doc)
    if name == "serving.json":
        return _serving_errors(path, doc)
    if name == "serve_check.json" or doc.get("format") == _SERVE_CHECK_FORMAT:
        return _serve_check_errors(path, doc)
    if name == "elasticity.json":
        return _elasticity_errors(path, doc)
    if name == "tuning_cache.json" or doc.get("format") == _TUNE_CACHE_FORMAT:
        # dispatch on the embedded format stamp as well as the canonical
        # name: a cache at any $DLT_TUNE_CACHE path (the documented drive)
        # must get the strict schema, not just the generic checks
        return _tuning_cache_errors(path, doc)
    for key in _DOC_SCHEMAS.get(name, ()):
        if key not in doc:
            errors.append(f"{path}: missing required key {key!r}")
    if name in _DOC_SCHEMAS and not isinstance(doc.get("step"), int):
        errors.append(f"{path}: 'step' must be an integer")
    if name == "manifest.json" and isinstance(doc.get("files"), dict):
        for rel, info in doc["files"].items():
            if not isinstance(info, dict):
                errors.append(f"{path}: files[{rel!r}] is not an object")
                continue
            if not _SHA256.match(str(info.get("sha256", ""))):
                errors.append(f"{path}: files[{rel!r}] has no valid sha256")
            if not isinstance(info.get("bytes"), int):
                errors.append(f"{path}: files[{rel!r}] has no integer bytes")
    elif name == "manifest.json" and "files" in doc:
        errors.append(f"{path}: 'files' must be an object")
    if name == "bundle.json" and "config" in doc and not isinstance(
            doc["config"], dict):
        errors.append(f"{path}: 'config' must be an object")
    return errors


def main(argv: list[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    failed = False
    for path in argv:
        if path.endswith(".jsonl"):
            # run-journal files (journal_rank<r>.jsonl + rotations,
            # journal_tail.jsonl in crash bundles) carry the journal
            # record schema; serve workloads (requests*.jsonl /
            # workload*.jsonl, the workload_gen output) and serve
            # responses (responses*.jsonl, the run_serve --out) carry
            # the serve/api line schemas; every other .jsonl is a
            # metrics log
            base = os.path.basename(path)
            if base.startswith("journal"):
                errors = validate_journal_file(path)
            elif base.startswith(("requests", "workload")):
                errors = validate_request_file(path)
            elif base.startswith("responses"):
                errors = validate_response_file(path)
            else:
                errors = validate_file(path)
        else:
            errors = validate_json_doc(path)
        if errors:
            failed = True
            for e in errors:
                print(f"INVALID {e}")
        else:
            print(f"ok {path}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
