"""DPO evidence row: step rate + comm bytes for the last reference
workload without numbers (VERDICT r4 #7).

Drives the REAL CLI (`distributed_lion_tpu.cli.run_dpo` — the repaired
semantics of the reference's broken ``dpo_llama2.py``; intended loop at
/root/reference/dpo_llama2.py:216-231) end to end on synthetic preference
pairs, then distills the trainer's own metrics.jsonl into one appended row
of $DPO_BENCH_OUT (default scripts/SWEEP_r3_raw/dpo.jsonl). Honest
provenance: the row carries backend/device_kind, so a CPU-mesh fallback
row (DLION_PLATFORM=cpu8, the tunnel-dead case) can never be mistaken for
a chip capture.

    DLION_PLATFORM=cpu8 python scripts/bench_dpo.py small:none:1:1:512:0
    python scripts/bench_dpo.py small:nf4:2:1:512:0      # on the chip

Spec grammar: model:quant_ref:batch_per_dev:accum:max_length:vocab_chunks.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULTS = ["small", "none", "1", "1", "512", "0"]
STEPS = int(os.environ.get("DPO_BENCH_STEPS", "30"))
LOG_EVERY = 5


def main() -> None:
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()
    spec = sys.argv[1] if len(sys.argv) > 1 else ":".join(DEFAULTS)
    parts = spec.split(":")
    model, quant_ref, bs, accum, max_len, vc = (
        parts + DEFAULTS[len(parts):])[:6]

    out_dir = os.environ.get("DPO_BENCH_DIR",
                             os.path.join(REPO, "runs", "dpo_bench"))
    shutil.rmtree(out_dir, ignore_errors=True)
    argv = [
        "--model_name", model, "--dataset", "synthetic",
        "--quant_ref", quant_ref,
        "--max_length", max_len, "--max_prompt_length",
        str(max(int(max_len) // 2, 8)),
        "--num_train_samples", "512", "--size_valid_set", "32",
        "--lion", "--async_grad",
        # pin the banked-row comm methodology (same pin as bench.py /
        # bench_sft_7b.py): every-step sign_psum voting, so rows rank
        # comparably across backends and against the sweep tables
        "--wire", "sign_psum", "--vote_every", "1",
        "--per_device_train_batch_size", bs,
        "--gradient_accumulation_steps", accum,
        "--vocab_chunks", vc,
        "--max_steps", str(STEPS), "--warmup_steps", "5",
        "--logging_steps", str(LOG_EVERY),
        # no mid-run eval/checkpoint pauses inside the timed window
        "--eval_steps", str(STEPS * 10), "--save_steps", str(STEPS * 10),
        "--learning_rate", "1e-4",
        "--output_dir", out_dir,
    ]
    from distributed_lion_tpu.cli.run_dpo import main as dpo_main

    t0 = time.time()
    dpo_main(argv)
    wall = time.time() - t0

    import jax

    dev = jax.devices()[0]
    rows = []
    with open(os.path.join(out_dir, "metrics.jsonl")) as f:
        for line in f:
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "train/tokens_per_sec" in d:
                rows.append(d)
    if not rows:
        raise SystemExit("[bench_dpo] no train metrics rows were logged")
    # the FIRST logged row includes compile; steady state = the rest
    steady = rows[1:] or rows
    tps = sum(r["train/tokens_per_sec"] for r in steady) / len(steady)
    row = {
        "workload": "DPO train step (policy+frozen ref, LoRA, vote-Lion)",
        "spec": spec, "model": model, "quant_ref": quant_ref,
        "batch_per_dev": int(bs), "accum": int(accum),
        "max_length": int(max_len), "vocab_chunks": int(vc),
        "steps": STEPS, "n_dev": len(jax.devices()),
        "backend": dev.platform, "device_kind": dev.device_kind,
        "tokens_per_sec_per_chip": round(tps / len(jax.devices()), 1),
        "comm_bytes_per_step": steady[-1].get("train/comm_bytes_per_step"),
        "final_loss": round(rows[-1].get("train/loss", 0.0), 4),
        "wall_s": round(wall, 1),
    }
    out_path = os.environ.get(
        "DPO_BENCH_OUT", os.path.join(REPO, "scripts", "SWEEP_r3_raw",
                                      "dpo.jsonl"))
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
