#!/bin/bash
# Sequential reduced-scale CPU parity legs — the tunnel-dead fallback for
# VERDICT r4 next-steps #1/#3: capture parity:local/vote/lazy as 2000-step
# curves at >=10M params on the CPU backend (runs/parity_cpu), so the
# round's scientific core claim (vote-Lion trajectory == local Lion,
# /root/reference/README.md:75-83) has committed data even if the TPU
# tunnel never opens. Full-scale TPU legs in runs/parity supersede these:
# the whole driver stands down only when runs/parity holds the COMPLETE
# qualifying set (all three modes) — a partial full-scale capture must not
# split the leg set across directories, because the parity:PASS criterion
# (check_evidence.parity_mad) only compares legs within one directory.
#
#   nohup bash scripts/parity_cpu_driver.sh > /tmp/parity_cpu_driver.log 2>&1 &
#
# Idempotent: per-mode skip defers to check_evidence's _leg_ok (the ONE
# leg-qualification rule: f32-stamped meta + >=1900 steps), and
# loss_parity's own mid-leg checkpoint makes a killed leg resume rather
# than restart. nice'd so a concurrently-firing TPU runbook window wins
# the single host core.
set -u
cd "$(dirname "$0")/.."
stamp() { date -u +%FT%TZ; }

full_set_captured() { # all three FULL-SCALE legs qualify => stand down
  python - <<'EOF'
import sys
sys.path.insert(0, "scripts")
import check_evidence as ce
ok = all(ce._leg_ok(ce._load_leg("parity", m))
         for m in ("local", "vote", "lazy"))
sys.exit(0 if ok else 1)
EOF
}

captured() { # $1 = mode; qualification delegated to check_evidence._leg_ok
  # on the CPU directory only (presence-based, not the numeric-PASS gate: a
  # deterministic failing leg would re-run forever producing identical data)
  python - "$1" <<'EOF'
import sys
sys.path.insert(0, "scripts")
import check_evidence as ce
sys.exit(0 if ce._leg_ok(ce._load_leg("parity_cpu", sys.argv[1])) else 1)
EOF
}

if full_set_captured; then
  echo "$(stamp) full-scale runs/parity leg set already captured; no CPU legs needed"
  exit 0
fi

for mode in local vote lazy; do
  if captured "$mode"; then
    echo "$(stamp) parity_cpu:$mode leg already qualifies; skipping"
    continue
  fi
  # retry transient failures (loss_parity's mid-leg checkpoint makes a
  # retry resume, not restart); after 3 strikes move on to the next mode
  # rather than hard-exiting — one stuck leg must not stall the whole
  # fallback program (code-review r5)
  ok=0
  for attempt in 1 2 3; do
    echo "$(stamp) running reduced parity leg: $mode (attempt $attempt)"
    if nice -n 15 python scripts/loss_parity.py --phase run --mode "$mode" \
        --reduced --steps 2000; then
      ok=1; break
    fi
    echo "$(stamp) leg $mode attempt $attempt failed"
    sleep 60
  done
  if [ "$ok" = 1 ]; then
    git add runs/parity_cpu && git commit -q \
      -m "Capture reduced CPU parity leg: $mode" && \
      echo "$(stamp) committed $mode leg"
  else
    echo "$(stamp) leg $mode FAILED after 3 attempts; continuing"
  fi
done
python scripts/loss_parity.py --phase report --out runs/parity_cpu \
  && git add runs/parity_cpu && git commit -q -m "Parity report for reduced CPU legs" \
  && echo "$(stamp) report committed"
echo "$(stamp) parity driver done"
