#!/bin/bash
# Unattended version of TPU_RUNBOOK.md: capture every missing evidence axis
# in priority order, tolerating individual failures. Outputs land in
# scripts/SWEEP_r3_raw/ for the operator to fold into the .md evidence files.
#
# Ordering rationale: bench.py FIRST — it refreshes
# scripts/last_tpu_measurement.json within ~5 min of the tunnel recovering,
# so even a window too short for the sweep converts the headline from
# round-2-attested to this-round-measured. Then the sweep (new levers), a
# re-bench under the best untested config via env knobs, then 7B and the
# parity curves (longest).
set -u
cd "$(dirname "$0")/.."
OUT=scripts/SWEEP_r3_raw
mkdir -p "$OUT"
stamp() { date -u +%FT%TZ; }

echo "$(stamp) runbook start" | tee -a "$OUT/log.txt"

# NB: capture rc BEFORE the echo — $(stamp) inside the echo would reset $?
timeout 1200 python bench.py > "$OUT/bench_flagship.json" 2> "$OUT/bench_flagship.err"
rc=$?; echo "$(stamp) bench(flagship) rc=$rc" | tee -a "$OUT/log.txt"

# splash:16 and splash:8 without chunks already measured this round
# (61.5k / 55.6k, /tmp/sweep_r3.log) — highest-value configs first so a
# short window still captures the vocab_chunks lever
timeout 3000 python scripts/bench_sweep.py \
    noremat:4:xla:16:bf16:8 noremat:8:xla:8:bf16:8 \
    noremat:8:xla:16:bf16:8 noremat:16:xla:4:bf16:8 \
    noremat:2:xla:32:bf16:8 noremat:4:xla:16:bf16:8:bf16 \
    noremat:4:xla:16:bf16:0:bf16 noremat:4:splash:16:bf16:8 \
    noremat:4:flash@256x512:16:bf16:0 noremat:4:flash@512x1024:16:bf16:0 \
    > "$OUT/sweep.jsonl" 2> "$OUT/sweep.err"
rc=$?; echo "$(stamp) sweep rc=$rc" | tee -a "$OUT/log.txt"

# re-bench under the sweep's strongest NEW lever (vocab_chunks) using the
# env knobs — bench.py only records last_tpu_measurement.json when the run
# beats nothing (it always overwrites); keep the flagship artifact by
# re-running the stock config LAST if the chunked one was slower
timeout 1200 env BENCH_VOCAB_CHUNKS=8 python bench.py \
    > "$OUT/bench_chunks8.json" 2> "$OUT/bench_chunks8.err"
rc=$?; echo "$(stamp) bench(chunks8) rc=$rc" | tee -a "$OUT/log.txt"
python - "$OUT" <<'EOF'
import json, os, sys
out = sys.argv[1]
def val(p):
    try:
        with open(p) as f:
            d = json.load(f)
        return d.get("value", 0) if d.get("backend") == "tpu" else 0
    except Exception:
        return 0
flag, chunk = val(f"{out}/bench_flagship.json"), val(f"{out}/bench_chunks8.json")
print(f"flagship={flag} chunks8={chunk}")
# last_tpu_measurement.json now holds the chunks8 run; restore the better
# record marker for the operator to promote into bench.py's default config
best = "chunks8" if chunk >= flag else "flagship"
with open(f"{out}/BEST.txt", "w") as f:
    f.write(f"{best}\n")
EOF
if [ -f "$OUT/BEST.txt" ] && [ "$(cat "$OUT/BEST.txt")" = "flagship" ]; then
  timeout 1200 python bench.py > "$OUT/bench_flagship2.json" 2> "$OUT/bench_flagship2.err"
  echo "$(stamp) re-bench stock config to restore artifact" | tee -a "$OUT/log.txt"
fi

# third spec: long-context leg (T=2048 — the attention auto-dispatch's
# flash regime) at the same 7B NF4 QLoRA shapes
timeout 3000 python scripts/bench_sft_7b.py nf4:1:4:8 nf4:1:4:8::1024:dots \
    nf4:1:2:8::2048:dots \
    > "$OUT/sft7b.jsonl" 2> "$OUT/sft7b.err"
rc=$?; echo "$(stamp) 7b rc=$rc" | tee -a "$OUT/log.txt"

for mode in local vote lazy; do
  timeout 3600 python scripts/loss_parity.py --phase run --mode "$mode" \
      --steps 2000 >> "$OUT/parity_$mode.log" 2>&1
  rc=$?; echo "$(stamp) parity:$mode rc=$rc" | tee -a "$OUT/log.txt"
done
python scripts/loss_parity.py --phase report >> "$OUT/log.txt" 2>&1
echo "$(stamp) runbook done" | tee -a "$OUT/log.txt"
