#!/bin/bash
# Unattended version of TPU_RUNBOOK.md: capture every missing evidence axis
# in priority order, tolerating individual failures. Outputs land in
# scripts/SWEEP_r3_raw/ for the operator to fold into the .md evidence files.
set -u
cd "$(dirname "$0")/.."
OUT=scripts/SWEEP_r3_raw
mkdir -p "$OUT"
stamp() { date -u +%FT%TZ; }

echo "$(stamp) runbook start" | tee -a "$OUT/log.txt"

# NB: capture rc BEFORE the echo — $(stamp) inside the echo would reset $?
# splash:16 and splash:8 without chunks already measured this round
# (61.5k / 55.6k, /tmp/sweep_r3.log) — highest-value configs first so a
# short window still captures the vocab_chunks lever
timeout 2400 python scripts/bench_sweep.py \
    noremat:4:xla:16:bf16:8 noremat:8:xla:8:bf16:8 \
    noremat:8:xla:16:bf16:8 noremat:16:xla:4:bf16:8 \
    noremat:4:xla:16:bf16:0:bf16 noremat:4:splash:16:bf16:8 \
    noremat:4:flash@256x512:16:bf16:0 noremat:4:flash@512x1024:16:bf16:0 \
    > "$OUT/sweep.jsonl" 2> "$OUT/sweep.err"
rc=$?; echo "$(stamp) sweep rc=$rc" | tee -a "$OUT/log.txt"

timeout 1200 python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"
rc=$?; echo "$(stamp) bench rc=$rc" | tee -a "$OUT/log.txt"

timeout 2400 python scripts/bench_sft_7b.py nf4:1:4:8 nf4:1:4:8::1024:dots \
    > "$OUT/sft7b.jsonl" 2> "$OUT/sft7b.err"
rc=$?; echo "$(stamp) 7b rc=$rc" | tee -a "$OUT/log.txt"

for mode in local vote lazy; do
  timeout 3600 python scripts/loss_parity.py --phase run --mode "$mode" \
      --steps 2000 >> "$OUT/parity_$mode.log" 2>&1
  rc=$?; echo "$(stamp) parity:$mode rc=$rc" | tee -a "$OUT/log.txt"
done
python scripts/loss_parity.py --phase report >> "$OUT/log.txt" 2>&1
echo "$(stamp) runbook done" | tee -a "$OUT/log.txt"
