"""Microbenchmark the vote wires at 124M-scale ballot vectors.

Measures wall-clock per vote and trace+compile time for each wire format
(``sign_psum``, ``packed_allgather``, ``packed_a2a``, ``hier:<g>``) over a
mesh — the real chip mesh when multiple accelerators are attached, else a
forced-host-device CPU mesh (collectives are then shared-memory copies, so
absolute latency is a proxy; byte volumes and compile behavior are exact).

The compile-time column is the point of the scan-based rings
(parallel/collectives._hier_elect): pre-scan, a hier ring at g=16 unrolled
3(g−1) ppermute ops into the trace; now the trace is O(1) in g.

    python scripts/bench_wires.py --n 124000000 --world 8 \
        --wires sign_psum packed_allgather packed_a2a hier:2 hier:4
    python scripts/bench_wires.py --compile-only --world 32 \
        --wires hier:16 --n 65536

Each run prints one JSON line per (wire, world) combo; paste into
scripts/SWEEP_wires.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def run_inner(args) -> None:
    import numpy as np

    if args.force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_lion_tpu.parallel.collectives import vote_total
    from distributed_lion_tpu.ops.codec import wire_bytes_per_param

    w = args.world
    devs = jax.devices()
    if len(devs) < w:
        raise SystemExit(f"need {w} devices, have {len(devs)}")
    mesh = Mesh(np.array(devs[:w]), ("data",))
    n = args.n
    rng = np.random.default_rng(0)
    # uint8 draw, not rng.random: a float64 [w, n] transient would be ~8 GB
    # at the default 124M-coordinate size
    votes_np = rng.integers(0, 2, (w, n), dtype=np.uint8).astype(bool)

    for wire in args.wires:
        def body(v):
            # chain XOR of the elected bits back into the ballots so
            # repeated votes are data-dependent (no DCE / overlap games)
            elected = vote_total(v[0], "data", wire) > 0
            return jnp.logical_xor(v[0], elected)[None]

        f = jax.jit(
            jax.shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data"))
        )
        votes = jax.device_put(
            jnp.asarray(votes_np), NamedSharding(mesh, P("data")))

        t0 = time.perf_counter()
        lowered = f.lower(votes)
        t_trace = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
        stablehlo_lines = lowered.as_text().count("\n")

        acct = wire_bytes_per_param(n, w, wire)
        row = {
            "wire": wire,
            "world": w,
            "n": n,
            "backend": devs[0].platform,
            "trace_s": round(t_trace, 3),
            "compile_s": round(t_compile, 3),
            "stablehlo_lines": stablehlo_lines,
            "bits_per_param": acct.get("bits_per_param"),
        }
        if not args.compile_only:
            out = compiled(votes)
            jax.block_until_ready(out)  # warmup
            reps = args.reps
            t0 = time.perf_counter()
            for _ in range(reps):
                out = compiled(out)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / reps
            row["vote_ms"] = round(dt * 1e3, 2)
            row["effective_GBps"] = round(
                acct["bytes_per_step"] / dt / 1e9, 3)
        print(json.dumps(row), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=124_000_000)
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--wires", nargs="+",
                    default=["sign_psum", "packed_allgather", "packed_a2a",
                             "hier:2", "hier:4"])
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--compile-only", action="store_true")
    ap.add_argument("--inner", action="store_true")
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--timeout", type=float, default=1800.0)
    args = ap.parse_args()

    if args.inner:
        run_inner(args)
        return

    # Orchestrate in a child so a hung accelerator backend can't wedge the
    # run (memory: the axon tunnel hangs jax.devices() for hours), and so
    # the forced host-device count lands before jax import.
    env = dict(os.environ)
    try:
        import jax  # noqa: F401  — probe only in the child

        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(len(d), d[0].platform)"],
            capture_output=True, text=True, timeout=120, env=env,
        )
        n_real, backend = (probe.stdout.split() + ["", ""])[:2] \
            if probe.returncode == 0 else ("0", "")
    except Exception:
        n_real, backend = "0", ""
    use_real = backend in ("tpu", "gpu") and int(n_real) >= args.world
    child = [sys.executable, os.path.abspath(__file__), "--inner",
             "--n", str(args.n), "--world", str(args.world),
             "--reps", str(args.reps), "--wires", *args.wires]
    if args.compile_only:
        child.append("--compile-only")
    if not use_real:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count={args.world}")
        child.append("--force-cpu")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(child, timeout=args.timeout, env=env, cwd=repo_root)
    sys.exit(proc.returncode)


if __name__ == "__main__":
    main()
