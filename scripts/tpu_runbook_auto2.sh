#!/bin/bash
# Round-4 evidence runbook, ordered for SHORT tunnel windows (round-3
# windows lasted ~30 min; the full program needs ~4-5h of chip):
#
#   1. bench_best   — ~8 min: re-capture bench.py under the banked sweep
#                     winner (98.1k config) so the headline artifact is a
#                     driver-methodology TPU number as early as possible.
#   2. sweep3       — the >100k anchor-chasing configs (lever stacking +
#                     T=2048 legs).
#   3. bench_best2  — if sweep3 found something above the new headline,
#                     re-capture once more.
#   4. sweep2       — the remaining round-3 lever table (completes the
#                     published sweep evidence).
#   4c. autotune    — the device-keyed tile search (cli/run_tune: flash
#                     fwd/bwd + splash tiles, lion row_block, vocab_chunks,
#                     vote_buckets; per-candidate timeout guards) followed
#                     by a promote-gate re-fire under attn=auto so the
#                     tuned kernels become the headline mechanically.
#   5. sft7b        — NF4+LoRA Llama-2-7B rows (per-spec skip on re-fire).
#   6. parity legs  — 3 x 2000 steps (mid-leg checkpoint/resume: a window
#                     drop costs <=250 steps, not the leg).
#   7. conv         — 2000-step real-corpus canonical-config run (Orbax
#                     resume).
#
# IDEMPOTENT: capture-complete stages skip themselves; sweep stages run
# unconditionally but skip per-config via SWEEP_SKIP_FILE (so transiently
# errored configs retry on every recovery); the loop watcher
# (tpu_watch_loop.sh) re-runs the whole runbook after a mid-run tunnel
# drop without re-burning chip time on captured work.
set -u
cd "$(dirname "$0")/.."
OUT=scripts/SWEEP_r3_raw
mkdir -p "$OUT"
stamp() { date -u +%FT%TZ; }

echo "$(stamp) stage-2 runbook start" | tee -a "$OUT/log.txt"

# ---- 0. static-analysis gate (ISSUE 4, ~1 min, no chip time): ruff +
# graft-check tier-1 AST lint + shellcheck via ci_static.sh, then the
# jaxpr contract tier — trace the REAL train step for every wire x
# vote_buckets cell on this backend and assert the collective inventory
# matches the wire recipe (the static counterpart of comm_drift_bytes),
# zero host callbacks, donation applied, no bf16-param upcasts. The
# tier-2 report is the capture artifact check_evidence's `static` stage
# reads; tier 1 re-runs inside check_evidence on every poll.
if python scripts/check_evidence.py static; then
  echo "$(stamp) static gate already green — skip" | tee -a "$OUT/log.txt"
else
  bash scripts/ci_static.sh >> "$OUT/static.log" 2>&1
  rc=$?; echo "$(stamp) ci_static rc=$rc" | tee -a "$OUT/log.txt"
  timeout -k 30 900 python -m distributed_lion_tpu.analysis --tier2 \
      --json-out "$OUT/static_tier2.json" >> "$OUT/static.log" 2>&1
  rc=$?; echo "$(stamp) graft-check tier2 rc=$rc" | tee -a "$OUT/log.txt"
fi

# ---- 0b. serve-plane graft-check (ISSUE 19, ~1 min, no chip time):
# build the real ServingEngine for every serving-matrix cell (tp x ep x
# ep_batch x quant x speculate) and walk the jaxprs/MLIR of the actual
# registered dispatches — collective inventory vs the config-derived
# expectation, zero host callbacks in any dispatch (every prefill
# bucket included), page-pool donation, weight-upcast scan, compile
# counts within the power-of-two bucket budget. The committed
# runs/static/serve_check.json is the capture artifact check_evidence's
# `static_serve` stage (and ci_static.sh) validates.
if python scripts/check_evidence.py static_serve; then
  echo "$(stamp) static_serve gate already green — skip" | tee -a "$OUT/log.txt"
else
  mkdir -p runs/static
  timeout -k 30 900 python -m distributed_lion_tpu.analysis serve-check \
      --json-out runs/static/serve_check.json >> "$OUT/static.log" 2>&1
  rc=$?; echo "$(stamp) graft-check serve rc=$rc" | tee -a "$OUT/log.txt"
fi

# Pick the best promotable sweep row across sweep*.jsonl and re-bench
# bench.py under it via env knobs so last_tpu_measurement.json reflects
# the best measured config. $1 names the run-at-most-once marker: without
# it, a re-bench that measures BELOW its sweep row would leave recorded <
# best forever and re-burn ~10 min of chip on every watcher recovery.
bench_best_stage() {
  local marker="$1"
  if [ -e "$OUT/$marker.done" ]; then
    echo "$(stamp) $marker already captured — skip" | tee -a "$OUT/log.txt"
    return
  fi
python - "$OUT" > "$OUT/winner.env" <<'EOF'
import glob, json, sys
sys.path.insert(0, ".")
from bench import sweep_row_promotable  # the ONE promotability rule

rows = []
for path in sorted(glob.glob(f"{sys.argv[1]}/sweep*.jsonl")):
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    try:  # tolerate a line truncated by a mid-sweep drop
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if sweep_row_promotable(d):
                        rows.append(d)
    except OSError:
        pass
try:
    with open("scripts/last_tpu_measurement.json") as f:
        recorded = json.load(f).get("value", 0.0)
except Exception:
    recorded = 0.0
if rows:
    best = max(rows, key=lambda d: d["tokens_per_sec_per_chip"])
    if best["tokens_per_sec_per_chip"] <= recorded:
        sys.exit(0)  # headline already >= every sweep row: nothing to do
    print(f"export BENCH_ATTN={best['attn']}")
    print(f"export BENCH_VOCAB_CHUNKS={best.get('vocab_chunks', 8)}")
    md = best.get("mom_dtype", "")
    print(f"export BENCH_MOM_DTYPE={'' if md in ('', 'f32') else md}")
    print(f"export BENCH_BATCH={best['batch_per_dev']}")
    print(f"export BENCH_ACCUM={best['accum']}")
    print(f"export BENCH_VOCAB_PAD={best.get('vocab_pad', 0)}")
    print(f"export BENCH_REMAT={best.get('remat', 'noremat')}")
    print(f"export BENCH_DTYPE={best.get('dtype', 'bf16')}")
EOF
  if [ ! -s "$OUT/winner.env" ]; then
    echo "$(stamp) $marker: no sweep winner above the recorded headline" | tee -a "$OUT/log.txt"
    # nothing better to chase — mark done so the stage stops re-checking
    # only for the SECOND pass (the first must stay armed until a capture
    # happens: its purpose is a driver-methodology TPU number, and before
    # one exists the winner list is never empty)
    if [ "$marker" = "bench_best2" ]; then
      date -u +%FT%TZ > "$OUT/$marker.done"
    fi
    return
  fi
  tee -a "$OUT/log.txt" < "$OUT/winner.env"
  # shellcheck disable=SC1090
  . "$OUT/winner.env" 2>/dev/null || true
  # bench.py rewrites the headline artifact on every successful TPU run;
  # snapshot it so a winner that regresses vs the recorded number
  # (possible: combo interactions are untested) can't silently lower it
  cp scripts/last_tpu_measurement.json "$OUT/last_tpu.pre_best" 2>/dev/null || true
  # BENCH_PROMOTE marks the capture as the blessed flagship config: bare
  # `python bench.py` runs adopt promoted records' knobs as defaults
  timeout 1200 env BENCH_PROMOTE=1 python bench.py > "$OUT/$marker.json" 2> "$OUT/$marker.err"
  local rc=$?
  echo "$(stamp) $marker rc=$rc" | tee -a "$OUT/log.txt"
  unset BENCH_ATTN BENCH_VOCAB_CHUNKS BENCH_MOM_DTYPE BENCH_BATCH BENCH_ACCUM BENCH_VOCAB_PAD BENCH_REMAT BENCH_DTYPE
  if [ $rc -eq 0 ] && grep -q '"backend": "tpu"' "$OUT/$marker.json"; then
    date -u +%FT%TZ > "$OUT/$marker.done"
    # check_evidence's bench_best stage reads bench_best.done — a second-
    # pass capture satisfies the same evidence axis
    [ "$marker" = "bench_best2" ] && date -u +%FT%TZ > "$OUT/bench_best.done"
  fi
python - "$OUT" >> "$OUT/log.txt" <<'EOF'
import json, sys
out = sys.argv[1]
def val(p):
    try:
        with open(p) as f:
            d = json.load(f)
        return d.get("value", 0.0) if d.get("backend") == "tpu" else 0.0
    except Exception:
        return 0.0
new = val("scripts/last_tpu_measurement.json")
old = val(f"{out}/last_tpu.pre_best")
if old > new:
    import shutil
    shutil.copy(f"{out}/last_tpu.pre_best", "scripts/last_tpu_measurement.json")
    print(f"bench(best) {new} < prior {old}: restored prior headline artifact")
else:
    print(f"bench(best) {new} >= prior {old}: new headline artifact kept")
EOF
}

# ---- 1. headline capture under the banked winner (the 98,099 tok/s row
# is already committed in sweep2.jsonl, so this needs no sweep first)
bench_best_stage bench_best

# ---- 2. round-4 anchor-chasing window: stack the levers round 3
# measured singly (bwd tiles x vocab_pad x xla_bf16-scores x dots-remat x
# chunk count), then the T=2048 long-context legs (flash's memory regime;
# NOT anchor-comparable — the anchor is the T=1024 canonical workload).
# The last config (batch 2, bwd tiles, T=2048) is check_evidence's sweep3
# marker.
{
  timeout 3600 env SWEEP_SKIP_FILE="$OUT/sweep3.jsonl" BENCH_REQUIRE_TPU=1 python scripts/bench_sweep.py \
      noremat:4:flash@512x1024@512x512:16:bf16:8:bfloat16:1024 \
      noremat:4:flash@512x1024@256x512:16:bf16:8:bfloat16:1024 \
      noremat:4:xla_bf16:16:bf16:8:bfloat16:1024 \
      noremat:4:flash@512x1024:16:bf16:4:bfloat16:1024 \
      noremat:8:flash@512x1024:16:bf16:8:bfloat16:1024 \
      dots:8:flash@512x1024:8:bf16:8:bfloat16 \
      noremat:2:flash@512x1024:16:bf16:8:bfloat16:0:2048 \
      noremat:2:flash@512x1024@512x512:16:bf16:8:bfloat16:0:2048 \
      >> "$OUT/sweep3.jsonl" 2>> "$OUT/sweep3.err"
  rc=$?; echo "$(stamp) sweep3 rc=$rc" | tee -a "$OUT/log.txt"
}

# ---- 3. if sweep3 beat the captured headline, re-capture once
bench_best_stage bench_best2

# ---- 4. the remaining round-3 lever table. APPEND (>>): sweep2.jsonl
# already holds the first combo window's banked winner
# (flash@512x1024+chunks8+bf16mom = 98,099 tok/s); flash@1024x1024 is
# excluded — its remote_compile hung >14 min and had to be killed.
{
  timeout 3000 env SWEEP_SKIP_FILE="$OUT/sweep2.jsonl" BENCH_REQUIRE_TPU=1 python scripts/bench_sweep.py \
      noremat:4:flash@512x1024:16:bf16:8:bfloat16:1024 \
      noremat:4:flash@512x1024:16:bf16:0:bfloat16:1024 \
      noremat:8:flash@512x1024:8:bf16:8:bfloat16 \
      noremat:4:flash@512x1024:32:bf16:8:bfloat16 \
      noremat:4:flash@512x512:16:bf16:8:bfloat16 \
      noremat:4:flash@256x1024:16:bf16:8:bfloat16 \
      noremat:4:xla_bf16:16:bf16:8:bfloat16 \
      noremat:4:flash@512x1024:16:bf16:16:bfloat16 \
      noremat:4:flash@512x1024@256x512:16:bf16:8:bfloat16 \
      noremat:4:flash@512x1024@512x512:16:bf16:8:bfloat16 \
      >> "$OUT/sweep2.jsonl" 2>> "$OUT/sweep2.err"
  rc=$?; echo "$(stamp) sweep2 rc=$rc" | tee -a "$OUT/log.txt"
}

# ---- 4b. vote-wire overlap ablation (ISSUE 1): the flagship anchor config
# at vote_buckets {1, 4, 16} — same workload and trajectory (elections are
# bit-identical at any B), only WHEN the ballot bytes move changes, so the
# ms_per_step deltas measure how much wire the bucket pipeline hides behind
# the fused apply. bench.overlap_from_ablation derives the recorded
# comm_overlap_frac from these rows; check_evidence stage 'overlap'.
if python scripts/check_evidence.py overlap; then
  echo "$(stamp) overlap ablation already captured — skip" | tee -a "$OUT/log.txt"
else
  timeout 3000 env SWEEP_SKIP_FILE="$OUT/overlap.jsonl" BENCH_REQUIRE_TPU=1 python scripts/bench_sweep.py \
      noremat:4:flash@512x1024:16:bf16:8:bfloat16:0:1024:1 \
      noremat:4:flash@512x1024:16:bf16:8:bfloat16:0:1024:4 \
      noremat:4:flash@512x1024:16:bf16:8:bfloat16:0:1024:16 \
      >> "$OUT/overlap.jsonl" 2>> "$OUT/overlap.err"
  rc=$?; echo "$(stamp) overlap rc=$rc" | tee -a "$OUT/log.txt"
fi

# ---- 4c. kernel autotune (ISSUE 6 tentpole): the device-keyed tile
# search on the real chip — flash fwd then bwd tiles, splash tiles, the
# Pallas lion row_block, vocab_chunks, vote_buckets — every candidate in
# its own process group under a hard compile+run budget (--timeout_s), so
# a pathological tile costs one budget, never the window (the
# flash@1024x1024 lesson: >14 min of hung remote compile in round 3).
# Winners commit to scripts/tuning_cache.json keyed by THIS chip's
# device_kind after every knob (atomic), so a dropped window keeps
# finished knobs; check_evidence 'autotune' reads captured only once
# EVERY knob has a TPU-keyed entry, and --skip_cached makes the re-fire
# resume at the first missing knob instead of re-measuring finished ones.
if python scripts/check_evidence.py autotune; then
  echo "$(stamp) autotune cache already captured — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 5400 python -m distributed_lion_tpu.cli.run_tune \
      --preset flagship --timeout_s 420 --skip_cached \
      >> "$OUT/autotune.log" 2>&1
  rc=$?; echo "$(stamp) autotune rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/validate_metrics.py scripts/tuning_cache.json \
      >> "$OUT/autotune.log" 2>&1 || true
  # ---- promote-gate re-fire under the tuned config: a bare attn=auto
  # bench now resolves the fresh cache at dispatch (the ONE resolver,
  # ops/autotune), so the capture measures the TUNED kernels; the
  # snapshot/restore guard mirrors bench_best_stage — a tuned capture
  # below the recorded headline must not lower the promoted artifact.
  if python scripts/check_evidence.py autotune; then
    cp scripts/last_tpu_measurement.json "$OUT/last_tpu.pre_tune" 2>/dev/null || true
    timeout 1200 env BENCH_PROMOTE=1 BENCH_ATTN=auto python bench.py \
        > "$OUT/bench_tuned.json" 2> "$OUT/bench_tuned.err"
    rc=$?; echo "$(stamp) bench(tuned) rc=$rc" | tee -a "$OUT/log.txt"
python - "$OUT" >> "$OUT/log.txt" <<'EOF'
import json, sys
out = sys.argv[1]
def val(p):
    try:
        with open(p) as f:
            d = json.load(f)
        return d.get("value", 0.0) if d.get("backend") == "tpu" else 0.0
    except Exception:
        return 0.0
new = val("scripts/last_tpu_measurement.json")
old = val(f"{out}/last_tpu.pre_tune")
if old > new:
    import shutil
    shutil.copy(f"{out}/last_tpu.pre_tune", "scripts/last_tpu_measurement.json")
    print(f"bench(tuned) {new} < prior {old}: restored prior headline artifact")
else:
    print(f"bench(tuned) {new} >= prior {old}: new headline artifact kept")
EOF
  fi
fi

# ---- 5. 7B QLoRA evidence with the FIXED spec parser + host-side init
# (the "axon,cpu" platform list exposes the host backend the init path
# uses; axon stays first = default, so compute still runs on the chip)
if python scripts/check_evidence.py sft7b; then
  echo "$(stamp) 7B already captured (last spec row present) — skip" | tee -a "$OUT/log.txt"
else
  timeout 3000 env JAX_PLATFORMS=axon,cpu SFT7B_SKIP_FILE="$OUT/sft7b2.jsonl" \
      python scripts/bench_sft_7b.py nf4:1:4:8 nf4:1:4:8::1024:dots \
      nf4:1:2:8::2048:dots \
      >> "$OUT/sft7b2.jsonl" 2>> "$OUT/sft7b2.err"
  rc=$?; echo "$(stamp) 7b(fixed) rc=$rc" | tee -a "$OUT/log.txt"
fi

# ---- 5b. DPO chip row (~3 min): the small-model DPO step on the real
# chip — the last workload without numbers (VERDICT r4 #7). tpu-guarded:
# a CPU fallback row satisfies the evidence stage but must not stop a
# live window from capturing a chip row once.
if python scripts/check_evidence.py dpo tpu; then
  echo "$(stamp) DPO chip row already captured — skip" | tee -a "$OUT/log.txt"
else
  timeout 900 python scripts/bench_dpo.py small:none:4:1:512:0 \
      >> "$OUT/dpo.log" 2>&1
  rc=$?; echo "$(stamp) dpo rc=$rc" | tee -a "$OUT/log.txt"
fi

# ---- 5c. vote-health telemetry artifact (ISSUE 2, ~2 min): a short
# --telemetry --nan_sentinel run on the chip mesh emits the vote-health
# JSONL (margin histogram / flip rate / disagreement / measured-vs-analytic
# comm drift) that check_evidence's 'telemetry' stage validates — the stage
# asserts the margin histogram conserves the voted-coordinate count and the
# JSONL is strict JSON (validate_metrics). sign_psum + vote_every 1 pins a
# tally wire so the margin histogram is exact; kernel stays auto so the
# Pallas stats kernel runs on real hardware at least once per round.
if python scripts/check_evidence.py telemetry; then
  echo "$(stamp) telemetry artifact already captured — skip" | tee -a "$OUT/log.txt"
else
  mkdir -p runs/telemetry
  timeout -k 60 900 python -m distributed_lion_tpu.cli.run_clm \
      --model_name tiny --dataset synthetic --lion --async_grad \
      --telemetry --nan_sentinel \
      --wire sign_psum --vote_every 1 --vote_buckets 4 \
      --per_device_train_batch_size 2 --gradient_accumulation_steps 1 \
      --block_size 128 --max_steps 60 --warmup_steps 5 \
      --logging_steps 10 --eval_steps 100000 --save_steps 100000 \
      --output_dir runs/telemetry \
      >> "$OUT/telemetry.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/telemetry/metrics.jsonl \
      >> "$OUT/telemetry.log" 2>&1 || rc=$?
  echo "$(stamp) telemetry rc=$rc" | tee -a "$OUT/log.txt"
fi

# ---- 5d. resilience artifact (ISSUE 3, ~3 min): a short async-checkpoint
# run (runs/resilience) plus a synchronous baseline (runs/resilience_sync)
# at the SAME model/cadence. check_evidence's 'resilience' stage then
# asserts (a) the async run's newest checkpoint VERIFIES — per-file sha256
# manifest + COMMITTED marker — and (b) its logged ckpt_stall_s peak is
# below the sync baseline's, i.e. save boundaries really stopped blocking
# the step loop on chip. save_steps 10 with logging_steps 1 guarantees a
# post-boundary log row pops the stall counter in both legs.
if python scripts/check_evidence.py resilience; then
  echo "$(stamp) resilience artifact already captured — skip" | tee -a "$OUT/log.txt"
else
  # gpt2_124m, not tiny: the ~1 GB params+momentum payload makes the sync
  # serialize+write+digest clearly dominate Orbax's fixed async bookkeeping,
  # and bs 4 x block 512 steps give the background commit a ~5s+ window per
  # save interval to fully hide in — the async peak is then initiation-only
  for leg in resilience resilience_sync; do
    mkdir -p "runs/$leg"
    async_flag=true; [ "$leg" = resilience_sync ] && async_flag=false
    timeout -k 60 900 python -m distributed_lion_tpu.cli.run_clm \
        --model_name gpt2_124m --dataset synthetic --lion --async_grad \
        --per_device_train_batch_size 4 --gradient_accumulation_steps 1 \
        --block_size 512 --max_steps 30 --warmup_steps 5 \
        --logging_steps 1 --eval_steps 100000 --save_steps 10 \
        --save_total_limit 2 --async_ckpt "$async_flag" \
        --output_dir "runs/$leg" \
        >> "$OUT/resilience.log" 2>&1
    rc=$?; echo "$(stamp) resilience leg $leg rc=$rc" | tee -a "$OUT/log.txt"
  done
  python scripts/check_evidence.py resilience \
    && echo "$(stamp) resilience artifact captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) resilience artifact FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5e. vote-guard artifact (ISSUE 5, ~3 min): four short same-seed legs
# under runs/vote_guard/. check_evidence's 'vote_guard' stage asserts
# (a) all-healthy bit-identity — clean vs clean_enforce log byte-identical
# loss curves (guard enforce with an all-True mask moves no election) —
# and (b) the degraded-mode claim: with one flipped-ballot (adversarial)
# worker, enforce quarantines it and its tail loss stays within
# GUARD_ENFORCE_EPS of the clean run while guard-off degrades by at least
# GUARD_MIN_GAP more. Constant LR (decay-to-zero would flatten the gap),
# sign_psum so the run also exercises the masked tally wire on chip.
if python scripts/check_evidence.py vote_guard; then
  echo "$(stamp) vote_guard artifact already captured — skip" | tee -a "$OUT/log.txt"
else
  for leg in clean clean_enforce poison_enforce poison_off; do
    mkdir -p "runs/vote_guard/$leg"
    guard=off; case "$leg" in *enforce) guard=enforce;; esac
    poison=""; case "$leg" in poison_*) poison="--inject_poison flipped_ballot:1";; esac
    timeout -k 60 900 python -m distributed_lion_tpu.cli.run_clm \
        --model_name tiny --dataset synthetic --lion --async_grad \
        --wire sign_psum --vote_every 1 --vote_buckets 1 \
        --vote_guard "$guard" --guard_strikes 2 --guard_cooldown 1000 \
        $poison \
        --learning_rate 5e-3 --lr_scheduler_type constant --weight_decay 0 \
        --per_device_train_batch_size 6 --gradient_accumulation_steps 1 \
        --block_size 32 --max_steps 40 --warmup_steps 0 \
        --logging_steps 1 --eval_steps 100000 --save_steps 100000 \
        --output_dir "runs/vote_guard/$leg" \
        >> "$OUT/vote_guard.log" 2>&1
    rc=$?; echo "$(stamp) vote_guard leg $leg rc=$rc" | tee -a "$OUT/log.txt"
  done
  python scripts/validate_metrics.py runs/vote_guard/*/metrics.jsonl \
      >> "$OUT/vote_guard.log" 2>&1 || true
  python scripts/check_evidence.py vote_guard \
    && echo "$(stamp) vote_guard artifact captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) vote_guard artifact FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5f. run-journal artifact (ISSUE 7, ~3 min): a short --journal
# training at the stage-4 bench shape (gpt2_124m, the promoted-config
# model/cadence, async checkpoints ON so the ckpt spans have something to
# show) under runs/journal, then cli/run_analyze over it — step-time
# attribution (device/dispatch/data/ckpt/logging), top stall sources, and
# a diff against the promoted headline's journal_attribution so the next
# MFU push starts from a NAMED stall budget. check_evidence's 'journal'
# stage asserts the journal parses under the strict schema, the
# attribution closes, and >=95% of measured step wall lands in named
# buckets (the ISSUE-7 acceptance criterion, on a real leg).
if python scripts/check_evidence.py journal; then
  echo "$(stamp) journal artifact already captured — skip" | tee -a "$OUT/log.txt"
else
  mkdir -p runs/journal
  timeout -k 60 900 python -m distributed_lion_tpu.cli.run_clm \
      --model_name gpt2_124m --dataset synthetic --lion --async_grad \
      --journal \
      --per_device_train_batch_size 4 --gradient_accumulation_steps 1 \
      --block_size 512 --max_steps 30 --warmup_steps 5 \
      --logging_steps 5 --eval_steps 100000 --save_steps 10 \
      --save_total_limit 2 \
      --output_dir runs/journal \
      >> "$OUT/journal.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/journal/journal/journal_rank*.jsonl \
      >> "$OUT/journal.log" 2>&1 || rc=$?
  python -m distributed_lion_tpu.cli.run_analyze runs/journal \
      --baseline scripts/last_tpu_measurement.json \
      --json-out "$OUT/journal_analyze.json" \
      >> "$OUT/journal.log" 2>&1 || rc=$?
  echo "$(stamp) journal rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py journal \
    && echo "$(stamp) journal artifact captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) journal artifact FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5g. DCN-overlap artifact (ISSUE 8, ~4 min): scripts/bench_dcn.py —
# the hier wire's cross-step pipelined level-2 leg under an injected
# 100 ms dcn_delay link at depth {0,1,2} (W=4, g=2), the depth-0
# bit-identity legs, the bits-per-param x steps-to-loss frontier, and the
# pre-registered depth {1,2} loss-parity bound. The link is EMULATED on
# every backend (collectives' launch/consume gates), so the committed
# CPU-produced artifact is first-class evidence; this stage re-captures it
# on chip so the pipeline is also proven against real XLA async
# scheduling. check_evidence's 'dcn_overlap' stage judges the artifact
# (schema via validate_metrics, overlap >= 0.8 at depth 1, parity PASS).
if python scripts/check_evidence.py dcn_overlap \
    && [ "$(python -c 'import json;print(json.load(open("runs/dcn_overlap/dcn_overlap.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) dcn_overlap artifact already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1200 python scripts/bench_dcn.py --out runs/dcn_overlap \
      >> "$OUT/dcn_overlap.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/dcn_overlap/dcn_overlap.json \
      >> "$OUT/dcn_overlap.log" 2>&1 || rc=$?
  echo "$(stamp) dcn_overlap rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py dcn_overlap \
    && echo "$(stamp) dcn_overlap artifact captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) dcn_overlap artifact FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5h. serving bench artifact (ISSUE 9, ~5 min): scripts/bench_serve.py
# — the continuous-batching paged-KV decode engine at batch {32,128,256}
# (tokens/s/chip rows + NF4-vs-bf16 weight bytes + prefill-share ablation
# + live-recomputed bit-identity markers). The committed CPU smoke
# artifact (tiny model) is first-class mechanism evidence; this stage
# re-captures it on chip at gpt2_124m so serving regressions gate against
# real TPU numbers. check_evidence's 'serving' stage judges the artifact
# (schema via validate_metrics, both bit-identity markers, tokens/s floor
# at every required batch, nf4 < bf16/3 bytes).
if python scripts/check_evidence.py serving \
    && [ "$(python -c 'import json;print(json.load(open("runs/serving/serving.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) serving artifact already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) serving rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py serving \
    && echo "$(stamp) serving artifact captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) serving artifact FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5i. live-elasticity artifact (ISSUE 10, ~4 min):
# scripts/bench_elasticity.py — the control plane's worker leave/join
# without a restart at W=4 (drop worker 2 at step k, re-absorb at k+m):
# the survive leg, the degraded bit-identity legs (departed-from-step-0 ==
# masked-from-scratch W−1), the journal-read membership timeline, and the
# pre-registered post-rejoin parity bound. The committed CPU artifact is
# first-class mechanism evidence (membership transitions are host-side
# mask flips on every backend); this stage re-captures on chip so the
# numbers carry real-fabric scheduling. check_evidence's 'elasticity'
# stage judges the artifact (schema via validate_metrics, survival facts,
# both bit-identity markers, timeline events, parity pass).
if python scripts/check_evidence.py elasticity \
    && [ "$(python -c 'import json;print(json.load(open("runs/elasticity/elasticity.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) elasticity artifact already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1200 python scripts/bench_elasticity.py --out runs/elasticity \
      >> "$OUT/elasticity.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/elasticity/elasticity.json \
      >> "$OUT/elasticity.log" 2>&1 || rc=$?
  echo "$(stamp) elasticity rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py elasticity \
    && echo "$(stamp) elasticity artifact captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) elasticity artifact FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5j. speculative-decode frontier (ISSUE 11, ~3 min): the
# draft/verify/commit accept-rate × tokens/s/chip frontier over drafter
# (ngram prompt-lookup, draft self-draft smoke) × k on a repetitive and a
# random workload, plus live-recomputed speculative identity markers
# (greedy speculative == plain paged decode; sampled speculative == the
# same per-request PRNG stream). bench_serve writes it into the SAME
# runs/serving/serving.json that stage 5h captures, so a fresh 5h capture
# already carries it — this stage only re-runs the bench when the banked
# artifact predates the speculative section (or a marker failed).
# check_evidence's 'speculative' stage judges it (strict schema incl.
# accept_rate ∈ [0,1], both markers, a baseline + both drafters on both
# workloads, ngram accept_rate > 0 on the repetitive traffic).
if python scripts/check_evidence.py speculative \
    && [ "$(python -c 'import json;print(json.load(open("runs/serving/serving.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) speculative frontier already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) speculative rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py speculative \
    && echo "$(stamp) speculative frontier captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) speculative frontier FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5k. TP serving + prefix sharing (ISSUE 13, ~5 min): the
# tp_serving section of the SAME runs/serving/serving.json — TP-degree
# decode rows (tokens/s/CHIP + p50/p99 tick latency at tp {1,2,4}: on a
# v5e slice the degrees that divide the model's heads run, the rest are
# dropped loudly), the 256-request shared-system-prompt memory leg
# (prefix_mem_ratio = physical ÷ logical pages, both measured), and the
# five live-recomputed identity markers (tp1/tpN vs unsharded;
# shared vs unshared greedy/sampled/speculative). bench_serve writes it
# alongside stages 5h/5j's sections, so a fresh 5h capture already
# carries it — this stage only re-runs the bench when the banked
# artifact predates ISSUE 13 (or a marker/ratio failed).
# check_evidence's 'tp_serving' stage judges it (strict schema, all five
# markers, a tp>=2 row above the tokens/s floor, ratio <= 0.15).
if python scripts/check_evidence.py tp_serving \
    && [ "$(python -c 'import json;print(json.load(open("runs/serving/serving.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) tp_serving section already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) tp_serving rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py tp_serving \
    && echo "$(stamp) tp_serving section captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) tp_serving section FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5l. elastic-serving fault matrix (ISSUE 14, ~3 min): the
# serve_resilience section of the SAME runs/serving/serving.json — the
# replica plane's crash-at-tick matrix (tokens lost == 0 and migrated
# outputs token-identical at every cut, recovery-latency column), the
# one-slow-replica leg (per-replica p99 tick latency vs clean, detection
# + route-around), the drain and rejoin legs, and the eight identity
# markers recomputed live (greedy/sampled/speculative/prefix-cache
# migration identity, zero token loss, drain/slow/rejoin behavior).
# bench_serve writes it alongside stages 5h/5j/5k's sections, so a fresh
# 5h capture already carries it — this stage only re-runs the bench when
# the banked artifact predates ISSUE 14 (or a marker failed).
# check_evidence's 'serve_resilience' stage judges it (strict schema,
# all eight markers, >= 3 crash cut points each with zero loss and at
# least one real migration, slow-replica p99 above its clean peer's).
if python scripts/check_evidence.py serve_resilience \
    && [ "$(python -c 'import json;print(json.load(open("runs/serving/serving.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) serve_resilience section already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) serve_resilience rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py serve_resilience \
    && echo "$(stamp) serve_resilience section captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) serve_resilience section FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5m. MoE serving (ISSUE 15 + 16, ~5 min): the moe_serving section
# of the SAME runs/serving/serving.json — the dense-vs-MoE-vs-MoE+ep
# decode matrix (tokens/s/CHIP at the standard batches with
# expert-capacity utilization + dropped-rate columns from the engine's
# on-device routing stats), with each ep degree measured BOTH replicated
# and batch-sharded (ISSUE 16's throughput-lever rows, sharding =
# 'replicated' | 'batch' + the beats_dense_per_chip column), and the TEN
# live-recomputed identity markers (paged MoE == dense-KV MoE generate,
# engine batched == solo, left-padded batched generate == solo, ep=1
# bit-identical, ep>=2 and ep×tp token-identical, and the four ep_batch
# markers incl. the microbatch-overlap split). bench_serve writes it
# alongside stages 5h/5j/5k/5l's sections, so a fresh 5h capture already
# carries it — this stage only re-runs the bench when the banked
# artifact predates ISSUE 16 (or a marker/row failed). check_evidence's
# 'moe_serving' stage judges it (strict schema, all ten markers, dense +
# moe + moe_ep>=2 rows, a batch-sharded row STRICTLY above the
# replicated row at a matched (batch, ep), MoE rows above the tokens/s
# floor, [0,1] capacity columns).
if python scripts/check_evidence.py moe_serving \
    && [ "$(python -c 'import json;print(json.load(open("runs/serving/serving.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) moe_serving section already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) moe_serving rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py moe_serving \
    && echo "$(stamp) moe_serving section captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) moe_serving section FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 5n. serve SLO soak (ISSUE 17, ~2 min): the slo section of the
# SAME runs/serving/serving.json — the seeded scripts/workload_gen.py
# open-loop soak (Poisson + bursts, heavy-tail lengths, shared-prefix
# populations, ONE fixed seed) drained through the serve/metrics.py
# plane with the SLO monitor armed. Banked: TTFT + per-token decode
# latency p50/p95/p99 read from the LogHistogram sketches, goodput
# (in-SLO tokens/s), terminal status counts, token-loss accounting,
# breach count, and the metrics_inert marker (metrics-ON token streams
# byte-identical to metrics-OFF). bench_serve writes it alongside the
# other serving sections, so a fresh capture already carries it.
# check_evidence's 'slo' stage judges it (strict schema incl. ordered
# quantiles, all three markers, tokens_lost == 0 — the token-loss
# regression gate — and banked p99s inside the banked targets — the SLO
# regression gate); this stage FAILS LOUDLY on either regression.
if python scripts/check_evidence.py slo \
    && [ "$(python -c 'import json;print(json.load(open("runs/serving/serving.json"))["meta"]["backend"])' 2>/dev/null)" = "tpu" ]; then
  echo "$(stamp) slo section already captured on chip — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) slo rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py slo \
    && echo "$(stamp) slo section captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) slo section FAILED (SLO or token-loss regression, or schema)" | tee -a "$OUT/log.txt"
fi

# ---- 5o. process-isolated fleet (ISSUE 20, ~4 min): the
# fleet_resilience section of the SAME runs/serving/serving.json — the
# SIGKILL-at-tick matrix over REAL replica child processes under live
# socket traffic (serve/net.drive_open_loop; tick 1/3/6 greedy plus a
# sampled cut — zero accepted-token loss, token-identical migrations,
# each cut an actual declared process death), the full-stop restart leg
# (serve/fleet_state shadow + chain index → a fresh fleet resumes
# token-identically with prefill tokens saved by the warm-started page
# pool), and the seeded workload soak through the socket front with its
# stream_sha256 byte-determinism pin. The section always runs on the
# tiny gpt2 model (the worker builder reconstructs weights from the init
# seed — process spawn/SIGKILL/pipe-EOF/persistence are host-plane
# mechanics on every backend), so a CPU artifact is first-class and this
# stage only re-runs the bench when the banked artifact predates
# ISSUE 20 or a marker/row failed. check_evidence's 'fleet_resilience'
# stage judges it (strict schema, all six markers, >= 3 distinct kill
# ticks incl. a stochastic one, per-row zero loss + declared_dead, a
# restart that interrupted real work, a fully-served soak).
if python scripts/check_evidence.py fleet_resilience; then
  echo "$(stamp) fleet_resilience section already captured — skip" | tee -a "$OUT/log.txt"
else
  timeout -k 60 1800 python scripts/bench_serve.py --out runs/serving \
      >> "$OUT/serving.log" 2>&1
  rc=$?
  python scripts/validate_metrics.py runs/serving/serving.json \
      >> "$OUT/serving.log" 2>&1 || rc=$?
  echo "$(stamp) fleet_resilience rc=$rc" | tee -a "$OUT/log.txt"
  python scripts/check_evidence.py fleet_resilience \
    && echo "$(stamp) fleet_resilience section captured" | tee -a "$OUT/log.txt" \
    || echo "$(stamp) fleet_resilience section FAILED validation" | tee -a "$OUT/log.txt"
fi

# ---- 6. parity legs (mid-leg checkpoint/resume: a tunnel drop costs at
# most 250 steps; re-fires continue from the checkpoint)
for mode in local vote lazy; do
  # parity_full: only FULL-SCALE legs skip this stage — reduced CPU legs
  # (runs/parity_cpu) satisfy the watcher but must not stop a live TPU
  # window from capturing the flagship-scale curves
  if python scripts/check_evidence.py parity_full "$mode"; then
    echo "$(stamp) parity:$mode already captured — skip" | tee -a "$OUT/log.txt"
    continue
  fi
  timeout -k 60 3600 python scripts/loss_parity.py --phase run --mode "$mode" \
      --steps 2000 >> "$OUT/parity_$mode.log" 2>&1
  rc=$?; echo "$(stamp) parity:$mode rc=$rc" | tee -a "$OUT/log.txt"
done
python scripts/loss_parity.py --phase report >> "$OUT/log.txt" 2>&1

# ---- 7. LAST stage (VERDICT r3 stretch, after all higher-priority
# evidence): a real-corpus convergence artifact — 2000 steps of the
# canonical config (bs 20 x accum 8, GPT-2 124M) on the parity corpus
# through the native BPE, with the reference's convergence signals (eval
# accuracy/perplexity) logged. Orbax resume (save_steps 250) makes a
# tunnel drop cost one checkpoint interval, not the run.
if python scripts/check_evidence.py conv_full; then
  echo "$(stamp) convergence run already captured — skip" | tee -a "$OUT/log.txt"
else
  mkdir -p runs/convergence
  if [ ! -s runs/convergence/tokens.bin ]; then
    python - <<'EOF'
import numpy as np
a = np.load("runs/parity/tokens.npy", mmap_mode="r")
assert int(np.asarray(a[:1_000_000]).max()) < 65536
np.asarray(a, dtype=np.uint16).tofile("runs/convergence/tokens.bin")
EOF
  fi
  timeout -k 60 9000 python -m distributed_lion_tpu.cli.run_clm \
      --model_name gpt2_124m --dataset bin:runs/convergence/tokens.bin \
      --vocab_size 16384 --lion --async_grad \
      --per_device_train_batch_size 20 --gradient_accumulation_steps 8 \
      --block_size 1024 --max_steps 2000 --warmup_steps 200 \
      --learning_rate 1e-4 --weight_decay 0.1 \
      --eval_steps 250 --eval_iters 10 --logging_steps 25 \
      --save_steps 250 --save_total_limit 2 \
      --param_dtype float32 --compute_dtype bfloat16 \
      --vocab_chunks 8 --mom_dtype bfloat16 --remat false \
      --output_dir runs/convergence \
      >> "$OUT/conv.log" 2>&1
  rc=$?; echo "$(stamp) convergence rc=$rc" | tee -a "$OUT/log.txt"
fi
echo "$(stamp) stage-2 runbook done" | tee -a "$OUT/log.txt"
