#!/bin/bash
# Re-arming TPU watcher: keep firing the (idempotent) stage-2 runbook on
# every tunnel recovery until ALL round-3 evidence exists, so a mid-run
# tunnel drop costs one partial window instead of the whole round.
#
#   nohup scripts/tpu_watch_loop.sh > /tmp/tpu_watch_loop.log 2>&1 &
#
# Evidence-complete = 7B rows in sft7b2.jsonl AND all three 2000-step
# parity legs (the runbook's own per-stage guards skip captured stages).
set -u
cd "$(dirname "$0")/.."
stamp() { date -u +%FT%TZ; }

complete() {
  grep -q tokens_per_sec scripts/SWEEP_r3_raw/sft7b2.jsonl 2>/dev/null || return 1
  for mode in local vote lazy; do
    python - "$mode" <<'EOF' || return 1
import json, sys
try:
    with open(f"runs/parity/{sys.argv[1]}.jsonl") as f:
        last = 0
        for line in f:
            try:
                last = max(last, json.loads(line).get("step", 0))
            except json.JSONDecodeError:
                pass
    sys.exit(0 if last >= 1900 else 1)
except OSError:
    sys.exit(1)
EOF
  done
  return 0
}

while true; do
  if complete; then
    echo "$(stamp) all round-3 evidence captured; watcher exiting"
    exit 0
  fi
  out=$(timeout 120 python -c \
    "import jax; d=jax.devices(); print(len(d), d[0].platform)" 2>/dev/null)
  case "$out" in
    *tpu*)
      echo "$(stamp) TPU up ($out); running stage-2 runbook"
      bash scripts/tpu_runbook_auto2.sh
      echo "$(stamp) runbook exited; re-checking evidence"
      ;;
    "")
      echo "$(stamp) probe timed out/failed" ;;
    *)
      echo "$(stamp) backend: $out (not tpu)" ;;
  esac
  sleep 120
done
