#!/bin/bash
# Re-arming TPU watcher: keep firing the (idempotent) stage-2 runbook on
# every tunnel recovery until ALL round-3 evidence exists, so a mid-run
# tunnel drop costs one partial window instead of the whole round.
#
#   nohup scripts/tpu_watch_loop.sh > /tmp/tpu_watch_loop.log 2>&1 &
#
# Evidence-complete per scripts/check_evidence.py `all` — the ONE shared
# definition the runbook's per-stage skip guards also use: the sweep
# window's last config, the bench_best.done marker, the 7B spec list's
# last spec, and all three 2000-step parity legs.
set -u
cd "$(dirname "$0")/.."
stamp() { date -u +%FT%TZ; }

complete() {
  python scripts/check_evidence.py all
}

while true; do
  if complete; then
    echo "$(stamp) all round-3 evidence captured; watcher exiting"
    exit 0
  fi
  out=$(timeout 120 python -c \
    "import jax; d=jax.devices(); print(len(d), d[0].platform)" 2>/dev/null)
  case "$out" in
    *tpu*)
      echo "$(stamp) TPU up ($out); running stage-2 runbook"
      bash scripts/tpu_runbook_auto2.sh
      echo "$(stamp) runbook exited; re-checking evidence"
      # bank whatever the window produced immediately — a later crash or
      # round-end race must not lose captured chip evidence
      git add scripts/SWEEP_r3_raw scripts/last_tpu_measurement.json \
          runs/parity runs/convergence 2>/dev/null
      if ! git diff --cached --quiet 2>/dev/null; then
        git commit -q -m "Record TPU evidence captures from watcher window" \
          && echo "$(stamp) committed window captures"
      fi
      ;;
    "")
      echo "$(stamp) probe timed out/failed" ;;
    *)
      echo "$(stamp) backend: $out (not tpu)" ;;
  esac
  sleep 120
done
