#!/bin/bash
# Re-arming TPU watcher: keep firing the (idempotent) stage-2 runbook on
# every tunnel recovery until ALL round-3 evidence exists, so a mid-run
# tunnel drop costs one partial window instead of the whole round.
#
#   nohup scripts/tpu_watch_loop.sh > /tmp/tpu_watch_loop.log 2>&1 &
#
# Evidence-complete per scripts/check_evidence.py `all` — the ONE shared
# definition the runbook's per-stage skip guards also use: the sweep
# window's last config, the bench_best.done marker, the 7B spec list's
# last spec, and all three 2000-step parity legs.
set -u
cd "$(dirname "$0")/.."
stamp() { date -u +%FT%TZ; }

complete() {
  # `automation`: every stage a re-fired window can still affect. The
  # parity:PASS criterion is deterministic over captured legs — if it
  # fails, looping forever cannot fix it; exit loudly instead.
  python scripts/check_evidence.py automation
}

while true; do
  if complete; then
    echo "$(stamp) all automatable evidence captured; watcher exiting"
    python scripts/check_evidence.py all \
      || echo "$(stamp) NOTE: parity:PASS criterion FAILED on captured legs — needs a human"
    exit 0
  fi
  out=$(timeout 120 python -c \
    "import jax; d=jax.devices(); print(len(d), d[0].platform)" 2>/dev/null)
  case "$out" in
    *tpu*)
      echo "$(stamp) TPU up ($out); running stage-2 runbook"
      bash scripts/tpu_runbook_auto2.sh
      echo "$(stamp) runbook exited; re-checking evidence"
      # bank whatever the window produced immediately — a later crash or
      # round-end race must not lose captured chip evidence. The raw
      # capture files are append-only; the headline artifact is validated
      # before banking (advisor r4: an unparseable or non-TPU artifact
      # must not be committed unattended — bench.py itself already refuses
      # to overwrite a promoted record with an unpromoted capture)
      # per-path adds: `git add a b c` is atomic — ONE unmatched pathspec
      # (e.g. runs/parity_cpu absent on a TPU-only host) would stage
      # nothing at all and the stderr redirect would eat the evidence loss
      for p in scripts/SWEEP_r3_raw runs/parity runs/parity_cpu \
          runs/convergence; do
        [ -e "$p" ] && git add "$p" 2>/dev/null
      done
      if python - <<'EOF'
import json, sys
try:
    with open("scripts/last_tpu_measurement.json") as f:
        d = json.load(f)
    sys.exit(0 if d.get("backend") == "tpu" and d.get("value", 0) > 0
             else 1)
except Exception:
    sys.exit(1)
EOF
      then
        git add scripts/last_tpu_measurement.json 2>/dev/null
      else
        echo "$(stamp) headline artifact failed validation; not banking it"
      fi
      if ! git diff --cached --quiet 2>/dev/null; then
        git commit -q -m "Record TPU evidence captures from watcher window" \
          && echo "$(stamp) committed window captures"
      fi
      ;;
    "")
      echo "$(stamp) probe timed out/failed" ;;
    *)
      echo "$(stamp) backend: $out (not tpu)" ;;
  esac
  sleep 120
done
